"""Informer tests: the watch-fed cached observe path (k8s/informer.py).

ISSUE 2 coverage: delta application ordering, relist-on-410, parse-memo
invalidation on resourceVersion change, fallback-to-LIST while the
watch is down, the FakeKube watch journal (410 below the floor), and
the reconciler consuming snapshots — including the two staleness
bypasses (just-ACTIVE supply, mid-pass drain cancel).
"""

from __future__ import annotations

import threading
import time

import pytest

from tpu_autoscaler.k8s.fake import FakeKube
from tpu_autoscaler.k8s.informer import (
    ClusterInformer,
    ObjectCache,
    ResourceWatch,
    WatchError,
    WatchGone,
)
from tpu_autoscaler.k8s.objects import (
    clear_parse_caches,
    parse_node,
    parse_pod,
)
from tpu_autoscaler.metrics.metrics import Metrics


@pytest.fixture(autouse=True)
def _fresh_parse_caches():
    clear_parse_caches()
    yield
    clear_parse_caches()


def pod_payload(name, rv, phase="Pending", uid=None, ns="default",
                annotations=None):
    return {
        "metadata": {"name": name, "namespace": ns,
                     "uid": uid or f"uid-{name}",
                     "resourceVersion": str(rv),
                     "annotations": annotations or {}},
        "spec": {},
        "status": {"phase": phase},
    }


def ev(etype, obj=None, code=None, message=None):
    event = {"type": etype, "object": obj if obj is not None else {}}
    if code is not None:
        event["object"]["code"] = code
    if message is not None:
        event["object"]["message"] = message
    return event


class TestObjectCache:
    def test_delta_application_ordering(self):
        """ADDED → MODIFIED → DELETED applied in stream order leaves
        exactly the surviving objects, at their latest version."""
        cache = ObjectCache("pods", parse_pod)
        cache.replace([], "0")
        cache.apply(ev("ADDED", pod_payload("a", 1)))
        cache.apply(ev("ADDED", pod_payload("b", 2)))
        cache.apply(ev("MODIFIED", pod_payload("a", 3, phase="Running")))
        cache.apply(ev("DELETED", pod_payload("b", 4)))
        snap = cache.snapshot()
        assert [p.name for p in snap] == ["a"]
        assert snap[0].phase == "Running"
        assert cache.resource_version == "4"

    def test_bookmark_moves_cursor_without_state_change(self):
        cache = ObjectCache("pods", parse_pod)
        cache.replace([pod_payload("a", 1)], "1")
        relevant = cache.apply(ev("BOOKMARK", {
            "metadata": {"resourceVersion": "9"}}))
        assert relevant is False
        assert cache.resource_version == "9"
        assert len(cache.snapshot()) == 1

    def test_410_raises_watch_gone(self):
        cache = ObjectCache("pods", parse_pod)
        cache.replace([], "1")
        with pytest.raises(WatchGone):
            cache.apply(ev("ERROR", code=410, message="expired"))
        with pytest.raises(WatchError):
            cache.apply(ev("ERROR", code=500, message="boom"))

    def test_unsynced_snapshot_is_none(self):
        cache = ObjectCache("pods", parse_pod)
        assert cache.snapshot() is None
        cache.replace([pod_payload("a", 1)], "1")
        assert cache.snapshot() is not None
        cache.mark_unsynced()
        assert cache.snapshot() is None
        assert cache.resource_version is None  # cursor dropped too

    def test_snapshot_objects_are_parsed_once(self):
        """Unchanged objects across snapshots are the SAME parsed
        instance; a resourceVersion bump invalidates the memo."""
        cache = ObjectCache("pods", parse_pod)
        cache.replace([pod_payload("a", 1)], "1")
        first = cache.snapshot()[0]
        assert cache.snapshot()[0] is first
        # Relist with the same (uid, rv) payloads: memo hit, no re-parse.
        cache.replace([pod_payload("a", 1)], "2")
        assert cache.snapshot()[0] is first
        # rv bump: stale parse must not survive.
        cache.apply(ev("MODIFIED", pod_payload(
            "a", 5, annotations={"x": "1"})))
        second = cache.snapshot()[0]
        assert second is not first
        assert second.annotations == {"x": "1"}


class TestParseMemo:
    def test_memo_keyed_on_uid_and_rv(self):
        p1 = pod_payload("a", 1)
        assert parse_pod(p1) is parse_pod(dict(p1))  # same (uid, rv)
        assert parse_pod(pod_payload("a", 2)) is not parse_pod(p1)
        # Same rv, different uid (deleted + recreated): distinct entry.
        assert parse_pod(pod_payload("a", 1, uid="other")) \
            is not parse_pod(p1)

    def test_unversioned_payloads_parse_fresh(self):
        bare = {"metadata": {"name": "a"}, "spec": {}, "status": {}}
        assert parse_pod(bare) is not parse_pod(bare)
        node = {"metadata": {"name": "n"}, "status": {}}
        assert parse_node(node) is not parse_node(node)


class _ScriptedClient:
    """list/watch double: scripted watch batches, counting lists."""

    def __init__(self, batches, items=None, rv="10"):
        self._batches = list(batches)
        self.items = items if items is not None else []
        self.rv = rv
        self.lists = 0
        self.watch_rvs = []

    def list_pods(self):
        self.lists += 1
        return list(self.items)

    def list_pods_raw(self):
        self.lists += 1
        return {"metadata": {"resourceVersion": self.rv},
                "items": list(self.items)}

    def watch_pods(self, timeout_seconds=0, resource_version=None):
        self.watch_rvs.append(resource_version)
        if not self._batches:
            return
        batch = self._batches.pop(0)
        if batch == "down":
            raise ConnectionError("watch down")
        yield from batch


def make_watch(client, metrics=None, wake=None, resync_seconds=900.0):
    cache = ObjectCache("pods", parse_pod)
    watch = ResourceWatch(
        cache,
        lambda: (client.list_pods_raw().get("items", []),
                 client.list_pods_raw()["metadata"]["resourceVersion"]),
        client.watch_pods, wake=wake, timeout_seconds=0,
        resync_seconds=resync_seconds, metrics=metrics)
    return cache, watch


class TestResourceWatch:
    def test_initial_sync_then_deltas(self):
        client = _ScriptedClient(
            [[ev("ADDED", pod_payload("b", 11))]],
            items=[pod_payload("a", 1)])
        metrics = Metrics()
        wake = threading.Event()
        cache, watch = make_watch(client, metrics, wake)
        watch._run_once()
        assert {p.name for p in cache.snapshot()} == {"a", "b"}
        assert client.watch_rvs == ["10"]  # resumed from the list's rv
        assert metrics.snapshot()["counters"]["informer_relists"] == 1
        assert wake.is_set()

    def test_relist_on_410(self):
        """A 410 ERROR event marks the cache unsynced; the next loop
        iteration relists (counted) and resumes from the fresh rv."""
        client = _ScriptedClient(
            [[ev("ERROR", code=410, message="too old")], []],
            items=[pod_payload("a", 1)])
        metrics = Metrics()
        cache, watch = make_watch(client, metrics)
        with pytest.raises(WatchGone):
            watch._run_once()  # sync, then the stream 410s
        # run() would catch, mark unsynced, backoff, loop; emulate:
        cache.mark_unsynced()
        assert cache.snapshot() is None
        watch._run_once()
        counters = metrics.snapshot()["counters"]
        assert counters["informer_relists"] == 2
        assert cache.snapshot() is not None
        # Second watch resumed from the relist's rv, not the dead cursor.
        assert client.watch_rvs == ["10", "10"]

    def test_watch_failure_via_run_marks_unsynced_and_recovers(self):
        client = _ScriptedClient(
            ["down", [ev("ADDED", pod_payload("b", 11))]],
            items=[pod_payload("a", 1)])
        metrics = Metrics()
        cache, watch = make_watch(client, metrics)
        watch._rng = type("R", (), {
            "uniform": staticmethod(lambda a, b: 0.0)})()
        watch.start()
        deadline = time.time() + 3.0
        while time.time() < deadline:
            snap = cache.snapshot()
            if snap and {p.name for p in snap} == {"a", "b"}:
                break
            time.sleep(0.01)
        watch.stop()
        watch.join(timeout=2.0)
        counters = metrics.snapshot()["counters"]
        assert counters["watch_failures"] >= 1
        assert counters["informer_relists"] >= 2  # resync after failure
        assert {p.name for p in cache.snapshot()} == {"a", "b"}


class TestClusterInformerFallback:
    def test_fallback_to_list_when_watch_down(self):
        """Never started (or failed) watch: reads are direct LISTs,
        counted, and correct."""
        kube = FakeKube()
        kube.add_pod(pod_fixture("p1"))
        kube.add_node(node_fixture("n1"))
        metrics = Metrics()
        informer = ClusterInformer(kube, metrics=metrics,
                                   timeout_seconds=0)
        assert [p.name for p in informer.pods()] == ["p1"]
        assert [n.name for n in informer.nodes()] == ["n1"]
        counters = metrics.snapshot()["counters"]
        assert counters["informer_fallback_lists"] == 2
        # After a pump (sync + drain) reads come from the cache.
        informer.pump()
        assert [p.name for p in informer.pods()] == ["p1"]
        counters = metrics.snapshot()["counters"]
        assert counters["informer_fallback_lists"] == 2  # unchanged
        assert counters["informer_relists"] == 2

    def test_nodes_fall_back_when_client_cannot_watch_nodes(self):
        class PodsOnly:
            def __init__(self, kube):
                self._kube = kube

            def list_pods(self):
                return self._kube.list_pods()

            def list_nodes(self):
                return self._kube.list_nodes()

            def watch_pods(self, timeout_seconds=0,
                           resource_version=None):
                return self._kube.watch_pods(timeout_seconds,
                                             resource_version)

        kube = FakeKube()
        kube.add_node(node_fixture("n1"))
        metrics = Metrics()
        informer = ClusterInformer(PodsOnly(kube), metrics=metrics,
                                   timeout_seconds=0)
        informer.pump()
        assert informer.pod_cache.synced
        assert not informer.node_cache.synced
        assert [n.name for n in informer.nodes()] == ["n1"]
        assert metrics.snapshot()["counters"][
            "informer_fallback_lists"] == 1


def pod_fixture(name, phase="Pending", node=None):
    payload = {
        "metadata": {"name": name, "namespace": "default"},
        "spec": {},
        "status": {"phase": phase},
    }
    if node:
        payload["spec"]["nodeName"] = node
    return payload


def node_fixture(name):
    return {
        "metadata": {"name": name, "labels": {}},
        "spec": {},
        "status": {"allocatable": {"cpu": "4", "memory": "8Gi",
                                   "pods": "110"},
                   "conditions": [{"type": "Ready", "status": "True"}]},
    }


class TestFakeKubeWatchJournal:
    def test_resource_version_bumps_on_every_mutation(self):
        kube = FakeKube()
        kube.add_pod(pod_fixture("p1"))
        rv1 = kube.get_pod("default", "p1")["metadata"]["resourceVersion"]
        kube.patch_pod("default", "p1",
                       {"metadata": {"annotations": {"a": "1"}}})
        rv2 = kube.get_pod("default", "p1")["metadata"]["resourceVersion"]
        assert int(rv2) > int(rv1)

    def test_watch_streams_journal_from_cursor(self):
        kube = FakeKube()
        kube.add_pod(pod_fixture("p1"))
        start = kube.list_pods_raw()["metadata"]["resourceVersion"]
        # Journaling engages on first watch; a cursor at "now" then
        # sees exactly the subsequent mutations.
        events = kube.watch_pods(timeout_seconds=0,
                                 resource_version=start)
        kube.patch_pod("default", "p1",
                       {"metadata": {"annotations": {"a": "1"}}})
        kube.delete_pod("default", "p1")
        got = list(events)
        assert [e["type"] for e in got] == ["MODIFIED", "DELETED"]
        # Journal payloads are snapshots, not live references.
        assert got[0]["object"]["metadata"]["annotations"] == {"a": "1"}

    def test_cursor_below_journal_floor_yields_410(self):
        kube = FakeKube()
        kube.add_pod(pod_fixture("p1"))  # journaling off: floor tracks
        got = list(kube.watch_pods(timeout_seconds=0,
                                   resource_version="0"))
        assert [e["type"] for e in got] == ["ERROR"]
        assert got[0]["object"]["code"] == 410


class TestReconcilerWithInformer:
    def _controller(self, kube, informer, metrics):
        from tpu_autoscaler.actuators.fake import FakeActuator
        from tpu_autoscaler.controller import Controller, ControllerConfig
        from tpu_autoscaler.engine.planner import PoolPolicy

        actuator = FakeActuator(kube, provision_delay=0.0)
        return Controller(kube, actuator, ControllerConfig(
            policy=PoolPolicy(spare_nodes=0)), metrics=metrics,
            informer=informer)

    def test_scale_up_converges_from_informer_snapshots(self):
        """The north-star scenario driven entirely off the cache —
        including the just-ACTIVE bypass that keeps fresh supply
        visible the pass its provision lands."""
        from tpu_autoscaler.sim import seed_scenario

        kube = FakeKube()
        metrics = Metrics()
        informer = ClusterInformer(kube, metrics=metrics,
                                   timeout_seconds=0)
        controller = self._controller(kube, informer, metrics)
        seed_scenario(kube, "v5e-64")

        def all_running():
            pods = kube.list_pods()
            return bool(pods) and all(
                p["status"]["phase"] == "Running" for p in pods)

        sim_t = 0.0
        for _ in range(30):
            informer.pump()
            controller.reconcile_once(now=sim_t)
            kube.schedule_step()
            sim_t += 1.0
            if all_running():
                break
        assert all_running()
        counters = controller.metrics.snapshot()["counters"]
        assert counters["provisions_submitted"] == 1  # no double-provision
        assert counters.get("informer_bypass_lists", 0) >= 1
        assert counters.get("informer_fallback_lists", 0) == 0

    def test_bypass_sticks_until_node_cache_catches_up(self):
        """The just-ACTIVE bypass must outlive the pass that saw the
        ACTIVE status: the watch's delivery lag is independent of pass
        boundaries, so a wake-triggered pass milliseconds later would
        otherwise see neither the in-flight provision nor the new
        supply and double-provision."""
        from tpu_autoscaler.sim import seed_scenario

        kube = FakeKube()
        metrics = Metrics()
        informer = ClusterInformer(kube, metrics=metrics,
                                   timeout_seconds=0)
        controller = self._controller(kube, informer, metrics)
        seed_scenario(kube, "v5e-64")
        informer.pump()  # synced on the pre-provision world

        sim_t = 0.0
        n_before = len(kube.list_nodes())
        for _ in range(10):  # drive to just-ACTIVE, never pumping
            controller.reconcile_once(now=sim_t)
            sim_t += 1.0
            if len(kube.list_nodes()) > n_before:
                break
        assert len(kube.list_nodes()) > n_before
        # The node cache never saw the ADDED events, so the guard is
        # armed and holds through wake-triggered passes...
        assert controller._nodes_awaiting_cache
        for _ in range(3):
            sim_t += 0.001
            controller.reconcile_once(now=sim_t)
        assert controller._nodes_awaiting_cache
        counters = metrics.snapshot()["counters"]
        assert counters["provisions_submitted"] == 1  # no double
        # ...and clears once the watch delivers the new nodes.
        informer.pump()
        sim_t += 1.0
        controller.reconcile_once(now=sim_t)
        assert not controller._nodes_awaiting_cache

    def test_informer_and_baseline_observe_identically(self):
        """Snapshot-fed and relist-fed controllers see the same world."""
        from tpu_autoscaler.k8s.gangs import group_into_gangs
        from tpu_autoscaler.sim import seed_scenario

        kube = FakeKube()
        seed_scenario(kube, "v5e-64")
        informer = ClusterInformer(kube, timeout_seconds=0)
        informer.pump()
        from tpu_autoscaler.k8s.objects import Node, Pod

        base_pods = [Pod(p) for p in kube.list_pods()]
        inf_pods = informer.pods()
        assert ({p.name for p in base_pods}
                == {p.name for p in inf_pods})
        base_gangs = group_into_gangs(
            [p for p in base_pods if p.is_unschedulable])
        inf_gangs = group_into_gangs(
            [p for p in inf_pods if p.is_unschedulable])
        assert [g.key for g in base_gangs] == [g.key for g in inf_gangs]
        assert [Node(n) for n in kube.list_nodes()] == informer.nodes()
