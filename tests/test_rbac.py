"""RBAC parity: the deploy manifest must grant every verb the client uses.

VERDICT r1 item 3: the round-1 manifest granted pods only list/get/delete
while the controller also PATCHes pods (checkpoint / unsatisfiable
annotations) and WATCHes them (pending-pod trigger) — a real cluster
would 403. This test pins manifest ⊇ client so a new client verb cannot
land without the matching RBAC rule.
"""

from __future__ import annotations

import os

import yaml

from tpu_autoscaler.k8s.client import KubeClient, RestKubeClient

MANIFEST = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "deploy", "autoscaler.yaml")

# Every KubeClient method -> the (apiGroup, resource, verb) grants it
# needs. Keep in sync with RestKubeClient's HTTP calls; the meta-test
# below fails if a client method is missing from this map.
METHOD_GRANTS: dict[str, set[tuple[str, str, str]]] = {
    "list_nodes": {("", "nodes", "list")},
    "list_pods": {("", "pods", "list")},
    # Raw list verbs hit the same endpoints (the informer needs the
    # collection resourceVersion to resume its watch from).
    "list_nodes_raw": {("", "nodes", "list")},
    "list_pods_raw": {("", "pods", "list")},
    "patch_node": {("", "nodes", "patch")},
    "patch_pod": {("", "pods", "patch")},
    "evict_pod": {("", "pods/eviction", "create")},
    "delete_pod": {("", "pods", "delete")},
    "delete_node": {("", "nodes", "delete")},
    "create_event": {("", "events", "create")},
    "get_lease": {("coordination.k8s.io", "leases", "get")},
    # put_lease POSTs on first acquisition, PUTs on renewal.
    "put_lease": {("coordination.k8s.io", "leases", "create"),
                  ("coordination.k8s.io", "leases", "update")},
    # ?watch=1 on the list endpoints requires the watch verb; nodes are
    # watched by the informer's supply-side cache (k8s/informer.py).
    "watch_pods": {("", "pods", "watch")},
    "watch_nodes": {("", "nodes", "watch")},
}


def manifest_grants() -> set[tuple[str, str, str]]:
    with open(MANIFEST) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    grants: set[tuple[str, str, str]] = set()
    for doc in docs:
        if doc.get("kind") != "ClusterRole":
            continue
        for rule in doc.get("rules", []):
            for group in rule.get("apiGroups", []):
                for resource in rule.get("resources", []):
                    for verb in rule.get("verbs", []):
                        grants.add((group, resource, verb))
    return grants


class TestRbacParity:
    def test_manifest_covers_every_client_verb(self):
        granted = manifest_grants()
        missing = {
            (method, grant)
            for method, needs in METHOD_GRANTS.items()
            for grant in needs if grant not in granted
        }
        assert not missing, (
            f"deploy/autoscaler.yaml is missing RBAC grants: {missing}")

    def test_every_client_method_has_declared_grants(self):
        # A new KubeClient/RestKubeClient verb must declare its grants
        # here (and thereby get checked against the manifest).
        # Constructors + local wiring (set_metrics registers the retry
        # counter sink; it makes no apiserver call, so no grant).
        exempt = {"from_kubeconfig", "in_cluster", "set_metrics"}
        methods = {
            name for cls in (KubeClient, RestKubeClient)
            for name in vars(cls)
            if not name.startswith("_") and callable(getattr(cls, name, None))
        } - exempt
        undeclared = methods - set(METHOD_GRANTS)
        assert not undeclared, (
            f"client methods with no RBAC declaration: {undeclared}")

    def test_manifest_parses_and_binds_the_role(self):
        with open(MANIFEST) as f:
            docs = [d for d in yaml.safe_load_all(f) if d]
        kinds = [d.get("kind") for d in docs]
        assert "ClusterRole" in kinds and "ClusterRoleBinding" in kinds
        binding = next(d for d in docs if d["kind"] == "ClusterRoleBinding")
        role = next(d for d in docs if d["kind"] == "ClusterRole")
        sa = next(d for d in docs if d["kind"] == "ServiceAccount")
        assert binding["roleRef"]["name"] == role["metadata"]["name"]
        assert binding["subjects"][0]["name"] == sa["metadata"]["name"]
