"""Watch-trigger tests: level-triggered detection (controller/watch.py).

VERDICT r1 item 6 coverage: failure backoff + jitter, resourceVersion
resume across reconnects, 410 reset, watch_failures metric, and
bookmark/irrelevant events not waking the reconciler.
"""

import random
import threading
import time

from tpu_autoscaler.controller.watch import (
    BACKOFF_BASE_S,
    BACKOFF_CAP_S,
    WatchTrigger,
)
from tpu_autoscaler.metrics.metrics import Metrics


class FakeWatchClient:
    def __init__(self, batches):
        self._batches = list(batches)
        self.calls = 0
        self.resource_versions = []

    def watch_pods(self, timeout_seconds=60, resource_version=None):
        self.calls += 1
        self.resource_versions.append(resource_version)
        if not self._batches:
            time.sleep(0.05)
            return
        batch = self._batches.pop(0)
        if batch == "error":
            raise ConnectionError("watch dropped")
        yield from batch


class NoRvWatchClient(FakeWatchClient):
    """A KubeClient predating the resource_version kwarg: passing it must
    TypeError at call time (argument binding), like a real signature."""

    def watch_pods(self, timeout_seconds=60):  # noqa: D102
        return super().watch_pods(timeout_seconds)


def ev(etype, rv=None, code=None):
    obj = {}
    if rv is not None:
        obj["metadata"] = {"resourceVersion": rv}
    if code is not None:
        obj["code"] = code
    return {"type": etype, "object": obj}


class _InstantRng(random.Random):
    """uniform() returns the ceiling: deterministic, and lets tests
    assert on the computed backoff bound."""

    def __init__(self):
        super().__init__(0)
        self.ceilings = []

    def uniform(self, a, b):
        self.ceilings.append(b)
        return 0.0  # no waiting in tests


class TestWatchTrigger:
    def wait_for(self, cond, timeout=2.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if cond():
                return True
            time.sleep(0.01)
        return False

    def test_event_wakes_loop(self):
        wake = threading.Event()
        client = FakeWatchClient([[ev("ADDED")]])
        t = WatchTrigger(client, wake)
        t.start()
        assert self.wait_for(wake.is_set)
        t.stop()

    def test_watch_error_degrades_not_crashes(self):
        wake = threading.Event()
        client = FakeWatchClient(["error", [ev("MODIFIED")]])
        t = WatchTrigger(client, wake, rng=_InstantRng())
        t.start()
        assert self.wait_for(wake.is_set)  # recovered after the error
        assert t.is_alive()
        t.stop()

    def test_stop_terminates(self):
        wake = threading.Event()
        t = WatchTrigger(FakeWatchClient([]), wake)
        t.start()
        t.stop()
        t.join(timeout=2.0)
        # Thread may be sleeping in its final empty poll; alive() False soon.
        assert self.wait_for(lambda: not t.is_alive(), timeout=3.0)

    # -- hardening ---------------------------------------------------------

    def test_failures_counted_and_backoff_grows(self):
        wake = threading.Event()
        metrics = Metrics()
        rng = _InstantRng()
        client = FakeWatchClient(["error", "error", "error",
                                  [ev("ADDED")]])
        t = WatchTrigger(client, wake, metrics=metrics, rng=rng)
        t.start()
        assert self.wait_for(wake.is_set)
        t.stop()
        assert metrics.snapshot()["counters"]["watch_failures"] == 3
        # Exponential ceilings: base, 2*base, 4*base (full jitter).
        assert rng.ceilings[:3] == [BACKOFF_BASE_S, 2 * BACKOFF_BASE_S,
                                    4 * BACKOFF_BASE_S]

    def test_backoff_capped(self):
        rng = _InstantRng()
        t = WatchTrigger(FakeWatchClient([]), threading.Event(), rng=rng)
        t._failure_streak = 50
        t._backoff_seconds()
        # The jitter CEILING must be capped (2^49s otherwise) — assert on
        # what was actually passed to uniform(), not its return value.
        assert rng.ceilings == [BACKOFF_CAP_S]

    def test_resource_version_resumes_across_reconnects(self):
        wake = threading.Event()
        client = FakeWatchClient([
            [ev("ADDED", rv="100"), ev("MODIFIED", rv="101")],
            [ev("MODIFIED", rv="102")],
        ])
        t = WatchTrigger(client, wake, rng=_InstantRng())
        t.start()
        assert self.wait_for(lambda: client.calls >= 3)
        t.stop()
        # First watch starts cold; reconnects resume from the cursor.
        assert client.resource_versions[0] is None
        assert client.resource_versions[1] == "101"
        assert client.resource_versions[2] == "102"

    def test_bookmark_updates_cursor_without_waking(self):
        wake = threading.Event()
        client = FakeWatchClient([[ev("BOOKMARK", rv="500")]])
        t = WatchTrigger(client, wake, rng=_InstantRng())
        t.start()
        assert self.wait_for(lambda: client.calls >= 2)
        t.stop()
        assert not wake.is_set()
        assert client.resource_versions[1] == "500"

    def test_410_gone_resets_cursor(self):
        wake = threading.Event()
        client = FakeWatchClient([
            [ev("ADDED", rv="100")],
            [ev("ERROR", code=410)],
            [ev("ADDED", rv="200")],
        ])
        metrics = Metrics()
        t = WatchTrigger(client, wake, metrics=metrics, rng=_InstantRng())
        t.start()
        assert self.wait_for(lambda: client.calls >= 3)
        t.stop()
        assert client.resource_versions[1] == "100"  # resumed
        assert client.resource_versions[2] is None   # reset after 410

    def test_error_event_counts_as_failure(self):
        wake = threading.Event()
        metrics = Metrics()
        client = FakeWatchClient([[ev("ERROR", code=410)]])
        t = WatchTrigger(client, wake, metrics=metrics, rng=_InstantRng())
        t.start()
        assert self.wait_for(
            lambda: metrics.snapshot()["counters"].get("watch_failures",
                                                       0) >= 1)
        t.stop()
        assert not wake.is_set()

    def test_client_without_resource_version_kwarg_still_works(self):
        wake = threading.Event()
        client = NoRvWatchClient([[ev("ADDED", rv="1")]])
        t = WatchTrigger(client, wake, rng=_InstantRng())
        t.start()
        assert self.wait_for(wake.is_set)
        t.stop()

    def test_warning_only_on_first_failure_of_streak(self, caplog):
        import logging

        wake = threading.Event()
        client = FakeWatchClient(["error", "error", "error",
                                  [ev("ADDED")]])
        t = WatchTrigger(client, wake, rng=_InstantRng())
        with caplog.at_level(logging.DEBUG,
                             logger="tpu_autoscaler.controller.watch"):
            t.start()
            assert self.wait_for(wake.is_set)
            t.stop()
        warnings = [r for r in caplog.records
                    if r.levelno == logging.WARNING]
        assert len(warnings) == 1
