"""Watch-trigger tests: level-triggered detection (controller/watch.py)."""

import threading
import time

from tpu_autoscaler.controller.watch import WatchTrigger


class FakeWatchClient:
    def __init__(self, batches):
        self._batches = list(batches)
        self.calls = 0

    def watch_pods(self, timeout_seconds=60):
        self.calls += 1
        if not self._batches:
            time.sleep(0.05)
            return
        batch = self._batches.pop(0)
        if batch == "error":
            raise ConnectionError("watch dropped")
        yield from batch


class TestWatchTrigger:
    def wait_for(self, cond, timeout=2.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if cond():
                return True
            time.sleep(0.01)
        return False

    def test_event_wakes_loop(self):
        wake = threading.Event()
        client = FakeWatchClient([[{"type": "ADDED"}]])
        t = WatchTrigger(client, wake)
        t.start()
        assert self.wait_for(wake.is_set)
        t.stop()

    def test_watch_error_degrades_not_crashes(self):
        wake = threading.Event()
        client = FakeWatchClient(["error", [{"type": "MODIFIED"}]])
        t = WatchTrigger(client, wake)
        t.start()
        # Survives the dropped watch... but the retry backoff is 5s; don't
        # wait for it — just confirm the thread is alive after the error.
        assert self.wait_for(lambda: client.calls >= 1)
        time.sleep(0.1)
        assert t.is_alive()
        t.stop()

    def test_stop_terminates(self):
        wake = threading.Event()
        t = WatchTrigger(FakeWatchClient([]), wake)
        t.start()
        t.stop()
        t.join(timeout=2.0)
        # Thread may be sleeping in its final empty poll; alive() False soon.
        assert self.wait_for(lambda: not t.is_alive(), timeout=3.0)
