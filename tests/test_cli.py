"""CLI-level tests via click's CliRunner (reference parity: main.py flag
surface, SURVEY.md §3.1)."""

import pytest
from click.testing import CliRunner

from tpu_autoscaler.main import cli


class TestDemoCommand:
    def test_demo_cpu_scenario(self):
        result = CliRunner().invoke(cli, [
            "demo", "--scenario", "cpu", "--provision-delay", "30",
            "--spare-agents", "0"])
        assert result.exit_code == 0, result.output
        assert "Unschedulable→Running" in result.output
        assert "stranded 0" in result.output

    def test_demo_timeout_reports_failure(self):
        result = CliRunner().invoke(cli, [
            "demo", "--scenario", "v5p-256", "--provision-delay", "500",
            "--until", "100", "--spare-agents", "0"])
        assert result.exit_code == 1
        assert "FAILED" in result.output

    def test_no_scale_flag_prevents_provisioning(self):
        result = CliRunner().invoke(cli, [
            "demo", "--scenario", "cpu", "--no-scale", "--until", "60",
            "--spare-agents", "0"])
        assert result.exit_code == 1  # pod never runs

    def test_bad_spare_slice_rejected(self):
        result = CliRunner().invoke(cli, [
            "demo", "--scenario", "cpu", "--spare-slice", "bogus=2"])
        assert result.exit_code == 2
        assert "unknown slice shape" in result.output

    def test_sleep_zero_rejected(self):
        result = CliRunner().invoke(cli, [
            "demo", "--scenario", "cpu", "--sleep", "0"])
        assert result.exit_code == 2

    def test_help_lists_reference_parity_flags(self):
        result = CliRunner().invoke(cli, ["demo", "--help"])
        for flag in ("--sleep", "--idle-threshold", "--spare-agents",
                     "--over-provision", "--no-scale", "--no-maintenance",
                     "--slack-hook"):
            assert flag in result.output

    def test_run_requires_cluster_identifiers(self):
        result = CliRunner().invoke(cli, [
            "run", "--kube-url", "https://example:6443",
            "--actuator", "gke"])
        assert result.exit_code != 0
        assert "needs" in str(result.exception or result.output)


class TestScalePerfSmoke:
    def test_planner_handles_hundreds_of_gangs_quickly(self):
        import time

        from tpu_autoscaler.engine.planner import Planner, PoolPolicy
        from tpu_autoscaler.k8s.gangs import group_into_gangs
        from tpu_autoscaler.k8s.objects import Pod
        from tests.fixtures import make_tpu_pod

        pods = [Pod(make_tpu_pod(name=f"p{i}", chips=8, job=f"job-{i}"))
                for i in range(300)]
        gangs = group_into_gangs(pods)
        planner = Planner(PoolPolicy(spare_nodes=0, max_total_chips=10**6))
        t0 = time.perf_counter()
        plan = planner.plan(gangs, [], pods, [])
        elapsed = time.perf_counter() - t0
        assert len(plan.requests) == 300
        # O(gangs x shapes); must stay far inside one reconcile interval.
        assert elapsed < 1.0, f"planner took {elapsed:.2f}s for 300 gangs"


class TestModuleEntry:
    def test_python_dash_m_package(self):
        import subprocess
        import sys

        result = subprocess.run(
            [sys.executable, "-m", "tpu_autoscaler", "--help"],
            capture_output=True, text=True, timeout=60)
        assert result.returncode == 0
        assert "demo" in result.stdout and "run" in result.stdout


class TestConfigFile:
    def test_yaml_defaults_applied(self, tmp_path):
        cfg = tmp_path / "config.yaml"
        cfg.write_text("scenario: v5e-8\nprovision_delay: 45\n"
                       "spare_agents: 0\n")
        result = CliRunner().invoke(cli, ["demo", "--config", str(cfg)])
        assert result.exit_code == 0, result.output
        assert "[v5e-8]" in result.output
        assert "45.0s" in result.output

    def test_cli_flag_overrides_config(self, tmp_path):
        cfg = tmp_path / "config.yaml"
        cfg.write_text("scenario: v5e-8\nspare_agents: 0\n"
                       "provision_delay: 45\n")
        result = CliRunner().invoke(cli, [
            "demo", "--config", str(cfg), "--scenario", "cpu",
            "--provision-delay", "30"])
        assert result.exit_code == 0, result.output
        assert "[cpu]" in result.output
        assert "30.0s" in result.output  # CLI value beat the config's 45

    def test_unknown_config_key_rejected(self, tmp_path):
        cfg = tmp_path / "config.yaml"
        cfg.write_text("idle-treshold: 900\n")  # typo'd key
        result = CliRunner().invoke(cli, ["demo", "--config", str(cfg)])
        assert result.exit_code == 2
        assert "unknown config key" in result.output

    def test_dashed_keys_normalized(self, tmp_path):
        cfg = tmp_path / "config.yaml"
        cfg.write_text("provision-delay: 45\nscenario: cpu\n"
                       "spare-agents: 0\n")
        result = CliRunner().invoke(cli, ["demo", "--config", str(cfg)])
        assert result.exit_code == 0, result.output
        assert "45.0s" in result.output

    def test_malformed_yaml_clean_error(self, tmp_path):
        cfg = tmp_path / "config.yaml"
        cfg.write_text("foo: [unclosed\n")
        result = CliRunner().invoke(cli, ["demo", "--config", str(cfg)])
        assert result.exit_code == 2
        assert "invalid YAML" in result.output

    def test_spare_slices_config_key_works(self, tmp_path):
        # The docstring's example key must actually reach the policy.
        cfg = tmp_path / "config.yaml"
        cfg.write_text('spare_slices: ["v5e-8=1"]\nscenario: cpu\n'
                       "spare_agents: 0\nidle_threshold: 99999\n")
        result = CliRunner().invoke(cli, ["demo", "--config", str(cfg),
                                          "--until", "400"])
        assert result.exit_code == 0, result.output
        # The warm v5e-8 slice provisioned alongside the cpu node.
        assert "chips=8" in result.output

    def test_non_mapping_config_rejected(self, tmp_path):
        cfg = tmp_path / "config.yaml"
        cfg.write_text("- just\n- a list\n")
        result = CliRunner().invoke(cli, ["demo", "--config", str(cfg)])
        assert result.exit_code == 2
        assert "YAML mapping" in result.output


class TestBigClusterPerfSmoke:
    def test_maintain_scales_to_hundreds_of_units(self):
        """One reconcile pass over a big cluster stays well inside the
        loop interval (the reference called this trivially cheap at k8s
        scale — SURVEY §4.5; hold ourselves to the same)."""
        import time

        from tpu_autoscaler.actuators.fake import FakeActuator
        from tpu_autoscaler.controller import Controller, ControllerConfig
        from tpu_autoscaler.engine.planner import PoolPolicy
        from tpu_autoscaler.k8s.fake import FakeKube
        from tpu_autoscaler.topology import shape_by_name
        from tests.fixtures import make_pod, make_slice_nodes, make_node

        kube = FakeKube()
        shape = shape_by_name("v5e-16")
        # 50 TPU slices (200 nodes) + 100 CPU nodes + 300 running pods.
        for i in range(50):
            for payload in make_slice_nodes(shape, f"s{i}"):
                kube.add_node(payload)
        for i in range(100):
            kube.add_node(make_node(name=f"cpu-{i}", slice_id=f"cpu-{i}"))
        for i in range(300):
            kube.add_pod(make_pod(
                name=f"w{i}", owner_kind="ReplicaSet", phase="Running",
                node_name=f"cpu-{i % 100}", unschedulable=False,
                requests={"cpu": "100m"}))
        controller = Controller(kube, FakeActuator(kube), ControllerConfig(
            policy=PoolPolicy(spare_nodes=0)))
        controller.reconcile_once(now=0.0)  # warm caches/trackers
        t0 = time.perf_counter()
        controller.reconcile_once(now=5.0)
        elapsed = time.perf_counter() - t0
        assert elapsed < 1.0, f"reconcile took {elapsed:.2f}s at 300 nodes"


class TestNamespaceQuotaFlag:
    def test_flag_parsed_and_enforced(self):
        result = CliRunner().invoke(cli, [
            "demo", "--scenario", "v5e-8", "--namespace-quota", "default=4",
            "--spare-agents", "0", "--until", "120"])
        # The 8-chip gang exceeds default's 4-chip quota: never runs.
        assert result.exit_code == 1
        assert "FAILED" in result.output

    def test_bad_quota_rejected(self):
        result = CliRunner().invoke(cli, [
            "demo", "--scenario", "cpu", "--namespace-quota", "oops"])
        assert result.exit_code == 2
        assert "NAMESPACE=CHIPS" in result.output

    def test_negative_and_duplicate_quota_rejected(self):
        r = CliRunner().invoke(cli, [
            "demo", "--scenario", "cpu", "--namespace-quota", "t=-8"])
        assert r.exit_code == 2 and "negative" in r.output
        r = CliRunner().invoke(cli, [
            "demo", "--scenario", "cpu", "--namespace-quota", "t=64",
            "--namespace-quota", "t=4096"])
        assert r.exit_code == 2 and "duplicate" in r.output
        r = CliRunner().invoke(cli, [
            "demo", "--scenario", "cpu", "--spare-slice", "v5e-8=1",
            "--spare-slice", "v5e-8=2"])
        assert r.exit_code == 2 and "duplicate" in r.output


class TestChurnScenario:
    @pytest.mark.slow
    def test_churn_serves_jobs_and_summarizes(self):
        result = CliRunner().invoke(cli, [
            "demo", "--scenario", "churn", "--provision-delay", "60",
            "--idle-threshold", "300", "--spare-agents", "0",
            "--until", "20000"])
        assert result.exit_code == 0, result.output
        assert "jobs served" in result.output
        assert "0 pods pending at cutoff" in result.output
