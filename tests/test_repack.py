"""Cost-aware continuous repacking (ISSUE 12, docs/REPACK.md).

Three layers, mirroring the subsystem:

- pure algebra (repack/policy.py): candidate selection, projections,
  the abort verdict, realized attribution — no controller;
- lifecycle e2e (Controller + FakeKube + FakeActuator): a displaced
  gang migrates onto idle spot, an oversized gang right-sizes, the
  budget guard aborts when the destination vanishes and leaves the
  fleet planner-reachable;
- the seeded churn property suite: repack-vs-no-repack $-proxy never
  worse on every seed, the conservation identity holds through every
  migration, and the ledger's incremental counters match the rebuild
  oracle.
"""

from __future__ import annotations

import random

import pytest

from tpu_autoscaler.actuators.fake import FakeActuator
from tpu_autoscaler.controller import Controller, ControllerConfig
from tpu_autoscaler.cost.pricebook import PriceBook
from tpu_autoscaler.engine.planner import PoolPolicy
from tpu_autoscaler.k8s.fake import FakeKube
from tpu_autoscaler.k8s.payloads import tpu_host_payload
from tpu_autoscaler.repack import (
    RepackConfig,
    UnitRow,
    plan_candidates,
    realized_attribution,
    should_abort,
)
from tpu_autoscaler.sim import gang_pods
from tpu_autoscaler.topology.catalog import shape_by_name


def _rate(accel: str, tier: str) -> float:
    return PriceBook().rate(accel, tier)[0]


def _row(unit_id="u0", pool="pool-a", accel="tpu-v5-lite-podslice",
         tier="on_demand", shape="v5e-16", chips=16, used=16,
         state="training", since=0.0, gang_id="job/default/a#0"):
    return UnitRow(unit_id=unit_id, pool=pool, accel=accel, tier=tier,
                   shape=shape, chips=chips, used_chips=used,
                   state=state, since=since, gang_id=gang_id)


CFG = RepackConfig(min_dwell_seconds=60.0, drain_estimate_seconds=30.0,
                   provision_estimate_seconds=30.0,
                   savings_horizon_seconds=3600.0)


class TestPlanCandidates:
    def test_displace_needs_idle_spot_of_same_shape(self):
        plans, _ = plan_candidates(
            [_row()], {"v5e-16": 16}, _rate, 120.0, CFG,
            active_migrations=0, budget_remaining_cs=1e9)
        assert len(plans) == 1
        assert plans[0].kind == "displace"
        assert plans[0].target_shape == "v5e-16"
        plans, _ = plan_candidates(
            [_row()], {"v5e-8": 16}, _rate, 120.0, CFG,
            active_migrations=0, budget_remaining_cs=1e9)
        assert plans == []

    def test_spot_tier_unit_never_displaced(self):
        plans, _ = plan_candidates(
            [_row(tier="spot")], {"v5e-16": 16}, _rate, 120.0, CFG,
            active_migrations=0, budget_remaining_cs=1e9)
        assert plans == []

    def test_min_dwell_rejects_fresh_unit(self):
        plans, rejections = plan_candidates(
            [_row(since=100.0)], {"v5e-16": 16}, _rate, 120.0, CFG,
            active_migrations=0, budget_remaining_cs=1e9)
        assert plans == []
        assert any("min dwell" in r for r in rejections)

    def test_burning_pool_excluded(self):
        plans, rejections = plan_candidates(
            [_row(state="serving")], {"v5e-16": 16}, _rate, 120.0, CFG,
            active_migrations=0, budget_remaining_cs=1e9,
            burning_pools=frozenset({"pool-a"}))
        assert plans == []
        assert any("SLO-burning" in r for r in rejections)

    def test_rightsize_uses_caller_target(self):
        plans, _ = plan_candidates(
            [_row(used=8)], {}, _rate, 120.0, CFG,
            active_migrations=0, budget_remaining_cs=1e9,
            rightsize_targets={"u0": ("v5e-8", 8)})
        assert len(plans) == 1
        assert plans[0].kind == "rightsize"
        assert plans[0].target_chips == 8
        # 8 chips freed for an hour vs (16*30 + 8*30) drain+provision.
        assert plans[0].projected_saving_cs == pytest.approx(8 * 3600.0)

    def test_budget_and_concurrency_gates(self):
        rows = [_row(unit_id="u0"), _row(unit_id="u1",
                                         gang_id="job/default/b#0")]
        plans, rejections = plan_candidates(
            rows, {"v5e-16": 32}, _rate, 120.0, CFG,
            active_migrations=0, budget_remaining_cs=1e9)
        assert len(plans) == 1  # max_concurrent_migrations = 1
        assert any("max_concurrent_migrations" in r for r in rejections)
        plans, rejections = plan_candidates(
            rows, {"v5e-16": 32}, _rate, 120.0, CFG,
            active_migrations=0, budget_remaining_cs=10.0)
        assert plans == []
        assert any("budget" in r for r in rejections)

    def test_one_idle_spot_slice_not_double_counted(self):
        cfg = RepackConfig(min_dwell_seconds=60.0,
                           max_concurrent_migrations=4,
                           drain_estimate_seconds=30.0)
        rows = [_row(unit_id="u0"), _row(unit_id="u1",
                                         gang_id="job/default/b#0")]
        plans, _ = plan_candidates(
            rows, {"v5e-16": 16}, _rate, 120.0, cfg,
            active_migrations=0, budget_remaining_cs=1e9)
        assert len(plans) == 1

    def test_admission_bar_rejects_thin_savings(self):
        # A horizon so short the drain cost dominates.
        cfg = RepackConfig(min_dwell_seconds=0.0,
                           savings_horizon_seconds=10.0,
                           drain_estimate_seconds=120.0)
        plans, rejections = plan_candidates(
            [_row()], {"v5e-16": 16}, _rate, 120.0, cfg,
            active_migrations=0, budget_remaining_cs=1e9)
        assert plans == []
        assert any("admission bar" in r for r in rejections)


class TestAbortVerdict:
    def _plan(self):
        plans, _ = plan_candidates(
            [_row()], {"v5e-16": 16}, _rate, 120.0, CFG,
            active_migrations=0, budget_remaining_cs=1e9)
        return plans[0]

    def test_destination_gone_aborts(self):
        verdict = should_abort(self._plan(), CFG, realized_cost_cs=0.0,
                               elapsed=5.0,
                               destination_available=False,
                               provision_pending=False)
        assert verdict is not None and "destination gone" in verdict

    def test_cost_overrun_aborts(self):
        plan = self._plan()
        assert should_abort(plan, CFG, realized_cost_cs=10.0,
                            elapsed=5.0, destination_available=True,
                            provision_pending=False) is None
        verdict = should_abort(
            plan, CFG, realized_cost_cs=plan.projected_saving_cs + 1,
            elapsed=5.0, destination_available=True,
            provision_pending=False)
        assert verdict is not None and "exceeds projected savings" \
            in verdict

    def test_attribution_nets_out_cost(self):
        plan = self._plan()
        attrs = realized_attribution(plan, CFG, realized_cost_cs=100.0,
                                     landed_rate=plan.rate_dst)
        assert attrs["migration_cost_chip_seconds"] == 100.0
        assert attrs["chip_seconds_saved"] == pytest.approx(
            plan.freed_cs_per_s * 3600.0 - 100.0)
        # Landing somewhere expensive (misfire) erases the savings.
        misfire = realized_attribution(plan, CFG,
                                       realized_cost_cs=100.0,
                                       landed_rate=plan.rate_src)
        assert misfire["chip_seconds_saved"] < 0


# ---------------------------------------------------------------------------
# Lifecycle e2e through the real Controller.


class _StubAdvice:
    advisory: list = []
    scale_in: dict = {}
    desired: dict = {}


class _StubServingScaler:
    """Just enough scaler for the burning-pool exclusion test: a live
    adapter whose ``burning_pools`` is canned."""

    def __init__(self, burning):
        class _Adapter:
            def burning_pools(self, floor):
                return set(burning)

        self.adapter = _Adapter()

    def bind(self, **kw):
        pass

    def advise(self, statuses, now):
        return _StubAdvice()


class RepackWorld:
    """FakeKube + FakeActuator + Controller with the world-model bits
    the chaos engine supplies (node GC, Job controller)."""

    def __init__(self, repack=True, provision_delay=10.0,
                 serving_scaler=None, **cfg_kw):
        self.kube = FakeKube()
        self.actuator = FakeActuator(self.kube,
                                     provision_delay=provision_delay)
        repack_cfg = cfg_kw.pop("repack_cfg", None) or RepackConfig(
            min_dwell_seconds=30.0, drain_estimate_seconds=30.0,
            provision_estimate_seconds=30.0,
            savings_horizon_seconds=3600.0,
            gang_cooldown_seconds=600.0)
        self.controller = Controller(
            self.kube, self.actuator,
            ControllerConfig(
                policy=PoolPolicy(spare_nodes=0),
                grace_seconds=30.0, idle_threshold_seconds=600.0,
                drain_grace_seconds=20.0,
                enable_repack=repack, repack=repack_cfg, **cfg_kw),
            serving_scaler=serving_scaler)
        self.jobs: dict[str, dict] = {}
        self.t = 0.0

    def launch(self, job, shape, pinned=True, count=None):
        """``count`` keeps only the first N member pods — a partial
        gang whose chip demand undershoots the slice shapes it can
        bind to (the overprovisioned-placement generator)."""
        spec = {"job": job, "shape": shape, "pinned": pinned,
                "count": count}
        names = []
        for p in gang_pods(shape, job, pin_topology=pinned)[:count]:
            self.kube.add_pod(p)
            names.append(p["metadata"]["name"])
        spec["names"] = names
        self.jobs[job] = spec

    def add_idle_slice(self, shape_name, sid, *, preemptible=True,
                       pool=None):
        shape = shape_by_name(shape_name)
        for i in range(shape.hosts):
            self.kube.add_node(tpu_host_payload(
                shape, sid, i, created_at=self.t,
                pool=pool or ("spot-pool" if preemptible else "od-pool"),
                preemptible=preemptible))

    def _world_model(self):
        node_names = {n["metadata"]["name"]
                      for n in self.kube.list_nodes()}
        for p in list(self.kube.list_pods()):
            if p["spec"].get("nodeName") \
                    and p["spec"]["nodeName"] not in node_names:
                self.kube.delete_pod(
                    p["metadata"].get("namespace", "default"),
                    p["metadata"]["name"])
        for spec in self.jobs.values():
            fresh = {p["metadata"]["name"]: p
                     for p in gang_pods(spec["shape"], spec["job"],
                                        pin_topology=spec["pinned"]
                                        )[:spec.get("count")]}
            for n in spec["names"]:
                if self.kube.get_pod("default", n) is None:
                    self.kube.add_pod(fresh[n])

    def step(self, n=1, dt=5.0):
        for _ in range(n):
            self._world_model()
            self.controller.reconcile_once(now=self.t)
            self.kube.schedule_step()
            assert self.controller.cost.conservation_violations == 0
            self.t += dt

    def counters(self):
        return self.controller.metrics.snapshot()["counters"]

    def all_running(self):
        pods = self.kube.list_pods()
        return bool(pods) and all(p["status"]["phase"] == "Running"
                                  for p in pods)

    def gang_tiers(self, job):
        nodes = {n["metadata"]["name"]: n
                 for n in self.kube.list_nodes()}
        tiers = set()
        for p in self.kube.list_pods():
            if not p["metadata"]["name"].startswith(job):
                continue
            labels = nodes.get(p["spec"].get("nodeName", ""),
                               {}).get("metadata", {}).get("labels", {})
            tiers.add("spot" if labels.get("cloud.google.com/gke-spot")
                      else "on_demand")
        return tiers


class TestDisplaceMigration:
    def test_gang_moves_to_idle_spot_and_source_is_released(self):
        w = RepackWorld()
        w.launch("job-a", "v5e-16")
        w.step(12)
        assert w.all_running()
        assert w.gang_tiers("job-a") == {"on_demand"}
        w.add_idle_slice("v5e-16", "spot-s0")
        w.step(40)
        c = w.counters()
        assert c.get("repack_migrations_started") == 1
        assert c.get("repack_migrations_completed") == 1
        assert w.all_running()
        assert w.gang_tiers("job-a") == {"spot"}
        # The expensive source slice was released whole.
        assert any(u.startswith("v5e-16-prov")
                   for u in w.actuator.deleted_units)
        assert c.get("repack_chip_seconds_saved", 0) > 0

    def test_trace_closes_with_attribution(self):
        from tpu_autoscaler.obs import trace_gaps

        w = RepackWorld()
        w.launch("job-a", "v5e-16")
        w.step(12)
        w.add_idle_slice("v5e-16", "spot-s0")
        w.step(40)
        dump = w.controller.debug_dump()
        roots = [s for s in dump["spans"] if s["name"] == "repack"
                 and s["parent_id"] is None]
        assert len(roots) == 1
        root = roots[0]
        assert root["end"] is not None
        assert root["attrs"]["chip_seconds_saved"] > 0
        assert root["attrs"]["dollar_proxy_saved"] > 0
        assert "migration_cost_chip_seconds" in root["attrs"]
        assert trace_gaps(dump, root["trace_id"]) == []
        # Children: the drain phase at minimum.
        names = {s["name"] for s in dump["spans"]
                 if s["trace_id"] == root["trace_id"]}
        assert "repack_drain" in names

    def test_no_migration_without_repack_enabled(self):
        w = RepackWorld(repack=False)
        w.launch("job-a", "v5e-16")
        w.step(12)
        w.add_idle_slice("v5e-16", "spot-s0")
        w.step(40)
        assert w.counters().get("repack_migrations_started") is None
        assert w.gang_tiers("job-a") == {"on_demand"}

    def test_cooldown_prevents_thrash(self):
        w = RepackWorld()
        w.launch("job-a", "v5e-16")
        w.step(12)
        w.add_idle_slice("v5e-16", "spot-s0")
        w.step(40)
        # One more idle spot slice appears; the gang is already on
        # spot so no displacement — and even a hypothetical candidate
        # is inside its cooldown.  No second migration.
        w.add_idle_slice("v5e-16", "spot-s1")
        w.step(20)
        assert w.counters().get("repack_migrations_started") == 1


class TestRightsizeMigration:
    def test_oversized_gang_moves_to_fitted_slice(self):
        w = RepackWorld()
        # A partial unpinned podslice gang (2 of v5e-16's 4 pods =
        # 8 chips) binds to the only free supply, an idle on-demand
        # v5e-32 — a topology-poor placement stranding 24 chips
        # INSIDE a busy unit.  The fitter right-sizes it to the
        # smallest feasible podslice shape and the repacker migrates.
        w.add_idle_slice("v5e-32", "big-s0", preemptible=False)
        w.launch("job-b", "v5e-16", pinned=False, count=2)
        w.step(4)
        assert w.all_running()
        bound = {p["spec"]["nodeName"] for p in w.kube.list_pods()
                 if p["spec"].get("nodeName")}
        assert all(b.startswith("big-s0") for b in bound)
        w.step(48)
        c = w.counters()
        assert c.get("repack_migrations_started") == 1
        assert c.get("repack_migrations_completed") == 1
        assert w.all_running()
        # The gang now runs on a right-sized slice; the v5e-32 is gone.
        bound = {p["spec"]["nodeName"] for p in w.kube.list_pods()
                 if p["spec"].get("nodeName")}
        assert all(not b.startswith("big-s0") for b in bound)
        assert "big-s0" in w.actuator.deleted_units
        assert c.get("repack_chip_seconds_saved", 0) > 0


class TestBudgetGuardAbort:
    def test_destination_vanishes_mid_drain_aborts_planner_reachable(
            self):
        w = RepackWorld(provision_delay=30.0)
        w.launch("job-a", "v5e-16")
        w.step(12)
        assert w.all_running()
        w.add_idle_slice("v5e-16", "spot-s0")
        # Step until the migration starts (drain begins).
        for _ in range(30):
            w.step(1)
            if w.counters().get("repack_migrations_started"):
                break
        assert w.counters().get("repack_migrations_started") == 1
        # Spot market dries up: the destination slice disappears
        # before the gang landed (its nodes are still workload-free).
        for n in list(w.kube.list_nodes()):
            if n["metadata"]["name"].startswith("spot-s0"):
                w.kube.delete_node(n["metadata"]["name"])
        w.step(30)
        c = w.counters()
        assert c.get("repack_migrations_aborted") == 1
        assert not c.get("repack_migrations_completed")
        # Planner-reachable: the gang converges Running again and no
        # bookkeeping is left open.
        assert w.all_running()
        assert not w.controller._slice_repairs
        assert w.gang_tiers("job-a") == {"on_demand"}
        # The trace closed, explained.
        dump = w.controller.debug_dump()
        roots = [s for s in dump["spans"] if s["name"] == "repack"
                 and s["parent_id"] is None]
        assert len(roots) == 1
        assert roots[0]["end"] is not None
        assert roots[0]["attrs"].get("aborted") is True
        assert "reason" in roots[0]["attrs"]


class TestServingExclusion:
    def test_burning_pool_replicas_never_migrated(self):
        """Serving pool names are LOGICAL — the do-not-touch mapping
        rides the serve-<pool>-<n> gang-name convention, not node-pool
        labels (review-found: a label-only check never fires)."""
        w = RepackWorld(
            serving_scaler=_StubServingScaler({"web"}))
        w.launch("serve-web-1", "v5e-16")
        w.step(12)
        assert w.all_running()
        w.add_idle_slice("v5e-16", "spot-s0")
        w.step(30)
        assert w.counters().get("repack_migrations_started") is None

    def test_healthy_pool_replicas_still_migrate(self):
        w = RepackWorld(serving_scaler=_StubServingScaler(set()))
        w.launch("serve-web-1", "v5e-16")
        w.step(12)
        w.add_idle_slice("v5e-16", "spot-s0")
        w.step(40)
        assert w.counters().get("repack_migrations_completed") == 1


class TestAbandonCleanup:
    def test_timed_out_migration_cancels_provision_and_uncordons(self):
        """Review-found: the timeout close must run the SAME cleanup
        as a budget abort — cancel the replacement provision, uncordon
        the un-landed source — or it leaks an orphan provision and
        drains a healthy slice for nothing."""
        w = RepackWorld(
            slice_repair_timeout_seconds=120.0,
            repack_cfg=RepackConfig(
                min_dwell_seconds=30.0, drain_estimate_seconds=30.0,
                provision_estimate_seconds=1e6,  # guard never trips
                savings_horizon_seconds=1e9,
                gang_cooldown_seconds=600.0))
        w.launch("job-a", "v5e-16")
        w.step(12)
        assert w.all_running()
        w.add_idle_slice("v5e-16", "spot-s0")
        for _ in range(30):
            w.step(1)
            if w.counters().get("repack_migrations_started"):
                break
        assert w.counters().get("repack_migrations_started") == 1
        # The destination vanishes and every new provision stalls
        # forever: with the guard silenced the migration can never
        # finish and must hit the timeout.
        for n in list(w.kube.list_nodes()):
            if n["metadata"]["name"].startswith("spot-s0"):
                w.kube.delete_node(n["metadata"]["name"])
        w.controller._guard_repacks = lambda *a, **k: None
        w.actuator.set_provision_delay(1e9)
        linked = None
        for _ in range(40):
            w.step(1)
            st = next(iter(w.controller._slice_repairs.values()), None)
            if st is not None and st.get("provision_id"):
                linked = st["provision_id"]
            if w.counters().get("repack_migrations_abandoned"):
                break
        c = w.counters()
        assert c.get("repack_migrations_abandoned") == 1
        assert not w.controller._slice_repairs
        # The LINKED replacement provision was cancelled at close (an
        # organic re-provision for the re-pended gang may follow —
        # that one is the planner's business, not the migration's).
        assert linked is not None
        assert not any(s.id == linked and s.in_flight
                       for s in w.actuator.statuses())
        # Planner-reachable: restore the cloud and the gang converges
        # back onto on-demand supply.
        w.actuator.set_provision_delay(10.0)
        w.step(30)
        assert w.all_running()
        assert w.gang_tiers("job-a") == {"on_demand"}


class TestRepackRoute:
    def test_debugz_repack_body(self):
        w = RepackWorld()
        w.launch("job-a", "v5e-16")
        w.step(12)
        w.add_idle_slice("v5e-16", "spot-s0")
        w.step(40)
        body = w.controller.repack_route()
        assert body["totals"]["completed"] == 1
        assert body["recent"][-1]["outcome"] == "completed"
        assert body["active"] == []
        # And the incident bundle carries the same section.
        bundle = w.controller.incident_bundle("test")
        assert bundle["repack"]["totals"]["completed"] == 1

    def test_disabled_route_says_so(self):
        w = RepackWorld(repack=False)
        w.step(2)
        assert w.controller.repack_route()["disabled"] is True


# ---------------------------------------------------------------------------
# Seeded churn property suite: repack never worse than no-repack.


def _churn_world(seed: int, repack: bool) -> RepackWorld:
    """One seeded churn scenario: gangs on on-demand supply, spot
    slices appearing over time, deterministic per seed."""
    rng = random.Random(seed)
    w = RepackWorld(repack=repack)
    shapes = [rng.choice(("v5e-8", "v5e-16")) for _ in range(2)]
    for i, shape in enumerate(shapes):
        w.launch(f"job-{seed}-{i}", shape)
    w.step(14)
    # Spot capacity frees up for a random subset of the shapes.
    for i, shape in enumerate(shapes):
        if rng.random() < 0.7:
            w.add_idle_slice(shape, f"spot-{seed}-{i}")
    w.step(50)
    return w


@pytest.mark.parametrize("seed", range(8))
def test_repack_dollar_proxy_never_worse(seed):
    """The acceptance property: on every seed, the fleet's steady-state
    $-proxy burn with the repacker ON is never worse than OFF, the
    conservation identity held through every migration (asserted per
    step inside RepackWorld.step), and the ledger's incremental
    counters match the rebuild oracle at the end."""
    on = _churn_world(seed, repack=True)
    off = _churn_world(seed, repack=False)
    assert on.all_running() and off.all_running()
    rate_on = on.controller.metrics.snapshot()["gauges"][
        "cost_dollar_proxy_per_hour"]
    rate_off = off.controller.metrics.snapshot()["gauges"][
        "cost_dollar_proxy_per_hour"]
    assert rate_on <= rate_off + 1e-9, (
        f"seed {seed}: repack burned ${rate_on}/h vs ${rate_off}/h "
        f"without")
    for w in (on, off):
        live, rebuilt = (w.controller.cost.live_counts(),
                         w.controller.cost.rebuild())
        for key in live:
            assert live[key] == {k: v for k, v in rebuilt[key].items()
                                 if v}, f"seed {seed}: {key} drifted"


def test_ledger_placement_quality_rows():
    w = RepackWorld(repack=False)
    w.launch("job-a", "v5e-16")
    w.step(12)
    w.add_idle_slice("v5e-16", "spot-s0")
    w.step(2)
    pq = w.controller.cost.placement_quality()
    rows = {r["unit_id"]: r for r in pq["rows"]}
    assert len(rows) == 1
    row = next(iter(rows.values()))
    assert row["tier"] == "on_demand"
    assert row["shape"] == "v5e-16"
    assert row["chips"] == 16 and row["used_chips"] == 16
    assert pq["idle_spot_chips"] == {"v5e-16": 16}


class TestCliSurfaces:
    """The operator surfaces (ISSUE 12 satellites): ``repack-report``,
    ``cost-report --frag``, and the glob-capable ``metrics-history``
    prefix filter with url/file parity."""

    def _migrated_world(self):
        w = RepackWorld()
        w.launch("job-a", "v5e-16")
        w.step(12)
        w.add_idle_slice("v5e-16", "spot-s0")
        w.step(40)
        assert w.counters().get("repack_migrations_completed") == 1
        return w

    def _bundle_file(self, w, tmp_path):
        import json

        path = tmp_path / "bundle.json"
        path.write_text(json.dumps(
            w.controller.incident_bundle("test"), default=str))
        return str(path)

    def test_repack_report_from_bundle(self, tmp_path):
        from click.testing import CliRunner

        from tpu_autoscaler.main import cli

        path = self._bundle_file(self._migrated_world(), tmp_path)
        result = CliRunner().invoke(cli, ["repack-report", "--from",
                                          path])
        assert result.exit_code == 0, result.output
        assert "REPACK REPORT" in result.output
        assert "1 completed" in result.output
        assert "saved" in result.output

    def test_repack_report_rejects_sectionless_dump(self, tmp_path):
        import json

        from click.testing import CliRunner

        from tpu_autoscaler.main import cli

        path = tmp_path / "plain.json"
        path.write_text(json.dumps({"passes": []}))
        result = CliRunner().invoke(cli, ["repack-report", "--from",
                                          str(path)])
        assert result.exit_code != 0
        assert "no repack section" in result.output

    def test_cost_report_frag_section(self, tmp_path):
        from click.testing import CliRunner

        from tpu_autoscaler.main import cli

        w = RepackWorld(repack=False)
        w.launch("job-a", "v5e-16")
        w.step(12)
        path = self._bundle_file(w, tmp_path)
        result = CliRunner().invoke(cli, ["cost-report", "--from",
                                          path, "--frag"])
        assert result.exit_code == 0, result.output
        assert "FRAGMENTATION" in result.output
        assert "score=" in result.output
        # Without the flag the section stays out (the bill is long
        # enough already).
        plain = CliRunner().invoke(cli, ["cost-report", "--from",
                                         path])
        assert "FRAGMENTATION" not in plain.output

    @pytest.mark.parametrize("pattern,family", [
        ("repack_*", "repack_"),
        ("frag_score_*", "frag_score_"),
    ])
    def test_metrics_history_glob_url_file_parity(
            self, tmp_path, monkeypatch, pattern, family):
        """The ISSUE 12 regression pin: a glob series filter yields
        IDENTICAL output whether it runs against a live controller's
        ``/debugz/tsdb`` (server-side literal-head prefix + client
        glob) or a bundle file (pure client-side)."""
        from click.testing import CliRunner

        import tpu_autoscaler.main as main_mod
        from tpu_autoscaler.main import cli

        w = self._migrated_world()
        path = self._bundle_file(w, tmp_path)

        def fake_fetch(url, endpoint, params=None):
            assert endpoint == "/debugz/tsdb"
            # The live route applies the server-side PLAIN prefix —
            # the glob's literal head must have been sent, never the
            # raw glob (a server matching 'repack_*' as a literal
            # would return nothing).
            assert "*" not in (params or {}).get("prefix", "")
            return w.controller.tsdb_route(params or {})

        monkeypatch.setattr(main_mod, "_fetch_debugz", fake_fetch)
        runner = CliRunner()
        via_url = runner.invoke(cli, [
            "metrics-history", "--url", "host:1", "--prefix", pattern,
            "--format", "csv"])
        via_file = runner.invoke(cli, [
            "metrics-history", "--from", path, "--prefix", pattern,
            "--format", "csv"])
        assert via_url.exit_code == 0, via_url.output
        assert via_file.exit_code == 0, via_file.output
        assert via_url.output == via_file.output
        names = [line.split(",")[0]
                 for line in via_url.output.strip().splitlines()[1:]]
        assert names, f"glob {pattern!r} matched nothing"
        assert all(n.startswith(family) for n in names)

    def test_metrics_history_plain_prefix_still_prefix(self, tmp_path):
        from click.testing import CliRunner

        from tpu_autoscaler.main import cli

        path = self._bundle_file(self._migrated_world(), tmp_path)
        result = CliRunner().invoke(cli, [
            "metrics-history", "--from", path, "--prefix", "repack_",
            "--format", "csv"])
        names = [line.split(",")[0]
                 for line in result.output.strip().splitlines()[1:]]
        assert names and all(n.startswith("repack_") for n in names)


def test_budget_remaining_shared_algebra():
    from tpu_autoscaler.policy.slo import budget_remaining

    events = [(0.0, 100.0), (50.0, 200.0), (120.0, 50.0)]
    kept, spent, remaining = budget_remaining(events, 130.0, 100.0,
                                              300.0)
    assert kept == [(50.0, 200.0), (120.0, 50.0)]
    assert spent == 250.0
    assert remaining == 50.0
    # Never negative.
    _, _, remaining = budget_remaining(events, 130.0, 100.0, 100.0)
    assert remaining == 0.0
