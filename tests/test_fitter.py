"""Fit engine tests: shape choice + bin packing (reference: test_cluster.py
scale-up unit math)."""

import pytest

from tpu_autoscaler.engine.fitter import (
    FitError,
    choose_shape_for_gang,
    free_capacity,
    pack_cpu_pods,
)
from tpu_autoscaler.k8s.gangs import group_into_gangs
from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.topology import shape_by_name
from tpu_autoscaler.topology.catalog import (
    ACCELERATOR_LABEL,
    DEFAULT_CPU_SHAPE,
    TOPOLOGY_LABEL,
)

from tests.fixtures import make_gang, make_node, make_pod, make_tpu_pod


def gang_of(payloads):
    gangs = group_into_gangs([Pod(p) for p in payloads])
    assert len(gangs) == 1
    return gangs[0]


class TestChooseShape:
    def test_exact_topology_pin(self):
        shape = shape_by_name("v5e-64")
        choice = choose_shape_for_gang(gang_of(make_gang(shape, job="j")))
        assert choice.shape.name == "v5e-64"
        assert choice.stranded_chips == 0

    def test_topology_pin_too_small_fails(self):
        shape = shape_by_name("v5e-8")
        g = gang_of([make_tpu_pod(chips=16, shape=shape, job="j")])
        with pytest.raises(FitError, match="pins"):
            choose_shape_for_gang(g)

    def test_accelerator_only_rounds_up(self):
        g = gang_of([make_tpu_pod(
            chips=4, job="j",
            selectors={ACCELERATOR_LABEL: "tpu-v5p-slice"},
            requests={"google.com/tpu": "4"})])
        # 1 pod x 4 chips on v5p -> smallest v5p shape with >= 4 chips.
        choice = choose_shape_for_gang(g)
        assert choice.shape.name == "v5p-4"
        assert choice.stranded_chips == 0

    def test_no_selectors_uses_default_generation(self):
        g = gang_of([make_tpu_pod(chips=8, job="j")])
        choice = choose_shape_for_gang(g, default_generation="v5e")
        assert choice.shape.name == "v5e-8"

    def test_stranded_chips_computed(self):
        g = gang_of([make_tpu_pod(chips=5, job="j")])
        choice = choose_shape_for_gang(g)
        assert choice.shape.name == "v5e-8"
        assert choice.stranded_chips == 3
        assert choice.stranded_pct == pytest.approx(37.5)

    def test_demand_too_large(self):
        g = gang_of([make_tpu_pod(chips=4096, job="j")])
        with pytest.raises(FitError, match="largest"):
            choose_shape_for_gang(g, default_generation="v5e")

    def test_unknown_accelerator(self):
        g = gang_of([make_tpu_pod(
            chips=8, job="j", selectors={ACCELERATOR_LABEL: "tpu-v99"})])
        with pytest.raises(FitError, match="unknown accelerator"):
            choose_shape_for_gang(g)

    def test_unknown_topology_pin(self):
        g = gang_of([make_tpu_pod(
            chips=8, job="j",
            selectors={ACCELERATOR_LABEL: "tpu-v5p-slice",
                       TOPOLOGY_LABEL: "3x3x3"})])
        with pytest.raises(FitError, match="no catalog shape"):
            choose_shape_for_gang(g)

    def test_north_star_256_chips(self):
        # The north-star job: 256 chips on v5p, 0 stranded.
        shape = shape_by_name("v5p-256")
        choice = choose_shape_for_gang(gang_of(make_gang(shape, job="big")))
        assert choice.shape.name == "v5p-256"
        assert choice.stranded_chips == 0
        assert choice.shape.hosts == 64

    def test_cpu_gang_rejected(self):
        g = gang_of([make_pod(requests={"cpu": "2"})])
        with pytest.raises(FitError, match="no TPU chips"):
            choose_shape_for_gang(g)


class TestFreeCapacity:
    def test_subtracts_bound_pods(self):
        nodes = [Node(make_node(name="n1"))]
        pods = [Pod(make_pod(name="p", phase="Running", node_name="n1",
                             requests={"cpu": "2"}, unschedulable=False))]
        free = free_capacity(nodes, pods)
        assert free["n1"].get("cpu") == pytest.approx(7.91 - 2)

    def test_skips_notready_and_cordoned(self):
        nodes = [Node(make_node(name="bad", ready=False)),
                 Node(make_node(name="cordoned", unschedulable=True))]
        assert free_capacity(nodes, []) == {}


class TestPackCpuPods:
    def pod(self, cpu, name="p"):
        return Pod(make_pod(name=name, requests={"cpu": cpu}))

    def test_fits_existing(self):
        free = {"n1": Node(make_node()).allocatable}
        count, unplaced = pack_cpu_pods([self.pod("2")], free,
                                        DEFAULT_CPU_SHAPE)
        assert (count, unplaced) == (0, [])

    def test_needs_new_nodes(self):
        # 3 pods x 3 cpu, unit holds 7.91 -> 2 per node -> 2 new nodes.
        pods = [self.pod("3", f"p{i}") for i in range(3)]
        count, unplaced = pack_cpu_pods(pods, {}, DEFAULT_CPU_SHAPE)
        assert (count, unplaced) == (2, [])

    def test_pod_too_big_for_unit_surfaced(self):
        big = self.pod("64")
        count, unplaced = pack_cpu_pods([big], {}, DEFAULT_CPU_SHAPE)
        assert count == 0
        assert unplaced == [big]

    def test_first_fit_uses_remaining_unit_space(self):
        pods = [self.pod("4", "a"), self.pod("3", "b"), self.pod("4", "c")]
        # a+b share node 1 (7 <= 7.91), c -> node 2.
        count, _ = pack_cpu_pods(pods, {}, DEFAULT_CPU_SHAPE)
        assert count == 2


class TestPerHostFeasibility:
    """Review regression: total chips alone is not feasibility."""

    def test_pod_chips_exceed_host_chips_rejected(self):
        # 3 pods x 8 chips = 24 total; v5e-32 hosts expose only 4 chips.
        pods = [make_tpu_pod(name=f"p{i}", chips=8, job="j",
                             requests={"google.com/tpu": "8"})
                for i in range(3)]
        g = gang_of(pods)
        with pytest.raises(FitError, match="no v5e shape"):
            choose_shape_for_gang(g)

    def test_more_pods_than_host_slots_rejected(self):
        # v5e-16: 4 hosts x 4 chips. 5 pods x 3 chips = 15 <= 16 total, but
        # each host fits only one 3-chip pod -> 4 slots < 5 pods.
        shape = shape_by_name("v5e-16")
        pods = [make_tpu_pod(name=f"p{i}", chips=3, shape=shape, job="j",
                             requests={"google.com/tpu": "3"})
                for i in range(5)]
        with pytest.raises(FitError, match="host slots"):
            choose_shape_for_gang(gang_of(pods))

    def test_two_pods_share_one_host(self):
        # 2 pods x 4 chips on a v5e-8 single host: 2 slots, feasible.
        shape = shape_by_name("v5e-8")
        pods = [make_tpu_pod(name=f"p{i}", chips=4, shape=shape, job="j",
                             requests={"google.com/tpu": "4"})
                for i in range(2)]
        assert choose_shape_for_gang(gang_of(pods)).shape.name == "v5e-8"
