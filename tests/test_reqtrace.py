"""Request-level data-plane tracing (ISSUE 14, serving/reqtrace.py).

The sampler's contract: deterministic head sampling, ALWAYS-captured
tail (SLO misses, preemptions, drain losses), gap-free span trees
(obs.trace_gaps knows the request shape), bounded memory under any
load, O(1) hooks wired into the real batcher family's host-side
bookkeeping, and the queue-wait/execute split riding the stats
recorder + serve.py's final-stats receipt.
"""

from __future__ import annotations

import numpy as np
import pytest

from tpu_autoscaler.obs.recorder import trace_gaps
from tpu_autoscaler.serving.reqtrace import (
    RequestTraceSampler,
    head_sampled,
)
from tpu_autoscaler.serving.stats import ServingStatsRecorder


class TestHeadSampling:
    def test_deterministic_and_rate_shaped(self):
        ids = [f"r{i}" for i in range(20_000)]
        picked = [rid for rid in ids if head_sampled(rid, 0.01)]
        again = [rid for rid in ids if head_sampled(rid, 0.01)]
        assert picked == again            # pure function of the id
        assert 0.003 < len(picked) / len(ids) < 0.03
        assert not any(head_sampled(r, 0.0) for r in ids[:100])
        assert all(head_sampled(r, 1.0) for r in ids[:100])


class TestSamplerLifecycles:
    def test_unsampled_fast_request_leaves_nothing(self):
        s = RequestTraceSampler("rep", sample_rate=0.0, slo_ticks=100)
        s.note_submit("r1", 0)
        s.note_admit("r1", 1)
        s.note_seeded("r1", 2)
        assert s.note_finish("r1", 5) is None
        assert s.sampled_total == 0
        assert s.pending == 0
        assert s.dump()["spans"] == []

    def test_slo_miss_is_tail_captured_and_gap_free(self):
        s = RequestTraceSampler("rep", sample_rate=0.0, slo_ticks=4)
        s.note_submit("r1", 0)
        s.note_admit("r1", 3)
        s.note_seeded("r1", 4)
        tid = s.note_finish("r1", 9, tokens=5)
        assert tid == "request-rep-r1"
        dump = s.dump()
        assert trace_gaps(dump, tid) == []
        root = next(sp for sp in dump["spans"]
                    if sp["name"] == "request")
        assert root["attrs"]["slo_miss"] is True
        assert root["attrs"]["sampled"] == "tail"
        names = {sp["name"] for sp in dump["spans"]}
        assert {"queue_wait", "prefill", "decode"} <= names
        assert s.tail_captured_total == 1

    def test_preempted_request_always_captured_with_requeue_span(self):
        s = RequestTraceSampler("rep", sample_rate=0.0,
                                slo_ticks=10_000)
        s.note_submit("r1", 0)
        s.note_admit("r1", 1)
        s.note_seeded("r1", 2)
        s.note_preempt("r1", 5)
        s.note_admit("r1", 8)
        s.note_seeded("r1", 9)
        tid = s.note_finish("r1", 12)
        dump = s.dump()
        assert trace_gaps(dump, tid) == []
        requeue = [sp for sp in dump["spans"]
                   if sp["name"] == "preempt_requeue"]
        assert len(requeue) == 1
        assert requeue[0]["start"] == 5 and requeue[0]["end"] == 8
        decodes = [sp for sp in dump["spans"]
                   if sp["name"] == "decode"]
        assert len(decodes) == 2   # one per seeded window, not per token

    def test_drain_lost_request_always_captured(self):
        s = RequestTraceSampler("rep", sample_rate=0.0, slo_ticks=None)
        s.note_submit("r9", 0)
        tid = s.note_drain_lost("r9", 7)
        dump = s.dump()
        assert trace_gaps(dump, tid) == []
        root = next(sp for sp in dump["spans"]
                    if sp["name"] == "request")
        assert root["attrs"]["lost"] is True
        assert any(sp["name"] == "drain_handoff"
                   for sp in dump["spans"])

    def test_forwarded_request_is_not_lost(self):
        s = RequestTraceSampler("rep", sample_rate=1.0)
        s.note_submit("r1", 0)
        s.note_forward("r1")
        assert s.pending == 0
        assert s.rerouted_total == 1
        assert s.dump()["spans"] == []

    def test_note_cohort_fast_path_and_promotion(self):
        s = RequestTraceSampler("rep", sample_rate=0.0, slo_ticks=10.0)
        assert s.note_cohort("c1", arrival=0.0, finish=5.0, n=7,
                             exec_time=2.0) is None
        tid = s.note_cohort("c1", arrival=0.0, finish=30.0, n=3,
                            exec_time=2.0)
        assert tid is not None
        dump = s.dump()
        assert trace_gaps(dump, tid) == []
        root = next(sp for sp in dump["spans"]
                    if sp["name"] == "request")
        assert root["attrs"]["n"] == 3
        qw = next(sp for sp in dump["spans"]
                  if sp["name"] == "queue_wait")
        assert qw["end"] - qw["start"] == pytest.approx(28.0)

    def test_exemplar_and_counters_mirror_into_stats(self):
        rec = ServingStatsRecorder(slots=4, slo_ticks=4)
        s = RequestTraceSampler("rep", sample_rate=0.0, slo_ticks=4,
                                stats=rec)
        s.note_submit("r1", 0)
        s.note_admit("r1", 1)
        s.note_seeded("r1", 2)
        tid = s.note_finish("r1", 9)
        snap = rec.snapshot()
        assert snap.exemplar_trace_id == tid
        assert snap.exemplar_value == 9.0
        assert snap.exemplar_seq == 1
        assert snap.trace_sampled_total == 1
        assert snap.trace_tail_total == 1


class TestSamplerBounds:
    def test_pending_overflow_drops_oldest_and_counts(self):
        rec = ServingStatsRecorder(slots=1)
        s = RequestTraceSampler("rep", sample_rate=1.0, max_pending=8,
                                stats=rec)
        for i in range(50):
            s.note_submit(f"r{i}", i)
        assert s.pending == 8
        assert s.dropped_total == 42
        assert rec.snapshot().trace_dropped_total == 42
        # The survivors still promote normally.
        assert s.note_finish("r49", 100) is not None

    def test_event_cap_yields_declared_truncation(self):
        s = RequestTraceSampler("rep", sample_rate=1.0, max_events=6)
        s.note_submit("r1", 0)
        for i in range(1, 30):
            s.note_preempt("r1", i)
        tid = s.note_finish("r1", 40)
        dump = s.dump()
        root = next(sp for sp in dump["spans"]
                    if sp["name"] == "request")
        assert root["attrs"]["truncated"] is True
        # Declared truncation exempts the phase contract.
        assert trace_gaps(dump, tid) == []

    def test_trace_ring_is_bounded(self):
        s = RequestTraceSampler("rep", sample_rate=1.0, max_traces=4)
        for i in range(40):
            s.note_cohort(f"c{i}", arrival=0.0, finish=1.0)
        assert s.sampled_total == 40
        assert len(s.dump()["spans"]) <= 4 * 8


class TestEngineIntegration:
    @pytest.fixture(scope="class")
    def engine_setup(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp

        from tpu_autoscaler.workloads.model import (
            ModelConfig,
            init_params,
        )

        cfg = ModelConfig(vocab=32, d_model=16, n_layers=1, n_heads=2,
                          d_ff=32, seq_len=32, dtype=jnp.float32)
        return init_params(jax.random.PRNGKey(0), cfg), cfg

    def test_continuous_batcher_emits_gap_free_traces(self,
                                                      engine_setup):
        from tpu_autoscaler.workloads.serving import (
            ContinuousBatcher,
            Request,
        )

        params, cfg = engine_setup
        sampler = RequestTraceSampler("eng", sample_rate=1.0)
        eng = ContinuousBatcher(params, cfg, slots=2, max_len=32,
                                chunk=8, slo_ticks=100,
                                reqtrace=sampler)
        rng = np.random.default_rng(0)
        reqs = [Request(prompt=rng.integers(0, cfg.vocab, (n,)).astype(
                    np.int32), max_new_tokens=2) for n in (3, 5, 2)]
        for r in reqs:
            eng.submit(r)
        eng.run()
        assert sampler.sampled_total == 3
        dump = sampler.dump()
        roots = [sp for sp in dump["spans"] if sp["name"] == "request"]
        assert len(roots) == 3
        for root in roots:
            assert trace_gaps(dump, root["trace_id"]) == []
            assert root["attrs"]["tokens"] == 2
        # Wait split: every request was scheduled exactly once.
        snap = eng.stats()
        assert snap.first_scheduled_total == 3
        assert all(r.first_scheduled_tick is not None for r in reqs)

    def test_paged_preemption_requeue_split(self, engine_setup):
        from tpu_autoscaler.workloads.paged import (
            PagedBatcher,
            Request,
        )

        params, cfg = engine_setup
        sampler = RequestTraceSampler("pag", sample_rate=0.0,
                                      slo_ticks=10_000)
        eng = PagedBatcher(params, cfg, slots=2, max_len=32,
                           block_size=8, num_blocks=4, chunk=8,
                           reqtrace=sampler)
        rng = np.random.default_rng(1)
        for n in (9, 9, 9):
            eng.submit(Request(
                prompt=rng.integers(0, cfg.vocab, (n,)).astype(
                    np.int32),
                max_new_tokens=4))
        eng.run()
        snap = eng.stats()
        if eng.preemptions:
            # Tail capture promoted every preempted request, and the
            # requeue wait landed in the recorder split.
            assert sampler.tail_captured_total >= 1
            assert snap.requeue_wait_ticks_total > 0
            dump = sampler.dump()
            roots = [sp for sp in dump["spans"]
                     if sp["name"] == "request"
                     and sp["attrs"]["preemptions"] > 0]
            assert roots
            for root in roots:
                assert trace_gaps(dump, root["trace_id"]) == []
        assert snap.first_scheduled_total >= 3

    def test_spec_engine_annotates_accept_economics(self,
                                                    engine_setup):
        jax = pytest.importorskip("jax")
        from tpu_autoscaler.workloads.spec_serving import (
            Request,
            SpeculativePagedBatcher,
        )

        params, cfg = engine_setup
        sampler = RequestTraceSampler("spec", sample_rate=1.0)
        eng = SpeculativePagedBatcher(
            params, cfg, params, cfg, k=2, slots=2, max_len=32,
            block_size=8, chunk=8, key=jax.random.PRNGKey(0),
            reqtrace=sampler)
        rng = np.random.default_rng(2)
        eng.submit(Request(
            prompt=rng.integers(0, cfg.vocab, (4,)).astype(np.int32),
            max_new_tokens=4))
        eng.run()
        dump = sampler.dump()
        root = next(sp for sp in dump["spans"]
                    if sp["name"] == "request")
        assert "accept_rate" in root["attrs"]
        assert "target_pass_ratio" in root["attrs"]

    def test_drain_handoff_traces_lost_requests(self, engine_setup):
        from tpu_autoscaler.workloads.serving import (
            ContinuousBatcher,
            Request,
        )

        class _DrainNow:
            def drain_requested(self):
                return True

        params, cfg = engine_setup
        sampler = RequestTraceSampler("drain", sample_rate=0.0)
        eng = ContinuousBatcher(params, cfg, slots=1, max_len=32,
                                chunk=8, reqtrace=sampler)
        rng = np.random.default_rng(3)
        reqs = [Request(prompt=rng.integers(0, cfg.vocab, (3,)).astype(
                    np.int32), max_new_tokens=2) for _ in range(3)]
        for r in reqs:
            eng.submit(r)
        eng.run(watcher=_DrainNow())
        lost = [r for r in reqs if not r.done]
        assert lost                       # drain left queued requests
        dump = sampler.dump()
        roots = [sp for sp in dump["spans"]
                 if sp["name"] == "request" and sp["attrs"].get("lost")]
        assert len(roots) == len(lost)
        for root in roots:
            assert trace_gaps(dump, root["trace_id"]) == []


class TestFinalStatsSplit:
    @pytest.fixture(scope="class")
    def engine_setup(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp

        from tpu_autoscaler.workloads.model import (
            ModelConfig,
            init_params,
        )

        cfg = ModelConfig(vocab=32, d_model=16, n_layers=1, n_heads=2,
                          d_ff=32, seq_len=32, dtype=jnp.float32)
        return init_params(jax.random.PRNGKey(0), cfg), cfg

    def test_receipt_carries_wait_exec_split(self, engine_setup):
        import json

        from tpu_autoscaler.workloads.serve import final_stats_payload
        from tpu_autoscaler.workloads.serving import (
            ContinuousBatcher,
            Request,
        )

        params, cfg = engine_setup
        eng = ContinuousBatcher(params, cfg, slots=1, max_len=32,
                                chunk=8)
        rng = np.random.default_rng(4)
        reqs = [Request(prompt=rng.integers(0, cfg.vocab, (4,)).astype(
                    np.int32), max_new_tokens=2) for _ in range(3)]
        for r in reqs:
            eng.submit(r)
        eng.run()
        out = final_stats_payload(reqs, eng, 0.5)
        assert len(out["request_wait_ticks"]) == 3
        assert len(out["request_exec_ticks"]) == 3
        for lat, wait, ex in zip(out["request_latency_ticks"],
                                 out["request_wait_ticks"],
                                 out["request_exec_ticks"]):
            assert lat == wait + ex
            assert wait >= 0 and ex >= 0
        # One slot: later requests waited for earlier ones.
        assert max(out["request_wait_ticks"]) > 0
        assert out["stats"]["first_scheduled_total"] == 3
        assert out["stats"]["queue_wait_ticks_total"] == sum(
            out["request_wait_ticks"])
        json.dumps(out)
