"""Exhaustive tests of the slice-shape catalog (SURVEY.md §8 step 1)."""

import pytest

from tpu_autoscaler.topology import (
    ACCELERATOR_LABEL,
    CPU_SHAPES,
    SLICE_SHAPES,
    TOPOLOGY_LABEL,
    TPU_RESOURCE,
    MultiSliceSpec,
    SliceShape,
    cpu_shape_by_name,
    shape_by_name,
    shape_from_selectors,
    shapes_for_generation,
    smallest_shape_for_chips,
)


class TestCatalogInvariants:
    def test_every_shape_consistent(self):
        for name, s in SLICE_SHAPES.items():
            assert name == f"{s.generation}-{s.chips}"
            prod = 1
            for d in s.topology:
                prod *= d
            assert prod == s.chips
            assert s.chips % s.chips_per_host == 0
            assert s.hosts == s.chips // s.chips_per_host
            assert s.host_cpu_m > 0 and s.host_memory > 0

    def test_topology_label_roundtrip(self):
        assert shape_by_name("v5e-64").topology_label == "8x8"
        assert shape_by_name("v5p-128").topology_label == "4x4x8"
        assert shape_by_name("v5p-256").topology_label == "4x8x8"
        assert shape_by_name("v5e-8").topology_label == "2x4"

    def test_driver_eval_shapes_present(self):
        # Every shape named in BASELINE.md's eval configs must exist.
        for name in ("v5e-8", "v5e-64", "v5p-128", "v5p-256"):
            assert name in SLICE_SHAPES

    def test_host_counts(self):
        assert shape_by_name("v5e-8").hosts == 1     # single-host
        assert shape_by_name("v5e-64").hosts == 16   # SURVEY §8: 16 hosts
        assert shape_by_name("v5p-256").hosts == 64
        assert shape_by_name("v5p-128").hosts == 32

    def test_multi_host_flag(self):
        assert not shape_by_name("v5e-8").multi_host
        assert shape_by_name("v5e-16").multi_host

    def test_v5p_product_name_counts_cores(self):
        # Real product naming counts TensorCores (2/chip on v5p).
        assert shape_by_name("v5p-128").product_name == "v5p-256"

    def test_node_capacity_exposes_tpu_resource(self):
        cap = shape_by_name("v5e-64").node_capacity()
        assert cap[TPU_RESOURCE] == 4.0
        cap8 = shape_by_name("v5e-8").node_capacity()
        assert cap8[TPU_RESOURCE] == 8.0

    def test_node_selectors_contract(self):
        sel = shape_by_name("v5e-64").node_selectors()
        assert sel[ACCELERATOR_LABEL] == "tpu-v5-lite-podslice"
        assert sel[TOPOLOGY_LABEL] == "8x8"

    def test_invalid_shape_rejected(self):
        with pytest.raises(ValueError):
            SliceShape(generation="v9", chips=7, topology=(2, 4),
                       chips_per_host=4, accelerator_type="x",
                       machine_type="m", host_cpu_m=1000, host_memory=1)
        with pytest.raises(ValueError):
            SliceShape(generation="v9", chips=6, topology=(2, 3),
                       chips_per_host=4, accelerator_type="x",
                       machine_type="m", host_cpu_m=1000, host_memory=1)


class TestLookups:
    def test_shape_by_name_unknown(self):
        with pytest.raises(KeyError, match="unknown slice shape"):
            shape_by_name("v9e-3")

    def test_shapes_for_generation_sorted(self):
        chips = [s.chips for s in shapes_for_generation("v5p")]
        assert chips == sorted(chips)
        with pytest.raises(KeyError):
            shapes_for_generation("v99")

    def test_smallest_shape_exact(self):
        assert smallest_shape_for_chips("v5e", 64).name == "v5e-64"
        assert smallest_shape_for_chips("v5p", 256).name == "v5p-256"

    def test_smallest_shape_rounds_up(self):
        assert smallest_shape_for_chips("v5e", 5).name == "v5e-8"
        assert smallest_shape_for_chips("v5e", 65).name == "v5e-128"
        assert smallest_shape_for_chips("v5p", 100).name == "v5p-128"

    def test_smallest_shape_too_big(self):
        assert smallest_shape_for_chips("v5e", 100000) is None

    def test_cpu_shapes(self):
        s = cpu_shape_by_name("e2-standard-8")
        assert s.cpu_m == 7910
        assert TPU_RESOURCE not in s.node_capacity()
        with pytest.raises(KeyError):
            cpu_shape_by_name("weird-machine")
        assert all(v.cpu_m > 0 for v in CPU_SHAPES.values())


class TestSelectorsInversion:
    def test_exact_pin(self):
        sel = {ACCELERATOR_LABEL: "tpu-v5p-slice", TOPOLOGY_LABEL: "4x8x8"}
        assert shape_from_selectors(sel).name == "v5p-256"

    def test_accelerator_only_picks_smallest(self):
        sel = {ACCELERATOR_LABEL: "tpu-v5p-slice"}
        assert shape_from_selectors(sel).name == "v5p-4"

    def test_no_tpu_selectors(self):
        assert shape_from_selectors({}) is None
        assert shape_from_selectors({"disktype": "ssd"}) is None

    def test_unknown_combination_raises(self):
        with pytest.raises(KeyError, match="no catalog shape"):
            shape_from_selectors({ACCELERATOR_LABEL: "tpu-v5p-slice",
                                  TOPOLOGY_LABEL: "3x3x3"})

    def test_all_shapes_invert(self):
        for s in SLICE_SHAPES.values():
            assert shape_from_selectors(s.node_selectors()).name == s.name


class TestMultiSlice:
    def test_2x_v5p_128(self):
        ms = MultiSliceSpec(shape=shape_by_name("v5p-128"), num_slices=2)
        assert ms.name == "2xv5p-128"
        assert ms.total_chips == 256
        assert ms.total_hosts == 64

    def test_invalid(self):
        with pytest.raises(ValueError):
            MultiSliceSpec(shape=shape_by_name("v5e-8"), num_slices=0)


class TestCatalogDataQuality:
    def test_machine_types_consistent_per_generation(self):
        """Multi-host shapes of one generation share one machine type
        (one host SKU per generation's slice pools)."""
        for gen in ("v4", "v5p", "v5e", "v6e"):
            machines = {s.machine_type for s in shapes_for_generation(gen)
                        if s.multi_host}
            assert len(machines) == 1, (gen, machines)

    def test_topology_dims_positive_and_labels_unique_per_generation(self):
        # Dims mirror GKE's real label strings (v5p-4 is "2x2x1" — NOT
        # ascending), so only positivity and per-generation label
        # uniqueness are invariants.
        for s in SLICE_SHAPES.values():
            assert all(d >= 1 for d in s.topology), s.name
        for gen in ("v4", "v5p", "v5e", "v6e"):
            labels = [s.topology_label for s in shapes_for_generation(gen)]
            assert len(labels) == len(set(labels)), gen
