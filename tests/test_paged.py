"""Paged KV cache engine (workloads/paged.py).

Oracle: single-sequence generate() and the linear ContinuousBatcher.
The paged engine's cache read gathers its pages into exactly the
contiguous per-row view the linear engine holds natively, so greedy
decoding must be bit-exact — plus the paged-only behaviors: block
accounting (live blocks ≤ live tokens + bounded slack), on-demand
growth, preemption under pool pressure, and batched multi-lane prefill.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpu_autoscaler.workloads.decode import generate  # noqa: E402
from tpu_autoscaler.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)
from tpu_autoscaler.workloads.paged import (  # noqa: E402
    BlockAllocator,
    PagedBatcher,
    Request,
)

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                  d_ff=64, seq_len=64, dtype=jnp.float32)


def oracle_rollouts(params, cfg, prompts, new_tokens):
    return [np.asarray(
        generate(params, jnp.asarray(p)[None], cfg, nt)[0, len(p):])
        for p, nt in zip(prompts, new_tokens)]


class TestBlockAllocator:
    def test_alloc_free_roundtrip(self):
        a = BlockAllocator(4)
        got = [a.alloc() for _ in range(4)]
        assert sorted(got) == [0, 1, 2, 3]
        assert a.alloc() is None and a.free_blocks == 0
        a.free([2, -1, 0])  # -1 (no block) must be ignored
        assert a.free_blocks == 2 and a.used_blocks == 2


class TestPagedParity:
    def test_mixed_lengths_match_oracle(self):
        """5 mixed-length greedy requests through 3 slots with block
        churn reproduce each single-sequence rollout exactly."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, CFG.vocab, (n,)).astype(np.int32)
                   for n in (5, 17, 33, 9, 41)]
        new_tokens = [6, 4, 8, 3, 5]
        want = oracle_rollouts(params, CFG, prompts, new_tokens)
        eng = PagedBatcher(params, CFG, slots=3, max_len=64,
                           block_size=8, chunk=8, prefill_lanes=2)
        reqs = [Request(prompt=p, max_new_tokens=nt)
                for p, nt in zip(prompts, new_tokens)]
        for r in reqs:
            eng.submit(r)
        eng.run()
        for r, w in zip(reqs, want):
            assert r.done
            np.testing.assert_array_equal(
                np.asarray(r.generated, np.int64), w)
        assert eng.preemptions == 0  # full-size pool: no pressure

    @pytest.mark.slow
    def test_moe_through_paged_engine(self):
        """MoE decode flows through the shared _ffn_residual: the paged
        engine serves expert models with the same greedy output as
        single-sequence generate."""
        cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                          d_ff=64, seq_len=64, dtype=jnp.float32,
                          moe_experts=4, moe_top_k=2,
                          moe_capacity_factor=8.0)
        params = init_params(jax.random.PRNGKey(3), cfg)
        rng = np.random.default_rng(13)
        prompts = [rng.integers(0, cfg.vocab, (n,)).astype(np.int32)
                   for n in (7, 15)]
        want = oracle_rollouts(params, cfg, prompts, [4, 4])
        eng = PagedBatcher(params, cfg, slots=2, max_len=64,
                           block_size=8, chunk=8)
        reqs = [Request(prompt=p, max_new_tokens=4) for p in prompts]
        for r in reqs:
            eng.submit(r)
        eng.run()
        for r, w in zip(reqs, want):
            np.testing.assert_array_equal(
                np.asarray(r.generated, np.int64), w)

    @pytest.mark.slow
    def test_gqa_and_window_through_paged_engine(self):
        cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                          n_kv_heads=2, attention_window=16, d_ff=64,
                          seq_len=64, dtype=jnp.float32)
        params = init_params(jax.random.PRNGKey(2), cfg)
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, cfg.vocab, (n,)).astype(np.int32)
                   for n in (21, 6)]
        want = oracle_rollouts(params, cfg, prompts, [4, 4])
        eng = PagedBatcher(params, cfg, slots=2, max_len=64,
                           block_size=16, chunk=8)
        reqs = [Request(prompt=p, max_new_tokens=4) for p in prompts]
        for r in reqs:
            eng.submit(r)
        eng.run()
        for r, w in zip(reqs, want):
            np.testing.assert_array_equal(
                np.asarray(r.generated, np.int64), w)


class TestBlockAccounting:
    def test_live_blocks_bounded_by_live_tokens(self):
        """Per-tick HBM invariant: allocated token-slots never exceed
        live tokens + (block + chunk) slack per live sequence; a
        drained engine holds ZERO blocks."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, CFG.vocab, (n,)).astype(np.int32)
                   for n in (30, 7, 19, 11)]
        eng = PagedBatcher(params, CFG, slots=2, max_len=64,
                           block_size=8, chunk=8)
        for p in prompts:
            eng.submit(Request(prompt=p, max_new_tokens=4))
        for _ in range(10_000):
            if eng.idle:
                break
            eng.tick()
            eng.check_accounting()
        assert eng.idle
        assert eng.allocator.used_blocks == 0
        assert (eng.tables == -1).all()

    def test_short_requests_use_few_blocks(self):
        """The point of paging: a 9-token sequence in a 64-token row
        holds ceil(len/block) blocks, not max_len/block."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        eng = PagedBatcher(params, CFG, slots=1, max_len=64,
                           block_size=8, chunk=8)
        eng.submit(Request(
            prompt=np.arange(9, dtype=np.int32) % CFG.vocab,
            max_new_tokens=2))
        peak = 0
        while not eng.idle:
            eng.tick()
            peak = max(peak, eng.allocator.used_blocks)
        # 9 prompt + 2 generated = 11 tokens -> ceil(11/8)=2 blocks
        # (+1 growth look-ahead at a boundary).  Linear would hold 8.
        assert peak <= 3


class TestPreemption:
    def test_pool_pressure_preempts_and_recovers(self):
        """A pool half the worst case forces preemption; every request
        still completes with oracle-exact output (the preempted victim
        re-prefills from scratch)."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(4)
        prompts = [rng.integers(0, CFG.vocab, (n,)).astype(np.int32)
                   for n in (40, 40, 40)]
        new_tokens = [8, 8, 8]
        want = oracle_rollouts(params, CFG, prompts, new_tokens)
        # 3 slots x 64 tokens worst case = 24 blocks of 8; give 13 —
        # enough for two live 48-token sequences, not three.
        eng = PagedBatcher(params, CFG, slots=3, max_len=64,
                           block_size=8, num_blocks=13, chunk=8)
        reqs = [Request(prompt=p, max_new_tokens=nt)
                for p, nt in zip(prompts, new_tokens)]
        for r in reqs:
            eng.submit(r)
        eng.run()
        assert eng.preemptions > 0
        for r, w in zip(reqs, want):
            assert r.done
            np.testing.assert_array_equal(
                np.asarray(r.generated, np.int64), w)


class TestPoolPressureEdgeCases:
    def test_request_larger_than_pool_rejected_at_submit(self):
        """A request whose worst case exceeds the whole pool would
        self-preempt forever; submit must reject it up front."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        eng = PagedBatcher(params, CFG, slots=2, max_len=64,
                           block_size=8, num_blocks=4, chunk=8)
        with pytest.raises(ValueError, match="never be scheduled"):
            eng.submit(Request(
                prompt=(np.arange(40, dtype=np.int32) % CFG.vocab),
                max_new_tokens=8))  # 48 tokens > 32-token pool

    def test_admission_partial_allocation_released(self):
        """Admission needing 2 blocks with only 1 free must return the
        partial allocation to the pool (review finding: the old path
        wiped the table row without freeing, leaking the block)."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        eng = PagedBatcher(params, CFG, slots=2, max_len=32,
                           block_size=8, num_blocks=5, chunk=16)
        # First request occupies 4 blocks (prompt 17 -> chunk-padded
        # writes across 3 blocks, + growth); second needs 2 up front.
        eng.submit(Request(
            prompt=(np.arange(17, dtype=np.int32) % CFG.vocab),
            max_new_tokens=8))
        eng.submit(Request(
            prompt=(np.arange(16, dtype=np.int32) % CFG.vocab),
            max_new_tokens=4))
        while not eng.idle:
            eng.tick()
            eng.check_accounting()  # trips on any allocator/table drift
        assert eng.allocator.used_blocks == 0

    def test_preemption_of_collected_prefill_lane(self):
        """Three long prompts prefilling concurrently under a pool too
        small for all of them: a later lane's growth preempts an
        earlier COLLECTED lane (review finding: the launch loop then
        crashed on the evicted slot's None prompt)."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(9)
        prompts = [rng.integers(0, CFG.vocab, (48,)).astype(np.int32)
                   for _ in range(3)]
        want = oracle_rollouts(params, CFG, prompts, [4, 4, 4])
        eng = PagedBatcher(params, CFG, slots=3, max_len=64,
                           block_size=8, num_blocks=14, chunk=16,
                           prefill_lanes=3)
        reqs = [Request(prompt=p, max_new_tokens=4) for p in prompts]
        for r in reqs:
            eng.submit(r)
        for _ in range(10_000):
            if eng.idle:
                break
            eng.tick()
            eng.check_accounting()
        assert eng.idle and eng.preemptions > 0
        for r, w in zip(reqs, want):
            assert r.done
            np.testing.assert_array_equal(
                np.asarray(r.generated, np.int64), w)


class TestBatchedPrefill:
    def test_burst_of_short_prompts_admits_together(self):
        """serving.py's one-chunk-per-tick admission serializes a burst;
        the paged engine prefills up to prefill_lanes prompts per tick,
        so 4 one-chunk prompts all seed generation on the first tick."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(5)
        eng = PagedBatcher(params, CFG, slots=4, max_len=64,
                           block_size=8, chunk=8, prefill_lanes=4)
        for _ in range(4):
            p = rng.integers(0, CFG.vocab, (6,)).astype(np.int32)
            eng.submit(Request(prompt=p, max_new_tokens=3))
        eng.tick()
        seeded = sum(1 for s in eng._slots
                     if s.request is not None and s.seeded)
        assert seeded == 4
        eng.run()

    def test_long_prompt_does_not_starve_short(self):
        """With 2 lanes, a 40-token prompt and a 6-token prompt prefill
        concurrently: the short one seeds on tick 1 instead of queueing
        behind the long one's 5 chunks."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(6)
        long_p = rng.integers(0, CFG.vocab, (40,)).astype(np.int32)
        short_p = rng.integers(0, CFG.vocab, (6,)).astype(np.int32)
        eng = PagedBatcher(params, CFG, slots=2, max_len=64,
                           block_size=8, chunk=8, prefill_lanes=2)
        long_r = Request(prompt=long_p, max_new_tokens=2)
        short_r = Request(prompt=short_p, max_new_tokens=2)
        eng.submit(long_r)
        eng.submit(short_r)
        eng.tick()
        assert eng._slots[1].seeded           # short prompt: done in 1
        assert len(eng._slots[0].remaining_prompt) == 32  # long: 1 chunk
        eng.run()
        want = oracle_rollouts(params, CFG, [long_p, short_p], [2, 2])
        np.testing.assert_array_equal(
            np.asarray(long_r.generated, np.int64), want[0])
        np.testing.assert_array_equal(
            np.asarray(short_r.generated, np.int64), want[1])


class TestCapacityAtEqualHbm:
    def test_paged_serves_more_concurrent_at_equal_hbm(self):
        """The headline economics: at the SAME token-slot budget the
        linear cache holds 2 sequences; the paged pool serves 8 mixed
        short requests concurrently (≥2x concurrency), no preemption."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(7)
        # Linear budget: 2 slots x 64 = 128 token-slots.
        eng = PagedBatcher(params, CFG, slots=8, max_len=64,
                           block_size=8, num_blocks=16, chunk=8,
                           prefill_lanes=4)
        reqs = []
        for _ in range(8):
            p = rng.integers(0, CFG.vocab, (7,)).astype(np.int32)
            r = Request(prompt=p, max_new_tokens=4)
            reqs.append(r)
            eng.submit(r)
        peak_live = 0
        while not eng.idle:
            eng.tick()
            eng.check_accounting()
            peak_live = max(peak_live, sum(
                1 for s in eng._slots if s.request is not None))
        assert all(r.done for r in reqs)
        assert peak_live >= 4          # ≥2x the linear budget's 2 slots
        assert eng.preemptions == 0    # short sequences actually fit


class TestPagedFlashKernel:
    """attention.paged_flash_decode: in-place pool reads through the
    scalar-prefetched block table (interpret mode on CPU)."""

    def _setup(self, seed=0, slots=3, h=4, hkv=2, d=16, bs=8, tpr=4,
               nb=10):
        import jax.numpy as jnp

        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(slots, h, 1, d)), jnp.float32)
        kp = jnp.asarray(rng.normal(size=(nb, hkv, bs, d)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(nb, hkv, bs, d)), jnp.float32)
        tables = np.full((slots, tpr), -1, np.int32)
        tables[0, :2] = [3, 7]
        tables[1, :4] = [1, 0, 9, 5]
        tables[2, :1] = [2]
        lengths = np.array([12, 29, 5], np.int32)
        return q, kp, vp, tables, lengths

    @pytest.mark.parametrize("window", [None, 10])
    def test_matches_gather_plus_linear_kernel(self, window):
        import jax.numpy as jnp

        from tpu_autoscaler.workloads.attention import (
            flash_decode,
            paged_flash_decode,
        )

        q, kp, vp, tables, lengths = self._setup()
        out = paged_flash_decode(q, kp, vp, jnp.asarray(tables),
                                 jnp.asarray(lengths), window=window,
                                 interpret=True)
        nb, hkv, bs, d = kp.shape
        slots, tpr = tables.shape
        safe = np.clip(tables, 0, nb - 1)
        k_rows = np.asarray(kp)[safe].transpose(0, 2, 1, 3, 4).reshape(
            slots, hkv, tpr * bs, d)
        v_rows = np.asarray(vp)[safe].transpose(0, 2, 1, 3, 4).reshape(
            slots, hkv, tpr * bs, d)
        ref = flash_decode(q, jnp.asarray(k_rows), jnp.asarray(v_rows),
                           jnp.asarray(lengths), window=window,
                           interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_engine_greedy_parity_through_kernel(self):
        """The whole PagedBatcher with attention='pallas' (the paged
        kernel in interpret mode on the decode path) reproduces the
        single-sequence oracle exactly."""
        import dataclasses as dc

        cfg = dc.replace(CFG, attention="pallas")
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(11)
        prompts = [rng.integers(0, cfg.vocab, (n,)).astype(np.int32)
                   for n in (9, 21)]
        want = oracle_rollouts(params, dc.replace(CFG), prompts, [4, 4])
        eng = PagedBatcher(params, cfg, slots=2, max_len=64,
                           block_size=8, chunk=8)
        reqs = [Request(prompt=p, max_new_tokens=4) for p in prompts]
        for r in reqs:
            eng.submit(r)
        eng.run()
        for r, w in zip(reqs, want):
            np.testing.assert_array_equal(
                np.asarray(r.generated, np.int64), w)


@pytest.mark.slow
class TestPagedUnderTpMesh:
    def test_paged_engine_under_model_mesh(self):
        """End-to-end paged serving under a ('model',) TP mesh matches
        the single-device oracle (KV heads shard; pool/tables
        replicate)."""
        from jax.sharding import Mesh

        cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                          n_kv_heads=2, d_ff=64, seq_len=64,
                          dtype=jnp.float32)
        params = init_params(jax.random.PRNGKey(1), cfg)
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, cfg.vocab, (n,)).astype(np.int32)
                   for n in (12, 5)]
        want = oracle_rollouts(params, cfg, prompts, [3, 3])
        mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
        eng = PagedBatcher(params, cfg, slots=2, max_len=64,
                           block_size=8, chunk=8, mesh=mesh)
        reqs = [Request(prompt=p, max_new_tokens=3) for p in prompts]
        for r in reqs:
            eng.submit(r)
        eng.run()
        for r, w in zip(reqs, want):
            np.testing.assert_array_equal(
                np.asarray(r.generated, np.int64), w)

    def test_paged_kernel_under_tp_mesh(self):
        """The fused paged kernel shard_maps over 'model' (KV heads
        shard, pool block dim + tables replicate): engine output stays
        oracle-exact."""
        import dataclasses as dc

        from jax.sharding import Mesh

        cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                          n_kv_heads=2, d_ff=64, seq_len=64,
                          dtype=jnp.float32, attention="pallas")
        params = init_params(jax.random.PRNGKey(1), cfg)
        rng = np.random.default_rng(12)
        prompts = [rng.integers(0, cfg.vocab, (n,)).astype(np.int32)
                   for n in (11, 6)]
        want = oracle_rollouts(params, dc.replace(cfg, attention="auto"),
                               prompts, [3, 3])
        mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
        eng = PagedBatcher(params, cfg, slots=2, max_len=64,
                           block_size=8, chunk=8, mesh=mesh)
        reqs = [Request(prompt=p, max_new_tokens=3) for p in prompts]
        for r in reqs:
            eng.submit(r)
        eng.run()
        for r, w in zip(reqs, want):
            np.testing.assert_array_equal(
                np.asarray(r.generated, np.int64), w)
