"""Large-batch fit tests (ISSUE 6): the wide native pack kernel and the
vectorized jaxfit batch scorer must agree decision-for-decision with
the reference Python engine — the same zero-mismatch contract the
bench gates (`bench.py fit_batch --gangs 8192`).
"""

from __future__ import annotations

import copy
import random

import pytest

from tpu_autoscaler import native
from tpu_autoscaler.engine.fitter import (
    batch_choose_shapes,
    choose_shape_for_gang,
    pack_cpu_pods_multi,
)
from tpu_autoscaler.k8s.gangs import group_into_gangs
from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.k8s.resources import ResourceVector
from tpu_autoscaler.topology.catalog import TPU_RESOURCE
from tpu_autoscaler.topology.shapes import CpuShape

needs_native = pytest.mark.skipif(not native.pack_multi_available(),
                                  reason="native toolchain unavailable")


def mkpod(i, cpu, mem_mi, sel=None, tol=None):
    return Pod({"metadata": {"name": f"p{i}", "uid": f"u{i}"},
                "spec": {"containers": [{"resources": {"requests": {
                    "cpu": str(cpu), "memory": f"{mem_mi}Mi"}}}],
                    "nodeSelector": sel or {},
                    "tolerations": tol or []},
                "status": {"phase": "Pending"}})


def mknode(i, tainted=False):
    return Node({"metadata": {"name": f"n{i}", "uid": f"nu{i}",
                              "labels": {"zone": "a" if i % 2 else "b"}},
                 "spec": {"taints": ([{"key": "k", "value": "v",
                                       "effect": "NoSchedule"}]
                                     if tainted else [])},
                 "status": {"allocatable": {"cpu": "8", "memory": "16Gi",
                                            "pods": "110"},
                            "conditions": [{"type": "Ready",
                                            "status": "True"}]}})


SHAPES = [CpuShape("e2-standard-4", cpu_m=3_920, memory=13 * 1024**3),
          CpuShape("n2-standard-16", cpu_m=15_890, memory=56 * 1024**3)]


@needs_native
class TestPackNativeParity:
    @pytest.mark.parametrize("seed", [3, 11, 77, 1009])
    def test_randomized_parity_with_python_path(self, seed):
        """Counts, unplaceable set AND ORDER, and the mutated free map
        must be identical between the Python loop and the native
        kernel across randomized selector/taint/size mixes."""
        rng = random.Random(seed)
        for _trial in range(60):
            pods = []
            for i in range(rng.randint(0, 40)):
                sel = ({"zone": rng.choice(["a", "b"])}
                       if rng.random() < 0.4 else None)
                tol = ([{"key": "k", "operator": "Exists"}]
                       if rng.random() < 0.3 else None)
                pods.append(mkpod(i, rng.choice(["250m", "1", "2", "7",
                                                 "12", "30"]),
                                  rng.choice([256, 1024, 4096, 60_000]),
                                  sel, tol))
            nodes = [mknode(i, tainted=rng.random() < 0.3)
                     for i in range(rng.randint(0, 8))]
            nbn = {n.name: n for n in nodes}
            free_py = {n.name: ResourceVector(
                {"cpu": rng.choice(["2", "4", "8"]), "memory": "8Gi",
                 "pods": "110"}) for n in nodes}
            free_nat = copy.deepcopy(free_py)
            c_py, u_py = pack_cpu_pods_multi(list(pods), free_py,
                                             SHAPES, nbn)
            c_nat, u_nat = pack_cpu_pods_multi(
                list(pods), free_nat, SHAPES, nbn, native_threshold=0)
            assert c_py == c_nat
            assert [p.name for p in u_py] == [p.name for p in u_nat]
            assert free_py == free_nat

    def test_threshold_gates_the_kernel(self, monkeypatch):
        calls = []
        real = native.pack_ffd_multi

        def counting(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(native, "pack_ffd_multi", counting)
        pods = [mkpod(i, "1", 512) for i in range(8)]
        pack_cpu_pods_multi(list(pods), {}, SHAPES,
                            native_threshold=100)
        assert not calls  # below threshold: pure Python
        pack_cpu_pods_multi(list(pods), {}, SHAPES, native_threshold=4)
        assert len(calls) == 1

    def test_admission_mask_is_honored(self):
        """A pod whose selector no free node satisfies must open a new
        unit on both paths, never land on the rejecting node."""
        pod = mkpod(0, "1", 512, sel={"zone": "a"})
        node_b = mknode(0)  # even index -> zone "b": rejects the pod
        free = {node_b.name: ResourceVector({"cpu": "8",
                                             "memory": "8Gi"})}
        nbn = {node_b.name: node_b}
        c_nat, u_nat = pack_cpu_pods_multi(
            [pod], dict(free), SHAPES, nbn, native_threshold=0)
        c_py, u_py = pack_cpu_pods_multi([pod], dict(free), SHAPES, nbn)
        assert c_nat == c_py == {"e2-standard-4": 1}
        assert not u_nat and not u_py


class TestJaxfitBackendParity:
    def _gangs(self, n=96):
        mixes = [(8, 1), (4, 4), (4, 16), (1, 3), (4, 64), (4, 32)]
        pods = []
        for i in range(n):
            per, cnt = mixes[i % len(mixes)]
            pods += [Pod({"metadata": {
                "name": f"g{i}-p{j}", "uid": f"g{i}-p{j}",
                "labels": {"batch.kubernetes.io/job-name": f"g{i}"}},
                "spec": {"containers": [{"resources": {"requests": {
                    TPU_RESOURCE: str(per)}}}]},
                "status": {"phase": "Pending"}})
                for j in range(cnt)]
        return group_into_gangs(pods)

    def test_jaxfit_matches_python_decisions(self):
        gangs = self._gangs()
        py = {g.key: choose_shape_for_gang(g, "v5e") for g in gangs}
        jx = batch_choose_shapes(gangs, "v5e", backend="jaxfit")
        assert len(jx) == len(gangs)
        for key, choice in jx.items():
            assert (choice.shape.name, choice.stranded_chips) == \
                (py[key].shape.name, py[key].stranded_chips)

    def test_jaxfit_matches_native_when_available(self):
        if not native.available():
            pytest.skip("native toolchain unavailable")
        gangs = self._gangs()
        nat = batch_choose_shapes(gangs, "v5e", backend="native")
        jx = batch_choose_shapes(gangs, "v5e", backend="jaxfit")
        assert {k: (c.shape.name, c.stranded_chips)
                for k, c in nat.items()} \
            == {k: (c.shape.name, c.stranded_chips)
                for k, c in jx.items()}

    def test_pinned_and_fractional_gangs_fall_through(self):
        from tpu_autoscaler.topology.catalog import ACCELERATOR_LABEL

        pinned = Pod({"metadata": {
            "name": "pin", "uid": "pin",
            "labels": {"batch.kubernetes.io/job-name": "pin"}},
            "spec": {"nodeSelector": {
                ACCELERATOR_LABEL: "tpu-v5-lite-podslice"},
                "containers": [{"resources": {"requests": {
                    TPU_RESOURCE: "4"}}}]},
            "status": {"phase": "Pending"}})
        gangs = group_into_gangs([pinned])
        assert batch_choose_shapes(gangs, "v5e",
                                   backend="jaxfit") == {}
