"""Slice state machine tests (reference: test_cluster.py made every
ClusterNodeState reachable with crafted pods/timestamps — same here, but
per-slice)."""

from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.state import (
    SliceState,
    SliceTracker,
    classify_slice,
)
from tpu_autoscaler.state.tracker import DRAIN_ANNOTATION
from tpu_autoscaler.topology import shape_by_name

from tests.fixtures import make_pod, make_slice_nodes, make_tpu_pod

GRACE = 300.0
IDLE = 1800.0


def classify(view, spare=False):
    return classify_slice(view, grace_seconds=GRACE,
                          idle_threshold_seconds=IDLE, spare=spare)


def slice_nodes(shape_name="v5e-64", slice_id="s1", **kw):
    return [Node(p) for p in
            make_slice_nodes(shape_by_name(shape_name), slice_id, **kw)]


def running_pod(node_name, name="w"):
    return Pod(make_tpu_pod(name=name, chips=4, phase="Running",
                            node_name=node_name, unschedulable=False,
                            job="trainer"))


class TestBarrierAndGrace:
    def test_not_all_ready_is_provisioning(self):
        tracker = SliceTracker()
        nodes = slice_nodes()
        # Mark one host NotReady.
        nodes[3] = Node({**nodes[3]._p, "status": {
            **nodes[3]._p["status"],
            "conditions": [{"type": "Ready", "status": "False"}]}})
        view = tracker.observe("s1", nodes, [], now=100.0)
        assert classify(view) is SliceState.PROVISIONING

    def test_all_ready_enters_grace(self):
        tracker = SliceTracker()
        view = tracker.observe("s1", slice_nodes(), [], now=100.0)
        assert classify(view) is SliceState.LAUNCH_GRACE

    def test_grace_expires_to_idle(self):
        tracker = SliceTracker()
        tracker.observe("s1", slice_nodes(), [], now=100.0)
        view = tracker.observe("s1", slice_nodes(), [], now=100.0 + GRACE + 1)
        assert classify(view) is SliceState.IDLE

    def test_partial_registration_holds_barrier(self):
        """Hosts of a multi-host slice register gradually: a subset that is
        individually Ready must NOT clear the barrier (a 4-of-16 v5e-64 is
        not a usable slice) — the catalog's host count is the authority."""
        tracker = SliceTracker()
        nodes = slice_nodes()  # v5e-64: 16 hosts
        view = tracker.observe("s1", nodes[:4], [], now=100.0)
        assert view.all_ready_since is None
        assert classify(view) is SliceState.PROVISIONING
        # Still partial at a later pass: barrier still holds.
        view = tracker.observe("s1", nodes[:15], [], now=150.0)
        assert classify(view) is SliceState.PROVISIONING
        # Full registration clears it at the CURRENT pass's time.
        view = tracker.observe("s1", nodes, [], now=200.0)
        assert view.all_ready_since == 200.0
        assert classify(view) is SliceState.LAUNCH_GRACE

    def test_ready_then_host_lost_is_unhealthy(self):
        tracker = SliceTracker()
        nodes = slice_nodes()
        tracker.observe("s1", nodes, [], now=100.0)
        broken = list(nodes)
        broken[0] = Node({**nodes[0]._p, "status": {
            **nodes[0]._p["status"],
            "conditions": [{"type": "Ready", "status": "False"}]}})
        view = tracker.observe("s1", broken, [], now=200.0)
        assert classify(view) is SliceState.UNHEALTHY


class TestBusyIdle:
    def test_workload_makes_busy(self):
        tracker = SliceTracker()
        nodes = slice_nodes()
        pods = [running_pod(nodes[0].name)]
        view = tracker.observe("s1", nodes, pods, now=100.0)
        assert classify(view) is SliceState.BUSY

    def test_daemonset_and_mirror_do_not_make_busy(self):
        tracker = SliceTracker()
        nodes = slice_nodes()
        tracker.observe("s1", nodes, [], now=0.0)
        pods = [
            Pod(make_pod(name="ds", owner_kind="DaemonSet", phase="Running",
                         node_name=nodes[0].name, unschedulable=False)),
            Pod(make_pod(name="mirror", phase="Running",
                         node_name=nodes[0].name, unschedulable=False,
                         annotations={"kubernetes.io/config.mirror": "x"})),
        ]
        view = tracker.observe("s1", nodes, pods, now=GRACE + 1)
        assert classify(view) is SliceState.IDLE

    def test_idle_past_threshold_drainable(self):
        tracker = SliceTracker()
        tracker.observe("s1", slice_nodes(), [], now=0.0)
        view = tracker.observe("s1", slice_nodes(), [], now=IDLE + 1)
        assert classify(view) is SliceState.IDLE_DRAINABLE

    def test_idle_clock_resets_when_busy(self):
        tracker = SliceTracker()
        nodes = slice_nodes()
        tracker.observe("s1", nodes, [], now=0.0)
        # Busy at t=1000 resets idleness.
        tracker.observe("s1", nodes, [running_pod(nodes[0].name)],
                        now=1000.0)
        view = tracker.observe("s1", nodes, [], now=IDLE + 500)
        assert classify(view) is SliceState.IDLE  # only idle since t=IDLE+500
        view = tracker.observe("s1", nodes, [], now=2 * IDLE + 1001)
        assert classify(view) is SliceState.IDLE_DRAINABLE

    def test_spare_retained(self):
        tracker = SliceTracker()
        tracker.observe("s1", slice_nodes(), [], now=0.0)
        view = tracker.observe("s1", slice_nodes(), [], now=IDLE + 1)
        assert classify(view, spare=True) is SliceState.SPARE


class TestCordonStates:
    def test_our_cordon_is_draining(self):
        tracker = SliceTracker()
        nodes = slice_nodes()
        tracker.observe("s1", nodes, [], now=0.0)
        tracker.note_cordoned("s1")
        cordoned = [Node({**n._p, "spec": {"unschedulable": True}})
                    for n in nodes]
        view = tracker.observe("s1", cordoned, [], now=10.0)
        assert classify(view) is SliceState.DRAINING

    def test_foreign_cordon_is_unschedulable(self):
        tracker = SliceTracker()
        nodes = slice_nodes()
        tracker.observe("s1", nodes, [], now=0.0)
        cordoned = [Node({**n._p, "spec": {"unschedulable": True}})
                    for n in nodes]
        view = tracker.observe("s1", cordoned, [], now=10.0)
        assert classify(view) is SliceState.UNSCHEDULABLE

    def test_drain_annotation_survives_restart(self):
        # A fresh tracker (process restart) still sees our cordon via the
        # node annotation.
        nodes = slice_nodes()
        annotated = []
        for n in nodes:
            p = {**n._p,
                 "spec": {"unschedulable": True},
                 "metadata": {**n._p["metadata"],
                              "annotations": {DRAIN_ANNOTATION: "123"}}}
            annotated.append(Node(p))
        fresh = SliceTracker()
        view = fresh.observe("s1", annotated, [], now=500.0)
        assert classify(view) is SliceState.DRAINING


class TestCpuDegenerateCase:
    def test_single_cpu_node_flows_through_machine(self):
        from tests.fixtures import make_node

        tracker = SliceTracker()
        node = [Node(make_node(name="n1"))]
        tracker.observe("n1", node, [], now=0.0)
        view = tracker.observe("n1", node, [], now=IDLE + 1)
        assert classify(view) is SliceState.IDLE_DRAINABLE


class TestUnhealthyDrainPath:
    """Review regression: an unhealthy slice being reclaimed must classify
    DRAINING so the drain completes and hardware is deleted."""

    def test_our_cordon_wins_over_unhealthy(self):
        tracker = SliceTracker()
        nodes = slice_nodes()
        tracker.observe("s1", nodes, [], now=0.0)   # barrier cleared
        tracker.note_cordoned("s1")
        broken = []
        for i, n in enumerate(nodes):
            p = {**n._p, "spec": {"unschedulable": True}}
            if i == 0:
                p = {**p, "status": {**n._p["status"], "conditions": [
                    {"type": "Ready", "status": "False"}]}}
            broken.append(Node(p))
        view = tracker.observe("s1", broken, [], now=100.0)
        assert classify(view) is SliceState.DRAINING


class TestUnderUtilized:
    """Reference parity: UNDER_UTILIZED_DRAINABLE (cluster.py state
    machine), rebuilt for CPU units only."""

    def small_pod(self, node_name):
        return Pod(make_pod(name="tiny", owner_kind="ReplicaSet",
                            phase="Running", node_name=node_name,
                            unschedulable=False,
                            requests={"cpu": "200m", "memory": "256Mi"}))

    def cpu_unit(self):
        from tests.fixtures import make_node

        return [Node(make_node(name="n1"))]

    def test_low_utilization_drainable_pod(self):
        tracker = SliceTracker()
        nodes = self.cpu_unit()
        tracker.observe("n1", nodes, [], now=0.0)
        view = tracker.observe("n1", nodes, [self.small_pod("n1")],
                               now=GRACE + 1)
        state = classify_slice(view, grace_seconds=GRACE,
                               idle_threshold_seconds=IDLE,
                               utilization_threshold=0.5)
        assert state is SliceState.UNDER_UTILIZED

    def test_disabled_by_default(self):
        tracker = SliceTracker()
        nodes = self.cpu_unit()
        tracker.observe("n1", nodes, [], now=0.0)
        view = tracker.observe("n1", nodes, [self.small_pod("n1")],
                               now=GRACE + 1)
        assert classify(view) is SliceState.BUSY

    def test_bare_pod_blocks_consolidation(self):
        tracker = SliceTracker()
        nodes = self.cpu_unit()
        tracker.observe("n1", nodes, [], now=0.0)
        bare = Pod(make_pod(name="bare", phase="Running", node_name="n1",
                            unschedulable=False,
                            requests={"cpu": "100m"}))
        view = tracker.observe("n1", nodes, [bare], now=GRACE + 1)
        state = classify_slice(view, grace_seconds=GRACE,
                               idle_threshold_seconds=IDLE,
                               utilization_threshold=0.5)
        assert state is SliceState.BUSY

    def test_tpu_slice_never_under_utilized(self):
        tracker = SliceTracker()
        nodes = slice_nodes("v5e-8", "s1")
        tracker.observe("s1", nodes, [], now=0.0)
        small = Pod(make_tpu_pod(name="w", chips=1, phase="Running",
                                 node_name=nodes[0].name,
                                 unschedulable=False, job="j",
                                 requests={"google.com/tpu": "1",
                                           "cpu": "100m"}))
        view = tracker.observe("s1", nodes, [small], now=GRACE + 1)
        state = classify_slice(view, grace_seconds=GRACE,
                               idle_threshold_seconds=IDLE,
                               utilization_threshold=0.9)
        assert state is SliceState.BUSY

    def test_high_utilization_stays_busy(self):
        tracker = SliceTracker()
        nodes = self.cpu_unit()
        tracker.observe("n1", nodes, [], now=0.0)
        big = Pod(make_pod(name="big", owner_kind="ReplicaSet",
                           phase="Running", node_name="n1",
                           unschedulable=False, requests={"cpu": "6"}))
        view = tracker.observe("n1", nodes, [big], now=GRACE + 1)
        state = classify_slice(view, grace_seconds=GRACE,
                               idle_threshold_seconds=IDLE,
                               utilization_threshold=0.5)
        assert state is SliceState.BUSY
