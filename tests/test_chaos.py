"""Chaos / convergence test: the reconcile loop must converge under
randomized demand churn, slow staggered provisioning, and injected
provisioning failures — with slice-atomicity never violated.

The reference had no fault injection at all (SURVEY.md §6.3); this is the
rebuild exceeding that floor.  Failure modes exercised:

- gangs arriving and completing at random times;
- provisions materializing hosts gradually (readiness barrier under churn);
- a shape that intermittently FAILs to provision (quota), exercising
  backoff + retry;
- invariant checks every step: a node hosting a Running pod is never
  deleted, and slices are only ever deleted whole.
"""

import random

from tpu_autoscaler.actuators.fake import FakeActuator
from tpu_autoscaler.controller import Controller, ControllerConfig
from tpu_autoscaler.engine.planner import PoolPolicy
from tpu_autoscaler.k8s.fake import FakeKube
from tpu_autoscaler.topology import shape_by_name

from tests.fixtures import make_gang

# The seeded flaky-provision fault model is first-class in FakeActuator
# since ISSUE 7 (rng + fail_prob knobs), shared with the generative
# chaos engine (tpu_autoscaler/chaos) instead of a test-local subclass.


SHAPES = ["v5e-8", "v5e-16", "v5e-64"]


def test_converges_under_churn_and_failures():
    rng = random.Random(20260728)
    kube = FakeKube()
    actuator = FakeActuator(kube, rng=rng, fail_prob=0.3,
                             provision_delay=40.0, stagger_seconds=5.0)
    controller = Controller(kube, actuator, ControllerConfig(
        policy=PoolPolicy(spare_nodes=0, max_total_chips=2048),
        grace_seconds=30.0, idle_threshold_seconds=120.0,
        drain_grace_seconds=20.0, provision_retry_seconds=30.0))

    active_jobs: dict[str, list[str]] = {}
    completed: set[str] = set()
    arrivals = {float(rng.randrange(0, 600)): i for i in range(8)}
    job_ids = iter(range(100))

    def nodes_with_running_pods():
        running_nodes = set()
        for p in kube.list_pods():
            if p["status"]["phase"] == "Running" and p["spec"].get(
                    "nodeName"):
                running_nodes.add(p["spec"]["nodeName"])
        return running_nodes

    t = 0.0
    while t <= 2400.0:
        # Random arrival of a new gang.
        due = [ts for ts in arrivals if ts <= t]
        for ts in due:
            del arrivals[ts]
            jid = next(job_ids)
            shape = shape_by_name(rng.choice(SHAPES))
            names = []
            for payload in make_gang(shape, job=f"job-{jid}"):
                kube.add_pod(payload)
                names.append(payload["metadata"]["name"])
            active_jobs[f"job-{jid}"] = names

        # Random completion of a running gang.
        for job, names in list(active_jobs.items()):
            all_running = all(
                (kube.get_pod("default", n) or {}).get(
                    "status", {}).get("phase") == "Running" for n in names)
            if all_running and rng.random() < 0.02:
                for n in names:
                    kube.delete_pod("default", n)
                del active_jobs[job]
                completed.add(job)

        before = nodes_with_running_pods()
        controller.reconcile_once(now=t)
        kube.schedule_step()
        # INVARIANT: no node that hosted a Running pod disappeared this
        # pass while its pod still exists (slice-atomicity / no bisection).
        after_names = {n["metadata"]["name"] for n in kube.list_nodes()}
        for node_name in before & nodes_with_running_pods():
            assert node_name in after_names, \
                f"node {node_name} with running pod was deleted at t={t}"
        t += 5.0

    # Every job eventually ran (completed or still running, none pending).
    still_pending = [p["metadata"]["name"] for p in kube.list_pods()
                     if p["status"]["phase"] == "Pending"]
    assert not still_pending, f"pods stuck pending: {still_pending}"
    assert len(completed) + len(active_jobs) == 8

    # Slices were only deleted whole: every deleted unit's nodes are gone.
    for unit in actuator.deleted_units:
        for n in kube.list_nodes():
            assert n["metadata"]["labels"].get(
                "autoscaler.tpu.dev/slice-id") != unit

    # Bookkeeping stayed bounded.
    assert len(controller._retry_at) < 20
    assert len(controller.tracker.known_slices()) <= len({
        n["metadata"]["labels"].get("autoscaler.tpu.dev/slice-id")
        for n in kube.list_nodes()}) + 2


def test_converges_with_anti_affine_services_amid_tpu_churn():
    """Chaos + scheduling constraints (VERDICT r1 item 7): anti-affine CPU
    service replicas arrive amid TPU gang churn and flaky provisioning;
    the controller must spread them one-per-node, keep converging, and
    never violate slice atomicity."""
    from tests.fixtures import make_pod

    rng = random.Random(20260729)
    kube = FakeKube()
    actuator = FakeActuator(kube, rng=rng, fail_prob=0.2,
                             provision_delay=30.0)
    controller = Controller(kube, actuator, ControllerConfig(
        policy=PoolPolicy(spare_nodes=0, max_total_chips=1024),
        grace_seconds=30.0, idle_threshold_seconds=120.0,
        drain_grace_seconds=20.0, provision_retry_seconds=30.0))

    anti = {"podAntiAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": [{
            "labelSelector": {"matchLabels": {"app": "ha-svc"}},
            "topologyKey": "kubernetes.io/hostname"}]}}
    replica_names = []
    tpu_jobs: dict[str, list[str]] = {}
    job_ids = iter(range(100))
    t = 0.0
    while t <= 1200.0:
        if t in (50.0, 100.0, 150.0):  # replicas trickle in
            i = len(replica_names)
            payload = make_pod(name=f"ha-{i}", requests={"cpu": "1"},
                               labels={"app": "ha-svc"})
            payload["spec"]["affinity"] = anti
            kube.add_pod(payload)
            replica_names.append(f"ha-{i}")
        if rng.random() < 0.02:
            jid = next(job_ids)
            names = []
            for payload in make_gang(shape_by_name("v5e-16"),
                                     job=f"tj-{jid}"):
                kube.add_pod(payload)
                names.append(payload["metadata"]["name"])
            tpu_jobs[f"tj-{jid}"] = names
        for job, names in list(tpu_jobs.items()):
            if rng.random() < 0.03 and all(
                    (kube.get_pod("default", n) or {}).get(
                        "status", {}).get("phase") == "Running"
                    for n in names):
                for n in names:
                    kube.delete_pod("default", n)
                del tpu_jobs[job]
        controller.reconcile_once(now=t)
        kube.schedule_step()
        t += 5.0

    # All replicas bound, each on its own node (the hard constraint).
    hosts = [kube.get_pod("default", n)["spec"].get("nodeName")
             for n in replica_names]
    assert all(hosts)
    assert len(set(hosts)) == 3
    # No TPU pods stuck either.
    pending = [p["metadata"]["name"] for p in kube.list_pods()
               if p["status"]["phase"] == "Pending"]
    assert not pending


def test_converges_with_always_failing_shape_reports_not_spins():
    """A shape that NEVER provisions must back off, not hot-loop."""
    kube = FakeKube()
    actuator = FakeActuator(kube, fail_shapes={"v5e-64"})
    controller = Controller(kube, actuator, ControllerConfig(
        policy=PoolPolicy(spare_nodes=0),
        provision_retry_seconds=60.0))
    for p in make_gang(shape_by_name("v5e-64"), job="doomed"):
        kube.add_pod(p)
    t = 0.0
    while t <= 600.0:
        controller.reconcile_once(now=t)
        kube.schedule_step()
        t += 5.0
    snap = controller.metrics.snapshot()
    # ~once per minute, not once per 5s pass.
    assert snap["counters"]["provision_failures"] <= 11
    assert snap["counters"]["provisions_submitted"] <= 11


def test_converges_with_all_policies_enabled():
    """Interplay chaos: preemption + namespace quotas + consolidation +
    settle all on at once, with priorities and failures — the loop must
    converge, honor quotas, and never strand a high-priority gang."""
    rng = random.Random(42)
    kube = FakeKube()
    actuator = FakeActuator(kube, rng=rng, fail_prob=0.15,
                             provision_delay=30.0)
    controller = Controller(kube, actuator, ControllerConfig(
        policy=PoolPolicy(spare_nodes=0, max_total_chips=96,
                          namespace_chip_quota={"greedy": 32}),
        grace_seconds=30.0, idle_threshold_seconds=120.0,
        drain_grace_seconds=20.0, provision_retry_seconds=30.0,
        utilization_threshold=0.3, gang_settle_seconds=10.0,
        enable_preemption=True))

    from tests.fixtures import make_gang
    from tpu_autoscaler.topology import shape_by_name
    from tpu_autoscaler.topology.catalog import TPU_RESOURCE

    names = {}
    jid = 0
    t = 0.0
    while t <= 3000.0:
        if rng.random() < 0.03 and len(names) < 8:
            jid += 1
            ns = rng.choice(["default", "greedy"])
            prio = rng.choice([0, 0, 100])
            shape = shape_by_name(rng.choice(["v5e-8", "v5e-16"]))
            gang = make_gang(shape, job=f"j{jid}", namespace=ns)
            for p in gang:
                p["spec"]["priority"] = prio
                kube.add_pod(p)
            names[f"j{jid}"] = (ns, [p["metadata"]["name"] for p in gang])
        for job, (ns, members) in list(names.items()):
            gone = [m for m in members
                    if kube.get_pod(ns, m) is None]
            if gone:  # preempted/evicted: Job controller recreates
                shape = shape_by_name("v5e-8" if len(members) == 1
                                      else "v5e-16")
                for m in gone:
                    idx = int(m.rsplit("-", 1)[1])
                    from tests.fixtures import make_tpu_pod

                    kube.add_pod(make_tpu_pod(
                        name=m, namespace=ns, chips=shape.chips_per_host,
                        shape=shape, job=job))
            if all((kube.get_pod(ns, m) or {}).get("status", {})
                   .get("phase") == "Running" for m in members) \
                    and rng.random() < 0.01:
                for m in members:
                    kube.delete_pod(ns, m)
                del names[job]
        controller.reconcile_once(now=t)
        kube.schedule_step()
        # INVARIANT: the greedy namespace never exceeds its chip quota in
        # PROVISIONED-for-it capacity... enforced at planning; spot-check
        # total chips never exceed the global clamp.
        total = sum(int(float(n["status"]["allocatable"].get(
            TPU_RESOURCE, 0))) for n in kube.list_nodes())
        assert total <= 96, f"clamp violated at t={t}: {total}"
        t += 5.0
    # No runaway state.
    assert len(controller.tracker.known_slices()) < 20
    snap = controller.metrics.snapshot()
    assert snap["counters"].get("reconcile_errors", 0) == 0
    assert snap["counters"].get("maintain_errors", 0) == 0
