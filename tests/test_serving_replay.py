"""Serving replay + chaos serving profile (ISSUE 9).

Small-scale smokes of the ``bench.py serving`` evaluation loop: both
scaling modes drive the REAL Controller, the drain contract loses no
request, and the signal mode's scaler actually exercises the advisory
path.  The full-scale gates (10k-replica adapter hot path, the
diurnal+spike outcome ratio) live in the bench, not here.
"""

from __future__ import annotations

import pytest

from tpu_autoscaler.serving.replay import (
    ServingReplayConfig,
    compare,
    replay,
)

#: One compressed mini-trace: small fleet, two days, cheap enough for
#: tier-1 (a replay is a few hundred reconcile passes).
MINI = ServingReplayConfig(
    seed=0, day_seconds=600.0, days=2, step=5.0,
    peak_rps=80.0, trough_rps=16.0, spike_duration=60.0,
    baseline_replicas=3, max_replicas=24)


class TestServingReplay:
    @pytest.mark.parametrize("mode", ["reactive", "signal"])
    def test_no_request_lost(self, mode):
        """The drain contract end to end: every arrived request is
        served even as replicas drain and slices are reclaimed."""
        result = replay(MINI, mode=mode)
        assert result.arrived > 1000
        assert result.unserved == 0
        assert result.served == result.arrived
        assert 0.0 < result.attainment <= 1.0

    def test_signal_mode_exercises_advisory_path(self):
        result = replay(MINI, mode="signal")
        assert result.scaleouts > 0
        assert result.provisions > 0
        assert result.peak_replicas > MINI.baseline_replicas

    def test_reactive_mode_uses_pending_pods_only(self):
        result = replay(MINI, mode="reactive")
        assert result.scaleouts == 0          # no scaler attached
        assert result.provisions > 0          # pod-pending provisions

    def test_fleet_scales_with_the_day(self):
        seen = []
        replay(MINI, mode="signal",
               probe=lambda t, n, b, s: seen.append((t, n)))
        peak_fleet = max(n for _t, n in seen)
        trough_fleet = min(
            n for t, n in seen if MINI.day_seconds * 0.6 < t
            < MINI.day_seconds * 0.9)
        assert peak_fleet > trough_fleet

    def test_compare_scorecard_shape(self):
        card = compare(MINI)
        assert card["trace"]["modeled_users"] > 0
        assert set(card) >= {"reactive", "signal", "miss_rate_ratio",
                             "tail_attainment_reactive",
                             "tail_attainment_signal"}


class TestServingChaosProfile:
    def test_profile_generates_serving_events(self):
        from tpu_autoscaler.chaos.scenario import generate

        programs = [generate(s, profile="serving") for s in range(12)]
        assert all(p.serving for p in programs)
        kinds = {e.kind for p in programs for e in p.events}
        assert "replica_restart" in kinds
        assert kinds & {"counter_reset", "stale_burst",
                        "replica_churn"}

    def test_seed_runs_green(self):
        from tpu_autoscaler.chaos.engine import run_scenario

        result = run_scenario(3, profile="serving")
        assert result.ok, result.violations
        assert result.converged_at is not None

    def test_counter_reset_invariant_is_armed(self):
        """Sabotage the adapter mid-run: the serving fuzz monitor must
        catch a negative aggregate (proves the invariant has teeth)."""
        from tpu_autoscaler.chaos.engine import _Run
        from tpu_autoscaler.chaos.scenario import generate

        program = generate(3, profile="serving")
        run = _Run(program)
        fuzz = run.serving_fuzz
        assert fuzz is not None
        fuzz.step(0.0)
        run.controller.serving_scaler.adapter.fold(0.0)
        # Corrupt a raw rate sum the way a signed-delta bug would.
        adapter = run.controller.serving_scaler.adapter
        adapter._pool_sums[:, 5:] = -100.0
        fuzz.check(0.0)
        assert any(v.invariant == "serving-nonnegative-rates"
                   for v in run.monitor.violations)
