"""Serving replay + chaos serving profile (ISSUE 9).

Small-scale smokes of the ``bench.py serving`` evaluation loop: both
scaling modes drive the REAL Controller, the drain contract loses no
request, and the signal mode's scaler actually exercises the advisory
path.  The full-scale gates (10k-replica adapter hot path, the
diurnal+spike outcome ratio) live in the bench, not here.
"""

from __future__ import annotations

import pytest

from tpu_autoscaler.serving.replay import (
    ServingReplayConfig,
    compare,
    replay,
)

#: One compressed mini-trace: small fleet, two days, cheap enough for
#: tier-1 (a replay is a few hundred reconcile passes).
MINI = ServingReplayConfig(
    seed=0, day_seconds=600.0, days=2, step=5.0,
    peak_rps=80.0, trough_rps=16.0, spike_duration=60.0,
    baseline_replicas=3, max_replicas=24)


class TestServingReplay:
    @pytest.mark.parametrize("mode", ["reactive", "signal"])
    def test_no_request_lost(self, mode):
        """The drain contract end to end: every arrived request is
        served even as replicas drain and slices are reclaimed."""
        result = replay(MINI, mode=mode)
        assert result.arrived > 1000
        assert result.unserved == 0
        assert result.served == result.arrived
        assert 0.0 < result.attainment <= 1.0

    def test_signal_mode_exercises_advisory_path(self):
        result = replay(MINI, mode="signal")
        assert result.scaleouts > 0
        assert result.provisions > 0
        assert result.peak_replicas > MINI.baseline_replicas

    def test_reactive_mode_uses_pending_pods_only(self):
        result = replay(MINI, mode="reactive")
        assert result.scaleouts == 0          # no scaler attached
        assert result.provisions > 0          # pod-pending provisions

    def test_fleet_scales_with_the_day(self):
        seen = []
        replay(MINI, mode="signal",
               probe=lambda t, n, b, s: seen.append((t, n)))
        peak_fleet = max(n for _t, n in seen)
        trough_fleet = min(
            n for t, n in seen if MINI.day_seconds * 0.6 < t
            < MINI.day_seconds * 0.9)
        assert peak_fleet > trough_fleet

    def test_compare_scorecard_shape(self):
        card = compare(MINI)
        assert card["trace"]["modeled_users"] > 0
        assert set(card) >= {"reactive", "signal", "miss_rate_ratio",
                             "tail_attainment_reactive",
                             "tail_attainment_signal"}


class TestServingChaosProfile:
    def test_profile_generates_serving_events(self):
        from tpu_autoscaler.chaos.scenario import generate

        programs = [generate(s, profile="serving") for s in range(12)]
        assert all(p.serving for p in programs)
        kinds = {e.kind for p in programs for e in p.events}
        assert "replica_restart" in kinds
        assert kinds & {"counter_reset", "stale_burst",
                        "replica_churn"}

    def test_seed_runs_green(self):
        from tpu_autoscaler.chaos.engine import run_scenario

        result = run_scenario(3, profile="serving")
        assert result.ok, result.violations
        assert result.converged_at is not None

    def test_counter_reset_invariant_is_armed(self):
        """Sabotage the adapter mid-run: the serving fuzz monitor must
        catch a negative aggregate (proves the invariant has teeth)."""
        from tpu_autoscaler.chaos.engine import _Run
        from tpu_autoscaler.chaos.scenario import generate

        program = generate(3, profile="serving")
        run = _Run(program)
        fuzz = run.serving_fuzz
        assert fuzz is not None
        fuzz.step(0.0)
        run.controller.serving_scaler.adapter.fold(0.0)
        # Corrupt a raw rate sum the way a signed-delta bug would.
        adapter = run.controller.serving_scaler.adapter
        adapter._pool_sums[:, 5:] = -100.0
        fuzz.check(0.0)
        assert any(v.invariant == "serving-nonnegative-rates"
                   for v in run.monitor.violations)


#: The traced twin of MINI (ISSUE 14): sampling on, so the e2e
#: acceptance assertions run at tier-1 scale (the full 2.2M-user
#: version lives in ``bench.py serving-trace``).
TRACED = ServingReplayConfig(
    seed=0, day_seconds=600.0, days=2, step=5.0,
    peak_rps=80.0, trough_rps=16.0, spike_duration=60.0,
    baseline_replicas=3, max_replicas=24, trace_sample_rate=0.01)


class TestRequestTracing:
    """ISSUE 14 acceptance, tier-1 scale: every SLO-missing cohort
    tail-captured gap-free, exemplars resolving to retained traces,
    and the tail-report attributing the miss onset to scale-up lag
    with a working scaleup-* cross-link."""

    @pytest.fixture(scope="class")
    def traced(self):
        artifacts = {}
        result = replay(TRACED, mode="signal", artifacts=artifacts)
        return result, artifacts

    def test_every_missing_request_is_tail_captured_gap_free(
            self, traced):
        from tpu_autoscaler.obs.recorder import trace_gaps

        result, artifacts = traced
        assert result.unserved == 0
        score = artifacts["score"]
        dump = artifacts["controller"].recorder.dump()
        roots = [s for s in dump["spans"]
                 if s["name"] == "request"
                 and s["attrs"].get("slo_miss")]
        assert len(score.miss_cohorts) > 0
        assert len(roots) == len(score.miss_cohorts)
        by_trace = {}
        for s in dump["spans"]:
            by_trace.setdefault(s["trace_id"], []).append(s)
        for root in roots:
            tid = root["trace_id"]
            assert trace_gaps({"spans": by_trace[tid]}, tid) == []

    def test_bundle_exemplar_resolves_to_a_retained_trace(
            self, traced):
        from tpu_autoscaler.serving.adapter import EXEMPLAR_FAMILY

        _result, artifacts = traced
        controller = artifacts["controller"]
        bundle = controller.incident_bundle("test")
        rows = bundle["tsdb"]["exemplars"][EXEMPLAR_FAMILY]
        assert rows
        retained = {s["trace_id"] for s in bundle["spans"]}
        assert rows[-1][2] in retained
        # The serving-SLO alert fired during the overload and its
        # firing summary named an exemplar trace.
        state = controller.alerts.state_of("serving-slo-attainment")
        assert state.fired_count >= 1

    def test_tail_report_attributes_scaleup_lag_with_cross_link(
            self, traced):
        from tpu_autoscaler.obs import tailcause

        _result, artifacts = traced
        controller = artifacts["controller"]
        bundle = controller.incident_bundle("test")
        assert bundle["tailcause"]["tail_requests"] > 0
        score = artifacts["score"]
        onset = min(m[0] for m in score.miss_cohorts)
        report = tailcause.analyze(bundle,
                                   window=(onset, onset + 600.0))
        assert report["dominant_cause"] == "scaleup-lag"
        link = report["scaleup"]["trace_id"]
        assert link.startswith("scaleup-")
        assert any(s["trace_id"] == link for s in bundle["spans"])

    def test_offline_replay_reproduces_the_bundle(self, traced,
                                                  tmp_path):
        import json

        from tpu_autoscaler.obs.__main__ import main as replay_main

        _result, artifacts = traced
        bundle = artifacts["controller"].incident_bundle("test")
        path = tmp_path / "bundle.json"
        path.write_text(json.dumps(bundle, default=str))
        assert replay_main(["replay", str(path), "-q"]) == 0

    def test_untraced_replay_has_zero_sampler_footprint(self):
        artifacts = {}
        replay(MINI, mode="signal", artifacts=artifacts)
        assert artifacts["samplers"] == []
        dump = artifacts["controller"].recorder.dump()
        assert not any(s["trace_id"].startswith("request-")
                       for s in dump["spans"])


class TestSlowDecodeChaosProfile:
    def test_profile_generates_slow_decode(self):
        from tpu_autoscaler.chaos.scenario import generate

        programs = [generate(s, profile="serving") for s in range(16)]
        kinds = {e.kind for p in programs for e in p.events}
        assert "slow_decode" in kinds

    def test_slow_decode_seed_green_with_tail_captures(self):
        from tpu_autoscaler.chaos.engine import run_scenario
        from tpu_autoscaler.chaos.scenario import generate

        seed = next(s for s in range(40)
                    if any(e.kind == "slow_decode"
                           for e in generate(s,
                                             profile="serving").events))
        result = run_scenario(seed, profile="serving")
        assert result.ok, result.violations

    def test_gap_invariant_is_armed(self):
        """Sabotage a sampler's retained spans: the per-step gap
        check must catch the hole (proves the invariant has teeth)."""
        from tpu_autoscaler.chaos.engine import _Run
        from tpu_autoscaler.chaos.scenario import generate

        run = _Run(generate(3, profile="serving"))
        fuzz = run.serving_fuzz
        assert fuzz is not None
        for step in range(6):
            fuzz.step(float(step * 5))
        name = sorted(fuzz._samplers)[0]
        sampler = fuzz._samplers[name]
        # Drive one guaranteed promotion, then corrupt its tree by
        # deleting a child span from the ring.
        sampler.note_submit("sab", 0)
        sampler.note_admit("sab", 1)
        sampler.note_seeded("sab", 2)
        tid = sampler.note_finish("sab", 99)  # tail (slo_ticks=4)
        assert tid is not None
        spans = sampler.recorder._spans
        victim = next(s for s in spans
                      if s.trace_id == tid and s.name == "decode")
        spans.remove(victim)
        fuzz.check_traces(99.0)
        assert any(v.invariant == "reqtrace-gap-free"
                   for v in run.monitor.violations)

    def test_sampler_memory_bounded_under_restart_and_churn(self):
        from tpu_autoscaler.chaos.engine import run_scenario
        from tpu_autoscaler.chaos.scenario import generate

        program = generate(5, profile="serving")
        assert any(e.kind in ("replica_restart", "counter_reset",
                              "stale_burst", "replica_churn")
                   for e in program.events)
        result = run_scenario(program)
        assert result.ok, result.violations
