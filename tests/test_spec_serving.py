"""Speculative decoding inside the paged serving engine
(workloads/spec_serving.py).

Oracle: the plain PagedBatcher (itself pinned bit-exact against
single-sequence generate) — greedy speculative serving must emit the
IDENTICAL token streams, only in fewer target passes.  The per-slot
accept math mirrors decode.speculative_sample_generate, whose marginal
exactness is pinned in test_decode.py."""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpu_autoscaler.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)
from tpu_autoscaler.workloads.paged import PagedBatcher  # noqa: E402
from tpu_autoscaler.workloads.spec_serving import (  # noqa: E402
    Request,
    SpeculativePagedBatcher,
)

CFG = ModelConfig(vocab=64, d_model=32, n_layers=4, n_heads=4,
                  d_ff=64, seq_len=64, dtype=jnp.float32)
DCFG = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=4,
                   d_ff=64, seq_len=64, dtype=jnp.float32)


def make_models(seed=0):
    params = init_params(jax.random.PRNGKey(seed), CFG)
    # Cheap draft: the target's first layer only (decode.py's
    # TestSpeculativeDecoding recipe) — agrees often, not always.
    dparams = {**params, "blocks": jax.tree.map(
        lambda x: x[:1], params["blocks"])}
    return params, dparams


def plain_rollouts(params, prompts, new_tokens, **eng_kw):
    eng = PagedBatcher(params, CFG, **eng_kw)
    reqs = [Request(prompt=p, max_new_tokens=nt)
            for p, nt in zip(prompts, new_tokens)]
    for r in reqs:
        eng.submit(r)
    eng.run()
    return [list(r.generated) for r in reqs]


class TestGreedyParity:
    def test_matches_plain_paged_engine(self):
        """Mixed lengths through 3 slots: token-for-token identical to
        the non-speculative engine, in strictly fewer target passes."""
        params, dparams = make_models()
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, CFG.vocab, (n,)).astype(np.int32)
                   for n in (5, 17, 9, 26)]
        new_tokens = [8, 6, 10, 5]
        kw = dict(slots=3, max_len=64, block_size=8, chunk=8)
        want = plain_rollouts(params, prompts, new_tokens, **kw)
        eng = SpeculativePagedBatcher(params, CFG, dparams, DCFG, k=3,
                                      **kw)
        reqs = [Request(prompt=p, max_new_tokens=nt)
                for p, nt in zip(prompts, new_tokens)]
        for r in reqs:
            eng.submit(r)
        eng.run()
        for r, w in zip(reqs, want):
            assert r.done
            assert list(r.generated) == w
        # Each request's FIRST token is seeded by prefill, not decode.
        decode_total = sum(new_tokens) - len(prompts)
        assert eng.decode_tokens == decode_total
        # The speculative economics: fewer verify passes than tokens.
        assert eng.verify_passes < decode_total
        assert 0.0 < eng.target_pass_ratio < 1.0

    def test_self_draft_accepts_everything(self):
        """draft == target: every proposal accepted — the efficiency
        ceiling, and the sharpest bookkeeping check (full-accept
        exercises the draft replay every round)."""
        params, _ = make_models()
        kw = dict(slots=2, max_len=64, block_size=8, chunk=8)
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, CFG.vocab, (n,)).astype(np.int32)
                   for n in (6, 11)]
        want = plain_rollouts(params, prompts, [9, 9], **kw)
        eng = SpeculativePagedBatcher(params, CFG, params, CFG, k=4,
                                      **kw)
        reqs = [Request(prompt=p, max_new_tokens=9) for p in prompts]
        for r in reqs:
            eng.submit(r)
        eng.run()
        for r, w in zip(reqs, want):
            assert list(r.generated) == w
        assert eng.accept_rate == 1.0
        # 9 tokens per request at k=4: ceil((9-1)/5)+1 = 3 verify
        # rounds each, interleaved in at most 4 engine passes.
        assert eng.target_pass_ratio <= 0.5

    def test_replay_write_at_block_boundary(self):
        """Full acceptance whose replay position starts a NEW draft
        block (len 4 + k 4 = position 8 at block_size 8): without the
        +1 draft reservation the write dropped silently and the draft
        attended over garbage from then on (review finding) — with a
        self-draft, acceptance must stay total through the boundary."""
        params, _ = make_models()
        p = (np.arange(4, dtype=np.int32) * 7) % CFG.vocab
        kw = dict(slots=1, max_len=64, block_size=8, chunk=8)
        want = plain_rollouts(params, [p], [16], **kw)[0]
        eng = SpeculativePagedBatcher(params, CFG, params, CFG, k=4,
                                      **kw)
        r = Request(prompt=p, max_new_tokens=16)
        eng.submit(r)
        eng.run()
        assert list(r.generated) == want
        assert eng.accept_rate == 1.0

    def test_eos_mid_accepted_block(self):
        """An eos inside an accepted block truncates the emission and
        frees the slot (the next queued request is served)."""
        params, dparams = make_models()
        kw = dict(slots=1, max_len=64, block_size=8, chunk=8)
        rng = np.random.default_rng(2)
        p1 = rng.integers(0, CFG.vocab, (7,)).astype(np.int32)
        p2 = rng.integers(0, CFG.vocab, (5,)).astype(np.int32)
        ref = plain_rollouts(params, [p1], [10], **kw)[0]
        # Choose an eos that appears mid-stream in the reference.
        cut = next((i for i in range(1, len(ref))
                    if ref[i] not in ref[:i]), 0)
        eos = int(ref[cut])
        ref2 = plain_rollouts(params, [p2], [4], **kw)[0]
        eng = SpeculativePagedBatcher(params, CFG, dparams, DCFG, k=3,
                                      **kw)
        r1 = Request(prompt=p1, max_new_tokens=10, eos_id=eos)
        r2 = Request(prompt=p2, max_new_tokens=4)
        eng.submit(r1)
        eng.submit(r2)
        eng.run()
        assert r1.done and r1.generated[-1] == eos
        assert len(r1.generated) == cut + 1
        assert list(r1.generated) == ref[:cut + 1]
        assert list(r2.generated) == ref2
        # Accept accounting after eos truncation (ADVICE r5 #4): drafts
        # past the eos were never emitted, so accepted can never exceed
        # emitted — pre-fix, an eos mid-block overstated accept_rate.
        assert eng.accepted_tokens <= eng.decode_tokens
        assert eng.accept_rate <= 1.0

    def test_max_new_tokens_never_exceeded(self):
        """The per-slot k_eff cap: a request one token from its budget
        degenerates to plain decode instead of overshooting."""
        params, dparams = make_models()
        kw = dict(slots=2, max_len=64, block_size=8, chunk=8)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, CFG.vocab, (n,)).astype(np.int32)
                   for n in (6, 9)]
        new_tokens = [1, 2]  # tiny budgets force k_eff 0/1
        want = plain_rollouts(params, prompts, new_tokens, **kw)
        eng = SpeculativePagedBatcher(params, CFG, dparams, DCFG, k=4,
                                      **kw)
        reqs = [Request(prompt=p, max_new_tokens=nt)
                for p, nt in zip(prompts, new_tokens)]
        for r in reqs:
            eng.submit(r)
        eng.run()
        for r, w, nt in zip(reqs, want, new_tokens):
            assert len(r.generated) == nt
            assert list(r.generated) == w


class TestAccountingAndPressure:
    def test_accounting_holds_every_tick(self):
        params, dparams = make_models()
        rng = np.random.default_rng(4)
        prompts = [rng.integers(0, CFG.vocab, (n,)).astype(np.int32)
                   for n in (20, 9, 14)]
        eng = SpeculativePagedBatcher(
            params, CFG, dparams, DCFG, k=3, slots=2, max_len=64,
            block_size=8, chunk=8)
        for p in prompts:
            eng.submit(Request(prompt=p, max_new_tokens=5))
        for _ in range(10_000):
            if eng.idle:
                break
            eng.tick()
            eng.check_accounting()
        assert eng.idle
        assert eng.allocator.used_blocks == 0
        assert eng.d_allocator.used_blocks == 0

    def test_pool_pressure_preempts_and_stays_exact(self):
        """A pool smaller than the three prompts' combined prefill
        footprint: preemption churns BOTH caches and the greedy output
        still matches the plain engine.

        The pool must be tight enough that preemption is STRUCTURAL:
        3 prompts x ceil(30/8) = 12 blocks of prompt alone exceed the
        10-block pool, so some slot always hits exhaustion during
        prefill no matter how accept lengths interleave.  (The old
        14-block pool only preempted for *some* accept patterns —
        whether the assertion held depended on floating-point argmax
        ties that shift with jax version and test order: the
        order-dependent flake noted at PR 7.)"""
        params, dparams = make_models()
        rng = np.random.default_rng(5)
        prompts = [rng.integers(0, CFG.vocab, (n,)).astype(np.int32)
                   for n in (30, 30, 30)]
        kw = dict(slots=3, max_len=64, block_size=8, chunk=8)
        want = plain_rollouts(params, prompts, [6, 6, 6], **kw)
        eng = SpeculativePagedBatcher(
            params, CFG, dparams, DCFG, k=3, slots=3, max_len=64,
            block_size=8, num_blocks=10, chunk=8)
        reqs = [Request(prompt=p, max_new_tokens=6) for p in prompts]
        for r in reqs:
            eng.submit(r)
        for _ in range(10_000):
            if eng.idle:
                break
            eng.tick()
            eng.check_accounting()
        assert eng.idle and eng.preemptions > 0
        for r, w in zip(reqs, want):
            assert r.done
            assert list(r.generated) == w


class TestSampledServing:
    def test_sampled_self_draft_accepts_everything(self):
        """p == q at every position: min(1, p/q) = 1 — total
        acceptance, the internal-consistency check of the sampled
        accept ratio through the engine."""
        params, _ = make_models()
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, CFG.vocab, (n,)).astype(np.int32)
                   for n in (6, 10)]
        eng = SpeculativePagedBatcher(
            params, CFG, params, CFG, k=3, slots=2, max_len=64,
            block_size=8, chunk=8)
        for p in prompts:
            eng.submit(Request(prompt=p, max_new_tokens=8,
                               temperature=1.0))
        eng.run()
        assert eng.accept_rate > 0.99

    def test_mixed_greedy_and_sampled_slots(self):
        """Greedy and sampled requests batch together: the greedy row
        stays exactly the plain engine's stream while its neighbor
        samples."""
        params, dparams = make_models()
        rng = np.random.default_rng(7)
        gp = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
        sp = rng.integers(0, CFG.vocab, (6,)).astype(np.int32)
        kw = dict(slots=2, max_len=64, block_size=8, chunk=8)
        want = plain_rollouts(params, [gp], [7], **kw)[0]
        eng = SpeculativePagedBatcher(params, CFG, dparams, DCFG, k=3,
                                      **kw)
        greedy = Request(prompt=gp, max_new_tokens=7)
        sampled = Request(prompt=sp, max_new_tokens=7, temperature=0.9)
        eng.submit(greedy)
        eng.submit(sampled)
        eng.run()
        assert list(greedy.generated) == want
        assert len(sampled.generated) == 7

    def test_validation(self):
        params, dparams = make_models()
        with pytest.raises(ValueError, match="k must be"):
            SpeculativePagedBatcher(params, CFG, dparams, DCFG, k=0)
        with pytest.raises(ValueError, match="must be < chunk"):
            SpeculativePagedBatcher(params, CFG, dparams, DCFG, k=8,
                                    chunk=8)
        import dataclasses as dc

        bad = dc.replace(DCFG, vocab=32)
        with pytest.raises(ValueError, match="vocab"):
            SpeculativePagedBatcher(params, CFG, dparams, bad)
