"""ICI-atomic slice repair (ISSUE 7).

The acceptance scenario: one failed host inside a live v5p slice
resolves via cordon → checkpoint drain → whole-slice replacement —
never a full fleet re-provision, never a lone-host backfill — with a
complete ``slice_repair`` span in the flight recorder, and the supply
guard held across the repair's re-provision (no phantom-free-capacity
window even when its TTL expires mid-repair).
"""

import pytest

from tpu_autoscaler.actuators.base import ACTIVE
from tpu_autoscaler.actuators.fake import FakeActuator
from tpu_autoscaler.controller import Controller, ControllerConfig
from tpu_autoscaler.engine.planner import PoolPolicy
from tpu_autoscaler.k8s.fake import FakeKube
from tpu_autoscaler.k8s.payloads import tpu_host_payload
from tpu_autoscaler.obs import trace_gaps
from tpu_autoscaler.topology import shape_by_name

from tests.fixtures import make_gang, make_tpu_pod

SHAPE = "v5p-16"  # 4 hosts, 4 chips each — the smallest multi-host v5p


def make_harness(policy=None, **cfg):
    kube = FakeKube()
    actuator = FakeActuator(kube)
    cfg.setdefault("grace_seconds", 30.0)
    cfg.setdefault("idle_threshold_seconds", 120.0)
    cfg.setdefault("drain_grace_seconds", 20.0)
    cfg.setdefault("provision_retry_seconds", 30.0)
    cfg.setdefault("slice_repair_after_seconds", 30.0)
    controller = Controller(kube, actuator, ControllerConfig(
        policy=policy or PoolPolicy(spare_nodes=0), **cfg))
    return kube, actuator, controller


def drive(kube, controller, shape, names, job, t0, until, step=5.0):
    """Sim loop with a Job-controller model: evicted/GC'd members are
    recreated Pending; pods bound to deleted nodes are GC'd."""
    t = t0
    while t <= until:
        node_names = {n["metadata"]["name"] for n in kube.list_nodes()}
        for p in list(kube.list_pods()):
            bound = p["spec"].get("nodeName")
            if bound and bound not in node_names:
                kube.delete_pod(p["metadata"].get("namespace", "default"),
                                p["metadata"]["name"])
        for n in names:
            if kube.get_pod("default", n) is None:
                kube.add_pod(make_tpu_pod(
                    name=n, chips=shape.chips_per_host, shape=shape,
                    job=job))
        controller.reconcile_once(now=t)
        kube.schedule_step()
        t += step
    return t


def running(kube, names):
    return all((kube.get_pod("default", n) or {}).get(
        "status", {}).get("phase") == "Running" for n in names)


def start_gang(kube, controller, shape, job="train"):
    names = []
    for p in make_gang(shape, job=job):
        kube.add_pod(p)
        names.append(p["metadata"]["name"])
    t = 0.0
    while t <= 100.0 and not running(kube, names):
        controller.reconcile_once(now=t)
        kube.schedule_step()
        t += 5.0
    assert running(kube, names)
    return names, t


class TestSliceRepairAcceptance:
    def _run(self, kill_mode):
        kube, actuator, controller = make_harness()
        shape = shape_by_name(SHAPE)
        names, t = start_gang(kube, controller, shape)
        first_nodes = {n["metadata"]["name"] for n in kube.list_nodes()}
        assert len(first_nodes) == shape.hosts
        submitted_before = int(controller.metrics.snapshot()[
            "counters"]["provisions_submitted"])

        victim = sorted(first_nodes)[0]
        actuator.fail_host(victim, kill_mode)
        drive(kube, controller, shape, names, "train", t, t + 400.0)
        assert running(kube, names)
        second_nodes = {n["metadata"]["name"] for n in kube.list_nodes()}
        # Whole-slice replacement: a fresh slice, full host count, and
        # NO surviving host of the original (never a lone-host backfill
        # into the old ICI domain).
        assert len(second_nodes) == shape.hosts
        assert second_nodes.isdisjoint(first_nodes)
        # Never a fleet re-provision: exactly ONE replacement provision.
        snap = controller.metrics.snapshot()
        assert int(snap["counters"]["provisions_submitted"]) \
            == submitted_before + 1
        assert snap["counters"]["slice_repairs_started"] == 1
        assert snap["counters"]["slice_repairs_completed"] == 1
        assert snap["summaries"]["slice_repair_seconds"]["count"] == 1
        # The slice_repair trace is in the recorder and complete.
        dump = controller.recorder.dump(tracer=controller.tracer)
        repair_traces = {s["trace_id"] for s in dump["spans"]
                         if s["name"] == "slice_repair"}
        assert len(repair_traces) == 1
        (trace_id,) = repair_traces
        assert trace_gaps(dump, trace_id) == []
        span_names = {s["name"] for s in dump["spans"]
                      if s["trace_id"] == trace_id}
        # The repair story carries its drain AND the replacement's
        # dispatch/provision (repair-ahead provisioning).
        assert {"slice_repair", "repair_drain", "dispatch",
                "provision"} <= span_names
        return controller, dump

    def test_notready_host_in_live_v5p_slice(self):
        self._run("notready")

    def test_deleted_host_in_live_v5p_slice(self):
        self._run("delete")

    def test_flap_window_holds_for_notready(self):
        """A NotReady blip shorter than slice_repair_after_seconds never
        starts a repair."""
        kube, actuator, controller = make_harness(
            slice_repair_after_seconds=60.0)
        shape = shape_by_name(SHAPE)
        names, t = start_gang(kube, controller, shape)
        victim = sorted(n["metadata"]["name"]
                        for n in kube.list_nodes())[0]
        kube.set_node_ready(victim, False)
        controller.reconcile_once(now=t)
        controller.reconcile_once(now=t + 20.0)
        kube.set_node_ready(victim, True)  # flap over
        controller.reconcile_once(now=t + 40.0)
        controller.reconcile_once(now=t + 120.0)
        snap = controller.metrics.snapshot()
        assert snap["counters"].get("slice_repairs_started", 0) == 0
        assert running(kube, names)

    def test_deleted_host_repairs_without_flap_window(self):
        """A DELETED host starts the repair on the very pass it is
        observed — there is nothing to flap."""
        kube, actuator, controller = make_harness(
            slice_repair_after_seconds=3600.0)
        shape = shape_by_name(SHAPE)
        names, t = start_gang(kube, controller, shape)
        victim = sorted(n["metadata"]["name"]
                        for n in kube.list_nodes())[0]
        actuator.fail_host(victim, "delete")
        controller.reconcile_once(now=t)
        assert controller.metrics.snapshot()[
            "counters"]["slice_repairs_started"] == 1

    def test_repair_disabled_falls_back_to_legacy_replace(self):
        kube, actuator, controller = make_harness(
            enable_slice_repair=False, unhealthy_timeout_seconds=30.0)
        shape = shape_by_name(SHAPE)
        names, t = start_gang(kube, controller, shape)
        victim = sorted(n["metadata"]["name"]
                        for n in kube.list_nodes())[0]
        kube.set_node_ready(victim, False)
        drive(kube, controller, shape, names, "train", t, t + 400.0)
        assert running(kube, names)
        snap = controller.metrics.snapshot()
        assert snap["counters"].get("slice_repairs_started", 0) == 0
        assert snap["counters"]["unhealthy_units_replaced"] == 1


class TestNoLoneHostBackfill:
    def test_recreated_member_never_planned_solo(self):
        """The dead host's recreated pod must not be sized alone (a
        1-pod gang would fit a tiny slice — bisecting the job across
        ICI domains); it waits for the whole-gang replacement."""
        kube, actuator, controller = make_harness()
        shape = shape_by_name(SHAPE)
        names, t = start_gang(kube, controller, shape)
        victim = sorted(n["metadata"]["name"]
                        for n in kube.list_nodes())[0]
        actuator.fail_host(victim, "delete")
        drive(kube, controller, shape, names, "train", t, t + 400.0)
        assert running(kube, names)
        # Every provision ever submitted was the FULL slice shape.
        shapes = {s.request.shape_name for s in actuator.statuses()}
        assert shapes <= {SHAPE}
        for req_shape in shapes:
            assert shape_by_name(req_shape).hosts == shape.hosts
        # And the gang ended up on ONE slice.
        slice_ids = {n["metadata"]["labels"][
            "autoscaler.tpu.dev/slice-id"] for n in kube.list_nodes()}
        assert len(slice_ids) == 1


class SlowRegisterActuator(FakeActuator):
    """Provisions go ACTIVE immediately but their nodes register only
    when the test says so — the real-cloud registration lag, long
    enough here to outlive the supply-guard TTL."""

    def __init__(self, kube):
        super().__init__(kube)
        self.register_held: set[str] = set()

    def _materialize(self, pid, status, now):
        req = status.request
        if req.kind == "tpu-slice" and pid in self.register_held:
            status.state = ACTIVE
            status.unit_ids = [f"{req.shape_name}-{pid}"]
            return
        super()._materialize(pid, status, now)

    def release(self, now):
        for pid in list(self.register_held):
            self.register_held.discard(pid)
            status = self._statuses.get(pid)
            if status is None:
                continue
            shape = shape_by_name(status.request.shape_name)
            for slice_id in status.unit_ids:
                for i in range(shape.hosts):
                    self._kube.add_node(tpu_host_payload(
                        shape, slice_id, i, created_at=now))


class TestSupplyGuardRepairHold:
    """ISSUE 7 satellite: supply-guard TTL expiry racing an in-flight
    slice repair — the guard must stay engaged across the repair's
    re-provision; no window where the planner sees phantom free
    capacity and double-provisions."""

    def _run(self, *, hold_enabled):
        kube, actuator, controller = make_harness(
            provision_timeout_seconds=40.0)
        actuator.__class__ = SlowRegisterActuator
        actuator.register_held = set()
        shape = shape_by_name(SHAPE)
        names, t = start_gang(kube, controller, shape)
        if not hold_enabled:
            controller._repair_depends_on = lambda gang_key: False
        # Every FUTURE provision registers its nodes late.
        real_provision = actuator.provision

        def held_provision(request):
            status = real_provision(request)
            actuator.register_held.add(status.id)
            return status

        actuator.provision = held_provision
        victim = sorted(n["metadata"]["name"]
                        for n in kube.list_nodes())[0]
        actuator.fail_host(victim, "delete")
        # Run well past the 40 s guard TTL with registration held.
        t_end = drive(kube, controller, shape, names, "train", t,
                      t + 160.0)
        snap = controller.metrics.snapshot()
        replacements = int(snap["counters"]["provisions_submitted"]) - 1
        holds = int(snap["counters"].get("supply_guard_repair_holds", 0))
        # Let registration finally complete and the repair finish.
        actuator.release(t_end)
        drive(kube, controller, shape, names, "train", t_end,
              t_end + 200.0)
        return replacements, holds, kube, names, controller

    def test_guard_held_across_repair_reprovision(self):
        replacements, holds, kube, names, controller = self._run(
            hold_enabled=True)
        assert replacements == 1, \
            "guard hold must prevent a duplicate replacement"
        assert holds >= 1
        assert running(kube, names)
        snap = controller.metrics.snapshot()
        assert snap["counters"]["slice_repairs_completed"] == 1

    def test_without_hold_guard_expiry_double_provisions(self):
        """Seeded-bug direction (racefixtures-style): with the hold
        disabled, TTL expiry mid-repair opens the phantom-capacity
        window and a duplicate replacement IS submitted — proving the
        hold is load-bearing, not decorative."""
        replacements, _holds, _kube, _names, _controller = self._run(
            hold_enabled=False)
        assert replacements >= 2


class TestRepairDeferredUnderClamp:
    def test_repair_waits_for_headroom_never_unsatisfiable(self):
        """With max_total_chips exactly the fleet size, the replacement
        cannot pre-provision; it is DEFERRED (explained, never reported
        unsatisfiable) until the broken slice is deleted, then lands."""
        shape = shape_by_name(SHAPE)
        kube, actuator, controller = make_harness(
            policy=PoolPolicy(spare_nodes=0,
                              max_total_chips=shape.chips))
        names, t = start_gang(kube, controller, shape)
        victim = sorted(n["metadata"]["name"]
                        for n in kube.list_nodes())[0]
        actuator.fail_host(victim, "delete")
        drive(kube, controller, shape, names, "train", t, t + 500.0)
        assert running(kube, names)
        snap = controller.metrics.snapshot()
        assert snap["counters"]["slice_repairs_completed"] == 1
        assert snap["counters"].get("unsatisfiable_gangs", 0) == 0


class TestOrphanedPartialReclaim:
    def test_partial_slice_with_no_backing_provision_is_reclaimed(self):
        """Fuzzer-found: a provision that FAILs after materializing
        some hosts leaves a forever-PROVISIONING partial slice; it is
        reclaimed whole after provision_timeout_seconds."""
        kube, actuator, controller = make_harness(
            provision_timeout_seconds=60.0)
        shape = shape_by_name(SHAPE)
        # Orphan hosts: 2 of 4, no actuator status behind them.
        for i in range(2):
            kube.add_node(tpu_host_payload(shape, "orphan-1", i,
                                           created_at=0.0))
        t = 0.0
        while t <= 120.0:
            controller.reconcile_once(now=t)
            t += 5.0
        assert kube.list_nodes() == []
        snap = controller.metrics.snapshot()
        assert snap["counters"]["orphaned_partial_units_reclaimed"] == 1

    def test_workload_bearing_partial_slice_is_not_orphan_reclaimed(self):
        """A partial slice HOSTING pods goes through repair, never the
        orphan path."""
        kube, actuator, controller = make_harness(
            provision_timeout_seconds=60.0)
        shape = shape_by_name(SHAPE)
        names, t = start_gang(kube, controller, shape)
        victim = sorted(n["metadata"]["name"]
                        for n in kube.list_nodes())[0]
        actuator.fail_host(victim, "delete")
        drive(kube, controller, shape, names, "train", t, t + 400.0)
        snap = controller.metrics.snapshot()
        assert snap["counters"].get(
            "orphaned_partial_units_reclaimed", 0) == 0
        assert snap["counters"]["slice_repairs_completed"] == 1


class TestRepairTimeout:
    def test_abandoned_repair_is_bounded_and_traced(self):
        """A repair whose replacement never lands closes abandoned at
        slice_repair_timeout_seconds (bookkeeping bounded; span ends
        with the error attr so the trace is still whole)."""
        kube, actuator, controller = make_harness(
            slice_repair_timeout_seconds=100.0)
        shape = shape_by_name(SHAPE)
        names, t = start_gang(kube, controller, shape)
        # Every future provision fails: no replacement can ever land.
        actuator._fail_shapes.add(SHAPE)
        victim = sorted(n["metadata"]["name"]
                        for n in kube.list_nodes())[0]
        actuator.fail_host(victim, "delete")
        drive(kube, controller, shape, names, "train", t, t + 300.0)
        snap = controller.metrics.snapshot()
        assert snap["counters"]["slice_repairs_started"] == 1
        assert snap["counters"]["slice_repairs_abandoned"] == 1
        assert controller._slice_repairs == {}
        assert controller._repair_roots == {}
