"""Gang grouping tests: demand units are gangs, not pods (SURVEY.md §6.7)."""

from tpu_autoscaler.k8s.gangs import group_into_gangs
from tpu_autoscaler.k8s.objects import Pod
from tpu_autoscaler.topology import shape_by_name

from tests.fixtures import make_gang, make_pod, make_tpu_pod


def pods(payloads):
    return [Pod(p) for p in payloads]


class TestGrouping:
    def test_solo_pods_are_singleton_gangs(self):
        gs = group_into_gangs(pods([make_pod(name="a"), make_pod(name="b")]))
        assert len(gs) == 2
        assert all(g.size == 1 for g in gs)

    def test_job_pods_group(self):
        shape = shape_by_name("v5e-64")
        gs = group_into_gangs(pods(make_gang(shape, job="train")))
        assert len(gs) == 1
        g = gs[0]
        assert g.size == 16          # one pod per host
        assert g.tpu_chips == 64     # 4 chips per pod
        assert g.key == ("job", "default", "train")

    def test_jobset_replicas_are_separate_gangs(self):
        # Multi-slice: 2 x v5p-128, one gang per slice (BASELINE config #4).
        shape = shape_by_name("v5p-128")
        all_pods = []
        for idx in range(2):
            all_pods += make_gang(shape, job=f"ms-job-{idx}", jobset="ms",
                                  job_index=idx)
        # Strip the job label so grouping exercises the jobset/index path.
        for p in all_pods:
            del p["metadata"]["labels"]["batch.kubernetes.io/job-name"]
        gs = group_into_gangs(pods(all_pods))
        assert len(gs) == 2
        assert {g.key for g in gs} == {("jobset", "default", "ms/0"),
                                       ("jobset", "default", "ms/1")}
        assert all(g.tpu_chips == 128 for g in gs)
        assert all(g.jobset_name == "ms" for g in gs)

    def test_ordering_oldest_first(self):
        old = make_pod(name="old", created="2026-07-28T10:00:00Z")
        new = make_pod(name="new", created="2026-07-28T12:00:00Z")
        untimed = make_pod(name="untimed", created=None)
        gs = group_into_gangs(pods([new, untimed, old]))
        assert [g.name for g in gs] == ["old", "new", "untimed"]


class TestGangProperties:
    def test_selectors_merged(self):
        shape = shape_by_name("v5e-16")
        gs = group_into_gangs(pods(make_gang(shape, job="j")))
        sel = gs[0].node_selectors
        assert sel["cloud.google.com/gke-tpu-topology"] == "4x4"

    def test_per_pod_envelope(self):
        a = make_tpu_pod(name="a", chips=4, job="j",
                         requests={"cpu": "2", "google.com/tpu": "4"})
        b = make_tpu_pod(name="b", chips=4, job="j",
                         requests={"cpu": "8", "google.com/tpu": "4"})
        g = group_into_gangs(pods([a, b]))[0]
        assert g.per_pod_resources.get("cpu") == 8.0
        assert g.per_pod_resources.get("google.com/tpu") == 4.0
        assert g.total_resources.get("cpu") == 10.0

    def test_cpu_only_gang(self):
        g = group_into_gangs(pods([make_pod(requests={"cpu": "2"})]))[0]
        assert not g.requests_tpu
        assert g.tpu_chips == 0


class TestPriorityOrdering:
    def test_higher_priority_served_first(self):
        low = make_pod(name="low", created="2026-07-28T10:00:00Z")
        high = make_pod(name="high", created="2026-07-28T12:00:00Z")
        high["spec"]["priority"] = 1000
        gs = group_into_gangs(pods([low, high]))
        # Priority beats age.
        assert [g.name for g in gs] == ["high", "low"]

    def test_equal_priority_falls_back_to_age(self):
        a = make_pod(name="newer", created="2026-07-28T12:00:00Z")
        b = make_pod(name="older", created="2026-07-28T10:00:00Z")
        for p in (a, b):
            p["spec"]["priority"] = 5
        gs = group_into_gangs(pods([a, b]))
        assert [g.name for g in gs] == ["older", "newer"]

    def test_gang_priority_is_max_of_members(self):
        a = make_tpu_pod(name="a", chips=4, job="j")
        b = make_tpu_pod(name="b", chips=4, job="j")
        b["spec"]["priority"] = 7
        g = group_into_gangs(pods([a, b]))[0]
        assert g.priority == 7
