"""ActuationExecutor tests (actuators/executor.py): bounded concurrency,
drain-side completion delivery on the reconcile thread, and
deadline-aware retry RESCHEDULING (a backing-off call is parked at
retry_at, never slept on, and never occupies a worker slot)."""

import functools
import threading

import pytest

from tpu_autoscaler.actuators.executor import ActuationExecutor, RetryLater


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


class Sink:
    def __init__(self):
        self.counts = {}
        self.observed = {}
        self.gauges = {}

    def inc(self, name, by=1.0):
        self.counts[name] = self.counts.get(name, 0) + by

    def observe(self, name, value):
        self.observed.setdefault(name, []).append(value)

    def set_gauge(self, name, value):
        self.gauges[name] = value


@pytest.fixture()
def executor():
    ex = ActuationExecutor(max_workers=4, clock=FakeClock())
    yield ex
    ex.shutdown()


def run_settled(ex, rounds=50):
    """wait+drain until idle (real worker threads finish fast)."""
    for _ in range(rounds):
        ex.wait(timeout=5)
        ex.drain()
        if not ex.depth:
            return
    raise AssertionError(f"executor never went idle (depth={ex.depth})")


class TestDelivery:
    def test_success_delivered_on_drain_only(self, executor):
        done = []
        executor.submit(lambda: 42, lambda r, e: done.append((r, e)))
        executor.wait()
        assert done == []  # completion exists but is NOT delivered yet
        executor.drain()
        assert done == [(42, None)]

    def test_callbacks_run_on_draining_thread(self, executor):
        tids = []
        executor.submit(lambda: threading.get_ident(),
                        lambda r, e: tids.append((r, threading.get_ident())))
        executor.wait()
        executor.drain()
        worker_tid, callback_tid = tids[0]
        assert callback_tid == threading.get_ident()  # reconcile thread
        assert worker_tid != callback_tid             # work ran off-thread

    def test_terminal_exception_delivered_as_error(self, executor):
        done = []
        boom = ValueError("no")

        def fn():
            raise boom

        executor.submit(fn, lambda r, e: done.append((r, e)))
        executor.wait()
        executor.drain()
        assert done == [(None, boom)]

    def test_callback_exception_does_not_starve_drain(self):
        sink = Sink()
        ex = ActuationExecutor(max_workers=2, metrics=sink)
        try:
            done = []

            def bad_callback(r, e):
                raise RuntimeError("callback bug")

            ex.submit(lambda: 1, bad_callback)
            ex.submit(lambda: 2, lambda r, e: done.append(r))
            run_settled(ex)
            assert done == [2]
            assert sink.counts["actuation_callback_errors"] == 1
        finally:
            ex.shutdown()

    def test_concurrency_is_real(self):
        # 4 calls must run simultaneously to pass the barrier at all.
        ex = ActuationExecutor(max_workers=4)
        try:
            barrier = threading.Barrier(4, timeout=5)
            done = []
            for _ in range(4):
                ex.submit(barrier.wait, lambda r, e: done.append(e))
            run_settled(ex)
            assert done == [None] * 4
        finally:
            ex.shutdown()


class TestRescheduling:
    def test_retry_parked_until_retry_at_then_succeeds(self):
        clock = FakeClock()
        sink = Sink()
        ex = ActuationExecutor(max_workers=2, clock=clock, metrics=sink)
        try:
            attempts = []
            done = []

            def flaky():
                attempts.append(1)
                if len(attempts) < 3:
                    raise RetryLater("503", retry_after="2")
                return "ok"

            ex.submit(flaky, lambda r, e: done.append((r, e)))
            ex.wait()
            ex.drain()  # first failure -> parked at now+2 (Retry-After)
            assert done == [] and ex.depth == 1
            assert sink.counts["actuation_retries_rescheduled"] == 1
            ex.drain()  # retry_at not reached: stays parked, no dispatch
            assert len(attempts) == 1
            clock.advance(2.0)
            ex.drain()  # woken and redispatched
            ex.wait()
            ex.drain()  # second failure -> parked again
            assert len(attempts) == 2 and done == []
            clock.advance(2.0)
            ex.drain()
            ex.wait()
            ex.drain()
            assert done == [("ok", None)]
            assert ex.depth == 0
        finally:
            ex.shutdown()

    def test_retries_exhausted_delivers_terminal(self):
        clock = FakeClock()
        ex = ActuationExecutor(max_workers=2, clock=clock, max_attempts=2)
        try:
            done = []

            def always_503():
                raise RetryLater("503", retry_after="1")

            ex.submit(always_503, lambda r, e: done.append(e))
            ex.wait()
            ex.drain()  # attempt 0 failed -> parked (1 of 2 attempts used)
            clock.advance(1.0)
            ex.drain()
            ex.wait()
            ex.drain()  # attempt 1 failed -> attempts exhausted
            assert len(done) == 1
            assert isinstance(done[0], RetryLater)
        finally:
            ex.shutdown()

    def test_deadline_blocks_reschedule(self):
        # A reschedule that would land past the call's deadline delivers
        # the terminal error instead of parking.
        clock = FakeClock()
        ex = ActuationExecutor(max_workers=2, clock=clock, max_attempts=5)
        try:
            done = []

            def always_503():
                raise RetryLater("503", retry_after="30")

            ex.submit(always_503, lambda r, e: done.append(e),
                      deadline_s=10.0)
            ex.wait()
            ex.drain()  # retry_at = now+30 > deadline now+10 -> terminal
            assert len(done) == 1 and isinstance(done[0], RetryLater)
            assert ex.depth == 0
        finally:
            ex.shutdown()

    def test_reschedule_with_gcp_rest_fake_transport(self, monkeypatch):
        """End-to-end satellite: GcpRest.once through the executor — a
        503 is RESCHEDULED at retry_at (no reconcile-thread sleep), the
        redispatched attempt resends the same call and succeeds."""
        import random

        from tpu_autoscaler.actuators.gcp import GcpRest

        class Resp:
            def __init__(self, status, body):
                self.status_code = status
                self._body = body
                self.headers = {}
                self.content = b"x"

            def json(self):
                return self._body

        script = [Resp(503, {"error": {"message": "hiccup"}}),
                  Resp(200, {"state": {"state": "ACTIVE"}})]
        calls = []

        def transport(method, url, headers=None, json=None, timeout=None):
            calls.append((method, url, json))
            return script.pop(0)

        monkeypatch.setenv("GCP_ACCESS_TOKEN", "tok-x")
        sleeps = []
        rest = GcpRest(sleep=sleeps.append, rng=random.Random(0),
                       transport=transport)
        clock = FakeClock()
        ex = ActuationExecutor(max_workers=2, clock=clock,
                               rng=random.Random(0))
        try:
            done = []
            ex.submit(functools.partial(rest.once, "GET", "https://t/qr"),
                      lambda r, e: done.append((r, e)), label="qr-poll")
            ex.wait()
            ex.drain()  # 503 -> parked
            assert done == [] and len(calls) == 1
            clock.advance(10.0)  # past any jittered backoff (cap 8 s)
            ex.drain()
            ex.wait()
            ex.drain()
            assert done == [({"state": {"state": "ACTIVE"}}, None)]
            assert len(calls) == 2
            assert sleeps == []  # NOTHING slept in-place
        finally:
            ex.shutdown()


class TestMetrics:
    def test_dispatch_latency_and_depth_exported(self):
        clock = FakeClock()
        sink = Sink()
        ex = ActuationExecutor(max_workers=2, clock=clock, metrics=sink)
        try:
            ex.submit(lambda: 1, lambda r, e: None)
            ex.wait()
            clock.advance(0.25)
            ex.drain()
            assert sink.observed[
                "actuation_dispatch_latency_seconds"] == [0.25]
            assert sink.gauges["actuation_pool_depth"] == 0
        finally:
            ex.shutdown()

    def test_depth_counts_parked_retries(self):
        clock = FakeClock()
        sink = Sink()
        ex = ActuationExecutor(max_workers=2, clock=clock, metrics=sink)
        try:
            ex.submit(lambda: (_ for _ in ()).throw(RetryLater("503")),
                      lambda r, e: None)
            ex.wait()
            ex.drain()
            assert ex.depth == 1  # parked, not running
            assert sink.gauges["actuation_pool_depth"] == 1
        finally:
            ex.shutdown()
