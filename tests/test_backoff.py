"""Tests for the shared backoff arithmetic (tpu_autoscaler/backoff.py)
and the WatchTrigger cursor contract (controller/watch.py) — both were
previously asserted only in docstrings.

Covers: full-jitter bounds stay within [0, min(cap, base*2^attempt)],
the cap holds after arbitrarily many failures (no 2^49s sleeps),
Retry-After wins but is itself capped, malformed Retry-After falls back
to the computed backoff, and the 410 Gone ERROR event drops the watch
resourceVersion cursor (next watch starts from "now") while other ERROR
events keep it.
"""

import random
import threading

import pytest

from tpu_autoscaler.backoff import backoff_seconds
from tpu_autoscaler.controller.watch import (
    BACKOFF_BASE_S,
    BACKOFF_CAP_S,
    WatchTrigger,
    _WatchError,
)


class _RecordingRng(random.Random):
    """uniform() records its bounds and returns the upper one, so tests
    can assert on the jitter CEILING, not a sampled value."""

    def __init__(self):
        super().__init__(0)
        self.bounds = []

    def uniform(self, a, b):
        self.bounds.append((a, b))
        return b


class TestBackoffSeconds:
    BASE, CAP, RA_CAP = 0.5, 8.0, 32.0

    def call(self, attempt, retry_after=None, rng=None):
        return backoff_seconds(
            attempt, retry_after, base_s=self.BASE, cap_s=self.CAP,
            retry_after_cap_s=self.RA_CAP,
            rng=rng if rng is not None else random.Random(1234))

    def test_jitter_within_base_and_cap(self):
        # Sampled values never exceed min(cap, base * 2^attempt) and
        # never go negative — the full-jitter window of the docstring.
        rng = random.Random(42)
        for attempt in range(12):
            ceiling = min(self.CAP, self.BASE * 2 ** attempt)
            for _ in range(200):
                s = self.call(attempt, rng=rng)
                assert 0.0 <= s <= ceiling

    def test_exponential_ceiling_doubles_per_attempt(self):
        rng = _RecordingRng()
        for attempt in range(5):
            self.call(attempt, rng=rng)
        assert [b for _a, b in rng.bounds] == [
            self.BASE, self.BASE * 2, self.BASE * 4, self.BASE * 8,
            self.CAP]  # 0.5,1,2,4 then capped at 8

    def test_cap_respected_after_many_failures(self):
        # attempt=60 would be base*2^60 seconds uncapped — ~18k years.
        rng = _RecordingRng()
        assert self.call(60, rng=rng) == self.CAP
        assert rng.bounds == [(0, self.CAP)]

    def test_retry_after_wins_and_is_capped(self):
        assert self.call(0, retry_after="3") == 3.0
        assert self.call(0, retry_after=2.5) == 2.5
        # An hour-long server hint must not park the control loop.
        assert self.call(0, retry_after="3600") == self.RA_CAP

    def test_malformed_retry_after_falls_back_to_jitter(self):
        rng = _RecordingRng()
        s = self.call(2, retry_after="Wed, 21 Oct 2015 07:28:00 GMT",
                      rng=rng)
        assert s == self.BASE * 4  # computed ceiling, not the header
        assert self.call(1, retry_after=None) <= self.BASE * 2


class TestWatchTriggerCursor:
    """Unit tests of the cursor contract, no threads started."""

    def trigger(self):
        return WatchTrigger(client=None, wake=threading.Event())

    def ev(self, etype, rv=None, code=None, message=None):
        obj = {}
        if rv is not None:
            obj["metadata"] = {"resourceVersion": rv}
        if code is not None:
            obj["code"] = code
        if message is not None:
            obj["message"] = message
        return {"type": etype, "object": obj}

    def test_410_gone_resets_cursor(self):
        t = self.trigger()
        t._handle_event(self.ev("ADDED", rv="100"))
        assert t._resource_version == "100"
        with pytest.raises(_WatchError):
            t._handle_event(self.ev("ERROR", code=410,
                                    message="too old resource version"))
        assert t._resource_version is None  # next watch starts from now

    def test_non_410_error_keeps_cursor(self):
        # A transient ERROR (e.g. 500) must NOT throw away the resume
        # point — relisting the world is the expensive path.
        t = self.trigger()
        t._handle_event(self.ev("MODIFIED", rv="7"))
        with pytest.raises(_WatchError):
            t._handle_event(self.ev("ERROR", code=500, message="boom"))
        assert t._resource_version == "7"

    def test_events_advance_cursor_monotonically_by_stream_order(self):
        t = self.trigger()
        for rv in ("1", "2", "3"):
            t._handle_event(self.ev("MODIFIED", rv=rv))
        assert t._resource_version == "3"

    def test_watch_backoff_ceiling_capped_like_shared_formula(self):
        rng = _RecordingRng()
        t = WatchTrigger(client=None, wake=threading.Event(), rng=rng)
        t._failure_streak = 1
        assert t._backoff_seconds() == BACKOFF_BASE_S
        t._failure_streak = 99
        assert t._backoff_seconds() == BACKOFF_CAP_S
        assert rng.bounds == [(0.0, BACKOFF_BASE_S),
                              (0.0, BACKOFF_CAP_S)]
