"""RestKubeClient integration test against a real in-process HTTP server.

The reference never exercised its HTTP layer in tests (pykube was mocked —
SURVEY.md §5); here a stdlib HTTP server speaks just enough apiserver to
verify paths, verbs, content types, eviction bodies, and watch streaming.
"""

import http.server
import json
import threading

import pytest

from tpu_autoscaler.k8s.client import RestKubeClient


class ApiServerStub(http.server.BaseHTTPRequestHandler):
    requests_log: list[tuple] = []
    pods = {"items": [{"metadata": {"name": "p1", "namespace": "ns"}}]}
    nodes = {"items": [{"metadata": {"name": "n1"}}]}

    def _send_json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        self.requests_log.append(("GET", self.path, None, dict(self.headers)))
        if self.path == "/api/v1/nodes":
            self._send_json(self.nodes)
        elif self.path == "/api/v1/pods":
            self._send_json(self.pods)
        elif self.path.startswith("/api/v1/pods?watch=1"):
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            for event in ({"type": "ADDED"}, {"type": "MODIFIED"}):
                self.wfile.write((json.dumps(event) + "\n").encode())
            # server closes: end of this watch window
        else:
            self._send_json({}, 404)

    def _body(self):
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length).decode() if length else ""

    def do_PATCH(self):  # noqa: N802
        self.requests_log.append(
            ("PATCH", self.path, self._body(), dict(self.headers)))
        self._send_json({})

    def do_POST(self):  # noqa: N802
        self.requests_log.append(
            ("POST", self.path, self._body(), dict(self.headers)))
        self._send_json({})

    def do_DELETE(self):  # noqa: N802
        self.requests_log.append(("DELETE", self.path, None,
                                  dict(self.headers)))
        self._send_json({})

    def log_message(self, *args):
        pass


@pytest.fixture()
def server():
    ApiServerStub.requests_log = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), ApiServerStub)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


class TestRestKubeClient:
    def client(self, base):
        return RestKubeClient(base_url=base, token="tok", ca_cert=False)

    def test_lists(self, server):
        c = self.client(server)
        assert c.list_nodes()[0]["metadata"]["name"] == "n1"
        assert c.list_pods()[0]["metadata"]["name"] == "p1"
        method, path, _, headers = ApiServerStub.requests_log[0]
        assert headers.get("Authorization") == "Bearer tok"

    def test_patch_node_content_type(self, server):
        c = self.client(server)
        c.patch_node("n1", {"spec": {"unschedulable": True}})
        method, path, body, headers = ApiServerStub.requests_log[-1]
        assert (method, path) == ("PATCH", "/api/v1/nodes/n1")
        assert headers["Content-Type"] == \
            "application/strategic-merge-patch+json"
        assert json.loads(body) == {"spec": {"unschedulable": True}}

    def test_eviction_body(self, server):
        c = self.client(server)
        c.evict_pod("ns", "p1")
        method, path, body, _ = ApiServerStub.requests_log[-1]
        assert (method, path) == (
            "POST", "/api/v1/namespaces/ns/pods/p1/eviction")
        parsed = json.loads(body)
        assert parsed["kind"] == "Eviction"
        assert parsed["metadata"] == {"name": "p1", "namespace": "ns"}

    def test_deletes(self, server):
        c = self.client(server)
        c.delete_pod("ns", "p1")
        c.delete_node("n1")
        paths = [(m, p) for m, p, _, _ in ApiServerStub.requests_log]
        assert ("DELETE", "/api/v1/namespaces/ns/pods/p1") in paths
        assert ("DELETE", "/api/v1/nodes/n1") in paths

    def test_watch_streams_events(self, server):
        c = self.client(server)
        events = list(c.watch_pods(timeout_seconds=5))
        assert [e["type"] for e in events] == ["ADDED", "MODIFIED"]

    def test_dry_run_suppresses_mutations(self, server):
        c = RestKubeClient(base_url=server, token="tok", ca_cert=False,
                           dry_run=True)
        c.patch_node("n1", {"spec": {"unschedulable": True}})
        c.delete_node("n1")
        mutations = [(m, p) for m, p, _, _ in ApiServerStub.requests_log
                     if m != "GET"]
        assert mutations == []

    def test_dry_run_suppresses_lease_writes(self, server):
        # ADVICE r1 (medium): a --dry-run --leader-elect process must not
        # write the real Lease and steal leadership from production.
        c = RestKubeClient(base_url=server, token="tok", ca_cert=False,
                           dry_run=True)
        c.put_lease("kube-system", "tpu-autoscaler",
                    {"metadata": {"name": "tpu-autoscaler"}})
        c.put_lease("kube-system", "tpu-autoscaler",
                    {"metadata": {"name": "tpu-autoscaler",
                                  "resourceVersion": "5"}})
        writes = [(m, p) for m, p, _, _ in ApiServerStub.requests_log
                  if m in ("POST", "PUT")]
        assert writes == []
