"""RestKubeClient integration test against a real in-process HTTP server.

The reference never exercised its HTTP layer in tests (pykube was mocked —
SURVEY.md §5); here a stdlib HTTP server speaks just enough apiserver to
verify paths, verbs, content types, eviction bodies, and watch streaming.
"""

import http.server
import json
import threading

import pytest

from tpu_autoscaler.k8s.client import RestKubeClient


class ApiServerStub(http.server.BaseHTTPRequestHandler):
    requests_log: list[tuple] = []
    pods = {"items": [{"metadata": {"name": "p1", "namespace": "ns"}}]}
    nodes = {"items": [{"metadata": {"name": "n1"}}]}

    def _send_json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        self.requests_log.append(("GET", self.path, None, dict(self.headers)))
        if self.path == "/api/v1/nodes":
            self._send_json(self.nodes)
        elif self.path == "/api/v1/pods":
            self._send_json(self.pods)
        elif self.path.startswith(("/api/v1/pods?watch=1",
                                   "/api/v1/nodes?watch=1")):
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            for event in ({"type": "ADDED"}, {"type": "MODIFIED"}):
                self.wfile.write((json.dumps(event) + "\n").encode())
            # server closes: end of this watch window
        else:
            self._send_json({}, 404)

    def _body(self):
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length).decode() if length else ""

    def do_PATCH(self):  # noqa: N802
        self.requests_log.append(
            ("PATCH", self.path, self._body(), dict(self.headers)))
        self._send_json({})

    def do_POST(self):  # noqa: N802
        self.requests_log.append(
            ("POST", self.path, self._body(), dict(self.headers)))
        self._send_json({})

    def do_DELETE(self):  # noqa: N802
        self.requests_log.append(("DELETE", self.path, None,
                                  dict(self.headers)))
        self._send_json({})

    def log_message(self, *args):
        pass


@pytest.fixture()
def server():
    ApiServerStub.requests_log = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), ApiServerStub)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


class TestRestKubeClient:
    def client(self, base):
        return RestKubeClient(base_url=base, token="tok", ca_cert=False)

    def test_lists(self, server):
        c = self.client(server)
        assert c.list_nodes()[0]["metadata"]["name"] == "n1"
        assert c.list_pods()[0]["metadata"]["name"] == "p1"
        method, path, _, headers = ApiServerStub.requests_log[0]
        assert headers.get("Authorization") == "Bearer tok"

    def test_patch_node_content_type(self, server):
        c = self.client(server)
        c.patch_node("n1", {"spec": {"unschedulable": True}})
        method, path, body, headers = ApiServerStub.requests_log[-1]
        assert (method, path) == ("PATCH", "/api/v1/nodes/n1")
        assert headers["Content-Type"] == \
            "application/strategic-merge-patch+json"
        assert json.loads(body) == {"spec": {"unschedulable": True}}

    def test_eviction_body(self, server):
        c = self.client(server)
        c.evict_pod("ns", "p1")
        method, path, body, _ = ApiServerStub.requests_log[-1]
        assert (method, path) == (
            "POST", "/api/v1/namespaces/ns/pods/p1/eviction")
        parsed = json.loads(body)
        assert parsed["kind"] == "Eviction"
        assert parsed["metadata"] == {"name": "p1", "namespace": "ns"}

    def test_deletes(self, server):
        c = self.client(server)
        c.delete_pod("ns", "p1")
        c.delete_node("n1")
        paths = [(m, p) for m, p, _, _ in ApiServerStub.requests_log]
        assert ("DELETE", "/api/v1/namespaces/ns/pods/p1") in paths
        assert ("DELETE", "/api/v1/nodes/n1") in paths

    def test_watch_streams_events(self, server):
        c = self.client(server)
        events = list(c.watch_pods(timeout_seconds=5))
        assert [e["type"] for e in events] == ["ADDED", "MODIFIED"]

    def test_watch_nodes_hits_node_endpoint_with_cursor(self, server):
        c = self.client(server)
        list(c.watch_nodes(timeout_seconds=5, resource_version="42"))
        method, path, _, _ = ApiServerStub.requests_log[-1]
        assert method == "GET"
        assert path.startswith("/api/v1/nodes?watch=1")
        assert "resourceVersion=42" in path
        assert "allowWatchBookmarks=true" in path

    def test_raw_lists_return_collection_metadata(self, server):
        """The informer resumes its watch from the LIST response's
        collection resourceVersion — list_*_raw must expose it."""
        ApiServerStub.nodes = {"metadata": {"resourceVersion": "77"},
                               "items": [{"metadata": {"name": "n1"}}]}
        c = self.client(server)
        raw = c.list_nodes_raw()
        assert raw["metadata"]["resourceVersion"] == "77"
        assert raw["items"][0]["metadata"]["name"] == "n1"
        assert c.list_pods_raw()["items"] == c.list_pods()

    def test_dry_run_suppresses_mutations(self, server):
        c = RestKubeClient(base_url=server, token="tok", ca_cert=False,
                           dry_run=True)
        c.patch_node("n1", {"spec": {"unschedulable": True}})
        c.delete_node("n1")
        mutations = [(m, p) for m, p, _, _ in ApiServerStub.requests_log
                     if m != "GET"]
        assert mutations == []

    def test_dry_run_suppresses_lease_writes(self, server):
        # ADVICE r1 (medium): a --dry-run --leader-elect process must not
        # write the real Lease and steal leadership from production.
        c = RestKubeClient(base_url=server, token="tok", ca_cert=False,
                           dry_run=True)
        c.put_lease("kube-system", "tpu-autoscaler",
                    {"metadata": {"name": "tpu-autoscaler"}})
        c.put_lease("kube-system", "tpu-autoscaler",
                    {"metadata": {"name": "tpu-autoscaler",
                                  "resourceVersion": "5"}})
        writes = [(m, p) for m, p, _, _ in ApiServerStub.requests_log
                  if m in ("POST", "PUT")]
        assert writes == []


class FlakyApiStub(http.server.BaseHTTPRequestHandler):
    """Per-(method, path) scripted failures: pops a status code from the
    script before succeeding — the flaky-apiserver harness (VERDICT r4
    item 7, mirroring test_gcp_auth's actuator retry coverage)."""

    script: dict = {}          # (method, path) -> [status, status, ...]
    hits: list = []
    lease: dict = {}

    def _pop_failure(self, method):
        key = (method, self.path.split("?")[0])
        FlakyApiStub.hits.append(key)
        codes = FlakyApiStub.script.get(key)
        return codes.pop(0) if codes else None

    def _send_json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _handle(self, method, ok_body=None):
        code = self._pop_failure(method)
        if code is not None:
            self._send_json({"kind": "Status", "code": code}, code)
            return
        self._send_json(ok_body if ok_body is not None else {})

    def do_GET(self):  # noqa: N802
        if "/leases/" in self.path:
            self._handle("GET", FlakyApiStub.lease)
        else:
            self._handle("GET", {"items": []})

    def do_PATCH(self):  # noqa: N802
        self._handle("PATCH")

    def do_POST(self):  # noqa: N802
        self._handle("POST")

    def do_PUT(self):  # noqa: N802
        self._handle("PUT")

    def do_DELETE(self):  # noqa: N802
        self._handle("DELETE")

    def log_message(self, *args):
        pass


@pytest.fixture()
def flaky_server():
    FlakyApiStub.script = {}
    FlakyApiStub.hits = []
    FlakyApiStub.lease = {}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FlakyApiStub)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


class _MetricSink:
    def __init__(self):
        self.counts = {}

    def inc(self, name, by=1.0):
        self.counts[name] = self.counts.get(name, 0) + by


class TestKubeClientRetries:
    """Mutate verbs + the lease path survive a flooded apiserver
    (429/5xx) with bounded backoff — the coverage gcp.py's REST layer
    got in r4, now on the k8s side."""

    def client(self, base, metrics=None):
        c = RestKubeClient(base_url=base, token="tok", ca_cert=False,
                           sleep=lambda s: None)
        if metrics is not None:
            c.set_metrics(metrics)
        return c

    def test_patch_retries_429_then_succeeds(self, flaky_server):
        sink = _MetricSink()
        FlakyApiStub.script[("PATCH", "/api/v1/nodes/n1")] = [429, 503]
        c = self.client(flaky_server, sink)
        c.patch_node("n1", {"spec": {"unschedulable": True}})  # no raise
        assert FlakyApiStub.hits.count(("PATCH", "/api/v1/nodes/n1")) == 3
        assert sink.counts["kube_retries"] == 2

    def test_eviction_retries_500(self, flaky_server):
        FlakyApiStub.script[
            ("POST", "/api/v1/namespaces/ns/pods/p1/eviction")] = [500]
        self.client(flaky_server).evict_pod("ns", "p1")

    def test_retries_exhausted_raises(self, flaky_server):
        import requests

        FlakyApiStub.script[("PATCH", "/api/v1/nodes/n1")] = [503] * 10
        with pytest.raises(requests.exceptions.HTTPError):
            self.client(flaky_server).patch_node("n1", {})
        # Bounded: max_attempts requests, not 10.
        assert FlakyApiStub.hits.count(
            ("PATCH", "/api/v1/nodes/n1")) == RestKubeClient.max_attempts

    def test_delete_404_is_success(self, flaky_server):
        FlakyApiStub.script[("DELETE", "/api/v1/nodes/gone")] = [404]
        self.client(flaky_server).delete_node("gone")  # no raise

    def test_conflict_not_retried(self, flaky_server):
        import requests

        FlakyApiStub.script[(
            "PUT",
            "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases/"
            "tpu-autoscaler")] = [409]
        c = self.client(flaky_server)
        with pytest.raises(requests.exceptions.HTTPError):
            c.put_lease("kube-system", "tpu-autoscaler",
                        {"metadata": {"name": "tpu-autoscaler",
                                      "resourceVersion": "5"}})
        key = ("PUT", "/apis/coordination.k8s.io/v1/namespaces/"
                      "kube-system/leases/tpu-autoscaler")
        assert FlakyApiStub.hits.count(key) == 1  # conflict is terminal

    def test_create_409_with_our_holder_is_acquired(self, flaky_server):
        """A retried lease-create POST whose FIRST attempt committed
        answers 409 on the retry; re-reading and finding holder == us
        must count as acquired, not a lost election (ADVICE r5 #3)."""
        lease_base = ("/apis/coordination.k8s.io/v1/namespaces/"
                      "kube-system/leases")
        FlakyApiStub.script[("POST", lease_base)] = [409]
        FlakyApiStub.lease = {
            "metadata": {"name": "tpu-autoscaler",
                         "resourceVersion": "1"},
            "spec": {"holderIdentity": "me"}}
        c = self.client(flaky_server)
        c.put_lease("kube-system", "tpu-autoscaler", {
            "metadata": {"name": "tpu-autoscaler"},
            "spec": {"holderIdentity": "me"}})  # no raise: we hold it

    def test_create_409_with_other_holder_still_conflicts(self,
                                                          flaky_server):
        import requests

        lease_base = ("/apis/coordination.k8s.io/v1/namespaces/"
                      "kube-system/leases")
        FlakyApiStub.script[("POST", lease_base)] = [409]
        FlakyApiStub.lease = {
            "metadata": {"name": "tpu-autoscaler",
                         "resourceVersion": "1"},
            "spec": {"holderIdentity": "somebody-else"}}
        c = self.client(flaky_server)
        with pytest.raises(requests.exceptions.HTTPError):
            c.put_lease("kube-system", "tpu-autoscaler", {
                "metadata": {"name": "tpu-autoscaler"},
                "spec": {"holderIdentity": "me"}})

    def test_leader_renewal_survives_flaky_apiserver(self, flaky_server):
        """The incumbent leader renews through a 429 on the lease READ
        and a 503 on the WRITE — no leadership flap."""
        from tpu_autoscaler.k8s.leader import LeaseLock

        c = self.client(flaky_server)
        lock = LeaseLock(c, identity="me", lease_seconds=15.0)
        lease_path = ("/apis/coordination.k8s.io/v1/namespaces/"
                      "kube-system/leases/tpu-autoscaler")
        FlakyApiStub.lease = {
            "metadata": {"name": "tpu-autoscaler", "resourceVersion": "7"},
            "spec": {"holderIdentity": "me",
                     "renewTime": "2026-07-30T00:00:10.000000Z"},
        }
        FlakyApiStub.script[("GET", lease_path)] = [429]
        FlakyApiStub.script[("PUT", lease_path)] = [503]
        # now just after the recorded renewTime: we are the holder.
        import datetime

        now = datetime.datetime(
            2026, 7, 30, 0, 0, 12,
            tzinfo=datetime.timezone.utc).timestamp()
        assert lock.try_acquire(now) is True
        assert FlakyApiStub.hits.count(("GET", lease_path)) == 2
        assert FlakyApiStub.hits.count(("PUT", lease_path)) == 2

    def test_eviction_429_is_terminal_pdb_verdict(self, flaky_server):
        """The Eviction API answers 429 when a PodDisruptionBudget
        disallows the disruption — a policy verdict, surfaced
        immediately (no backoff stall of the reconcile pass)."""
        import requests

        FlakyApiStub.script[
            ("POST", "/api/v1/namespaces/ns/pods/p1/eviction")] = [429] * 5
        c = self.client(flaky_server)
        with pytest.raises(requests.exceptions.HTTPError):
            c.evict_pod("ns", "p1")
        assert FlakyApiStub.hits.count(
            ("POST", "/api/v1/namespaces/ns/pods/p1/eviction")) == 1

    def test_eviction_404_is_success(self, flaky_server):
        FlakyApiStub.script[
            ("POST", "/api/v1/namespaces/ns/pods/gone/eviction")] = [404]
        self.client(flaky_server).evict_pod("ns", "gone")  # no raise

    def test_lease_budget_stays_under_ttl(self, flaky_server):
        """The lease path's retry budget is its own (2 attempts, tight
        caps): a persistently-429 apiserver exhausts it after 2 tries
        instead of 4, keeping worst-case renewal well under the TTL."""
        import requests

        lease_path = ("/apis/coordination.k8s.io/v1/namespaces/"
                      "kube-system/leases/tpu-autoscaler")
        FlakyApiStub.script[("GET", lease_path)] = [429] * 10
        c = self.client(flaky_server)
        with pytest.raises(requests.exceptions.HTTPError):
            c.get_lease("kube-system", "tpu-autoscaler")
        assert FlakyApiStub.hits.count(("GET", lease_path)) == \
            RestKubeClient.LEASE_ATTEMPTS
