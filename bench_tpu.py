"""Bounded real-TPU benchmark harness (SURVEY §7; VERDICT r1 item 2).

Measures, on the single real TPU chip behind this image's ``axon`` relay:

- flagship-model training step time + tokens/s + estimated MFU
  (``tpu_autoscaler.workloads.model``, bf16, lax.scan blocks);
- Pallas fused flash-attention vs reference einsum attention, forward
  and forward+backward wall time (``tpu_autoscaler.workloads.attention``).

The axon relay is known to hang on backend init for minutes-to-forever,
so the harness is structured to be UNABLE to hang the caller:

- this parent process never imports jax;
- backend init is probed in a throwaway subprocess with a hard timeout;
- each measurement runs in its own subprocess with a hard timeout;
- the result file is written either way — real numbers, or an explicit
  ``{"skipped": <reason>}`` record per phase — and the process exits 0
  so driver pipelines never wedge on it.

Usage:
    python bench_tpu.py                 # probe + measure on the TPU
    python bench_tpu.py --cpu-smoke     # same harness on 1 virtual CPU
                                        # device (validates the plumbing)

Output: one JSON line on stdout; full record in BENCH_TPU.json
(or --out).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
DEFAULT_OUT = os.path.join(REPO, "BENCH_TPU.json")

# Peak dense bf16 FLOP/s per chip, by device_kind substring (public
# Cloud TPU spec sheet numbers). Used only for the MFU estimate.
_PEAK_FLOPS = (
    ("v6", 918e12),      # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e reports as "TPU v5 lite"
    ("v5e", 197e12),
    ("v4", 275e12),
)


def _peak_flops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


# --------------------------------------------------------------------------
# Subprocess plumbing (parent side; no jax here)
# --------------------------------------------------------------------------


def _cpu_env() -> dict[str, str]:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO  # drop sitecustomize (.axon_site)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("JAX_PLATFORM_NAME", None)
    return env


def _tpu_env() -> dict[str, str]:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "axon")
    return env


def _run_bounded(argv: list[str], env: dict[str, str],
                 timeout_s: float) -> dict:
    """Run argv; return {ok, rc|timeout, json|tail, seconds}."""
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable] + argv, env=env, cwd=REPO,
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"ok": False, "seconds": round(time.monotonic() - t0, 1),
                "skipped": f"timeout after {timeout_s:.0f}s"}
    seconds = round(time.monotonic() - t0, 1)
    if proc.returncode != 0:
        return {"ok": False, "seconds": seconds,
                "skipped": f"rc={proc.returncode}",
                "stderr_tail": proc.stderr[-1000:]}
    # Last stdout line is the impl's JSON payload.
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        payload = json.loads(line)
    except ValueError:
        return {"ok": False, "seconds": seconds,
                "skipped": "no JSON on impl stdout",
                "stdout_tail": proc.stdout[-500:]}
    payload.update({"ok": True, "seconds": seconds})
    return payload


# --------------------------------------------------------------------------
# Impl side (runs in the bounded subprocess; jax allowed here)
# --------------------------------------------------------------------------


def _impl_probe() -> None:
    import jax

    d = jax.devices()[0]
    print(json.dumps({"platform": d.platform,
                      "device_kind": d.device_kind,
                      "n_devices": len(jax.devices())}))


def _sync(x) -> None:
    """Force completion via a real device->host fetch of a tiny slice.

    Through this image's axon relay, block_until_ready returns at
    dispatch time (round-1 capture showed a physically impossible 102%
    MFU); a transfer cannot complete before the computation it depends
    on has."""
    import jax

    jax.device_get(x[(0,) * (x.ndim - 1) + (slice(0, 1),)])


def _scanned(op, q, k, v, n_apps: int):
    """One jitted program applying ``op`` n_apps times with a serial
    dependency (output feeds back as q), so a single dispatch amortizes
    the host->relay->device round trip (~6 ms — measured larger than the
    ops themselves, compressing every per-call speedup toward 1x)."""
    import jax

    @jax.jit
    def many(q, k, v):
        def body(c, _):
            return op(c, k, v).astype(c.dtype), ()
        out, _ = jax.lax.scan(body, q, None, length=n_apps)
        return out

    _sync(many(q, k, v))  # compile
    t0 = time.perf_counter()
    _sync(many(q, k, v))
    return (time.perf_counter() - t0) / n_apps


def _impl_step(small: bool) -> None:
    import jax
    import jax.numpy as jnp

    from tpu_autoscaler.workloads.model import (
        ModelConfig,
        make_mesh,
        make_sharded_train_step,
    )

    if small:
        cfg = ModelConfig(seq_len=64, d_model=64, n_layers=2, n_heads=2,
                          d_ff=128)
        batch_size, iters = 2, 3
    else:
        # attention defaults to "auto" -> the Pallas flash kernel on TPU
        # (1.4x step time vs einsum, and einsum OOMs HBM at this batch).
        cfg = ModelConfig(vocab=32768, d_model=1024, n_layers=8,
                          n_heads=16, d_ff=4096, seq_len=1024)
        batch_size, iters = 16, 10

    dev = jax.devices()[0]
    mesh = make_mesh([dev])
    init_fn, step_fn = make_sharded_train_step(mesh, cfg)
    params, opt_state = init_fn(jax.random.PRNGKey(0))
    batch = jax.random.randint(jax.random.PRNGKey(1),
                               (batch_size, cfg.seq_len + 1), 0, cfg.vocab,
                               dtype=jnp.int32)
    # Warmup (compile) then timed steps; _sync-style device_get forces
    # real completion (see _sync).
    for _ in range(2):
        params, opt_state, loss = step_fn(params, opt_state, batch)
    float(jax.device_get(loss))
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step_fn(params, opt_state, batch)
    float(jax.device_get(loss))
    step_s = (time.perf_counter() - t0) / iters

    n_params = sum(int(x.size) for x in jax.tree.leaves(params))
    tokens = batch_size * cfg.seq_len
    # 6ND matmul flops (fwd+bwd) + attention score/context flops
    # (4*b*s^2*d per layer fwd, 3x for bwd, halved for the causal mask —
    # the kernel only computes the live triangle).
    flops = (6.0 * n_params * tokens
             + 6.0 * cfg.n_layers * batch_size
             * cfg.seq_len ** 2 * cfg.d_model)
    peak = _peak_flops(dev.device_kind)
    mfu = flops / (step_s * peak) if peak else None
    print(json.dumps({
        "device_kind": dev.device_kind,
        "attention": cfg.resolved_for_mesh(mesh).resolved_attention(),
        "batch_size": batch_size,
        "n_params": n_params,
        "step_seconds": round(step_s, 5),
        "tokens_per_second": round(tokens / step_s, 1),
        "flops_per_step": flops,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "loss": float(loss),
    }))


def _impl_step_large(small: bool) -> None:
    """Training-step MFU at representative scale (VERDICT r2 item 1):
    a ~0.67B-param config — d_model 1536 (12 heads x head_dim 128, the
    MXU-native lane width), 20 layers, seq 2048 — with remat + chunked
    CE so optimizer state + activations fit single-chip HBM, measured
    over a small flash-attention tile sweep (the 512/1024 default was
    never tuned for head_dim 128 at this length)."""
    import jax
    import jax.numpy as jnp

    from tpu_autoscaler.workloads.model import (
        ModelConfig,
        make_mesh,
        make_sharded_train_step,
    )

    if small:
        base = dict(seq_len=64, d_model=64, n_layers=2, n_heads=2,
                    d_ff=128, remat=True, ce_chunk=32)
        batch_size, iters = 2, 2
        tiles = [(512, 1024), (64, 64)]
    else:
        base = dict(vocab=32768, d_model=1536, n_layers=20, n_heads=12,
                    d_ff=6144, seq_len=2048, remat=True, ce_chunk=256)
        batch_size, iters = 8, 6
        tiles = [(512, 1024), (512, 2048), (1024, 1024)]

    dev = jax.devices()[0]
    mesh = make_mesh([dev])
    batch = None
    best: dict | None = None
    sweep: dict = {}
    n_params = None
    for bq, bk in tiles:
        cfg = ModelConfig(attn_block_q=bq, attn_block_k=bk, **base)
        init_fn, step_fn = make_sharded_train_step(mesh, cfg)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        if n_params is None:
            n_params = sum(int(x.size) for x in jax.tree.leaves(params))
        if batch is None:
            batch = jax.random.randint(
                jax.random.PRNGKey(1), (batch_size, cfg.seq_len + 1), 0,
                cfg.vocab, dtype=jnp.int32)
        for _ in range(2):
            params, opt_state, loss = step_fn(params, opt_state, batch)
        float(jax.device_get(loss))
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step_fn(params, opt_state, batch)
        float(jax.device_get(loss))
        step_s = (time.perf_counter() - t0) / iters
        sweep[f"bq{bq}_bk{bk}"] = round(step_s, 5)
        if best is None or step_s < best["step_seconds"]:
            best = {"attn_block_q": bq, "attn_block_k": bk,
                    "step_seconds": step_s, "loss": float(loss)}
        del params, opt_state

    cfg = ModelConfig(**base)
    tokens = batch_size * cfg.seq_len
    # 6ND matmul flops (fwd+bwd) + attention score/context flops
    # (causal-halved, same convention as _impl_step); remat recomputes
    # the block forward, but MFU conventionally counts the model's
    # algorithmic flops, not the recompute (hardware does more work
    # than the numerator — the honest direction).
    flops = (6.0 * n_params * tokens
             + 6.0 * cfg.n_layers * batch_size
             * cfg.seq_len ** 2 * cfg.d_model)
    peak = _peak_flops(dev.device_kind)
    step_s = best["step_seconds"]
    mfu = flops / (step_s * peak) if peak else None
    print(json.dumps({
        "device_kind": dev.device_kind,
        "attention": cfg.resolved_for_mesh(mesh).resolved_attention(),
        "batch_size": batch_size,
        "n_params": n_params,
        "remat": True,
        "tile_sweep_step_seconds": sweep,
        "attn_block_q": best["attn_block_q"],
        "attn_block_k": best["attn_block_k"],
        "step_seconds": round(step_s, 5),
        "tokens_per_second": round(tokens / step_s, 1),
        "flops_per_step": flops,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "loss": best["loss"],
    }))


def _impl_attn(small: bool) -> None:
    import jax
    import jax.numpy as jnp

    from tpu_autoscaler.workloads.attention import (
        flash_attention,
        reference_attention,
    )

    on_cpu = jax.devices()[0].platform == "cpu"
    if small:
        b, h, s, d, n_apps = 1, 2, 128, 32, 2
        dtype = jnp.float32
    else:
        b, h, s, d, n_apps = 4, 8, 2048, 128, 20
        dtype = jnp.bfloat16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), dtype) for kk in ks)

    def flash(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=on_cpu)

    def ref(q, k, v):
        return reference_attention(q, k, v, causal=True)

    def grad_op(op):
        # All three grads, folded into the carry so none is dead code —
        # argnums=(0,) would let XLA eliminate the whole dk/dv kernel.
        g = jax.grad(
            lambda q, k, v: op(q, k, v).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))

        def combined(c, k, v):
            dq, dk, dv = g(c, k, v)
            return dq + dk + dv
        return combined

    fwd_flash = _scanned(flash, q, k, v, n_apps)
    fwd_ref = _scanned(ref, q, k, v, n_apps)
    bwd_flash = _scanned(grad_op(flash), q, k, v, n_apps)
    bwd_ref = _scanned(grad_op(ref), q, k, v, n_apps)
    print(json.dumps({
        "shape": [b, h, s, d],
        "dtype": str(dtype.__name__ if hasattr(dtype, "__name__")
                     else dtype),
        "interpret_mode": on_cpu,
        "apps_per_dispatch": n_apps,
        "fwd_pallas_seconds": round(fwd_flash, 6),
        "fwd_einsum_seconds": round(fwd_ref, 6),
        "fwd_speedup": round(fwd_ref / fwd_flash, 3),
        "fwdbwd_pallas_seconds": round(bwd_flash, 6),
        "fwdbwd_einsum_seconds": round(bwd_ref, 6),
        "fwdbwd_speedup": round(bwd_ref / bwd_flash, 3),
    }))


# --------------------------------------------------------------------------
# Harness
# --------------------------------------------------------------------------


def _impl_longctx(small: bool) -> None:
    """Long-context evidence: the flash kernel at sequence lengths where
    einsum attention cannot exist (scores alone exceed HBM), plus a
    remat'd train step at 8k tokens."""
    import jax
    import jax.numpy as jnp

    from tpu_autoscaler.workloads.attention import flash_attention

    on_cpu = jax.devices()[0].platform == "cpu"
    if small:
        b, h, s, d, n_apps = 1, 2, 256, 32, 2
        dtype = jnp.float32
    else:
        # At [2, 8, 16384, 128] the einsum path's f32 scores would be
        # 2*8*16384^2 * 4B = 16 GiB > 15.75 GiB usable HBM by themselves.
        b, h, s, d, n_apps = 2, 8, 16384, 128, 5
        dtype = jnp.bfloat16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), dtype) for kk in ks)

    attn_s = _scanned(
        lambda c, k, v: flash_attention(c, k, v, causal=True,
                                        interpret=on_cpu),
        q, k, v, n_apps)
    # Causal attention flops: 4*b*h*s^2*d (QK^T + PV), halved by the mask.
    attn_flops = 2.0 * b * h * s * s * d

    rec = {
        "attn_shape": [b, h, s, d],
        "attn_seconds_per_app": round(attn_s, 6),
        "attn_tflops": round(attn_flops / attn_s / 1e12, 1),
        "einsum_feasible": bool(small),
    }

    if not small:
        from tpu_autoscaler.workloads.model import (
            ModelConfig,
            make_mesh,
            make_sharded_train_step,
        )

        cfg = ModelConfig(vocab=32768, d_model=1024, n_layers=4,
                          n_heads=8, d_ff=4096, seq_len=8192, remat=True)
        mesh = make_mesh([jax.devices()[0]])
        init_fn, step_fn = make_sharded_train_step(mesh, cfg)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        batch = jax.random.randint(jax.random.PRNGKey(1),
                                   (2, cfg.seq_len + 1), 0, cfg.vocab,
                                   dtype=jnp.int32)
        for _ in range(2):
            params, opt_state, loss = step_fn(params, opt_state, batch)
        float(jax.device_get(loss))
        t0 = time.perf_counter()
        for _ in range(5):
            params, opt_state, loss = step_fn(params, opt_state, batch)
        float(jax.device_get(loss))
        step_s = (time.perf_counter() - t0) / 5
        tokens = 2 * cfg.seq_len
        rec.update({
            "train_seq_len": cfg.seq_len,
            "train_remat": True,
            "train_step_seconds": round(step_s, 5),
            "train_tokens_per_second": round(tokens / step_s, 1),
        })
    print(json.dumps(rec))


def _impl_decode(small: bool) -> None:
    """KV-cache inference throughput (workloads/decode.py): one jitted
    generate() whose lax.scan amortizes every decode step into a single
    dispatch (same rationale as _scanned), measured for an MHA cache and
    a GQA 8:1 cache — decode is HBM-bandwidth-bound on the cache reads,
    so the grouped layout's 8x smaller cache should show up directly."""
    import jax
    import jax.numpy as jnp

    from tpu_autoscaler.workloads.decode import generate
    from tpu_autoscaler.workloads.model import ModelConfig, init_params

    if small:
        base = dict(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                    seq_len=16)
        batch, prompt_len, steps = 2, 4, 8
        kv_variants = {"mha": None, "gqa": 2}
    else:
        base = dict(vocab=32768, d_model=1024, n_layers=8, n_heads=16,
                    d_ff=4096, seq_len=1024)
        batch, prompt_len, steps = 8, 128, 256
        kv_variants = {"mha": None, "gqa": 2}

    from tpu_autoscaler.workloads.decode import prefill

    rec: dict = {"batch": batch, "prompt_len": prompt_len, "steps": steps}
    for tag, n_kv in kv_variants.items():
        cfg = ModelConfig(n_kv_heads=n_kv, **base)
        params = init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, prompt_len), 0, cfg.vocab,
                                    dtype=jnp.int32)

        # generate() = prefill + decode scan in one dispatch; timing a
        # prefill-only program separately isolates decode, which is the
        # cache-bandwidth-bound phase this benchmark is about.
        pf = jax.jit(lambda p, pr: prefill(p, pr, cfg,
                                           prompt_len + steps)[0])
        fn = jax.jit(lambda p, pr: generate(p, pr, cfg, steps))
        _sync(pf(params, prompt))  # compile
        _sync(fn(params, prompt))
        # Average several timed iterations: decode time is the
        # DIFFERENCE of two measured programs, so single-shot timing
        # noise can drive it negative — average, and mark the record
        # not-ok instead of clamping to an absurd tokens/s.
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            _sync(pf(params, prompt))
        pf_dt = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            _sync(fn(params, prompt))
        gen_dt = (time.perf_counter() - t0) / reps
        decode_dt = gen_dt - pf_dt
        ok = decode_dt > 0
        rec[tag] = {
            "kv_heads": cfg.kv_heads,
            "ok": ok,
            "prefill_seconds": round(pf_dt, 5),
            "decode_seconds": round(decode_dt, 5),
        }
        if ok:
            rec[tag].update({
                "decode_tokens_per_second": round(
                    batch * steps / decode_dt, 1),
                "ms_per_step": round(decode_dt / steps * 1e3, 3),
            })
    if rec.get("mha", {}).get("ok") and rec.get("gqa", {}).get("ok"):
        rec["gqa_speedup"] = round(
            rec["mha"]["decode_seconds"] / rec["gqa"]["decode_seconds"], 3)

    # Fused flash_decode kernel vs the einsum cached-attention path, on
    # the GQA config, across a batch sweep (decode is bandwidth-bound:
    # larger batches amortize the per-step weight read, so the kernel's
    # single-pass cache read should show most at the high end).
    cfg_gqa = ModelConfig(n_kv_heads=2, **base)
    params = init_params(jax.random.PRNGKey(0), cfg_gqa)
    sweep: dict = {}
    for b2 in dict.fromkeys((batch, 4 * batch)):
        prompt2 = jax.random.randint(jax.random.PRNGKey(2),
                                     (b2, prompt_len), 0,
                                     cfg_gqa.vocab, dtype=jnp.int32)
        entry: dict = {}
        for impl in ("einsum", "pallas"):
            cfg2 = ModelConfig(n_kv_heads=2, attention=impl, **base)
            pf = jax.jit(lambda p, pr, c=cfg2: prefill(
                p, pr, c, prompt_len + steps)[0])
            fn = jax.jit(lambda p, pr, c=cfg2: generate(
                p, pr, c, steps))
            _sync(pf(params, prompt2))
            _sync(fn(params, prompt2))
            reps = 3
            t0 = time.perf_counter()
            for _ in range(reps):
                _sync(pf(params, prompt2))
            pf_dt = (time.perf_counter() - t0) / reps
            t0 = time.perf_counter()
            for _ in range(reps):
                _sync(fn(params, prompt2))
            decode_dt = (time.perf_counter() - t0) / reps - pf_dt
            ok = decode_dt > 0
            entry[impl] = {"ok": ok,
                           "decode_seconds": round(decode_dt, 5)}
            if ok:
                entry[impl]["decode_tokens_per_second"] = round(
                    b2 * steps / decode_dt, 1)
        if entry["einsum"].get("ok") and entry["pallas"].get("ok"):
            entry["fused_speedup"] = round(
                entry["einsum"]["decode_seconds"]
                / entry["pallas"]["decode_seconds"], 3)
        sweep[f"batch{b2}"] = entry
    rec["fused_vs_einsum"] = sweep
    print(json.dumps(rec))


def _impl_serve(small: bool) -> None:
    """Continuous-batching throughput (workloads/serving.py): mixed
    prompt lengths through the slot engine — admit/evict + chunked
    prefill — reporting decoded tokens/s, vs a naive serial per-request
    generate() of the same workload (what a fixed-batch server without
    slot reuse would do for mixed lengths)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_autoscaler.workloads.decode import generate
    from tpu_autoscaler.workloads.model import ModelConfig, init_params
    from tpu_autoscaler.workloads.serving import (
        ContinuousBatcher,
        Request,
    )

    if small:
        cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                          d_ff=64, seq_len=64, dtype=jnp.float32)
        lens = (5, 17, 9)
        new_tokens, slots, max_len, chunk = 4, 2, 64, 8
    else:
        cfg = ModelConfig(vocab=32768, d_model=1024, n_layers=8,
                          n_heads=16, n_kv_heads=2, d_ff=4096,
                          seq_len=1024)
        lens = (64, 384, 896, 128, 640, 256, 512, 96)
        new_tokens, slots, max_len, chunk = 128, 4, 1024, 128

    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab, (n,)).astype(np.int32)
               for n in lens]

    # One engine instance: its compiled decode/prefill programs live on
    # the instance, so pass 1 pays the compiles and pass 2 (timed) is
    # steady-state throughput.
    eng = ContinuousBatcher(params, cfg, slots=slots, max_len=max_len,
                            chunk=chunk)
    for p in prompts:
        eng.submit(Request(prompt=p, max_new_tokens=new_tokens))
    eng.run()
    reqs = [Request(prompt=p, max_new_tokens=new_tokens)
            for p in prompts]
    ticks_before = eng.ticks
    t0 = time.perf_counter()
    for r in reqs:
        eng.submit(r)
    eng.run()
    eng_dt = time.perf_counter() - t0
    timed_ticks = eng.ticks - ticks_before
    decoded = sum(len(r.generated) for r in reqs)

    # Serial per-request baseline: one jitted generate per distinct
    # padded length at batch 1 (prompts padded to chunk multiples to
    # bound compiled shapes the same way the engine does), warmed, then
    # timed — the no-slot-reuse, no-batching server this engine beats.
    pad = [int(np.ceil(n / chunk) * chunk) for n in lens]
    fns = {}
    for plen in dict.fromkeys(pad):
        fns[plen] = jax.jit(
            lambda p, pr, n=plen: generate(
                p, pr, cfg, new_tokens, max_len=n + new_tokens))
        _sync(fns[plen](params, jnp.zeros((1, plen), jnp.int32)))
    t0 = time.perf_counter()
    for p, plen in zip(prompts, pad):
        pr = np.zeros((1, plen), np.int32)
        pr[0, :len(p)] = p
        _sync(fns[plen](params, jnp.asarray(pr)))
    serial_dt = time.perf_counter() - t0

    # Paged engine at the SAME HBM budget (VERDICT r4 item 3): the pool
    # holds exactly the linear cache's slots*max_len token-slots, but
    # sequences only occupy ceil(len/block) blocks — so the same HBM
    # serves MORE concurrent sequences at mixed lengths, and the deeper
    # decode batch lifts tokens/s per byte of cache.
    from tpu_autoscaler.workloads.paged import PagedBatcher

    block_size = 8 if small else 16
    paged_slots = slots * 4
    paged = PagedBatcher(
        params, cfg, slots=paged_slots, max_len=max_len,
        block_size=block_size, num_blocks=slots * max_len // block_size,
        chunk=chunk, prefill_lanes=min(4, paged_slots))
    workload = prompts * 2                       # deeper mixed burst
    for p in workload:                           # warm the programs
        paged.submit(Request(prompt=p, max_new_tokens=new_tokens))
    paged.run()
    preqs = [Request(prompt=p, max_new_tokens=new_tokens)
             for p in workload]
    t0 = time.perf_counter()
    for r in preqs:
        paged.submit(r)
    peak_live = 0
    while not paged.idle:
        paged.tick()
        peak_live = max(peak_live, sum(
            1 for s in paged._slots if s.request is not None))
    paged_dt = time.perf_counter() - t0
    paged_decoded = sum(len(r.generated) for r in preqs)

    print(json.dumps({
        "requests": len(lens),
        "prompt_lens": list(lens),
        "new_tokens_per_request": new_tokens,
        "slots": slots, "chunk": chunk,
        "engine_seconds": round(eng_dt, 4),
        "engine_decode_tokens_per_second": round(decoded / eng_dt, 1),
        "serial_seconds": round(serial_dt, 4),
        "serial_decode_tokens_per_second": round(decoded / serial_dt, 1),
        "speedup_vs_serial": round(serial_dt / eng_dt, 3),
        "ticks": timed_ticks,
        "paged": {
            "hbm_token_slots": slots * max_len,   # == linear budget
            "block_size": block_size,
            "requests": len(workload),
            "peak_concurrent": peak_live,
            "concurrency_vs_linear": round(peak_live / slots, 2),
            "preemptions": paged.preemptions,
            "engine_seconds": round(paged_dt, 4),
            "decode_tokens_per_second": round(
                paged_decoded / paged_dt, 1),
        },
    }))


def _make_bigram_shard(path: str, vocab: int, n_tokens: int):
    """THE structured training shard, shared by the converge and spec
    phases: 90% deterministic bigram (t -> (31t + 17) mod V), 10%
    uniform noise — a learnable next-token rule whose cross-entropy
    floor sits well below ln(V).  Returns the token array."""
    import numpy as np

    from tpu_autoscaler.dataio import write_token_file

    rng = np.random.default_rng(7)
    toks = np.empty(n_tokens, np.uint32)
    toks[0] = 1
    a, c = 31, 17
    noise = rng.random(n_tokens) < 0.1
    rand = rng.integers(0, vocab, n_tokens, dtype=np.uint32)
    for i in range(1, n_tokens):
        toks[i] = rand[i] if noise[i] else (a * int(toks[i - 1]) + c) % vocab
    write_token_file(path, toks)
    return toks


def _impl_spec(small: bool) -> None:
    """Speculative-decoding economics on TRAINED models: fit a target
    and a cheaper draft (fewer layers) on the same structured bigram
    shard (the converge phase's data), then serve the target greedily
    with and without the draft.  The hardware-independent win is
    target_pass_ratio = target forward passes / tokens (1.0 for plain
    decode; 1/(mean accepted + 1) speculative) — decode is bound by the
    target's weight/cache reads, so wall-clock at scale tracks it."""
    import shutil
    import tempfile

    import numpy as np

    if small:
        vocab, n_tokens, steps_train = 256, 120_000, 50
        t_layers, d_layers, d_model, seq = 2, 1, 64, 32
        gen_steps, k = 32, 4
    else:
        vocab, n_tokens, steps_train = 4096, 2_000_000, 600
        t_layers, d_layers, d_model, seq = 6, 1, 512, 256
        gen_steps, k = 128, 4

    workdir = tempfile.mkdtemp(prefix="bench-spec-")
    shard = os.path.join(workdir, "shard.bin")
    toks = _make_bigram_shard(shard, vocab, n_tokens)

    try:
        def train(layers, ckpt):
            cmd = [sys.executable, "-m", "tpu_autoscaler.workloads.train",
                   "--steps", str(steps_train), "--d-model", str(d_model),
                   "--n-layers", str(layers), "--seq-len", str(seq),
                   "--batch", "4", "--vocab", str(vocab),
                   "--data-file", shard, "--checkpoint-dir", ckpt,
                   "--checkpoint-every", str(steps_train),
                   "--lr", "3e-3", "--grad-clip", "1.0",
                   "--annotations-file", os.path.join(workdir, "none")]
            proc = subprocess.run(cmd, cwd=REPO, capture_output=True,
                                  text=True, timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(f"trainer failed: {proc.stderr[-500:]}")

        t_ckpt = os.path.join(workdir, "target")
        d_ckpt = os.path.join(workdir, "draft")
        train(t_layers, t_ckpt)
        train(d_layers, d_ckpt)

        import jax
        import jax.numpy as jnp

        from tpu_autoscaler.workloads.checkpoint import restore_checkpoint
        from tpu_autoscaler.workloads.decode import (
            generate,
            speculative_generate,
        )
        from tpu_autoscaler.workloads.model import ModelConfig

        t_cfg = ModelConfig(vocab=vocab, d_model=d_model, n_layers=t_layers,
                            seq_len=seq)
        d_cfg = ModelConfig(vocab=vocab, d_model=d_model, n_layers=d_layers,
                            seq_len=seq)
        t_params = restore_checkpoint(t_ckpt, steps_train, None)["params"]
        d_params = restore_checkpoint(d_ckpt, steps_train, None)["params"]
        prompt = jnp.asarray(toks[:16].astype(np.int32))[None]

        fn = jax.jit(lambda p, pr: generate(p, pr, t_cfg, gen_steps))
        _sync(fn(t_params, prompt))
        t0 = time.perf_counter()
        _sync(fn(t_params, prompt))
        plain_dt = time.perf_counter() - t0
        # Token-parity oracle runs EAGERLY: whole-program jit fuses
        # differently and can flip a bf16 near-tie argmax, which would
        # falsely read as a speculative mismatch.
        plain = generate(t_params, prompt, t_cfg, gen_steps)

        spec, stats = speculative_generate(
            t_params, d_params, prompt, t_cfg, gen_steps, draft_cfg=d_cfg,
            k=k)  # warm
        t0 = time.perf_counter()
        spec, stats = speculative_generate(
            t_params, d_params, prompt, t_cfg, gen_steps, draft_cfg=d_cfg,
            k=k)
        spec_dt = time.perf_counter() - t0
        tokens_match = bool(np.array_equal(np.asarray(plain),
                                           np.asarray(spec)))

        # Distribution-preserving sampled verification (VERDICT r4 item
        # 4): acceptance falls as temperature flattens p and q apart —
        # report the curve; exactness itself is pinned by
        # tests/test_decode.py::TestSpeculativeSampling's marginal tests.
        from tpu_autoscaler.workloads.decode import (
            speculative_sample_generate,
        )

        accept_vs_temp = {}
        for temp in (0.3, 0.7, 1.0):
            _, st = speculative_sample_generate(
                t_params, d_params, prompt, t_cfg, gen_steps,
                key=jax.random.PRNGKey(0), temperature=temp,
                draft_cfg=d_cfg, k=k)
            accept_vs_temp[str(temp)] = round(st["accept_rate"], 3)

        # In-ENGINE speculative serving (spec_serving.py): the trained
        # draft assists the paged continuous-batching engine over mixed
        # requests; report the per-slot acceptance + target passes per
        # token vs the plain paged engine at the same traffic.
        import numpy as _np

        from tpu_autoscaler.workloads.paged import (
            PagedBatcher,
            Request as _Req,
        )
        from tpu_autoscaler.workloads.spec_serving import (
            SpeculativePagedBatcher,
        )

        eng_kw = dict(slots=2 if small else 4,
                      max_len=min(128, 2 * seq), block_size=16,
                      chunk=16)
        spec_new = 12 if small else min(64, gen_steps)
        n_req = 3 if small else 6
        prompts_srv = [_np.asarray(toks[o:o + 12].astype(np.int32))
                       for o in range(0, 40 * n_req, 40)]

        def drive(eng):
            rs = [_Req(prompt=p, max_new_tokens=spec_new)
                  for p in prompts_srv]
            for r in rs:
                eng.submit(r)
            t0 = time.perf_counter()
            eng.run()
            return rs, time.perf_counter() - t0

        spec_eng = SpeculativePagedBatcher(
            t_params, t_cfg, d_params, d_cfg, k=k, **eng_kw)
        srs, spec_dt = drive(spec_eng)
        plain_eng = PagedBatcher(t_params, t_cfg, **eng_kw)
        prs, plain_dt = drive(plain_eng)
        serving = {
            "requests": n_req,
            "new_tokens_per_request": spec_new,
            "engine_accept_rate": round(spec_eng.accept_rate, 3),
            "engine_target_pass_ratio": round(
                spec_eng.target_pass_ratio, 3),
            # Single cold drive each: compile-inclusive, informational
            # only — the hardware-independent win is the pass ratio.
            "spec_seconds_cold": round(spec_dt, 4),
            "plain_seconds_cold": round(plain_dt, 4),
            "greedy_outputs_match_plain": bool(all(
                list(a.generated) == list(b.generated)
                for a, b in zip(srs, prs))),
        }

        print(json.dumps({
            "target_layers": t_layers, "draft_layers": d_layers,
            "train_steps": steps_train, "gen_steps": gen_steps, "k": k,
            "accept_rate": round(stats["accept_rate"], 3),
            "rounds": stats["rounds"],
            # Target forward passes per generated token (prefill excluded):
            # plain decode = 1.0; the speculative win at decode-bound scale.
            "target_pass_ratio": round(stats["rounds"] / gen_steps, 3),
            "tokens_match_plain_greedy": tokens_match,
            "sampled_accept_rate_vs_temperature": accept_vs_temp,
            "speculative_serving": serving,
            "plain_seconds": round(plain_dt, 4),
            "speculative_seconds": round(spec_dt, 4),
            "note": ("speculative wall-clock includes per-round host "
                     "scheduling; at small scale the jitted plain scan "
                     "wins on seconds — target_pass_ratio is the "
                     "scale-relevant number"),
        }))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _impl_converge(small: bool) -> None:
    """Real-training evidence (VERDICT r2 item 2; data path upgraded in
    r5): drive the trainer CLI on the committed byte-BPE corpus shard
    (data/corpus.bin — repo docs+source at vocab 8192), SIGKILL it
    mid-run, re-launch the identical command, and verify (a) it resumes
    from the checkpoint, (b) the data stream replays exactly (pure
    function of seed/step — dataio.row_offset), and (c) the loss curve
    over the full run decreases clearly below the uniform ln(V) floor.
    The record reports epochs consumed: the corpus is ~200k tokens, so
    the large config revisits it — honest small-corpus training, and
    the reason the gate is a ln(V)-relative decrease, not a
    held-out-perplexity claim.

    No jax in this phase: the trainer subprocesses own the device; this
    orchestrator watches their logs."""
    import re
    import shutil
    import signal
    import tempfile

    # The REAL data path (VERDICT r4 item 8): the committed byte-BPE
    # corpus shard (data/corpus.bin — repo docs+source encoded at vocab
    # 8192 by data/tokenizer.json), not a synthetic bigram stream, so
    # the loss curve reflects learning at realistic token statistics.
    vocab = 8192
    if small:
        # Calibrated on this corpus: 150 steps at lr 3e-3 reaches ~8.2
        # from 9.4 — enough to clear both gates below on CPU in ~1 min.
        steps, kill_at, ckpt_every = 150, 75, 25
        arch = ["--d-model", "64", "--n-layers", "2", "--seq-len", "64",
                "--batch", "8", "--vocab", str(vocab)]
    else:
        steps, kill_at, ckpt_every = 1000, 500, 100
        arch = ["--d-model", "512", "--n-layers", "6", "--seq-len", "256",
                "--batch", "16", "--vocab", str(vocab)]

    workdir = tempfile.mkdtemp(prefix="bench-converge-")
    shard = os.path.join(REPO, "data", "corpus.bin")
    if not os.path.exists(shard):
        # Regenerate from the committed corpus + tokenizer (slow path;
        # the shard is normally committed).
        from tpu_autoscaler.workloads.tokenizer import build_shard

        shard = os.path.join(workdir, "corpus.bin")
        build_shard(os.path.join(REPO, "data", "corpus.txt"),
                    os.path.join(REPO, "data", "tokenizer.json"),
                    shard, vocab)

    ckpt_dir = os.path.join(workdir, "ckpt")
    cmd = [sys.executable, "-m", "tpu_autoscaler.workloads.train",
           "--steps", str(steps), *arch,
           "--data-file", shard, "--checkpoint-dir", ckpt_dir,
           "--checkpoint-every", str(ckpt_every),
           "--lr", "3e-3", "--warmup-steps", str(max(steps // 20, 2)),
           "--lr-schedule", "cosine", "--grad-clip", "1.0",
           "--annotations-file", os.path.join(workdir, "nonexistent")]

    step_re = re.compile(
        r"step (\d+) loss ([0-9.naif]+) \((\d+) tok/s\)")
    resume_re = re.compile(r"resumed from checkpoint step (\d+)")

    def run(kill_at_step=None):
        """Run the trainer, returning (losses {step: loss}, resumed_at,
        killed_bool, tok_s list)."""
        proc = subprocess.Popen(cmd, cwd=REPO, text=True,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.PIPE)
        losses, resumed, toks = {}, None, []
        try:
            for line in proc.stderr:
                m = resume_re.search(line)
                if m:
                    resumed = int(m.group(1))
                m = step_re.search(line)
                if m:
                    losses[int(m.group(1))] = float(m.group(2))
                    toks.append(float(m.group(3)))
                    if kill_at_step and int(m.group(1)) >= kill_at_step:
                        proc.send_signal(signal.SIGKILL)
                        proc.wait()
                        return losses, resumed, True, toks
            proc.wait()
        finally:
            if proc.poll() is None:
                proc.kill()
        return losses, resumed, False, toks

    try:
        losses1, _, killed, _ = run(kill_at_step=kill_at)
        losses2, resumed_at, _, toks2 = run()
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    # The two runs' logs compose into one curve across the kill: run 1
    # covers the start, run 2 (post-resume) the rest.
    import math

    curve = {**losses1, **losses2}
    steps_sorted = sorted(curve)
    first = curve[steps_sorted[0]] if steps_sorted else float("nan")
    last = curve[steps_sorted[-1]] if steps_sorted else float("nan")
    ln_v = math.log(vocab)
    post = sorted(losses2)
    batch_i = int(arch[arch.index("--batch") + 1])
    seq_i = int(arch[arch.index("--seq-len") + 1])
    try:
        shard_tokens = os.path.getsize(shard) // 4
    except OSError:
        shard_tokens = 0
    rec = {
        "steps": steps,
        "data": "data/corpus.bin (byte-BPE, repo docs+source)",
        "vocab": vocab,
        "corpus_tokens": shard_tokens,
        "epochs_consumed": round(
            steps * batch_i * seq_i / max(1, shard_tokens), 2),
        "killed_mid_run": killed,
        "kill_after_step": kill_at,
        "resumed_from_step": resumed_at,
        "train_tokens_per_second_median": (
            sorted(toks2)[len(toks2) // 2] if toks2 else None),
        "loss_first": first,
        "loss_last": last,
        "loss_uniform_floor": round(ln_v, 4),
        "curve": {str(s): curve[s]
                  for s in steps_sorted[:: max(1, len(steps_sorted)
                                               // 12)]},
        # Learned: the end of the curve sits well under the uniform
        # entropy AND under where it started.
        "decreasing": bool(steps_sorted and last < first - 0.5
                           and last < ln_v - 0.5),
        # The relaunched run continued the curve (first post-resume
        # loss far below a from-scratch start), not restarted.
        "resume_continued_curve": bool(
            resumed_at is not None and post
            and losses2[post[0]] < ln_v - 0.2),
    }
    print(json.dumps(rec))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="run the same harness on 1 virtual CPU device")
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    ap.add_argument("--measure-timeout", type=float, default=900.0)
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--impl",
                    choices=["probe", "step", "step_large", "attn",
                             "longctx", "decode", "serve", "spec",
                             "converge"],
                    help=argparse.SUPPRESS)  # internal subprocess entry
    ap.add_argument("--small", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.impl:
        {"probe": _impl_probe,
         "step": lambda: _impl_step(args.small),
         "step_large": lambda: _impl_step_large(args.small),
         "attn": lambda: _impl_attn(args.small),
         "longctx": lambda: _impl_longctx(args.small),
         "decode": lambda: _impl_decode(args.small),
         "serve": lambda: _impl_serve(args.small),
         "spec": lambda: _impl_spec(args.small),
         "converge": lambda: _impl_converge(args.small)}[args.impl]()
        return 0

    env = _cpu_env() if args.cpu_smoke else _tpu_env()
    small = args.cpu_smoke
    record: dict = {
        "mode": "cpu-smoke" if args.cpu_smoke else "tpu",
        "probe_timeout_s": args.probe_timeout,
        "measure_timeout_s": args.measure_timeout,
    }

    me = os.path.join(REPO, "bench_tpu.py")
    record["probe"] = _run_bounded([me, "--impl", "probe"], env,
                                   args.probe_timeout)
    if record["probe"].get("ok"):
        extra = ["--small"] if small else []
        record["train_step"] = _run_bounded(
            [me, "--impl", "step"] + extra, env, args.measure_timeout)
        record["train_step_large"] = _run_bounded(
            [me, "--impl", "step_large"] + extra, env,
            args.measure_timeout)
        record["attention"] = _run_bounded(
            [me, "--impl", "attn"] + extra, env, args.measure_timeout)
        record["long_context"] = _run_bounded(
            [me, "--impl", "longctx"] + extra, env, args.measure_timeout)
        record["decode"] = _run_bounded(
            [me, "--impl", "decode"] + extra, env, args.measure_timeout)
        record["serving"] = _run_bounded(
            [me, "--impl", "serve"] + extra, env, args.measure_timeout)
        record["speculative"] = _run_bounded(
            [me, "--impl", "spec"] + extra, env, args.measure_timeout)
        record["convergence"] = _run_bounded(
            [me, "--impl", "converge"] + extra, env, args.measure_timeout)
    else:
        reason = record["probe"].get("skipped", "probe failed")
        for phase in ("train_step", "train_step_large", "attention",
                      "long_context", "decode", "serving",
                      "speculative", "convergence"):
            record[phase] = {"ok": False,
                             "skipped": f"backend probe: {reason}"}
        # The relay can be down for a whole round: don't clobber real
        # hardware numbers from a previous run with skip records —
        # carry them forward, marked stale.
        try:
            with open(args.out) as f:
                prev = json.load(f)
        except (OSError, ValueError):
            prev = None
        if prev and prev.get("probe", {}).get("ok"):
            record["previous_results"] = prev
        elif prev and prev.get("previous_results"):
            # prev was itself a skip record carrying older real numbers:
            # keep carrying them, don't drop on the 2nd down round.
            record["previous_results"] = prev["previous_results"]

    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
