// Native fit/pack kernels for the decision engine.
//
// The reference is pure Python (SURVEY.md §3: zero native components), so
// this is beyond-parity: the two numeric hot spots of the planner — batch
// shape scoring and first-fit-decreasing CPU packing — as a small C++
// library with a C ABI, loaded via ctypes (tpu_autoscaler/native.py).
// The Python implementations in engine/fitter.py remain the reference
// semantics; tests assert bit-identical decisions between the two.
//
// Build: make -C native   (or it is built on demand by native.py)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <numeric>
#include <vector>

extern "C" {

// Score G gangs against S shapes.
// gangs:   G rows of (total_chips, per_pod_chips, n_pods)
// shapes:  S rows of (chips, chips_per_host, hosts)
// best:    G entries out — index of the feasible shape with minimal
//          stranded chips (ties: first/smallest in given order), -1 none.
// stranded:G entries out — stranded chips for the chosen shape.
void fitpack_best_shapes(const double* gangs, int64_t n_gangs,
                         const double* shapes, int64_t n_shapes,
                         int32_t* best, double* stranded) {
  for (int64_t g = 0; g < n_gangs; ++g) {
    const double total = gangs[g * 3 + 0];
    const double per_pod = gangs[g * 3 + 1];
    const double pods = gangs[g * 3 + 2];
    int32_t arg = -1;
    double best_cost = 0;
    for (int64_t s = 0; s < n_shapes; ++s) {
      const double chips = shapes[s * 3 + 0];
      const double cph = shapes[s * 3 + 1];
      const double hosts = shapes[s * 3 + 2];
      if (chips < total || cph < per_pod) continue;
      if (per_pod > 0) {
        const double slots =
            hosts * std::floor(cph / std::max(per_pod, 1.0));
        if (slots < pods) continue;
      }
      const double cost = chips - total;
      if (arg < 0 || cost < best_cost) {
        arg = static_cast<int32_t>(s);
        best_cost = cost;
      }
    }
    best[g] = arg;
    stranded[g] = arg < 0 ? -1.0 : best_cost;
  }
}

// First-fit-decreasing packing of pods into existing free capacity and
// new units of one machine shape (2 resource axes: cpu, mem).
// pods:  N rows (cpu, mem) — NOT pre-sorted; FFD order is applied inside.
// free:  F rows (cpu, mem) — mutated as pods are placed.
// unit:  (cpu, mem) capacity of one new node.
// placed_unit: N entries out — -2 placed on existing node, >=0 index of
//              new unit, -1 unplaceable.
// Returns the number of new units opened.
int32_t fitpack_pack_ffd(const double* pods, int64_t n_pods, double* free,
                         int64_t n_free, double unit_cpu, double unit_mem,
                         int32_t* placed_unit) {
  std::vector<int64_t> order(n_pods);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) {
                     if (pods[a * 2] != pods[b * 2])
                       return pods[a * 2] > pods[b * 2];
                     return pods[a * 2 + 1] > pods[b * 2 + 1];
                   });
  std::vector<double> units;  // (cpu, mem) remaining per new unit
  for (int64_t k = 0; k < n_pods; ++k) {
    const int64_t p = order[k];
    const double cpu = pods[p * 2], mem = pods[p * 2 + 1];
    bool done = false;
    for (int64_t f = 0; f < n_free && !done; ++f) {
      if (free[f * 2] >= cpu && free[f * 2 + 1] >= mem) {
        free[f * 2] -= cpu;
        free[f * 2 + 1] -= mem;
        placed_unit[p] = -2;
        done = true;
      }
    }
    for (size_t u = 0; u < units.size() / 2 && !done; ++u) {
      if (units[u * 2] >= cpu && units[u * 2 + 1] >= mem) {
        units[u * 2] -= cpu;
        units[u * 2 + 1] -= mem;
        placed_unit[p] = static_cast<int32_t>(u);
        done = true;
      }
    }
    if (!done) {
      if (unit_cpu >= cpu && unit_mem >= mem) {
        placed_unit[p] = static_cast<int32_t>(units.size() / 2);
        units.push_back(unit_cpu - cpu);
        units.push_back(unit_mem - mem);
      } else {
        placed_unit[p] = -1;
      }
    }
  }
  return static_cast<int32_t>(units.size() / 2);
}

// Multi-shape, K-axis, admission-aware first-fit packing (ISSUE 6):
// the wide entry point behind engine/fitter.py::pack_cpu_pods_multi at
// fleet scale.  The Python caller pre-sorts pods into FFD order (the
// exact `sorted` call the reference path uses, so ordering semantics
// can never drift) and pre-computes the template×node admission mask
// (selectors + taints stay Python-authoritative); this kernel does the
// numeric inner loop — the O(pods × nodes) hot spot.
//
// pods:   N rows × K axes, ALREADY in first-fit-decreasing order.
// tmpl:   N entries — admission-template id per pod (0..T-1).
// free:   F rows × K axes — existing nodes' free capacity, mutated.
// admit:  T×F bytes — nonzero iff template t may land on free-node f.
// shapes: S rows × K axes — capacity of one new node per shape, tried
//         in the caller's order (smallest machine first).
// placed: N entries out — -2 existing node (free row untracked),
//         >=0 index of opened unit, -1 unplaceable.
// unit_shape: out, shape index per opened unit (capacity N).
// Returns the number of new units opened.
int32_t fitpack_pack_ffd_multi(const double* pods, int64_t n_pods,
                               int64_t k, const int32_t* tmpl,
                               double* free_caps, int64_t n_free,
                               const uint8_t* admit, int64_t n_tmpl,
                               const double* shapes, int64_t n_shapes,
                               int32_t* placed, int32_t* unit_shape) {
  (void)n_tmpl;
  auto fits = [k](const double* need, const double* cap) {
    for (int64_t a = 0; a < k; ++a) {
      if (need[a] > 0 && need[a] > cap[a]) return false;
    }
    return true;
  };
  std::vector<double> units;  // remaining capacity per opened unit
  int32_t n_units = 0;
  for (int64_t p = 0; p < n_pods; ++p) {
    const double* need = pods + p * k;
    const uint8_t* row = admit + static_cast<int64_t>(tmpl[p]) * n_free;
    bool done = false;
    for (int64_t f = 0; f < n_free && !done; ++f) {
      double* cap = free_caps + f * k;
      if (row[f] && fits(need, cap)) {
        for (int64_t a = 0; a < k; ++a) cap[a] -= need[a];
        placed[p] = -2;
        done = true;
      }
    }
    // Previously opened units, in creation order (the Python path
    // checks no admission here either: a planned node's labels are
    // unknown pre-creation).
    for (int32_t u = 0; u < n_units && !done; ++u) {
      double* cap = units.data() + static_cast<int64_t>(u) * k;
      if (fits(need, cap)) {
        for (int64_t a = 0; a < k; ++a) cap[a] -= need[a];
        placed[p] = u;
        done = true;
      }
    }
    if (!done) {
      for (int64_t s = 0; s < n_shapes; ++s) {
        const double* cap = shapes + s * k;
        if (fits(need, cap)) {
          placed[p] = n_units;
          unit_shape[n_units] = static_cast<int32_t>(s);
          units.resize(units.size() + k);
          double* rem = units.data() + static_cast<int64_t>(n_units) * k;
          for (int64_t a = 0; a < k; ++a) rem[a] = cap[a] - need[a];
          ++n_units;
          done = true;
          break;
        }
      }
    }
    if (!done) placed[p] = -1;
  }
  return n_units;
}

}  // extern "C"
