// Native token-shard loader for the in-tree trainer.
//
// The reference autoscaler has no data path at all (SURVEY §3: it is an
// infrastructure controller); this is runtime infrastructure for the
// in-tree workload: a memory-mapped reader over a binary file of uint32
// tokens that serves [batch, seq+1] next-token-prediction windows.
//
// Design for the TPU host:
// - mmap, not read(): the OS page cache backs every shard once per host
//   no matter how many loader instances exist, and first-touch faulting
//   overlaps with compute.
// - Stateless sampling: row r of step s starts at
//   splitmix64(seed, step, row) % (n_tokens - window + 1) — a pure
//   function of (seed, step), so checkpoint resume replays the exact
//   stream with no loader state to persist (crash-only, like the
//   controller), and a Python fallback can be bit-identical.
// - Double-buffered prefetch: a background thread fills the next step's
//   host buffer while JAX consumes the current one, hiding page-fault
//   and memcpy latency behind the device step.
//
// C ABI (ctypes-friendly): tl_open / tl_next / tl_prefetch / tl_n_tokens
// / tl_close.  All return codes: 0 ok, negative errno-style failures.

#include <cstdint>
#include <cstring>
#include <condition_variable>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

inline uint64_t row_offset(uint64_t seed, uint64_t step, uint64_t row,
                           uint64_t span) {
  uint64_t h = splitmix64(seed ^ splitmix64(step ^ splitmix64(row)));
  return h % span;
}

struct Loader {
  const uint32_t* tokens = nullptr;
  size_t map_bytes = 0;
  int64_t n_tokens = 0;
  int64_t window = 0;  // seq + 1
  int64_t batch = 0;
  uint64_t seed = 0;

  // Prefetch state: one buffered step ahead.
  std::vector<uint32_t> buf;
  int64_t buf_step = -1;
  bool filling = false;
  std::mutex mu;
  std::condition_variable cv;
  std::thread worker;
  bool stop = false;

  void fill(int64_t step, uint32_t* out) const {
    const uint64_t span =
        static_cast<uint64_t>(n_tokens - window + 1);
    for (int64_t r = 0; r < batch; ++r) {
      const uint64_t off = row_offset(seed, static_cast<uint64_t>(step),
                                      static_cast<uint64_t>(r), span);
      std::memcpy(out + r * window, tokens + off,
                  static_cast<size_t>(window) * sizeof(uint32_t));
    }
  }

  void worker_loop() {
    std::unique_lock<std::mutex> lock(mu);
    while (true) {
      cv.wait(lock, [&] { return stop || filling; });
      if (stop) return;
      const int64_t step = buf_step;
      lock.unlock();
      fill(step, buf.data());
      lock.lock();
      filling = false;
      cv.notify_all();
    }
  }
};

std::mutex g_mu;
std::map<int64_t, Loader*> g_loaders;
int64_t g_next_handle = 1;

Loader* get(int64_t handle) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_loaders.find(handle);
  return it == g_loaders.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

// Open a uint32 token shard.  Returns a positive handle, or a negative
// error: -1 open/stat failure, -2 too short for one window, -3 bad args.
int64_t tl_open(const char* path, int64_t window, int64_t batch,
                uint64_t seed) {
  if (window < 2 || batch < 1) return -3;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { ::close(fd); return -1; }
  const int64_t n = static_cast<int64_t>(st.st_size / sizeof(uint32_t));
  if (n < window) { ::close(fd); return -2; }
  void* map = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                   MAP_PRIVATE, fd, 0);
  ::close(fd);
  if (map == MAP_FAILED) return -1;
  auto* l = new Loader();
  l->tokens = static_cast<const uint32_t*>(map);
  l->map_bytes = static_cast<size_t>(st.st_size);
  l->n_tokens = n;
  l->window = window;
  l->batch = batch;
  l->seed = seed;
  l->buf.resize(static_cast<size_t>(batch * window));
  l->worker = std::thread([l] { l->worker_loop(); });
  std::lock_guard<std::mutex> lock(g_mu);
  const int64_t handle = g_next_handle++;
  g_loaders[handle] = l;
  return handle;
}

int64_t tl_n_tokens(int64_t handle) {
  Loader* l = get(handle);
  return l ? l->n_tokens : -1;
}

// Fill out[batch * window] with step's batch.  Uses the prefetched
// buffer when it matches, else fills synchronously.  Kicks nothing off
// itself — call tl_prefetch(step + 1) after.
int tl_next(int64_t handle, int64_t step, uint32_t* out) {
  Loader* l = get(handle);
  if (!l) return -1;
  std::unique_lock<std::mutex> lock(l->mu);
  l->cv.wait(lock, [&] { return !l->filling; });
  if (l->buf_step == step) {
    std::memcpy(out, l->buf.data(), l->buf.size() * sizeof(uint32_t));
    return 0;
  }
  lock.unlock();
  l->fill(step, out);
  return 0;
}

// Start filling the internal buffer for `step` in the background.
int tl_prefetch(int64_t handle, int64_t step) {
  Loader* l = get(handle);
  if (!l) return -1;
  std::lock_guard<std::mutex> lock(l->mu);
  if (l->filling || l->buf_step == step) return 0;
  l->buf_step = step;
  l->filling = true;
  l->cv.notify_all();
  return 0;
}

int tl_close(int64_t handle) {
  Loader* l = nullptr;
  {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_loaders.find(handle);
    if (it == g_loaders.end()) return -1;
    l = it->second;
    g_loaders.erase(it);
  }
  {
    std::lock_guard<std::mutex> lock(l->mu);
    l->stop = true;
    l->cv.notify_all();
  }
  l->worker.join();
  munmap(const_cast<uint32_t*>(l->tokens), l->map_bytes);
  delete l;
  return 0;
}

}  // extern "C"
