#!/usr/bin/env bash
# Full-suite (fast + slow tier) validation, split to keep each pytest
# invocation inside a bounded wall-clock on a 1-core box.  This is the
# pre-round / pre-release gate VERDICT r3 weak-item 7 asked to make
# enforceable: run it before declaring a build done.
#
#   ./scripts/full_suite.sh            # everything
#   ./scripts/full_suite.sh fast       # fast tier only (default addopts)
#
# Exits non-zero on the first failing split.
set -euo pipefail
cd "$(dirname "$0")/.."

# Static gate first: the invariant linter catches architectural
# regressions (planner purity, thread discipline, exception hygiene,
# jax purity, interprocedural races, lock order, blocking-under-lock,
# replay determinism, cost-algebra units) before any test burns
# wall-clock.  --full: this is the pre-release gate, so it must not
# inherit lint.sh's local changed-only default (ISSUE 15).
./scripts/lint.sh --full

# Units-of-measure gate (ISSUE 16): the TAU10xx dimension pass re-run
# with NO baseline — the cost algebra's unit discipline can never grow
# grandfathered entries, mirroring ci_gate.sh stage 3.
python -m tpu_autoscaler.analysis --units --no-baseline tpu_autoscaler/

# Race gate (ISSUE 4, extended ISSUE 15): static TAR5xx + TAL7xx
# passes, the deterministic-schedule concurrency tier (seeded
# interleavings of the real informer/executor/reconciler paths under a
# vector-clock happens-before checker), and the lock-order witness
# cross-check (witnessed acquisition edges must all be modeled by the
# static TAL7xx graph — docs/ANALYSIS.md).
./scripts/race.sh

# Observe-path tier: informer vs relist-baseline at 5k pods/600 nodes
# with 1% churn must hold the >= 5x speedup floor (ISSUE 2).  Also
# sub-second, so it runs before the test splits.
JAX_PLATFORMS=cpu python bench.py observe

# Mega-cluster observe tier (ISSUE 6): indexed informer reads
# (unschedulable select + incremental CapacityView) vs the
# snapshot-scan path at 100k pods / 10k nodes with 1% churn, explicit
# >= 20x floor; the result is recorded in BENCH_SCALE.json.
JAX_PLATFORMS=cpu python bench.py observe --pods 100000 --nodes 10000 --floor 20

# Large-batch fit tier (ISSUE 6): python vs batch-kernel (native, or
# the vectorized jaxfit fallback) shape decisions at 8192 gangs — zero
# decision mismatches, explicit >= 2x floor; recorded in
# BENCH_SCALE.json.
JAX_PLATFORMS=cpu python bench.py fit_batch --gangs 8192 --floor 2

# Actuation tier: pipelined executor (pooled dispatch + ONE batched
# LIST poll) vs the serial blocking baseline at 64 in-flight / 16 new
# provisions with 50 ms injected RTT must hold the >= 10x floor
# (ISSUE 3; ~4 s — the serial baseline honestly pays its 80 RTTs).
JAX_PLATFORMS=cpu python bench.py actuate

# Chaos corpus (ISSUE 7): 200 seeded generative scenarios (brownouts,
# watch storms, 410 floods, stockouts, preemptions, partial slice host
# failures, multislice jobsets) through the real control loop, every
# property invariant asserted per step, under a fixed wall-clock
# budget (docs/CHAOS.md).  The policy profile (ISSUE 8) re-runs the
# corpus with the PolicyEngine attached — mispredicted prewarms must
# never violate no-double-provision or no-stranded-chips.
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 480
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 300 --profile policy
# Serving profile (ISSUE 9): fuzz the metrics-adapter path — replica
# restarts mid-window, counter resets, stale/out-of-order snapshots —
# with the ServingScaler's advisory demand riding the same invariants;
# counter resets must never yield negative rates, per step.
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 300 --profile serving
# Alerts profile (ISSUE 10): the burn-rate alert gate — injected
# scale-up-latency regressions must fire the alert inside the driven
# phase and resolve after the fault window; quiet seeds must stay
# silent (zero false positives) — docs/CHAOS.md, OBSERVABILITY.md.
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 300 --profile alerts
# Repack profile (ISSUE 12): the repacker ON over on-demand gangs with
# spot slices arriving mid-run — migrations raced by spot reclamation,
# destination stockouts and mid-drain gang deletes; conservation and
# ICI integrity per step, never-net-negative-savings and the
# guard-capped abort cost at terminal (docs/REPACK.md, CHAOS.md).
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 400 --profile repack
# Router profile (ISSUE 18): the routed replay raced by replica death
# mid-request, affinity staleness (epoch bumps under the table),
# hedge storms and counter resets during hedges; no lost requests and
# no double completions at terminal (docs/SERVING.md "Request
# routing", CHAOS.md).
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 300 --profile router
# Sharded corpora (ISSUE 13, docs/SHARDING.md): mixed + repair re-run
# with the sharded planner attached (every pass exercises the
# fan-out/merge path); the invariant catalog must hold unchanged —
# sharded plans are byte-identical to serial by the merge contract.
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 480 --reconcile-shards 4
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 400 --profile repair --reconcile-shards 4
# Verified-columnar corpus (ISSUE 17, docs/PLANNER.md): the mixed
# corpus re-runs with verify_columnar_plans ON — the python planner
# shadows every columnar pass and any plan mismatch fails the seed.
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 480 --verify-columnar

# Policy replay tier (ISSUE 8): the recurring north-star trace must
# show prewarmed detect->running <= 0.25x the reactive baseline, and a
# regime-change (misprediction) trace must keep wasted chip-seconds
# under the configured budget; results merge into BENCH_POLICY.json
# (docs/POLICY.md).
JAX_PLATFORMS=cpu python bench.py policy

# Serving tier (ISSUE 9): the metrics adapter must fold a
# 10k-replica fleet's snapshots in <= 1 ms per reconcile pass and
# beat the naive every-replica scan >= 10x, and on the diurnal+spike
# millions-of-users replay through the real Controller the
# signal-driven path must beat pod-pending reactive tail SLO
# attainment; results merge into BENCH_SERVING.json (docs/SERVING.md
# "Autoscaler integration").
JAX_PLATFORMS=cpu python bench.py serving

# Serving-trace tier (ISSUE 14): request-level data-plane tracing —
# the replica serving step and the 10k-replica exemplar fold traced
# vs untraced within 2% + noise grace at 1% sampling with tail
# capture ON, and the end-to-end acceptance replay: every SLO-missing
# cohort tail-captured gap-free, incident-bundle exemplars resolving
# to real request traces, the tail attributed to scale-up lag with a
# working scaleup-* cross-link; results merge into
# BENCH_SERVING.json (docs/OBSERVABILITY.md "Request spans &
# exemplars").
JAX_PLATFORMS=cpu python bench.py serving-trace

# Router tier (ISSUE 18): amortized routing decision <= 5 us and
# score refresh <= 1 ms per pass at 10k replicas, then the 2.2M-user
# route_compare replay at equal provisions — the router must beat
# random dispatch >= 2x on tail-SLO miss rate AND >= 2x on
# per-replica KV-occupancy variance with zero lost requests; results
# merge into BENCH_SERVING.json (docs/SERVING.md "Request routing").
JAX_PLATFORMS=cpu python bench.py router

# Tracer-overhead tier: the observe + actuate benches re-run with the
# decision tracer attached must stay within 5% of untraced (ISSUE 5 —
# instrumentation can never silently eat the PR-2/PR-3 wins).
JAX_PLATFORMS=cpu python bench.py trace

# Obs tier (ISSUE 10): TSDB+alert marginal per-pass cost within
# max(5% of the traced-only observe pass, 0.5 ms absolute);
# 10k-series per-pass ingest + alert-evaluation cost under their ms
# gates; results merge into BENCH_OBS.json (docs/OBSERVABILITY.md
# "Overhead gates").
JAX_PLATFORMS=cpu python bench.py obs

# Cost tier (ISSUE 11): the attribution ledger's pass-close cost
# <= 0.5 ms at 10k replica units with 10% state churn, per-dirty-unit
# ingestion bounded, the conservation identity + rebuild oracle green,
# and the north-star overhead budget (12 ms) still green with the
# ledger ON; results merge into BENCH_COST.json (docs/COST.md).
JAX_PLATFORMS=cpu python bench.py cost

# Repack tier (ISSUE 12): the churn-heavy week-long replay — repack
# never worse than no-repack on steady-state chip utilization AND
# total $-proxy, every completed `repack` trace carrying its
# chip-seconds-saved attribution, conservation intact through every
# migration, north-star budget green with the repacker ON; results
# merge into BENCH_REPACK.json (docs/REPACK.md).
JAX_PLATFORMS=cpu python bench.py repack

# Sharded reconcile tier (ISSUE 13, docs/SHARDING.md): the 1M-pod
# observe tier, then full reconcile passes/sec sharded vs serial at
# the million-pod tier — >= 2x at 8 shards, byte-identical plans
# asserted in-bench, parse-memo/index-sizing audit, north-star
# budget green with sharding ON; results merge into BENCH_SHARD.json.
JAX_PLATFORMS=cpu python bench.py observe --pods 1000000 --nodes 100000 --floor 20
JAX_PLATFORMS=cpu python bench.py loop --pods 1000000 --nodes 100000

# Columnar planner tier (ISSUE 17, docs/PLANNER.md): the serial
# million-pod planning pass on the struct-of-arrays fast path vs the
# python oracle — >= 5x with byte-identical decisions (plan AND the
# claim scan); results merge into BENCH_SCALE.json.
JAX_PLATFORMS=cpu python bench.py plan_columnar --pods 1000000 --nodes 100000

# Profiler tier (ISSUE 20, docs/OBSERVABILITY.md "Control-plane
# profiling"): the phase-tree profiler ON vs OFF — overhead within
# 2% + noise grace at the 100k-pod loop tier and the 10k-replica
# serving-pass tier, self-time conservation asserted in-bench on
# every profiled pass; records BENCH_PROFILE.json.  Then the
# cross-tier ratio diff: every gated ratio in the re-recorded
# BENCH_*.json files must sit within 20% of the committed copy — a
# tier passing its own floor can't quietly give back another PR's
# headroom.
JAX_PLATFORMS=cpu python bench.py profile
python scripts/bench_diff.py

controller_ignores=(
  --ignore=tests/test_attention.py --ignore=tests/test_ring_attention.py
  --ignore=tests/test_sp.py --ignore=tests/test_pipeline.py
  --ignore=tests/test_moe.py --ignore=tests/test_decode.py
  --ignore=tests/test_workloads.py --ignore=tests/test_elastic.py
  --ignore=tests/test_distributed.py --ignore=tests/test_ulysses.py
  --ignore=tests/test_train_cli.py
)

run() { echo "== pytest $*"; python -m pytest -q "$@"; }

# Fast tier, split controller-side vs workload-side.  (Do NOT mix a
# directory arg with a file inside it: pytest dedups the overlap and
# silently drops the directory's collection.  test_train_cli is
# all-slow — running its empty fast tier would exit 5 under set -e —
# so it appears only in the slow splits.)
run tests/ "${controller_ignores[@]}"
run tests/test_attention.py tests/test_ring_attention.py \
    tests/test_ulysses.py tests/test_distributed.py tests/test_elastic.py
run tests/test_sp.py tests/test_pipeline.py tests/test_moe.py \
    tests/test_decode.py tests/test_workloads.py

if [[ "${1:-all}" == "fast" ]]; then exit 0; fi

# Slow tier only (-m slow: the fast splits above already ran these
# files' fast tests once — re-running them under -m "" would double
# the script's wall time).  Each invocation bundles at least one file
# with slow-marked tests, so pytest never exits 5 on empty collection.
run -m slow tests/test_attention.py tests/test_ring_attention.py \
    tests/test_ulysses.py
run -m slow tests/test_sp.py
run -m slow tests/test_moe.py
run -m slow tests/test_pipeline.py
run -m slow tests/test_decode.py tests/test_workloads.py
run -m slow tests/test_train_cli.py tests/test_distributed.py \
    tests/test_elastic.py
run -m "slow" tests/ "${controller_ignores[@]}"
echo "FULL SUITE GREEN"
