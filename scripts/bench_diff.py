#!/usr/bin/env python3
"""Diff fresh BENCH_*.json records against the committed copies.

The bench tiers each gate themselves (speedup floors, overhead
bounds), but a tier can pass its own gate while quietly giving back
most of the headroom a previous PR bought.  This script closes that
gap: after the tiers have re-recorded their BENCH_*.json files, it
compares every GATED RATIO (speedups, overhead ratios — the
self-normalizing numbers, not raw wall-clock timings, which vary by
host) against the copy committed at ``--base`` (default HEAD) and
fails CI when any of them regressed by more than ``--threshold``
(default 20%).

Direction is keyed off the metric name: ``*speedup*`` and plain
``*ratio*`` keys are higher-is-better; ``*overhead*`` and ``tail_*``
ratios are lower-is-better.  Keys under a ``gates`` mapping (and
``gate``/``floor``/``*_gate`` keys) are configuration, not
measurements, and are skipped.  Files absent from the base commit are
noted and skipped — a brand-new tier has nothing to regress against.

Usage:  python scripts/bench_diff.py [--threshold 0.20] [--base REF]
                                     [FILE ...]
Exit codes: 0 clean, 1 regression found, 2 unusable input.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
from typing import Any, Iterator

# Round archives (BENCH_r01.json…) are the driver's history, not tier
# records; BENCH_TPU.json depends on attached hardware and records
# skips on CPU-only hosts.  Neither is comparable across commits.
SKIP_FILES = {"BENCH_TPU.json"}

LOWER_IS_BETTER_MARKERS = ("overhead", "tail_ratio")


def _gated_ratio_direction(key: str) -> str | None:
    """'up' (higher is better), 'down', or None (not a gated ratio)."""
    k = key.lower()
    if any(marker in k for marker in LOWER_IS_BETTER_MARKERS):
        # overhead_ratio / overhead_factor / tail_ratio: a bigger
        # number means more time burned.
        if "ratio" in k or "factor" in k:
            return "down"
        return None
    if "speedup" in k or "ratio" in k or k == "vs_baseline":
        return "up"
    return None


def _numeric_leaves(obj: Any, path: tuple[str, ...] = ()
                    ) -> Iterator[tuple[tuple[str, ...], float]]:
    if isinstance(obj, dict):
        for key, value in obj.items():
            if key == "gates" or key in ("gate", "floor") \
                    or key.endswith("_gate"):
                continue
            yield from _numeric_leaves(value, path + (str(key),))
    elif isinstance(obj, bool):
        return
    elif isinstance(obj, (int, float)):
        yield path, float(obj)


def _committed(base: str, filename: str) -> dict | None:
    proc = subprocess.run(
        ["git", "show", f"{base}:{filename}"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if proc.returncode != 0:
        return None
    try:
        return json.loads(proc.stdout)
    except json.JSONDecodeError:
        return None


def diff_file(filename: str, base: str, threshold: float
              ) -> tuple[list[str], list[str]]:
    """(regressions, notes) for one bench record."""
    notes: list[str] = []
    regressions: list[str] = []
    with open(filename, encoding="utf-8") as f:
        fresh = json.load(f)
    old = _committed(base, os.path.basename(filename))
    if old is None:
        notes.append(f"{filename}: not in {base} (new tier) — skipped")
        return regressions, notes
    old_leaves = dict(_numeric_leaves(old))
    compared = 0
    for path, new_value in _numeric_leaves(fresh):
        direction = _gated_ratio_direction(path[-1])
        if direction is None or path not in old_leaves:
            continue
        old_value = old_leaves[path]
        if old_value == 0:
            continue
        compared += 1
        if direction == "up":
            change = (old_value - new_value) / abs(old_value)
        else:
            change = (new_value - old_value) / abs(old_value)
        dotted = ".".join(path)
        if change > threshold:
            regressions.append(
                f"{filename}: {dotted} regressed "
                f"{change:+.1%} ({old_value:g} -> {new_value:g}, "
                f"{'higher' if direction == 'up' else 'lower'}"
                f"-is-better, threshold {threshold:.0%})")
    notes.append(f"{filename}: {compared} gated ratios compared vs "
                 f"{base}")
    return regressions, notes


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_diff.py",
        description="fail CI when a fresh BENCH_*.json gave back >"
                    "threshold of any gated ratio vs the committed copy")
    ap.add_argument("files", nargs="*",
                    help="bench records to diff (default: BENCH_*.json "
                         "in the repo root, minus round archives and "
                         "hardware-dependent tiers)")
    ap.add_argument("--threshold", type=float, default=0.20)
    ap.add_argument("--base", default="HEAD")
    args = ap.parse_args(argv)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    files = args.files or sorted(
        f for f in glob.glob(os.path.join(root, "BENCH_*.json"))
        if os.path.basename(f) not in SKIP_FILES
        and not os.path.basename(f).startswith("BENCH_r"))
    if not files:
        print("bench_diff: no BENCH_*.json records found", file=sys.stderr)
        return 2

    all_regressions: list[str] = []
    for filename in files:
        try:
            regressions, notes = diff_file(filename, args.base,
                                           args.threshold)
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_diff: cannot read {filename}: {e}",
                  file=sys.stderr)
            return 2
        for note in notes:
            print(note, file=sys.stderr)
        all_regressions.extend(regressions)

    if all_regressions:
        for line in all_regressions:
            print(f"REGRESSION {line}")
        print(json.dumps({"error": "bench ratio regression",
                          "count": len(all_regressions),
                          "threshold": args.threshold}))
        return 1
    print(json.dumps({"info": "bench_diff", "files": len(files),
                      "threshold": args.threshold, "regressions": 0}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
