#!/usr/bin/env bash
# Race gate (docs/ANALYSIS.md, ISSUE 4): both layers of the race
# detector, cheapest first.
#
#   layer 1 — static: the interprocedural escape/lockset pass (TAR5xx)
#             over the whole package (sub-2s);
#   layer 2 — dynamic: the deterministic-schedule concurrency tier
#             (tests/test_sched.py + tests/test_races.py), which drives
#             the real informer/executor/reconciler code through seeded
#             interleavings under a vector-clock happens-before checker.
#
# Run standalone before touching anything threaded; full_suite.sh runs
# it too (after the lint gate).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== race layer 1: static TAR5xx (python -m tpu_autoscaler.analysis --races)"
python -m tpu_autoscaler.analysis --races tpu_autoscaler/

echo "== race layer 2: deterministic-schedule tier"
JAX_PLATFORMS=cpu python -m pytest -q tests/test_sched.py tests/test_races.py \
  -p no:cacheprovider

echo "RACE GATE GREEN"
