#!/usr/bin/env bash
# Race gate (docs/ANALYSIS.md, ISSUE 4): both layers of the race
# detector, cheapest first.
#
#   layer 1 — static: the interprocedural escape/lockset pass (TAR5xx)
#             and the lock-order pass (TAL7xx) over the whole package
#             (one shared-graph run, seconds);
#   layer 2 — dynamic: the deterministic-schedule concurrency tier
#             (tests/test_sched.py + tests/test_races.py), which drives
#             the real informer/executor/reconciler code through seeded
#             interleavings under a vector-clock happens-before checker,
#             plus the lock-order witness cross-check
#             (tests/test_lockwitness.py): actual acquisition orders
#             recorded at the concurrency seam must all be modeled by
#             the static TAL7xx graph — a witnessed-but-unmodeled edge
#             is a checker blind spot and fails here (ISSUE 15,
#             docs/ANALYSIS.md).
#
# Run standalone before touching anything threaded; full_suite.sh runs
# it too (after the lint gate).
#
# RACE_STATIC_COVERED=1 (set by ci_gate.sh only): skip layer 1 and the
# witness cross-check because the caller ALREADY ran both — ci_gate
# stage 1 runs every program pass over the whole package and stage 2
# runs test_lockwitness.py verbatim, so repeating them here would pay
# for the whole-program analysis a third time and the witness tier a
# second.  Standalone runs (and full_suite.sh) keep both.
set -euo pipefail
cd "$(dirname "$0")/.."

witness="tests/test_lockwitness.py"
if [ "${RACE_STATIC_COVERED:-0}" = 1 ]; then
  echo "== race layer 1: static pass covered by caller (skipped)"
  witness=""
else
  echo "== race layer 1: static TAR5xx + TAL7xx"
  # One invocation: --select only filters the REPORT — every program
  # pass runs regardless — so a second run would just repeat the whole
  # analysis for the other code family.
  python -m tpu_autoscaler.analysis --select TAR,TAL tpu_autoscaler/
fi

echo "== race layer 2: deterministic-schedule tier${witness:+ + witness cross-check}"
# shellcheck disable=SC2086  # $witness is deliberately word-split
JAX_PLATFORMS=cpu python -m pytest -q tests/test_sched.py tests/test_races.py \
  $witness -p no:cacheprovider

echo "RACE GATE GREEN"
