#!/usr/bin/env bash
# Fast static gate: the invariant linter (docs/ANALYSIS.md) plus mypy
# on the strict islands (mypy.ini) when mypy is installed.  Sub-second
# without mypy — run it before every commit; full_suite.sh runs it too.
#
#   ./scripts/lint.sh              # analyzer + mypy-if-present
#   ./scripts/lint.sh --no-mypy    # analyzer only
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== invariant linter (python -m tpu_autoscaler.analysis)"
python -m tpu_autoscaler.analysis tpu_autoscaler/

if [[ "${1:-}" != "--no-mypy" ]]; then
  if python -c "import mypy" >/dev/null 2>&1; then
    echo "== mypy (strict islands: engine/, k8s/objects.py)"
    python -m mypy --config-file mypy.ini \
      tpu_autoscaler/engine tpu_autoscaler/k8s/objects.py
  else
    echo "== mypy not installed; skipping (config: mypy.ini)"
  fi
fi

echo "LINT GREEN"
