#!/usr/bin/env bash
# Fast static gate: the invariant linter (docs/ANALYSIS.md) plus mypy
# on the strict islands (mypy.ini) when mypy is installed.  Sub-second
# without mypy — run it before every commit; full_suite.sh runs it too.
#
#   ./scripts/lint.sh              # analyzer (changed files only when
#                                  # run locally; full tree in CI) +
#                                  # mypy-if-present
#   ./scripts/lint.sh --full       # analyzer over the full tree
#   ./scripts/lint.sh --no-mypy    # analyzer only
#   ./scripts/lint.sh --mypy-only  # just the mypy stage (ci_gate.sh
#                                  # reuses this so the strict-island
#                                  # list lives in exactly one place)
#
# Local runs default to `--changed-only`: findings are reported only
# for git-changed/untracked files (the whole-program passes still see
# the full tree, so interprocedural results stay sound).  CI (CI=true
# in the environment, the GitHub Actions convention) and --full always
# gate the whole tree.
set -euo pipefail
cd "$(dirname "$0")/.."

# THE strict-island list (mirrored in mypy.ini's per-module sections).
MYPY_TARGETS=(
  tpu_autoscaler/engine
  tpu_autoscaler/k8s/objects.py
  tpu_autoscaler/k8s/columnar.py
  tpu_autoscaler/analysis
  tpu_autoscaler/actuators/executor.py
  tpu_autoscaler/cost
  tpu_autoscaler/obs/tsdb.py
  tpu_autoscaler/obs/alerts.py
  tpu_autoscaler/units.py
  tpu_autoscaler/repack
  tpu_autoscaler/serving/router.py
  tpu_autoscaler/serving/drain.py
  tpu_autoscaler/obs/profiler.py
)

run_mypy() {
  if python -c "import mypy" >/dev/null 2>&1; then
    echo "== mypy (strict islands: ${MYPY_TARGETS[*]})"
    # Explicit hard-fail: an installed-but-failing mypy must gate, not
    # merely report (ISSUE 4 satellite).
    if ! python -m mypy --config-file mypy.ini "${MYPY_TARGETS[@]}"; then
      echo "mypy FAILED on the strict islands" >&2
      return 1
    fi
  else
    echo "== mypy not installed; skipping (config: mypy.ini)"
  fi
}

# All flags combine (`--full --no-mypy`); an unrecognized flag is an
# error, NOT a silent fall-through to the narrower changed-only
# default — a typo'd `--fulll` must not scope a release gate down.
FULL=""
NO_MYPY=""
MYPY_ONLY=""
for arg in "$@"; do
  case "$arg" in
    --full)      FULL=1 ;;
    --no-mypy)   NO_MYPY=1 ;;
    --mypy-only) MYPY_ONLY=1 ;;
    *)
      echo "lint.sh: unknown argument: $arg" >&2
      exit 2
      ;;
  esac
done

if [[ -n "$MYPY_ONLY" ]]; then
  run_mypy
  exit $?
fi

SCOPE_FLAG="--changed-only"
if [[ "${CI:-}" == "true" || -n "$FULL" ]]; then
  SCOPE_FLAG=""
fi

echo "== invariant linter (python -m tpu_autoscaler.analysis ${SCOPE_FLAG:-<full>})"
# shellcheck disable=SC2086 — SCOPE_FLAG is deliberately word-split
python -m tpu_autoscaler.analysis $SCOPE_FLAG tpu_autoscaler/

if [[ -z "$NO_MYPY" ]]; then
  run_mypy
fi

echo "LINT GREEN"
