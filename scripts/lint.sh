#!/usr/bin/env bash
# Fast static gate: the invariant linter (docs/ANALYSIS.md) plus mypy
# on the strict islands (mypy.ini) when mypy is installed.  Sub-second
# without mypy — run it before every commit; full_suite.sh runs it too.
#
#   ./scripts/lint.sh              # analyzer + mypy-if-present
#   ./scripts/lint.sh --no-mypy    # analyzer only
#   ./scripts/lint.sh --mypy-only  # just the mypy stage (ci_gate.sh
#                                  # reuses this so the strict-island
#                                  # list lives in exactly one place)
set -euo pipefail
cd "$(dirname "$0")/.."

# THE strict-island list (mirrored in mypy.ini's per-module sections).
MYPY_TARGETS=(
  tpu_autoscaler/engine
  tpu_autoscaler/k8s/objects.py
  tpu_autoscaler/analysis
  tpu_autoscaler/actuators/executor.py
)

run_mypy() {
  if python -c "import mypy" >/dev/null 2>&1; then
    echo "== mypy (strict islands: ${MYPY_TARGETS[*]})"
    # Explicit hard-fail: an installed-but-failing mypy must gate, not
    # merely report (ISSUE 4 satellite).
    if ! python -m mypy --config-file mypy.ini "${MYPY_TARGETS[@]}"; then
      echo "mypy FAILED on the strict islands" >&2
      return 1
    fi
  else
    echo "== mypy not installed; skipping (config: mypy.ini)"
  fi
}

if [[ "${1:-}" == "--mypy-only" ]]; then
  run_mypy
  exit $?
fi

echo "== invariant linter (python -m tpu_autoscaler.analysis)"
python -m tpu_autoscaler.analysis tpu_autoscaler/

if [[ "${1:-}" != "--no-mypy" ]]; then
  run_mypy
fi

echo "LINT GREEN"
