#!/usr/bin/env bash
# CI-friendly static + concurrency gate (ISSUE 4 satellite): runs
# analysis → mypy → race tier in order, each stage with a DISTINCT exit
# code so a CI job can tell which stage failed from $? alone:
#
#   0  everything green
#   2  invariant analysis (all checkers incl. TAR5xx + TAO6xx
#      metric/doc drift, unused waivers, stale baseline parse errors)
#   3  mypy strict islands (only when mypy is importable)
#   4  deterministic-schedule race tier
#   5  tracer-overhead gate (bench.py trace: traced observe/actuate
#      within 5% of untraced — ISSUE 5)
#   6  mega-cluster scale tiers (bench.py observe --pods 100000
#      --nodes 10000 >= 20x indexed-vs-scan; fit_batch --gangs 8192
#      zero decision mismatches + >= 2x — ISSUE 6)
#   7  generative chaos corpus (python -m tpu_autoscaler.chaos
#      --seed-corpus: 200 seeds under a fixed wall-clock budget; every
#      property invariant must hold — ISSUE 7, docs/CHAOS.md — plus
#      the 200-seed `policy` profile corpus: PolicyEngine prewarms
#      under fire, mispredictions never violate no-double-provision /
#      no-stranded-chips — ISSUE 8)
#   8  policy replay tier (bench.py policy: recurring-trace prewarmed
#      tail latency <= 0.25x reactive, misprediction waste under
#      budget; BENCH_POLICY.json — ISSUE 8, docs/POLICY.md)
#   9  serving tier (bench.py serving: metrics-adapter fold <= 1 ms
#      per pass at 10k replicas, >= 10x over the naive scan, AND the
#      diurnal+spike millions-of-users replay where signal-driven
#      scaling must beat pod-pending reactive tail SLO attainment;
#      BENCH_SERVING.json — ISSUE 9, docs/SERVING.md)
#   10 obs tier (bench.py obs: TSDB+alert marginal per-pass cost
#      within max(5% of the traced-only observe pass, 0.5 ms),
#      10k-series ingest + alert-evaluation under their ms gates;
#      BENCH_OBS.json — ISSUE 10, docs/OBSERVABILITY.md)
#   11 cost tier (bench.py cost: attribution-ledger pass-close
#      <= 0.5 ms at 10k units / 10% churn, per-dirty-unit note
#      bounded, conservation + rebuild oracle green, north-star
#      budget green with the ledger ON; BENCH_COST.json — ISSUE 11,
#      docs/COST.md)
#   12 repack tier (bench.py repack: churn-heavy week-long replay,
#      repack NEVER WORSE than no-repack on steady-state utilization
#      and $-proxy, per-migration chip-seconds-saved attribution on
#      every completed trace, north-star budget green with the
#      repacker ON; BENCH_REPACK.json — ISSUE 12, docs/REPACK.md.
#      The 200-seed chaos `repack` corpus — migrations raced by spot
#      reclamation, destination stockouts and mid-drain gang deletes,
#      with the never-net-negative-savings + guard-capped-abort
#      invariants — runs in the chaos stage above, exit 7.)
#   14 serving-trace tier (bench.py serving-trace: data-plane
#      request tracing — replica step + 10k-replica exemplar fold
#      traced vs untraced within 2% + noise grace at 1% sampling
#      with tail capture ON, and the diurnal+spike acceptance
#      replay: full tail capture gap-free, exemplars resolving,
#      scale-up-lag attribution with a working cross-link;
#      BENCH_SERVING.json — ISSUE 14, docs/OBSERVABILITY.md)
#   13 sharded reconcile tier (ISSUE 13, docs/SHARDING.md):
#      bench.py observe at the 1M-pod/100k-node tier (>= 20x), then
#      bench.py loop — full reconcile passes/sec sharded (8) vs
#      serial (the oracle), >= 2x with ZERO decision mismatches
#      (byte-identical plans asserted in-bench), the parse-memo/
#      index-sizing audit, and the north-star overhead budget green
#      with sharding ON; BENCH_SHARD.json.  The mixed + repair
#      corpora re-run with --reconcile-shards 4 in the chaos stage
#      above (exit 7).
#   15 deadlock & determinism layer (ISSUE 15, docs/ANALYSIS.md):
#      the three whole-program passes re-run --no-baseline and alone
#      — TAL7xx lock-order graph, TAB8xx blocking-under-lock, TAD9xx
#      replay-determinism — so these code families can NEVER grow
#      baseline entries (a fresh inversion/blocking call/determinism
#      leak fails here even if someone grandfathers it past stage 1),
#      then the runtime lock-order witness cross-check
#      (tests/test_lockwitness.py): every lock-order edge witnessed
#      under the DeterministicScheduler must be modeled by the static
#      TAL7xx graph — a witnessed-but-unmodeled edge is a checker
#      blind spot and fails the stage.
#   16 units-of-measure layer (ISSUE 16, docs/ANALYSIS.md): the
#      TAU10xx dimension checker over the cost algebra re-run
#      --no-baseline and alone — mixed-dimension arithmetic,
#      unblessed chip*second / $-per-chip-hour crossings, unsuffixed
#      dimensioned metrics and cross-currency budget compares can
#      NEVER grow baseline entries.
#   17 columnar planner tier (ISSUE 17, docs/PLANNER.md): bench.py
#      plan_columnar at the 1M-pod/100k-node tier — the serial
#      planning pass on the struct-of-arrays fast path >= 5x the
#      python oracle with byte-identical decisions (plan AND claim
#      scan; BENCH_SCALE.json), then the 200-seed mixed chaos corpus
#      with --verify-columnar (the python planner shadows every
#      columnar pass; any plan mismatch fails the seed).
#   19 profiler tier (ISSUE 20, docs/OBSERVABILITY.md "Control-plane
#      profiling"): bench.py profile — the phase-tree profiler's
#      overhead within 2% + noise grace of profiler-off at the
#      100k-pod loop tier and the 10k-replica serving-pass tier, with
#      the self-time conservation identity asserted in-bench on every
#      profiled pass (BENCH_PROFILE.json); then scripts/bench_diff.py
#      — every gated ratio in the freshly re-recorded BENCH_*.json
#      files within 20% of the committed copy, so a tier can't pass
#      its own floor while quietly giving back a prior PR's headroom.
#   18 router tier (ISSUE 18, docs/SERVING.md "Request routing"):
#      bench.py router — amortized routing decision <= 5 us and score
#      refresh <= 1 ms per pass at 10k replicas, then the 2.2M-user
#      route_compare replay at equal provisions: router tail-SLO
#      miss rate >= 2x better than random dispatch AND per-replica
#      KV-occupancy variance >= 2x lower, zero lost requests;
#      BENCH_SERVING.json["router"].  The 200-seed chaos `router`
#      corpus — replica death mid-request, affinity staleness,
#      hedge storms, counter resets during hedges, with the
#      no-lost-requests + no-double-completion invariants — runs in
#      the chaos stage above, exit 7.
#
# Analysis output defaults to GitHub Actions workflow-command
# annotations (::error file=...,line=...); set ANALYSIS_FORMAT=text for
# plain file:line:CODE lines locally.
set -uo pipefail
cd "$(dirname "$0")/.."

fmt="${ANALYSIS_FORMAT:-github}"

echo "== [1/18] invariant analysis (--format=$fmt)"
python -m tpu_autoscaler.analysis --format="$fmt" tpu_autoscaler/ || exit 2

echo "== [2/18] deadlock & determinism layer (TAL/TAB/TAD --no-baseline + witness cross-check)"
# Zero-baseline-growth enforcement for the ISSUE 15 code families:
# stage 1 honors baseline.toml, this stage deliberately does not.
python -m tpu_autoscaler.analysis --format="$fmt" --no-baseline \
    --select TAL,TAB,TAD tpu_autoscaler/ || exit 15
JAX_PLATFORMS=cpu python -m pytest -q tests/test_lockwitness.py \
    -p no:cacheprovider || exit 15

echo "== [3/18] units-of-measure layer (TAU10xx --no-baseline)"
# Zero-baseline-growth for the cost-algebra dimension checker, same
# contract as the stage above: stage 1 honors baseline.toml, this
# stage deliberately does not — a fresh TAU finding fails CI even if
# someone grandfathers it past stage 1.
python -m tpu_autoscaler.analysis --format="$fmt" --units --no-baseline \
    tpu_autoscaler/ || exit 16

echo "== [4/18] mypy strict islands"
# One source of truth for the strict-island list: lint.sh.
./scripts/lint.sh --mypy-only || exit 3

echo "== [5/18] deterministic-schedule race tier"
# One source of truth for the tier invocation: race.sh.  Its static
# layer and witness cross-check already ran above (stage 1 runs every
# program pass over the whole package; stage 2 runs
# tests/test_lockwitness.py) — RACE_STATIC_COVERED tells race.sh not
# to pay for the whole-program analysis a third time.
RACE_STATIC_COVERED=1 ./scripts/race.sh || exit 4

echo "== [6/18] tracer-overhead gate"
JAX_PLATFORMS=cpu python bench.py trace || exit 5

echo "== [7/18] mega-cluster scale tiers"
JAX_PLATFORMS=cpu python bench.py observe --pods 100000 --nodes 10000 --floor 20 || exit 6
JAX_PLATFORMS=cpu python bench.py fit_batch --gangs 8192 --floor 2 || exit 6

echo "== [8/18] generative chaos corpora (200 mixed + 200 policy + 200 serving + 200 alerts + 200 repack + 200 router)"
# Every seed must hold every property invariant (no stranded chips, no
# double provision, whole-slice deletes only, gang ICI integrity,
# convergence, complete traces).  The CLI exits 2 on a violation and 3
# when the budget blows; both fail this stage with exit 7.  The policy
# profile re-runs the corpus with the PolicyEngine attached:
# mispredicted prewarms must never break the same invariants.  The
# serving profile (ISSUE 9) fuzzes the metrics-adapter path — replica
# restarts mid-window, counter resets, stale/out-of-order snapshots —
# asserting rates never go negative and the incremental folds match a
# from-scratch rebuild, per step.
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 480 || exit 7
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 300 --profile policy || exit 7
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 300 --profile serving || exit 7
# The alert e2e gate (ISSUE 10): regression seeds must fire the
# burn-rate alert inside the driven phase and resolve after the fault
# window; quiet seeds must produce ZERO false-positive firings.
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 300 --profile alerts || exit 7
# The repack corpus (ISSUE 12): migrations raced by spot reclamation,
# destination stockouts (spot_dry) and mid-drain gang deletion, with
# ICI-integrity + cost-conservation live per step and the
# never-net-negative-savings / guard-capped-abort-cost invariants
# asserted at terminal (docs/REPACK.md).
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 400 --profile repack || exit 7
# The router corpus (ISSUE 18, docs/SERVING.md "Request routing"):
# the routed replay raced by replica death mid-request, affinity
# staleness (epoch bumps under the table's feet), hedge storms
# (stall bursts that make many requests hedge-eligible at once) and
# counter resets during hedges, with the no-lost-requests and
# no-double-completion invariants asserted at terminal.
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 300 --profile router || exit 7
# Sharded corpora (ISSUE 13, docs/SHARDING.md): the mixed and repair
# corpora re-run with the sharded planner attached (shard_min_gangs=0
# so every pass exercises fan-out/merge) — the full step/terminal
# invariant catalog must hold unchanged, because sharded plans are
# byte-identical to serial by the merge contract.
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 480 --reconcile-shards 4 || exit 7
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 400 --profile repair --reconcile-shards 4 \
    || exit 7

echo "== [9/18] policy replay tier"
JAX_PLATFORMS=cpu python bench.py policy || exit 8

echo "== [10/18] serving tier (adapter hot path + outcome replay)"
JAX_PLATFORMS=cpu python bench.py serving || exit 9

echo "== [11/18] serving-trace tier (data-plane tracing overhead + acceptance)"
# ISSUE 14 (docs/OBSERVABILITY.md "Request spans & exemplars"):
# traced-vs-untraced replica step and 10k-replica exemplar fold
# within 2% + noise grace at 1% sampling with tail capture ON, plus
# the diurnal+spike acceptance replay — every SLO-missing cohort
# tail-captured gap-free, bundle exemplars resolving to real request
# traces, the miss-onset tail attributed to scale-up lag with a
# working scaleup-* cross-link.  Records
# BENCH_SERVING.json["serving_trace"].
JAX_PLATFORMS=cpu python bench.py serving-trace || exit 14

echo "== [12/18] router tier (dispatch decision cost + route_compare)"
# ISSUE 18 (docs/SERVING.md "Request routing"): the routing decision
# must stay <= 5 us amortized and the score refresh <= 1 ms per pass
# at 10k replicas, then the 2.2M-user route_compare replay at equal
# provisions — router vs random vs round-robin with byte-identical
# arrivals — where the router must beat random >= 2x on tail-SLO
# miss rate AND >= 2x on per-replica KV-occupancy variance with zero
# lost requests.  Records BENCH_SERVING.json["router"].
JAX_PLATFORMS=cpu python bench.py router || exit 18

echo "== [13/18] obs tier (TSDB ingest + alert evaluation)"
JAX_PLATFORMS=cpu python bench.py obs || exit 10

echo "== [14/18] cost tier (attribution ledger pass cost + conservation)"
JAX_PLATFORMS=cpu python bench.py cost || exit 11

echo "== [15/18] repack tier (week-long churn replay, never-worse gate)"
JAX_PLATFORMS=cpu python bench.py repack || exit 12

echo "== [16/18] sharded reconcile tier (million-pod loop + observe)"
# ISSUE 13 (docs/SHARDING.md): the 1M-pod observe tier (indexed reads
# must hold the 20x floor at 10x the PR-6 scale), then the full-loop
# tier — sharded reconcile >= 2x serial passes/sec at 8 shards with
# ZERO decision mismatches (byte-identical plans asserted in-bench),
# the memory-contract audit (parse-memo ratchet, index sizing), and
# the north-star overhead budget re-checked with sharding ON.
# Records BENCH_SHARD.json.
JAX_PLATFORMS=cpu python bench.py observe --pods 1000000 --nodes 100000 --floor 20 || exit 13
JAX_PLATFORMS=cpu python bench.py loop --pods 1000000 --nodes 100000 || exit 13

echo "== [17/18] columnar planner tier (million-pod plan + verified chaos corpus)"
# ISSUE 17 (docs/PLANNER.md): the columnar planner tier — the serial
# million-pod planning pass on the struct-of-arrays fast path must
# beat the python oracle >= 5x with byte-identical decisions (plan
# AND claim scan), then the 200-seed mixed chaos corpus re-runs with
# verify_columnar_plans ON (the python planner shadowing every
# columnar pass; any plan mismatch fails the seed).  Records
# BENCH_SCALE.json["plan_columnar"].
JAX_PLATFORMS=cpu python bench.py plan_columnar --pods 1000000 --nodes 100000 || exit 17
JAX_PLATFORMS=cpu python -m tpu_autoscaler.chaos --seed-corpus \
    --seeds 200 --budget 480 --verify-columnar || exit 17

echo "== [18/18] profiler tier (overhead + conservation) and bench ratio diff"
# ISSUE 20 (docs/OBSERVABILITY.md "Control-plane profiling"): the
# phase-tree profiler within 2% + noise grace of profiler-off at the
# 100k-pod loop tier and the 10k-replica serving-pass tier, zero
# conservation violations asserted in-bench (BENCH_PROFILE.json);
# then the cross-tier ratio diff — the bench stages above re-recorded
# their BENCH_*.json files, and every gated ratio (speedups, overhead
# ratios) must sit within 20% of the committed copy.
JAX_PLATFORMS=cpu python bench.py profile || exit 19
python scripts/bench_diff.py || exit 19

echo "CI GATE GREEN"
