# Container packaging (reference parity: Dockerfile — python base +
# requirements + CMD main loop; SURVEY.md §3 item 13).
FROM python:3.12-slim

WORKDIR /app
COPY requirements.txt .
RUN pip install --no-cache-dir -r requirements.txt

COPY tpu_autoscaler/ tpu_autoscaler/

# In-cluster auth (service account) + GKE workload identity for the GCP
# APIs; all configuration via flags/env (see deploy/autoscaler.yaml).
ENTRYPOINT ["python", "-m", "tpu_autoscaler.main"]
CMD ["run"]
