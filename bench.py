"""Benchmark: the north-star scale-up path, end to end.

The BASELINE metric is "Scale-up latency (Pending→Running) + stranded-chip %
per N-chip JAX job".  Two tiers run here:

1. Zero-delay tier: every BASELINE config against an instant-provisioning
   cloud — proves correctness (all Running, 0 stranded) and gates pure
   controller overhead (detection, gang grouping, shape fit, plan,
   actuation, readiness barrier) against a 20 ms CPU-time budget with the
   cross-round trend.
2. Realistic tier (the headline): every config again with the latency the
   cloud actually charges — 90 s slice creation/VM boot, 2 s/host
   registration spread (the PROVISIONING barrier), 5 s scheduler bind
   batching — gated on the north star itself: v5p-256 Unschedulable→
   Running < 360 s sim-time, with the detect/provision/register/bind
   phase anatomy printed per config.

Prints ONE JSON line: {"metric": "north_star_v5p256_realistic_scaleup",
"value": <sim seconds>, "unit": "s_simtime", "vs_baseline": budget/actual}
(vs_baseline > 1 beats the < 6 min BASELINE.json north-star target; the
reference publishes no numbers of its own, SURVEY.md §7).  The controller
overhead stays visible as a stderr info line and keeps its regression gate.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time

REFERENCE_DETECTION_BOUND_S = 60.0
# Regression gate (VERDICT r3 weak item 2): the north-star controller
# overhead drifted 12 ms (r1) → 16 ms (r3) with nothing watching it.
# r5's hot-path work (quantity-parse memoization + unrolled
# admits/fits_in loops) brought it to ~7.5-9 ms (best ever; the r1-r4
# trend was 12-16 ms); the budget tracks that with ~35-60% headroom —
# tight enough to catch r3-class drift at bench time, loose enough
# for cross-host variance.
OVERHEAD_BUDGET_S = 0.012

# The budget is stated in CPU seconds ON THE REFERENCE HOST CLASS that
# set it.  Bench hosts across rounds differ by ~±40% in single-core
# throughput (the recorded trend spans 7.7-16 ms for equivalent
# controller code), and a single host drifts over MINUTES (observed
# 13 ms -> 16 ms across back-to-back best-of-N passes on a shared
# box), so an absolute CPU-time gate false-trips with zero code
# change.  The gate therefore interleaves a fixed pure-Python
# reference spin with every north-star rep and gates on the best
# controller-CPU : spin-CPU ratio rescaled to reference seconds
# (ratio * NOMINAL_SPIN_S) — host speed and its drift hit numerator
# and denominator of the SAME rep alike and cancel; the budget itself
# is NOT loosened.  NOMINAL_SPIN_S is the spin's cost on the reference
# host class the budget was set against.
NOMINAL_SPIN_S = 0.0027


def _reference_spin_s() -> float:
    """One pass of a fixed interpreter-bound workload (int arithmetic
    + string-keyed dict churn, the controller loop's own mix) — the
    host-speed yardstick for the overhead gate.  Callers interleave it
    with the measured reps and best-of-N the ratio."""
    c0 = time.process_time()
    acc = 0
    d: dict = {}
    keys = ["node-%d" % i for i in range(512)]
    for i in range(20_000):
        d[keys[i & 511]] = acc
        acc += (i * i) & 0xFFFF
        if i & 1023 == 0:
            acc += sum(d.values()) & 0xFFFF
    return time.process_time() - c0


def _overhead_trend() -> list:
    """Prior rounds' north-star overhead, oldest first, from the
    BENCH_r*.json records the driver leaves at the repo root.

    Rounds ≤ 4 carried the overhead as the parsed stdout headline; later
    rounds emit it as a stderr info line (captured in the record's
    "tail") because the headline became the realistic end-to-end latency.
    """
    trend = []
    here = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                record = json.load(f)
        except (OSError, ValueError):
            continue
        candidates = [record.get("parsed") or {}]
        for line in (record.get("tail") or "").splitlines():
            try:
                candidates.append(json.loads(line))
            except ValueError:
                continue
        for obj in candidates:
            if (isinstance(obj, dict) and obj.get("metric")
                    == "north_star_v5p256_controller_overhead"):
                trend.append({"round": os.path.basename(path),
                              "value_s": obj.get("value")})
                break
    return trend


def run_north_star(config_extra: dict | None = None) -> dict:
    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.sim import seed_scenario

    kube = FakeKube()
    actuator = FakeActuator(kube, provision_delay=0.0)
    controller = Controller(kube, actuator, ControllerConfig(
        policy=PoolPolicy(spare_nodes=0), **(config_extra or {})))
    chips_requested = seed_scenario(kube, "v5p-256")

    def all_running() -> bool:
        pods = kube.list_pods()
        return bool(pods) and all(
            p["status"]["phase"] == "Running" for p in pods)

    t0 = time.perf_counter()
    c0 = time.process_time()
    sim_t, passes = 0.0, 0
    while not all_running():
        controller.reconcile_once(now=sim_t)
        kube.schedule_step()
        sim_t += 1.0
        passes += 1
        if passes > 100:
            raise RuntimeError("north-star scenario did not converge")
    controller.reconcile_once(now=sim_t)
    cpu = time.process_time() - c0
    elapsed = time.perf_counter() - t0

    chips = sum(
        int(float(n["status"]["allocatable"].get("google.com/tpu", 0)))
        for n in kube.list_nodes())
    return {
        "elapsed_s": elapsed,
        "cpu_s": cpu,
        "passes": passes,
        "nodes": len(kube.list_nodes()),
        "chips": chips,
        "stranded": max(0, chips - chips_requested),
    }


# Realistic-actuation profile for the end-to-end gate (VERDICT r4 item 1):
# the zero-delay configs above prove correctness + controller overhead; this
# profile re-runs every BASELINE config with the latency terms the cloud
# actually charges — slice creation / VM boot, per-host registration spread
# (the PROVISIONING barrier), and kube-scheduler bind batching — and gates
# the north star itself: v5p-256 Unschedulable→Running < 360 s sim-time.
REALISTIC_PROVISION_DELAY_S = 90.0   # QR accept → first VM boots
REALISTIC_HOST_STAGGER_S = 2.0       # per-host kubelet registration spread
REALISTIC_SCHEDULER_PERIOD_S = 5.0   # kube-scheduler bind batching
NORTH_STAR_BUDGET_S = 360.0          # BASELINE.json north_star: < 6 min


def run_realistic(scenario: str, chips_budget_s: float) -> dict:
    """One BASELINE config under the realistic-actuation profile.

    Returns {ok, latency_s, stranded, phases} where phases is the
    detect / provision / register / bind anatomy of the latency, read
    from the controller's own phase metrics (reconciler.py
    PHASE_LATENCY_METRICS) — the same series a real cluster exports.
    """
    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.sim import seed_scenario
    from tpu_autoscaler.topology.catalog import TPU_RESOURCE

    kube = FakeKube()
    actuator = FakeActuator(
        kube, provision_delay=REALISTIC_PROVISION_DELAY_S,
        stagger_seconds=REALISTIC_HOST_STAGGER_S)
    controller = Controller(kube, actuator, ControllerConfig(
        policy=PoolPolicy(spare_nodes=0)))
    chips_requested = seed_scenario(kube, scenario)

    def all_running() -> bool:
        pods = kube.list_pods()
        return bool(pods) and all(
            p["status"]["phase"] == "Running" for p in pods)

    sim_t, finished = 0.0, None
    while sim_t <= 600.0:
        controller.reconcile_once(now=sim_t)
        if sim_t % REALISTIC_SCHEDULER_PERIOD_S == 0.0:
            kube.schedule_step()
        if all_running():
            finished = sim_t
            controller.reconcile_once(now=sim_t)  # record gang latency
            break
        sim_t += 1.0

    snap = controller.metrics.snapshot()
    summaries = snap["summaries"]
    phases = {}
    for name, label in (("detect_latency_seconds", "detect"),
                        ("provision_latency_seconds", "provision"),
                        ("ready_barrier_seconds", "register"),
                        ("bind_latency_seconds", "bind")):
        s = summaries.get(name, {})
        if s.get("count"):
            phases[label] = round(s["max"], 1)
    chips = sum(
        int(float(n["status"]["allocatable"].get(TPU_RESOURCE, 0)))
        for n in kube.list_nodes())
    latency = summaries.get("scale_up_latency_seconds", {}).get("max")
    if latency is None:
        latency = finished
    stranded = max(0, chips - chips_requested)
    ok = (finished is not None and stranded == 0
          and latency is not None and latency < chips_budget_s)
    return {"ok": ok, "latency_s": latency, "stranded": stranded,
            "phases": phases}


def check_realistic_configs() -> tuple[bool, float | None]:
    """Gate every BASELINE config under realistic actuation latency.

    Returns (all_ok, north_star_latency_s) — the latter is the v5p-256
    end-to-end sim-time, the bench's headline metric.
    """
    ok, north_star = True, None
    for scenario in ("cpu", "v5e-8", "v5e-64", "2xv5p-128", "v5p-256"):
        r = run_realistic(scenario, NORTH_STAR_BUDGET_S)
        ok = ok and r["ok"]
        if scenario == "v5p-256":
            north_star = r["latency_s"]
        phase_txt = " ".join(f"{k}={v:g}s" for k, v in r["phases"].items())
        lat = ("timeout" if r["latency_s"] is None
               else f"{r['latency_s']:.1f}s")
        print(f"{'PASS' if r['ok'] else 'FAIL'} [{scenario} realistic] "
              f"Unschedulable→Running in {lat} sim-time "
              f"(budget {NORTH_STAR_BUDGET_S:g}s, provision_delay="
              f"{REALISTIC_PROVISION_DELAY_S:g}s, host_stagger="
              f"{REALISTIC_HOST_STAGGER_S:g}s, scheduler_period="
              f"{REALISTIC_SCHEDULER_PERIOD_S:g}s); stranded="
              f"{r['stranded']}; phases: {phase_txt}", file=sys.stderr)
    return ok, north_star


def check_all_configs() -> bool:
    """Gate: every BASELINE eval config must run clean (0 stranded)."""
    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.sim import seed_scenario, simulate

    ok = True
    for scenario in ("cpu", "v5e-8", "v5e-64", "2xv5p-128", "v5p-256"):
        kube = FakeKube()
        controller = Controller(kube, FakeActuator(kube), ControllerConfig(
            policy=PoolPolicy(spare_nodes=0)))
        chips = seed_scenario(kube, scenario)
        result = simulate(kube, controller, until=120.0, step=1.0,
                          scenario=scenario, chips_requested=chips)
        line_ok = result.all_running and result.stranded_chips == 0
        ok = ok and line_ok
        print(("PASS " if line_ok else "FAIL ") + result.describe(),
              file=sys.stderr)
    return ok


def bench_fit_batch(n_gangs: int = 512) -> dict:
    """Python per-gang vs batch-kernel shape scoring (the crossover that
    justifies PoolPolicy.native_fit_threshold).  Uses the native kernel
    when a toolchain exists, else the vectorized jaxfit/numpy kernel —
    so the zero-decision-mismatch parity gate always runs.  Reports any
    decision mismatch between the paths; main() fails the bench on one.
    """
    from tpu_autoscaler import native
    from tpu_autoscaler.engine.fitter import (
        batch_choose_shapes,
        choose_shape_for_gang,
    )
    from tpu_autoscaler.k8s.gangs import group_into_gangs
    from tpu_autoscaler.k8s.objects import Pod
    from tpu_autoscaler.sim import _pod
    from tpu_autoscaler.topology.catalog import TPU_RESOURCE

    backend = "native" if native.available() else "jaxfit"
    info: dict = {"info": "fit_batch", "gangs": n_gangs,
                  "backend": backend}
    mixes = [(8, 1), (4, 4), (4, 16), (1, 3), (4, 64), (4, 32)]
    pods = []
    for i in range(n_gangs):
        per, n = mixes[i % len(mixes)]
        pods += [Pod(_pod(f"g{i}-p{j}", {TPU_RESOURCE: str(per)},
                          labels={"batch.kubernetes.io/job-name": f"g{i}"}))
                 for j in range(n)]
    gangs = group_into_gangs(pods)
    t0 = time.perf_counter()
    py = {g.key: choose_shape_for_gang(g, "v5e") for g in gangs}
    py_s = time.perf_counter() - t0
    # Warm (builds/loads the library, or first numpy dispatch).
    batch_choose_shapes(gangs, "v5e", backend=backend)
    t0 = time.perf_counter()
    batch = batch_choose_shapes(gangs, "v5e", backend=backend)
    batch_s = time.perf_counter() - t0
    mismatch = sum(
        1 for k, c in batch.items()
        if (py[k].shape.name, py[k].stranded_chips)
        != (c.shape.name, c.stranded_chips))
    info.update({
        "python_ms": round(py_s * 1e3, 2),
        "batch_ms": round(batch_s * 1e3, 2),
        # Back-compat key: pre-scale rounds called this native_ms.
        "native_ms": round(batch_s * 1e3, 2),
        "speedup": round(py_s / batch_s, 1) if batch_s > 0 else None,
        "batch_decided": len(batch),
        "native_decided": len(batch),
        "decision_mismatches": mismatch,
    })
    return info


# Large-batch fit tier (ISSUE 6): the 512-gang default above proved the
# crossover; this tier gates the claim at fleet-admission scale.
FIT_BATCH_SCALE_GANGS = 8192
FIT_BATCH_SPEEDUP_FLOOR = 2.0


def check_fit_batch(n_gangs: int,
                    floor: float = FIT_BATCH_SPEEDUP_FLOOR
                    ) -> tuple[bool, dict]:
    """Gate: zero python/kernel decision mismatches AND the batch path
    at least ``floor``x faster than per-gang Python at ``n_gangs``."""
    info = bench_fit_batch(n_gangs)
    info["floor"] = floor
    print(json.dumps(info), file=sys.stderr)
    _record_scale_tier("fit_batch", info)
    ok = (info.get("decision_mismatches") == 0
          and info.get("batch_decided", 0) > 0
          and (info.get("speedup") or 0) >= floor)
    if not ok:
        print(json.dumps({"error": "fit_batch regression: decision "
                          "mismatch or speedup below floor", **info}),
              file=sys.stderr)
    return ok, info


def _record_tier(filename: str, key: str, info: dict) -> None:
    """Merge one tier result into a repo-root JSON record."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        filename)
    record: dict = {}
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, ValueError):
        record = {}
    record[key] = info
    with open(path, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")


def _record_scale_tier(key: str, info: dict) -> None:
    """Merge one scale-tier result into BENCH_SCALE.json (repo root)."""
    _record_tier("BENCH_SCALE.json", key, info)


# Policy tier (ISSUE 8): the predictive-prewarm claim, gated.  The
# north-star v5p-256 realistic scale-up is ~220 s sim-time and the
# PR-5 traces show provision dominates it; driven with a
# recurring-arrival trace, the PolicyEngine must hide provision from
# the critical path — post-warmup detect->running <= 0.25x the
# reactive baseline — while a regime-change trace (forecasts that go
# WRONG) must keep realized wasted chip-seconds under the configured
# budget.  Results merge into BENCH_POLICY.json.
POLICY_TAIL_RATIO_GATE = 0.25
POLICY_RECURRING_PERIOD_S = 1200.0
POLICY_RECURRING_CYCLES = 6


def bench_policy() -> dict:
    from tpu_autoscaler.policy.replay import (
        compare,
        default_policy_config,
        make_program,
        replay,
    )

    recurring = make_program(
        "recurring", shape="v5p-256",
        period=POLICY_RECURRING_PERIOD_S,
        cycles=POLICY_RECURRING_CYCLES, run_seconds=300.0)
    card = compare(recurring)
    regime = make_program("regime", shape="v5p-256", period=900.0,
                          cycles=6, run_seconds=240.0)
    misfire = replay(regime, policy=True)
    waste_budget = default_policy_config(
        regime).slo.waste_budget_chip_seconds
    return {
        "info": "policy",
        "recurring": card,
        "misfire": misfire.as_dict(),
        "waste_budget_chip_s": waste_budget,
        "tail_ratio_gate": POLICY_TAIL_RATIO_GATE,
    }


def check_policy() -> tuple[bool, dict]:
    """Gate: prewarmed tail latency <= 0.25x reactive on the recurring
    north-star trace; mispredictions (regime change) keep wasted
    chip-seconds under budget; neither run leaves pods pending."""
    info = bench_policy()
    card = info["recurring"]
    ratio = card.get("tail_ratio")
    hits = card["policy"]["prewarm_hits"]
    pending = (card["reactive"]["pending_at_end"]
               + card["policy"]["pending_at_end"]
               + info["misfire"]["pending_at_end"])
    waste = info["misfire"]["wasted_prewarm_chip_s"]
    ok = (ratio is not None and ratio <= POLICY_TAIL_RATIO_GATE
          and hits > 0 and pending == 0
          and waste <= info["waste_budget_chip_s"])
    print(json.dumps({k: info[k] for k in
                      ("recurring", "misfire", "waste_budget_chip_s")},
                     default=str), file=sys.stderr)
    _record_tier("BENCH_POLICY.json", "policy", {
        "tail_ratio": ratio,
        "tail_latency_reactive_s": card["tail_latency_reactive_s"],
        "tail_latency_policy_s": card["tail_latency_policy_s"],
        "prewarm_hits": hits,
        "hidden_provision_s":
            card["policy"]["hidden_provision_s"],
        "misfire_wasted_chip_s": waste,
        "waste_budget_chip_s": info["waste_budget_chip_s"],
        "gate": POLICY_TAIL_RATIO_GATE,
    })
    if not ok:
        print(json.dumps({"error": "policy regression: prewarmed tail "
                          "latency above the 0.25x gate, no hits, "
                          "pending pods, or waste over budget",
                          "tail_ratio": ratio, "hits": hits,
                          "pending": pending, "waste": waste}),
              file=sys.stderr)
    return ok, info


# Serving tier (ISSUE 9): the live-signal hot path from the serving
# engines to the planner, two claims gated together and recorded in
# BENCH_SERVING.json:
#
# - PERF: the metrics adapter folds a 10k-replica fleet's snapshots
#   into per-pool demand signals in <= 1 ms per reconcile pass
#   (O(churn), vectorized), and beats the naive every-replica scan by
#   >= 10x;
# - OUTCOME: on the diurnal+spike millions-of-users replay through
#   the REAL Controller, signal-driven scaling beats pod-pending
#   reactive tail SLO attainment (miss-rate ratio >= the gate).
SERVING_ADAPTER_REPLICAS = 10_000
SERVING_ADAPTER_POOLS = 16
SERVING_ADAPTER_CHURN = 0.10
SERVING_ADAPTER_PASSES = 50
SERVING_ADAPTER_MS_GATE = 1.0
SERVING_AGG_SPEEDUP_FLOOR = 10.0
SERVING_MISS_RATIO_GATE = 2.0


def _serving_snapshot(seq: int, rng, exemplar: bool = False,
                      rid: int = 0) -> "object":
    from tpu_autoscaler.serving.stats import ServingSnapshot

    finished = seq * 40 + int(rng.integers(0, 20))
    extra = {}
    if exemplar:
        # A FRESH exemplar only when a promotion occurred since the
        # last delivery (~1% head + tail bursts, modeled as every 4th
        # tick); between promotions the snapshot re-carries the stale
        # seq — the adapter's common path is the one-int-compare
        # reject, which is what the overhead gate must measure.
        fresh = seq - seq % 4
        extra = {"exemplar_trace_id": f"request-rep-{rid}-r{fresh}",
                 "exemplar_value": float(fresh % 37),
                 "exemplar_seq": fresh}
    return ServingSnapshot(
        epoch=1, seq=seq, queue_depth=int(rng.integers(0, 8)),
        active=int(rng.integers(0, 16)), slots=16,
        kv_used=int(rng.integers(0, 4096)), kv_capacity=4096,
        admitted_total=finished + 4, preempted_total=seq // 50,
        finished_total=finished, slo_ok_total=int(finished * 0.97),
        decode_tokens_total=finished * 100,
        queue_depth_mean=2.0, tokens_per_tick=40.0,
        latency_p50_ticks=3.0, latency_p95_ticks=7.0, **extra)


def bench_serving_adapter(n_replicas: int = SERVING_ADAPTER_REPLICAS,
                          churn: float = SERVING_ADAPTER_CHURN,
                          passes: int = SERVING_ADAPTER_PASSES) -> dict:
    """Adapter fold vs naive scan at fleet scale, plus the fold wired
    into a real reconcile pass (Controller + ServingScaler)."""
    import numpy as np

    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.serving.adapter import (
        ServingMetricsAdapter,
        scan_aggregate,
    )
    from tpu_autoscaler.serving.scaler import (
        ServingPolicy,
        ServingScaler,
    )

    rng = np.random.default_rng(0)
    adapter = ServingMetricsAdapter(capacity=n_replicas)
    pools = [f"pool-{i}" for i in range(SERVING_ADAPTER_POOLS)]
    seqs = [1] * n_replicas
    latest: list = [None] * n_replicas
    for i in range(n_replicas):
        snap = _serving_snapshot(seqs[i], rng)
        latest[i] = snap
        adapter.ingest(f"rep-{i}", pools[i % len(pools)],
                       "tpu-v5-lite-device", "v5e-4", snap, now=0.0)
    adapter.fold(0.0)

    n_churn = max(1, int(n_replicas * churn))
    fold_s = 0.0
    ingest_s = 0.0
    cursor = 0
    for p in range(1, passes + 1):
        now = float(p * 5)
        t0 = time.perf_counter()
        for _ in range(n_churn):
            i = cursor % n_replicas
            cursor += 1
            seqs[i] += 1
            snap = _serving_snapshot(seqs[i], rng)
            latest[i] = snap
            adapter.ingest(f"rep-{i}", pools[i % len(pools)],
                           "tpu-v5-lite-device", "v5e-4", snap,
                           now=now)
        ingest_s += time.perf_counter() - t0
        t0 = time.perf_counter()
        adapter.fold(now)
        signals = adapter.signals()
        fold_s += time.perf_counter() - t0
    assert len(signals) == len(pools)

    # Naive baseline: re-derive every pool aggregate by scanning EVERY
    # replica's latest snapshot each pass.
    scan_rows = [(f"rep-{i}", pools[i % len(pools)],
                  "tpu-v5-lite-device", "v5e-4", latest[i],
                  float(latest[i].decode_tokens_total - 200), 5.0)
                 for i in range(n_replicas)]
    t0 = time.perf_counter()
    for _ in range(passes):
        scan_aggregate(scan_rows)
    scan_s = time.perf_counter() - t0

    # The same fold inside a REAL reconcile pass: Controller +
    # ServingScaler over the 10k-replica adapter (empty cluster — the
    # measured delta is the serving pass itself).
    kube = FakeKube()
    controller = Controller(
        kube, FakeActuator(kube),
        ControllerConfig(policy=PoolPolicy(spare_nodes=0)),
        serving_scaler=ServingScaler(
            adapter, ServingPolicy(forecast=False, max_replicas=0)))
    t0 = time.perf_counter()
    for p in range(10):
        controller.reconcile_once(now=float(1000 + p))
    reconcile_ms = (time.perf_counter() - t0) / 10 * 1e3
    drift = adapter.drift()

    fold_ms = fold_s / passes * 1e3
    scan_ms = scan_s / passes * 1e3
    return {
        "info": "serving_adapter",
        "replicas": n_replicas,
        "churn_per_pass": n_churn,
        "passes": passes,
        "fold_ms_per_pass": round(fold_ms, 4),
        "ingest_us_per_snapshot": round(
            ingest_s / (passes * n_churn) * 1e6, 2),
        "scan_ms_per_pass": round(scan_ms, 3),
        "speedup": round(scan_ms / max(fold_ms, 1e-9), 1),
        "reconcile_pass_ms": round(reconcile_ms, 3),
        "rebuild_drift": drift,
    }


def bench_serving_outcome(seed: int = 0) -> dict:
    from tpu_autoscaler.serving.replay import (
        ServingReplayConfig,
        compare,
    )

    return compare(ServingReplayConfig(seed=seed))


def check_serving(replicas: int = SERVING_ADAPTER_REPLICAS,
                  ms_gate: float = SERVING_ADAPTER_MS_GATE,
                  speedup_floor: float = SERVING_AGG_SPEEDUP_FLOOR,
                  ratio_gate: float = SERVING_MISS_RATIO_GATE
                  ) -> tuple[bool, dict]:
    """Gate the serving tier: adapter fold <= 1 ms/pass at 10k
    replicas, incremental >= 10x over the scan, AND signal-driven
    tail SLO attainment beats pod-pending reactive (miss-rate ratio
    >= gate, no request lost in either mode)."""
    perf = bench_serving_adapter(n_replicas=replicas)
    print(json.dumps(perf), file=sys.stderr)
    outcome = bench_serving_outcome()
    print(json.dumps({k: outcome[k] for k in
                      ("trace", "reactive", "signal",
                       "miss_rate_ratio")}), file=sys.stderr)
    perf_ok = (perf["fold_ms_per_pass"] <= ms_gate
               and perf["speedup"] >= speedup_floor
               and perf["rebuild_drift"] < 1e-3)
    ratio = outcome["miss_rate_ratio"]
    outcome_ok = (
        ratio >= ratio_gate
        and outcome["tail_attainment_signal"]
        >= outcome["tail_attainment_reactive"]
        and outcome["reactive"]["unserved"] == 0
        and outcome["signal"]["unserved"] == 0)
    info = {
        "adapter": {**perf, "ms_gate": ms_gate,
                    "speedup_floor": speedup_floor},
        "outcome": {
            "trace": outcome["trace"],
            "tail_attainment_reactive":
                outcome["tail_attainment_reactive"],
            "tail_attainment_signal":
                outcome["tail_attainment_signal"],
            "miss_rate_ratio": ratio,
            "ratio_gate": ratio_gate,
            "reactive_provisions": outcome["reactive"]["provisions"],
            "signal_provisions": outcome["signal"]["provisions"],
            "latency_p99_reactive_s":
                outcome["reactive"]["latency_p99_s"],
            "latency_p99_signal_s":
                outcome["signal"]["latency_p99_s"],
        },
    }
    _record_tier("BENCH_SERVING.json", "serving", info)
    ok = perf_ok and outcome_ok
    if not ok:
        print(json.dumps({"error": "serving regression: adapter fold "
                          "over 1 ms/pass, speedup below floor, or "
                          "signal-driven scaling failed to beat the "
                          "pod-pending reactive tail", **info},
                         default=str), file=sys.stderr)
    return ok, info


# Serving-trace tier (ISSUE 14): request-level data-plane tracing must
# be effectively free.  Two overhead gates, both at 1% head sampling
# with tail capture ON, plus the end-to-end acceptance replay:
#
# - DATA PLANE: the replay-replica serving step (FIFO completions +
#   stats rings + sampler hooks) traced vs untraced — wall time and
#   tokens/s within TRACE_OVERHEAD_GATE (+ an explicit noise grace:
#   host timers on a shared box jitter more than 2%, so the gate is
#   2% measured + the grace, stated rather than hidden);
# - CONTROL PLANE at the 10k-replica adapter scale: ingest+fold with
#   exemplar-carrying snapshots vs exemplar-free, same bound;
# - ACCEPTANCE: the diurnal+spike millions-of-users replay with
#   tracing on — every SLO-missing cohort in the spike window
#   tail-captured with a gap-free span tree, the incident bundle's
#   exemplar resolving to a retained request trace, and the
#   tail-report attributing the spike tail to scale-up lag with a
#   working scaleup-* cross-link.
TRACE_OVERHEAD_GATE = 0.02
TRACE_NOISE_GRACE = 0.03
TRACE_STEP_REPLICAS = 40
TRACE_STEP_STEPS = 300
TRACE_BEST_OF = 3


def _trace_replica_run(traced: bool, *, replicas_n: int,
                       steps: int, seed: int = 0) -> tuple[float, int]:
    """One seeded replica-fleet run (identical load either way);
    returns (wall seconds, decode tokens served)."""
    import numpy as np

    from tpu_autoscaler.serving.replay import (
        ServingReplayConfig,
        _Replica,
    )

    cfg = ServingReplayConfig(
        seed=seed, trace_sample_rate=0.01 if traced else 0.0)
    rng = np.random.default_rng(seed)
    reps = [_Replica(f"bench-rep-{i}", f"n{i}", cfg)
            for i in range(replicas_n)]

    def score(arrival, finish, n):
        pass

    t0 = time.perf_counter()
    t = 0.0
    for _ in range(steps):
        for rep in reps:
            rep.assign(t, int(rng.integers(0, 40)))
            rep.step(t, cfg, score)
        t += cfg.step
    elapsed = time.perf_counter() - t0
    return elapsed, sum(r.decode_tokens for r in reps)


def _trace_adapter_run(exemplars: bool,
                       n_replicas: int) -> tuple[float, int]:
    """Ingest+fold+take at fleet scale, snapshots carrying exemplars
    or not; returns (wall seconds over the churn passes, exemplars
    taken).  Snapshot CONSTRUCTION happens outside the timed window
    (pre-built per pass), so the measured delta is the adapter's
    exemplar branch alone, not harness cost."""
    import numpy as np

    from tpu_autoscaler.serving.adapter import ServingMetricsAdapter

    rng = np.random.default_rng(0)
    adapter = ServingMetricsAdapter(capacity=n_replicas)
    seqs = [1] * n_replicas
    names = [f"rep-{i}" for i in range(n_replicas)]
    pools = [f"pool-{i % 16}" for i in range(n_replicas)]
    for i in range(n_replicas):
        adapter.ingest(names[i], pools[i], "tpu-v5-lite-device",
                       "v5e-4",
                       _serving_snapshot(seqs[i], rng,
                                         exemplar=exemplars, rid=i),
                       now=0.0)
    adapter.fold(0.0)
    n_churn = max(1, int(n_replicas * SERVING_ADAPTER_CHURN))
    cursor = 0
    batches = []
    for p in range(1, SERVING_ADAPTER_PASSES + 1):
        batch = []
        for _ in range(n_churn):
            i = cursor % n_replicas
            cursor += 1
            seqs[i] += 1
            batch.append((i, _serving_snapshot(
                seqs[i], rng, exemplar=exemplars, rid=i)))
        batches.append((float(p * 5), batch))
    taken = 0
    t0 = time.perf_counter()
    for now, batch in batches:
        for i, snap in batch:
            adapter.ingest(names[i], pools[i], "tpu-v5-lite-device",
                           "v5e-4", snap, now=now)
        adapter.fold(now)
        taken += len(adapter.take_exemplars())
    return time.perf_counter() - t0, taken


def bench_serving_trace(replicas: int = SERVING_ADAPTER_REPLICAS
                        ) -> dict:
    """Traced-vs-untraced overheads (best-of-N, interleaved so drift
    hits both arms)."""
    step_untraced = []
    step_traced = []
    tokens = [0, 0]
    for _ in range(TRACE_BEST_OF):
        el, tok = _trace_replica_run(False,
                                     replicas_n=TRACE_STEP_REPLICAS,
                                     steps=TRACE_STEP_STEPS)
        step_untraced.append(el)
        tokens[0] = tok
        el, tok = _trace_replica_run(True,
                                     replicas_n=TRACE_STEP_REPLICAS,
                                     steps=TRACE_STEP_STEPS)
        step_traced.append(el)
        tokens[1] = tok
    adapter_plain = []
    adapter_ex = []
    for _ in range(TRACE_BEST_OF):
        adapter_plain.append(_trace_adapter_run(False, replicas)[0])
        el, taken = _trace_adapter_run(True, replicas)
        adapter_ex.append(el)
    step_ratio = min(step_traced) / max(min(step_untraced), 1e-9)
    adapter_ratio = min(adapter_ex) / max(min(adapter_plain), 1e-9)
    assert tokens[0] == tokens[1], "traced run changed the workload"
    return {
        "info": "serving_trace_overhead",
        "sample_rate": 0.01,
        "step_untraced_s": round(min(step_untraced), 4),
        "step_traced_s": round(min(step_traced), 4),
        "step_overhead_ratio": round(step_ratio, 4),
        "tokens_per_s_untraced": round(
            tokens[0] / max(min(step_untraced), 1e-9)),
        "tokens_per_s_traced": round(
            tokens[1] / max(min(step_traced), 1e-9)),
        "adapter_replicas": replicas,
        "adapter_plain_s": round(min(adapter_plain), 4),
        "adapter_exemplar_s": round(min(adapter_ex), 4),
        "adapter_overhead_ratio": round(adapter_ratio, 4),
        "exemplars_taken": taken,
    }


def bench_serving_trace_acceptance(seed: int = 0) -> dict:
    """The ISSUE 14 end-to-end acceptance on the full diurnal+spike
    millions-of-users replay (signal mode, 1% sampling + tail
    capture).  The well-tuned signal path absorbs the spike itself —
    the SLO misses concentrate at the overload ONSETS (cold start and
    the morning ramps, where demand outruns provisioning), which is
    exactly where "replica arrived late" is the story: the
    attribution window is the first miss onset, and the coverage
    property is GLOBAL — every SLO-missing cohort anywhere in the
    replay has a tail-captured, gap-free trace."""
    from tpu_autoscaler.obs import tailcause, trace_gaps
    from tpu_autoscaler.serving.adapter import EXEMPLAR_FAMILY
    from tpu_autoscaler.serving.replay import (
        ServingReplayConfig,
        replay,
    )

    cfg = ServingReplayConfig(seed=seed, trace_sample_rate=0.01)
    artifacts: dict = {}
    result = replay(cfg, mode="signal", artifacts=artifacts)
    controller = artifacts["controller"]
    score = artifacts["score"]
    dump = controller.recorder.dump()
    roots = [s for s in dump["spans"]
             if s["name"] == "request" and s["attrs"].get("slo_miss")]
    # Per-trace gap check on grouped spans (trace_gaps over the full
    # 30k-span dump per trace would be quadratic).
    by_trace: dict = {}
    for s in dump["spans"]:
        by_trace.setdefault(s["trace_id"], []).append(s)
    gap_traces = sum(
        1 for s in roots
        if trace_gaps({"spans": by_trace[s["trace_id"]]},
                      s["trace_id"]))
    bundle = controller.incident_bundle("bench")
    exemplar = controller.tsdb.exemplar_latest(EXEMPLAR_FAMILY)
    retained_ids = set(by_trace)
    exemplar_resolves = (exemplar is not None
                         and exemplar[2] in retained_ids)
    onset = min((m[0] for m in score.miss_cohorts),
                default=0.0)
    report = tailcause.analyze(bundle, window=(onset, onset + 900.0))
    link = report.get("scaleup") or {}
    link_resolves = bool(link.get("trace_id")
                         and link["trace_id"] in retained_ids)
    alert = controller.alerts.state_of("serving-slo-attainment")
    return {
        "info": "serving_trace_acceptance",
        "modeled_users": cfg.modeled_users,
        "unserved": result.unserved,
        "miss_cohorts": len(score.miss_cohorts),
        "tail_roots": len(roots),
        "gap_traces": gap_traces,
        "exemplar_trace": exemplar[2] if exemplar else None,
        "exemplar_resolves": exemplar_resolves,
        "onset": onset,
        "dominant_cause": report.get("dominant_cause"),
        "scaleup_link": link.get("trace_id"),
        "scaleup_link_resolves": link_resolves,
        "serving_alert_fired": alert.fired_count,
        "tail_sampled_total": int(sum(
            s.tail_captured_total for s in artifacts["samplers"])),
    }


def check_serving_trace(replicas: int = SERVING_ADAPTER_REPLICAS,
                        gate: float = TRACE_OVERHEAD_GATE,
                        grace: float = TRACE_NOISE_GRACE
                        ) -> tuple[bool, dict]:
    """Gate the serving-trace tier (ISSUE 14): both overhead ratios
    within (1 + gate + grace), and the acceptance replay's tail
    coverage / exemplar resolution / scale-up attribution all green."""
    perf = bench_serving_trace(replicas=replicas)
    print(json.dumps(perf), file=sys.stderr)
    acc = bench_serving_trace_acceptance()
    print(json.dumps(acc), file=sys.stderr)
    bound = 1.0 + gate + grace
    perf_ok = (perf["step_overhead_ratio"] <= bound
               and perf["adapter_overhead_ratio"] <= bound
               and perf["exemplars_taken"] > 0)
    acc_ok = (acc["unserved"] == 0
              and acc["miss_cohorts"] > 0
              and acc["tail_roots"] >= acc["miss_cohorts"]
              and acc["gap_traces"] == 0
              and acc["exemplar_resolves"]
              and acc["dominant_cause"] == "scaleup-lag"
              and acc["scaleup_link_resolves"]
              and acc["serving_alert_fired"] > 0)
    info = {"overhead": {**perf, "gate": gate, "noise_grace": grace},
            "acceptance": acc}
    _record_tier("BENCH_SERVING.json", "serving_trace", info)
    ok = perf_ok and acc_ok
    if not ok:
        print(json.dumps({
            "error": "serving-trace regression: data-plane tracing "
                     "overhead above the 2%+grace gate, or the "
                     "acceptance replay lost tail coverage / exemplar "
                     "resolution / scale-up attribution", **info},
            default=str), file=sys.stderr)
    return ok, info


# Router tier (ISSUE 18) — BENCH_SERVING.json["router"]:
#
# - PERF: one routing decision <= 5 us amortized over a sustained
#   dispatch burst against a 10k-replica fleet (candidate heap + lazy
#   re-pricing, no O(fleet) work per decision), and the post-fold
#   score/candidate refresh <= 1 ms per pass (vectorized argpartition);
# - OUTCOME: on the 2.2M-user diurnal replay at EQUAL provisions
#   (frozen fleet, byte-identical arrivals), KV/queue-aware dispatch
#   beats random dispatch >= 2x on tail SLO miss-rate AND >= 2x on
#   per-replica KV-occupancy variance, with zero lost requests in
#   every mode.
ROUTER_BENCH_REPLICAS = 10_000
ROUTER_BENCH_DISPATCHES = 50_000
ROUTER_DECISION_US_GATE = 5.0
ROUTER_REFRESH_MS_GATE = 1.0
ROUTER_MISS_RATIO_GATE = 2.0
ROUTER_KV_VAR_RATIO_GATE = 2.0


def bench_router_hotpath(n_replicas: int = ROUTER_BENCH_REPLICAS,
                         dispatches: int = ROUTER_BENCH_DISPATCHES
                         ) -> dict:
    """Router decision + refresh cost at fleet scale: a 10k-replica
    adapter census, then a sustained dispatch burst (30% session-
    sticky, cohort weights) with a score refresh every 2k decisions —
    the refresh clock is gated separately from the amortized decision
    clock."""
    import numpy as np

    from tpu_autoscaler.serving.adapter import ServingMetricsAdapter
    from tpu_autoscaler.serving.router import RouterCore

    rng = np.random.default_rng(0)
    adapter = ServingMetricsAdapter(capacity=n_replicas)
    for i in range(n_replicas):
        adapter.ingest(f"rep-{i}", f"pool-{i % SERVING_ADAPTER_POOLS}",
                       "tpu-v5-lite-device", "v5e-4",
                       _serving_snapshot(1, rng), now=0.0)
    adapter.fold(0.0)
    router = RouterCore(adapter)
    router.refresh(5.0)

    # Refresh under churn: 10% of the fleet re-reports between passes
    # (the adapter's dirty-fold rides the same set).
    n_churn = n_replicas // 10
    passes = 20
    refresh_s = 0.0
    for p in range(1, passes + 1):
        now = float(p * 5)
        for j in range(n_churn):
            i = (p * n_churn + j) % n_replicas
            adapter.ingest(f"rep-{i}",
                           f"pool-{i % SERVING_ADAPTER_POOLS}",
                           "tpu-v5-lite-device", "v5e-4",
                           _serving_snapshot(1 + p, rng), now=now)
        adapter.fold(now)
        t0 = time.perf_counter()
        router.refresh(now)
        refresh_s += time.perf_counter() - t0

    # Decision burst: session mix + tracked rids, refresh every 2k
    # decisions (counted on the refresh clock, not the decision
    # clock — each has its own gate).
    sessions = [f"s{i}" for i in range(4096)]
    # The plan (session key + cohort weight per decision) is built
    # OUTSIDE the clock: the gate prices the routing decision, not
    # the harness's string formatting.
    plan = [(sessions[k % 4096] if k % 10 < 3 else None,
             float(1 + k % 8)) for k in range(dispatches)]
    now = float(passes * 5)
    dispatch_s = 0.0
    done = 0
    while done < dispatches:
        burst = min(2000, dispatches - done)
        chunk = plan[done:done + burst]
        dispatch = router.dispatch
        t0 = time.perf_counter()
        for session, weight in chunk:
            dispatch(now, session=session, weight=weight)
        dispatch_s += time.perf_counter() - t0
        done += burst
        now += 0.05
        t0 = time.perf_counter()
        router.refresh(now)
        refresh_s += time.perf_counter() - t0
        passes += 1

    return {
        "info": "router_hotpath",
        "replicas": n_replicas,
        "dispatches": dispatches,
        "decision_us": round(dispatch_s / dispatches * 1e6, 3),
        "refresh_ms_per_pass": round(refresh_s / passes * 1e3, 4),
        "refresh_passes": passes,
        "affinity_size": router.affinity_size,
    }


def check_router(replicas: int = ROUTER_BENCH_REPLICAS,
                 decision_gate: float = ROUTER_DECISION_US_GATE,
                 refresh_gate: float = ROUTER_REFRESH_MS_GATE,
                 miss_gate: float = ROUTER_MISS_RATIO_GATE,
                 var_gate: float = ROUTER_KV_VAR_RATIO_GATE
                 ) -> tuple[bool, dict]:
    """Gate the router tier: hot-path budgets at 10k replicas plus the
    equal-provisions route_compare scorecard (router >= 2x better than
    random on tail miss-rate AND on KV-occupancy variance, zero lost
    requests in every mode)."""
    from tpu_autoscaler.serving.replay import route_compare

    perf = bench_router_hotpath(n_replicas=replicas)
    print(json.dumps(perf), file=sys.stderr)
    outcome = route_compare()
    print(json.dumps({k: outcome[k] for k in
                      ("trace", "miss_rate_ratio",
                       "kv_variance_ratio", "lost_requests")}),
          file=sys.stderr)
    perf_ok = (perf["decision_us"] <= decision_gate
               and perf["refresh_ms_per_pass"] <= refresh_gate)
    outcome_ok = (outcome["miss_rate_ratio"] >= miss_gate
                  and outcome["kv_variance_ratio"] >= var_gate
                  and outcome["lost_requests"] == 0)
    info = {
        "hotpath": {**perf, "decision_us_gate": decision_gate,
                    "refresh_ms_gate": refresh_gate},
        "outcome": {
            "trace": outcome["trace"],
            "miss_rate_ratio": outcome["miss_rate_ratio"],
            "kv_variance_ratio": outcome["kv_variance_ratio"],
            "miss_gate": miss_gate,
            "var_gate": var_gate,
            "lost_requests": outcome["lost_requests"],
            "modes": {m: {k: d[k] for k in
                          ("tail_miss_rate", "latency_p99_s",
                           "kv_occ_variance", "unserved")}
                      for m, d in outcome["modes"].items()},
        },
    }
    _record_tier("BENCH_SERVING.json", "router", info)
    ok = perf_ok and outcome_ok
    if not ok:
        print(json.dumps({"error": "router regression: decision over "
                          "5 us / refresh over 1 ms at 10k replicas, "
                          "or KV/queue-aware dispatch failed to beat "
                          "random 2x on tail miss-rate and KV-"
                          "occupancy variance at equal provisions",
                          **info}, default=str), file=sys.stderr)
    return ok, info


# Observe-path tier (ISSUE 2): steady-state per-pass observation cost —
# list + parse of the whole cluster — at production scale, relist
# baseline vs the informer's delta-applying cache (k8s/informer.py).
# The informer pays O(churn) parses per pass instead of O(cluster); the
# gate requires >= 5x on a 5k-pod / 600-node cluster with 1% churn.
OBSERVE_PODS = 5000
OBSERVE_NODES = 600
OBSERVE_CHURN = 0.01
OBSERVE_PASSES = 5
OBSERVE_SPEEDUP_FLOOR = 5.0


def _observe_pod_payload(i: int, rv: int,
                         n_nodes: int = OBSERVE_NODES) -> dict:
    running = i % 50 != 0  # ~2% pending (the demand tail)
    payload = {
        "metadata": {
            "name": f"pod-{i}", "namespace": f"ns-{i % 20}",
            "uid": f"uid-pod-{i}", "resourceVersion": str(rv),
            "labels": {"batch.kubernetes.io/job-name": f"job-{i // 4}",
                       "app": f"app-{i % 100}"},
            "annotations": {},
            "creationTimestamp": "2026-01-01T00:00:00Z",
            "ownerReferences": [{"kind": "Job", "name": f"job-{i // 4}"}],
        },
        "spec": {
            "nodeName": f"node-{i % n_nodes}" if running else None,
            "nodeSelector": {},
            "tolerations": [{"key": "google.com/tpu",
                             "operator": "Exists",
                             "effect": "NoSchedule"}],
            "containers": [{"name": "main", "resources": {
                "requests": {"cpu": "2", "memory": "4Gi",
                             "google.com/tpu": "4"}}}],
        },
        "status": {"phase": "Running" if running else "Pending",
                   "conditions": [] if running else [
                       {"type": "PodScheduled", "status": "False",
                        "reason": "Unschedulable"}]},
    }
    return payload


def _observe_node_payload(i: int, rv: int) -> dict:
    return {
        "metadata": {
            "name": f"node-{i}", "uid": f"uid-node-{i}",
            "resourceVersion": str(rv),
            "labels": {
                "cloud.google.com/gke-nodepool": f"pool-{i // 4}",
                "cloud.google.com/gke-tpu-accelerator": "tpu-v5p-slice",
                "cloud.google.com/gke-tpu-topology": "2x2x1",
                "node.kubernetes.io/instance-type": "ct5p-hightpu-4t",
            },
            "annotations": {},
            "creationTimestamp": "2026-01-01T00:00:00Z",
        },
        "spec": {"taints": [{"key": "google.com/tpu", "value": "present",
                             "effect": "NoSchedule"}]},
        "status": {
            "allocatable": {"cpu": "208", "memory": "400Gi",
                            "pods": "110", "google.com/tpu": "4"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def bench_observe_path(n_pods: int = OBSERVE_PODS,
                       n_nodes: int = OBSERVE_NODES,
                       churn: float = OBSERVE_CHURN,
                       tracer=None, per_pass=None) -> dict:
    """Relist baseline vs informer steady-state, best-of-N passes each.

    Baseline = exactly what ``reconcile_once`` did before the informer:
    construct every ``Node``/``Pod`` from the freshly-listed payloads.
    Informer = apply the pass's churn deltas (bumped resourceVersions)
    to warm caches, then snapshot — parse work is O(churn) through the
    (uid, resourceVersion) memo, snapshot is an O(n) list copy.

    ``tracer``: when set, each informer pass carries the tracing work
    ``reconcile_once`` adds per pass (a span end + a decision record) —
    the traced variant the tracer-overhead gate compares (ISSUE 5).

    ``per_pass``: optional callable(pass_index) run INSIDE the timed
    informer loop — how the obs tier (ISSUE 10) adds the per-pass
    TSDB-ingest + alert-evaluation work the reconciler now does.
    """
    from tpu_autoscaler.k8s.informer import ObjectCache
    from tpu_autoscaler.k8s.objects import (
        Node,
        Pod,
        clear_parse_caches,
        parse_node,
        parse_pod,
    )

    rv = 1
    pod_payloads = [_observe_pod_payload(i, rv) for i in range(n_pods)]
    node_payloads = [_observe_node_payload(i, rv) for i in range(n_nodes)]

    # -- relist baseline: full re-parse each pass ------------------------
    baseline_s = float("inf")
    for _ in range(OBSERVE_PASSES):
        t0 = time.perf_counter()
        nodes = [Node(p) for p in node_payloads]
        pods = [Pod(p) for p in pod_payloads]
        baseline_s = min(baseline_s, time.perf_counter() - t0)
    assert len(nodes) == n_nodes and len(pods) == n_pods

    # -- informer steady state: churn deltas + snapshot ------------------
    clear_parse_caches()
    pod_cache = ObjectCache("pods", parse_pod)
    node_cache = ObjectCache("nodes", parse_node)
    pod_cache.replace(pod_payloads, str(rv))
    node_cache.replace(node_payloads, str(rv))

    # Pre-build each pass's churn events (the watch stream's job, not
    # the observe path's): churn% of pods and nodes, new resourceVersion.
    churn_pods = max(1, int(n_pods * churn))
    churn_nodes = max(1, int(n_nodes * churn))
    passes = []
    for p in range(OBSERVE_PASSES):
        events = []
        for j in range(churn_pods):
            rv += 1
            i = (p * churn_pods + j) % n_pods
            events.append({"type": "MODIFIED",
                           "object": _observe_pod_payload(i, rv)})
        for j in range(churn_nodes):
            rv += 1
            i = (p * churn_nodes + j) % n_nodes
            events.append({"type": "MODIFIED",
                           "object": _observe_node_payload(i, rv)})
        passes.append(events)

    informer_s = float("inf")
    for p, events in enumerate(passes):
        t0 = time.perf_counter()
        span = (tracer.start("observe", attrs={"pass": p})
                if tracer is not None else None)
        for ev in events:
            kind = "pods" if "pod-" in ev["object"]["metadata"]["name"] \
                else "nodes"
            (pod_cache if kind == "pods" else node_cache).apply(ev)
        nodes = node_cache.snapshot()
        pods = pod_cache.snapshot()
        if tracer is not None:
            tracer.end(span, attrs={"nodes": len(nodes),
                                    "pods": len(pods)})
            if tracer.recorder is not None:
                tracer.recorder.record_pass(
                    {"pass": p, "t": time.time(),
                     "inputs": {"nodes": len(nodes), "pods": len(pods)},
                     "events": []})
        if per_pass is not None:
            per_pass(p)
        informer_s = min(informer_s, time.perf_counter() - t0)
    assert len(nodes) == n_nodes and len(pods) == n_pods
    clear_parse_caches()

    return {
        "info": "observe_path",
        "pods": n_pods, "nodes": n_nodes, "churn": churn,
        "baseline_ms": round(baseline_s * 1e3, 2),
        "informer_ms": round(informer_s * 1e3, 2),
        "speedup": round(baseline_s / informer_s, 1)
        if informer_s > 0 else None,
        "floor": OBSERVE_SPEEDUP_FLOOR,
    }


def check_observe_path() -> bool:
    """Gate: informer observe path >= OBSERVE_SPEEDUP_FLOOR x faster
    than the relist baseline at production scale."""
    info = bench_observe_path()
    print(json.dumps(info), file=sys.stderr)
    ok = (info.get("speedup") or 0) >= OBSERVE_SPEEDUP_FLOOR
    if not ok:
        print(json.dumps({"error": "observe-path regression: informer "
                          "speedup below floor", **info}), file=sys.stderr)
    return ok


# Mega-cluster observe tier (ISSUE 6): steady-state per-pass observe
# cost on the RECONCILE thread at 100k pods / 10k nodes — what a pass
# actually pulls (Unschedulable pods, per-node/per-pool free capacity)
# — indexed reads vs the snapshot-scan path (materialize the full
# parsed snapshot, scan it for pending demand, re-derive free capacity
# from every pod).  Watch-delta ingestion is the watch thread's work
# and identical for both paths, so it stays outside the timed windows;
# the indexed path's incremental CapacityView fold IS timed (it runs
# per pass).  Gate: >= 20x.
OBSERVE_SCALE_PODS = 100_000
OBSERVE_SCALE_NODES = 10_000
OBSERVE_SCALE_PASSES = 3
OBSERVE_SCALE_FLOOR = 20.0


def bench_observe_scale(n_pods: int = OBSERVE_SCALE_PODS,
                        n_nodes: int = OBSERVE_SCALE_NODES,
                        churn: float = OBSERVE_CHURN,
                        passes: int = OBSERVE_SCALE_PASSES) -> dict:
    """Indexed observe vs snapshot-scan at mega-cluster scale.

    Setup streams payload generators straight into ``replace`` —
    nothing is materialized as a Python list before the caches, so the
    tier's wall-clock measures the observe paths, not fixture
    construction (and peak memory stays one payload dict per object).
    """
    from tpu_autoscaler.engine.fitter import free_capacity
    from tpu_autoscaler.k8s.informer import (
        PENDING,
        CapacityView,
        make_node_cache,
        make_pod_cache,
    )
    from tpu_autoscaler.k8s.objects import clear_parse_caches

    clear_parse_caches()
    rv = 1
    pod_cache = make_pod_cache()
    node_cache = make_node_cache()
    # Streamed: replace() consumes the generator item by item.
    pod_cache.replace(
        (_observe_pod_payload(i, rv, n_nodes) for i in range(n_pods)),
        str(rv))
    node_cache.replace(
        (_observe_node_payload(i, rv) for i in range(n_nodes)), str(rv))
    view = CapacityView(node_cache, pod_cache)
    view.refresh()  # initial full build (cold start, untimed)

    churn_pods = max(1, int(n_pods * churn))
    churn_nodes = max(1, int(n_nodes * churn))
    scan_s = indexed_s = float("inf")
    n_pending_scan = n_pending_idx = -1
    for p in range(passes):
        # The pass's churn, applied the way the watch thread applies it
        # (identical ingestion for both paths; generated lazily).
        for j in range(churn_pods):
            rv += 1
            i = (p * churn_pods + j) % n_pods
            pod_cache.apply({"type": "MODIFIED",
                             "object": _observe_pod_payload(i, rv,
                                                            n_nodes)})
        for j in range(churn_nodes):
            rv += 1
            i = (p * churn_nodes + j) % n_nodes
            node_cache.apply({"type": "MODIFIED",
                              "object": _observe_node_payload(i, rv)})

        # -- snapshot-scan path: what a pass costs without indices ----
        t0 = time.perf_counter()
        pods = pod_cache.snapshot()
        nodes = node_cache.snapshot()
        pending = [pod for pod in pods if pod.is_unschedulable]
        free = free_capacity(nodes, pods)
        scan_s = min(scan_s, time.perf_counter() - t0)
        n_pending_scan = len(pending)

        # -- indexed path: select + incremental capacity fold ---------
        t0 = time.perf_counter()
        pending_idx = pod_cache.select("unschedulable", PENDING)
        view.refresh()
        indexed_s = min(indexed_s, time.perf_counter() - t0)
        n_pending_idx = len(pending_idx)

    # Cross-path sanity: same demand set, same free-capacity support.
    assert n_pending_scan == n_pending_idx > 0
    assert set(view.free) == set(free)
    sample = next(iter(free))
    assert abs(view.free[sample].get("cpu")
               - free[sample].get("cpu")) < 1e-6
    clear_parse_caches()
    return {
        "info": "observe_scale",
        "pods": n_pods, "nodes": n_nodes, "churn": churn,
        "pending": n_pending_idx,
        "scan_ms": round(scan_s * 1e3, 2),
        "indexed_ms": round(indexed_s * 1e3, 3),
        "speedup": round(scan_s / indexed_s, 1) if indexed_s > 0
        else None,
        "floor": OBSERVE_SCALE_FLOOR,
    }


def check_observe_scale(n_pods: int, n_nodes: int,
                        floor: float = OBSERVE_SCALE_FLOOR) -> bool:
    """Gate: indexed observe >= ``floor``x faster than snapshot-scan at
    the requested scale; records the tier in BENCH_SCALE.json."""
    info = bench_observe_scale(n_pods, n_nodes)
    info["floor"] = floor
    print(json.dumps(info), file=sys.stderr)
    _record_scale_tier("observe_scale", info)
    ok = (info.get("speedup") or 0) >= floor
    if not ok:
        print(json.dumps({"error": "observe-scale regression: indexed "
                          "speedup below floor", **info}),
              file=sys.stderr)
    return ok


# Actuation tier (ISSUE 3): one reconcile pass's actuation wall-clock at
# a busy-fleet working set — 64 in-flight provisions being polled plus 16
# new submissions — against a latency-injecting fake Cloud TPU transport
# charging one real RTT per HTTP call.  Serial baseline = the
# pre-executor behavior (blocking POSTs, per-id GET polling):
# O(in-flight + new) RTTs.  Pipelined = ActuationExecutor dispatch + ONE
# batched queuedResources LIST: ~1 RTT.  Gate: >= 10x.
ACTUATE_IN_FLIGHT = 64
ACTUATE_NEW = 16
ACTUATE_RTT_S = 0.05
ACTUATE_WORKERS = 16
ACTUATE_SPEEDUP_FLOOR = 10.0


class _LatencyQrTransport:
    """requests-shaped fake Cloud TPU API charging ``rtt_s`` of real
    wall-clock per call.  Thread-safe: executor workers call it
    concurrently (list.append is atomic; state is append-only)."""

    class _Resp:
        status_code = 200
        headers: dict = {}
        content = b"{}"

        def __init__(self, body):
            self._body = body

        def json(self):
            return self._body

    def __init__(self, rtt_s: float = 0.0):
        self.rtt_s = rtt_s
        self.calls: list = []
        self._created: list = []

    def __call__(self, method, url, headers=None, json=None, timeout=None):
        if self.rtt_s:
            time.sleep(self.rtt_s)
        self.calls.append((method, url))
        if method == "POST":
            self._created.append(url.rsplit("queuedResourceId=", 1)[-1])
            return self._Resp({})
        if "pageSize" in url:  # batched LIST
            return self._Resp({"queuedResources": [
                {"name": f"p/queuedResources/{qid}",
                 "state": {"state": "ACTIVE"}}
                for qid in list(self._created)]})
        return self._Resp({"state": {"state": "ACTIVE"}})  # per-id GET


def _make_qr_bench_actuator(batch_poll, executor=None):
    """Bench QueuedResource actuator over the latency-injecting fake
    transport (shared by the actuation tier and the tracer-overhead
    tier so they can never measure different setups)."""
    from tpu_autoscaler.actuators.gcp import GcpRest, TokenProvider
    from tpu_autoscaler.actuators.queued_resources import (
        QueuedResourceActuator,
    )

    tp = TokenProvider()
    tp._token, tp._expires_at = "bench-token", time.time() + 3600.0
    transport = _LatencyQrTransport()
    rest = GcpRest(token_provider=tp, transport=transport,
                   sleep=lambda s: None)
    act = QueuedResourceActuator(project="bench", zone="z", rest=rest,
                                 executor=executor,
                                 batch_poll=batch_poll)
    return act, transport


def _qr_bench_request(i):
    from tpu_autoscaler.engine.planner import ProvisionRequest

    return ProvisionRequest(kind="tpu-slice", shape_name="v5e-8",
                            gang_key=("job", "bench", f"g{i}"))


def _pipelined_actuation_run(tracer=None):
    """One pipelined busy-fleet actuation pass (the measurement BOTH
    the actuate tier and the tracer-overhead tier run — one loop, so
    they can never measure different workloads), optionally with the
    tracer attached the way the Controller attaches it.  Returns
    (elapsed_seconds, actuator)."""
    from tpu_autoscaler.actuators.executor import ActuationExecutor

    executor = ActuationExecutor(max_workers=ACTUATE_WORKERS)
    if tracer is not None:
        executor.set_tracer(tracer)
    act, transport = _make_qr_bench_actuator(batch_poll=True,
                                             executor=executor)
    if tracer is not None:
        act.set_tracer(tracer)
    for i in range(ACTUATE_IN_FLIGHT):
        act.provision(_qr_bench_request(i))
    executor.wait(timeout=30)
    executor.drain()                   # creates land -> pollable
    transport.rtt_s = ACTUATE_RTT_S
    t0 = time.perf_counter()
    act.poll(0.0)                      # dispatches ONE LIST
    for i in range(ACTUATE_NEW):
        act.provision(_qr_bench_request(1000 + i))  # concurrent POSTs
    executor.wait(timeout=30)
    executor.drain()                   # everything applied on the drain
    elapsed = time.perf_counter() - t0
    executor.shutdown()
    assert sum(1 for s in act.statuses()
               if s.state == "ACTIVE") == ACTUATE_IN_FLIGHT
    return elapsed, act


def _pipelined_actuation_seconds(tracer=None) -> float:
    return _pipelined_actuation_run(tracer)[0]


def bench_actuation_path() -> dict:
    make, req = _make_qr_bench_actuator, _qr_bench_request

    # -- serial baseline: blocking POSTs + per-id GET polling ------------
    act, transport = make(batch_poll=False)
    for i in range(ACTUATE_IN_FLIGHT):
        act.provision(req(i))          # RTT off while seeding in-flight
    transport.rtt_s = ACTUATE_RTT_S
    t0 = time.perf_counter()
    act.poll(0.0)                      # 64 serial GETs
    for i in range(ACTUATE_NEW):
        act.provision(req(1000 + i))   # 16 serial, blocking POSTs
    serial_s = time.perf_counter() - t0
    assert sum(1 for s in act.statuses()
               if s.state == "ACTIVE") == ACTUATE_IN_FLIGHT

    # -- pipelined: executor dispatch + ONE batched LIST (the shared
    # measurement loop — the tracer-overhead tier runs the same one)
    piped_s, act2 = _pipelined_actuation_run()
    assert len(act2._created) == ACTUATE_IN_FLIGHT + ACTUATE_NEW

    return {
        "info": "actuation_path",
        "in_flight": ACTUATE_IN_FLIGHT, "new": ACTUATE_NEW,
        "rtt_ms": ACTUATE_RTT_S * 1e3, "workers": ACTUATE_WORKERS,
        "serial_ms": round(serial_s * 1e3, 1),
        "pipelined_ms": round(piped_s * 1e3, 1),
        "speedup": round(serial_s / piped_s, 1) if piped_s > 0 else None,
        "floor": ACTUATE_SPEEDUP_FLOOR,
    }


def check_actuation_path() -> tuple[bool, dict]:
    """Gate: pipelined actuation pass >= ACTUATE_SPEEDUP_FLOOR x faster
    than the serial baseline at the busy-fleet working set."""
    info = bench_actuation_path()
    print(json.dumps(info), file=sys.stderr)
    ok = (info.get("speedup") or 0) >= ACTUATE_SPEEDUP_FLOOR
    if not ok:
        print(json.dumps({"error": "actuation-path regression: pipelined "
                          "speedup below floor", **info}), file=sys.stderr)
    return ok, info


# Tracer-overhead tier (ISSUE 5): the observe (PR-2) and actuate (PR-3)
# wins are wall-clock numbers this repo gates on; instrumentation that
# silently ate them would be a regression wearing an observability hat.
# Each tier runs twice — untraced (tracer=None at every seam: zero span
# work) and traced (recorder-backed tracer attached the way the
# Controller attaches it) — and the traced run must stay within 5%.
# GRACE absorbs sub-millisecond timer noise on the observe tier (whose
# per-pass time is ~1-3 ms); it is far below anything a real
# instrumentation regression would cost at these scales.
TRACE_OVERHEAD_FACTOR = 1.05
TRACE_OVERHEAD_GRACE_S = 0.0005
TRACE_ACTUATE_ROUNDS = 3


def bench_tracer_overhead() -> dict:
    from tpu_autoscaler.obs import FlightRecorder, Tracer

    # -- observe tier: per-pass span + decision record ------------------
    plain_obs = bench_observe_path()
    recorder = FlightRecorder()
    traced_obs = bench_observe_path(
        tracer=Tracer(recorder=recorder))
    # -- actuate tier: executor + actuator spans -------------------------
    plain_act = min(_pipelined_actuation_seconds()
                    for _ in range(TRACE_ACTUATE_ROUNDS))
    traced_act = min(
        _pipelined_actuation_seconds(
            tracer=Tracer(recorder=FlightRecorder()))
        for _ in range(TRACE_ACTUATE_ROUNDS))
    spans = recorder.dump()["counts"]["spans_recorded"]
    return {
        "info": "tracer_overhead",
        "observe_untraced_ms": plain_obs["informer_ms"],
        "observe_traced_ms": traced_obs["informer_ms"],
        "actuate_untraced_ms": round(plain_act * 1e3, 1),
        "actuate_traced_ms": round(traced_act * 1e3, 1),
        "observe_spans_recorded": spans,
        "factor": TRACE_OVERHEAD_FACTOR,
        "grace_ms": TRACE_OVERHEAD_GRACE_S * 1e3,
    }


def check_tracer_overhead() -> tuple[bool, dict]:
    """Gate: traced observe + actuate passes within 5% of untraced."""
    info = bench_tracer_overhead()
    budget_obs = (info["observe_untraced_ms"] * TRACE_OVERHEAD_FACTOR
                  + TRACE_OVERHEAD_GRACE_S * 1e3)
    budget_act = (info["actuate_untraced_ms"] * TRACE_OVERHEAD_FACTOR
                  + TRACE_OVERHEAD_GRACE_S * 1e3)
    ok = (info["observe_traced_ms"] <= budget_obs
          and info["actuate_traced_ms"] <= budget_act
          and info["observe_spans_recorded"] > 0)
    print(json.dumps(info), file=sys.stderr)
    if not ok:
        print(json.dumps({"error": "tracer overhead above the 5% gate "
                          "(instrumentation is eating the PR-2/PR-3 "
                          "wins)", **info}), file=sys.stderr)
    return ok, info


# Obs tier (ISSUE 10, docs/OBSERVABILITY.md): the time-series health
# layer may not eat the PR-5 tracing budget.  Two gates:
#
# 1. Ingest overhead: the traced+recorded+INGESTED observe pass (the
#    reconciler's per-pass obs work — metrics snapshot, TSDB fold,
#    alert evaluation over a realistic ~100-series registry) within
#    MAX(5% of the traced-only baseline, an absolute 0.5 ms marginal
#    budget).  The obs work is genuinely additive (snapshot + fold +
#    rule windows exist in no traced-only pass), so against a
#    sub-millisecond observe baseline a pure 5% bound is
#    unsatisfiable and a big flat grace would be a non-gate
#    (review-found: 1.5 ms of grace let a 3.5x regression through
#    while claiming 5%); the absolute term IS the real per-pass
#    budget at small scale, the relative term takes over once the
#    observe pass dwarfs it.
# 2. Scale: per-pass ingest cost at 10k series with 10% churn, and
#    alert-evaluation cost over the same store — alert evaluation
#    reads only its rules' series (O(rules), never O(series)), so it
#    must stay flat as series count grows.
OBS_INGEST_OVERHEAD_FACTOR = 1.05
OBS_MARGINAL_BUDGET_MS = 0.5
OBS_SCALE_SERIES = 10_000
OBS_SCALE_CHURN = 0.10
OBS_SCALE_PASSES = 20
OBS_SCALE_INGEST_MS_GATE = 25.0
OBS_SCALE_ALERT_MS_GATE = 5.0


def _obs_registry():
    """A controller-realistic metrics registry: ~100 series incl. the
    alert catalog's histogram + gauge/counter families."""
    from tpu_autoscaler.metrics import Metrics

    metrics = Metrics()
    buckets = (0.5, 1.0, 5.0, 30.0, 60.0, 120.0, 360.0, 1200.0)
    metrics.declare_histogram("scale_up_latency_seconds", buckets)
    for i in range(40):
        metrics.inc(f"bench_counter_{i}", i)
    for i in range(40):
        metrics.set_gauge(f"bench_gauge_{i}", float(i))
    metrics.set_gauge("serving_slo_attainment", 0.99)
    metrics.inc("watch_failures", 0)
    metrics.inc("wasted_prewarm_chip_seconds", 0)
    for v in (20.0, 45.0, 90.0):
        metrics.observe("scale_up_latency_seconds", v)
        metrics.observe("reconcile_seconds", 0.004)
    return metrics


def bench_obs_overhead() -> dict:
    """Traced-only (the PR 5 baseline) vs traced+ingested observe
    passes — the marginal per-pass cost of the TSDB + alert layer."""
    from tpu_autoscaler.obs import (
        AlertEngine,
        FlightRecorder,
        TimeSeriesDB,
        Tracer,
    )

    traced = bench_observe_path(tracer=Tracer(recorder=FlightRecorder()))

    metrics = _obs_registry()
    tsdb = TimeSeriesDB()
    engine = AlertEngine()
    rng = __import__("random").Random(0)

    def per_pass(p: int) -> None:
        now = float(p) * 5.0
        # Realistic churn: a dozen series move per pass.
        for _ in range(12):
            metrics.set_gauge(f"bench_gauge_{rng.randrange(40)}",
                              rng.random())
        metrics.observe("reconcile_seconds", 0.004)
        tsdb.ingest(metrics.snapshot(), now)
        engine.evaluate(tsdb, now)

    ingested = bench_observe_path(
        tracer=Tracer(recorder=FlightRecorder()), per_pass=per_pass)
    return {
        "info": "obs_overhead",
        "traced_ms": traced["informer_ms"],
        "ingested_ms": ingested["informer_ms"],
        "marginal_ms": round(ingested["informer_ms"]
                             - traced["informer_ms"], 3),
        "series": tsdb.series_count(),
        "factor": OBS_INGEST_OVERHEAD_FACTOR,
        "marginal_budget_ms": OBS_MARGINAL_BUDGET_MS,
    }


def bench_obs_scale(n_series: int = OBS_SCALE_SERIES,
                    churn: float = OBS_SCALE_CHURN,
                    passes: int = OBS_SCALE_PASSES) -> dict:
    """Per-pass TSDB ingest + alert-evaluation cost at ``n_series``
    scale with ``churn`` of them moving per pass."""
    from tpu_autoscaler.metrics import Metrics
    from tpu_autoscaler.obs import AlertEngine, TimeSeriesDB

    rng = __import__("random").Random(0)
    metrics = Metrics()
    buckets = (0.5, 1.0, 5.0, 30.0, 60.0, 120.0, 360.0, 1200.0)
    metrics.declare_histogram("scale_up_latency_seconds", buckets)
    metrics.observe("scale_up_latency_seconds", 30.0)
    metrics.observe("reconcile_seconds", 0.004)
    metrics.set_gauge("serving_slo_attainment", 0.99)
    metrics.inc("watch_failures", 0)
    metrics.inc("wasted_prewarm_chip_seconds", 0)
    for i in range(n_series):
        metrics.set_gauge(f"series_{i}", 0.0)
    tsdb = TimeSeriesDB(max_series=n_series + 64)
    engine = AlertEngine()
    moved = max(1, int(n_series * churn))
    ingest_ms, alert_ms = float("inf"), float("inf")
    for p in range(passes):
        for _ in range(moved):
            metrics.set_gauge(f"series_{rng.randrange(n_series)}",
                              rng.random())
        now = float(p) * 5.0
        t0 = time.perf_counter()
        tsdb.ingest(metrics.snapshot(), now)
        ingest_ms = min(ingest_ms, (time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        engine.evaluate(tsdb, now)
        alert_ms = min(alert_ms, (time.perf_counter() - t0) * 1e3)
    return {
        "info": "obs_scale",
        "series": tsdb.series_count(),
        "churn": churn,
        "ingest_ms": round(ingest_ms, 3),
        "alert_eval_ms": round(alert_ms, 3),
        "ingest_gate_ms": OBS_SCALE_INGEST_MS_GATE,
        "alert_gate_ms": OBS_SCALE_ALERT_MS_GATE,
    }


def check_obs(series: int = OBS_SCALE_SERIES,
              ms_gate: float = OBS_SCALE_INGEST_MS_GATE,
              alert_gate: float = OBS_SCALE_ALERT_MS_GATE
              ) -> tuple[bool, dict]:
    """Gate: the obs layer's marginal per-pass cost within
    max(5% of the traced-only baseline, 0.5 ms absolute); 10k-series
    ingest + alert evaluation under their ms gates.  Records
    BENCH_OBS.json."""
    overhead = bench_obs_overhead()
    scale = bench_obs_scale(n_series=series)
    print(json.dumps(overhead), file=sys.stderr)
    print(json.dumps(scale), file=sys.stderr)
    budget_ms = overhead["traced_ms"] + max(
        overhead["traced_ms"] * (OBS_INGEST_OVERHEAD_FACTOR - 1.0),
        OBS_MARGINAL_BUDGET_MS)
    ok = (overhead["ingested_ms"] <= budget_ms
          and overhead["series"] > 0
          and scale["ingest_ms"] <= ms_gate
          and scale["alert_eval_ms"] <= alert_gate)
    info = {"overhead": overhead, "scale": scale,
            "ingest_budget_ms": round(budget_ms, 3)}
    _record_tier("BENCH_OBS.json", "obs", {
        "traced_ms": overhead["traced_ms"],
        "ingested_ms": overhead["ingested_ms"],
        "scale_series": scale["series"],
        "scale_ingest_ms": scale["ingest_ms"],
        "scale_alert_eval_ms": scale["alert_eval_ms"],
        "gates": {"overhead_factor": OBS_INGEST_OVERHEAD_FACTOR,
                  "scale_ingest_ms": ms_gate,
                  "scale_alert_eval_ms": alert_gate},
    })
    if not ok:
        print(json.dumps({"error": "obs tier regression: TSDB ingest "
                          "or alert evaluation above gate", **info}),
              file=sys.stderr)
    return ok, info


# Cost tier (ISSUE 11, docs/COST.md): the attribution ledger may not
# eat the pass budget.  Mirrors the PR 9 adapter tier's shape: the
# GATED number is the per-pass rollup cost (close_pass — conservation
# check, metric export, frag scoring; O(states+combos), never
# O(units)); per-dirty-unit ingestion (note_unit — the adapter-ingest
# analog, charged per observation on the maintain loop the reconciler
# already owns) is gated per unit.  Both at 10k single-host replica
# units (the 10k-replica / 100k-pod fleet) with 10% of units flipping
# state per pass.  The north-star overhead budget is re-checked with
# the ledger ON (it is always on) as the end-to-end guard.
COST_LEDGER_UNITS = 10_000
COST_LEDGER_CHURN = 0.10
COST_LEDGER_PASSES = 20
COST_CLOSE_MS_GATE = 0.5
COST_NOTE_US_GATE = 25.0


def bench_cost_ledger(n_units: int = COST_LEDGER_UNITS,
                      churn: float = COST_LEDGER_CHURN,
                      passes: int = COST_LEDGER_PASSES) -> dict:
    """Ledger pass cost at fleet scale: 10k v5e-8 replica units, 10%
    state churn per pass, conservation + rebuild-oracle asserted."""
    import random

    from tpu_autoscaler.cost import CostLedger
    from tpu_autoscaler.k8s.objects import Node, Pod
    from tpu_autoscaler.k8s.payloads import tpu_host_payload
    from tpu_autoscaler.topology.catalog import (
        TPU_RESOURCE,
        shape_by_name,
    )

    shape = shape_by_name("v5e-8")
    units = []
    for i in range(n_units):
        sid = f"bench-s{i}"
        node = Node(tpu_host_payload(
            shape, sid, 0, created_at=0.0, pool=f"pool-{i % 8}",
            preemptible=(i % 4 == 0)))
        pod = Pod({
            "metadata": {"name": f"bench-p{i}", "namespace": "default",
                         "uid": f"bench-u{i}",
                         "labels": {"batch.kubernetes.io/job-name":
                                    f"bench-job{i}"}},
            "spec": {"nodeName": node.name, "containers": [
                {"resources": {"requests": {TPU_RESOURCE: "8"}}}]},
            "status": {"phase": "Running"}})
        units.append((sid, [node], [pod]))
    fleet_chips = n_units * shape.chips

    ledger = CostLedger()
    now = 0.0
    for sid, nodes, pods in units:
        ledger.note_unit(sid, nodes, pods, "busy", now)
    ledger.close_pass(now, fleet_chips)

    rng = random.Random(0)
    busy = [True] * n_units
    moved = max(1, int(n_units * churn))
    note_s = 0.0
    best_close = float("inf")
    conserved = True
    for _ in range(passes):
        now += 5.0
        idxs = rng.sample(range(n_units), moved)
        t0 = time.perf_counter()
        for i in idxs:
            sid, nodes, pods = units[i]
            busy[i] = not busy[i]
            ledger.note_unit(sid, nodes, pods if busy[i] else [],
                             "busy" if busy[i] else "idle", now)
        note_s += time.perf_counter() - t0
        t0 = time.perf_counter()
        info = ledger.close_pass(now, fleet_chips)
        best_close = min(best_close, time.perf_counter() - t0)
        conserved = conserved and info["conserved"]
    live, rebuilt = ledger.live_counts(), ledger.rebuild()
    oracle_ok = all(live[k] == {kk: vv for kk, vv in rebuilt[k].items()
                                if vv}
                    for k in live)
    return {
        "info": "cost_ledger",
        "units": n_units,
        "churn_per_pass": moved,
        "passes": passes,
        "close_ms_per_pass": round(best_close * 1e3, 4),
        "note_us_per_dirty_unit": round(
            note_s / (passes * moved) * 1e6, 2),
        "conserved_every_pass": conserved,
        "rebuild_oracle_ok": oracle_ok,
        "close_gate_ms": COST_CLOSE_MS_GATE,
        "note_gate_us": COST_NOTE_US_GATE,
    }


def check_cost(units: int = COST_LEDGER_UNITS,
               close_gate: float = COST_CLOSE_MS_GATE,
               note_gate: float = COST_NOTE_US_GATE
               ) -> tuple[bool, dict]:
    """Gate: ledger pass-close cost <= 0.5 ms at 10k units / 10%
    churn, per-dirty-unit note cost bounded, conservation + rebuild
    oracle green, and the north-star overhead budget still green with
    the ledger ON.  Records BENCH_COST.json."""
    scale = bench_cost_ledger(n_units=units)
    print(json.dumps(scale), file=sys.stderr)
    # End-to-end guard: the full controller (ledger always on) still
    # inside the overhead budget.  Warm once, gate on best CPU time of
    # three like the default north-star gate (the 10k-unit ledger
    # bench above leaves caches cold — one run is all warm-up).
    run_north_star()
    results = [run_north_star() for _ in range(3)]
    north_cpu = min(r["cpu_s"] for r in results)
    ok = (scale["close_ms_per_pass"] <= close_gate
          and scale["note_us_per_dirty_unit"] <= note_gate
          and scale["conserved_every_pass"]
          and scale["rebuild_oracle_ok"]
          and north_cpu <= OVERHEAD_BUDGET_S)
    info = {"scale": scale, "north_star_cpu_s": round(north_cpu, 4),
            "north_star_budget_s": OVERHEAD_BUDGET_S}
    _record_tier("BENCH_COST.json", "cost", {
        "close_ms_per_pass": scale["close_ms_per_pass"],
        "note_us_per_dirty_unit": scale["note_us_per_dirty_unit"],
        "units": scale["units"],
        "churn_per_pass": scale["churn_per_pass"],
        "north_star_cpu_s": round(north_cpu, 4),
        "gates": {"close_ms": close_gate, "note_us": note_gate,
                  "north_star_s": OVERHEAD_BUDGET_S},
    })
    if not ok:
        print(json.dumps({"error": "cost tier regression: ledger pass "
                          "cost, conservation, or north-star budget "
                          "above gate", **info}), file=sys.stderr)
    return ok, info


# Repack tier (ISSUE 12, docs/REPACK.md): a churn-heavy week-long
# replay — long-running gangs on on-demand supply, a daily spot-market
# cycle (idle spot slices appear, gangs riding them get preempted
# later), short churn jobs arriving around the clock — run twice
# through the REAL controller: repacker ON vs OFF.  Gated never-worse
# on BOTH steady-state chip utilization and total $-proxy, with the
# per-migration chip-seconds-saved attribution asserted on every
# completed `repack` trace; the north-star overhead budget re-checked
# with the repacker ON.  Recorded in BENCH_REPACK.json.
REPACK_SIM_SECONDS = 7 * 86400.0
REPACK_STEP_SECONDS = 60.0
REPACK_MIN_MIGRATIONS = 3


def _repack_week(repack: bool, seed: int = 0) -> dict:
    import random

    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.k8s.payloads import tpu_host_payload
    from tpu_autoscaler.repack import RepackConfig
    from tpu_autoscaler.sim import gang_pods
    from tpu_autoscaler.topology.catalog import (
        SLICE_ID_LABEL,
        shape_by_name,
    )

    rng = random.Random(seed)
    kube = FakeKube()
    actuator = FakeActuator(kube, provision_delay=90.0,
                            stagger_seconds=2.0)
    controller = Controller(kube, actuator, ControllerConfig(
        policy=PoolPolicy(spare_nodes=0),
        grace_seconds=120.0, idle_threshold_seconds=1800.0,
        drain_grace_seconds=120.0,
        enable_repack=repack,
        repack=RepackConfig() if repack else None))

    base_shapes = ("v5e-16", "v5e-32")
    live: dict[str, dict] = {}
    spot_seq = 0

    def launch(job, shape, until=None):
        names = []
        for p in gang_pods(shape, job):
            kube.add_pod(p)
            names.append(p["metadata"]["name"])
        live[job] = {"shape": shape, "names": names, "until": until}

    def add_spot(shape_name):
        nonlocal spot_seq
        spot_seq += 1
        shape = shape_by_name(shape_name)
        sid = f"spot-{spot_seq}-{shape_name}"
        for h in range(shape.hosts):
            kube.add_node(tpu_host_payload(
                shape, sid, h, created_at=t, pool="spot-pool",
                preemptible=True))

    def world_model():
        node_names = {n["metadata"]["name"] for n in kube.list_nodes()}
        for p in list(kube.list_pods()):
            if p["spec"].get("nodeName") \
                    and p["spec"]["nodeName"] not in node_names:
                kube.delete_pod(p["metadata"].get("namespace",
                                                  "default"),
                                p["metadata"]["name"])
        for job, spec in list(live.items()):
            if spec["until"] is not None and t >= spec["until"]:
                for n in spec["names"]:
                    if kube.get_pod("default", n) is not None:
                        kube.delete_pod("default", n)
                del live[job]
                continue
            fresh = {p["metadata"]["name"]: p
                     for p in gang_pods(spec["shape"], job)}
            for n in spec["names"]:
                if kube.get_pod("default", n) is None:
                    kube.add_pod(fresh[n])

    def preempt_spot_units():
        # The spot market reclaims: busy spot slices get the
        # impending-termination taint (checkpoint drain), idle ones
        # vanish outright.
        bound = {p["spec"].get("nodeName") for p in kube.list_pods()
                 if p["spec"].get("nodeName")}
        units: dict[str, list[str]] = {}
        for n in kube.list_nodes():
            labels = n["metadata"].get("labels", {})
            sid = labels.get(SLICE_ID_LABEL)
            if sid and sid.startswith("spot-"):
                units.setdefault(sid, []).append(n["metadata"]["name"])
        for sid, hosts in units.items():
            if any(h in bound for h in hosts):
                actuator.preempt_unit(sid)
            else:
                for h in hosts:
                    kube.delete_node(h)

    # The week's program, derived deterministically from the seed.
    for i, shape in enumerate(base_shapes):
        launch(f"steady-{i}", shape)
    events = []  # (t, fn)
    day = 86400.0
    for d in range(int(REPACK_SIM_SECONDS // day)):
        # Spot frees up mid-morning, is reclaimed in the evening.
        at = d * day + rng.uniform(2.0, 4.0) * 3600.0
        for shape in base_shapes:
            events.append((at, lambda s=shape: add_spot(s)))
        events.append((d * day + rng.uniform(14.0, 16.0) * 3600.0,
                       preempt_spot_units))
        # Churn: short jobs around the clock.
        for c in range(2):
            start = d * day + rng.uniform(0.0, 20.0) * 3600.0
            dur = rng.uniform(1.0, 2.0) * 3600.0
            events.append((start,
                           lambda j=f"churn-{d}-{c}", e=start + dur:
                           launch(j, "v5e-16", until=e)))
    events.sort(key=lambda e: e[0])

    t = 0.0
    util_samples = []
    while t <= REPACK_SIM_SECONDS:
        while events and events[0][0] <= t:
            events.pop(0)[1]()
        world_model()
        controller.reconcile_once(now=t)
        kube.schedule_step()
        snap = controller.metrics.snapshot()["gauges"]
        fleet = snap.get("fleet_chips", 0)
        if fleet:
            busy = (snap.get("cost_chips_serving", 0)
                    + snap.get("cost_chips_training", 0))
            util_samples.append(busy / fleet)
        t += REPACK_STEP_SECONDS

    counters = controller.metrics.snapshot()["counters"]
    dump = controller.recorder.dump(tracer=controller.tracer)
    roots = [s for s in dump["spans"] if s["name"] == "repack"
             and s["parent_id"] is None and s["end"] is not None]
    completed = [s for s in roots
                 if not s["attrs"].get("aborted")
                 and not s["attrs"].get("error")]
    return {
        "repack": repack,
        "dollar_proxy_total": round(
            counters.get("cost_dollar_proxy_total", 0.0), 2),
        "utilization": round(sum(util_samples)
                             / max(1, len(util_samples)), 4),
        "migrations_started": int(
            counters.get("repack_migrations_started", 0)),
        "migrations_completed": int(
            counters.get("repack_migrations_completed", 0)),
        "migrations_aborted": int(
            counters.get("repack_migrations_aborted", 0)),
        "chip_seconds_saved": round(
            counters.get("repack_chip_seconds_saved", 0.0), 1),
        "dollar_proxy_saved": round(
            counters.get("repack_dollar_proxy_saved", 0.0), 2),
        "conservation_violations":
            controller.cost.conservation_violations,
        "completed_traces": len(completed),
        "completed_traces_attributed": sum(
            1 for s in completed
            if "chip_seconds_saved" in s["attrs"]
            and "dollar_proxy_saved" in s["attrs"]),
    }


def bench_repack(seed: int = 0) -> dict:
    on = _repack_week(repack=True, seed=seed)
    off = _repack_week(repack=False, seed=seed)
    return {"info": "repack", "on": on, "off": off,
            "sim_seconds": REPACK_SIM_SECONDS,
            "step_seconds": REPACK_STEP_SECONDS}


def check_repack(seed: int = 0) -> tuple[bool, dict]:
    """Gate (ISSUE 12): on the churn-heavy week-long replay the
    repacker must be NEVER WORSE than no-repack on both steady-state
    chip utilization and total $-proxy, every completed `repack`
    trace must carry its chip-seconds-saved attribution, the
    conservation identity must hold through every migration, and the
    north-star overhead budget must stay green with the repacker ON.
    Records BENCH_REPACK.json."""
    info = bench_repack(seed=seed)
    on, off = info["on"], info["off"]
    print(json.dumps(info), file=sys.stderr)

    # North-star overhead with the repacker ON (the always-on repack
    # pass must fit the same budget every other subsystem honors).
    from tpu_autoscaler.repack import RepackConfig

    def north_with_repack():
        return run_north_star(
            config_extra={"enable_repack": True,
                          "repack": RepackConfig()})

    north_with_repack()
    north_cpu = min(north_with_repack()["cpu_s"] for _ in range(3))

    never_worse = (on["dollar_proxy_total"]
                   <= off["dollar_proxy_total"] * 1.001
                   and on["utilization"] >= off["utilization"] - 1e-3)
    attributed = (on["completed_traces"] >= 1
                  and on["completed_traces_attributed"]
                  == on["completed_traces"])
    ok = (never_worse and attributed
          and on["migrations_completed"] >= REPACK_MIN_MIGRATIONS
          and on["conservation_violations"] == 0
          and off["conservation_violations"] == 0
          and north_cpu <= OVERHEAD_BUDGET_S)
    result = {**info, "north_star_cpu_s": round(north_cpu, 4),
              "north_star_budget_s": OVERHEAD_BUDGET_S}
    _record_tier("BENCH_REPACK.json", "repack", {
        "dollar_proxy_on": on["dollar_proxy_total"],
        "dollar_proxy_off": off["dollar_proxy_total"],
        "utilization_on": on["utilization"],
        "utilization_off": off["utilization"],
        "migrations_completed": on["migrations_completed"],
        "migrations_aborted": on["migrations_aborted"],
        "chip_seconds_saved": on["chip_seconds_saved"],
        "dollar_proxy_saved": on["dollar_proxy_saved"],
        "north_star_cpu_s": round(north_cpu, 4),
        "gates": {"never_worse": True,
                  "min_migrations": REPACK_MIN_MIGRATIONS,
                  "north_star_s": OVERHEAD_BUDGET_S},
    })
    if not ok:
        print(json.dumps({"error": "repack tier regression: repack "
                          "worse than no-repack, missing trace "
                          "attribution, conservation broken, or "
                          "north-star budget blown", **result}),
              file=sys.stderr)
    return ok, result


# Sharded full-loop tier (ISSUE 13, docs/SHARDING.md): full
# ``reconcile_once`` passes/sec at the million-pod tier, sharded
# (--reconcile-shards 8) vs serial (0, the oracle), with decision
# parity asserted in-bench — the sharded plan must be byte-identical
# to the serial plan over the same observed world.  The fleet is 8
# (accelerator class, pool) partitions of pinned demand plus a CPU
# majority (real fleets are mostly CPU pods); the serial pass's
# superlinear terms (free-slice matching per gang, the maintain claim
# scan per unit) are what partitioning collapses.  Results merge into
# BENCH_SHARD.json; the north-star overhead budget is re-checked with
# sharding ON.
LOOP_PODS = 200_000          # CI runs --pods 1000000 --nodes 100000
LOOP_NODES = 20_000
LOOP_SHARDS = 8
LOOP_SPEEDUP_FLOOR = 2.0
LOOP_PASSES = 2             # measured passes after one warmup (the
                            # serial oracle pays ~40 s/pass at 1M)
LOOP_GANGS_PER_POOL = 384    # pending gangs per (class, pool)
LOOP_FREE_PER_POOL = 128     # idle slices per (class, pool)
_LOOP_SHAPES = ("v5p-16", "v5e-16", "v6e-16", "v4-16")  # all 4-host


def _loop_world(n_pods: int, n_nodes: int):
    """Payload generators for the loop tier's fleet.

    Returns (node_payloads_iter, pod_payloads_iter, meta).  80% of
    nodes are TPU hosts in 8 pools (4 accelerator classes x 2 pools,
    4-host slices; the first LOOP_FREE_PER_POOL slices of each pool
    idle, the rest hosting one running pod per host), 20% are CPU
    nodes padded with running CPU pods up to ``n_pods``; pending
    demand is LOOP_GANGS_PER_POOL 4-pod gangs per pool, pinned to
    their (accelerator, pool).
    """
    from tpu_autoscaler.topology.catalog import (
        ACCELERATOR_LABEL,
        POOL_LABEL,
        SLICE_ID_LABEL,
        TOPOLOGY_LABEL,
        shape_by_name,
    )

    shapes = [shape_by_name(s) for s in _LOOP_SHAPES]
    pools = [(f"lp{i}", shapes[i % len(shapes)]) for i in range(8)]
    tpu_nodes_total = (n_nodes * 4 // 5) // (8 * 4) * (8 * 4)
    per_pool_nodes = tpu_nodes_total // 8
    slices_per_pool = per_pool_nodes // 4
    free_per_pool = min(LOOP_FREE_PER_POOL, slices_per_pool // 2)
    cpu_nodes = n_nodes - tpu_nodes_total

    def tpu_node(pool, shape, s, h, rv=1):
        name = f"tpu-{pool}-s{s}-h{h}"
        return {
            "metadata": {
                "name": name, "uid": f"uid-{name}",
                "resourceVersion": str(rv),
                "labels": {
                    ACCELERATOR_LABEL: shape.accelerator_type,
                    TOPOLOGY_LABEL: shape.topology_label,
                    "node.kubernetes.io/instance-type":
                        shape.machine_type,
                    SLICE_ID_LABEL: f"{pool}-s{s}",
                    POOL_LABEL: pool,
                },
                "creationTimestamp": "2026-01-01T00:00:00Z",
            },
            "spec": {"taints": [{"key": "google.com/tpu",
                                 "value": "present",
                                 "effect": "NoSchedule"}]},
            "status": {
                "allocatable": {"cpu": "208", "memory": "400Gi",
                                "pods": "110",
                                "google.com/tpu":
                                    str(shape.chips_per_host)},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }

    def cpu_node(i, rv=1):
        return {
            "metadata": {
                "name": f"cpu-{i}", "uid": f"uid-cpu-{i}",
                "resourceVersion": str(rv),
                "labels": {"node.kubernetes.io/instance-type":
                           "e2-standard-32"},
                "creationTimestamp": "2026-01-01T00:00:00Z",
            },
            "spec": {},
            "status": {
                "allocatable": {"cpu": "32", "memory": "128Gi",
                                "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }

    def running_pod(name, node, ns, job, resources, rv=1,
                    tolerate_tpu=False):
        spec = {
            "nodeName": node,
            "containers": [{"name": "m",
                            "resources": {"requests": resources}}],
        }
        if tolerate_tpu:
            spec["tolerations"] = [{"key": "google.com/tpu",
                                    "operator": "Exists",
                                    "effect": "NoSchedule"}]
        return {
            "metadata": {"name": name, "namespace": ns,
                         "uid": f"uid-{ns}-{name}",
                         "resourceVersion": str(rv),
                         "labels": {"batch.kubernetes.io/job-name": job},
                         "creationTimestamp": "2026-01-01T00:00:00Z",
                         "ownerReferences": [{"kind": "Job",
                                              "name": job}]},
            "spec": spec,
            "status": {"phase": "Running"},
        }

    def pending_pod(pool, shape, g, m, rv=1):
        name = f"pend-{pool}-g{g}-m{m}"
        job = f"job-{pool}-g{g}"
        return {
            "metadata": {"name": name, "namespace": "default",
                         "uid": f"uid-{name}",
                         "resourceVersion": str(rv),
                         "labels": {"batch.kubernetes.io/job-name": job},
                         "creationTimestamp": "2026-01-01T00:00:00Z",
                         "ownerReferences": [{"kind": "Job",
                                              "name": job}]},
            "spec": {
                "nodeSelector": {ACCELERATOR_LABEL:
                                 shape.accelerator_type,
                                 POOL_LABEL: pool},
                "tolerations": [{"key": "google.com/tpu",
                                 "operator": "Exists",
                                 "effect": "NoSchedule"}],
                "containers": [{"name": "m", "resources": {"requests": {
                    "cpu": "1", "memory": "1Gi",
                    "google.com/tpu": str(shape.chips_per_host)}}}],
            },
            "status": {"phase": "Pending",
                       "conditions": [{"type": "PodScheduled",
                                       "status": "False",
                                       "reason": "Unschedulable"}]},
        }

    def nodes_iter():
        for pool, shape in pools:
            for s in range(slices_per_pool):
                for h in range(4):
                    yield tpu_node(pool, shape, s, h)
        for i in range(cpu_nodes):
            yield cpu_node(i)

    n_pending = 8 * LOOP_GANGS_PER_POOL * 4
    n_tpu_running = 8 * (slices_per_pool - free_per_pool) * 4
    n_cpu_pods = max(0, n_pods - n_tpu_running - n_pending)

    def pods_iter():
        for pool, shape in pools:
            for s in range(free_per_pool, slices_per_pool):
                for h in range(4):
                    yield running_pod(
                        f"tp-{pool}-s{s}-h{h}", f"tpu-{pool}-s{s}-h{h}",
                        "tpu-jobs", f"tjob-{pool}-{s}",
                        {"cpu": "2", "memory": "4Gi",
                         "google.com/tpu": str(shape.chips_per_host)},
                        tolerate_tpu=True)
        for i in range(n_cpu_pods):
            yield running_pod(f"cp-{i}", f"cpu-{i % max(1, cpu_nodes)}",
                              f"ns-{i % 20}", f"cjob-{i // 8}",
                              {"cpu": "1", "memory": "2Gi"})
        for pool, shape in pools:
            for g in range(LOOP_GANGS_PER_POOL):
                for m in range(4):
                    yield pending_pod(pool, shape, g, m)

    meta = {"tpu_nodes": tpu_nodes_total, "cpu_nodes": cpu_nodes,
            "pods": n_tpu_running + n_cpu_pods + n_pending,
            "pending_gangs": 8 * LOOP_GANGS_PER_POOL,
            "free_slices": 8 * free_per_pool}
    return nodes_iter, pods_iter, meta


class _LoopClient:
    """Client stub for the loop tier: the informer caches are pre-
    seeded, so ANY list call means a path under measurement silently
    fell back — counted and asserted zero."""

    def __init__(self):
        self.lists = 0

    def list_pods(self):
        self.lists += 1
        return []

    def list_nodes(self):
        self.lists += 1
        return []

    def patch_node(self, *a, **kw):
        pass

    def patch_pod(self, *a, **kw):
        pass

    def create_event(self, *a, **kw):
        pass


class _LoopActuator:
    """Discarding actuator: provisions are acknowledged and dropped —
    the tier measures the planning/maintain loop, and a constant
    demand set re-plans identically every pass in both modes."""

    def __init__(self):
        self.provisions = 0
        self.log = []

    def poll(self, now):
        pass

    def statuses(self):
        return []

    def provision(self, request):
        import types

        self.provisions += 1
        self.log.append((request.shape_name, request.gang_key,
                         request.count))
        return types.SimpleNamespace(
            id=f"loop-{self.provisions}", request=request,
            unit_ids=(), state="ACCEPTED", in_flight=True)

    def cancel(self, provision_id):
        pass

    def delete(self, unit_id):
        pass


def _loop_controller(shards: int, informer, columnar: bool = False):
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy

    config = ControllerConfig(
        policy=PoolPolicy(spare_nodes=0, max_total_chips=10**9),
        reconcile_shards=shards,
        # Explicit either way: the python rows must stay comparable to
        # the PR 13 baseline, the columnar rows measure ISSUE 17.
        columnar_planning=columnar,
        # Delta planning off: the tier measures FULL planning each
        # pass (the delta layer is PR 6's orthogonal win, and a
        # static world would otherwise plan zero gangs after pass 1).
        delta_planning=False,
        idle_threshold_seconds=1e12, grace_seconds=1e12,
        provision_timeout_seconds=1e12,
        unhealthy_timeout_seconds=1e12)
    client = _LoopClient()
    controller = Controller(client, _LoopActuator(), config,
                            informer=informer)
    return controller, client


def bench_loop(n_pods: int = LOOP_PODS, n_nodes: int = LOOP_NODES,
               shards: int = LOOP_SHARDS,
               passes: int = LOOP_PASSES) -> dict:
    """Full reconcile passes/sec, sharded vs serial, one shared world.

    Both controllers read the SAME pre-seeded informer caches (the
    world is static; the actuator discards, so every pass replans the
    same demand).  Decision parity is asserted in-bench: the sharded
    planner's output over the observed snapshot must be byte-identical
    to the serial planner's, and the sharded pass must actually have
    run sharded (a silent serial fallback would fake the ratio).
    Also audits the 1M-tier memory contract (ISSUE 13 satellite): the
    parse memos hold their ratcheted bound and the informer's index
    buckets stay O(store).
    """
    from tpu_autoscaler.k8s import objects as k8s_objects
    from tpu_autoscaler.k8s.gangs import group_into_gangs
    from tpu_autoscaler.k8s.informer import ClusterInformer
    from tpu_autoscaler.k8s.objects import clear_parse_caches

    clear_parse_caches()
    nodes_iter, pods_iter, meta = _loop_world(n_pods, n_nodes)
    informer_client = _LoopClient()
    informer = ClusterInformer(informer_client)
    # Streamed replace: nothing materialized before the caches.
    informer.pod_cache.replace(pods_iter(), "1")
    informer.node_cache.replace(nodes_iter(), "1")

    # -- memory-contract audit (the reserve_parse_cache ratchet and
    # index sizing were tuned at 100k; pin them at this tier) --------
    store = len(informer.pod_cache)
    limit = k8s_objects._parse_limits["pods"]
    assert limit >= 2 * store, (limit, store)
    assert len(k8s_objects._pod_cache) <= limit
    index_entries = sum(
        len(bucket)
        for index in informer.pod_cache._indices.values()
        for bucket in index.values())
    # Each pod lands in at most one bucket per index (4 pod indexes).
    assert index_entries <= len(informer.pod_cache._indexers) * store, (
        index_entries, store)

    # Four rows: the PR 13 python pair, then the ISSUE 17 columnar
    # pair over the SAME informer (the memoized ColumnarView carries
    # across modes — a static world means later refreshes are free).
    modes = (("serial", 0, False), ("sharded", shards, False),
             ("serial_columnar", 0, True),
             ("sharded_columnar", shards, True))
    results = {}
    parity = None
    mismatches = 0
    for mode, mode_shards, columnar in modes:
        controller, client = _loop_controller(mode_shards, informer,
                                              columnar=columnar)
        best = float("inf")
        for p in range(passes + 1):
            t0 = time.perf_counter()
            controller.reconcile_once(now=60.0 * (p + 1))
            dt = time.perf_counter() - t0
            if p > 0:  # first pass warms tracker/trace state
                best = min(best, dt)
        # BOTH clients: the controller's own, and the one the informer
        # would LIST through if a cache ever went unsynced mid-bench
        # (review-found: the latter was unasserted, so a fallback to
        # an empty world would have silently zeroed the measurement).
        assert client.lists == 0, "a measured path fell back to LIST"
        assert informer_client.lists == 0, \
            "the informer fell back to LIST mid-bench"
        snap = controller.metrics.snapshot()
        if columnar:
            # The fast path must actually have carried every measured
            # pass — a silent python fallback would fake the row.
            counters = snap["counters"]
            assert counters.get("columnar_passes", 0) >= passes, counters
            assert counters.get("columnar_fallbacks", 0) == 0, counters
            assert counters.get("columnar_stale", 0) == 0, counters
            nodes, pods, pending = controller._observe()
            gangs = group_into_gangs(pending)
            oracle = controller.planner.plan(gangs, nodes, pods, [])
            state = informer.columnar_view().refresh()
            assert state is not None and state.attachable(nodes, pods)
            if mode_shards:
                col_plan = controller.sharder.plan(
                    gangs, nodes, pods, [],
                    candidate_accels=controller._candidate_accels,
                    columnar=state)
                assert controller.sharder.last_info.get("mode") \
                    == "sharded", controller.sharder.last_info
            else:
                col_plan = controller.planner.plan(gangs, nodes, pods,
                                                   [], columnar=state)
            if not (oracle.requests == col_plan.requests
                    and [(g.key, r) for g, r in oracle.unsatisfiable]
                    == [(g.key, r) for g, r in col_plan.unsatisfiable]):
                mismatches += 1
        elif mode_shards:
            nodes, pods, pending = controller._observe()
            gangs = group_into_gangs(pending)
            serial_plan = controller.planner.plan(gangs, nodes, pods, [])
            shard_plan = controller.sharder.plan(
                gangs, nodes, pods, [],
                candidate_accels=controller._candidate_accels)
            assert controller.sharder.last_info.get("mode") \
                == "sharded", controller.sharder.last_info
            parity = {
                "requests_equal":
                    serial_plan.requests == shard_plan.requests,
                "unsatisfiable_equal":
                    [(g.key, r) for g, r in serial_plan.unsatisfiable]
                    == [(g.key, r) for g, r in shard_plan.unsatisfiable],
                "requests": len(serial_plan.requests),
                "sharding": dict(controller.sharder.last_info),
            }
        results[mode] = {
            "pass_s": best,
            "passes_per_sec": round(1.0 / best, 3),
            "shard_errors": snap["counters"].get("shard_errors", 0),
            "merge_conflicts": snap["counters"].get(
                "shard_merge_conflicts", 0),
        }
        controller.close()
    clear_parse_caches()

    serial_s = results["serial"]["pass_s"]
    sharded_s = results["sharded"]["pass_s"]
    serial_col_s = results["serial_columnar"]["pass_s"]
    sharded_col_s = results["sharded_columnar"]["pass_s"]
    if not (parity and parity["requests_equal"]
            and parity["unsatisfiable_equal"]):
        mismatches += 1
    return {
        "info": "loop", **meta,
        "requested_pods": n_pods, "requested_nodes": n_nodes,
        "shards": shards,
        "serial_pass_ms": round(serial_s * 1e3, 1),
        "sharded_pass_ms": round(sharded_s * 1e3, 1),
        "serial_columnar_pass_ms": round(serial_col_s * 1e3, 1),
        "sharded_columnar_pass_ms": round(sharded_col_s * 1e3, 1),
        "serial_passes_per_sec": results["serial"]["passes_per_sec"],
        "sharded_passes_per_sec": results["sharded"]["passes_per_sec"],
        "speedup": round(serial_s / sharded_s, 2) if sharded_s else None,
        "columnar_speedup": (round(serial_s / serial_col_s, 2)
                             if serial_col_s else None),
        "sharded_columnar_speedup": (round(serial_s / sharded_col_s, 2)
                                     if sharded_col_s else None),
        "decision_mismatches": mismatches,
        "shard_errors": max(r["shard_errors"] for r in results.values()),
        "merge_conflicts": max(r["merge_conflicts"]
                               for r in results.values()),
        "parity": parity,
        "floor": LOOP_SPEEDUP_FLOOR,
    }


def check_loop(n_pods: int, n_nodes: int, shards: int = LOOP_SHARDS,
               floor: float = LOOP_SPEEDUP_FLOOR) -> tuple[bool, dict]:
    """Gate: sharded full-loop passes/sec >= ``floor`` x serial at the
    requested tier with ZERO decision mismatches, shard errors and
    merge conflicts, AND the north-star overhead budget still green
    with sharding ON.  Records BENCH_SHARD.json."""
    info = bench_loop(n_pods, n_nodes, shards=shards)
    info["floor"] = floor
    print(json.dumps(info), file=sys.stderr)
    ok = ((info.get("speedup") or 0) >= floor
          and info["decision_mismatches"] == 0
          and info["shard_errors"] == 0
          and info["merge_conflicts"] == 0)
    if not ok:
        print(json.dumps({"error": "sharded loop regression: speedup "
                          "below floor or parity broken", **info}),
              file=sys.stderr)
    # North-star budget with sharding ON (prod knobs: the small-pass
    # cutoff is part of the feature) — warm once, best of 3.
    run_north_star(config_extra={"reconcile_shards": shards})
    ns = [run_north_star(config_extra={"reconcile_shards": shards})
          for _ in range(3)]
    ns_cpu = min(r["cpu_s"] for r in ns)
    ns_ok = ns_cpu <= OVERHEAD_BUDGET_S \
        and all(r["stranded"] == 0 for r in ns)
    print(json.dumps({"info": "north_star_sharded",
                      "cpu_s": round(ns_cpu, 4),
                      "budget_s": OVERHEAD_BUDGET_S,
                      "ok": ns_ok}), file=sys.stderr)
    info["north_star_sharded_cpu_s"] = round(ns_cpu, 4)
    info["north_star_sharded_ok"] = ns_ok
    _record_tier("BENCH_SHARD.json", "loop", {
        "pods": info["pods"], "nodes": info["tpu_nodes"]
        + info["cpu_nodes"], "shards": shards,
        "serial_pass_ms": info["serial_pass_ms"],
        "sharded_pass_ms": info["sharded_pass_ms"],
        "serial_columnar_pass_ms": info["serial_columnar_pass_ms"],
        "sharded_columnar_pass_ms": info["sharded_columnar_pass_ms"],
        "speedup": info["speedup"],
        "columnar_speedup": info["columnar_speedup"],
        "sharded_columnar_speedup": info["sharded_columnar_speedup"],
        "floor": floor,
        "decision_mismatches": info["decision_mismatches"],
        "merge_conflicts": info["merge_conflicts"],
        "north_star_sharded_cpu_s": info["north_star_sharded_cpu_s"],
    })
    return ok and ns_ok, info


# --------------------------------------------------------------------------
# Columnar planner tier (ISSUE 17, scripts/full_suite.sh + ci_gate.sh):
# the serial million-pod planning pass, python oracle vs the columnar
# struct-of-arrays fast path over the informer-maintained view.  Decisions
# must be byte-identical (requests, unsatisfiable, deferred, AND the
# claim scan's unit set); the columnar pass must beat the python pass by
# the floor.  Records BENCH_SCALE.json["plan_columnar"].

PLAN_COLUMNAR_PODS = 1_000_000
PLAN_COLUMNAR_NODES = 100_000
PLAN_COLUMNAR_SPEEDUP_FLOOR = 5.0
PLAN_COLUMNAR_PASSES = 2


def bench_plan_columnar(n_pods: int = PLAN_COLUMNAR_PODS,
                        n_nodes: int = PLAN_COLUMNAR_NODES,
                        passes: int = PLAN_COLUMNAR_PASSES) -> dict:
    """Serial planning pass, python vs columnar, one shared world.

    The columnar timing INCLUDES the per-pass ``ColumnarView.refresh``
    (the incremental maintenance the reconcile loop pays each pass) but
    not the initial view build — steady state, not cold start.  The
    claim scan (``shard.claimed_by_pending``) is measured alongside as
    the third ported hot loop; its unit set must match exactly.
    """
    from tpu_autoscaler.controller.shard import claimed_by_pending
    from tpu_autoscaler.k8s.gangs import group_into_gangs
    from tpu_autoscaler.k8s.informer import ClusterInformer
    from tpu_autoscaler.k8s.objects import clear_parse_caches
    from tpu_autoscaler.k8s.units import group_supply_units

    clear_parse_caches()
    nodes_iter, pods_iter, meta = _loop_world(n_pods, n_nodes)
    informer_client = _LoopClient()
    informer = ClusterInformer(informer_client)
    informer.pod_cache.replace(pods_iter(), "1")
    informer.node_cache.replace(nodes_iter(), "1")
    controller, _ = _loop_controller(0, informer, columnar=True)
    nodes, pods, pending = controller._observe()
    gangs = group_into_gangs(pending)
    view = informer.columnar_view()
    t0 = time.perf_counter()
    state = view.refresh()
    build_s = time.perf_counter() - t0
    assert state is not None and state.attachable(nodes, pods)

    def timed(fn):
        best = float("inf")
        out = fn()  # warm
        for _ in range(passes):
            t0 = time.perf_counter()
            out = fn()
            best = min(best, time.perf_counter() - t0)
        return best, out

    def columnar_plan():
        st = view.refresh()
        assert st is not None
        return controller.planner.plan(gangs, nodes, pods, [],
                                       columnar=st)

    col_s, col_plan = timed(columnar_plan)
    py_s, py_plan = timed(
        lambda: controller.planner.plan(gangs, nodes, pods, []))
    mismatches = 0
    if not (py_plan.requests == col_plan.requests
            and [(g.key, r) for g, r in py_plan.unsatisfiable]
            == [(g.key, r) for g, r in col_plan.unsatisfiable]
            and [(g.key, r) for g, r in py_plan.deferred]
            == [(g.key, r) for g, r in col_plan.deferred]):
        mismatches += 1

    # The claim scan, python vs columnar (single shot each: the python
    # side is an O(units x gangs) walk at this tier).
    units = group_supply_units(nodes)
    t0 = time.perf_counter()
    py_claim = claimed_by_pending(units, gangs, pods)
    claim_py_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    col_claim = claimed_by_pending(units, gangs, pods,
                                   columnar=view.refresh())
    claim_col_s = time.perf_counter() - t0
    if py_claim != col_claim:
        mismatches += 1
    controller.close()
    clear_parse_caches()

    return {
        "info": "plan_columnar", **meta,
        "requested_pods": n_pods, "requested_nodes": n_nodes,
        "view_build_ms": round(build_s * 1e3, 1),
        "python_plan_ms": round(py_s * 1e3, 1),
        "columnar_plan_ms": round(col_s * 1e3, 1),
        "speedup": round(py_s / col_s, 2) if col_s else None,
        "python_claim_ms": round(claim_py_s * 1e3, 1),
        "columnar_claim_ms": round(claim_col_s * 1e3, 1),
        "claim_speedup": (round(claim_py_s / claim_col_s, 2)
                          if claim_col_s else None),
        "requests": len(py_plan.requests),
        "claimed_units": len(py_claim),
        "decision_mismatches": mismatches,
    }


def check_plan_columnar(n_pods: int = PLAN_COLUMNAR_PODS,
                        n_nodes: int = PLAN_COLUMNAR_NODES,
                        floor: float = PLAN_COLUMNAR_SPEEDUP_FLOOR
                        ) -> tuple[bool, dict]:
    """Gate: columnar planning pass >= ``floor`` x the python pass at
    the requested tier with ZERO decision mismatches (plan AND claim
    scan).  Records BENCH_SCALE.json["plan_columnar"]."""
    info = bench_plan_columnar(n_pods, n_nodes)
    info["floor"] = floor
    print(json.dumps(info), file=sys.stderr)
    ok = ((info.get("speedup") or 0) >= floor
          and info["decision_mismatches"] == 0)
    if not ok:
        print(json.dumps({"error": "columnar planner regression: "
                          "speedup below floor or decisions diverged",
                          **info}), file=sys.stderr)
    _record_tier("BENCH_SCALE.json", "plan_columnar", {
        "pods": info["pods"],
        "nodes": info["tpu_nodes"] + info["cpu_nodes"],
        "python_plan_ms": info["python_plan_ms"],
        "columnar_plan_ms": info["columnar_plan_ms"],
        "speedup": info["speedup"],
        "python_claim_ms": info["python_claim_ms"],
        "columnar_claim_ms": info["columnar_claim_ms"],
        "claim_speedup": info["claim_speedup"],
        "floor": floor,
        "decision_mismatches": info["decision_mismatches"],
    })
    return ok, info


# Profiler tier (ISSUE 20) — BENCH_PROFILE.json["profile"]:
#
# - OVERHEAD: full reconcile passes with the phase-tree profiler ON
#   within 2% (+ an explicit noise grace — the loop rows are best-of-3
#   at ~1 s/pass, so run-to-run jitter dwarfs the ~10 context-manager
#   enters the profiler adds) of the SAME controller with the profiler
#   disabled, interleaved best-of over one shared 100k-pod world, and
#   the 10k-replica adapter fold hot path within the same bound;
# - CONSERVATION: every measured profiled pass satisfies the self-time
#   identity (sum of phase self-times + other == pass window within
#   tolerance) — zero violations, asserted in-bench, and the profile
#   ring stays bounded.
PROFILE_LOOP_PODS = 100_000
PROFILE_LOOP_NODES = 10_000
PROFILE_LOOP_PAIRS = 12
PROFILE_FOLD_REPLICAS = SERVING_ADAPTER_REPLICAS
PROFILE_FOLD_PASSES = 120
PROFILE_OVERHEAD_GATE = 0.02
PROFILE_NOISE_GRACE = 0.05


def bench_profile(n_pods: int = PROFILE_LOOP_PODS,
                  n_nodes: int = PROFILE_LOOP_NODES,
                  pairs: int = PROFILE_LOOP_PAIRS,
                  fold_replicas: int = PROFILE_FOLD_REPLICAS,
                  fold_passes: int = PROFILE_FOLD_PASSES) -> dict:
    """Profiler-on vs profiler-off, same-instance alternation.

    Two controllers over one world would hand the ratio their
    instance-level noise (dict layout, tracker state) — measured at
    ~±10%/pass, far above a 2% gate.  Instead ONE controller runs
    alternating on/off passes (order flipped each pair so host drift
    and pass-sequence effects hit both sides equally) and the ratio
    compares the per-side medians: the floor pass cost and its drift
    are common to both modes, so the ratio isolates the profiler's
    marginal cost.
    GC is paused over the measured passes — sporadic full collections
    are the dominant per-pass variance and land on either side at
    random, and what's gated is the profiler's marginal cost, not GC
    scheduling.  The serving tier does the same with a REAL reconcile
    pass over a churned 10k-replica adapter (Controller +
    ServingScaler, the bench_serving_adapter idiom) so the fold hook,
    pass bracketing, and per-phase metric observations are all paid
    where production pays them — inside a full pass.  Conservation is
    asserted here, in-bench, for every profiled pass.
    """
    import gc
    import numpy as np

    from tpu_autoscaler.k8s.informer import ClusterInformer
    from tpu_autoscaler.k8s.objects import clear_parse_caches
    from tpu_autoscaler.obs.profiler import RING_PASSES, PassProfiler
    from tpu_autoscaler.serving.adapter import ServingMetricsAdapter

    # -- loop tier ----------------------------------------------------
    clear_parse_caches()
    nodes_iter, pods_iter, meta = _loop_world(n_pods, n_nodes)
    informer_client = _LoopClient()
    informer = ClusterInformer(informer_client)
    informer.pod_cache.replace(pods_iter(), "1")
    informer.node_cache.replace(nodes_iter(), "1")
    controller, client = _loop_controller(0, informer, columnar=True)
    controller.reconcile_once(now=60.0)  # warm tracker/trace/view
    loop_samples: dict[str, list] = {"off": [], "on": []}
    now = 60.0
    gc.collect()
    gc.disable()
    try:
        for pair in range(pairs):
            order = ("off", "on") if pair % 2 == 0 else ("on", "off")
            for mode in order:
                controller.profiler.enabled = (mode == "on")
                now += 60.0
                t0 = time.perf_counter()
                controller.reconcile_once(now=now)
                loop_samples[mode].append(time.perf_counter() - t0)
    finally:
        gc.enable()
    controller.profiler.enabled = True
    # Median per side: pass cost drifts upward as tracker/TSDB state
    # accumulates, and the interleaving hands each side the same drift
    # — the medians cancel it where a min would just race the floor.
    best = {mode: sorted(vals)[len(vals) // 2]
            for mode, vals in loop_samples.items()}
    assert client.lists == 0, "a measured path fell back to LIST"
    assert informer_client.lists == 0, \
        "the informer fell back to LIST mid-bench"
    prof = controller.profiler
    ring = prof.ring()
    loop_violations = prof.conservation_violations
    loop_conserved = all(entry["conserved"] for entry in ring)
    # Warmup + the ``on`` half of every pair reached the ring.
    assert prof.passes_total == pairs + 1, prof.passes_total
    assert len(ring) <= RING_PASSES, len(ring)
    dominants = {entry["dominant"] for entry in ring}
    controller.close()
    clear_parse_caches()

    # -- serving-pass tier (10k-replica adapter in a REAL pass) -------
    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.serving.scaler import (
        ServingPolicy,
        ServingScaler,
    )

    rng = np.random.default_rng(0)
    pools = [f"pool-{i}" for i in range(SERVING_ADAPTER_POOLS)]
    adapter = ServingMetricsAdapter(capacity=fold_replicas)
    seqs = [1] * fold_replicas
    for i in range(fold_replicas):
        snap = _serving_snapshot(seqs[i], rng)
        adapter.ingest(f"rep-{i}", pools[i % len(pools)],
                       "tpu-v5-lite-device", "v5e-4", snap, now=0.0)
    kube = FakeKube()
    serving_controller = Controller(
        kube, FakeActuator(kube),
        ControllerConfig(policy=PoolPolicy(spare_nodes=0)),
        serving_scaler=ServingScaler(
            adapter, ServingPolicy(forecast=False, max_replicas=0)))
    serving_controller.reconcile_once(now=1000.0)  # warm
    n_churn = max(1, int(fold_replicas * SERVING_ADAPTER_CHURN))
    cursor = 0
    fold_samples: dict[str, list] = {"off": [], "on": []}
    for p in range(1, fold_passes + 1):
        now = float(1000 + p * 5)
        for _ in range(n_churn):
            i = cursor % fold_replicas
            cursor += 1
            seqs[i] += 1
            snap = _serving_snapshot(seqs[i], rng)
            adapter.ingest(f"rep-{i}", pools[i % len(pools)],
                           "tpu-v5-lite-device", "v5e-4", snap,
                           now=now)
        mode = "on" if p % 2 == 0 else "off"
        serving_controller.profiler.enabled = (mode == "on")
        t0 = time.perf_counter()
        serving_controller.reconcile_once(now=now)
        dt = time.perf_counter() - t0
        if p > 2:  # first pair warms both code paths
            fold_samples[mode].append(dt)
    serving_controller.profiler.enabled = True
    # Median, not min: at ms granularity the min is an order statistic
    # of the timer's left tail and jitters several % between runs; the
    # median of ~60 alternating samples resolves a sub-% overhead.
    fold_best = {mode: sorted(vals)[len(vals) // 2]
                 for mode, vals in fold_samples.items()}
    fold_prof = serving_controller.profiler
    fold_ring = fold_prof.ring()
    fold_violations = fold_prof.conservation_violations
    fold_conserved = all(entry["conserved"] for entry in fold_ring)
    assert any(entry["phases"].get("adapter_fold", 0.0) > 0.0
               for entry in fold_ring), \
        "the profiled serving pass never hit the fold hook"
    serving_controller.close()

    loop_ratio = (best["on"] / best["off"]
                  if best["off"] else None)
    fold_ratio = (fold_best["on"] / fold_best["off"]
                  if fold_best["off"] else None)
    return {
        "info": "profile", **meta,
        "requested_pods": n_pods, "requested_nodes": n_nodes,
        "loop_off_pass_ms": round(best["off"] * 1e3, 2),
        "loop_on_pass_ms": round(best["on"] * 1e3, 2),
        "loop_overhead_ratio": (round(loop_ratio, 4)
                                if loop_ratio else None),
        "fold_replicas": fold_replicas,
        "serving_off_pass_ms": round(fold_best["off"] * 1e3, 3),
        "serving_on_pass_ms": round(fold_best["on"] * 1e3, 3),
        "serving_overhead_ratio": (round(fold_ratio, 4)
                                   if fold_ratio else None),
        "conservation_violations": loop_violations + fold_violations,
        "ring_conserved": loop_conserved and fold_conserved,
        "ring_passes": len(ring),
        "dominant_phases": sorted(dominants),
    }


def check_profile(n_pods: int = PROFILE_LOOP_PODS,
                  n_nodes: int = PROFILE_LOOP_NODES,
                  gate: float = PROFILE_OVERHEAD_GATE,
                  grace: float = PROFILE_NOISE_GRACE
                  ) -> tuple[bool, dict]:
    """Gate the profiler tier (ISSUE 20): both overhead ratios within
    (1 + gate + grace), ZERO conservation violations across every
    profiled pass, every retained ring entry conserved, and the ring
    bounded.  Records BENCH_PROFILE.json["profile"]."""
    info = bench_profile(n_pods, n_nodes)
    bound = 1.0 + gate + grace
    info["gates"] = {"overhead_gate": gate, "noise_grace": grace}
    print(json.dumps(info), file=sys.stderr)
    perf_ok = ((info["loop_overhead_ratio"] or float("inf")) <= bound
               and (info["serving_overhead_ratio"] or float("inf"))
               <= bound)
    conserve_ok = (info["conservation_violations"] == 0
                   and info["ring_conserved"])
    ok = perf_ok and conserve_ok
    if not ok:
        print(json.dumps({
            "error": "profiler regression: overhead above the "
                     "2%+grace gate, or the self-time conservation "
                     "identity broke in-bench", **info}),
            file=sys.stderr)
    _record_tier("BENCH_PROFILE.json", "profile", info)
    return ok, info


def main(argv: list[str] | None = None) -> int:
    import argparse

    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "observe":
        # Observe tiers (scripts/full_suite.sh).  Bare: the PR-2
        # informer-vs-relist gate (sub-second).  With --pods/--nodes:
        # the mega-cluster indexed-vs-snapshot-scan tier (ISSUE 6).
        ap = argparse.ArgumentParser(prog="bench.py observe")
        ap.add_argument("--pods", type=int, default=None)
        ap.add_argument("--nodes", type=int, default=None)
        ap.add_argument("--floor", type=float,
                        default=OBSERVE_SCALE_FLOOR)
        args = ap.parse_args(argv[1:])
        if args.pods is None and args.nodes is None:
            return 0 if check_observe_path() else 1
        return 0 if check_observe_scale(
            args.pods or OBSERVE_SCALE_PODS,
            args.nodes or OBSERVE_SCALE_NODES,
            floor=args.floor) else 1
    if argv and argv[0] == "loop":
        # Sharded full-loop tier (ISSUE 13, scripts/full_suite.sh +
        # ci_gate.sh): full reconcile passes/sec sharded vs serial at
        # the million-pod tier, decision parity asserted in-bench,
        # north-star overhead budget re-checked with sharding ON;
        # records BENCH_SHARD.json.
        ap = argparse.ArgumentParser(prog="bench.py loop")
        ap.add_argument("--pods", type=int, default=LOOP_PODS)
        ap.add_argument("--nodes", type=int, default=LOOP_NODES)
        ap.add_argument("--shards", type=int, default=LOOP_SHARDS)
        ap.add_argument("--floor", type=float,
                        default=LOOP_SPEEDUP_FLOOR)
        args = ap.parse_args(argv[1:])
        ok, info = check_loop(args.pods, args.nodes,
                              shards=args.shards, floor=args.floor)
        print(json.dumps({
            "metric": "sharded_loop_speedup",
            "value": info.get("speedup"),
            "unit": "x_vs_serial",
            "vs_baseline": round((info.get("speedup") or 0)
                                 / args.floor, 2),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "plan_columnar":
        # Columnar planner tier (ISSUE 17, scripts/full_suite.sh +
        # ci_gate.sh): serial planning pass python-oracle vs columnar
        # at the million-pod tier, byte-identical decisions + claim
        # set, speedup >= floor; records BENCH_SCALE.json.
        ap = argparse.ArgumentParser(prog="bench.py plan_columnar")
        ap.add_argument("--pods", type=int, default=PLAN_COLUMNAR_PODS)
        ap.add_argument("--nodes", type=int,
                        default=PLAN_COLUMNAR_NODES)
        ap.add_argument("--floor", type=float,
                        default=PLAN_COLUMNAR_SPEEDUP_FLOOR)
        args = ap.parse_args(argv[1:])
        ok, info = check_plan_columnar(args.pods, args.nodes,
                                       floor=args.floor)
        print(json.dumps({
            "metric": "plan_columnar_speedup",
            "value": info.get("speedup"),
            "unit": "x_vs_python",
            "vs_baseline": round((info.get("speedup") or 0)
                                 / args.floor, 2),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "fit_batch":
        # Large-batch fit tier (ISSUE 6): python/kernel decision parity
        # + speedup floor at --gangs scale; records BENCH_SCALE.json.
        ap = argparse.ArgumentParser(prog="bench.py fit_batch")
        ap.add_argument("--gangs", type=int,
                        default=FIT_BATCH_SCALE_GANGS)
        ap.add_argument("--floor", type=float,
                        default=FIT_BATCH_SPEEDUP_FLOOR)
        args = ap.parse_args(argv[1:])
        ok, info = check_fit_batch(args.gangs, floor=args.floor)
        print(json.dumps({
            "metric": "fit_batch_speedup",
            "value": info.get("speedup"),
            "unit": "x_vs_python",
            "vs_baseline": round((info.get("speedup") or 0)
                                 / args.floor, 2),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "actuate":
        # Actuation tier only (scripts/full_suite.sh): ~4 s (the serial
        # baseline honestly pays its 80 RTTs).  Emits the measured
        # speedup as a BENCH-record-style metric line on stdout.
        ok, info = check_actuation_path()
        print(json.dumps({
            "metric": "actuation_pipeline_speedup",
            "value": info["speedup"],
            "unit": "x_vs_serial",
            "vs_baseline": round((info["speedup"] or 0)
                                 / ACTUATE_SPEEDUP_FLOOR, 2),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "policy":
        # Policy replay tier (ISSUE 8, scripts/full_suite.sh +
        # ci_gate.sh): recurring-trace prewarmed tail <= 0.25x
        # reactive, misprediction waste under budget; records
        # BENCH_POLICY.json.
        ok, info = check_policy()
        ratio = info["recurring"].get("tail_ratio")
        print(json.dumps({
            "metric": "policy_prewarm_tail_latency_ratio",
            "value": ratio,
            "unit": "x_vs_reactive",
            "vs_baseline": (round(POLICY_TAIL_RATIO_GATE / ratio, 2)
                            if ratio else None),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "serving":
        # Serving-aware autoscaling tier (ISSUE 9, scripts/
        # full_suite.sh + ci_gate.sh): 10k-replica adapter hot path
        # (<= 1 ms/pass, >= 10x vs scan) + the millions-of-users
        # diurnal+spike outcome replay (signal beats pod-pending
        # reactive tail SLO); records BENCH_SERVING.json.
        ap = argparse.ArgumentParser(prog="bench.py serving")
        ap.add_argument("--replicas", type=int,
                        default=SERVING_ADAPTER_REPLICAS)
        ap.add_argument("--ms-gate", type=float,
                        default=SERVING_ADAPTER_MS_GATE)
        ap.add_argument("--floor", type=float,
                        default=SERVING_AGG_SPEEDUP_FLOOR)
        ap.add_argument("--ratio-gate", type=float,
                        default=SERVING_MISS_RATIO_GATE)
        args = ap.parse_args(argv[1:])
        ok, info = check_serving(replicas=args.replicas,
                                 ms_gate=args.ms_gate,
                                 speedup_floor=args.floor,
                                 ratio_gate=args.ratio_gate)
        print(json.dumps({
            "metric": "serving_signal_tail_miss_ratio",
            "value": info["outcome"]["miss_rate_ratio"],
            "unit": "x_vs_reactive_miss_rate",
            "vs_baseline": round(
                (info["outcome"]["miss_rate_ratio"] or 0)
                / args.ratio_gate, 2),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "serving-trace":
        # Request-trace tier (ISSUE 14, scripts/full_suite.sh +
        # ci_gate.sh): data-plane tracing overhead (replica step +
        # 10k-replica exemplar fold) within 2% + noise grace at 1%
        # sampling with tail capture ON, plus the end-to-end
        # acceptance replay (spike tail fully captured gap-free,
        # exemplars resolve, tail attributed to scale-up lag);
        # records BENCH_SERVING.json["serving_trace"].
        ap = argparse.ArgumentParser(prog="bench.py serving-trace")
        ap.add_argument("--replicas", type=int,
                        default=SERVING_ADAPTER_REPLICAS)
        ap.add_argument("--gate", type=float,
                        default=TRACE_OVERHEAD_GATE)
        ap.add_argument("--grace", type=float,
                        default=TRACE_NOISE_GRACE)
        args = ap.parse_args(argv[1:])
        ok, info = check_serving_trace(replicas=args.replicas,
                                       gate=args.gate,
                                       grace=args.grace)
        print(json.dumps({
            "metric": "serving_trace_step_overhead",
            "value": info["overhead"]["step_overhead_ratio"],
            "unit": "x_vs_untraced",
            "vs_baseline": round(
                (1.0 + args.gate + args.grace)
                / max(info["overhead"]["step_overhead_ratio"], 1e-9),
                2),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "router":
        # Fleet request-router tier (ISSUE 18, scripts/full_suite.sh +
        # ci_gate.sh): routing decision <= 5 us amortized + score
        # refresh <= 1 ms/pass at 10k replicas, AND the 2.2M-user
        # equal-provisions replay where KV/queue-aware dispatch beats
        # random >= 2x on tail miss-rate and KV-occupancy variance
        # with zero lost requests; records
        # BENCH_SERVING.json["router"].
        ap = argparse.ArgumentParser(prog="bench.py router")
        ap.add_argument("--replicas", type=int,
                        default=ROUTER_BENCH_REPLICAS)
        ap.add_argument("--decision-gate", type=float,
                        default=ROUTER_DECISION_US_GATE)
        ap.add_argument("--refresh-gate", type=float,
                        default=ROUTER_REFRESH_MS_GATE)
        ap.add_argument("--miss-gate", type=float,
                        default=ROUTER_MISS_RATIO_GATE)
        ap.add_argument("--var-gate", type=float,
                        default=ROUTER_KV_VAR_RATIO_GATE)
        args = ap.parse_args(argv[1:])
        ok, info = check_router(replicas=args.replicas,
                                decision_gate=args.decision_gate,
                                refresh_gate=args.refresh_gate,
                                miss_gate=args.miss_gate,
                                var_gate=args.var_gate)
        print(json.dumps({
            "metric": "router_vs_random_tail_miss_ratio",
            "value": info["outcome"]["miss_rate_ratio"],
            "unit": "x_vs_random_miss_rate",
            "vs_baseline": round(
                (info["outcome"]["miss_rate_ratio"] or 0)
                / args.miss_gate, 2),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "obs":
        # Time-series health tier (ISSUE 10, scripts/full_suite.sh +
        # ci_gate.sh stage 9): TSDB ingest within 5% of the traced-
        # only baseline; 10k-series ingest + alert evaluation under
        # their ms gates; records BENCH_OBS.json.
        ap = argparse.ArgumentParser(prog="bench.py obs")
        ap.add_argument("--series", type=int, default=OBS_SCALE_SERIES)
        ap.add_argument("--ms-gate", type=float,
                        default=OBS_SCALE_INGEST_MS_GATE)
        ap.add_argument("--alert-gate", type=float,
                        default=OBS_SCALE_ALERT_MS_GATE)
        args = ap.parse_args(argv[1:])
        ok, info = check_obs(series=args.series, ms_gate=args.ms_gate,
                             alert_gate=args.alert_gate)
        marginal = info["overhead"]["marginal_ms"]
        budget = info["ingest_budget_ms"] - info["overhead"]["traced_ms"]
        print(json.dumps({
            "metric": "obs_marginal_pass_cost",
            "value": marginal,
            "unit": "ms_per_pass",
            # Headroom vs the marginal budget; a noise-negative
            # marginal (obs cost below the run-to-run floor) has no
            # meaningful ratio — null, never a fake "exactly at
            # budget" 1.0 (review-found).
            "vs_baseline": (round(budget / marginal, 2)
                            if marginal > 0 else None),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "cost":
        # Cost-ledger tier (ISSUE 11, scripts/full_suite.sh +
        # ci_gate.sh): pass-close <= 0.5 ms at 10k units / 10% churn,
        # per-dirty-unit note bounded, conservation + rebuild oracle
        # green, north-star budget green with the ledger ON; records
        # BENCH_COST.json.
        ap = argparse.ArgumentParser(prog="bench.py cost")
        ap.add_argument("--units", type=int, default=COST_LEDGER_UNITS)
        ap.add_argument("--close-gate", type=float,
                        default=COST_CLOSE_MS_GATE)
        ap.add_argument("--note-gate", type=float,
                        default=COST_NOTE_US_GATE)
        args = ap.parse_args(argv[1:])
        ok, info = check_cost(units=args.units,
                              close_gate=args.close_gate,
                              note_gate=args.note_gate)
        close_ms = info["scale"]["close_ms_per_pass"]
        print(json.dumps({
            "metric": "cost_ledger_close_ms_per_pass",
            "value": close_ms,
            "unit": "ms_per_pass",
            "vs_baseline": (round(args.close_gate / close_ms, 2)
                            if close_ms else None),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "repack":
        # Repack tier (ISSUE 12, scripts/full_suite.sh + ci_gate.sh):
        # week-long churn replay, repack never worse than no-repack on
        # utilization AND $-proxy, per-migration attribution on every
        # completed trace, north-star budget green with the repacker
        # ON; records BENCH_REPACK.json.
        ap = argparse.ArgumentParser(prog="bench.py repack")
        ap.add_argument("--seed", type=int, default=0)
        args = ap.parse_args(argv[1:])
        ok, info = check_repack(seed=args.seed)
        saved = info["on"]["dollar_proxy_saved"]
        off_usd = info["off"]["dollar_proxy_total"]
        print(json.dumps({
            "metric": "repack_week_dollar_proxy_saved",
            "value": saved,
            "unit": "usd_proxy",
            "vs_baseline": (round(off_usd
                                  / info["on"]["dollar_proxy_total"],
                                  3)
                            if info["on"]["dollar_proxy_total"]
                            else None),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "profile":
        # Profiler tier (ISSUE 20, scripts/full_suite.sh + ci_gate.sh):
        # phase-tree profiler overhead within 2%+grace of profiler-off
        # at the 100k-pod loop tier and the 10k-replica fold tier,
        # self-time conservation asserted in-bench; records
        # BENCH_PROFILE.json.
        ap = argparse.ArgumentParser(prog="bench.py profile")
        ap.add_argument("--pods", type=int, default=PROFILE_LOOP_PODS)
        ap.add_argument("--nodes", type=int,
                        default=PROFILE_LOOP_NODES)
        ap.add_argument("--gate", type=float,
                        default=PROFILE_OVERHEAD_GATE)
        ap.add_argument("--grace", type=float,
                        default=PROFILE_NOISE_GRACE)
        args = ap.parse_args(argv[1:])
        ok, info = check_profile(args.pods, args.nodes,
                                 gate=args.gate, grace=args.grace)
        bound = 1.0 + args.gate + args.grace
        print(json.dumps({
            "metric": "profiler_overhead_ratio",
            "value": info.get("loop_overhead_ratio"),
            "unit": "x_vs_off",
            "vs_baseline": round(
                bound / (info.get("loop_overhead_ratio") or bound), 2),
        }))
        return 0 if ok else 1
    if argv and argv[0] == "trace":
        # Tracer-overhead tier only (scripts/full_suite.sh /
        # ci_gate.sh): traced observe + actuate within 5% of untraced.
        ok, info = check_tracer_overhead()
        print(json.dumps({
            "metric": "tracer_overhead_actuate_ratio",
            "value": round(info["actuate_traced_ms"]
                           / max(info["actuate_untraced_ms"], 1e-9), 3),
            "unit": "x_vs_untraced",
            "vs_baseline": TRACE_OVERHEAD_FACTOR,
        }))
        return 0 if ok else 1
    if not check_all_configs():
        print(json.dumps({"error": "a BASELINE config failed"}),
              file=sys.stderr)
        return 1
    realistic_ok, north_star_s = check_realistic_configs()
    if not realistic_ok or north_star_s is None:
        print(json.dumps({"error": "a BASELINE config failed under "
                          "realistic actuation latency"}), file=sys.stderr)
        return 1
    if not check_observe_path():
        return 1
    if not check_actuation_path()[0]:
        return 1
    if not check_tracer_overhead()[0]:
        return 1
    # Informational (stderr: stdout is ONE metric line by contract) —
    # except decision parity, which is a hard gate.
    try:
        fit_info = bench_fit_batch()
    except Exception as e:  # noqa: BLE001 — optional path must not fail
        fit_info = {"info": "fit_batch", "error": str(e)}
    print(json.dumps(fit_info), file=sys.stderr)
    if fit_info.get("decision_mismatches"):
        print(json.dumps({"error": "native/python fit decisions diverged",
                          **fit_info}), file=sys.stderr)
        return 1
    # Warm once (imports, first-pass construction), measure best of 3 —
    # the driver wants steady-state controller overhead, not import time.
    run_north_star()
    results = [run_north_star() for _ in range(3)]
    best = min(results, key=lambda r: r["elapsed_s"])
    if best["stranded"] != 0:
        print(json.dumps({"error": "stranded chips nonzero",
                          **best}), file=sys.stderr)
        return 1
    value = best["elapsed_s"]
    # The regression gate runs on PROCESS CPU time: the controller loop
    # is single-threaded pure Python, so cpu_s measures its code path
    # regardless of what else the bench host is running — wall-clock
    # (the reported value) false-trips under a noisy neighbor (observed
    # when the gate ran right after a 400-test suite on a 1-core box).
    # Each rep is paired with an interleaved reference spin and the
    # gate reads the best cpu:spin ratio in reference seconds (see
    # NOMINAL_SPIN_S) so neither a slower bench host nor minute-scale
    # host drift false-trips the unchanged controller; a genuinely
    # regressed controller is slow in any units.  Best-of-N is
    # adaptive: a borderline reading earns more reps (each ~20 ms)
    # because only noise, never a real regression, can dip back under
    # the budget.
    def _paired_rep(res: dict) -> float:
        spin = min(_reference_spin_s(), _reference_spin_s())
        return res["cpu_s"] / max(spin, 1e-9) * NOMINAL_SPIN_S
    reps = [_paired_rep(r) for r in results]
    gate_value = min(reps)
    while gate_value > OVERHEAD_BUDGET_S and len(reps) < 9:
        reps.append(_paired_rep(run_north_star()))
        gate_value = min(reps)
    # Stated noise floor: the spread of this run's own estimator (how
    # far a typical rep sits above the best one), capped at a quarter
    # of the budget so it can absorb timer jitter but never a real
    # drift of r3's magnitude (+33%).
    ordered = sorted(reps)
    noise_floor = min(ordered[len(ordered) // 2] - ordered[0],
                      OVERHEAD_BUDGET_S / 4.0)
    trend = _overhead_trend()
    print(json.dumps({"info": "overhead_trend", "prior_rounds": trend,
                      "this_run_s": round(value, 4),
                      "this_run_cpu_s": round(gate_value, 4),
                      "noise_floor_s": round(noise_floor, 4),
                      "reps": len(reps),
                      "budget_s": OVERHEAD_BUDGET_S}), file=sys.stderr)
    if gate_value > OVERHEAD_BUDGET_S + noise_floor:
        print(json.dumps({
            "error": "controller overhead regression",
            "cpu_s": round(gate_value, 4),
            "budget_s": OVERHEAD_BUDGET_S,
            "noise_floor_s": round(noise_floor, 4),
            "prior_rounds": trend}), file=sys.stderr)
        return 1
    print(json.dumps({"info": "controller_overhead",
                      "metric": "north_star_v5p256_controller_overhead",
                      "value": round(value, 4), "unit": "s",
                      "vs_detection_bound": round(
                          REFERENCE_DETECTION_BOUND_S / value, 1)}),
          file=sys.stderr)
    # Headline: the BASELINE metric itself — end-to-end Unschedulable→
    # Running sim-time for the 256-chip north star under realistic
    # actuation latency.  vs_baseline is budget/actual against the
    # < 6 min north-star target (>1 beats it); the old headline (pure
    # controller overhead vs the reference's 60 s poll bound) stays as
    # the stderr info line above.
    print(json.dumps({
        "metric": "north_star_v5p256_realistic_scaleup",
        "value": round(north_star_s, 1),
        "unit": "s_simtime",
        "vs_baseline": round(NORTH_STAR_BUDGET_S / north_star_s, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
