"""Benchmark: north-star scale-up path, controller-side.

The BASELINE metric is "Scale-up latency (Pending→Running) + stranded-chip %
per N-chip JAX job".  Cloud VM boot time is out of the controller's hands
(and unmeasurable in a bench sandbox), so this measures the part the
framework owns: the REAL wall-clock the controller spends taking the
256-chip north-star job from Unschedulable to Running against an
instant-provisioning cloud — detection, gang grouping, shape fit, plan,
actuation, readiness barrier, latency accounting — plus the scheduler sim.

Baseline comparison: the reference's detection alone is bounded by its
--sleep poll (default ~60 s, SURVEY.md §7) and its actuation is serialized
one-ARM-deployment-at-a-time.  vs_baseline is reference_detection_bound /
measured_overhead (higher is better).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time

REFERENCE_DETECTION_BOUND_S = 60.0
# Regression gate (VERDICT r3 weak item 2): the north-star controller
# overhead drifted 12 ms (r1) → 16 ms (r3) with nothing watching it.
# The budget is generous vs the 6-min provisioning target but tight
# enough to catch the next 33% drift at bench time.
OVERHEAD_BUDGET_S = 0.020


def _overhead_trend() -> list:
    """Prior rounds' north-star overhead, oldest first, from the
    BENCH_r*.json records the driver leaves at the repo root."""
    trend = []
    here = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                parsed = json.load(f).get("parsed") or {}
            if parsed.get("metric") == "north_star_v5p256_controller_overhead":
                trend.append({"round": os.path.basename(path),
                              "value_s": parsed.get("value")})
        except (OSError, ValueError):
            continue
    return trend


def run_north_star() -> dict:
    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.sim import seed_scenario

    kube = FakeKube()
    actuator = FakeActuator(kube, provision_delay=0.0)
    controller = Controller(kube, actuator, ControllerConfig(
        policy=PoolPolicy(spare_nodes=0)))
    chips_requested = seed_scenario(kube, "v5p-256")

    def all_running() -> bool:
        pods = kube.list_pods()
        return bool(pods) and all(
            p["status"]["phase"] == "Running" for p in pods)

    t0 = time.perf_counter()
    c0 = time.process_time()
    sim_t, passes = 0.0, 0
    while not all_running():
        controller.reconcile_once(now=sim_t)
        kube.schedule_step()
        sim_t += 1.0
        passes += 1
        if passes > 100:
            raise RuntimeError("north-star scenario did not converge")
    controller.reconcile_once(now=sim_t)
    cpu = time.process_time() - c0
    elapsed = time.perf_counter() - t0

    chips = sum(
        int(float(n["status"]["allocatable"].get("google.com/tpu", 0)))
        for n in kube.list_nodes())
    return {
        "elapsed_s": elapsed,
        "cpu_s": cpu,
        "passes": passes,
        "nodes": len(kube.list_nodes()),
        "chips": chips,
        "stranded": max(0, chips - chips_requested),
    }


def check_all_configs() -> bool:
    """Gate: every BASELINE eval config must run clean (0 stranded)."""
    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.sim import seed_scenario, simulate

    ok = True
    for scenario in ("cpu", "v5e-8", "v5e-64", "2xv5p-128", "v5p-256"):
        kube = FakeKube()
        controller = Controller(kube, FakeActuator(kube), ControllerConfig(
            policy=PoolPolicy(spare_nodes=0)))
        chips = seed_scenario(kube, scenario)
        result = simulate(kube, controller, until=120.0, step=1.0,
                          scenario=scenario, chips_requested=chips)
        line_ok = result.all_running and result.stranded_chips == 0
        ok = ok and line_ok
        print(("PASS " if line_ok else "FAIL ") + result.describe(),
              file=sys.stderr)
    return ok


def bench_fit_batch(n_gangs: int = 512) -> dict:
    """Python per-gang vs native batch shape scoring (the crossover that
    justifies PoolPolicy.native_fit_threshold).  Reports any decision
    mismatch between the two paths; main() fails the bench on one."""
    from tpu_autoscaler import native
    from tpu_autoscaler.engine.fitter import (
        batch_choose_shapes,
        choose_shape_for_gang,
    )
    from tpu_autoscaler.k8s.gangs import group_into_gangs
    from tpu_autoscaler.k8s.objects import Pod
    from tpu_autoscaler.sim import _pod
    from tpu_autoscaler.topology.catalog import TPU_RESOURCE

    info: dict = {"info": "fit_batch", "gangs": n_gangs}
    if not native.available():
        info["skipped"] = "native toolchain unavailable"
        return info
    mixes = [(8, 1), (4, 4), (4, 16), (1, 3), (4, 64), (4, 32)]
    pods = []
    for i in range(n_gangs):
        per, n = mixes[i % len(mixes)]
        pods += [Pod(_pod(f"g{i}-p{j}", {TPU_RESOURCE: str(per)},
                          labels={"batch.kubernetes.io/job-name": f"g{i}"}))
                 for j in range(n)]
    gangs = group_into_gangs(pods)
    t0 = time.perf_counter()
    py = {g.key: choose_shape_for_gang(g, "v5e") for g in gangs}
    py_s = time.perf_counter() - t0
    batch_choose_shapes(gangs, "v5e")  # warm (builds/loads the library)
    t0 = time.perf_counter()
    nat = batch_choose_shapes(gangs, "v5e")
    nat_s = time.perf_counter() - t0
    mismatch = sum(
        1 for k, c in nat.items()
        if (py[k].shape.name, py[k].stranded_chips)
        != (c.shape.name, c.stranded_chips))
    info.update({
        "python_ms": round(py_s * 1e3, 2),
        "native_ms": round(nat_s * 1e3, 2),
        "speedup": round(py_s / nat_s, 1) if nat_s > 0 else None,
        "native_decided": len(nat),
        "decision_mismatches": mismatch,
    })
    return info


def main() -> int:
    if not check_all_configs():
        print(json.dumps({"error": "a BASELINE config failed"}),
              file=sys.stderr)
        return 1
    # Informational (stderr: stdout is ONE metric line by contract) —
    # except decision parity, which is a hard gate.
    try:
        fit_info = bench_fit_batch()
    except Exception as e:  # noqa: BLE001 — optional path must not fail
        fit_info = {"info": "fit_batch", "error": str(e)}
    print(json.dumps(fit_info), file=sys.stderr)
    if fit_info.get("decision_mismatches"):
        print(json.dumps({"error": "native/python fit decisions diverged",
                          **fit_info}), file=sys.stderr)
        return 1
    # Warm once (imports, first-pass construction), measure best of 3 —
    # the driver wants steady-state controller overhead, not import time.
    run_north_star()
    results = [run_north_star() for _ in range(3)]
    best = min(results, key=lambda r: r["elapsed_s"])
    if best["stranded"] != 0:
        print(json.dumps({"error": "stranded chips nonzero",
                          **best}), file=sys.stderr)
        return 1
    value = best["elapsed_s"]
    # The regression gate runs on PROCESS CPU time: the controller loop
    # is single-threaded pure Python, so cpu_s measures its code path
    # regardless of what else the bench host is running — wall-clock
    # (the reported value) false-trips under a noisy neighbor (observed
    # when the gate ran right after a 400-test suite on a 1-core box).
    gate_value = min(r["cpu_s"] for r in results)
    trend = _overhead_trend()
    print(json.dumps({"info": "overhead_trend", "prior_rounds": trend,
                      "this_run_s": round(value, 4),
                      "this_run_cpu_s": round(gate_value, 4),
                      "budget_s": OVERHEAD_BUDGET_S}), file=sys.stderr)
    if gate_value > OVERHEAD_BUDGET_S:
        print(json.dumps({
            "error": "controller overhead regression",
            "cpu_s": round(gate_value, 4),
            "budget_s": OVERHEAD_BUDGET_S,
            "prior_rounds": trend}), file=sys.stderr)
        return 1
    print(json.dumps({
        "metric": "north_star_v5p256_controller_overhead",
        "value": round(value, 4),
        "unit": "s",
        "vs_baseline": round(REFERENCE_DETECTION_BOUND_S / value, 1),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
