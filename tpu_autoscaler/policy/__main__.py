"""Policy replay CLI: ``python -m tpu_autoscaler.policy``.

Replays a traffic program through the real control loop (docs/POLICY.md
workflow) and prints the scorecard as JSON.  ``--compare`` runs the
program twice — reactive baseline vs PolicyEngine — and reports the
tail-latency ratio the bench gates on.

Exit codes: 0 ok; 2 the replay left pods pending (the policy broke
convergence — never acceptable for an advisory layer).
"""

from __future__ import annotations

import argparse
import json
import sys

from tpu_autoscaler.policy.replay import compare, make_program, replay


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_autoscaler.policy",
        description="Offline policy evaluation: replay traffic programs "
                    "and score SLO attainment vs wasted chip-seconds.")
    parser.add_argument("--program", default="recurring",
                        choices=("recurring", "diurnal", "spike",
                                 "coldstart", "regime"))
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--shape", default="v5e-16",
                        help="slice shape the traffic demands")
    parser.add_argument("--period", type=float, default=900.0,
                        help="base period seconds (default 900)")
    parser.add_argument("--cycles", type=int, default=6,
                        help="recurring arrivals (default 6)")
    parser.add_argument("--compare", action="store_true",
                        help="run reactive AND policy-enabled, report "
                             "the tail-latency ratio")
    parser.add_argument("--no-policy", action="store_true",
                        help="reactive baseline only")
    args = parser.parse_args(argv)

    program = make_program(args.program, args.seed, shape=args.shape,
                           period=args.period, cycles=args.cycles)
    if args.compare:
        card = compare(program)
        print(json.dumps(card, indent=2))
        pending = (card["reactive"]["pending_at_end"]
                   + card["policy"]["pending_at_end"])
        return 2 if pending else 0
    result = replay(program, policy=not args.no_policy)
    print(json.dumps(result.as_dict(), indent=2))
    return 2 if result.pending_at_end else 0


if __name__ == "__main__":
    sys.exit(main())
