"""PolicyEngine: the advisory decision layer the Reconciler consults.

Sits BESIDE the control loop, never inside the planner (ISSUE 8): each
reconcile pass feeds it the pass's own observation (pending gangs,
nodes, pods, actuator statuses) and gets back :class:`PolicyAdvice` —

- **advisory prewarm demand** through the planner's existing
  ``advisory_gangs`` hook: synthetic one-pod gangs keyed
  ``("prewarm", ns, name)`` naming an exact slice shape.  The planner
  stays a pure function (TAP1xx) and admits them with its normal
  free-slice / clamp / quota algebra, AFTER organic demand — a
  misprediction can never displace a real gang;
- **prewarm-hold hints**: supply units carrying an un-consumed prewarm
  are deferred from idle reclaim until the prediction's hold window
  closes (a warm slice reclaimed seconds before its predicted gang
  arrives is the worst of both worlds);
- **early-reclaim hints**: per-unit idle-threshold overrides from the
  SLO/cost tradeoff (``slo.idle_threshold_for``) — idle capacity whose
  class shows no forecast demand is returned early.

Observability is first-class (docs/OBSERVABILITY.md): when a predicted
gang lands on prewarmed supply, the engine records a ``prewarm`` span
into that gang's own scale-up trace (the provision happened BEFORE the
trace began — the span shows the latency that was hidden), and exports
forecast error, prewarm hit rate, hidden-provision seconds and wasted
chip-seconds (docs/OPERATIONS.md, TAO6xx-checked).

Threading: the engine is reconcile-thread-only state, like the rest of
the controller's bookkeeping — no locks, no threads, nothing for the
race detector to find.  Every method takes the injected pass clock.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Mapping, Sequence

from tpu_autoscaler.k8s.gangs import Gang
from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.policy.forecast import (
    EwmaForecaster,
    Forecast,
    HoltWintersForecaster,
    RecurringGangPredictor,
    merge_forecasts,
)
from tpu_autoscaler.policy.slo import (
    PrewarmDecision,
    SloPolicy,
    decide_prewarms,
    expires_at,
    idle_threshold_for,
    rolling_waste,
)
from tpu_autoscaler.units import ChipSeconds, Seconds

log = logging.getLogger(__name__)

GangKey = tuple[str, str, str]

#: Namespace synthetic prewarm gangs carry (kept out of tenant quota
#: maps on purpose: prewarms ride the global chip clamp only).
PREWARM_NAMESPACE = "tpu-autoscaler"


@dataclasses.dataclass(frozen=True)
class PolicyConfig:
    """PolicyEngine wiring (docs/POLICY.md)."""

    slo: SloPolicy = dataclasses.field(default_factory=SloPolicy)
    use_ewma: bool = True
    use_holt_winters: bool = True
    use_recurring: bool = True
    ewma_alpha: float = 0.3
    hw_bin_seconds: Seconds = 300.0
    hw_season_bins: int = 24
    recurring_max_cv: float = 0.25
    # Terminal (consumed/expired) prewarm records are kept this long
    # for /debugz introspection, then dropped (bounded state).
    retention_seconds: Seconds = 3600.0


@dataclasses.dataclass
class PolicyAdvice:
    """One pass's policy output, folded into the reconcile pass."""

    advisory: list[tuple[Gang, str]] = dataclasses.field(
        default_factory=list)
    hold_units: set[str] = dataclasses.field(default_factory=set)
    idle_overrides: dict[str, Seconds] = dataclasses.field(
        default_factory=dict)
    rejections: list[str] = dataclasses.field(default_factory=list)
    decisions: list[PrewarmDecision] = dataclasses.field(
        default_factory=list)
    digest: int = 0


@dataclasses.dataclass
class _Prewarm:
    """Lifecycle record of one prewarm (reconcile-thread-only)."""

    decision: PrewarmDecision
    gang: Gang
    created_at: Seconds
    provision_id: str | None = None
    submitted_at: Seconds | None = None
    ready_at: Seconds | None = None
    unit_ids: tuple[str, ...] = ()
    covered_unit: str | None = None     # pre-existing free slice
    consumed_by: GangKey | None = None
    consumed_at: Seconds | None = None
    expired_at: Seconds | None = None

    @property
    def key(self) -> str:
        return self.decision.key

    @property
    def terminal(self) -> bool:
        return self.consumed_by is not None or self.expired_at is not None

    @property
    def warm_units(self) -> tuple[str, ...]:
        if self.unit_ids:
            return self.unit_ids
        if self.covered_unit is not None:
            return (self.covered_unit,)
        return ()


def _probe_pod_payload(shape_name: str, name: str,
                       namespace: str) -> dict[str, Any]:
    """A pending-pod payload shaped like one member of the predicted
    gang, used ONLY as the planner's admission probe — it is never
    written to the cluster."""
    from tpu_autoscaler.topology.catalog import (
        ACCELERATOR_LABEL,
        TOPOLOGY_LABEL,
        TPU_RESOURCE,
        shape_by_name,
    )

    shape = shape_by_name(shape_name)
    return {
        "metadata": {
            "name": name, "namespace": namespace,
            "labels": {"batch.kubernetes.io/job-name": name},
            "creationTimestamp": "1970-01-01T00:00:00Z",
        },
        "spec": {
            "containers": [{"name": "main", "resources": {
                "requests": {TPU_RESOURCE: str(shape.chips_per_host)}}}],
            "nodeSelector": {ACCELERATOR_LABEL: shape.accelerator_type,
                             TOPOLOGY_LABEL: shape.topology_label},
            "tolerations": [{"key": TPU_RESOURCE, "operator": "Exists",
                             "effect": "NoSchedule"}],
        },
        "status": {"phase": "Pending", "conditions": [
            {"type": "PodScheduled", "status": "False",
             "reason": "Unschedulable"}]},
    }


class PolicyEngine:
    """Forecast -> SLO/cost -> advisory demand, one pass at a time."""

    def __init__(self, config: PolicyConfig | None = None) -> None:
        self.config = config or PolicyConfig()
        cfg = self.config
        self.ewma = EwmaForecaster(alpha=cfg.ewma_alpha)
        self.holt_winters = HoltWintersForecaster(
            bin_seconds=cfg.hw_bin_seconds,
            season_bins=cfg.hw_season_bins)
        self.recurring = RecurringGangPredictor(
            max_cv=cfg.recurring_max_cv)
        self._metrics: Any = None
        self._tracer: Any = None
        self._cost_ledger: Any = None
        self._default_generation = "v5e"
        self._prewarms: dict[str, _Prewarm] = {}
        self._seq = 0
        # Gang keys already counted as arrivals (bounded: pruned
        # against the live pod set every pass).
        self._seen_pending: set[GangKey] = set()
        # Per-class nearest active prediction, for forecast error:
        # class -> (predicted_at, forecast key).
        self._pending_prediction: dict[str, tuple[Seconds, str]] = {}
        # Rolling realized-waste events: (t, chip_seconds).
        self._waste_events: list[tuple[Seconds, ChipSeconds]] = []
        # Measured provision durations (prewarms the engine itself
        # timed), EWMA-folded over the configured estimate.
        self._provision_estimate: Seconds | None = None
        self._hits = 0
        self._expired = 0

    # -- wiring -----------------------------------------------------------

    def bind(self, metrics: Any = None, tracer: Any = None,
             default_generation: str | None = None,
             cost_ledger: Any = None) -> None:
        """Adopt the controller's metrics/tracer and planner default
        generation (the Controller calls this at construction).

        ``cost_ledger`` (ISSUE 11): when attached, realized prewarm
        waste is read from the ledger's per-unit attribution instead
        of re-derived from the decision's chips×hold estimate — ONE
        source of truth for wasted chip-seconds (docs/COST.md)."""
        if metrics is not None:
            self._metrics = metrics
        if tracer is not None:
            self._tracer = tracer
        if default_generation is not None:
            self._default_generation = default_generation
        if cost_ledger is not None:
            self._cost_ledger = cost_ledger

    def bootstrap(self, dump: Mapping[str, Any]) -> int:
        """Recover learned periods from a flight-recorder dump (a
        restarted controller re-learns from its own history instead of
        from zero).  Returns arrivals ingested."""
        return self.recurring.ingest_dump(dict(dump))

    # -- metrics helpers --------------------------------------------------

    def _inc(self, name: str, by: float = 1.0) -> None:
        if self._metrics is not None:
            self._metrics.inc(name, by)

    def _observe(self, name: str, value: float) -> None:
        if self._metrics is not None:
            self._metrics.observe(name, value)

    def set_gauge(self, name: str, value: float) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge(name, value)

    def provision_estimate(self) -> Seconds:
        """Reactive provision latency estimate: measured (EWMA over
        provisions the engine timed) when available, else configured."""
        if self._provision_estimate is not None:
            return self._provision_estimate
        return self.config.slo.provision_estimate_seconds

    def _note_provision_duration(self, seconds: Seconds) -> None:
        if seconds <= 0.0:
            return
        if self._provision_estimate is None:
            self._provision_estimate = seconds
        else:
            self._provision_estimate = (0.7 * self._provision_estimate
                                        + 0.3 * seconds)

    # -- observe side -----------------------------------------------------

    def _classify_gang(self, gang: Gang) -> tuple[str, str | None]:
        """(accelerator class, exact shape name|None) for one gang."""
        from tpu_autoscaler.engine.fitter import (
            FitError,
            choose_shape_for_gang,
        )
        from tpu_autoscaler.topology.catalog import ACCELERATOR_LABEL

        shape_name: str | None = None
        accel = gang.node_selectors.get(ACCELERATOR_LABEL)
        try:
            choice = choose_shape_for_gang(gang, self._default_generation)
            shape_name = choice.shape.name
            if accel is None:
                accel = choice.shape.accelerator_type
        except FitError:
            pass
        return accel or "unknown", shape_name

    def observe(self, gangs: Sequence[Gang], nodes: Sequence[Node],
                pods: Sequence[Pod], statuses: Sequence[Any],
                now: Seconds,
                gang_traces: Mapping[GangKey, Any] | None = None
                ) -> None:
        """Feed one pass's world into the forecasters and advance every
        prewarm's lifecycle (provisioned -> ready -> consumed|expired).
        Call BEFORE the pass's latency tracking so a consumption span
        lands in the gang's still-open trace."""
        cfg = self.config
        # ---- arrivals: first-pending TPU gangs --------------------------
        live_keys = {p.gang_key for p in pods}
        self._seen_pending &= live_keys
        for gang in gangs:
            if not gang.requests_tpu or gang.key in self._seen_pending:
                continue
            if gang.key and gang.key[0] == "prewarm":
                continue  # never learn from our own synthetic demand
            self._seen_pending.add(gang.key)
            accel, shape_name = self._classify_gang(gang)
            chips = gang.tpu_chips
            if cfg.use_ewma:
                self.ewma.note(accel, shape_name, now, chips)
            if cfg.use_holt_winters:
                self.holt_winters.note(accel, shape_name, now, chips)
            if cfg.use_recurring and shape_name is not None:
                self.recurring.note(gang.name, accel, shape_name, now)
            predicted = self._pending_prediction.pop(accel, None)
            if predicted is not None:
                self._observe("forecast_error_seconds",
                                     abs(now - predicted[0]))
        if cfg.use_holt_winters:
            self.holt_winters.observe_silence(now)

        # ---- prewarm lifecycle off the actuator statuses ----------------
        by_key: dict[GangKey, Any] = {}
        for status in statuses:
            key = getattr(status.request, "gang_key", None)
            if key is not None and key and key[0] == "prewarm":
                by_key[key] = status
        for pw in self._prewarms.values():
            if pw.terminal:
                continue
            status = by_key.get(pw.gang.key)
            if status is None:
                continue
            if pw.provision_id != status.id:
                pw.provision_id = status.id
                pw.submitted_at = now if pw.submitted_at is None \
                    else pw.submitted_at
            if status.state == "ACTIVE" and pw.ready_at is None:
                pw.ready_at = now
                pw.unit_ids = tuple(status.unit_ids)
                if pw.submitted_at is not None:
                    self._note_provision_duration(now - pw.submitted_at)
            elif status.state == "FAILED":
                # Advisory re-emission resumes; the reconciler's
                # per-key backoff paces the retry.
                pw.provision_id = None

        # ---- consumption: predicted gang runs on warm supply ------------
        slice_of: dict[str, str] = {}
        for n in nodes:
            if n.is_tpu and n.slice_id:
                slice_of[n.name] = n.slice_id
        warm_owner: dict[str, _Prewarm] = {}
        for pw in self._prewarms.values():
            if pw.terminal:
                continue
            for unit in pw.warm_units:
                warm_owner.setdefault(unit, pw)
        if warm_owner:
            for p in pods:
                if not p.is_workload or p.node_name is None \
                        or p.phase != "Running":
                    continue
                sid = slice_of.get(p.node_name)
                pw = warm_owner.get(sid) if sid is not None else None
                if pw is None or pw.terminal \
                        or p.gang_key is None \
                        or p.gang_key[0] == "prewarm":
                    continue
                self._consume(pw, p.gang_key, now, gang_traces)

        # ---- expiry: the hold window closed unconsumed ------------------
        for pw in self._prewarms.values():
            if pw.terminal:
                continue
            if now >= expires_at(pw.decision.predicted_at, cfg.slo):
                pw.expired_at = now
                self._expired += 1
                self._inc("prewarm_expired")
                # Realized waste: the cost ledger's attributed prewarm
                # chip-seconds for the warm units when attached (one
                # source of truth — ISSUE 11); the decision-based
                # chips×warm-window estimate only when the ledger
                # never saw the units (no controller, or the units
                # vanished before expiry).
                waste = None
                if self._cost_ledger is not None and pw.warm_units:
                    waste = self._cost_ledger.accrued_chip_seconds(
                        pw.warm_units, now, state="prewarm")
                if waste is None:
                    warm_since = pw.ready_at if pw.ready_at is not None \
                        else (pw.created_at if pw.covered_unit
                              else None)
                    if warm_since is not None:
                        waste = pw.decision.chips * max(
                            0.0, now - warm_since)
                if waste:
                    self._inc("wasted_prewarm_chip_seconds", waste)
                    self._waste_events.append((now, waste))
                log.info("prewarm %s expired unconsumed (%s)",
                         pw.key, pw.decision.shape_name)

        # ---- bounded state ----------------------------------------------
        horizon = now - cfg.retention_seconds
        for key in [k for k, pw in self._prewarms.items()
                    if pw.terminal
                    and (pw.consumed_at or pw.expired_at or 0.0)
                    < horizon]:
            del self._prewarms[key]
        self._waste_events, _ = rolling_waste(
            self._waste_events, now, cfg.slo.waste_window_seconds)
        total = self._hits + self._expired
        if total:
            self.set_gauge("prewarm_hit_rate", self._hits / total)

    def _consume(self, pw: _Prewarm, consumer: GangKey, now: Seconds,
                 gang_traces: Mapping[GangKey, Any] | None) -> None:
        pw.consumed_by = consumer
        pw.consumed_at = now
        self._hits += 1
        self._inc("prewarm_hits")
        covered = pw.provision_id is None or pw.ready_at is None
        if not covered:
            # Only a prewarm that actually PROVISIONED hid latency; a
            # covered one (an adopted free slice the hold protected)
            # saved a reclaim, not a provision — claiming the estimate
            # would inflate the operator-facing hidden-latency series
            # whenever free capacity already existed.
            hidden = (pw.ready_at or now) - (pw.submitted_at or now)
            self._observe("hidden_provision_seconds", hidden)
        else:
            hidden = 0.0
        log.info("prewarm %s consumed by %s (%s)",
                 pw.key, consumer,
                 "held free slice" if covered
                 else f"hid {hidden:.0f}s of provision")
        root = (gang_traces or {}).get(consumer)
        if root is not None and self._tracer is not None:
            # The provision ran BEFORE this gang's trace was minted:
            # the span records the latency that never reached the
            # critical path (docs/OBSERVABILITY.md prewarm model).
            start = pw.submitted_at if pw.submitted_at is not None \
                else pw.created_at
            attrs = {"shape": pw.decision.shape_name,
                     "forecast": pw.key,
                     "provision_id": pw.provision_id,
                     "covered": covered,
                     "hidden_s": round(hidden, 3),
                     "confidence": round(pw.decision.confidence, 3)}
            if self._cost_ledger is not None and pw.warm_units:
                # The prewarm's bill (ISSUE 11): chip-seconds the
                # slice sat warm before this gang consumed it — the
                # cost the hidden latency was bought with.
                warm_cs = self._cost_ledger.accrued_chip_seconds(
                    pw.warm_units, now, state="prewarm")
                if warm_cs:
                    attrs["cost_chip_seconds"] = round(warm_cs, 3)
            self._tracer.record(
                "prewarm", start=start,
                end=pw.ready_at if pw.ready_at is not None else now,
                parent=root, attrs=attrs)

    # -- advise side ------------------------------------------------------

    def forecasts(self, now: Seconds) -> list[Forecast]:
        cfg = self.config
        streams: list[list[Forecast]] = []
        if cfg.use_recurring:
            streams.append(self.recurring.forecasts(now))
        if cfg.use_holt_winters:
            streams.append(self.holt_winters.forecasts(now))
        if cfg.use_ewma:
            streams.append(self.ewma.forecasts(now))
        return merge_forecasts(streams)

    def _free_slices_by_shape(self, nodes: Sequence[Node],
                              pods: Sequence[Pod]) -> dict[str, str]:
        """Map free slice id -> its catalog shape name."""
        from tpu_autoscaler.engine.planner import _free_slices
        from tpu_autoscaler.topology.catalog import shape_from_selectors

        out: dict[str, str] = {}
        for sid, members in _free_slices(list(nodes), list(pods)).items():
            try:
                shape = shape_from_selectors(members[0].labels)
            except KeyError:
                continue
            if shape is not None and len(members) == shape.hosts:
                out[sid] = shape.name
        return out

    def advise(self, nodes: Sequence[Node], pods: Sequence[Pod],
               now: Seconds, *, base_idle_threshold: Seconds
               ) -> PolicyAdvice:
        """Turn the current forecast set into this pass's advice."""
        cfg = self.config
        slo = cfg.slo
        advice = PolicyAdvice()
        forecasts = self.forecasts(now)

        # Forecast-error bookkeeping: remember the nearest active
        # prediction per class; the next arrival scores it.
        for f in forecasts:
            if f.confidence < slo.min_confidence:
                continue
            cur = self._pending_prediction.get(f.accel_class)
            if cur is None or f.at < cur[0]:
                self._pending_prediction[f.accel_class] = (f.at, f.key)

        active = [pw for pw in self._prewarms.values() if not pw.terminal]
        committed = sum(pw.decision.expected_waste_chip_seconds
                        for pw in active)
        _, realized = rolling_waste(self._waste_events, now,
                                    slo.waste_window_seconds)
        # Belt over the key-level dedup: one predicted event must never
        # hold two prewarms — drop forecasts whose shape already has an
        # active prewarm with an overlapping predicted window (keys can
        # legitimately differ across forecaster sources).
        def _duplicates_active(f: Forecast) -> bool:
            return any(
                pw.decision.shape_name == f.shape_name
                and abs(pw.decision.predicted_at - f.at)
                < slo.prewarm_hold_seconds
                for pw in active)

        forecasts_to_gate = [f for f in forecasts
                             if not _duplicates_active(f)]
        decisions, rejections = decide_prewarms(
            forecasts_to_gate, now, policy=slo,
            provision_estimate=self.provision_estimate(),
            waste_spent_chip_seconds=committed + realized,
            active_prewarms=len(active),
            active_keys=frozenset(pw.key for pw in active))
        advice.rejections = rejections
        advice.decisions = decisions

        for d in decisions:
            self._seq += 1
            name = f"prewarm-{self._seq}-{d.shape_name}"
            gang = Gang(
                key=("prewarm", PREWARM_NAMESPACE, name),
                pods=[Pod(_probe_pod_payload(d.shape_name, name,
                                             PREWARM_NAMESPACE))])
            pw = _Prewarm(decision=d, gang=gang, created_at=now)
            self._prewarms[pw.key] = pw
            active.append(pw)
            self._inc("prewarm_decisions")
            log.info("prewarm decided: %s (%s)", d.key, d.reason)

        # A free slice of exactly the predicted shape covers a prewarm
        # without provisioning: hold it for the prediction instead.
        free_by_shape = self._free_slices_by_shape(nodes, pods) \
            if active else {}
        covered_units = {pw.covered_unit for pw in active
                         if pw.covered_unit is not None}
        for pw in active:
            if pw.unit_ids or pw.covered_unit is not None:
                continue
            for sid, shape in sorted(free_by_shape.items()):
                if shape == pw.decision.shape_name \
                        and sid not in covered_units:
                    pw.covered_unit = sid
                    covered_units.add(sid)
                    break

        for pw in active:
            if pw.covered_unit is None and not pw.unit_ids:
                advice.advisory.append((pw.gang,
                                        pw.decision.shape_name))
            advice.hold_units.update(pw.warm_units)

        # ---- early-reclaim / hold idle-threshold overrides --------------
        next_by_class: dict[str, tuple[float, float]] = {}
        for f in forecasts:
            cur = next_by_class.get(f.accel_class)
            if cur is None or f.at < cur[0]:
                next_by_class[f.accel_class] = (f.at, f.confidence)
        idle_units = self._idle_tpu_units(nodes, pods)
        for unit_id, accel in sorted(idle_units.items()):
            if unit_id in advice.hold_units:
                continue  # the prewarm hold already protects it
            nxt = next_by_class.get(accel)
            override = idle_threshold_for(
                accel, now, policy=slo,
                base_threshold=base_idle_threshold,
                provision_estimate=self.provision_estimate(),
                next_arrival_at=nxt[0] if nxt else None,
                confidence=nxt[1] if nxt else 0.0)
            if override != base_idle_threshold:
                advice.idle_overrides[unit_id] = override

        advice.digest = hash((
            tuple(sorted((g.key, s) for g, s in advice.advisory)),
            tuple(sorted(advice.hold_units)),
            tuple(sorted(advice.idle_overrides.items())),
        ))
        self.set_gauge("policy_advisory_gangs", len(advice.advisory))
        return advice

    def _idle_tpu_units(self, nodes: Sequence[Node],
                        pods: Sequence[Pod]) -> dict[str, str]:
        """Workload-free TPU units -> accelerator class."""
        from tpu_autoscaler.k8s.units import group_supply_units

        busy: set[str] = set()
        for p in pods:
            if p.node_name and p.is_workload \
                    and p.phase in ("Pending", "Running"):
                busy.add(p.node_name)
        out: dict[str, str] = {}
        for unit_id, unit_nodes in group_supply_units(
                list(nodes)).items():
            if not unit_nodes[0].is_tpu:
                continue
            if any(n.name in busy for n in unit_nodes):
                continue
            accel = unit_nodes[0].tpu_accelerator
            if accel:
                out[unit_id] = accel
        return out

    # -- introspection ----------------------------------------------------

    def debug_state(self) -> dict[str, Any]:
        """JSON-able prewarm table for /debugz.

        Called from the /debugz HTTP thread while the reconcile thread
        mutates ``_prewarms`` lock-free — copy with a bounded retry
        (the ``debug_dump`` supply-guard pattern): a resize mid-copy
        raises RuntimeError, and a diagnostic endpoint must degrade,
        not 500, exactly when the controller is busy."""
        for _ in range(5):
            try:
                prewarms = {
                    pw.key: {
                        "shape": pw.decision.shape_name,
                        "predicted_at": pw.decision.predicted_at,
                        "confidence": pw.decision.confidence,
                        "provision_id": pw.provision_id,
                        "units": list(pw.warm_units),
                        "consumed_by": ("/".join(str(x) for x in
                                                 pw.consumed_by)
                                        if pw.consumed_by else None),
                        "expired_at": pw.expired_at,
                    } for pw in list(self._prewarms.values())}
                break
            except RuntimeError:  # mutated mid-copy; retry
                continue
        else:
            prewarms = {"unavailable": "mutating"}
        return {
            "provision_estimate_s": round(self.provision_estimate(), 3),
            "prewarms": prewarms,
        }
