"""Shared traffic-shape primitives (ISSUE 9 dedupe).

The diurnal and spike patterns used to live twice: once inside
``policy/replay.py``'s gang-level ``make_program`` and (nearly) again
in the serving bench's request-level generator.  Two copies of "what a
day of traffic looks like" drift apart; this module is the single
definition both consume:

- ``diurnal_phase_rate`` — the day-shape: a busy first half and a
  quiet second half (optionally with linear shoulders for
  request-level intensity; the gang-level program keeps the hard
  split so historical seeds reproduce exactly);
- ``diurnal_arrival_times`` — the gang-level arrival sampler
  ``make_program("diurnal")`` uses (draw-for-draw identical to the
  pre-ISSUE-9 loop, so seeded programs are unchanged);
- ``spike_times`` — the unforecastable-burst schedule shared by
  ``make_program("spike")`` and the serving replay's spike overlay;
- ``request_rate`` — request-level intensity (requests/second) for the
  millions-of-users serving replay: the same day-shape scaled to an
  rps band, with multiplicative spike windows on top.

Everything is a pure function of its arguments (injected rng included)
— same determinism contract as the rest of the policy package.
"""

from __future__ import annotations

import random
from typing import Iterable, Sequence

#: Fraction of the day that is the busy phase.
DIURNAL_PEAK_FRACTION = 0.5

#: Gang-level per-step arrival probabilities in the busy/quiet phases
#: (the original ``make_program("diurnal")`` constants).
DIURNAL_HIGH_RATE = 0.9
DIURNAL_LOW_RATE = 0.1

#: Jitter added to each gang-level diurnal arrival.
ARRIVAL_JITTER_S = 30.0

#: Gang-level spike schedule: burst size and spacing.
SPIKE_COUNT = 3
SPIKE_SPACING_S = 10.0


def diurnal_phase_rate(phase: float, high: float = DIURNAL_HIGH_RATE,
                       low: float = DIURNAL_LOW_RATE,
                       ramp_fraction: float = 0.0) -> float:
    """Rate at ``phase`` in [0, 1) of the day: ``high`` through the
    busy first half, ``low`` after.  ``ramp_fraction`` > 0 replaces
    the hard edges with linear shoulders of that width (request-level
    traffic ramps; job-level traffic switches) — the ramp is exactly
    the surface predictive scaling wins on."""
    phase = phase % 1.0
    split = DIURNAL_PEAK_FRACTION
    if ramp_fraction <= 0.0:
        return high if phase < split else low
    r = min(ramp_fraction, split / 2.0)
    # Shoulders: rise over [1-r, 1)->[0, r) wrap, fall over
    # [split-r, split+r).
    if phase < r:
        f = 0.5 + 0.5 * (phase / r)
        return low + (high - low) * f
    if phase < split - r:
        return high
    if phase < split + r:
        f = 1.0 - (phase - (split - r)) / (2.0 * r)
        return low + (high - low) * f
    if phase < 1.0 - r:
        return low
    f = 0.5 * (phase - (1.0 - r)) / r
    return low + (high - low) * f


def diurnal_arrival_times(rng: random.Random, day: float, step: float,
                          days: int = 2,
                          jitter: float = ARRIVAL_JITTER_S
                          ) -> list[float]:
    """Gang-level diurnal arrival times over ``days`` repeating days.

    Draw-for-draw identical to the pre-ISSUE-9 ``make_program`` loop
    (one ``rng.random()`` per step, one ``rng.uniform`` per hit), so
    every historical seed compiles to the same program.
    """
    out: list[float] = []
    t = 0.0
    while t < day * days:
        phase = (t % day) / day
        if rng.random() < diurnal_phase_rate(phase):
            out.append(t + rng.uniform(0.0, jitter))
        t += step
    return out


def spike_times(start: float, count: int = SPIKE_COUNT,
                spacing: float = SPIKE_SPACING_S) -> list[float]:
    """The unforecastable burst: ``count`` arrivals from ``start`` at
    fixed ``spacing`` (quiet before, nothing after)."""
    return [start + i * spacing for i in range(count)]


def request_rate(t: float, day: float, peak_rps: float,
                 trough_rps: float, ramp_fraction: float = 0.15,
                 spikes: Sequence[tuple[float, float, float]] = ()
                 ) -> float:
    """Request-level intensity (requests/second) at sim-time ``t``:
    the shared day-shape scaled to [trough_rps, peak_rps], times any
    open spike window's multiplier.  ``spikes``: (start, duration,
    multiplier) triples."""
    rate = diurnal_phase_rate((t % day) / day, high=peak_rps,
                              low=trough_rps,
                              ramp_fraction=ramp_fraction)
    for start, duration, mult in spikes:
        if start <= t < start + duration:
            rate *= mult
    return rate


def arrivals_in_step(rng, rate: float, dt: float) -> int:
    """Poisson arrival count for one sim step (``rng`` is a
    ``numpy.random.Generator``; rate in 1/s)."""
    lam = max(0.0, rate * dt)
    if lam <= 0.0:
        return 0
    return int(rng.poisson(lam))


def total_requests(day: float, peak_rps: float, trough_rps: float,
                   days: int = 2, ramp_fraction: float = 0.15,
                   spikes: Iterable[tuple[float, float, float]] = (),
                   step: float = 5.0) -> float:
    """Expected request volume of a replay (reporting: the
    "millions of users" derivation in BENCH_SERVING.json)."""
    total = 0.0
    t = 0.0
    while t < day * days:
        total += request_rate(t, day, peak_rps, trough_rps,
                              ramp_fraction, tuple(spikes)) * step
        t += step
    return total
