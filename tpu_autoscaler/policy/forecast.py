"""Demand forecasters for the predictive scaling policy (ISSUE 8).

The planner is reactive: a gang must go Unschedulable before
provisioning starts, and the PR-5 phase traces show provision dominates
the north-star latency (216 s of the 220 s v5p-256 realistic scale-up).
Prediction is the only remaining lever: these forecasters turn the
arrival history the controller already observes into *explicit,
confidence-weighted* predictions of future demand, which ``slo.py``
converts into prewarm decisions and ``engine.py`` feeds to the pure
planner as advisory demand.

Three models, cheapest first (NimbusGuard / SLO-driven-autoscaling
lineage from PAPERS.md, without the RL machinery — the repo's
deterministic replay harness is the evaluation loop):

- :class:`EwmaForecaster` — exponentially-weighted inter-arrival model
  per accelerator class.  Confidence is ``1 - cv`` (coefficient of
  variation of the inter-arrival gap): regular traffic forecasts
  sharply, Poisson-ish traffic honestly reports low confidence.
- :class:`HoltWintersForecaster` — additive Holt-Winters over binned
  per-class chip-arrival counts with a fixed season length (diurnal
  traffic).  Confidence comes from the in-sample one-step error
  relative to the mean demand level, ramped by seasons observed.
- :class:`RecurringGangPredictor` — mines scale-up records (live
  arrivals, or a flight-recorder dump via ``ingest_dump``) for gangs
  whose *base name* (trailing run counters stripped) re-arrives on a
  stable period: the nightly-training-job pattern.  This is the only
  model precise enough to name an exact slice shape, so it is the one
  that drives shape-exact prewarms.

Everything here is pure computation over injected timestamps — no
clocks, no randomness, no I/O (the module sits in the purity checker's
TAP1xx scope next to the planner it advises).  All mutation is
instance-local; callers (the reconcile thread) own the objects.
"""

from __future__ import annotations

import dataclasses
import math
import re
from collections import deque
from typing import Any, Iterable

from tpu_autoscaler.units import Chips, Fraction, Seconds

#: Trailing run counters stripped to find a recurring gang's identity:
#: ``nightly-train-17`` and ``nightly-train-18`` are the same job.
_RUN_SUFFIX = re.compile(r"[-_]?\d+$")

#: Minimum observations before a model reports a forecast at all.
MIN_OBSERVATIONS = 3


def base_name(name: str) -> str:
    """Recurring-job identity: the gang name with trailing run
    counters stripped (``ckpt-eval-0042`` -> ``ckpt-eval``)."""
    return _RUN_SUFFIX.sub("", name) or name


@dataclasses.dataclass(frozen=True)
class Forecast:
    """One predicted demand event, with explicit confidence.

    ``key`` is a stable identity for deduplication across passes: the
    same underlying prediction (same source, same basis, same predicted
    window) must not spawn a second prewarm when re-emitted next pass.
    """

    accel_class: str        # gke-tpu-accelerator value the demand needs
    shape_name: str | None  # exact catalog shape (recurring model only)
    at: Seconds             # predicted arrival time (same clock as input)
    chips: Chips            # predicted chip demand
    confidence: Fraction    # 0..1, honest (see per-model docstrings)
    source: str             # "ewma" | "holt_winters" | "recurring"
    key: str                # stable dedup identity

    def describe(self) -> str:
        return (f"{self.source}: {self.chips} chips of "
                f"{self.shape_name or self.accel_class} at t={self.at:g} "
                f"(confidence {self.confidence:.2f})")


def _ramp(count: int, full_at: int) -> float:
    """Observation-count confidence ramp: 0 below MIN_OBSERVATIONS,
    linear to 1.0 at ``full_at`` — a model must earn its confidence."""
    if count < MIN_OBSERVATIONS:
        return 0.0
    return min(1.0, count / float(full_at))


class EwmaForecaster:
    """Per-class EWMA of inter-arrival gaps and chip sizes.

    ``note`` once per gang arrival; ``forecasts`` predicts each class's
    next arrival at ``last + mean_gap`` with confidence
    ``(1 - cv) * ramp``.  Bursty traffic (cv >= 1) reports 0.
    """

    def __init__(self, alpha: float = 0.3, full_at: int = 8) -> None:
        self.alpha = alpha
        self.full_at = full_at
        # class -> [last_t, mean_gap, mean_abs_dev, mean_chips, count]
        self._state: dict[str, list[float]] = {}
        # class -> modal shape bookkeeping (shape -> arrivals seen)
        self._shapes: dict[str, dict[str, int]] = {}

    def note(self, accel_class: str, shape_name: str | None, t: float,
             chips: int) -> None:
        a = self.alpha
        st = self._state.get(accel_class)
        if st is None:
            self._state[accel_class] = [t, 0.0, 0.0, float(chips), 1.0]
        else:
            gap = max(0.0, t - st[0])
            if st[4] < 2:
                st[1], st[2] = gap, 0.0
            else:
                dev = abs(gap - st[1])
                st[1] = (1 - a) * st[1] + a * gap
                st[2] = (1 - a) * st[2] + a * dev
            st[0] = t
            st[3] = (1 - a) * st[3] + a * float(chips)
            st[4] += 1.0
        if shape_name is not None:
            counts = self._shapes.setdefault(accel_class, {})
            counts[shape_name] = counts.get(shape_name, 0) + 1

    def modal_shape(self, accel_class: str) -> str | None:
        counts = self._shapes.get(accel_class)
        if not counts:
            return None
        return max(sorted(counts), key=lambda s: counts[s])

    def forecasts(self, now: float) -> list[Forecast]:
        out: list[Forecast] = []
        for cls in sorted(self._state):
            last_t, gap, dev, chips, count = self._state[cls]
            if count < MIN_OBSERVATIONS or gap <= 0.0:
                continue
            cv = dev / gap
            confidence = max(0.0, 1.0 - cv) * _ramp(int(count),
                                                    self.full_at)
            if confidence <= 0.0:
                continue
            at = expected = last_t + gap
            # A prediction already in the past rolls forward one period
            # (the arrival is late, not cancelled) — but only one: two
            # missed periods mean the pattern broke.  The dedup KEY
            # stays anchored to the expected event, never the rolled
            # time: a late arrival must not mint a fresh key every
            # pass and spawn duplicate prewarms for one event.
            if at < now:
                if now - at > gap:
                    continue
                at += gap
                expected = at
            out.append(Forecast(
                accel_class=cls, shape_name=self.modal_shape(cls),
                at=at, chips=int(round(chips)),
                confidence=confidence, source="ewma",
                key=f"ewma:{cls}:{int(expected // max(1.0, gap))}"))
        return out


class HoltWintersForecaster:
    """Additive Holt-Winters over fixed-width arrival bins per class.

    Chip arrivals are accumulated into ``bin_seconds`` buckets; the
    classic level/trend/seasonal recursion updates once per *closed*
    bin (empty bins update with 0 — silence is data).  ``forecasts``
    scans the next season for the first bin whose prediction clears
    ``min_chips`` and reports its start time.

    Confidence: ``1 - err/level`` (one-step absolute forecast error
    EWMA over the demand level EWMA), ramped by full seasons observed —
    a model that has not seen one whole season yet predicts nothing.
    """

    def __init__(self, bin_seconds: float = 300.0, season_bins: int = 24,
                 alpha: float = 0.35, beta: float = 0.05,
                 gamma: float = 0.3, min_chips: int = 1) -> None:
        if season_bins < 2:
            raise ValueError(f"season_bins must be >= 2, got {season_bins}")
        self.bin_seconds = bin_seconds
        self.season_bins = season_bins
        self.alpha, self.beta, self.gamma = alpha, beta, gamma
        self.min_chips = min_chips
        # class -> mutable state dict
        self._state: dict[str, dict[str, Any]] = {}
        self._shapes: dict[str, dict[str, int]] = {}

    def _new_state(self, t: float) -> dict[str, Any]:
        return {
            "origin": t, "bin": 0, "acc": 0.0,
            "level": 0.0, "trend": 0.0,
            "seasonal": [0.0] * self.season_bins,
            "bins_closed": 0, "err": 0.0, "demand": 0.0,
        }

    def _close_bins(self, st: dict[str, Any], upto_bin: int) -> None:
        while st["bin"] < upto_bin:
            y = st["acc"]
            st["acc"] = 0.0
            i = st["bin"] % self.season_bins
            seasonal: list[float] = st["seasonal"]
            if st["bins_closed"] < self.season_bins:
                # First season: seed level/seasonal from raw data.
                st["level"] = ((st["level"] * st["bins_closed"] + y)
                               / (st["bins_closed"] + 1))
                seasonal[i] = y - st["level"]
            else:
                predicted = st["level"] + st["trend"] + seasonal[i]
                err = abs(y - predicted)
                st["err"] = 0.8 * st["err"] + 0.2 * err
                st["demand"] = 0.8 * st["demand"] + 0.2 * abs(y)
                last_level = st["level"]
                st["level"] = (self.alpha * (y - seasonal[i])
                               + (1 - self.alpha)
                               * (st["level"] + st["trend"]))
                st["trend"] = (self.beta * (st["level"] - last_level)
                               + (1 - self.beta) * st["trend"])
                seasonal[i] = (self.gamma * (y - st["level"])
                               + (1 - self.gamma) * seasonal[i])
            st["bin"] += 1
            st["bins_closed"] += 1

    def _bin_of(self, st: dict[str, Any], t: float) -> int:
        return max(0, int((t - st["origin"]) // self.bin_seconds))

    def note(self, accel_class: str, shape_name: str | None, t: float,
             chips: int) -> None:
        st = self._state.get(accel_class)
        if st is None:
            st = self._new_state(t)
            self._state[accel_class] = st
        self._close_bins(st, self._bin_of(st, t))
        st["acc"] += float(chips)
        if shape_name is not None:
            counts = self._shapes.setdefault(accel_class, {})
            counts[shape_name] = counts.get(shape_name, 0) + 1

    def observe_silence(self, now: float) -> None:
        """Close empty bins up to ``now`` — quiet periods train the
        seasonal profile too; call once per control pass."""
        for st in self._state.values():
            self._close_bins(st, self._bin_of(st, now))

    def modal_shape(self, accel_class: str) -> str | None:
        counts = self._shapes.get(accel_class)
        if not counts:
            return None
        return max(sorted(counts), key=lambda s: counts[s])

    def predict_bin(self, accel_class: str, h: int) -> float:
        """Predicted chip arrivals ``h`` bins ahead (h >= 1)."""
        st = self._state.get(accel_class)
        if st is None:
            return 0.0
        i = (st["bin"] + h - 1) % self.season_bins
        seasonal: list[float] = st["seasonal"]
        return max(0.0, st["level"] + h * st["trend"] + seasonal[i])

    def confidence(self, accel_class: str) -> float:
        st = self._state.get(accel_class)
        if st is None:
            return 0.0
        seasons = st["bins_closed"] / float(self.season_bins)
        if seasons < 2.0:
            return 0.0  # needs one full season past the seed season
        rel_err = st["err"] / max(st["demand"], 1e-9)
        return max(0.0, 1.0 - rel_err) * min(1.0, (seasons - 1.0) / 2.0)

    def forecasts(self, now: float) -> list[Forecast]:
        out: list[Forecast] = []
        for cls in sorted(self._state):
            st = self._state[cls]
            self._close_bins(st, self._bin_of(st, now))
            confidence = self.confidence(cls)
            if confidence <= 0.0:
                continue
            # A "demand bin" must clear half the learned demand level,
            # not just min_chips: level+trend leak small positives into
            # quiet bins, and predicting those would fire prewarms into
            # the valley instead of the next peak.
            floor = max(float(self.min_chips), 0.5 * st["demand"])
            for h in range(1, self.season_bins + 1):
                chips = self.predict_bin(cls, h)
                if chips < floor:
                    continue
                at = (st["origin"]
                      + (st["bin"] + h - 1) * self.bin_seconds)
                out.append(Forecast(
                    accel_class=cls, shape_name=self.modal_shape(cls),
                    at=at, chips=int(round(chips)),
                    confidence=confidence, source="holt_winters",
                    key=f"hw:{cls}:{st['bin'] + h - 1}"))
                break  # nearest predicted-demand bin per class
        return out


class RecurringGangPredictor:
    """Period mining over per-(base gang, shape) arrival histories.

    The model behind shape-exact prewarms: a gang whose base name
    re-arrives with a stable period (inter-arrival cv <= ``max_cv``)
    predicts its next run at ``last + mean_period`` with confidence
    ``(1 - cv / max_cv) ... * ramp``.  History is bounded per key.
    """

    def __init__(self, max_cv: float = 0.25, history: int = 16,
                 full_at: int = 4) -> None:
        self.max_cv = max_cv
        self.full_at = full_at
        # (base, shape, class) -> bounded arrival times
        self._arrivals: dict[tuple[str, str, str], deque[float]] = {}
        self._history = history

    def note(self, gang_name: str, accel_class: str,
             shape_name: str, t: float) -> None:
        key = (base_name(gang_name), shape_name, accel_class)
        times = self._arrivals.setdefault(
            key, deque(maxlen=self._history))
        if times and t <= times[-1]:
            return  # replays/duplicates never corrupt the period
        times.append(t)

    def ingest_dump(self, dump: dict[str, Any]) -> int:
        """Bootstrap from a flight-recorder dump (``/debugz`` shape):
        every completed ``scale_up`` root is one arrival; the trace's
        ``dispatch`` span names the shape.  Returns arrivals ingested —
        how a restarted controller recovers its learned periods."""
        shapes: dict[str, str] = {}
        for span in dump.get("spans", ()):
            if span.get("name") == "dispatch" \
                    and span.get("attrs", {}).get("shape"):
                shapes.setdefault(span["trace_id"],
                                  span["attrs"]["shape"])
        ingested = 0
        roots = [s for s in dump.get("spans", ())
                 if s.get("name") == "scale_up"
                 and s.get("parent_id") is None]
        roots.sort(key=lambda s: s.get("start", 0.0))
        for span in roots:
            gang = span.get("attrs", {}).get("gang", "")
            shape = shapes.get(span["trace_id"])
            if not gang or shape is None:
                continue
            name = gang.rsplit("/", 1)[-1]
            from tpu_autoscaler.topology.catalog import shape_by_name

            try:
                accel = shape_by_name(shape).accelerator_type
            except KeyError:
                continue
            self.note(name, accel, shape, float(span["start"]))
            ingested += 1
        return ingested

    def forecasts(self, now: float) -> list[Forecast]:
        from tpu_autoscaler.topology.catalog import shape_by_name

        out: list[Forecast] = []
        for (base, shape_name, cls) in sorted(self._arrivals):
            times = self._arrivals[(base, shape_name, cls)]
            if len(times) < MIN_OBSERVATIONS:
                continue
            seq = list(times)
            gaps = [b - a for a, b in zip(seq, seq[1:])]
            mean = sum(gaps) / len(gaps)
            if mean <= 0.0:
                continue
            var = sum((g - mean) ** 2 for g in gaps) / len(gaps)
            cv = math.sqrt(var) / mean
            if cv > self.max_cv:
                continue
            confidence = ((1.0 - cv / self.max_cv) * 0.5 + 0.5) \
                * _ramp(len(seq), self.full_at)
            if confidence <= 0.0:
                continue
            at = expected = seq[-1] + mean
            if at < now:
                if now - at > 0.5 * mean:
                    continue  # a missed period breaks the pattern
                at = now
            try:
                chips = shape_by_name(shape_name).chips
            except KeyError:
                continue
            # Key anchored to the EXPECTED run, not the (possibly
            # rolled) `at`: while an arrival runs late the same
            # predicted event keeps one identity, so the prewarm gate
            # never fires twice for it.
            out.append(Forecast(
                accel_class=cls, shape_name=shape_name, at=at,
                chips=chips, confidence=confidence, source="recurring",
                key=f"recurring:{base}:{shape_name}:"
                    f"{int(expected // max(1.0, mean / 2))}"))
        return out


def merge_forecasts(streams: Iterable[Iterable[Forecast]]
                    ) -> list[Forecast]:
    """Combine forecaster outputs: per (class, shape) keep the single
    most confident prediction (recurring's shape-exact forecasts do not
    compete with class-level rate forecasts for a different shape)."""
    best: dict[tuple[str, str | None], Forecast] = {}
    for stream in streams:
        for f in stream:
            k = (f.accel_class, f.shape_name)
            cur = best.get(k)
            if cur is None or f.confidence > cur.confidence:
                best[k] = f
    return sorted(best.values(), key=lambda f: (f.at, f.key))
