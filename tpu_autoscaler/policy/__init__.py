"""Predictive SLO-driven scaling policy (ISSUE 8, docs/POLICY.md).

The advisory decision layer over the reactive control loop: demand
forecasters (``forecast``), the SLO/cost algebra (``slo``), the
per-pass engine the Reconciler consults (``engine``), and the offline
replay/eval harness (``replay``; CLI ``python -m tpu_autoscaler.policy``).
"""

from tpu_autoscaler.policy.engine import (
    PREWARM_NAMESPACE,
    PolicyAdvice,
    PolicyConfig,
    PolicyEngine,
)
from tpu_autoscaler.policy.forecast import (
    EwmaForecaster,
    Forecast,
    HoltWintersForecaster,
    RecurringGangPredictor,
    merge_forecasts,
)
from tpu_autoscaler.policy.slo import (
    PrewarmDecision,
    SloPolicy,
    decide_prewarms,
    idle_threshold_for,
)

__all__ = [
    "PREWARM_NAMESPACE",
    "PolicyAdvice",
    "PolicyConfig",
    "PolicyEngine",
    "EwmaForecaster",
    "Forecast",
    "HoltWintersForecaster",
    "RecurringGangPredictor",
    "merge_forecasts",
    "PrewarmDecision",
    "SloPolicy",
    "decide_prewarms",
    "idle_threshold_for",
]
