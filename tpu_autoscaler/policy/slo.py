"""SLO/cost model: forecasts in, prewarm + reclaim decisions out.

The policy question is never "will demand arrive?" alone — it is
"does hiding the provision latency *pay*?".  This module is the pure
algebra that answers it (docs/POLICY.md):

**Prewarm side.**  A class whose reactive scale-up latency (dominated
by the measured provision time) already meets its SLO target gains
nothing from prediction; one that misses it gains the whole provision
phase.  A forecast therefore converts into a prewarm decision iff

- its confidence clears ``min_confidence`` (low-confidence predictions
  must emit NO advisory demand — wasted chips are real money),
- the predicted arrival is within the *firing window*: close enough
  that provisioning now finishes just-in-time (``provision estimate +
  lead slack`` before the arrival), not yet past the hold window,
- the *expected waste* fits the budget: a prewarm that goes unused
  burns ``chips x hold`` chip-seconds, which happens with probability
  ``(1 - confidence)`` — the expectation is charged against a rolling
  wasted-chip-seconds budget BEFORE the prewarm fires, so a string of
  bad predictions exhausts the budget and the policy self-mutes.

**Scale-down side.**  The fixed idle threshold becomes a tradeoff:
holding an idle slice costs ``chips x seconds`` chip-seconds; releasing
it risks paying the full reactive provision latency if demand returns
first.  With demand forecast inside the hold horizon the threshold
stretches to cover the predicted arrival; with no forecast in sight it
shrinks toward ``idle_floor_seconds`` (capacity is returned early —
the cost term wins when the SLO term is not in play).

Pure computation over injected values only (TAP1xx scope): the engine
measures, this module decides.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

from tpu_autoscaler.policy.forecast import Forecast
from tpu_autoscaler.units import (
    Chips,
    ChipSeconds,
    Fraction,
    Seconds,
    chip_seconds,
)


@dataclasses.dataclass(frozen=True)
class SloPolicy:
    """Knobs of the SLO/cost algebra (docs/POLICY.md)."""

    # Target detect->Running latency per accelerator class; classes
    # absent from the map use the default.  A class whose reactive
    # latency already meets target is never prewarmed.
    target_scaleup_seconds: Seconds = 120.0
    class_targets: Mapping[str, Seconds] = dataclasses.field(
        default_factory=dict)
    # Forecasts below this confidence emit NO advisory demand.
    min_confidence: Fraction = 0.6
    # Reactive provision estimate used until the controller has
    # measured provision_latency_seconds itself.
    provision_estimate_seconds: Seconds = 240.0
    # Fire a prewarm this long BEFORE provisioning must start, so a
    # slightly-early arrival still finds the slice Ready.
    lead_slack_seconds: Seconds = 60.0
    # How long past the predicted arrival a prewarmed slice is held
    # before it is declared a misprediction and released to reclaim.
    prewarm_hold_seconds: Seconds = 600.0
    # Rolling wasted-chip-seconds budget: expected waste of decided
    # prewarms plus realized waste of expired ones, per window.
    waste_budget_chip_seconds: ChipSeconds = 120_000.0
    waste_window_seconds: Seconds = 3600.0
    # Scale-down tradeoff bounds (see idle_threshold_for).
    idle_floor_seconds: Seconds = 120.0
    idle_ceiling_seconds: Seconds = 7200.0
    early_reclaim: bool = True
    # At most this many concurrent un-consumed prewarms fleet-wide.
    max_concurrent_prewarms: int = 4

    def target_for(self, accel_class: str) -> Seconds:
        return self.class_targets.get(accel_class,
                                      self.target_scaleup_seconds)


@dataclasses.dataclass(frozen=True)
class PrewarmDecision:
    """One approved prewarm: provision ``shape_name`` ahead of the
    forecast so the arrival finds warm supply."""

    key: str                # the forecast's dedup identity
    shape_name: str
    accel_class: str
    chips: Chips
    predicted_at: Seconds
    confidence: Fraction
    expected_waste_chip_seconds: ChipSeconds
    reason: str


def fire_at(forecast: Forecast, provision_estimate: Seconds,
            policy: SloPolicy) -> Seconds:
    """When provisioning must start for the slice to be Ready on
    arrival."""
    return forecast.at - provision_estimate - policy.lead_slack_seconds


def expires_at(predicted_at: Seconds, policy: SloPolicy) -> Seconds:
    """When an unconsumed prewarm becomes a misprediction."""
    return predicted_at + policy.prewarm_hold_seconds


def decide_prewarms(forecasts: list[Forecast], now: Seconds, *,
                    policy: SloPolicy, provision_estimate: Seconds,
                    waste_spent_chip_seconds: ChipSeconds,
                    active_prewarms: int,
                    active_keys: frozenset[str] = frozenset(),
                    ) -> tuple[list[PrewarmDecision], list[str]]:
    """The prewarm gate.  Returns ``(decisions, rejections)`` —
    rejections are human-readable "why not" lines for the flight
    recorder, so a silent policy is still an explainable one."""
    decisions: list[PrewarmDecision] = []
    rejections: list[str] = []
    budget = policy.waste_budget_chip_seconds
    committed = waste_spent_chip_seconds
    slots = policy.max_concurrent_prewarms - active_prewarms
    for f in forecasts:
        if f.key in active_keys:
            continue  # already being prewarmed (re-emitted forecast)
        if f.shape_name is None:
            rejections.append(
                f"{f.key}: no exact shape to prewarm (class-level "
                f"forecast; needs a recurring or modal shape)")
            continue
        if f.confidence < policy.min_confidence:
            rejections.append(
                f"{f.key}: confidence {f.confidence:.2f} < "
                f"min {policy.min_confidence:g} — no advisory demand")
            continue
        if provision_estimate <= policy.target_for(f.accel_class):
            rejections.append(
                f"{f.key}: reactive provisioning "
                f"(~{provision_estimate:g}s) already meets the "
                f"{policy.target_for(f.accel_class):g}s target")
            continue
        start = fire_at(f, provision_estimate, policy)
        if now < start:
            rejections.append(
                f"{f.key}: too early (fires at t={start:g})")
            continue
        if now >= expires_at(f.at, policy):
            rejections.append(f"{f.key}: window already passed")
            continue
        hold = (expires_at(f.at, policy)
                - max(now, fire_at(f, provision_estimate, policy)))
        expected_waste = (chip_seconds(f.chips, hold)
                          * (1.0 - f.confidence))
        if committed + expected_waste > budget:
            rejections.append(
                f"{f.key}: expected waste {expected_waste:.0f} "
                f"chip-s would blow the {budget:g} budget "
                f"({committed:.0f} committed)")
            continue
        if slots <= 0:
            rejections.append(
                f"{f.key}: max_concurrent_prewarms "
                f"({policy.max_concurrent_prewarms}) reached")
            continue
        slots -= 1
        committed += expected_waste
        decisions.append(PrewarmDecision(
            key=f.key, shape_name=f.shape_name,
            accel_class=f.accel_class, chips=f.chips,
            predicted_at=f.at, confidence=f.confidence,
            expected_waste_chip_seconds=expected_waste,
            reason=(f"forecast {f.source} predicts {f.chips} chips "
                    f"({f.shape_name}) at t={f.at:g} with confidence "
                    f"{f.confidence:.2f}; reactive would miss the "
                    f"{policy.target_for(f.accel_class):g}s target")))
    return decisions, rejections


def rolling_waste(events: list[tuple[Seconds, ChipSeconds]],
                  now: Seconds, window_seconds: Seconds
                  ) -> tuple[list[tuple[Seconds, ChipSeconds]],
                             ChipSeconds]:
    """Trim the realized-waste event series to the rolling window and
    sum what remains: ``(kept_events, realized_chip_seconds)``.

    One authority for the window algebra (ISSUE 11): the engine's
    budget gate and any ledger-side consumer trim and sum the SAME
    way, so "how much waste is in the window" can never disagree with
    "how much budget is left".  Pure over injected values (TAP1xx
    scope, like the rest of this module)."""
    floor = now - window_seconds
    kept = [(t, w) for t, w in events if t >= floor]
    return kept, sum(w for _t, w in kept)


def budget_remaining(events: list[tuple[Seconds, ChipSeconds]],
                     now: Seconds, window_seconds: Seconds,
                     budget_chip_seconds: ChipSeconds
                     ) -> tuple[list[tuple[Seconds, ChipSeconds]],
                                ChipSeconds, ChipSeconds]:
    """``rolling_waste`` plus the verdict: ``(kept_events, spent,
    remaining)`` against a rolling chip-seconds budget.

    The ISSUE 12 extension of the one-authority rule above: the
    prewarm waste gate and the repacker's migration-cost budget
    (repack/repacker.py) charge, trim and settle the SAME way, so
    "how much budget is left" can never mean two things.  Pure over
    injected values (TAP1xx scope)."""
    kept, spent = rolling_waste(events, now, window_seconds)
    return kept, spent, max(0.0, budget_chip_seconds - spent)


def idle_threshold_for(accel_class: str, now: Seconds, *,
                       policy: SloPolicy, base_threshold: Seconds,
                       provision_estimate: Seconds,
                       next_arrival_at: Seconds | None,
                       confidence: Fraction) -> Seconds:
    """Effective idle threshold for an idle unit of ``accel_class`` —
    the fixed-threshold scale-down turned into an SLO/cost tradeoff.

    - Demand forecast confidently inside the ceiling: stretch the
      threshold so the unit survives until the arrival (the
      prewarm-hold hint: warm supply beats a fresh provision).
    - No confident forecast and early reclaim on: shrink toward
      ``idle_floor_seconds`` — but never below the provision estimate
      (thrash guard: reclaiming faster than we could re-provision
      converts every blip into a full scale-up).
    - Early reclaim off: the configured threshold stands.
    """
    if next_arrival_at is not None \
            and confidence >= policy.min_confidence:
        wait = (next_arrival_at - now) + policy.lead_slack_seconds
        if wait <= policy.idle_ceiling_seconds:
            return min(policy.idle_ceiling_seconds,
                       max(base_threshold, wait))
    if not policy.early_reclaim:
        return base_threshold
    floor = max(policy.idle_floor_seconds, provision_estimate)
    return min(base_threshold, floor)
