"""Offline policy evaluation: replay traffic programs through the real
control loop and score SLO attainment vs wasted chip-seconds.

The KIS-S-style loop the ROADMAP called for, without the RL: a traffic
program (a pure function of its seed) drives ``FakeKube`` + the
production ``Controller`` exactly like ``sim.py``, once reactively and
once with the PolicyEngine attached, and the scorecard answers the only
question that matters — *how much provision latency did prediction hide,
and what did the mispredictions cost?*

Programs (docs/POLICY.md):

- ``recurring`` — the acceptance trace: one gang of a fixed shape
  re-arrives on a fixed period (nightly-training pattern); later
  arrivals should find prewarmed supply;
- ``diurnal``   — sinusoidal arrival intensity over repeating days;
- ``spike``     — quiet, then an unforecastable burst (the honesty
  check: the policy must not pretend to predict it);
- ``coldstart`` — a single first arrival (no history: the policy must
  stay silent);
- ``regime``    — a stable period that abruptly changes (confidence
  must collapse, then recover on the new period).

Run it: ``python -m tpu_autoscaler.policy --program recurring
--compare`` — or through ``bench.py policy``, which gates the
north-star claim (prewarmed detect->running <= 0.25x reactive) and
records BENCH_POLICY.json.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any

from tpu_autoscaler.policy.engine import PolicyConfig, PolicyEngine
from tpu_autoscaler.policy.slo import SloPolicy

#: Realistic-actuation profile, mirrored from bench.py's realistic tier
#: (slice create/VM boot, per-host registration spread, bind batching).
PROVISION_DELAY_S = 90.0
HOST_STAGGER_S = 2.0
SCHEDULER_PERIOD_S = 5.0


@dataclasses.dataclass(frozen=True)
class Arrival:
    t: float
    job: str
    shape: str
    run_seconds: float  # job runtime once fully Running


@dataclasses.dataclass(frozen=True)
class TrafficProgram:
    kind: str
    seed: int
    arrivals: tuple[Arrival, ...]
    until: float
    step: float = 5.0
    # Reactive reclaim pace: short enough that slices do NOT survive
    # between recurring arrivals on their own — warm supply between
    # arrivals must be EARNED by prediction, not by a lazy idle clock.
    idle_threshold: float = 240.0

    def describe(self) -> str:
        shapes = sorted({a.shape for a in self.arrivals})
        return (f"{self.kind} seed={self.seed}: {len(self.arrivals)} "
                f"arrivals of {'/'.join(shapes)} over {self.until:g}s")


def make_program(kind: str, seed: int = 0, *, shape: str = "v5e-16",
                 period: float = 900.0, cycles: int = 6,
                 run_seconds: float = 240.0) -> TrafficProgram:
    """Compile one traffic program (pure function of its arguments)."""
    from tpu_autoscaler.policy import traffic

    rng = random.Random(seed)
    arrivals: list[Arrival] = []
    if kind == "recurring":
        for k in range(cycles):
            arrivals.append(Arrival(
                t=60.0 + k * period, job=f"nightly-{k}", shape=shape,
                run_seconds=run_seconds))
        until = 60.0 + cycles * period
    elif kind == "diurnal":
        # Two "days" of the SHARED day-shape (policy/traffic.py —
        # arrivals cluster in each day's first half); draw-for-draw
        # identical to the pre-ISSUE-9 inline loop, so seeded programs
        # are unchanged.
        day = period * 4
        arrivals = [
            Arrival(t=t, job=f"web-{k}", shape=shape,
                    run_seconds=run_seconds)
            for k, t in enumerate(traffic.diurnal_arrival_times(
                rng, day, period / 2, days=2))]
        until = day * 2 + period
    elif kind == "spike":
        arrivals = [Arrival(t=t, job=f"burst-{i}", shape=shape,
                            run_seconds=run_seconds)
                    for i, t in enumerate(
                        traffic.spike_times(period * 2))]
        until = period * 3
    elif kind == "coldstart":
        arrivals = [Arrival(t=60.0, job="first-0", shape=shape,
                            run_seconds=run_seconds)]
        until = period
    elif kind == "regime":
        t = 60.0
        for k in range(cycles):
            arrivals.append(Arrival(t=t, job=f"shift-{k}", shape=shape,
                                    run_seconds=run_seconds))
            t += period if k < cycles // 2 else period * 2
        until = t + period
    else:
        raise ValueError(f"unknown traffic program {kind!r}")
    arrivals.sort(key=lambda a: a.t)
    return TrafficProgram(kind=kind, seed=seed,
                          arrivals=tuple(arrivals), until=until)


@dataclasses.dataclass
class ReplayResult:
    program: str
    policy_enabled: bool
    latencies: dict[str, float]          # job -> detect->Running seconds
    arrival_order: list[str]             # job names, by arrival time
    slo_attainment: float                # fraction <= target
    target_seconds: float
    prewarm_hits: int
    prewarm_expired: int
    hidden_provision_seconds: float      # summed over hits
    wasted_prewarm_chip_seconds: float
    chip_seconds_provisioned: float
    pending_at_end: int
    # Raw counter subset for scorecards/tests (holds, early reclaims,
    # decisions — the policy's maintenance-side fingerprints).
    counters: dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def max_latency(self) -> float:
        return max(self.latencies.values(), default=0.0)

    def tail_latencies(self, warmup: int) -> list[float]:
        """Latencies of arrivals after the first ``warmup`` (the
        history the forecasters need before they may fire), in
        ARRIVAL order — job names sort lexicographically ("web-10" <
        "web-2"), so name order would slice the wrong warmup set."""
        ordered = [self.latencies[j] for j in self.arrival_order
                   if j in self.latencies]
        return ordered[warmup:]

    def as_dict(self) -> dict[str, Any]:
        return {
            "program": self.program,
            "policy": self.policy_enabled,
            "latencies_s": {k: round(v, 1)
                            for k, v in sorted(self.latencies.items())},
            "slo_attainment": round(self.slo_attainment, 3),
            "target_s": self.target_seconds,
            "prewarm_hits": self.prewarm_hits,
            "prewarm_expired": self.prewarm_expired,
            "hidden_provision_s": round(self.hidden_provision_seconds, 1),
            "wasted_prewarm_chip_s":
                round(self.wasted_prewarm_chip_seconds, 1),
            "chip_seconds_provisioned":
                round(self.chip_seconds_provisioned, 1),
            "pending_at_end": self.pending_at_end,
        }


def default_policy_config(program: TrafficProgram) -> PolicyConfig:
    """Replay-scale policy config: thresholds sized to the program's
    clock (a 900 s period needs shorter holds than a real day)."""
    return PolicyConfig(
        slo=SloPolicy(
            target_scaleup_seconds=60.0,
            min_confidence=0.6,
            provision_estimate_seconds=PROVISION_DELAY_S + 60.0,
            lead_slack_seconds=45.0,
            prewarm_hold_seconds=300.0,
            waste_budget_chip_seconds=600_000.0,
            idle_floor_seconds=PROVISION_DELAY_S,
            idle_ceiling_seconds=program.until,
        ),
        hw_bin_seconds=120.0,
        hw_season_bins=12,
    )


def replay(program: TrafficProgram, *, policy: bool,
           policy_config: PolicyConfig | None = None) -> ReplayResult:
    """Drive one traffic program through the real control loop."""
    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.k8s.objects import clear_parse_caches
    from tpu_autoscaler.sim import gang_pods

    clear_parse_caches()  # hermetic across replays (fresh FakeKube uids)
    kube = FakeKube()
    actuator = FakeActuator(kube, provision_delay=PROVISION_DELAY_S,
                            stagger_seconds=HOST_STAGGER_S)
    engine = (PolicyEngine(policy_config
                           or default_policy_config(program))
              if policy else None)
    controller = Controller(
        kube, actuator,
        ControllerConfig(
            policy=PoolPolicy(spare_nodes=0),
            grace_seconds=60.0,
            idle_threshold_seconds=program.idle_threshold,
            drain_grace_seconds=30.0,
            provision_timeout_seconds=600.0),
        policy_engine=engine)

    target = (engine.config.slo.target_scaleup_seconds if engine
              else default_policy_config(program)
              .slo.target_scaleup_seconds)
    pending_jobs: list[Arrival] = list(program.arrivals)
    live: dict[str, list[str]] = {}
    running_since: dict[str, float] = {}
    started_at: dict[str, float] = {}
    latencies: dict[str, float] = {}

    t = 0.0
    horizon = program.until + 600.0
    while t <= horizon:
        for a in [a for a in pending_jobs if a.t <= t]:
            names = []
            for payload in gang_pods(a.shape, a.job):
                kube.add_pod(payload)
                names.append(payload["metadata"]["name"])
            live[a.job] = names
            started_at[a.job] = t
        pending_jobs = [a for a in pending_jobs if a.t > t]
        # Completions: a job that has been fully Running for its
        # runtime finishes (pods deleted -> the slice idles).
        by_arrival = {a.job: a for a in program.arrivals}
        for job, names in list(live.items()):
            all_running = all(
                (kube.get_pod("default", n) or {}).get(
                    "status", {}).get("phase") == "Running"
                for n in names)
            if not all_running:
                running_since.pop(job, None)
                continue
            if job not in latencies:
                latencies[job] = t - started_at[job]
            since = running_since.setdefault(job, t)
            if t - since >= by_arrival[job].run_seconds:
                for n in names:
                    kube.delete_pod("default", n)
                del live[job]
                running_since.pop(job, None)
        controller.reconcile_once(now=t)
        if t % SCHEDULER_PERIOD_S == 0.0:
            kube.schedule_step()
        if not pending_jobs and not live \
                and t > (program.arrivals[-1].t
                         if program.arrivals else 0.0):
            break
        t += program.step

    snap = controller.metrics.snapshot()
    counters = snap["counters"]
    summaries = snap["summaries"]
    met = sum(1 for v in latencies.values() if v <= target)
    pending = sum(1 for p in kube.list_pods()
                  if p["status"]["phase"] == "Pending")
    return ReplayResult(
        program=program.describe(),
        policy_enabled=policy,
        latencies=latencies,
        arrival_order=[a.job for a in program.arrivals],
        slo_attainment=(met / len(latencies)) if latencies else 0.0,
        target_seconds=target,
        prewarm_hits=int(counters.get("prewarm_hits", 0)),
        prewarm_expired=int(counters.get("prewarm_expired", 0)),
        hidden_provision_seconds=float(
            summaries.get("hidden_provision_seconds", {}).get("sum",
                                                              0.0)),
        wasted_prewarm_chip_seconds=float(
            counters.get("wasted_prewarm_chip_seconds", 0.0)),
        chip_seconds_provisioned=float(
            counters.get("chip_seconds_provisioned", 0.0)),
        pending_at_end=pending,
        counters={k: float(counters.get(k, 0.0))
                  for k in ("prewarm_decisions", "prewarm_holds",
                            "policy_early_reclaims", "policy_errors")},
    )


def compare(program: TrafficProgram,
            policy_config: PolicyConfig | None = None
            ) -> dict[str, Any]:
    """Reactive vs policy-enabled scorecard for one program."""
    reactive = replay(program, policy=False)
    predictive = replay(program, policy=True,
                        policy_config=policy_config)
    warmup = _warmup_arrivals(program)
    r_tail = reactive.tail_latencies(warmup)
    p_tail = predictive.tail_latencies(warmup)
    return {
        "program": program.describe(),
        "warmup_arrivals": warmup,
        "reactive": reactive.as_dict(),
        "policy": predictive.as_dict(),
        "tail_latency_reactive_s":
            round(max(r_tail), 1) if r_tail else None,
        "tail_latency_policy_s":
            round(max(p_tail), 1) if p_tail else None,
        "tail_ratio": (round(max(p_tail) / max(r_tail), 3)
                       if r_tail and p_tail and max(r_tail) > 0
                       else None),
    }


def _warmup_arrivals(program: TrafficProgram) -> int:
    """Arrivals the forecasters may spend learning before the scored
    tail begins (MIN_OBSERVATIONS periods for the recurring model)."""
    from tpu_autoscaler.policy.forecast import MIN_OBSERVATIONS

    return min(MIN_OBSERVATIONS, max(0, len(program.arrivals) - 1))
