"""Threading seam: one place the control plane's primitives come from.

Like ``backoff.py`` for retry arithmetic, this is drift-prone plumbing
centralized: every concurrent subsystem (k8s/informer.py watch threads,
controller/watch.py, actuators/executor.py's worker pool, gcp.py's
TokenProvider, the metrics registry, FakeKube's watch condition)
constructs its threads and synchronization primitives HERE instead of
reaching for ``threading`` directly.

In production the seam is a 1:1 pass-through to ``threading`` /
``concurrent.futures`` — zero behavior change, zero overhead beyond one
attribute read.  Under test, the deterministic-schedule harness
(``tpu_autoscaler/testing/sched.py``) installs a scheduler here; every
primitive constructed while it is active is scheduler-controlled, which
is what lets the harness serialize execution, permute interleavings at
sync points, and run its vector-clock happens-before checker over the
real informer/executor/reconciler code paths (docs/ANALYSIS.md).

Module-level primitives created at import time (e.g. the parse-memo
lock in ``k8s/objects.py``) deliberately stay on raw ``threading``: they
outlive any one scheduler activation, and a scheduler-owned primitive
must never escape its scheduler's lifetime.
"""

from __future__ import annotations

import concurrent.futures
import threading as _threading
from typing import Any, Optional

#: The active deterministic scheduler, or None (production).  Installed
#: only by tpu_autoscaler/testing/sched.py; never set in production.
_scheduler: Any = None


def install_scheduler(sched: Any) -> None:
    """Install (or, with None, remove) the deterministic scheduler.
    Harness-only; refuses to stack two schedulers."""
    global _scheduler
    if sched is not None and _scheduler is not None:
        raise RuntimeError("a deterministic scheduler is already active")
    _scheduler = sched


def active_scheduler() -> Any:
    return _scheduler


class Thread(_threading.Thread):
    """``threading.Thread`` that an active deterministic scheduler
    adopts at ``start()`` time (its ``run()`` becomes a managed,
    schedule-controlled thread); identical to ``threading.Thread``
    otherwise."""

    def start(self) -> None:
        sched = _scheduler
        if sched is not None:
            sched.adopt_thread(self)
        else:
            super().start()

    def join(self, timeout: Optional[float] = None) -> None:
        sched = _scheduler
        if sched is not None and sched.owns_thread(self):
            sched.join_thread(self)
        else:
            super().join(timeout)


def Lock():  # noqa: N802 — mirrors the threading API it stands in for
    sched = _scheduler
    return sched.create_lock() if sched is not None else _threading.Lock()


def RLock():  # noqa: N802
    sched = _scheduler
    return sched.create_rlock() if sched is not None else _threading.RLock()


def Event():  # noqa: N802
    sched = _scheduler
    return sched.create_event() if sched is not None else _threading.Event()


def Condition(lock=None):  # noqa: N802
    sched = _scheduler
    if sched is not None:
        return sched.create_condition(lock)
    return _threading.Condition(lock)


def pool_executor(max_workers: int, thread_name_prefix: str = ""):
    """A ``ThreadPoolExecutor``-shaped pool (``submit`` returning a
    ``concurrent.futures.Future``, ``shutdown``).  Under the harness,
    every submitted thunk runs as a managed thread so the scheduler can
    interleave worker execution with the reconcile thread."""
    sched = _scheduler
    if sched is not None:
        return sched.create_pool(max_workers)
    return concurrent.futures.ThreadPoolExecutor(
        max_workers=max_workers, thread_name_prefix=thread_name_prefix)
