"""Threading seam: one place the control plane's primitives come from.

Like ``backoff.py`` for retry arithmetic, this is drift-prone plumbing
centralized: every concurrent subsystem (k8s/informer.py watch threads,
controller/watch.py, actuators/executor.py's worker pool, gcp.py's
TokenProvider, the metrics registry, FakeKube's watch condition)
constructs its threads and synchronization primitives HERE instead of
reaching for ``threading`` directly.

In production the seam is a 1:1 pass-through to ``threading`` /
``concurrent.futures`` — zero behavior change, zero overhead beyond one
attribute read.  Under test, the deterministic-schedule harness
(``tpu_autoscaler/testing/sched.py``) installs a scheduler here; every
primitive constructed while it is active is scheduler-controlled, which
is what lets the harness serialize execution, permute interleavings at
sync points, and run its vector-clock happens-before checker over the
real informer/executor/reconciler code paths (docs/ANALYSIS.md).

Module-level primitives created at import time (e.g. the parse-memo
lock in ``k8s/objects.py``) deliberately stay on raw ``threading``: they
outlive any one scheduler activation, and a scheduler-owned primitive
must never escape its scheduler's lifetime.
"""

from __future__ import annotations

import concurrent.futures
import os
import sys
import threading as _threading
from typing import Any, Optional

#: The active deterministic scheduler, or None (production).  Installed
#: only by tpu_autoscaler/testing/sched.py; never set in production.
_scheduler: Any = None

#: The active lock-order witness, or None (production).  Installed only
#: by the race tier (tests/test_lockwitness.py); never set in
#: production — the seam stays a zero-overhead pass-through there.
_witness: "LockOrderWitness | None" = None

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rel(filename: str) -> str:
    return os.path.relpath(filename, _REPO_ROOT).replace(os.sep, "/")


def _external_site() -> tuple[str, int]:
    """File:line of the nearest frame OUTSIDE this module — where a
    primitive was constructed or acquired."""
    f: Any = sys._getframe(1)
    here = f.f_code.co_filename
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:                     # pragma: no cover — defensive
        return ("<unknown>", 0)
    return (_rel(f.f_code.co_filename), f.f_lineno)


class LockOrderWitness:
    """Runtime half of the TAL7xx lock-order analysis
    (tpu_autoscaler/analysis/lockorder.py, docs/ANALYSIS.md).

    While installed (``install_witness`` — test harness only), every
    Lock/RLock/Condition constructed through this seam is wrapped so
    each acquisition records, per acquiring thread, the order edges
    (already-held → acquired).  Locks are keyed by their CREATION SITE
    (file:line of the construction call) — the same identity the
    static pass carries on its graph nodes (``ClassInfo.attr_sites`` /
    ``ModuleInfo.global_sites``), which is what lets the race tier
    join the two graphs: a witnessed edge between two package locks
    that is absent from the static order graph means the static pass
    has a blind spot (an unresolved call edge hiding a nested
    acquisition), and ``analysis.lockorder.witness_gaps`` turns it
    into a race-tier failure instead of silent under-reporting.

    Thread-safety: held stacks are thread-local; the shared edge map
    is guarded by a raw (never-witnessed, never-scheduled) lock.
    """

    def __init__(self) -> None:
        #: (held site, acquired site) -> file:line of the acquisition
        #: that created the edge (the witness's evidence).
        self.edges: dict[tuple[tuple[str, int], tuple[str, int]],
                         tuple[str, int]] = {}
        #: Every creation site that constructed a primitive while this
        #: witness was installed — the coverage set cross-check tests
        #: assert against (a run that witnessed nothing proves nothing).
        self.sites: set[tuple[str, int]] = set()
        self._tls = _threading.local()
        self._mu = _threading.Lock()

    # -- registration (called by the seam constructors) -------------------

    def register(self, site: tuple[str, int]) -> None:
        with self._mu:
            self.sites.add(site)

    # -- acquisition bookkeeping (called by _WitnessedLock) ---------------

    def _stack(self) -> list[tuple[str, int]]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st  # analysis: allow=TAT201 threading.local IS the isolation: every thread reads/writes only its own cell, no lock needed
        return st

    def note_acquired(self, site: tuple[str, int]) -> None:
        st = self._stack()
        if st:
            at = _external_site()
            with self._mu:
                for held in st:
                    if held != site:   # re-entry is TAL703's business
                        self.edges.setdefault((held, site), at)
        st.append(site)

    def note_released(self, site: tuple[str, int]) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == site:
                del st[i]
                return


class _WitnessedLock:
    """Pass-through proxy reporting acquisitions to the witness.  Wraps
    production threading primitives AND scheduler shims alike — the
    bookkeeping lives at the wrapper layer, so the scheduler's own
    deadlock/handoff modeling is untouched.  ``Condition.wait`` is
    deliberately NOT unwound from the held stack: the waiter reholds
    the lock when it returns, and no acquisition can happen on the
    waiting thread in between."""

    __slots__ = ("_inner", "_site", "_w")

    def __init__(self, inner: Any, site: tuple[str, int],
                 witness: LockOrderWitness) -> None:
        self._inner = inner
        self._site = site
        self._w = witness

    def acquire(self, *args: Any, **kwargs: Any) -> bool:
        ok = self._inner.acquire(*args, **kwargs)
        if ok:
            self._w.note_acquired(self._site)
        return bool(ok)

    def release(self) -> None:
        self._inner.release()
        self._w.note_released(self._site)

    def __enter__(self) -> "_WitnessedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> bool:
        self.release()
        return False

    def __getattr__(self, name: str) -> Any:
        # wait/notify/locked/... delegate to the wrapped primitive.
        return getattr(self._inner, name)


def install_witness(witness: "LockOrderWitness | None") -> None:
    """Install (or, with None, remove) the lock-order witness.
    Harness-only; refuses to stack two witnesses."""
    global _witness
    if witness is not None and _witness is not None:
        raise RuntimeError("a lock-order witness is already active")
    _witness = witness


def active_witness() -> "LockOrderWitness | None":
    return _witness


def _maybe_witness(primitive: Any) -> Any:
    w = _witness
    if w is None:
        return primitive
    site = _external_site()
    w.register(site)
    return _WitnessedLock(primitive, site, w)


def install_scheduler(sched: Any) -> None:
    """Install (or, with None, remove) the deterministic scheduler.
    Harness-only; refuses to stack two schedulers."""
    global _scheduler
    if sched is not None and _scheduler is not None:
        raise RuntimeError("a deterministic scheduler is already active")
    _scheduler = sched


def active_scheduler() -> Any:
    return _scheduler


class Thread(_threading.Thread):
    """``threading.Thread`` that an active deterministic scheduler
    adopts at ``start()`` time (its ``run()`` becomes a managed,
    schedule-controlled thread); identical to ``threading.Thread``
    otherwise."""

    def start(self) -> None:
        sched = _scheduler
        if sched is not None:
            sched.adopt_thread(self)
        else:
            super().start()

    def join(self, timeout: Optional[float] = None) -> None:
        sched = _scheduler
        if sched is not None and sched.owns_thread(self):
            sched.join_thread(self)
        else:
            super().join(timeout)


def Lock():  # noqa: N802 — mirrors the threading API it stands in for
    sched = _scheduler
    return _maybe_witness(
        sched.create_lock() if sched is not None else _threading.Lock())


def RLock():  # noqa: N802
    sched = _scheduler
    return _maybe_witness(
        sched.create_rlock() if sched is not None else _threading.RLock())


def Event():  # noqa: N802
    sched = _scheduler
    return sched.create_event() if sched is not None else _threading.Event()


def Condition(lock=None):  # noqa: N802
    sched = _scheduler
    if isinstance(lock, _WitnessedLock):
        # Hand the condition the REAL primitive; the wrapper keeps
        # witnessing direct acquisitions of the lock itself.
        lock = lock._inner
    cond = (sched.create_condition(lock) if sched is not None
            else _threading.Condition(lock))
    return _maybe_witness(cond)


def pool_executor(max_workers: int, thread_name_prefix: str = ""):
    """A ``ThreadPoolExecutor``-shaped pool (``submit`` returning a
    ``concurrent.futures.Future``, ``shutdown``).  Under the harness,
    every submitted thunk runs as a managed thread so the scheduler can
    interleave worker execution with the reconcile thread."""
    sched = _scheduler
    if sched is not None:
        return sched.create_pool(max_workers)
    return concurrent.futures.ThreadPoolExecutor(
        max_workers=max_workers, thread_name_prefix=thread_name_prefix)
