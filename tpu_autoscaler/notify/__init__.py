from tpu_autoscaler.notify.notifier import LogNotifier, Notifier, SlackNotifier

__all__ = ["LogNotifier", "Notifier", "SlackNotifier"]
