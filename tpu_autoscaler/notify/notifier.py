"""Scale-event notifications.

Reference parity: notification.py §Notifier — fire-and-forget Slack
incoming-webhook POSTs on scale events and failures, never blocking or
failing the loop.
"""

from __future__ import annotations

import logging
import threading
from typing import Protocol

log = logging.getLogger(__name__)


class Notifier(Protocol):
    def notify(self, message: str) -> None: ...


class LogNotifier:
    """Default: events go to the structured log only."""

    def notify(self, message: str) -> None:
        log.info("event: %s", message)


class SlackNotifier:
    """POST to a Slack incoming webhook on a background thread.

    Failures are logged and swallowed — a notification must never take the
    control loop down (reference behavior: notification.py).
    """

    def __init__(self, hook_url: str, channel: str | None = None):
        self._url = hook_url
        self._channel = channel

    def notify(self, message: str) -> None:
        threading.Thread(target=self._post, args=(message,),
                         daemon=True).start()

    def _post(self, message: str) -> None:
        try:
            import requests

            payload: dict = {"text": message}
            if self._channel:
                payload["channel"] = self._channel
            requests.post(self._url, json=payload, timeout=10)
        except Exception:  # noqa: BLE001 — never propagate
            log.exception("slack notification failed")
