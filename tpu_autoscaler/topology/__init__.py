"""Slice-shape / topology catalog (L3b capacity model).

TPU-native analog of the reference's ``autoscaler/capacity.py`` (Azure VM
SKU -> resource-vector table): answers "what does one new unit of supply
provide?" *before* that unit exists.  For TPUs the unit of supply is a whole
ICI slice, not a single node — a v5e-64 slice is 16 hosts that must be
provisioned and deleted atomically.
"""

from tpu_autoscaler.topology.shapes import (
    CpuShape,
    MultiSliceSpec,
    SliceShape,
)
from tpu_autoscaler.topology.catalog import (
    ACCELERATOR_LABEL,
    CPU_SHAPES,
    DEFAULT_CPU_SHAPE,
    SLICE_SHAPES,
    TOPOLOGY_LABEL,
    TPU_RESOURCE,
    cpu_shape_by_name,
    shape_by_name,
    shape_from_selectors,
    shapes_for_generation,
    smallest_shape_for_chips,
)

__all__ = [
    "ACCELERATOR_LABEL",
    "CPU_SHAPES",
    "DEFAULT_CPU_SHAPE",
    "CpuShape",
    "MultiSliceSpec",
    "SLICE_SHAPES",
    "SliceShape",
    "TOPOLOGY_LABEL",
    "TPU_RESOURCE",
    "cpu_shape_by_name",
    "shape_by_name",
    "shape_from_selectors",
    "shapes_for_generation",
    "smallest_shape_for_chips",
]
