"""The slice-shape catalog: data, not code.

TPU-native analog of the reference's hard-coded Azure SKU dict
(capacity.py §get_capacity_for_instance_type).  SURVEY.md §6.6 calls for the
capacity table to become *data*; everything here is declarative and the
lookup functions are pure, so the whole layer is testable without clusters.

Conventions (documented, deliberate):

- Shape names are ``{generation}-{chips}`` — the driver's eval configs
  (BASELINE.md) use the suffix as chip count (v5e-8 = 8 chips, v5p-256 =
  256 chips).  Where the Cloud TPU *product* name counts TensorCores
  instead (v4/v5p), the entry records ``product_name``.
- ``google.com/tpu`` is the extended resource one host exposes
  (== chips_per_host), the TPU analog of the reference's
  ``alpha.kubernetes.io/nvidia-gpu`` requests.
- Host vCPU/memory figures are approximate GKE allocatable values; the fit
  math for TPU gangs is driven by chips + selectors, with cpu/mem as a
  sanity check.
"""

from __future__ import annotations

import functools

from tpu_autoscaler.topology.shapes import CpuShape, SliceShape

# Kubernetes extended-resource name for TPU chips on GKE.
TPU_RESOURCE = "google.com/tpu"

# GKE node labels that define the TPU placement contract.
ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"

# Well-known label carried by every GKE node with its machine type; the
# analog of the reference's `beta.kubernetes.io/instance-type` node label
# (kube.py §KubeNode.instance_type).
INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"

# Label this autoscaler stamps on nodes it provisions, recording slice
# membership: every host of one slice shares a slice id. Replaces the
# reference's per-VM identity (engine_scaler.py derived pool membership from
# VM name prefixes) with an explicit, slice-atomic identity.
SLICE_ID_LABEL = "autoscaler.tpu.dev/slice-id"
POOL_LABEL = "autoscaler.tpu.dev/pool"

GiB = 1024**3


def _v5e(chips: int, topology: tuple[int, ...], chips_per_host: int,
         machine_type: str, host_cpu_m: int, host_memory: int,
         accelerator_type: str) -> SliceShape:
    return SliceShape(
        generation="v5e", chips=chips, topology=topology,
        chips_per_host=chips_per_host, accelerator_type=accelerator_type,
        machine_type=machine_type, host_cpu_m=host_cpu_m,
        host_memory=host_memory,
    )


def _v5p(chips: int, topology: tuple[int, ...]) -> SliceShape:
    # v5p: 3-D torus, 4 chips per host VM (ct5p-hightpu-4t), 2 TensorCores
    # per chip, so the marketing name's core count is 2x the chip count.
    return SliceShape(
        generation="v5p", chips=chips, topology=topology, chips_per_host=4,
        accelerator_type="tpu-v5p-slice", machine_type="ct5p-hightpu-4t",
        host_cpu_m=208_000, host_memory=448 * GiB,
        product_name=f"v5p-{chips * 2}",
    )


def _v4(chips: int, topology: tuple[int, ...]) -> SliceShape:
    return SliceShape(
        generation="v4", chips=chips, topology=topology, chips_per_host=4,
        accelerator_type="tpu-v4-podslice", machine_type="ct4p-hightpu-4t",
        host_cpu_m=240_000, host_memory=407 * GiB,
        product_name=f"v4-{chips * 2}",
    )


def _v6e(chips: int, topology: tuple[int, ...], chips_per_host: int,
         machine_type: str) -> SliceShape:
    return SliceShape(
        generation="v6e", chips=chips, topology=topology,
        chips_per_host=chips_per_host, accelerator_type="tpu-v6e-slice",
        machine_type=machine_type, host_cpu_m=180_000, host_memory=720 * GiB,
    )


_ALL_SHAPES: tuple[SliceShape, ...] = (
    # ---- v5e (2-D torus; single-host machines expose 1/4/8 chips, multi-host
    # slices use 4-chip hosts). Single-host shapes use the *-device
    # accelerator type, multi-host the *-podslice type, per GKE semantics.
    _v5e(1, (1, 1), 1, "ct5lp-hightpu-1t", 24_000, 48 * GiB, "tpu-v5-lite-device"),
    _v5e(4, (2, 2), 4, "ct5lp-hightpu-4t", 112_000, 192 * GiB, "tpu-v5-lite-device"),
    _v5e(8, (2, 4), 8, "ct5lp-hightpu-8t", 224_000, 400 * GiB, "tpu-v5-lite-device"),
    _v5e(16, (4, 4), 4, "ct5lp-hightpu-4t", 112_000, 192 * GiB, "tpu-v5-lite-podslice"),
    _v5e(32, (4, 8), 4, "ct5lp-hightpu-4t", 112_000, 192 * GiB, "tpu-v5-lite-podslice"),
    _v5e(64, (8, 8), 4, "ct5lp-hightpu-4t", 112_000, 192 * GiB, "tpu-v5-lite-podslice"),
    _v5e(128, (8, 16), 4, "ct5lp-hightpu-4t", 112_000, 192 * GiB, "tpu-v5-lite-podslice"),
    _v5e(256, (16, 16), 4, "ct5lp-hightpu-4t", 112_000, 192 * GiB, "tpu-v5-lite-podslice"),
    # ---- v5p (3-D torus, 4-chip hosts)
    _v5p(4, (2, 2, 1)),
    _v5p(8, (2, 2, 2)),
    _v5p(16, (2, 2, 4)),
    _v5p(32, (2, 4, 4)),
    _v5p(64, (4, 4, 4)),
    _v5p(128, (4, 4, 8)),
    _v5p(256, (4, 8, 8)),
    _v5p(512, (8, 8, 8)),
    _v5p(1024, (8, 8, 16)),
    # ---- v4 (3-D torus, 4-chip hosts)
    _v4(8, (2, 2, 2)),
    _v4(16, (2, 2, 4)),
    _v4(32, (2, 4, 4)),
    _v4(64, (4, 4, 4)),
    _v4(128, (4, 4, 8)),
    _v4(256, (4, 8, 8)),
    _v4(512, (8, 8, 8)),
    # ---- v6e (Trillium; 2-D torus like v5e)
    _v6e(1, (1, 1), 1, "ct6e-standard-1t"),
    _v6e(4, (2, 2), 4, "ct6e-standard-4t"),
    _v6e(8, (2, 4), 8, "ct6e-standard-8t"),
    _v6e(16, (4, 4), 4, "ct6e-standard-4t"),
    _v6e(32, (4, 8), 4, "ct6e-standard-4t"),
    _v6e(64, (8, 8), 4, "ct6e-standard-4t"),
    _v6e(128, (8, 16), 4, "ct6e-standard-4t"),
    _v6e(256, (16, 16), 4, "ct6e-standard-4t"),
)

SLICE_SHAPES: dict[str, SliceShape] = {s.name: s for s in _ALL_SHAPES}

# CPU-only node shapes for the plain agent-node path (BASELINE config #1) —
# the analog of the reference capacity table's Standard_D* rows.  Allocatable
# is machine size minus typical GKE system reservation.
CPU_SHAPES: dict[str, CpuShape] = {
    s.machine_type: s
    for s in (
        CpuShape("e2-standard-4", cpu_m=3_920, memory=13 * GiB),
        CpuShape("e2-standard-8", cpu_m=7_910, memory=27 * GiB),
        CpuShape("e2-standard-16", cpu_m=15_890, memory=56 * GiB),
        CpuShape("n2-standard-8", cpu_m=7_910, memory=27 * GiB),
        CpuShape("n2-standard-16", cpu_m=15_890, memory=56 * GiB),
        CpuShape("n2-standard-32", cpu_m=31_850, memory=115 * GiB),
    )
}

DEFAULT_CPU_SHAPE = CPU_SHAPES["e2-standard-8"]


def shape_by_name(name: str) -> SliceShape:
    """Look up a shape by catalog name, e.g. ``"v5e-64"``."""
    try:
        return SLICE_SHAPES[name]
    except KeyError:
        raise KeyError(
            f"unknown slice shape {name!r}; known: {sorted(SLICE_SHAPES)}"
        ) from None


def cpu_shape_by_name(machine_type: str) -> CpuShape:
    try:
        return CPU_SHAPES[machine_type]
    except KeyError:
        raise KeyError(
            f"unknown CPU machine type {machine_type!r}; known: {sorted(CPU_SHAPES)}"
        ) from None


def shapes_for_generation(generation: str) -> list[SliceShape]:
    """All shapes of one TPU generation, ascending by chip count."""
    out = [s for s in SLICE_SHAPES.values() if s.generation == generation]
    if not out:
        raise KeyError(f"unknown TPU generation {generation!r}")
    return sorted(out, key=lambda s: s.chips)


def smallest_shape_for_chips(generation: str, chips: int) -> SliceShape | None:
    """Smallest catalog shape of ``generation`` with >= ``chips`` chips.

    The core of the stranded-chip objective: picking the smallest satisfying
    shape minimizes (chips provisioned - chips requested).  Returns None if
    no shape of the generation is large enough.
    """
    for shape in shapes_for_generation(generation):
        if shape.chips >= chips:
            return shape
    return None


def shape_from_selectors(selectors: dict[str, str]) -> SliceShape | None:
    """Resolve the slice shape a pod's nodeSelector pins it to, if any.

    A GKE TPU workload declares placement via the accelerator + topology
    labels; this inverts that contract back to a catalog entry.  Returns
    None when the selectors name no TPU shape (CPU workloads), raises
    KeyError when they name one the catalog doesn't know.
    """
    acc = selectors.get(ACCELERATOR_LABEL)
    topo = selectors.get(TOPOLOGY_LABEL)
    if acc is None and topo is None:
        return None
    return _shape_for_labels(acc, topo)


@functools.lru_cache(maxsize=256)
def _shape_for_labels(acc: str | None, topo: str | None) -> SliceShape:
    """Catalog scan memo: the tracker and the repair detector resolve
    every slice's shape from its labels each reconcile pass — a ~30-row
    scan per unit that is pure in the (static) catalog."""
    matches = [
        s
        for s in SLICE_SHAPES.values()
        if (acc is None or s.accelerator_type == acc)
        and (topo is None or s.topology_label == topo)
    ]
    if not matches:
        raise KeyError(
            f"no catalog shape matches accelerator={acc!r} topology={topo!r}"
        )
    # Accelerator alone can match many sizes; prefer exact topology pins,
    # else the smallest (caller can widen with chip-count demand).
    return sorted(matches, key=lambda s: s.chips)[0]
