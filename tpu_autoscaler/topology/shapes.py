"""Dataclasses for TPU slice shapes and CPU node shapes.

Analog of the reference's ``autoscaler/capacity.py`` SKU table entries, but a
TPU slice is an *atomic multi-host unit*: the capacity model must expose not
just per-node resources but the whole-slice chip count, host count, and ICI
topology, because provisioning / draining / deleting all operate on whole
slices (SURVEY.md §6.7, §8 "slice-atomic semantics").
"""

from __future__ import annotations

import dataclasses
from typing import Mapping


@dataclasses.dataclass(frozen=True)
class SliceShape:
    """One provisionable TPU slice shape (an atomic ICI domain).

    Naming convention: ``{generation}-{chips}`` (e.g. ``v5e-64`` = 64 chips,
    8x8 2-D torus, 16 hosts).  This matches the driver's eval configs
    (BASELINE.md: "v5e-8", "v5e-64", "2×v5p-128", "v5p-256") which use the
    suffix as the *chip count*.  Real Cloud TPU product names for v4/v5p use
    TensorCore counts (so product "v5p-256" is 128 chips); the catalog keys
    on chips to stay consistent with the fit math — the ``product_name``
    field records the marketing name where it differs.
    """

    generation: str            # "v4" | "v5e" | "v5p" | "v6e"
    chips: int                 # total chips in the slice == prod(topology)
    topology: tuple[int, ...]  # ICI torus dims, e.g. (8, 8) or (4, 4, 8)
    chips_per_host: int        # chips on each host VM in this shape
    accelerator_type: str      # cloud.google.com/gke-tpu-accelerator value
    machine_type: str          # GKE machine type for the node pool
    host_cpu_m: int            # allocatable vCPU per host, millicores (approx)
    host_memory: int           # allocatable memory per host, bytes (approx)
    host_pods: int = 110       # pod capacity per host
    product_name: str | None = None  # marketing name when != "{gen}-{chips}"

    def __post_init__(self) -> None:
        prod = 1
        for d in self.topology:
            prod *= d
        if prod != self.chips:
            raise ValueError(
                f"topology {self.topology} has {prod} chips, expected {self.chips}"
            )
        if self.chips % self.chips_per_host != 0:
            raise ValueError(
                f"{self.chips} chips not divisible by {self.chips_per_host}/host"
            )

    @property
    def name(self) -> str:
        return f"{self.generation}-{self.chips}"

    @property
    def hosts(self) -> int:
        """Number of host VMs (== k8s nodes) in one slice."""
        return self.chips // self.chips_per_host

    @property
    def topology_label(self) -> str:
        """Value of the ``cloud.google.com/gke-tpu-topology`` node label."""
        return "x".join(str(d) for d in self.topology)

    @property
    def multi_host(self) -> bool:
        return self.hosts > 1

    def node_selectors(self) -> dict[str, str]:
        """The nodeSelector a gang must carry to land on this shape.

        Mirrors how a pending pod in the reference carried
        ``beta.kubernetes.io/instance-type`` expectations (kube.py §KubeNode
        .is_match); in GKE the contract is the accelerator + topology labels.
        """
        from tpu_autoscaler.topology.catalog import ACCELERATOR_LABEL, TOPOLOGY_LABEL

        return {
            ACCELERATOR_LABEL: self.accelerator_type,
            TOPOLOGY_LABEL: self.topology_label,
        }

    def node_capacity(self) -> Mapping[str, float]:
        """Allocatable resources of ONE host in this slice, as a plain dict.

        Analog of capacity.py §get_capacity_for_instance_type: lets the fit
        engine reason about nodes that do not exist yet.
        """
        from tpu_autoscaler.topology.catalog import TPU_RESOURCE

        return {
            "cpu": self.host_cpu_m / 1000.0,
            "memory": float(self.host_memory),
            "pods": float(self.host_pods),
            TPU_RESOURCE: float(self.chips_per_host),
        }


@dataclasses.dataclass(frozen=True)
class CpuShape:
    """A CPU-only node shape (BASELINE config #1: plain agent nodes).

    Direct analog of the non-GPU rows of the reference capacity table
    (capacity.py: Standard_D*/Standard_A* entries).
    """

    machine_type: str
    cpu_m: int       # allocatable millicores
    memory: int      # allocatable bytes
    pods: int = 110

    @property
    def name(self) -> str:
        return self.machine_type

    def node_capacity(self) -> Mapping[str, float]:
        return {
            "cpu": self.cpu_m / 1000.0,
            "memory": float(self.memory),
            "pods": float(self.pods),
        }


@dataclasses.dataclass(frozen=True)
class MultiSliceSpec:
    """N identical slices composed over DCN (BASELINE config #4: 2×v5p-128).

    Chips within each slice communicate over ICI; slices communicate over
    DCN.  The autoscaler provisions each slice atomically and treats the
    group as one demand unit for gang scheduling, but each slice remains the
    unit of drain/delete (SURVEY.md §6.8).
    """

    shape: SliceShape
    num_slices: int

    def __post_init__(self) -> None:
        if self.num_slices < 1:
            raise ValueError("num_slices must be >= 1")

    @property
    def name(self) -> str:
        return f"{self.num_slices}x{self.shape.name}"

    @property
    def total_chips(self) -> int:
        return self.shape.chips * self.num_slices

    @property
    def total_hosts(self) -> int:
        return self.shape.hosts * self.num_slices
