"""The scale planner: pending demand + live supply -> provisioning plan.

Analog of the reference's cluster.py §Cluster.scale + scaler.py policy knobs
(--over-provision, --spare-agents, pool max sizes), re-derived for
slice-atomic supply.  The planner is a pure function of its inputs (gangs,
nodes, pods, in-flight provisions, policy) so it is exhaustively unit-testable
and the reconcile loop stays crash-only: desired state is recomputed from
scratch every iteration (SURVEY.md §6.3).

Idempotence replaces the reference's "one ARM deployment in flight"
serialization (deployments.py): each provision request is tagged with the
gang it serves, so a reconcile pass never double-provisions for a gang that
already has a slice in flight — but *disjoint* gangs provision in parallel,
which is what makes <6 min at 256 chips feasible (SURVEY.md §8 hard parts).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Sequence

from tpu_autoscaler.engine.columnar import (
    ColumnarMatcher,
    ColumnarState,
    PlanColumns,
    slice_is_free,
)
from tpu_autoscaler.engine.fitter import (
    FitError,
    ShapeChoice,
    batch_choose_shapes,
    choose_shape_for_gang,
    free_capacity,
    host_slots,
    pack_cpu_pods_multi,
)
from tpu_autoscaler.k8s.gangs import Gang
from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.k8s.resources import ResourceVector
from tpu_autoscaler.topology.catalog import (
    DEFAULT_CPU_SHAPE,
    TPU_RESOURCE,
    shape_by_name,
)
from tpu_autoscaler.topology.shapes import CpuShape

log = logging.getLogger(__name__)

GangKey = tuple[str, str, str]


@dataclasses.dataclass(frozen=True)
class PoolPolicy:
    """Scaling policy knobs (reference parity: main.py flags, §3.1)."""

    default_generation: str = "v5e"
    cpu_shape: CpuShape = DEFAULT_CPU_SHAPE
    # Additional CPU machine types: a pod too big for cpu_shape opens a
    # node of the smallest extra shape that fits it (reference parity:
    # multiple agent pools of different VM sizes).
    extra_cpu_shapes: tuple[CpuShape, ...] = ()
    # Extra CPU nodes beyond computed demand (reference: --over-provision).
    over_provision_nodes: int = 0
    # Min free CPU nodes kept warm (reference: --spare-agents, default 1).
    spare_nodes: int = 1
    # Warm spare slices per shape name, e.g. {"v5e-8": 1}.
    spare_slices: dict[str, int] = dataclasses.field(default_factory=dict)
    # Clamps (reference: AgentPool.max_size).
    max_cpu_nodes: int = 100
    max_total_chips: int = 4096
    # Multi-tenant fairness: max TPU chips (in use + in flight + planned)
    # per namespace; namespaces absent from the map are bounded only by
    # max_total_chips. Demand over quota is reported unsatisfiable with a
    # quota reason, not silently queued.
    namespace_chip_quota: dict[str, int] = dataclasses.field(
        default_factory=dict)
    # Provision preemptible/spot TPU capacity (BASELINE config #5).
    preemptible: bool = False
    # Multi-tenant fair-share: when chip budget is contended, serve
    # equal-priority gangs from the namespace currently using the FEWEST
    # chips (in use + in flight) first, instead of strict age order —
    # one namespace cannot monopolize a clamped budget by arriving
    # first.  Priority still dominates; off by default (reference-like
    # FIFO within priority).
    fair_share: bool = False
    # Capacity stockout fallback: when provisioning for an UNPINNED gang
    # keeps failing (quota / stockout), retry on these generations in
    # order (e.g. ("v6e", "v5p")).  Gangs pinned by accelerator/topology
    # selectors never fall back — the pin is the user's contract.
    generation_fallbacks: tuple[str, ...] = ()
    # Consecutive failures per demand unit before stepping to the next
    # fallback generation.
    fallback_after_failures: int = 2
    # At/above this many simultaneous shape decisions in one pass, score
    # them in one native fitpack call (C, O(gangs*shapes) without Python
    # overhead) instead of per-gang Python; each native pick is still
    # validated by the authoritative Python feasibility check, and any
    # gang the native path can't decide falls back per-gang, so the two
    # paths never disagree.  Python-only below the threshold: for tens of
    # gangs the crossover doesn't pay (see bench.py fit_batch line).
    native_fit_threshold: int = 32


@dataclasses.dataclass(frozen=True)
class ProvisionRequest:
    """One atomic provisioning action for the actuator."""

    kind: str                      # "tpu-slice" | "cpu-node"
    shape_name: str                # slice shape name or CPU machine type
    # Nodes for cpu-node; SLICES for tpu-slice (count > 1 = one multislice
    # provisioning unit, e.g. a single QueuedResource with node_count=N).
    count: int = 1
    gang_key: GangKey | None = None  # demand this provision serves
    # For multislice requests: the individual member gangs served (the
    # cohort).  gang_key is then the jobset group key; siblings of the
    # jobset that bound existing free slices are NOT listed here.
    gang_keys: tuple[GangKey, ...] = ()
    reason: str = ""
    preemptible: bool = False
    stranded_chips: int = 0        # chips provisioned beyond chips requested


@dataclasses.dataclass
class ScalePlan:
    requests: list[ProvisionRequest] = dataclasses.field(default_factory=list)
    # Gangs no catalog shape / clamp allows; surfaced, never silently dropped.
    unsatisfiable: list[tuple[Gang, str]] = dataclasses.field(
        default_factory=list)
    # Advisory (slice-repair) demand that could not be admitted THIS
    # pass (clamp/quota headroom): waiting, not misconfigured — the
    # controller explains it but never reports it unsatisfiable.
    deferred: list[tuple[Gang, str]] = dataclasses.field(
        default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.requests

    @property
    def total_new_chips(self) -> int:
        return sum(shape_by_name(r.shape_name).chips * r.count
                   for r in self.requests if r.kind == "tpu-slice")


@dataclasses.dataclass(frozen=True)
class InFlight:
    """A provision the actuator has accepted but not yet materialized.

    The planner's view of actuator state — analog of the reference checking
    its single in-flight ARM deployment's provisioning state
    (deployments.py) before submitting another.
    """

    kind: str
    shape_name: str
    gang_key: GangKey | None = None
    count: int = 1


def _free_slices(nodes: list[Node], pods: list[Pod]) -> dict[str, list[Node]]:
    """Fully-idle Ready TPU slices, keyed by slice id.

    A slice counts as free supply only when *every* host is Ready,
    schedulable, and has zero TPU chips in use — partial slices are never
    supply (slice-atomicity: a half-busy slice can't take a new gang without
    bisecting the ICI domain between jobs).
    """
    by_slice: dict[str, list[Node]] = {}
    slice_hosts: set[str] = set()
    for node in nodes:
        if node.is_tpu and node.slice_id:
            by_slice.setdefault(node.slice_id, []).append(node)
            slice_hosts.add(node.name)
    if not by_slice:
        return {}
    # Chip usage only matters ON slice hosts: a fleet that is mostly
    # CPU pods (the common shape at the million-pod tier) must not pay
    # an O(all pods) accounting walk to learn its TPU slices are busy.
    used_tpu: dict[str, float] = {}
    for pod in pods:
        if pod.node_name in slice_hosts \
                and pod.phase in {"Pending", "Running"}:
            used_tpu[pod.node_name] = (used_tpu.get(pod.node_name, 0.0)
                                       + pod.resources.get(TPU_RESOURCE))
    free: dict[str, list[Node]] = {}
    for slice_id, members in by_slice.items():
        # The ONE free-slice predicate, shared with the informer's
        # CapacityView.free_slice and the columnar mask
        # (engine/columnar.slice_free_mask) so the three cannot drift.
        ready = sum(1 for n in members
                    if n.is_ready and not n.unschedulable)
        used = sum(used_tpu.get(n.name, 0.0) for n in members)
        if slice_is_free(True, len(members), ready, used):
            free[slice_id] = members
    return free


def _gang_claims_partial(members: list[Node], gang: Gang,
                         occupants: list[Pod]) -> bool:
    """A slice partially occupied ONLY by this gang's own members
    counts as the gang's supply: same-gang co-residency cannot bisect
    the ICI domain, and provisioning another slice for the remainder
    WOULD split the gang across domains.  (Fuzzer-found during slice
    repair: a recreated member binds to the fresh replacement before
    its siblings drain over; the remainder must target that slice,
    not new capacity.)  ``occupants`` is the slice's bound workload,
    precomputed once per plan.  Conservative slot math: only fully
    chip-idle Ready hosts count as room."""
    probe = gang.pods[0] if gang.pods else None
    if probe is None or not all(n.admits(probe) for n in members):
        return False
    if not occupants or any(p.gang_key != gang.key for p in occupants):
        return False
    used = {p.node_name for p in occupants}
    per_pod = gang.per_pod_resources
    free_slots = sum(host_slots(n.allocatable, per_pod)
                     for n in members
                     if n.name not in used and n.is_ready
                     and not n.unschedulable)
    return free_slots >= gang.size


def _slice_satisfies(members: list[Node], gang: Gang) -> bool:
    # Selector + taint admission, checked with a representative member pod
    # (gang members share a template).
    probe = gang.pods[0] if gang.pods else None
    if probe is None or not all(n.admits(probe) for n in members):
        return False
    total_chips = sum(int(n.allocatable.get(TPU_RESOURCE)) for n in members)
    if total_chips < gang.tpu_chips:
        return False
    # Slot math mirrors fitter.shape_feasible_for_gang: a pod cannot span
    # hosts, so count how many member pods each host can hold — the
    # binding constraint on EVERY resource axis, not just chips (a host
    # with chips for 2 pods but memory for 1 holds 1).
    per_pod = gang.per_pod_resources
    slots = sum(host_slots(n.allocatable, per_pod) for n in members)
    return slots >= gang.size


def _chips_by_namespace(pods: list[Pod],
                        in_flight: list[InFlight],
                        base: dict[str, int] | None = None
                        ) -> dict[str, int]:
    """TPU chips per namespace: bound (Pending/Running) pods plus
    in-flight slice provisions.  The single source of truth for both
    quota enforcement and fair-share ordering.  ``base`` supplies the
    bound-pod part precomputed (the columnar twin) so the in-flight
    additions stay single-sourced here."""
    if base is not None:
        used = dict(base)
    else:
        used = {}
        for p in pods:
            if p.node_name and p.phase in {"Pending", "Running"}:
                used[p.namespace] = used.get(p.namespace, 0) + p.tpu_chips
    for f in in_flight:
        if f.kind == "tpu-slice" and f.gang_key:
            ns = f.gang_key[1]
            used[ns] = (used.get(ns, 0)
                        + shape_by_name(f.shape_name).chips * f.count)
    return used


def _cohort_fair_key(cohort: list[Gang], ns_usage: dict[str, int]
                     ) -> tuple[int, int, bool, float, GangKey]:
    """Admission order under fair-share: priority desc, then namespace
    chip ledger asc, then age asc (the (None-flag, timestamp) pattern —
    naive/aware datetimes never compare), then key for determinism."""
    prio = max(g.priority for g in cohort)
    ns = cohort[0].namespace
    times = [g.oldest_created for g in cohort
             if g.oldest_created is not None]
    oldest = min(times) if times else None
    return (-prio, ns_usage.get(ns, 0), oldest is None,
            oldest.timestamp() if oldest is not None else 0.0,
            cohort[0].key)


class _PlannedNode:
    """A not-yet-existing node, for predicate simulation (NodeLike)."""

    def __init__(self, name: str, machine_type: str) -> None:
        from tpu_autoscaler.k8s.scheduling import HOSTNAME_KEY
        from tpu_autoscaler.topology.catalog import INSTANCE_TYPE_LABEL

        self.name = name
        self.labels = {HOSTNAME_KEY: name,
                       INSTANCE_TYPE_LABEL: machine_type}


def _place_constrained_cpu(constrained: list[Pod],
                           free: dict[str, ResourceVector],
                           shapes: Sequence[CpuShape],
                           all_nodes: list[Node],
                           all_pods: list[Pod],
                           ) -> tuple[dict[str, int], list[Pod],
                                      dict[str, ResourceVector]]:
    """Place CPU pods that carry hard affinity/anti-affinity/spread
    constraints, using the same predicates the (fake or real) scheduler
    enforces — plain first-fit would count capacity the scheduler will
    refuse, and the pending pod would deadlock with no provision.

    Mutates ``free`` as pods land on existing nodes.  New capacity is
    simulated with synthetic nodes (hostname + machine-type labels only:
    constraints keyed on labels we cannot know pre-creation, e.g. zone,
    conservatively block, surfacing the pod as unplaceable rather than
    provisioning capacity the scheduler may still refuse).

    Returns ``(new_nodes_per_machine_type, unplaceable_pods,
    planned_leftovers)`` — the last maps each planned node's synthetic
    name to its remaining capacity, so the caller can offer it to the
    unconstrained packing pass (the real node will have that room).
    """
    import itertools

    from tpu_autoscaler.k8s.scheduling import scheduling_blocks

    # Values are Node or _PlannedNode (the NodeLike protocol).
    nodes_by_name: dict[str, Any] = {n.name: n for n in all_nodes}
    placements: dict[str, list[Pod]] = {}
    for p in all_pods:
        if p.node_name and p.phase in {"Pending", "Running"}:
            placements.setdefault(p.node_name, []).append(p)
    shapes = sorted(shapes, key=lambda s: (s.cpu_m, s.memory))
    caps = {s.machine_type: ResourceVector(dict(s.node_capacity()))
            for s in shapes}
    new_nodes: list[list[Any]] = []  # [name, machine_type, remaining]
    counts: dict[str, int] = {}
    unplaceable: list[Pod] = []
    seq = itertools.count(1)
    for pod in sorted(constrained,
                      key=lambda p: (-p.resources.get("cpu"),
                                     -p.resources.get("memory"))):
        placed = False
        for name, cap in free.items():
            node = nodes_by_name.get(name)
            if (node is None or not node.admits(pod)
                    or not pod.resources.fits_in(cap)
                    or scheduling_blocks(pod, node, placements,
                                         nodes_by_name)):
                continue
            free[name] = cap - pod.resources
            placements.setdefault(name, []).append(pod)
            placed = True
            break
        if placed:
            continue
        for entry in new_nodes:
            name, _machine, rem = entry
            if (not pod.resources.fits_in(rem)
                    or scheduling_blocks(pod, nodes_by_name[name],
                                         placements, nodes_by_name)):
                continue
            entry[2] = rem - pod.resources
            placements.setdefault(name, []).append(pod)
            placed = True
            break
        if placed:
            continue
        for s in shapes:
            cap = caps[s.machine_type]
            if not pod.resources.fits_in(cap):
                continue
            name = f"planned-{s.machine_type}-{next(seq)}"
            node = _PlannedNode(name, s.machine_type)
            nodes_by_name[name] = node
            if scheduling_blocks(pod, node, placements, nodes_by_name):
                del nodes_by_name[name]
                continue
            new_nodes.append([name, s.machine_type, cap - pod.resources])
            placements[name] = [pod]
            counts[s.machine_type] = counts.get(s.machine_type, 0) + 1
            placed = True
            break
        if not placed:
            unplaceable.append(pod)
    leftovers = {name: rem for name, _machine, rem in new_nodes}
    return counts, unplaceable, leftovers


class Planner:
    def __init__(self, policy: PoolPolicy | None = None) -> None:
        self.policy = policy or PoolPolicy()

    def plan(self, gangs: list[Gang], nodes: list[Node], pods: list[Pod],
             in_flight: Sequence[InFlight] = (),
             generation_overrides: dict[GangKey, str] | None = None,
             advisory_gangs: Sequence[tuple[Gang, str]] = (),
             extra_existing_chips: int = 0,
             columnar: ColumnarState | None = None) -> ScalePlan:
        """``generation_overrides`` maps a gang key to the TPU generation
        to fit it on instead of the policy default — the controller sets
        it from failure streaks (capacity stockout fallback).

        ``advisory_gangs`` is advisory demand: ``(gang, shape_name)``
        pairs naming an exact slice shape.  Two producers ride it —
        ICI-atomic repair replacements (ISSUE 7: the broken unit's own
        shape, because the gang may be partially observed mid-repair)
        and the policy engine's predictive prewarms (ISSUE 8:
        synthetic gangs keyed ``("prewarm", ...)`` ahead of forecast
        demand).  Either way the planner decides admission with the
        same free-slice/clamp/quota algebra as organic demand, AFTER
        organic demand (advisory work never displaces a real gang).
        Inadmissible advisory demand lands in ``plan.deferred``, never
        ``plan.unsatisfiable``.  The planner stays a pure function of
        its inputs (TAP1xx).

        ``extra_existing_chips`` counts TPU chips that exist in the
        fleet but are OUTSIDE ``nodes`` — the sharded reconcile path
        (ISSUE 13, docs/SHARDING.md) plans each accelerator-class
        shard against its own node slice while the max_total_chips
        clamp stays fleet-global, so the sharder passes the
        complement's chip total here.  0 (the default, and the serial
        path) means ``nodes`` IS the fleet.

        ``columnar`` is the struct-of-arrays twin of ``(nodes, pods)``
        (engine/columnar.py, docs/PLANNER.md): when it aligns, the
        free-slice / admission / claim hot loops run vectorized with
        value-identical results; any misalignment or columnar error
        degrades to the Python loops silently (crash-only).  The
        Python path stays the property oracle — ``verify_columnar_
        plans`` replans with ``columnar=None`` and compares."""
        plan = ScalePlan()
        pol = self.policy
        gen_override = generation_overrides or {}

        tpu_gangs = [g for g in gangs if g.requests_tpu]
        cpu_pods = [p for g in gangs if not g.requests_tpu for p in g.pods]

        # ---- columnar fast path (engine/columnar.py) ---------------------
        # Attach only when the state provably aligns with (nodes, pods);
        # every consumer below falls back to its Python twin on any error.
        cols: PlanColumns | None = None
        matcher: ColumnarMatcher | None = None
        free: dict[str, list[Node]] | None = None
        existing_cols: int | None = None
        ns_base: dict[str, int] | None = None
        if columnar is not None:
            try:
                if columnar.attachable(nodes, pods):
                    cols = PlanColumns(columnar)
                    free, _free_mask = cols.free_slices()
                    matcher = ColumnarMatcher(cols, _slice_satisfies)
                    existing_cols = cols.existing_tpu_chips()
                    if pol.namespace_chip_quota or pol.fair_share:
                        ns_base = cols.chips_by_namespace()
            except Exception:  # noqa: BLE001 — crash-only: a columnar
                # bug degrades to the Python oracle path, never fails
                # the plan pass.
                log.exception("columnar attach failed; Python fallback")
                cols = matcher = None
                free = existing_cols = ns_base = None

        # ---- TPU path: one slice per unserved gang -----------------------
        if free is None:
            free = _free_slices(nodes, pods)
        claimed: set[str] = set()
        served_keys = {f.gang_key for f in in_flight if f.gang_key}
        existing_chips = extra_existing_chips + (
            existing_cols if existing_cols is not None else sum(
                int(n.allocatable.get(TPU_RESOURCE))
                for n in nodes if n.is_tpu))
        inflight_chips = sum(shape_by_name(f.shape_name).chips * f.count
                             for f in in_flight if f.kind == "tpu-slice")
        planned_chips = 0
        # Per-namespace chip accounting for quota enforcement (enforced at
        # provisioning time: in-use by bound pods + in-flight + planned).
        # One per-namespace chip ledger (in use + in flight, then updated
        # with planned chips at each admission) serves BOTH quota
        # enforcement and fair-share ordering — one algebra, no drift.
        ns_chips: dict[str, int] = (
            _chips_by_namespace(pods, in_flight, base=ns_base)
            if pol.namespace_chip_quota or pol.fair_share else {})

        # Gang keys served by THIS plan's organic pass (free-slice match
        # or an emitted request): the advisory repair pass must never
        # double up on them.
        served_now: set[GangKey] = set()

        # Partial-claim state (slice membership + bound workload per
        # slice), built LAZILY at most once per plan: only gangs that
        # fall through the fully-free match need it, and the common
        # all-matched/all-provisioned pass must not pay an extra
        # O(nodes)+O(pods) walk (the PR-6 O(churn) contract — plan()
        # runs twice per pass under verify_delta_plans).
        partial_state: tuple[dict[str, list[Node]],
                             dict[str, list[Pod]]] | None = None

        def partial_claims() -> tuple[dict[str, list[Node]],
                                      dict[str, list[Pod]]]:
            nonlocal partial_state
            if partial_state is None:
                by_slice: dict[str, list[Node]] = {}
                node_slice: dict[str, str] = {}
                for node in nodes:
                    if node.is_tpu and node.slice_id:
                        by_slice.setdefault(node.slice_id,
                                            []).append(node)
                        node_slice[node.name] = node.slice_id
                occupants: dict[str, list[Pod]] = {}
                for p in pods:
                    if p.node_name and p.phase in {"Pending", "Running"} \
                            and p.is_workload:
                        sid_of = node_slice.get(p.node_name)
                        if sid_of is not None:
                            occupants.setdefault(sid_of, []).append(p)
                partial_state = (by_slice, occupants)
            return partial_state

        def match_free(gang: Gang) -> str | None:
            nonlocal matcher
            if matcher is not None:
                # Vectorized scan, candidate order identical to the dict
                # walks below (docs/PLANNER.md).
                try:
                    return matcher.match(gang, claimed)
                except Exception:  # noqa: BLE001 — crash-only: degrade
                    # to the Python scan for the rest of the pass.
                    log.exception("columnar match failed; Python fallback")
                    matcher = None
            # An existing fully-free matching slice satisfies the gang; the
            # scheduler will bind it — provisioning would strand chips.
            sid = next(
                (sid for sid, members in free.items()
                 if sid not in claimed and _slice_satisfies(members, gang)),
                None)
            if sid is not None:
                return sid
            # A slice the gang ALREADY partially occupies (and nothing
            # else does) is its supply too — the remainder binds beside
            # its siblings instead of splitting the gang.  Candidates
            # prefiltered to slices whose occupants lead with this gang.
            by_slice, occupants_by_slice = partial_claims()
            return next(
                (sid for sid, occ in occupants_by_slice.items()
                 if sid not in free and sid not in claimed
                 and occ[0].gang_key == gang.key
                 and _gang_claims_partial(by_slice[sid], gang, occ)),
                None)

        # ---- provisioning cohorts ------------------------------------
        # Pending sibling gangs of one JobSet (a multislice job: one gang
        # per slice over DCN) provision as ONE unit — a single request
        # with count=N, which the QueuedResource actuator submits as one
        # QR with node_count=N so Cloud TPU co-schedules the slices (the
        # XPK model; BASELINE config #4 / SURVEY §6.8).  A lone pending
        # sibling (e.g. replacing one failed slice of an established
        # multislice) provisions solo.
        cohorts: list[list[Gang]] = []
        processed: set[GangKey] = set()
        for gang in tpu_gangs:
            if gang.key in processed or gang.key in served_keys:
                continue
            group_key = gang.multislice_group_key
            if group_key is not None and group_key in served_keys:
                continue  # multislice provision in flight for this jobset
            processed.add(gang.key)
            matched = match_free(gang)
            if matched is not None:
                claimed.add(matched)
                served_now.add(gang.key)
                continue
            cohort = [gang]
            if group_key is not None:
                for sib in tpu_gangs:
                    if (sib.key in processed or sib.key in served_keys
                            or sib.multislice_group_key != group_key):
                        continue
                    processed.add(sib.key)
                    m = match_free(sib)
                    if m is not None:
                        claimed.add(m)
                        served_now.add(sib.key)
                    else:
                        cohort.append(sib)
            cohorts.append(cohort)

        # Bulk-score large decision batches with the native kernel
        # (fleet-scale admission); absent entries fall back per-gang.
        # Gangs with a generation override go per-gang (the batch scorer
        # runs against the default generation's catalog).
        decisions = [g for cohort in cohorts for g in cohort
                     if g.key not in gen_override]
        batch_choices = (
            batch_choose_shapes(decisions, pol.default_generation)
            if len(decisions) >= pol.native_fit_threshold else {})

        remaining = list(cohorts)
        while remaining:
            if pol.fair_share:
                # Re-weigh EVERY admission: each admitted unit raises its
                # namespace's ledger, so the next pick goes to whichever
                # namespace now uses the least — a single low-usage
                # namespace cannot capture every slot in one pass.
                remaining.sort(key=lambda c: _cohort_fair_key(c, ns_chips))
            cohort = remaining.pop(0)
            members: list[tuple[Gang, ShapeChoice]] = []
            for g in cohort:
                if g.key in batch_choices:
                    members.append((g, batch_choices[g.key]))
                    continue
                try:
                    members.append(
                        (g, choose_shape_for_gang(
                            g, gen_override.get(g.key,
                                                pol.default_generation))))
                except FitError as e:
                    plan.unsatisfiable.append((g, str(e)))
            if not members:
                continue
            # One multislice unit needs a uniform accelerator shape; a
            # heterogeneous jobset (unusual) degrades to solo provisions.
            if (len(members) >= 2
                    and len({c.shape.name for _, c in members}) == 1):
                units = [members]
            else:
                units = [[m] for m in members]
            for unit in units:
                gangs_u = [g for g, _ in unit]
                choice = unit[0][1]
                n = len(unit)
                unit_chips = choice.shape.chips * n
                new_total = (existing_chips + inflight_chips
                             + planned_chips + unit_chips)
                if new_total > pol.max_total_chips:
                    for g in gangs_u:
                        plan.unsatisfiable.append(
                            (g, f"would exceed max_total_chips="
                                f"{pol.max_total_chips} (at {new_total})"))
                    continue
                ns = gangs_u[0].namespace
                ns_new = ns_chips.get(ns, 0) + unit_chips
                quota = pol.namespace_chip_quota.get(ns)
                if quota is not None and ns_new > quota:
                    for g in gangs_u:
                        plan.unsatisfiable.append(
                            (g, f"namespace {ns!r} chip quota "
                                f"{quota} exceeded (at {ns_new})"))
                    continue
                ns_chips[ns] = ns_new
                planned_chips += unit_chips
                stranded = sum(c.stranded_chips for _, c in unit)
                if n == 1:
                    g = gangs_u[0]
                    key, reason = g.key, (
                        f"gang {g.name}: {g.tpu_chips} chips, "
                        f"{stranded} stranded")
                else:
                    key = gangs_u[0].multislice_group_key
                    reason = (
                        f"multislice jobset {key[2]}: {n}x "
                        f"{choice.shape.name} "
                        f"({sum(g.tpu_chips for g in gangs_u)} chips, "
                        f"{stranded} stranded)")
                served_now.update(g.key for g in gangs_u)
                if key is not None:
                    served_now.add(key)
                plan.requests.append(ProvisionRequest(
                    kind="tpu-slice", shape_name=choice.shape.name,
                    count=n, gang_key=key,
                    gang_keys=tuple(g.key for g in gangs_u),
                    preemptible=pol.preemptible,
                    stranded_chips=stranded, reason=reason))

        # ---- advisory repair demand (ISSUE 7) ----------------------------
        # Like-for-like replacement slices for units under ICI-atomic
        # repair.  Admitted AFTER organic demand (a re-pended gang
        # outranks a pre-provisioned repair under clamp contention —
        # the repaired gang becomes organic demand itself once its pods
        # are evicted) and BEFORE spares.  A free slice of exactly the
        # replacement shape satisfies the repair without provisioning:
        # the drain hands the gang to it.
        for gang, shape_name in advisory_gangs:
            if not gang.requests_tpu:
                continue  # repairs are slice-scoped by construction
            group_key = gang.multislice_group_key
            if gang.key in served_keys or gang.key in served_now \
                    or (group_key is not None
                        and (group_key in served_keys
                             or group_key in served_now)):
                continue  # replacement already in flight / served above
            shape = shape_by_name(shape_name)
            # Exact-shape match, with the same selector/taint admission
            # probe as the organic path: a tainted free slice (e.g. an
            # impending-termination notice) must not silently satisfy
            # the repair and suppress the real replacement.
            probe = gang.pods[0] if gang.pods else None
            matched = next(
                (sid for sid, members in free.items()
                 if sid not in claimed
                 and len(members) == shape.hosts
                 and probe is not None
                 and all(n.tpu_accelerator == shape.accelerator_type
                         and n.tpu_topology == shape.topology_label
                         and n.admits(probe)
                         for n in members)),
                None)
            if matched is not None:
                claimed.add(matched)
                continue
            new_total = (existing_chips + inflight_chips + planned_chips
                         + shape.chips)
            if new_total > pol.max_total_chips:
                plan.deferred.append(
                    (gang, f"would exceed max_total_chips="
                           f"{pol.max_total_chips} (at {new_total})"))
                continue
            ns = gang.namespace
            quota = pol.namespace_chip_quota.get(ns)
            if quota is not None:
                ns_new = ns_chips.get(ns, 0) + shape.chips
                if ns_new > quota:
                    plan.deferred.append(
                        (gang, f"namespace {ns!r} chip quota {quota} "
                               f"exceeded (at {ns_new})"))
                    continue
                ns_chips[ns] = ns_new
            planned_chips += shape.chips
            # Advisory demand is repair replacements (ISSUE 7) or
            # policy prewarms (ISSUE 8) — same admission algebra, told
            # apart by the synthetic "prewarm" key prefix so logs and
            # notifications say what the chips are actually for.
            if gang.key and gang.key[0] == "prewarm":
                reason = (f"predictive prewarm: {shape.name} ahead of "
                          f"forecast demand ({gang.name})")
            else:
                reason = (f"slice repair: like-for-like {shape.name} "
                          f"replacement for gang {gang.name}")
            plan.requests.append(ProvisionRequest(
                kind="tpu-slice", shape_name=shape.name, count=1,
                gang_key=gang.key, preemptible=pol.preemptible,
                reason=reason))

        # ---- warm spare slices (reference --spare-agents, per shape) -----
        for shape_name, want in pol.spare_slices.items():
            shape = shape_by_name(shape_name)
            have_free = sum(
                1 for sid, members in free.items()
                if sid not in claimed
                and all(n.tpu_accelerator == shape.accelerator_type
                        and n.tpu_topology == shape.topology_label
                        for n in members))
            have_inflight = sum(1 for f in in_flight
                                if f.kind == "tpu-slice" and f.gang_key is None
                                and f.shape_name == shape_name)
            for _ in range(max(0, want - have_free - have_inflight)):
                if (existing_chips + inflight_chips + planned_chips
                        + shape.chips) > pol.max_total_chips:
                    break
                planned_chips += shape.chips
                plan.requests.append(ProvisionRequest(
                    kind="tpu-slice", shape_name=shape_name,
                    preemptible=pol.preemptible,
                    reason=f"spare slice policy ({want} warm {shape_name})"))

        # ---- CPU path: first-fit pack, then spare + over-provision -------
        cpu_nodes = [n for n in nodes if not n.is_tpu]
        free_cpu: dict[str, ResourceVector] | None = None
        if cols is not None:
            try:
                free_cpu = cols.free_cpu_capacity()
            except Exception:  # noqa: BLE001 — crash-only fallback
                log.exception("columnar free_capacity failed; fallback")
                free_cpu = None
        if free_cpu is None:
            free_cpu = free_capacity(cpu_nodes, pods)
        pending_cpu = [p for p in cpu_pods if p.is_unschedulable]
        inflight_cpu = sum(f.count for f in in_flight
                           if f.kind == "cpu-node")
        cpu_shapes = (pol.cpu_shape, *pol.extra_cpu_shapes)
        # Pods with hard affinity/anti-affinity/spread constraints go
        # through predicate-aware placement FIRST (they are the pickiest);
        # plain resource packing would credit capacity the scheduler will
        # refuse and the pod would deadlock pending.
        from tpu_autoscaler.k8s.scheduling import has_scheduling_constraints

        total_pending_cpu = len(pending_cpu)
        constrained = [p for p in pending_cpu
                       if has_scheduling_constraints(p)]
        c_counts: dict[str, int] = {}
        c_unplaceable: list[Pod] = []
        if constrained:
            pending_cpu = [p for p in pending_cpu
                           if not has_scheduling_constraints(p)]
            c_counts, c_unplaceable, c_leftovers = _place_constrained_cpu(
                constrained, free_cpu, cpu_shapes, nodes, pods)
            # Planned nodes' remaining room is real capacity-to-be:
            # offer it to the unconstrained pass so mixed demand doesn't
            # open a second node where one suffices.
            free_cpu.update(c_leftovers)
        counts, unplaceable = pack_cpu_pods_multi(
            pending_cpu, free_cpu, cpu_shapes,
            nodes_by_name={n.name: n for n in cpu_nodes},
            native_threshold=pol.native_fit_threshold)
        for machine, n_new in c_counts.items():
            counts[machine] = counts.get(machine, 0) + n_new
        unplaceable = list(unplaceable) + c_unplaceable
        if unplaceable:
            gang_by_key = {g.key: g for g in gangs}
            reported: set[GangKey] = set()
            shapes_desc = "/".join(s.machine_type for s in cpu_shapes)
            constrained_keys = {id(p) for p in c_unplaceable}
            for pod in unplaceable:
                if pod.gang_key in reported:
                    continue
                reported.add(pod.gang_key)
                if id(pod) in constrained_keys:
                    reason = (f"pod {pod.name}: hard affinity/spread "
                              "constraints admit no existing node and "
                              "cannot be satisfied by new capacity")
                else:
                    reason = (f"pod {pod.name} requests "
                              f"{pod.resources!r}, larger than one "
                              f"{shapes_desc} node")
                plan.unsatisfiable.append((
                    gang_by_key.get(pod.gang_key,
                                    Gang(key=pod.gang_key, pods=[pod])),
                    reason))
        # In-flight nodes of the SAME machine type serve demand first
        # (idempotence): an in-flight small node must not cancel demand
        # for a large node a pod requires.
        inflight_by_machine: dict[str, int] = {}
        for f in in_flight:
            if f.kind == "cpu-node":
                inflight_by_machine[f.shape_name] = (
                    inflight_by_machine.get(f.shape_name, 0) + f.count)
        for machine in list(counts):
            take = min(counts[machine], inflight_by_machine.get(machine, 0))
            counts[machine] -= take
        demand_needed = sum(counts.values())
        # Over-provision and spare nodes are primary-shape EXTRAS, tracked
        # apart from demand so clamps shed them first (a warm spare must
        # never displace the node a pending pod needs).
        primary = pol.cpu_shape.machine_type
        extras = pol.over_provision_nodes if demand_needed else 0
        # Spare: keep at least N workload-free CPU nodes warm.  "Free" means
        # no non-daemonset/non-mirror pods — daemonsets run on every node
        # and must not disqualify a node from being spare.
        fully_free = -1
        if cols is not None:
            try:
                fully_free = cols.fully_free_cpu()
            except Exception:  # noqa: BLE001 — crash-only fallback
                log.exception("columnar fully_free failed; fallback")
                fully_free = -1
        if fully_free < 0:
            workload_nodes = {
                p.node_name for p in pods
                if p.node_name and p.phase in {"Pending", "Running"}
                and not p.is_daemonset and not p.is_mirrored}
            fully_free = sum(
                1 for n in cpu_nodes
                if n.is_ready and not n.unschedulable
                and n.name not in workload_nodes)
        spare_shortfall = max(
            0, pol.spare_nodes - fully_free - inflight_cpu - demand_needed)
        extras += spare_shortfall
        # Clamp total new CPU nodes to the room left under max_cpu_nodes
        # (reference: AgentPool.max_size).  Shed order: extras (spare /
        # over-provision) first, then primary-shape demand (small pods are
        # likelier to repack), extra-shape demand last (big pods have no
        # alternative home).  Shed demand is logged, never silent.
        room = max(0, pol.max_cpu_nodes - len(cpu_nodes) - inflight_cpu)
        overflow = max(0, demand_needed + extras - room)
        take = min(overflow, extras)
        extras -= take
        overflow -= take
        if overflow:
            log.warning(
                "max_cpu_nodes=%d clamps %d needed CPU node(s); pods will "
                "stay Pending", pol.max_cpu_nodes, overflow)
            for machine in sorted(
                    counts, key=lambda m: m != primary):
                take = min(overflow, counts[machine])
                counts[machine] -= take
                overflow -= take
        counts[primary] = counts.get(primary, 0) + extras
        for machine, count in sorted(counts.items()):
            if count > 0:
                plan.requests.append(ProvisionRequest(
                    kind="cpu-node", shape_name=machine, count=count,
                    reason=(f"{total_pending_cpu} pending CPU pods, "
                            f"spare={pol.spare_nodes}")))
        return plan
