"""Fit / decision engine (L4 policy math).

Analog of the reference's cluster.py §Cluster.scale (first-fit bin-packing of
pending pods into agent-pool units), rebuilt around two TPU-native ideas:

- the demand unit is the *gang* (not the pod) and the supply unit is the
  *slice* (not the node);
- shape selection minimizes stranded chips (chips provisioned minus chips
  requested), tie-breaking toward fewer hosts.
"""

from tpu_autoscaler.engine.fitter import (
    FitError,
    choose_shape_for_gang,
    free_capacity,
    pack_cpu_pods,
)
from tpu_autoscaler.engine.planner import (
    PoolPolicy,
    ProvisionRequest,
    ScalePlan,
    Planner,
)

__all__ = [
    "FitError",
    "Planner",
    "PoolPolicy",
    "ProvisionRequest",
    "ScalePlan",
    "choose_shape_for_gang",
    "free_capacity",
    "pack_cpu_pods",
]
