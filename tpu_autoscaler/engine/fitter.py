"""Shape selection and bin-packing primitives.

Reference parity: cluster.py §Cluster.scale did `for pod: find pool whose
unit capacity + selectors fit` then accumulated whole-node units.  Here the
TPU path picks a whole slice per gang (stranded-chip objective) and the CPU
path keeps the reference's first-fit whole-node accumulation.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Sequence

from tpu_autoscaler.k8s.gangs import Gang
from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.k8s.resources import ResourceVector
from tpu_autoscaler.topology.catalog import (
    ACCELERATOR_LABEL,
    TOPOLOGY_LABEL,
    TPU_RESOURCE,
    shape_from_selectors,
    shapes_for_generation,
)
from tpu_autoscaler.topology.shapes import CpuShape, SliceShape


class FitError(Exception):
    """A gang that can never be satisfied by the catalog (too big, unknown
    selectors, inconsistent topology pin)."""


@dataclasses.dataclass(frozen=True)
class ShapeChoice:
    shape: SliceShape
    stranded_chips: int

    @property
    def stranded_pct(self) -> float:
        return 100.0 * self.stranded_chips / self.shape.chips


def _generation_of_accelerator(accelerator: str) -> str | None:
    for gen in ("v4", "v5e", "v5p", "v6e"):
        for s in shapes_for_generation(gen):
            if s.accelerator_type == accelerator:
                return gen
    return None


def host_slots(allocatable: ResourceVector, per_pod: ResourceVector) -> int:
    """How many copies of ``per_pod`` fit in one host's ``allocatable`` —
    the binding constraint on EVERY resource axis (a host with chips for 2
    pods but memory for 1 holds 1)."""
    slots = None
    for key, req in per_pod.as_dict().items():
        if req <= 0:
            continue
        fit = int(allocatable.get(key) // req)
        slots = fit if slots is None else min(slots, fit)
    return 1 if slots is None else slots  # zero-request pod: 1 per host


@functools.lru_cache(maxsize=None)
def _host_capacity(shape: SliceShape) -> ResourceVector:
    """One host's capacity vector, memoized per shape (the catalog is
    static data, and feasibility checks run O(gangs x shapes) per pass).
    lru_cache over the frozen SliceShape keeps this module free of
    mutable global state (TAP104)."""
    return ResourceVector(dict(shape.node_capacity()))


def shape_feasible_for_gang(shape: SliceShape, gang: Gang) -> str | None:
    """Why ``gang`` cannot run on one ``shape`` slice, or None if it can.

    A pod cannot span hosts, so total-chip arithmetic alone is not enough:
    each member pod must fit one host on every resource axis, and there
    must be enough host slots for all members.  Without this check the
    planner would provision a slice the scheduler can never bind, see it
    free next pass, and provision another — a runaway loop.
    """
    chips = gang.tpu_chips
    per_pod = gang.per_pod_resources
    per_pod_chips = int(per_pod.get(TPU_RESOURCE))
    if chips > shape.chips:
        return (f"demands {chips} chips, shape {shape.name} has "
                f"{shape.chips}")
    if per_pod_chips > shape.chips_per_host:
        return (f"pod requests {per_pod_chips} chips but {shape.name} "
                f"hosts expose {shape.chips_per_host}")
    host_capacity = _host_capacity(shape)
    if not per_pod.fits_in(host_capacity):
        return (f"pod request {per_pod!r} exceeds one {shape.name} host's "
                f"capacity")
    slots = shape.hosts * host_slots(host_capacity, per_pod)
    if gang.size > slots:
        return (f"{gang.size} pods need {gang.size} host slots, "
                f"{shape.name} has {slots}")
    return None


def choose_shape_for_gang(gang: Gang,
                          default_generation: str = "v5e") -> ShapeChoice:
    """Pick the slice shape for one pending TPU gang.

    Resolution order:

    1. Exact topology pin (`gke-tpu-topology` selector) — the gang said
       precisely which ICI torus it wants; honor it, but fail loudly if the
       gang can never fit it (a gang that can never schedule).
    2. Accelerator pin only — smallest *feasible* shape of that generation
       (stranded-chip objective, subject to per-host fit).
    3. No TPU selectors — smallest feasible shape of the default generation.
    """
    selectors = gang.node_selectors
    chips = gang.tpu_chips
    if chips <= 0:
        raise FitError(f"{gang} requests no TPU chips")

    if TOPOLOGY_LABEL in selectors:
        try:
            shape = shape_from_selectors(selectors)
        except KeyError as e:
            raise FitError(str(e)) from None
        assert shape is not None
        problem = shape_feasible_for_gang(shape, gang)
        if problem:
            raise FitError(f"{gang} pins {shape.topology_label}: {problem}")
        return ShapeChoice(shape, shape.chips - chips)

    accelerator = selectors.get(ACCELERATOR_LABEL)
    if accelerator is not None:
        gen = _generation_of_accelerator(accelerator)
        if gen is None:
            raise FitError(f"unknown accelerator type {accelerator!r}")
    else:
        gen = default_generation

    last_problem = None
    for shape in shapes_for_generation(gen):
        if shape.chips < chips:
            continue
        last_problem = shape_feasible_for_gang(shape, gang)
        if last_problem is None:
            return ShapeChoice(shape, shape.chips - chips)
    raise FitError(
        f"no {gen} shape can host {gang}: "
        f"{last_problem or f'largest is {shapes_for_generation(gen)[-1].chips} chips'}")


def batch_choose_shapes(gangs: list[Gang],
                        default_generation: str = "v5e",
                        backend: str = "native"
                        ) -> dict[tuple[str, str, str], "ShapeChoice"]:
    """Bulk shape choice via a batch kernel: the native fitpack library
    (native/fitpack.cpp) or the vectorized numpy scorer (engine/jaxfit).

    Scores every unpinned gang against the generation's catalog in one
    call instead of O(gangs x shapes) Python — the planner switches to
    this above ``PoolPolicy.native_fit_threshold`` simultaneous
    decisions.  ``backend``: "native" (default; empty result when no
    toolchain), "jaxfit" (the vectorized kernel — same math, no
    toolchain needed), or "auto" (native, falling back to jaxfit).

    Decision safety: both kernels cover the chip axes only, so each
    pick is re-validated with the authoritative Python
    ``shape_feasible_for_gang`` (host cpu/memory binding).  Gangs whose
    pick fails validation, gangs with accelerator/topology pins, and
    all gangs when no backend is available are simply absent from the
    result — the caller falls back to ``choose_shape_for_gang``, so the
    paths can never disagree on a final decision.
    """
    from tpu_autoscaler import native

    use_native = backend in ("native", "auto")
    if use_native and not native.available():
        if backend == "native":
            return {}
        use_native = False

    def integral_chips(g: Gang) -> bool:
        # The kernels' slot math clamps per-pod to >=1 chip; fractional
        # TPU requests (parseable, if nonsensical) would diverge from
        # Python host_slots — keep such gangs on the Python path.
        per = g.per_pod_resources.get(TPU_RESOURCE)
        return per >= 1 and per == int(per)

    eligible = [
        g for g in gangs
        if g.tpu_chips > 0 and g.size > 0 and integral_chips(g)
        and ACCELERATOR_LABEL not in g.node_selectors
        and TOPOLOGY_LABEL not in g.node_selectors
    ]
    if not eligible:
        return {}
    shapes = shapes_for_generation(default_generation)
    shape_rows = [(float(s.chips), float(s.chips_per_host), float(s.hosts))
                  for s in shapes]
    gang_rows = [
        (float(g.tpu_chips),
         float(g.per_pod_resources.get(TPU_RESOURCE)),
         float(g.size))
        for g in eligible
    ]
    if use_native:
        scored = native.best_shapes(gang_rows, shape_rows)
    else:
        from tpu_autoscaler.engine.jaxfit import best_shapes_np

        name_to_idx = {s.name: i for i, s in enumerate(shapes)}
        scored = [(-1 if name is None else name_to_idx[name], stranded)
                  for name, stranded
                  in best_shapes_np(gang_rows, default_generation)]
    if scored is None:
        return {}
    out: dict[tuple[str, str, str], ShapeChoice] = {}
    for g, (idx, stranded) in zip(eligible, scored):
        if idx < 0:
            continue  # infeasible: Python path reports the exact reason
        shape = shapes[idx]
        # When the gang's per-pod request has ONLY the TPU axis, the C
        # kernel's math (total chips, chips/host, host slots) is exactly
        # shape_feasible_for_gang's — provably the same decision, no
        # re-validation needed.  Any other axis (cpu/memory bind on the
        # host) gets the authoritative Python check; a failed check drops
        # the gang to the per-gang Python fallback.
        per_pod_axes = set(g.per_pod_resources.as_dict())
        if (per_pod_axes <= {TPU_RESOURCE}
                or shape_feasible_for_gang(shape, g) is None):
            out[g.key] = ShapeChoice(shape, int(stranded))
    return out


def free_capacity(nodes: list[Node], pods: list[Pod],
                  include_unschedulable: bool = False,
                  ) -> dict[str, ResourceVector]:
    """Free allocatable per schedulable Ready node (allocatable - requests).

    The baseline the fit engine subtracts existing supply with, mirroring how
    the reference computed pool `actual_capacity` from live nodes
    (agent_pool.py §AgentPool).

    ``include_unschedulable=True`` counts cordoned nodes too — used when
    deciding whether pending demand could claim a DRAINING unit (whose
    nodes are cordoned by construction) so the drain can be cancelled
    instead of deleting capacity the demand is about to need.
    """
    used: dict[str, ResourceVector] = {}
    for pod in pods:
        if pod.node_name and pod.phase in {"Pending", "Running"}:
            used[pod.node_name] = used.get(pod.node_name,
                                           ResourceVector()) + pod.resources
    free: dict[str, ResourceVector] = {}
    for node in nodes:
        if node.is_ready and (include_unschedulable
                              or not node.unschedulable):
            free[node.name] = node.allocatable - used.get(node.name,
                                                          ResourceVector())
    return free


def pack_cpu_pods_multi(pods: list[Pod], free: dict[str, ResourceVector],
                        shapes: Sequence[CpuShape],
                        nodes_by_name: dict[str, Node] | None = None,
                        native_threshold: int | None = None
                        ) -> tuple[dict[str, int], list[Pod]]:
    """First-fit pending CPU pods into free capacity, then into new nodes.

    Returns ``(new_nodes_per_machine_type, unplaceable_pods)``.  Reference
    parity: cluster.py §Cluster.scale first-fit packed pods into whole
    agent-pool units and the cluster could have several pools of different
    VM sizes — here ``shapes`` plays that role; a pod that overflows
    existing capacity opens a unit of the SMALLEST machine type that fits
    it.  ``free`` is mutated as pods are placed so callers pass a fresh
    copy.  Pods that fit no machine type are returned as unplaceable
    (never silently dropped).

    ``native_threshold``: at/above this many pods, the O(pods × nodes)
    inner loop runs in the wide native kernel
    (``fitpack_pack_ffd_multi``) — same FFD order (sorted here, in
    Python), same axis algebra, with admission (selectors + taints)
    pre-computed per pod-template × node so the kernel and the Python
    path can never disagree; the Python loop remains the reference
    semantics and the fallback.
    """
    shapes = sorted(shapes, key=lambda s: (s.cpu_m, s.memory))
    capacities = {
        s.machine_type: ResourceVector(
            {k: v for k, v in s.node_capacity().items()})
        for s in shapes
    }
    # First-fit-DECREASING: big pods open units first so small pods pack
    # into their remainders instead of opening units of their own (the
    # outcome must not depend on arrival order).
    pods = sorted(pods, key=lambda p: (-p.resources.get("cpu"),
                                       -p.resources.get("memory")))
    if native_threshold is not None and len(pods) >= native_threshold:
        packed = _pack_cpu_pods_native(pods, free, shapes, capacities,
                                       nodes_by_name)
        if packed is not None:
            return packed
    new_units: list[tuple[str, ResourceVector]] = []  # (machine, remaining)
    unplaceable: list[Pod] = []
    for pod in pods:
        placed = False
        for name, cap in free.items():
            node = (nodes_by_name or {}).get(name)
            if node is not None and not node.admits(pod):
                continue
            if pod.resources.fits_in(cap):
                free[name] = cap - pod.resources
                placed = True
                break
        if placed:
            continue
        for i, (machine, cap) in enumerate(new_units):
            if pod.resources.fits_in(cap):
                new_units[i] = (machine, cap - pod.resources)
                placed = True
                break
        if placed:
            continue
        for shape in shapes:
            cap = capacities[shape.machine_type]
            if pod.resources.fits_in(cap):
                new_units.append((shape.machine_type, cap - pod.resources))
                placed = True
                break
        if not placed:
            unplaceable.append(pod)
    counts: dict[str, int] = {}
    for machine, _ in new_units:
        counts[machine] = counts.get(machine, 0) + 1
    return counts, unplaceable


def _pack_cpu_pods_native(pods: list[Pod],
                          free: dict[str, ResourceVector],
                          shapes: Sequence[CpuShape],
                          capacities: dict[str, ResourceVector],
                          nodes_by_name: dict[str, Node] | None
                          ) -> tuple[dict[str, int], list[Pod]] | None:
    """The wide-kernel body of ``pack_cpu_pods_multi``.

    ``pods`` arrive already FFD-sorted (same ``sorted`` call as the
    Python loop).  Admission templates: pods sharing (nodeSelector,
    tolerations) — gang members share a template — get ONE
    ``node.admits`` evaluation per existing node, so selector/taint
    semantics stay Python-authoritative and the admission work drops
    from O(pods × nodes) to O(templates × nodes).  Returns None when
    the kernel is unavailable (caller runs the reference loop).
    """
    from tpu_autoscaler import native

    if not native.pack_multi_available():
        return None
    # Axis order is load-bearing only in that all rows share it; cpu
    # and memory lead because they are the FFD sort keys.
    axes: list[str] = ["cpu", "memory"]
    seen = set(axes)
    rvs = ([p.resources for p in pods] + list(free.values())
           + list(capacities.values()))
    for rv in rvs:
        for axis in rv.as_dict():
            if axis not in seen:
                seen.add(axis)
                axes.append(axis)

    def row(rv: ResourceVector) -> list[float]:
        return [rv.get(a) for a in axes]

    templates: dict[tuple, int] = {}
    tmpl_ids: list[int] = []
    reps: list[Pod] = []
    for p in pods:
        key = (tuple(sorted(p.node_selectors.items())),
               tuple(tuple(sorted(t.items())) for t in p.tolerations))
        tid = templates.get(key)
        if tid is None:
            tid = templates[key] = len(reps)
            reps.append(p)
        tmpl_ids.append(tid)
    free_names = list(free)
    admit = bytearray()
    for rep in reps:
        for name in free_names:
            node = (nodes_by_name or {}).get(name)
            admit.append(1 if node is None or node.admits(rep) else 0)
    result = native.pack_ffd_multi(
        [row(p.resources) for p in pods], tmpl_ids,
        [row(free[name]) for name in free_names], bytes(admit),
        len(reps), [row(capacities[s.machine_type]) for s in shapes])
    if result is None:
        return None
    placed, unit_shapes, free_after = result
    counts: dict[str, int] = {}
    for sidx in unit_shapes:
        machine = shapes[sidx].machine_type
        counts[machine] = counts.get(machine, 0) + 1
    unplaceable = [p for p, code in zip(pods, placed) if code == -1]
    for name, vals in zip(free_names, free_after):
        free[name] = ResourceVector(
            {a: v for a, v in zip(axes, vals) if v != 0.0})
    return counts, unplaceable


def pack_cpu_pods(pods: list[Pod], free: dict[str, ResourceVector],
                  unit: CpuShape,
                  nodes_by_name: dict[str, Node] | None = None
                  ) -> tuple[int, list[Pod]]:
    """Single-machine-type convenience wrapper over pack_cpu_pods_multi."""
    counts, unplaceable = pack_cpu_pods_multi(pods, free, [unit],
                                              nodes_by_name)
    return counts.get(unit.machine_type, 0), unplaceable
