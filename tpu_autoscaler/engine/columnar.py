"""Columnar planner core: struct-of-arrays state for the million-pod pass.

Sharding (docs/SHARDING.md) bought the reconciler its fan-out, but every
shard still walked Python ``Pod``/``Node`` objects — the residual hot
path at the million-pod tier is per-object attribute churn, not
algorithm.  This module is the struct-of-arrays twin of the planner's
three measured hot loops:

* ``planner._free_slices``           -> :meth:`PlanColumns.free_slices`
* selector/taint admission masking   -> :class:`NodeTemplates` +
                                        :class:`ColumnarMatcher`
* the claim / partial-claim scan     -> :meth:`ColumnarMatcher.match` /
                                        :func:`claimed_units`

Design contract (docs/PLANNER.md):

* **Value-identical, not merely equivalent.**  Every twin reproduces the
  Python loop's *values* — same float accumulation order per node
  (``np.add.at`` is unbuffered and applies updates in element order, so
  per-node sums are the same additions in the same order as the serial
  pod walk), same dict insertion orders (rows are kept in snapshot
  order, groups in first-member order), same int truncation.  The
  Python planner stays the property oracle; ``verify_columnar_plans``
  (docs/PLANNER.md) replans every pass both ways and gates byte-identical
  decisions, exactly how delta planning and sharding were landed.
* **Templates, not nodes.**  ``Node.admits`` reads only labels and
  taints; ``host_slots`` reads only allocatable.  Fleets have a handful
  of node *templates* (same labels+taints+allocatable), so admission and
  slot math memoize exactly per ``(template, probe signature)`` — the
  O(slices x gangs) admission scan becomes O(templates x gang
  signatures) plus vectorized gathers.
* **Pure.**  No globals, no I/O, no clocks: a :class:`ColumnarState` is
  a value derived from ``(nodes, pods)`` and everything here is a pure
  function over it (TAP1xx scope).  Incremental maintenance lives in
  ``k8s/columnar.py`` next to the informer's indices and folds;
  :meth:`ColumnarState.build` is the from-scratch constructor the
  churn property suite rebuilds against every step.
* **Shard-composable.**  :meth:`ColumnarState.take` slices a sub-state
  for one shard's rows (gathers + order-preserving regroup); the
  sharded merge contract is untouched.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from tpu_autoscaler.engine.fitter import host_slots
from tpu_autoscaler.k8s.gangs import Gang
from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.k8s.resources import ResourceVector
from tpu_autoscaler.k8s.units import unit_key_of
from tpu_autoscaler.topology.catalog import TPU_RESOURCE
from tpu_autoscaler.units import Chips

_ACTIVE_PHASES = ("Pending", "Running")


# --------------------------------------------------------------------------
# The ONE free-slice predicate (satellite: CapacityView.free_slice and
# planner._free_slices used to hand-mirror each other).
# --------------------------------------------------------------------------

def slice_is_free(is_tpu: bool, members: int, ready_schedulable: int,
                  used_chips: float) -> bool:
    """A supply unit is free supply iff it is TPU, non-empty, every host
    is Ready+schedulable, and zero chips are in use.  Scalar form shared
    by ``planner._free_slices`` and ``CapacityView.free_slice``."""
    return bool(is_tpu and members
                and ready_schedulable == members and used_chips == 0)


def slice_free_mask(members: Any, ready_schedulable: Any,
                    used_chips: Any) -> Any:
    """Vector twin of :func:`slice_is_free` over all-TPU group arrays."""
    return ((members > 0) & (ready_schedulable == members)
            & (used_chips == 0))


# --------------------------------------------------------------------------
# Node templates: exact admission/slot memoization.
# --------------------------------------------------------------------------

def _scalar_sig(v: Any) -> tuple[str, str]:
    return (type(v).__name__, str(v))


def _taints_sig(taints: Iterable[dict]) -> tuple:
    return tuple(sorted(
        tuple(sorted((str(k), _scalar_sig(v)) for k, v in t.items()))
        for t in taints))


def probe_sig(pod: Pod) -> tuple:
    """Everything ``Node.admits`` reads from a pod: selectors and
    tolerations, canonicalized.  Two pods with equal signatures admit
    identically on every node."""
    return (tuple(sorted(pod.node_selectors.items())),
            tuple(tuple(sorted((str(k), _scalar_sig(v))
                               for k, v in t.items()))
                  for t in pod.tolerations))


def resources_sig(rv: ResourceVector) -> tuple:
    return tuple(sorted(rv.as_dict().items()))


class NodeTemplates:
    """Interned node templates keyed by (labels, taints, allocatable) —
    the complete input set of ``Node.admits`` and ``host_slots``, so a
    memoized answer per template is *exact*, not approximate.  Grow-only
    and shared across passes (and across ``take()`` sub-states)."""

    def __init__(self) -> None:
        self._ids: dict[Any, int] = {}
        self.reps: list[Node] = []
        #: chips per template host (dimension ``c``).
        self.chips: list[Chips] = []
        # probe_sig -> bool-per-template row; per_pod sig -> slots row.
        self._admit_rows: dict[Any, Any] = {}
        self._slot_rows: dict[Any, Any] = {}

    def template_of(self, node: Node) -> int:
        key = (tuple(sorted(node.labels.items())),
               _taints_sig(node.taints),
               resources_sig(node.allocatable))
        tid = self._ids.get(key)
        if tid is None:
            tid = len(self.reps)
            self._ids[key] = tid
            self.reps.append(node)
            self.chips.append(int(node.allocatable.get(TPU_RESOURCE)))
        return tid

    def admits(self, tmpl: int, probe: Pod, sig: Any = None) -> bool:
        row = self.admit_row(probe, sig)
        return bool(row[tmpl])

    def admit_row(self, probe: Pod, sig: Any = None) -> Any:
        """bool[n_templates]: does each template admit ``probe``."""
        sig = probe_sig(probe) if sig is None else sig
        row = self._admit_rows.get(sig)
        n = len(self.reps)
        if row is None or len(row) < n:
            start = 0 if row is None else len(row)
            tail = np.fromiter((r.admits(probe) for r in self.reps[start:]),
                               dtype=bool, count=n - start)
            row = tail if row is None else np.concatenate([row, tail])
            self._admit_rows[sig] = row
        return row

    def slot_row(self, per_pod: ResourceVector, sig: Any = None) -> Any:
        """int64[n_templates]: ``host_slots`` of each template host."""
        sig = resources_sig(per_pod) if sig is None else sig
        row = self._slot_rows.get(sig)
        n = len(self.reps)
        if row is None or len(row) < n:
            start = 0 if row is None else len(row)
            tail = np.fromiter(
                (host_slots(r.allocatable, per_pod)
                 for r in self.reps[start:]),
                dtype=np.int64, count=n - start)
            row = tail if row is None else np.concatenate([row, tail])
            self._slot_rows[sig] = row
        return row


# --------------------------------------------------------------------------
# Grouping (slice membership offsets).
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Groups:
    """Per-group membership in CSR form.  ``member_rows`` is sorted by
    (gid, row), so members of one group appear in node-snapshot order and
    ``member_rows[offsets[g]]`` is the group's FIRST node — which makes
    gid order equal the Python ``dict.setdefault`` insertion order the
    planner's free/claim dicts iterate in."""

    keys: list[str]
    gid_of: dict[str, int]
    member_rows: Any           # int64[sum(members)]
    offsets: Any               # int64[n_groups + 1]
    tmpl: Any                  # int32[n_groups]; -1 = heterogeneous
    chips: Any                 # int64[n_groups] (dimension ``c``)
    counts: Any                # int64[n_groups]

    def __len__(self) -> int:
        return len(self.keys)

    @property
    def first_rows(self) -> Any:
        return self.member_rows[self.offsets[:-1]]

    def members(self, gid: int) -> Any:
        return self.member_rows[self.offsets[gid]:self.offsets[gid + 1]]

    def member_nodes(self, gid: int, nodes: list[Node]) -> list[Node]:
        return [nodes[r] for r in self.members(gid)]


def build_groups(row_keys: Sequence[str | None], tmpl_col: Any,
                 chips_col: Any) -> tuple[Groups, Any]:
    """Group rows by key (None = not a member), first-appearance order.
    Returns ``(groups, gid_per_row)`` with gid -1 for non-members."""
    keys: list[str] = []
    gid_of: dict[str, int] = {}
    member_lists: list[list[int]] = []
    gid_col = np.full(len(row_keys), -1, np.int32)
    for row, key in enumerate(row_keys):
        if key is None:
            continue
        gid = gid_of.get(key)
        if gid is None:
            gid = len(keys)
            gid_of[key] = gid
            keys.append(key)
            member_lists.append([])
        member_lists[gid].append(row)
        gid_col[row] = gid
    return _finish_groups(keys, gid_of, member_lists,
                          tmpl_col, chips_col), gid_col


def _finish_groups(keys: list[str], gid_of: dict[str, int],
                   member_lists: list[list[int]], tmpl_col: Any,
                   chips_col: Any) -> Groups:
    counts = np.fromiter((len(m) for m in member_lists), np.int64,
                         count=len(member_lists))
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    member_rows = (np.concatenate(
        [np.asarray(m, np.int64) for m in member_lists])
        if member_lists else np.zeros(0, np.int64))
    tmpl, chips = _group_tmpl_chips(member_rows, offsets, tmpl_col,
                                    chips_col)
    return Groups(keys=keys, gid_of=gid_of, member_rows=member_rows,
                  offsets=offsets, tmpl=tmpl, chips=chips, counts=counts)


def _group_tmpl_chips(member_rows: Any, offsets: Any, tmpl_col: Any,
                      chips_col: Any) -> tuple[Any, Any]:
    n_groups = len(offsets) - 1
    if n_groups == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.int64))
    m_tmpl = np.asarray(tmpl_col, np.int64)[member_rows]
    starts = offsets[:-1]
    t_min = np.minimum.reduceat(m_tmpl, starts)
    t_max = np.maximum.reduceat(m_tmpl, starts)
    tmpl = np.where(t_min == t_max, t_min, -1).astype(np.int32)
    chips = np.add.reduceat(np.asarray(chips_col, np.int64)[member_rows],
                            starts)
    return tmpl, chips


def regroup(gid_col: Any, old_keys: list[str], tmpl_col: Any,
            chips_col: Any) -> tuple[Groups, Any]:
    """Rebuild groups after a row gather (shard ``take``): keep only
    groups with surviving members, in first-appearance order, members in
    row order.  Homogeneity/chips are recomputed honestly — a hetero
    group whose taken subset is homogeneous regains the fast path."""
    gid_col = np.asarray(gid_col)
    rows = np.flatnonzero(gid_col >= 0)
    new_gid_col = np.full(len(gid_col), -1, np.int32)
    if len(rows) == 0:
        return (Groups(keys=[], gid_of={},
                       member_rows=np.zeros(0, np.int64),
                       offsets=np.zeros(1, np.int64),
                       tmpl=np.zeros(0, np.int32),
                       chips=np.zeros(0, np.int64),
                       counts=np.zeros(0, np.int64)), new_gid_col)
    old = gid_col[rows]
    uniq, first = np.unique(old, return_index=True)
    order = np.argsort(first, kind="stable")
    uniq = uniq[order]
    remap = np.full(len(old_keys), -1, np.int64)
    remap[uniq] = np.arange(len(uniq))
    new_of_row = remap[old]
    new_gid_col[rows] = new_of_row.astype(np.int32)
    sort = np.argsort(new_of_row, kind="stable")
    member_rows = rows[sort].astype(np.int64)
    counts = np.bincount(new_of_row, minlength=len(uniq)).astype(np.int64)
    offsets = np.zeros(len(uniq) + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    keys = [old_keys[g] for g in uniq]
    tmpl, chips = _group_tmpl_chips(member_rows, offsets, tmpl_col,
                                    chips_col)
    return Groups(keys=keys, gid_of={k: i for i, k in enumerate(keys)},
                  member_rows=member_rows, offsets=offsets, tmpl=tmpl,
                  chips=chips, counts=counts), new_gid_col


# --------------------------------------------------------------------------
# The state value itself.
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ColumnarState:
    """Struct-of-arrays view of one ``(nodes, pods)`` observation.

    Node rows align with ``nodes`` (snapshot order); pod rows align with
    the pods list the planner is called with (``n_pods`` long — pod
    *objects* are deliberately not held, every consumer works from the
    columns).  ``attachable`` is the cheap defensive check the planner
    runs before trusting the alignment; the reconciler additionally
    gates on store digests (docs/PLANNER.md)."""

    templates: NodeTemplates
    # -- nodes --
    nodes: list[Node]
    n_ready: Any               # bool[N]
    n_sched: Any               # bool[N] (True = NOT cordoned)
    n_is_tpu: Any              # bool[N]
    n_chips: Any               # int64[N] (dimension ``c``)
    n_tmpl: Any                # int32[N]
    slice_gid: Any             # int32[N]; -1 = not a planner slice member
    unit_gid: Any              # int32[N]
    slices: Groups             # is_tpu & slice_id nodes, keyed slice id
    units: Groups              # ALL nodes, keyed unit_key_of
    # -- pods --
    n_pods: int
    p_node_row: Any            # int32[P]; -1 = unbound or unknown node
    p_has_node: Any            # bool[P]: node_name truthy
    p_active: Any              # bool[P]: phase in {Pending, Running}
    p_workload: Any            # bool[P]: Pod.is_workload
    p_tpu: Any                 # float64[P]: resources.get(TPU_RESOURCE)
    p_tpu_chips: Any           # int64[P]: Pod.tpu_chips (dimension ``c``)
    p_gang: Any                # int32[P]: interned gang_key
    p_ns: Any                  # int32[P]: interned namespace
    gang_keys: list[Any]
    gang_ids: dict[Any, int]
    ns_keys: list[str]
    ns_ids: dict[str, int]
    axes: list[str]            # resource axes seen (pods + allocatable)
    axis_ids: dict[str, int]
    p_axes: list[Any]          # per axis: float64[P] pod requests
    # -- identity stamps (None on take() sub-states) --
    node_digest: int | None = None
    pod_digest: int | None = None
    first_pod_sig: tuple | None = None
    last_pod_sig: tuple | None = None

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, nodes: list[Node], pods: list[Pod],
              templates: NodeTemplates | None = None) -> "ColumnarState":
        """From-scratch constructor — the churn suite's oracle and the
        view's full-rebuild path."""
        templates = templates if templates is not None else NodeTemplates()
        n = len(nodes)
        n_ready = np.zeros(n, bool)
        n_sched = np.zeros(n, bool)
        n_is_tpu = np.zeros(n, bool)
        n_chips = np.zeros(n, np.int64)
        n_tmpl = np.zeros(n, np.int32)
        slice_keys: list[str | None] = [None] * n
        unit_keys: list[str | None] = [None] * n
        rows_by_name: dict[str, int] = {}
        for i, nd in enumerate(nodes):
            n_ready[i] = nd.is_ready
            n_sched[i] = not nd.unschedulable
            tpu = nd.is_tpu
            n_is_tpu[i] = tpu
            tid = templates.template_of(nd)
            n_tmpl[i] = tid
            n_chips[i] = templates.chips[tid]
            if tpu and nd.slice_id:
                slice_keys[i] = nd.slice_id
            unit_keys[i] = unit_key_of(nd)
            rows_by_name[nd.name] = i
        slices, slice_gid = build_groups(slice_keys, n_tmpl, n_chips)
        units, unit_gid = build_groups(unit_keys, n_tmpl, n_chips)

        state = cls(
            templates=templates, nodes=list(nodes),
            n_ready=n_ready, n_sched=n_sched, n_is_tpu=n_is_tpu,
            n_chips=n_chips, n_tmpl=n_tmpl,
            slice_gid=slice_gid, unit_gid=unit_gid,
            slices=slices, units=units,
            n_pods=len(pods),
            p_node_row=np.full(len(pods), -1, np.int32),
            p_has_node=np.zeros(len(pods), bool),
            p_active=np.zeros(len(pods), bool),
            p_workload=np.zeros(len(pods), bool),
            p_tpu=np.zeros(len(pods), np.float64),
            p_tpu_chips=np.zeros(len(pods), np.int64),
            p_gang=np.zeros(len(pods), np.int32),
            p_ns=np.zeros(len(pods), np.int32),
            gang_keys=[], gang_ids={}, ns_keys=[], ns_ids={},
            axes=[], axis_ids={}, p_axes=[])
        for axis in _allocatable_axes(templates):
            state.ensure_axis(axis)
        for i, p in enumerate(pods):
            state._ingest_pod(i, p, rows_by_name)
        if pods:
            state.first_pod_sig = pod_sig(pods[0])
            state.last_pod_sig = pod_sig(pods[-1])
        return state

    def ensure_axis(self, axis: str) -> int:
        aid = self.axis_ids.get(axis)
        if aid is None:
            aid = len(self.axes)
            self.axis_ids[axis] = aid
            self.axes.append(axis)
            self.p_axes.append(np.zeros(self.n_pods, np.float64))
        return aid

    def _intern_gang(self, key: Any) -> int:
        gid = self.gang_ids.get(key)
        if gid is None:
            gid = len(self.gang_keys)
            self.gang_ids[key] = gid
            self.gang_keys.append(key)
        return gid

    def _intern_ns(self, ns: str) -> int:
        nid = self.ns_ids.get(ns)
        if nid is None:
            nid = len(self.ns_keys)
            self.ns_ids[ns] = nid
            self.ns_keys.append(ns)
        return nid

    def _ingest_pod(self, i: int, p: Pod,
                    rows_by_name: dict[str, int]) -> None:
        name = p.node_name
        if name:
            self.p_has_node[i] = True
            self.p_node_row[i] = rows_by_name.get(name, -1)
        self.p_active[i] = p.phase in _ACTIVE_PHASES
        self.p_workload[i] = p.is_workload
        self.p_tpu[i] = p.resources.get(TPU_RESOURCE)
        self.p_tpu_chips[i] = p.tpu_chips
        self.p_gang[i] = self._intern_gang(p.gang_key)
        self.p_ns[i] = self._intern_ns(p.namespace)
        for axis, v in p.resources.as_dict().items():
            self.p_axes[self.ensure_axis(axis)][i] = v

    # -- alignment check ---------------------------------------------------

    def attachable(self, nodes: list[Node], pods: list[Pod]) -> bool:
        if len(nodes) != len(self.nodes) or len(pods) != self.n_pods:
            return False
        if self.nodes and (self.nodes[0] is not nodes[0]
                           or self.nodes[-1] is not nodes[-1]):
            return False
        if pods and self.first_pod_sig is not None:
            if (pod_sig(pods[0]) != self.first_pod_sig
                    or pod_sig(pods[-1]) != self.last_pod_sig):
                return False
        return True

    # -- shard composition -------------------------------------------------

    def take(self, node_rows: Any, pod_rows: Any) -> "ColumnarState":
        """Sub-state for one shard's rows (ascending row order, matching
        the sharder's node/pod sub-lists).  Gathers + regroup; the
        template registry (and its memos) is shared, not copied."""
        node_rows = np.asarray(node_rows, np.int64)
        pod_rows = np.asarray(pod_rows, np.int64)
        remap = np.full(len(self.nodes), -1, np.int32)
        remap[node_rows] = np.arange(len(node_rows), dtype=np.int32)
        slices, slice_gid = regroup(self.slice_gid[node_rows],
                                    self.slices.keys,
                                    self.n_tmpl[node_rows],
                                    self.n_chips[node_rows])
        units, unit_gid = regroup(self.unit_gid[node_rows],
                                  self.units.keys,
                                  self.n_tmpl[node_rows],
                                  self.n_chips[node_rows])
        old_row = self.p_node_row[pod_rows]
        new_row = np.full(len(pod_rows), -1, np.int32)
        bound = old_row >= 0
        new_row[bound] = remap[old_row[bound]]
        return ColumnarState(
            templates=self.templates,
            nodes=[self.nodes[r] for r in node_rows],
            n_ready=self.n_ready[node_rows],
            n_sched=self.n_sched[node_rows],
            n_is_tpu=self.n_is_tpu[node_rows],
            n_chips=self.n_chips[node_rows],
            n_tmpl=self.n_tmpl[node_rows],
            slice_gid=slice_gid, unit_gid=unit_gid,
            slices=slices, units=units,
            n_pods=len(pod_rows),
            p_node_row=new_row,
            p_has_node=self.p_has_node[pod_rows],
            p_active=self.p_active[pod_rows],
            p_workload=self.p_workload[pod_rows],
            p_tpu=self.p_tpu[pod_rows],
            p_tpu_chips=self.p_tpu_chips[pod_rows],
            p_gang=self.p_gang[pod_rows],
            p_ns=self.p_ns[pod_rows],
            gang_keys=self.gang_keys, gang_ids=self.gang_ids,
            ns_keys=self.ns_keys, ns_ids=self.ns_ids,
            axes=self.axes, axis_ids=self.axis_ids,
            p_axes=[a[pod_rows] for a in self.p_axes])


def pod_sig(p: Pod) -> tuple:
    return (p.uid or p.name, p.resource_version)


def _allocatable_axes(templates: NodeTemplates) -> list[str]:
    axes: list[str] = []
    seen: set[str] = set()
    for rep in templates.reps:
        for axis in rep.allocatable.keys():
            if axis not in seen:
                seen.add(axis)
                axes.append(axis)
    return axes


# --------------------------------------------------------------------------
# Per-pass computations (the planner twins).
# --------------------------------------------------------------------------

@dataclasses.dataclass
class _Occupancy:
    """Workload occupants of planner slices, aggregated per slice gid."""

    per_node: Any              # int64[N] occupant pods on each node row
    total: Any                 # int64[n_slices]
    first_pod_row: Any         # int64[n_slices]; P (=none) when empty
    gang_min: Any              # int32[n_slices]
    gang_max: Any              # int32[n_slices]
    ordered_gids: Any          # occupied gids by first occupant pod row


class PlanColumns:
    """One plan pass's columnar computations, each lazy and computed at
    most once — mirroring the planner's own lazy ``partial_claims``."""

    def __init__(self, state: ColumnarState) -> None:
        self.s = state
        self._used_tpu: Any = None
        self._free: tuple[dict[str, list[Node]], Any] | None = None
        self._occ: _Occupancy | None = None
        self._sole: dict[int, list[int]] | None = None
        self._used_axes: list[Any] | None = None
        self._tmpl_alloc: list[Any] | None = None

    # -- planner._free_slices twin -----------------------------------------

    def used_tpu_per_node(self) -> Any:
        """float64[N]: TPU chips requested by active pods bound to each
        planner slice host — same additions in pod order as the Python
        ``used_tpu`` dict walk."""
        if self._used_tpu is None:
            s = self.s
            used = np.zeros(len(s.nodes), np.float64)
            rows = s.p_node_row
            sel = s.p_active & (rows >= 0)
            sel[sel] = s.slice_gid[rows[sel]] >= 0
            np.add.at(used, rows[sel], s.p_tpu[sel])
            self._used_tpu = used
        return self._used_tpu

    def free_slice_mask(self) -> Any:
        return self.free_slices()[1]

    def free_slices(self) -> tuple[dict[str, list[Node]], Any]:
        """``planner._free_slices`` twin: the same ``{slice_id:
        members}`` dict (same insertion order), plus the per-gid mask."""
        if self._free is None:
            s = self.s
            g = s.slices
            if len(g) == 0:
                self._free = ({}, np.zeros(0, bool))
                return self._free
            used = self.used_tpu_per_node()
            ok_col = s.n_ready & s.n_sched
            starts = g.offsets[:-1]
            ready = np.add.reduceat(
                ok_col[g.member_rows].astype(np.int64), starts)
            used_sum = np.add.reduceat(used[g.member_rows], starts)
            mask = slice_free_mask(g.counts, ready, used_sum)
            free: dict[str, list[Node]] = {}
            for gid in np.flatnonzero(mask):
                free[g.keys[gid]] = g.member_nodes(gid, s.nodes)
            self._free = (free, mask)
        return self._free

    # -- chip ledgers ------------------------------------------------------

    def existing_tpu_chips(self) -> Chips:
        s = self.s
        return int(s.n_chips[s.n_is_tpu].sum())

    def chips_by_namespace(self) -> dict[str, int]:
        """``planner._chips_by_namespace`` twin, bound-pod part only
        (the in-flight additions stay a Python loop in the planner)."""
        s = self.s
        sel = s.p_has_node & s.p_active
        ns = s.p_ns[sel]
        n_ns = len(s.ns_keys)
        counts = np.bincount(ns, minlength=n_ns)
        sums = np.zeros(n_ns, np.int64)
        np.add.at(sums, ns, s.p_tpu_chips[sel])
        return {s.ns_keys[i]: int(sums[i])
                for i in np.flatnonzero(counts)}

    # -- occupancy (partial-claim scan) ------------------------------------

    def occupancy(self) -> _Occupancy:
        if self._occ is None:
            s = self.s
            n_slices = len(s.slices)
            rows = s.p_node_row
            sel = s.p_workload & (rows >= 0)
            sel[sel] = s.slice_gid[rows[sel]] >= 0
            prow = np.flatnonzero(sel)
            nrow = rows[prow]
            sgid = s.slice_gid[nrow].astype(np.int64)
            per_node = np.zeros(len(s.nodes), np.int64)
            np.add.at(per_node, nrow, 1)
            total = np.bincount(sgid, minlength=n_slices).astype(np.int64)
            first = np.full(n_slices, s.n_pods, np.int64)
            np.minimum.at(first, sgid, prow)
            gmin = np.full(n_slices, np.iinfo(np.int32).max, np.int32)
            gmax = np.full(n_slices, -1, np.int32)
            gcol = s.p_gang[prow]
            np.minimum.at(gmin, sgid, gcol)
            np.maximum.at(gmax, sgid, gcol)
            occupied = np.flatnonzero(total > 0)
            ordered = occupied[np.argsort(first[occupied], kind="stable")]
            self._occ = _Occupancy(per_node=per_node, total=total,
                                   first_pod_row=first, gang_min=gmin,
                                   gang_max=gmax, ordered_gids=ordered)
        return self._occ

    def sole_occupants(self) -> dict[int, list[int]]:
        """gang id -> the slice gids that gang occupies ALONE, in
        first-occupant order.  ``match_partial`` can only ever return
        one of these, so the per-gang scan walks this list instead of
        every occupied slice (O(own candidates), not O(occupied) —
        the difference between 2 s and 30 ms at the 200k tier)."""
        if self._sole is None:
            occ = self.occupancy()
            sole: dict[int, list[int]] = {}
            for gid in occ.ordered_gids:
                gid = int(gid)
                gang = int(occ.gang_min[gid])
                if gang == occ.gang_max[gid]:
                    sole.setdefault(gang, []).append(gid)
            self._sole = sole
        return self._sole

    # -- CPU capacity twins ------------------------------------------------

    def _axis_tables(self) -> tuple[list[Any], list[Any]]:
        """(used[axis][node_row], alloc[axis][template]) — the columnar
        halves of ``fitter.free_capacity``'s used/allocatable maps."""
        if self._used_axes is None:
            s = self.s
            rows = s.p_node_row
            sel = s.p_active & (rows >= 0)
            target = rows[sel]
            used_axes = []
            for col in s.p_axes:
                used = np.zeros(len(s.nodes), np.float64)
                np.add.at(used, target, col[sel])
                used_axes.append(used)
            tmpl_alloc = []
            for axis in s.axes:
                tmpl_alloc.append(np.fromiter(
                    (r.allocatable.get(axis) for r in s.templates.reps),
                    np.float64, count=len(s.templates.reps)))
            self._used_axes = used_axes
            self._tmpl_alloc = tmpl_alloc
        return self._used_axes, self._tmpl_alloc

    def node_free_vector(self, row: int) -> ResourceVector:
        """allocatable - used for one node row, value-identical to the
        ``fitter.free_capacity`` entry (zero axes drop in both)."""
        used_axes, tmpl_alloc = self._axis_tables()
        tid = int(self.s.n_tmpl[row])
        out: dict[str, float] = {}
        for aid, axis in enumerate(self.s.axes):
            v = float(tmpl_alloc[aid][tid]) - float(used_axes[aid][row])
            if v != 0.0:
                out[axis] = v
        return ResourceVector(out)

    def free_cpu_capacity(self) -> dict[str, ResourceVector]:
        """``free_capacity(cpu_nodes, pods)`` twin (Ready, schedulable,
        non-TPU nodes, in node order)."""
        s = self.s
        eligible = np.flatnonzero(~s.n_is_tpu & s.n_ready & s.n_sched)
        return {s.nodes[r].name: self.node_free_vector(r)
                for r in eligible}

    def fully_free_cpu(self) -> int:
        """Count of Ready schedulable CPU nodes with no workload pods —
        the planner's ``workload_nodes`` set-difference twin."""
        s = self.s
        rows = s.p_node_row
        sel = s.p_workload & (rows >= 0)
        wl = np.zeros(len(s.nodes), np.int64)
        np.add.at(wl, rows[sel], 1)
        return int(np.count_nonzero(
            ~s.n_is_tpu & s.n_ready & s.n_sched & (wl == 0)))


# --------------------------------------------------------------------------
# The claim / partial-claim matcher.
# --------------------------------------------------------------------------

def gang_fit_sig(gang: Gang) -> tuple | None:
    """Signature under which a gang's slice-satisfaction answer is
    reusable: admission probe + per-pod shape + chip/size demand."""
    probe = gang.pods[0] if gang.pods else None
    if probe is None:
        return None
    return (probe_sig(probe), resources_sig(gang.per_pod_resources),
            int(gang.tpu_chips), int(gang.size))


class ColumnarMatcher:
    """Vectorized ``match_free``: the fully-free scan then the
    partial-claim scan, candidate order identical to the Python dict
    walks.  Heterogeneous groups (mixed templates — rare) resolve
    through the Python oracle predicates passed in."""

    def __init__(self, pc: PlanColumns,
                 py_satisfies: Callable[[list[Node], Gang], bool]) -> None:
        self.pc = pc
        self.py_satisfies = py_satisfies
        self._sat_memo: dict[tuple, Any] = {}
        self._hetero_memo: dict[tuple, bool] = {}

    def _sat_mask(self, groups: Groups, gang: Gang, sig: tuple,
                  kind: str) -> tuple[Any, Any]:
        """(sat, maybe): vectorized ``_slice_satisfies`` over homogeneous
        groups; ``maybe`` marks hetero groups needing the oracle."""
        key = (kind, sig)
        cached = self._sat_memo.get(key)
        if cached is not None:
            return cached
        t = self.pc.s.templates
        probe = gang.pods[0]
        admit = t.admit_row(probe, sig[0])
        slots = t.slot_row(gang.per_pod_resources, sig[1])
        tmpl = groups.tmpl
        homog = tmpl >= 0
        safe_t = np.where(homog, tmpl, 0)
        sat = (homog & admit[safe_t] & (groups.chips >= sig[2])
               & (groups.counts * slots[safe_t] >= sig[3]))
        maybe = ~homog
        self._sat_memo[key] = (sat, maybe)
        return sat, maybe

    def match_free(self, gang: Gang, claimed: set[str]) -> str | None:
        sig = gang_fit_sig(gang)
        if sig is None:
            return None
        pc = self.pc
        g = pc.s.slices
        _free, mask = pc.free_slices()
        sat, maybe = self._sat_mask(g, gang, sig, "slices")
        for gid in np.flatnonzero(mask & (sat | maybe)):
            gid = int(gid)
            key = g.keys[gid]
            if key in claimed:
                continue
            if maybe[gid] and not self._hetero_ok(g, gid, gang, sig):
                continue
            return key
        return None

    def _hetero_ok(self, groups: Groups, gid: int, gang: Gang,
                   sig: tuple) -> bool:
        mkey = ("sat", sig, id(groups), gid)
        hit = self._hetero_memo.get(mkey)
        if hit is None:
            hit = self.py_satisfies(
                groups.member_nodes(gid, self.pc.s.nodes), gang)
            self._hetero_memo[mkey] = hit
        return hit

    def match_partial(self, gang: Gang, claimed: set[str]) -> str | None:
        """``_gang_claims_partial`` scan: slices the gang already
        partially occupies alone, in first-occupant order."""
        sig = gang_fit_sig(gang)
        if sig is None:
            return None
        pc = self.pc
        s = pc.s
        g = s.slices
        gang_id = s.gang_ids.get(gang.key)
        if gang_id is None:
            return None
        occ = pc.occupancy()
        _free, free_mask = pc.free_slices()
        t = s.templates
        admit = t.admit_row(gang.pods[0], sig[0])
        slots = t.slot_row(gang.per_pod_resources, sig[1])
        # Sole-occupancy (occ[0].gang_key == gang.key, no foreign
        # occupants) is precomputed per gang; the candidate order
        # within one gang matches the full ordered_gids walk.
        for gid in pc.sole_occupants().get(int(gang_id), ()):
            key = g.keys[gid]
            if free_mask[gid] or key in claimed:
                continue
            rows = g.members(gid)
            tmpl = int(g.tmpl[gid])
            if tmpl < 0:
                if self._partial_hetero(rows, gang, sig):
                    return key
                continue
            if not admit[tmpl]:
                continue
            room = (s.n_ready[rows] & s.n_sched[rows]
                    & (occ.per_node[rows] == 0))
            if int(np.count_nonzero(room)) * int(slots[tmpl]) >= sig[3]:
                return key
        return None

    def _partial_hetero(self, rows: Any, gang: Gang, sig: tuple) -> bool:
        """Python ``_gang_claims_partial`` room math for mixed-template
        slices (occupant uniformity already proven from the columns)."""
        s = self.pc.s
        probe = gang.pods[0]
        nodes = [s.nodes[r] for r in rows]
        if not all(s.templates.admits(int(s.n_tmpl[r]), probe, sig[0])
                   for r in rows):
            return False
        per_pod = gang.per_pod_resources
        occ = self.pc.occupancy()
        free_slots = sum(
            host_slots(nd.allocatable, per_pod)
            for r, nd in zip(rows, nodes)
            if occ.per_node[r] == 0 and s.n_ready[r] and s.n_sched[r])
        return free_slots >= sig[3]

    def match(self, gang: Gang, claimed: set[str]) -> str | None:
        sid = self.match_free(gang, claimed)
        if sid is not None:
            return sid
        return self.match_partial(gang, claimed)


# --------------------------------------------------------------------------
# The claim scan (shard.claimed_by_pending twin).
# --------------------------------------------------------------------------

def claimed_units(state: ColumnarState, units: dict[str, list[Node]],
                  tpu_gangs: list[Gang], cpu_pods: list[Pod],
                  py_satisfies: Callable[[list[Node], Gang], bool],
                  ) -> set[str] | None:
    """Columnar ``shard.claimed_by_pending``: which supply units pending
    demand could bind.  Returns None when ``units`` does not align with
    the state's unit grouping (caller falls back to Python)."""
    g = state.units
    if list(units.keys()) != g.keys:
        return None
    matcher = ColumnarMatcher(PlanColumns(state), py_satisfies)
    claimed: set[str] = set()
    if len(g) == 0:
        return claimed
    first_tpu = state.n_is_tpu[g.first_rows]
    tpu_mask = np.zeros(len(g), bool)
    maybe_mask = np.zeros(len(g), bool)
    maybe_gangs: list[tuple[Gang, tuple]] = []
    seen_sigs: set[tuple] = set()
    for gang in tpu_gangs:
        sig = gang_fit_sig(gang)
        if sig is None or sig in seen_sigs:
            continue
        seen_sigs.add(sig)
        sat, maybe = matcher._sat_mask(g, gang, sig, "units")
        tpu_mask |= sat
        maybe_mask |= maybe
        maybe_gangs.append((gang, sig))
    hit = first_tpu & tpu_mask
    for gid in np.flatnonzero(hit):
        claimed.add(g.keys[int(gid)])
    # Heterogeneous TPU-led units: Python oracle per (unit, gang).
    for gid in np.flatnonzero(first_tpu & maybe_mask & ~hit):
        gid = int(gid)
        members = g.member_nodes(gid, state.nodes)
        if any(py_satisfies(members, gang) for gang, _ in maybe_gangs):
            claimed.add(g.keys[gid])
    if cpu_pods:
        pc = matcher.pc
        for gid in np.flatnonzero(~first_tpu):
            gid = int(gid)
            if _cpu_unit_claimed(state, pc, g.members(gid), cpu_pods):
                claimed.add(g.keys[gid])
    return claimed


def _cpu_unit_claimed(state: ColumnarState, pc: PlanColumns, rows: Any,
                      cpu_pods: list[Pod]) -> bool:
    """One CPU unit vs pending CPU pods: ``include_unschedulable=True``
    free capacity (Ready nodes, cordoned allowed) + admission + fit."""
    t = state.templates
    for r in rows:
        r = int(r)
        if not state.n_ready[r]:
            continue
        cap = pc.node_free_vector(r)
        tmpl = int(state.n_tmpl[r])
        for p in cpu_pods:
            if t.admits(tmpl, p) and p.resources.fits_in(cap):
                return True
    return False
