"""JAX-vectorized shape scoring: the fit engine's accelerated path.

``choose_shape_for_gang`` is O(shapes) Python per gang — fine for tens of
gangs.  At fleet scale (thousands of queued gangs scored against the whole
catalog, e.g. batch admission control or what-if capacity planning), the
same math vectorizes: one ``[gangs, shapes]`` feasibility/cost tensor,
computed in a single fused XLA kernel on CPU or TPU.

The kernel is pure (no data-dependent Python control flow; masking instead
of branching) so it jits once and reuses across reconcile passes — the
XLA-first rewrite of the reference's per-pod Python loop
(cluster.py §Cluster.scale, O(pods×pools) fit checks).

Scope: scoring is over the CHIP axes (total, per-pod, host slots) — the
dimensions that decide TPU shape choice in practice.  The Python engine
(engine/fitter.py) additionally binds host cpu/memory and is authoritative
when those axes constrain; use this scorer for bulk triage, the Python
path for the final decision.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from tpu_autoscaler.topology.catalog import SLICE_SHAPES

_BIG = np.float32(1e9)


def catalog_arrays(generation: str | None = None
                   ) -> tuple[list[str], Any, Any, Any]:
    """(names, chips[S], chips_per_host[S], hosts[S]) as numpy arrays."""
    shapes = [s for s in SLICE_SHAPES.values()
              if generation is None or s.generation == generation]
    shapes.sort(key=lambda s: (s.generation, s.chips))
    names = [s.name for s in shapes]
    chips = np.array([s.chips for s in shapes], np.float32)
    cph = np.array([s.chips_per_host for s in shapes], np.float32)
    hosts = np.array([s.hosts for s in shapes], np.float32)
    return names, chips, cph, hosts


def _score_kernel(total_chips: Any, per_pod_chips: Any, n_pods: Any,
                  chips: Any, cph: Any, hosts: Any) -> Any:
    """Vectorized feasibility + stranded-chip cost.

    Inputs: per-gang demand vectors [G]; catalog vectors [S].
    Output: cost [G, S] — stranded chips, or +inf where infeasible.
    Written against jax.numpy but numpy-compatible (tests run both).
    """
    import jax.numpy as jnp

    total = total_chips[:, None]
    per_pod = per_pod_chips[:, None]
    pods = n_pods[:, None]
    slots = hosts[None, :] * jnp.floor(
        jnp.where(per_pod > 0, cph[None, :] / jnp.maximum(per_pod, 1), _BIG))
    feasible = ((chips[None, :] >= total)
                & (cph[None, :] >= per_pod)
                & (slots >= pods))
    stranded = chips[None, :] - total
    return jnp.where(feasible, stranded, _BIG)


def make_batch_scorer(generation: str | None = None
                      ) -> tuple[list[str], Callable[[Any], Any]]:
    """Returns (names, score_fn) where score_fn(gang_demands) -> best index
    and stranded cost per gang, jitted once.

    ``gang_demands`` is a float32 array [G, 3] of (total_chips,
    per_pod_chips, n_pods).
    """
    import jax
    import jax.numpy as jnp

    names, chips, cph, hosts = catalog_arrays(generation)
    chips_j, cph_j, hosts_j = (jnp.asarray(chips), jnp.asarray(cph),
                               jnp.asarray(hosts))

    @jax.jit
    def score(demands):
        cost = _score_kernel(demands[:, 0], demands[:, 1], demands[:, 2],
                             chips_j, cph_j, hosts_j)
        best = jnp.argmin(cost, axis=1)
        best_cost = jnp.min(cost, axis=1)
        return best, best_cost

    return names, score


def best_shapes(demands: np.ndarray, generation: str | None = None
                ) -> list[tuple[str | None, float]]:
    """Convenience wrapper: [(shape_name | None, stranded), ...] per gang."""
    names, score = make_batch_scorer(generation)
    best, cost = score(np.asarray(demands, np.float32))
    out: list[tuple[str | None, float]] = []
    for b, c in zip(np.asarray(best), np.asarray(cost)):
        out.append((None, float("inf")) if c >= _BIG
                   else (names[int(b)], float(c)))
    return out


def best_shapes_np(demands: Any, generation: str | None = None
                   ) -> list[tuple[str | None, float]]:
    """Pure-numpy twin of ``best_shapes`` — same kernel math, no jax
    import (usable from the planner's batch path without paying jax's
    import/jit latency inside a reconcile pass).

    The catalog is sorted ascending by chips with unique chip counts
    per generation, and ``argmin`` returns the first minimum, so the
    pick matches the per-gang Python scan (and the native kernel)
    decision-for-decision on the chip axes.
    """
    names, chips, cph, hosts = catalog_arrays(generation)
    d = np.asarray(demands, np.float32).reshape(-1, 3)
    total = d[:, 0:1]
    per_pod = d[:, 1:2]
    pods = d[:, 2:3]
    with np.errstate(divide="ignore"):
        slots = hosts[None, :] * np.floor(
            np.where(per_pod > 0, cph[None, :] / np.maximum(per_pod, 1),
                     _BIG))
    feasible = ((chips[None, :] >= total)
                & (cph[None, :] >= per_pod)
                & (slots >= pods))
    cost = np.where(feasible, chips[None, :] - total, _BIG)
    best = cost.argmin(axis=1)
    best_cost = cost.min(axis=1)
    return [(None, float("inf")) if c >= _BIG else (names[int(b)], float(c))
            for b, c in zip(best, best_cost)]
