"""Fused causal attention as a Pallas TPU kernel.

The one genuinely hot op in the in-tree workload (workloads/model.py).  The
einsum path materializes [b, h, s, s] score tensors in HBM; this kernel
keeps each q-block's scores in VMEM, fusing QK^T → mask → softmax → PV into
one pass per (batch*head, q-block) grid cell — the standard flash-attention
blocking, simplified to whole-K rows because the workload's sequence
lengths (≤ a few K) keep K/V comfortably inside the ~16 MB VMEM budget.
fp32 accumulation on the MXU via ``preferred_element_type``; bf16 in/out.

Falls back to interpret mode off-TPU so the same code path is unit-tested
on the CPU mesh (tests/test_attention.py compares against the reference
einsum implementation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _fit_block(s: int, block: int) -> int:
    """Largest divisor of ``s`` not exceeding ``block``, so any sequence
    length works (the einsum path accepts any s; the kernels must too,
    not crash on s % 128 != 0)."""
    block = min(block, s)
    while s % block:
        block -= 1
    return block


def _fold_heads(x: jax.Array) -> jax.Array:
    """[b, h, ...] -> [b*h, ...] (one grid cell per batch*head)."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale: float,
                 causal: bool, block_q: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # [bq, d]
    k = k_ref[0].astype(jnp.float32)                     # [s, d]
    v = v_ref[0].astype(jnp.float32)                     # [s, d]
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bq, s]
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) / l          # [bq, d]
    o_ref[0] = o.astype(o_ref.dtype)


def _forward_pallas(q, k, v, causal, block_q, interpret):
    b, h, s, d = q.shape
    block_q = _fit_block(s, block_q)
    sm_scale = d ** -0.5

    fold = _fold_heads
    kernel = functools.partial(_attn_kernel, sm_scale=sm_scale,
                               causal=causal, block_q=block_q)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(fold(q), fold(k), fold(v))
    return out.reshape(b, h, s, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, causal, block_q, interpret):
    return _forward_pallas(q, k, v, causal, block_q, interpret)


def _attn_bwd_kernel(q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref,
                     *, sm_scale: float, causal: bool):
    """Fused backward for one (batch*head): recompute-p flash backward.

    Whole-sequence rows per grid cell (the workload's sequence lengths
    keep [s, s] comfortably in VMEM); probabilities are recomputed from
    q/k — the classic flash trade: no [s, s] tensor ever round-trips HBM.
    Masked entries have p == 0, so ds vanishes there without extra masking.
    """
    qs = q_ref[0].astype(jnp.float32) * sm_scale                 # [s, d]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    scores = jax.lax.dot_general(qs, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)                   # [s, s]
    dv = jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [s, d]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [s, s]
    delta = jnp.sum(p * dp, axis=-1, keepdims=True)              # [s, 1]
    ds = p * (dp - delta)
    dq = jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32) * sm_scale
    dk = jax.lax.dot_general(ds, qs, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dq_ref[0] = dq.astype(dq_ref.dtype)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _backward_pallas(q, k, v, do, causal, interpret):
    b, h, s, d = q.shape
    sm_scale = d ** -0.5
    fold = lambda x: x.reshape(b * h, s, x.shape[-1])  # noqa: E731
    kernel = functools.partial(_attn_bwd_kernel, sm_scale=sm_scale,
                               causal=causal)
    spec = pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0))
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(b * h,),
        in_specs=[spec, spec, spec, spec],
        out_specs=(spec, spec, spec),
        out_shape=tuple(
            jax.ShapeDtypeStruct((b * h, s, d), x.dtype)
            for x in (q, k, v)),
        interpret=interpret,
    )(fold(q), fold(k), fold(v), fold(do))
    unfold = lambda x: x.reshape(b, h, s, d)  # noqa: E731
    return unfold(dq), unfold(dk), unfold(dv)


def _flash_fwd(q, k, v, causal, block_q, interpret):
    return _forward_pallas(q, k, v, causal, block_q, interpret), (q, k, v)


def _flash_bwd(causal, block_q, interpret, residuals, g):
    q, k, v = residuals
    return _backward_pallas(q, k, v, g, causal, interpret)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, block_q: int = 128,
                    interpret: bool = False) -> jax.Array:
    """q, k, v: [batch, heads, seq, head_dim] -> same-shaped output.

    Differentiable end-to-end in Pallas: forward is the fused per-q-block
    kernel, backward the fused recompute-p kernel (_attn_bwd_kernel) via
    custom_vjp — no [s, s] tensor touches HBM in either direction.
    """
    return _flash_attention(q, k, v, causal, block_q, interpret)


def make_sharded_flash_attention(mesh, *, causal: bool = True,
                                 block_q: int = 128,
                                 batch_axis: str = "data",
                                 head_axis: str = "model"):
    """Run the fused kernel under a dp/tp mesh via shard_map.

    XLA cannot auto-partition a custom kernel, but attention is
    embarrassingly parallel over batch and heads: shard_map slices
    [b, h, s, d] over (batch_axis, head_axis), each device runs the
    kernel on its [b/dp, h/tp, s, d] shard, and no collectives are
    needed.  This is how ``attention="pallas"`` composes with the
    Megatron-style TP in model.py (heads are already split over 'model').
    """
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, head_axis, None, None)

    def body(q, k, v):
        return _flash_attention(
            q, k, v, causal, block_q,
            jax.default_backend() != "tpu")

    def attn(q, k, v):
        # check_vma=False: pallas_call's out_shape carries no varying-axis
        # metadata; the body is per-shard pure (no collectives), so the
        # check adds nothing here.
        return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False)(q, k, v)

    return attn


def _ring_step_kernel(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
                      m_out, l_out, acc_out, *, sm_scale: float,
                      diag: bool, block_q: int):
    """One ring-attention hop, fused: QK^T → (diag mask) → online-softmax
    merge into the carried (m, l, acc) — the cross-device analog of the
    flash forward, with the running stats living across ppermute hops
    instead of across k-blocks.  ``diag=True`` is the src==self hop of a
    causal ring (lower-triangular block); fully-visible hops use
    ``diag=False``; invisible hops never reach the kernel (lax.switch
    skips them outside)."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale           # [bq, d]
    k = k_ref[0].astype(jnp.float32)                      # [sk, d]
    v = v_ref[0].astype(jnp.float32)
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)               # [bq, sk]
    if diag:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    m_prev = m_ref[0]                                      # [bq, 1]
    l_prev = l_ref[0]
    acc_prev = acc_ref[0]                                  # [bq, d]
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
    p = jnp.exp(scores - m_new)
    corr = jnp.exp(m_prev - m_new)
    m_out[0] = m_new
    l_out[0] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_out[0] = acc_prev * corr + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def ring_flash_step(q, k_t, v_t, m, l, acc, *, diag: bool,
                    block_q: int = 128, interpret: bool = False):
    """Merge one rotating K/V block into the ring carry, fused in VMEM.

    q: [b, h, sq, d] (this device's queries; any dtype);
    k_t, v_t: [b, h, sk, d] (the block currently visiting);
    m, l: [b, h, sq, 1] f32; acc: [b, h, sq, d] f32.
    Returns the updated (m, l, acc).  No [sq, sk] tensor touches HBM.
    """
    b, h, sq, d = q.shape
    sk = k_t.shape[2]
    block_q = _fit_block(sq, block_q)
    sm_scale = d ** -0.5
    fold = _fold_heads
    kernel = functools.partial(_ring_step_kernel, sm_scale=sm_scale,
                               diag=diag, block_q=block_q)
    qspec = pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0))
    kspec = pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0))
    mspec = pl.BlockSpec((1, block_q, 1), lambda bh, i: (bh, i, 0))
    m2, l2, acc2 = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[qspec, kspec, kspec, mspec, mspec, qspec],
        out_specs=(mspec, mspec, qspec),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, d), jnp.float32),
        ),
        interpret=interpret,
    )(fold(q), fold(k_t), fold(v_t), fold(m), fold(l), fold(acc))
    unfold = lambda x: x.reshape(b, h, *x.shape[1:])  # noqa: E731
    return unfold(m2), unfold(l2), unfold(acc2)


def reference_attention(q, k, v, *, causal=True):
    """Plain einsum attention, the numerics oracle for the kernel."""
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * d ** -0.5
    if causal:
        s = scores.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)
