"""Fused causal attention as a Pallas TPU kernel.

The one genuinely hot op in the in-tree workload (workloads/model.py).  The
einsum path materializes [b, h, s, s] score tensors in HBM; these kernels
never let any [s, s] (or even [block_q, s]) tensor exist: both directions
iterate over K/V blocks with the online-softmax carry (m, l, acc) living in
VMEM scratch across the innermost grid dimension — the standard TPU flash
blocking.  Scoped-VMEM cost is O(block_q * block_k), independent of
sequence length, so the same kernel serves s=64 unit tests and s=8k+
training runs (the round-1 whole-K design OOMed scoped VMEM at s=2048 on
real v5e hardware: 31.77M > 16M — that failure drove this rewrite).

fp32 accumulation on the MXU via ``preferred_element_type``; bf16 in/out.
Causal runs skip fully-masked k-blocks' compute via ``pl.when`` (the MXU
work halves; the DMA still streams, which XLA overlaps).

The backward is the recompute-p flash backward split into two blocked
kernels — dq (k innermost) and dk/dv (q innermost) — driven by the
forward's saved logsumexp and delta = rowsum(do * o), each accumulating
into an fp32 VMEM scratch tile and writing once per output block.

Falls back to interpret mode off-TPU so the same code path is unit-tested
on the CPU mesh (tests/test_attention.py compares against the reference
einsum implementation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fit_block(s: int, block: int) -> int:
    """Largest divisor of ``s`` not exceeding ``block``, so any sequence
    length works (the einsum path accepts any s; the kernels must too,
    not crash on s % 128 != 0)."""
    block = min(block, s)
    while s % block:
        block -= 1
    return block


def _fold_heads(x: jax.Array) -> jax.Array:
    """[b, h, ...] -> [b*h, ...] (one grid cell per batch*head)."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def _block_mask(scores, qi, ki, block_q, block_k, window=None):
    """Causal (optionally sliding-window) mask for one [block_q,
    block_k] score tile: key visible iff q_pos - window < k_pos <=
    q_pos."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1)
    keep = q_pos >= k_pos
    if window is not None:
        keep &= q_pos - k_pos < window
    return jnp.where(keep, scores, NEG_INF)


def _block_visible(qi, ki, block_q: int, block_k: int, causal: bool,
                   window=None):
    """Whether tile (qi, ki) has any unmasked entry.  Under causality a
    k-block is fully masked iff its first key comes after the q-block's
    last query; with a sliding window, also iff its last key precedes
    the q-block's first query by >= window.  The kernels skip such
    tiles' (MXU) work via pl.when — for a window the live tiles form a
    diagonal band, so compute is O(seq * window), not O(seq^2).
    Must stay consistent with _block_mask.  The diagonal tile is always
    visible (q attends to itself), so the forward's online-softmax
    carry never ends at its NEG_INF init."""
    if not causal:
        return True
    vis = qi * block_q + block_q - 1 >= ki * block_k
    if window is not None:
        vis &= qi * block_q - (ki * block_k + block_k - 1) < window
    return vis


def _online_softmax_merge(scores, v, m_prev, l_prev, acc_prev):
    """Merge one score tile into the flash carry (m, l, acc).

    The single source of truth for the online-softmax update, shared by
    the k-block loop of the forward kernel and the cross-device hop of
    the ring kernel (same math, different iteration axis)."""
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
    p = jnp.exp(scores - m_new)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    # PV in v's dtype (bf16 in training) with f32 accumulation: the MXU
    # runs its native-precision path; p in f32 would force a slow f32
    # matmul (flash-attention's standard low-precision-p trade).
    acc_new = acc_prev * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _validate_attention_args(q, k, v, causal, window) -> None:
    """Shared by every public entry point; Pallas index-map clamping
    would otherwise turn these shape/flag errors into silently wrong
    output."""
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"query heads ({q.shape[1]}) must be a multiple of kv heads "
            f"({k.shape[1]})")
    if k.shape != v.shape:
        raise ValueError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1")
    if (q.shape[0], q.shape[2], q.shape[3]) != (
            k.shape[0], k.shape[2], k.shape[3]):
        # Self-attention only: a shorter KV (cross-attention / KV-cache
        # shape) would make the KV index maps read out of range.
        raise ValueError(
            f"q and k/v must share batch, seq and head_dim; got q "
            f"{q.shape} vs kv {k.shape}")


def causal_band_mask(s: int, window: int | None = None) -> jax.Array:
    """[s, s] boolean mask: key visible iff q - window < k <= q.

    The dense counterpart of the kernels' _block_mask, shared by the
    einsum paths (model._block, reference_attention) so the window
    semantics have one definition."""
    mask = jnp.tril(jnp.ones((s, s), bool))
    if window is not None:
        pos = jnp.arange(s)
        mask &= (pos[:, None] - pos[None, :]) < window
    return mask


def _cld(a: int, b: int) -> int:
    return -(-a // b)


def _kv_band(window, block_q: int, block_k: int, n_kb: int):
    """(n_vis, ki_of): how many k-block positions each q-block visits,
    and the TRUE k-block index for inner grid position j.

    window=None: every k-block (ki_of is identity; the causal upper
    triangle is pl.when-skipped but still streamed).  With a window the
    inner grid axis covers only the diagonal band — k-blocks that can
    intersect [q_lo - window + 1, q_hi] — so both compute AND the DMA
    stream scale O(seq * window).  ki_of may return a negative index at
    the left edge; callers clamp the BlockSpec index to 0 (harmless
    duplicate fetch) and pl.when-skip the compute."""
    if window is None:
        return n_kb, (lambda qi, j: j), (lambda qi, j: j)
    n_vis = min(n_kb, _cld(block_q + window - 1, block_k) + 1)

    def ki_of(qi, j):
        kb_hi = (qi * block_q + block_q - 1) // block_k
        return kb_hi - (n_vis - 1) + j

    def ki_clamped(qi, j):
        # Left-edge clamp for BlockSpec index maps (compute is skipped
        # for the duplicate fetch via pl.when on the true index).
        return jnp.maximum(ki_of(qi, j), 0)

    return n_vis, ki_of, ki_clamped


def _q_band(window, block_q: int, block_k: int, n_qb: int):
    """(n_visq, qb_of): the dk/dv-kernel mirror of _kv_band — the
    q-blocks that can see k-block ki.  qb_of may run past n_qb - 1 at
    the right edge; callers clamp the BlockSpec index and pl.when-skip
    the compute."""
    if window is None:
        return n_qb, (lambda ki, j: j)
    n_visq = min(n_qb, _cld(block_k + window - 1, block_q) + 1)

    def qb_of(ki, j):
        return (ki * block_k) // block_q + j

    return n_visq, qb_of


# --------------------------------------------------------------------------
# Forward: grid (b*h, q-blocks, k-band), k innermost; carry in scratch
# --------------------------------------------------------------------------


def _attn_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                     m_scr, l_scr, acc_scr, *, sm_scale: float,
                     causal: bool, block_q: int, block_k: int,
                     n_vis: int, ki_of, window=None):
    qi = pl.program_id(1)
    j = pl.program_id(2)
    ki = ki_of(qi, j)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = _block_visible(qi, ki, block_q, block_k, causal, window)
    if window is not None:
        live &= ki >= 0

    @pl.when(live)
    def _step():
        # QK^T in the input dtype with f32 accumulation — bf16 inputs
        # take the MXU's native path; upcasting first would force an
        # f32 matmul several times slower.  sm_scale applies to the f32
        # scores, not bf16 q, to keep its precision.
        scores = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            scores = _block_mask(scores, qi, ki, block_q, block_k,
                                 window)
        m_scr[...], l_scr[...], acc_scr[...] = _online_softmax_merge(
            scores, v_ref[0], m_scr[...], l_scr[...], acc_scr[...])

    @pl.when(j == n_vis - 1)
    def _finish():
        l = l_scr[...]
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[...] + jnp.log(l)


def _kv_head_map(h: int, h_kv: int):
    """Fold-space index of the KV head serving fold-space q-head ``bh``.

    GQA: h query heads share h_kv KV heads in contiguous groups (head g
    reads KV head g // (h // h_kv)); with h == h_kv this is identity
    (MHA), with h_kv == 1 it is MQA.  Pure index arithmetic, so KV blocks
    are shared at the DMA level — never materialized per q-head."""
    group = h // h_kv

    def to_kv(bh):
        return (bh // h) * h_kv + (bh % h) // group

    return to_kv


def _forward_pallas(q, k, v, causal, window, block_q, block_k,
                    interpret):
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    block_q = _fit_block(s, block_q)
    block_k = _fit_block(s, block_k)
    n_kb = s // block_k
    sm_scale = d ** -0.5
    kv_of = _kv_head_map(h, h_kv)
    n_vis, ki_of, ki_clamped = _kv_band(window, block_q, block_k, n_kb)

    def kv_block(bh, qi, j):
        return (kv_of(bh), ki_clamped(qi, j), 0)

    fold = _fold_heads
    kernel = functools.partial(
        _attn_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, n_vis=n_vis, ki_of=ki_of,
        window=window)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, s // block_q, n_vis),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, j: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_block),
            pl.BlockSpec((1, block_k, d), kv_block),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda bh, qi, j: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, j: (bh, qi, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, 1), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(fold(q), fold(k), fold(v))
    return out.reshape(b, h, s, d), lse.reshape(b, h, s, 1)


# --------------------------------------------------------------------------
# Backward: two blocked kernels sharing the saved lse and delta
# --------------------------------------------------------------------------


def _recompute_p(q_ref, k_ref, lse_ref, qi, ki, *, sm_scale, causal,
                 block_q, block_k, window=None):
    """Rebuild this tile's probabilities from q, k and the saved lse.

    Input-dtype QK^T with f32 accumulation (native MXU path for bf16)."""
    scores = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale    # [bq, bk]
    if causal:
        scores = _block_mask(scores, qi, ki, block_q, block_k, window)
    return jnp.exp(scores - lse_ref[0])                   # masked -> 0


def _attn_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, dq_scr, *, sm_scale: float, causal: bool,
                        block_q: int, block_k: int, n_vis: int, ki_of,
                        window=None):
    qi = pl.program_id(1)
    j = pl.program_id(2)
    ki = ki_of(qi, j)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = _block_visible(qi, ki, block_q, block_k, causal, window)
    if window is not None:
        live &= ki >= 0

    @pl.when(live)
    def _step():
        p = _recompute_p(q_ref, k_ref, lse_ref, qi, ki, sm_scale=sm_scale,
                         causal=causal, block_q=block_q, block_k=block_k,
                         window=window)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        ds = p * (dp - delta_ref[0])
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(j == n_vis - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _attn_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, dk_scr, dv_scr, *,
                         sm_scale: float, causal: bool, block_q: int,
                         block_k: int, n_qb: int, n_visq: int, qb_of,
                         n_inner: int, window=None):
    ki = pl.program_id(1)
    # Inner axis enumerates (q-head-in-group, q-band position) pairs:
    # each KV head accumulates dk/dv over every q-head of its GQA group
    # and every q-block that can see it (n_inner == group * n_visq;
    # MHA with no window is group == 1, n_visq == n_qb).
    inner = pl.program_id(2)
    qi = qb_of(ki, inner % n_visq)

    @pl.when(inner == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = _block_visible(qi, ki, block_q, block_k, causal, window)
    if window is not None:
        live &= qi <= n_qb - 1

    @pl.when(live)
    def _step():
        p = _recompute_p(q_ref, k_ref, lse_ref, qi, ki, sm_scale=sm_scale,
                         causal=causal, block_q=block_q, block_k=block_k,
                         window=window)
        p_lo = p.astype(do_ref.dtype)
        dv_scr[...] += jax.lax.dot_general(
            p_lo, do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, d]
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        ds = p * (dp - delta_ref[0])
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(inner == n_inner - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _backward_pallas(q, k, v, o, lse, do, causal, window, block_q,
                     block_k, interpret):
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    group = h // h_kv
    block_q = _fit_block(s, block_q)
    block_k = _fit_block(s, block_k)
    n_qb, n_kb = s // block_q, s // block_k
    sm_scale = d ** -0.5
    kv_of = _kv_head_map(h, h_kv)
    n_vis, ki_of, ki_clamped = _kv_band(window, block_q, block_k, n_kb)
    n_visq, qb_of = _q_band(window, block_q, block_k, n_qb)

    # delta = rowsum(do * o): cheap elementwise, fused by XLA outside.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)               # [b, h, s, 1]

    fold = _fold_heads
    fq, fk, fv, fdo = fold(q), fold(k), fold(v), fold(do)
    flse, fdelta = fold(lse), fold(delta)

    # dq: grid (b*h, q-blocks, k-band), k innermost; KV heads mapped.
    def kv_block(bh, qi, j):
        return (kv_of(bh), ki_clamped(qi, j), 0)

    qspec = pl.BlockSpec((1, block_q, d), lambda bh, qi, j: (bh, qi, 0))
    rspec = pl.BlockSpec((1, block_q, 1), lambda bh, qi, j: (bh, qi, 0))
    kspec = pl.BlockSpec((1, block_k, d), kv_block)
    dq = pl.pallas_call(
        functools.partial(
            _attn_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, n_vis=n_vis, ki_of=ki_of,
            window=window),
        grid=(b * h, n_qb, n_vis),
        in_specs=[qspec, kspec, kspec, qspec, rspec, rspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(fq, fk, fv, fdo, flse, fdelta)

    # dk/dv: grid (b*h_kv, k-blocks, group*q-band) — the inner axis
    # walks every (q-head-in-group, visible q-block) pair feeding this
    # KV head.
    def q_of(bhk, ki, inner):
        qb = qb_of(ki, inner % n_visq)
        if window is not None:
            qb = jnp.minimum(qb, n_qb - 1)  # right-edge clamp
        return ((bhk // h_kv) * h + (bhk % h_kv) * group
                + inner // n_visq, qb, 0)

    qspec_g = pl.BlockSpec((1, block_q, d), q_of)
    rspec_g = pl.BlockSpec((1, block_q, 1), q_of)
    kspec_g = pl.BlockSpec((1, block_k, d),
                           lambda bhk, ki, inner: (bhk, ki, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _attn_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, n_qb=n_qb, n_visq=n_visq,
            qb_of=qb_of, n_inner=group * n_visq, window=window),
        grid=(b * h_kv, n_kb, group * n_visq),
        in_specs=[qspec_g, kspec_g, kspec_g, qspec_g, rspec_g, rspec_g],
        out_specs=(kspec_g, kspec_g),
        out_shape=(jax.ShapeDtypeStruct((b * h_kv, s, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h_kv, s, d), v.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(fq, fk, fv, fdo, flse, fdelta)

    unfold_q = lambda x: x.reshape(b, h, s, d)  # noqa: E731
    unfold_kv = lambda x: x.reshape(b, h_kv, s, d)  # noqa: E731
    return unfold_q(dq), unfold_kv(dk), unfold_kv(dv)


# --------------------------------------------------------------------------
# custom_vjp plumbing + public API
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, window, block_q, block_k,
                     interpret):
    out, _ = _forward_pallas(q, k, v, causal, window, block_q, block_k,
                             interpret)
    return out


def _flash_fwd(q, k, v, causal, window, block_q, block_k, interpret):
    out, lse = _forward_pallas(q, k, v, causal, window, block_q, block_k,
                               interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, window, block_q, block_k, interpret, residuals, g):
    q, k, v, o, lse = residuals
    return _backward_pallas(q, k, v, o, lse, g, causal, window, block_q,
                            block_k, interpret)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.jit,
                   static_argnames=("causal", "window", "block_q",
                                    "block_k", "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, window: int | None = None,
                    block_q: int = 512, block_k: int = 1024,
                    interpret: bool = False) -> jax.Array:
    """q: [batch, heads, seq, head_dim]; k, v: [batch, kv_heads, seq,
    head_dim] with heads % kv_heads == 0 -> output shaped like q.

    kv_heads == heads is classic MHA; kv_heads < heads is GQA (MQA at
    kv_heads == 1): contiguous groups of heads // kv_heads query heads
    share one KV head, wired at the kernel index-map level so shared KV
    blocks are never materialized per q-head.

    ``window=w`` (requires causal) is sliding-window attention
    (Mistral-family): each query sees only the w most recent keys
    including itself.  Tiles outside the diagonal band are skipped
    entirely, so compute scales O(seq * window) instead of O(seq^2).

    Differentiable end-to-end in Pallas: forward is the KV-blocked
    online-softmax kernel (saving lse), backward the pair of blocked
    recompute-p kernels via custom_vjp — no [s, s] tensor touches HBM or
    VMEM in either direction.
    """
    _validate_attention_args(q, k, v, causal, window)
    return _flash_attention(q, k, v, causal, window, block_q, block_k,
                            interpret)


def make_sharded_flash_attention(mesh, *, causal: bool = True,
                                 window: int | None = None,
                                 block_q: int = 512, block_k: int = 1024,
                                 batch_axis: str = "data",
                                 head_axis: str = "model"):
    """Run the fused kernel under a dp/tp mesh via shard_map.

    XLA cannot auto-partition a custom kernel, but attention is
    embarrassingly parallel over batch and heads: shard_map slices
    [b, h, s, d] over (batch_axis, head_axis), each device runs the
    kernel on its [b/dp, h/tp, s, d] shard, and no collectives are
    needed.  This is how ``attention="pallas"`` composes with the
    Megatron-style TP in model.py (heads are already split over 'model').

    ``batch_axis`` may be a tuple of mesh axes (multi-slice meshes shard
    batch over ("dcn", "data")); ``head_axis=None`` replicates heads
    (meshes with no 'model' axis).  GQA constraint: the head_axis size
    must divide both q heads and kv heads so each shard keeps whole
    contiguous KV-head groups (ModelConfig.mesh_shardable).
    """
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, head_axis, None, None)

    def body(q, k, v):
        _validate_attention_args(q, k, v, causal, window)
        return _flash_attention(
            q, k, v, causal, window, block_q, block_k,
            jax.default_backend() != "tpu")

    def attn(q, k, v):
        # check_vma=False: pallas_call's out_shape carries no varying-axis
        # metadata; the body is per-shard pure (no collectives), so the
        # check adds nothing here.
        return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False)(q, k, v)

    return attn


def _rel_mask(scores, offset, window):
    """Causal/window mask on a [..., sq, sk] score block whose q
    positions lead its k positions by ``offset`` (traced): key visible
    iff 0 <= offset + q - k (< window).  The single definition of the
    ring hops' mask semantics, shared by the einsum merge
    (ring_attention.py) and the pallas ring kernels below."""
    q_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape,
                                     scores.ndim - 2)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape,
                                     scores.ndim - 1)
    rel = offset + q_pos - k_pos
    keep = rel >= 0
    if window is not None:
        keep &= rel < window
    return jnp.where(keep, scores, NEG_INF)


def _ring_mask(scores, off, qi, block_q: int, window):
    """_rel_mask for one [block_q, sk] tile at q-block ``qi``: fold the
    tile's q start into the hop offset."""
    return _rel_mask(scores, off + qi * block_q, window)


def _ring_step_kernel(off_ref, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
                      m_out, l_out, acc_out, *, sm_scale: float,
                      masked: bool, window, block_q: int):
    """One ring-attention hop, fused: QK^T → (mask) → online-softmax
    merge into the carried (m, l, acc) — the cross-device analog of the
    flash forward, with the running stats living across ppermute hops
    instead of across k-blocks.  ``masked=True`` applies the causal (and
    sliding-window) mask from the hop's element offset in SMEM; fully
    visible hops compile with ``masked=False`` and skip the iota work;
    invisible hops never reach the kernel (lax.switch skips them in the
    ring driver)."""
    qi = pl.program_id(1)
    # Input-dtype QK^T with f32 accumulation (native MXU path for bf16);
    # sm_scale applies to the f32 scores.
    scores = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale    # [bq, sk]
    if masked:
        scores = _ring_mask(scores, off_ref[0], qi, block_q, window)
    m_out[0], l_out[0], acc_out[0] = _online_softmax_merge(
        scores, v_ref[0], m_ref[0], l_ref[0], acc_ref[0])


def ring_flash_step(q, k_t, v_t, m, l, acc, *, offset, masked: bool,
                    window: int | None = None, block_q: int = 128,
                    interpret: bool = False):
    """Merge one rotating K/V block into the ring carry, fused in VMEM.

    q: [b, h, sq, d] (this device's queries; any dtype);
    k_t, v_t: [b, h_kv, sk, d] (the block currently visiting; h_kv may
    divide h — GQA wired at the index-map level like the flash kernels);
    m, l: [b, h, sq, 1] f32; acc: [b, h, sq, d] f32;
    offset: traced int32, global(q_block_start) - global(k_block_start)
    — only read when ``masked``.
    Returns the updated (m, l, acc).  No [sq, sk] tensor touches HBM.
    """
    b, h, sq, d = q.shape
    h_kv, sk = k_t.shape[1], k_t.shape[2]
    block_q = _fit_block(sq, block_q)
    sm_scale = d ** -0.5
    kv_of = _kv_head_map(h, h_kv)
    fold = _fold_heads
    kernel = functools.partial(_ring_step_kernel, sm_scale=sm_scale,
                               masked=masked, window=window,
                               block_q=block_q)
    qspec = pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0))
    kspec = pl.BlockSpec((1, sk, d), lambda bh, i: (kv_of(bh), 0, 0))
    mspec = pl.BlockSpec((1, block_q, 1), lambda bh, i: (bh, i, 0))
    off = jnp.asarray(offset, jnp.int32).reshape(1)
    m2, l2, acc2 = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  qspec, kspec, kspec, mspec, mspec, qspec],
        out_specs=(mspec, mspec, qspec),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, d), jnp.float32),
        ),
        interpret=interpret,
    )(off, fold(q), fold(k_t), fold(v_t), fold(m), fold(l), fold(acc))
    unfold = lambda x: x.reshape(b, h, *x.shape[1:])  # noqa: E731
    return unfold(m2), unfold(l2), unfold(acc2)


def _ring_bwd_dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, dq_ref, *, sm_scale: float,
                        masked: bool, window, block_q: int):
    """Per-hop dq: rebuild this (q-block, visiting-KV) tile's p from the
    saved lse — no forward recompute — then dq = (p∘(dp-δ)) K · scale."""
    qi = pl.program_id(1)
    scores = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale    # [bq, sk]
    if masked:
        scores = _ring_mask(scores, off_ref[0], qi, block_q, window)
    p = jnp.exp(scores - lse_ref[0])                      # masked -> 0
    dp = jax.lax.dot_general(
        do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0])
    dq_ref[0] = jax.lax.dot_general(
        ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale


def _ring_bwd_dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                         sm_scale: float, masked: bool, window,
                         block_q: int, n_qb: int, n_inner: int):
    """Per-hop dk/dv for the visiting block, accumulated in VMEM scratch
    over every (q-head-in-group, q-block) pair feeding this KV head."""
    inner = pl.program_id(1)
    qi = inner % n_qb

    @pl.when(inner == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    scores = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale    # [bq, sk]
    if masked:
        scores = _ring_mask(scores, off_ref[0], qi, block_q, window)
    p = jnp.exp(scores - lse_ref[0])
    dv_scr[...] += jax.lax.dot_general(
        p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)               # [sk, d]
    dp = jax.lax.dot_general(
        do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0])
    dk_scr[...] += jax.lax.dot_general(
        ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale

    @pl.when(inner == n_inner - 1)
    def _finish():
        dk_ref[0] = dk_scr[...]
        dv_ref[0] = dv_scr[...]


def ring_flash_bwd_step(q, k_t, v_t, do, lse, delta, *, offset,
                        masked: bool, window: int | None = None,
                        block_q: int = 128, interpret: bool = False):
    """One backward ring hop, fused: given this device's (q, do, lse, δ)
    and the visiting (k_t, v_t), return (dq_add [b,h,sq,d] f32,
    dk_add/dv_add [b,h_kv,sk,d] f32) — the contributions this hop adds
    to the local dq accumulator and to the rotating dk/dv buffers.
    Probabilities are rebuilt from the saved lse (recompute-p flash
    backward), so no forward pass and no [sq, sk] HBM tensor."""
    b, h, sq, d = q.shape
    h_kv, sk = k_t.shape[1], k_t.shape[2]
    group = h // h_kv
    block_q = _fit_block(sq, block_q)
    n_qb = sq // block_q
    sm_scale = d ** -0.5
    kv_of = _kv_head_map(h, h_kv)
    fold = _fold_heads
    fq, fk, fv, fdo = fold(q), fold(k_t), fold(v_t), fold(do)
    flse, fdelta = fold(lse), fold(delta)
    off = jnp.asarray(offset, jnp.int32).reshape(1)
    sspec = pl.BlockSpec(memory_space=pltpu.SMEM)

    qspec = pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0))
    kspec = pl.BlockSpec((1, sk, d), lambda bh, i: (kv_of(bh), 0, 0))
    rspec = pl.BlockSpec((1, block_q, 1), lambda bh, i: (bh, i, 0))
    dq_add = pl.pallas_call(
        functools.partial(_ring_bwd_dq_kernel, sm_scale=sm_scale,
                          masked=masked, window=window, block_q=block_q),
        grid=(b * h, n_qb),
        in_specs=[sspec, qspec, kspec, kspec, qspec, rspec, rspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), jnp.float32),
        interpret=interpret,
    )(off, fq, fk, fv, fdo, flse, fdelta)

    # dk/dv: grid (b*h_kv, group*n_qb) — inner axis walks every
    # (q-head-in-group, q-block) pair feeding this KV head.
    def q_of(bhk, inner):
        return ((bhk // h_kv) * h + (bhk % h_kv) * group + inner // n_qb,
                inner % n_qb, 0)

    qspec_g = pl.BlockSpec((1, block_q, d), q_of)
    rspec_g = pl.BlockSpec((1, block_q, 1), q_of)
    kspec_g = pl.BlockSpec((1, sk, d), lambda bhk, inner: (bhk, 0, 0))
    dk_add, dv_add = pl.pallas_call(
        functools.partial(_ring_bwd_dkv_kernel, sm_scale=sm_scale,
                          masked=masked, window=window, block_q=block_q,
                          n_qb=n_qb, n_inner=group * n_qb),
        grid=(b * h_kv, group * n_qb),
        in_specs=[sspec, qspec_g, kspec_g, kspec_g, qspec_g, rspec_g,
                  rspec_g],
        out_specs=(kspec_g, kspec_g),
        out_shape=(jax.ShapeDtypeStruct((b * h_kv, sk, d), jnp.float32),
                   jax.ShapeDtypeStruct((b * h_kv, sk, d), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((sk, d), jnp.float32),
                        pltpu.VMEM((sk, d), jnp.float32)],
        interpret=interpret,
    )(off, fq, fk, fv, fdo, flse, fdelta)

    unfold_q = lambda x: x.reshape(b, h, sq, d)  # noqa: E731
    unfold_kv = lambda x: x.reshape(b, h_kv, sk, d)  # noqa: E731
    return unfold_q(dq_add), unfold_kv(dk_add), unfold_kv(dv_add)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr,
                   acc_scr, *, sm_scale: float, window, block_k: int,
                   n_kb: int, h_kv: int, ring: bool):
    """Single-token cached attention, blocked over the KV cache: one
    GQA group's queries ([group, d]) stream the cache's k-blocks through
    VMEM with the online-softmax carry in scratch — probabilities never
    touch HBM.  Blocks entirely past the row's ``length`` (or behind the
    window) skip their MXU work via pl.when on the SMEM lengths —
    per-ROW lengths, so a continuous-batching slot batch pays each
    sequence only its own cache read.

    ``ring=True``: the cache is a ring buffer (serving.py's O(window)
    layout) — slot s holds absolute position (L-1) - ((L-1-s) mod
    width); the causal+window mask runs on those absolute positions.
    No block skipping: a ring sized to the window is almost always
    fully live."""
    j = pl.program_id(1)
    row = pl.program_id(0) // h_kv          # batch/slot of this grid row
    qpos = len_ref[row] - 1  # this row's new-token absolute position

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    if ring:
        live = True
    else:
        live = j * block_k <= qpos
        if window is not None:
            live &= j * block_k + block_k - 1 > qpos - window

    @pl.when(live)
    def _step():
        scores = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [g, bk]
        k_slot = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        if ring:
            width = n_kb * block_k
            k_pos = qpos - jnp.mod(qpos - k_slot, width)
            keep = (k_pos >= 0) & (k_pos <= qpos) \
                & (k_pos > qpos - window)
        else:
            k_pos = k_slot
            keep = k_pos <= qpos
            if window is not None:
                keep &= k_pos > qpos - window
        scores = jnp.where(keep, scores, NEG_INF)
        m_scr[...], l_scr[...], acc_scr[...] = _online_softmax_merge(
            scores, v_ref[0], m_scr[...], l_scr[...], acc_scr[...])

    @pl.when(j == n_kb - 1)
    def _finish():
        # length=0 leaves no live block (l stays 0); clamp like
        # _ring_driver so the kernel emits zeros, not 0/0 NaN.
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def flash_decode(q, k_cache, v_cache, length, *, window: int | None = None,
                 ring: bool = False, block_k: int = 512,
                 interpret: bool = False):
    """Fused cached attention for one decode step.

    q: [b, h, 1, d] (the new token's queries, already rotated);
    k_cache, v_cache: [b, kv_heads, max_len, d] (the new k/v already
    written at position length-1); length: traced int32 count of filled
    slots — a scalar (all rows equal: the fixed-batch path) or a [b]
    vector (per-row lengths: the continuous-batching slot path).
    Returns [b, h, 1, d].

    ``ring=True`` (requires ``window``): the cache is serving.py's ring
    layout over its max_len width — the mask recovers each slot's
    absolute position from the row's logical length, which may exceed
    the width (the new k/v must already be written at position
    (length-1) % width).

    Decode is HBM-bandwidth-bound (the cache read IS the cost); this
    kernel makes that read single-pass — QK^T, masked online softmax,
    and PV fused per k-block — instead of the einsum path's
    score-materialize + second cache pass.  GQA groups share each
    streamed KV block at the index-map level."""
    b, h, sq, d = q.shape
    if sq != 1:
        raise ValueError(f"flash_decode is single-token (sq=1); got {sq}")
    if ring and window is None:
        raise ValueError("ring=True requires a window")
    h_kv, max_len = k_cache.shape[1], k_cache.shape[2]
    group = h // h_kv
    block_k = _fit_block(max_len, block_k)
    n_kb = max_len // block_k
    sm_scale = d ** -0.5
    # One grid row per (batch, kv head): its GQA group's queries attend
    # together so the KV block is fetched once for the whole group.
    qg = q.reshape(b, h_kv, group, d).reshape(b * h_kv, group, d)
    fk = k_cache.reshape(b * h_kv, max_len, d)
    fv = v_cache.reshape(b * h_kv, max_len, d)
    lengths = jnp.broadcast_to(
        jnp.asarray(length, jnp.int32).reshape(-1), (b,))
    out = pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=sm_scale,
                          window=window, block_k=block_k, n_kb=n_kb,
                          h_kv=h_kv, ring=ring),
        grid=(b * h_kv, n_kb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, group, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, group, d), lambda bh, j: (bh, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h_kv, group, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
        interpret=interpret,
    )(lengths, qg, fk, fv)
    return out.reshape(b, h, 1, d)


def _paged_decode_kernel(len_ref, tab_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, sm_scale: float,
                         window, block_size: int, n_blocks: int,
                         h_kv: int):
    """_decode_kernel's math over a PAGED cache: grid step (row, j)
    streams the j-th table entry's POOL block, fetched in place by the
    scalar-prefetched block table (the index map chases tab_ref) — the
    vLLM/PagedAttention read pattern without the gather copy the
    einsum path pays.  Dead table slots (-1: positions past the row's
    length) skip their MXU work via the same pl.when the linear kernel
    uses for past-length blocks."""
    j = pl.program_id(1)
    row = pl.program_id(0) // h_kv
    qpos = len_ref[row] - 1

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = (j * block_size <= qpos) & (tab_ref[row, j] >= 0)
    if window is not None:
        live &= j * block_size + block_size - 1 > qpos - window

    @pl.when(live)
    def _step():
        scores = jax.lax.dot_general(
            q_ref[0], k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [g, bs]
        k_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        keep = k_pos <= qpos
        if window is not None:
            keep &= k_pos > qpos - window
        scores = jnp.where(keep, scores, NEG_INF)
        m_scr[...], l_scr[...], acc_scr[...] = _online_softmax_merge(
            scores, v_ref[0, 0], m_scr[...], l_scr[...], acc_scr[...])

    @pl.when(j == n_blocks - 1)
    def _finish():
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def paged_flash_decode(q, k_pool, v_pool, tables, lengths, *,
                       window: int | None = None,
                       interpret: bool = False):
    """Fused cached attention for one decode step over a PAGED cache.

    q: [slots, h, 1, d]; k_pool, v_pool: [num_blocks, kv_heads,
    block_size, d] (the global block pool — workloads/paged.py's
    layout, one layer's slice); tables: [slots, tpr] int32 block ids
    (-1 = no block); lengths: [slots] int32.  Returns [slots, h, 1, d].

    The pool blocks are read IN PLACE: the k/v index maps look the
    block id up in the scalar-prefetched table, so no [slots, tpr*bs]
    contiguous gather copy (which doubles the decode step's HBM
    traffic — the decode cost) happens before the read.  Dead table
    entries still fetch a (clamped) block per BlockSpec semantics;
    only their MXU work is skipped — the saving is the gather copy,
    not fewer-than-tpr fetches.  Same per-row online-softmax math as
    flash_decode; parity pinned in tests/test_paged.py."""
    slots, h, sq, d = q.shape
    if sq != 1:
        raise ValueError(
            f"paged_flash_decode is single-token (sq=1); got {sq}")
    nb, h_kv, block_size, dk = k_pool.shape
    if dk != d:
        raise ValueError(f"head dim mismatch: q {d} vs pool {dk}")
    tpr = tables.shape[1]
    group = h // h_kv
    sm_scale = d ** -0.5
    qg = q.reshape(slots, h_kv, group, d).reshape(slots * h_kv, group, d)
    lengths = jnp.asarray(lengths, jnp.int32).reshape(slots)
    tables = jnp.asarray(tables, jnp.int32)

    def q_map(bh, j, len_ref, tab_ref):
        return (bh, 0, 0)

    def kv_map(bh, j, len_ref, tab_ref):
        # Chase the block table: grid step (row, j) reads pool block
        # tables[row, j] for this row's kv head.  Out-of-range entries
        # clamp into the pool (same [0, nb-1] clip as _gather_rows);
        # dead entries' compute is pl.when-skipped.
        row = bh // h_kv
        head = bh % h_kv
        return (jnp.clip(tab_ref[row, j], 0, nb - 1), head, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots * h_kv, tpr),
        in_specs=[
            pl.BlockSpec((1, group, d), q_map),
            pl.BlockSpec((1, 1, block_size, d), kv_map),
            pl.BlockSpec((1, 1, block_size, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, group, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, sm_scale=sm_scale,
                          window=window, block_size=block_size,
                          n_blocks=tpr, h_kv=h_kv),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots * h_kv, group, d),
                                       q.dtype),
        interpret=interpret,
    )(lengths, tables, qg, k_pool, v_pool)
    return out.reshape(slots, h, 1, d)


def reference_attention(q, k, v, *, causal=True, window=None):
    """Plain einsum attention, the numerics oracle for the kernel.

    Accepts the same GQA layout as flash_attention (kv_heads dividing
    heads), materializing the repeat the straightforward HBM-hungry way.
    """
    if k.shape[1] != q.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * d ** -0.5
    if causal:
        mask = causal_band_mask(scores.shape[-1], window)
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)
