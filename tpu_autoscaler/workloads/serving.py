"""Continuous-batching serving engine: slot KV cache + chunked prefill.

workloads/decode.py serves one fixed-shape batch end-to-end; real
traffic is requests of different lengths arriving at different times.
This module adds the serving layer that makes a TPU slice earn its keep
under that traffic (VERDICT r3 item 4), with every device-side shape
STATIC (the XLA constraint that shapes the whole design):

- **SlotKVCache**: a fixed pool of ``slots`` sequences, each with its
  own cache region and its own ``length`` — mixed-length sequences
  decode together in ONE batched step (the per-row lengths flow into
  the flash_decode kernel's SMEM, so each row pays only its own cache
  read).
- **admit/evict**: a finished sequence frees its slot and the next
  request takes it over — the cache is reset per-slot (lengths[slot]=0)
  with no reallocation and no recompilation.
- **chunked prefill**: prompts enter the cache in fixed-size chunks
  interleaved with decode steps (one chunk per engine tick), so a long
  arriving prompt delays in-flight decodes by one bounded chunk, not by
  its full length — the Orca/vLLM scheduling insight, here with the
  chunk as the compiled unit.
- **one compiled program each** for (decode tick, prefill chunk): all
  control flow (which slot, how many valid tokens) is traced data, not
  shape.

Under the trainer's (data, model) mesh the slot batch shards over the
data axes and the cache/heads over 'model' exactly like decode.py's
fixed-batch path (cache_specs) — make_slot_decode_step takes the same
``mesh`` argument.

The reference has no serving stack at all (SURVEY §3); this is
beyond-parity evidence, continuing decode.py's story.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpu_autoscaler.serving.stats import (
    ServingSnapshot,
    ServingStatsRecorder,
)
from tpu_autoscaler.workloads.decode import _sample
from tpu_autoscaler.workloads.model import (
    ModelConfig,
    _rmsnorm,
    _split_qkv,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SlotKVCache:
    """Per-slot KV cache: k, v [layers, slots, kv_heads, max_len,
    head_dim]; lengths [slots] int32 — slot s holds a sequence whose
    first ``lengths[s]`` positions are live.  Free slots simply have
    length 0; admission resets a slot by writing 0 (stale K/V beyond
    every write point is never visible — writes always start exactly at
    the slot's current length)."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array

    @property
    def slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    @classmethod
    def zeros(cls, cfg: ModelConfig, slots: int,
              max_len: int) -> "SlotKVCache":
        shape = (cfg.n_layers, slots, cfg.kv_heads, max_len, cfg.head_dim)
        return cls(k=jnp.zeros(shape, cfg.dtype),
                   v=jnp.zeros(shape, cfg.dtype),
                   lengths=jnp.zeros((slots,), jnp.int32))


def _rope_rows(x: jax.Array, theta: float, positions: jax.Array):
    """RoPE with a PER-ROW position: x [b, h, s, hd], positions [b]
    (each row's absolute offset; within-row positions increment).
    model._rope generalized from one scalar offset to one per row —
    what a slot batch needs, where every slot sits at its own depth."""
    b, h, s, hd = x.shape
    half = hd // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    pos = positions[:, None].astype(jnp.float32) + jnp.arange(
        s, dtype=jnp.float32)[None, :]                      # [b, s]
    angles = pos[..., None] * freqs[None, None, :]          # [b, s, half]
    cos = jnp.cos(angles).astype(x.dtype)[:, None]          # [b, 1, s, half]
    sin = jnp.sin(angles).astype(x.dtype)[:, None]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _slot_cached_attention(q, k_cache, v_cache, lengths, cfg: ModelConfig):
    """Per-row-length cached attention (einsum path): q [b, h, 1, hd]
    at absolute positions ``lengths - 1``; row b sees cache slots
    j <= lengths[b]-1 (and within the window).  decode.py::
    _cached_attention generalized from one shared length."""
    b, h, sq, hd = q.shape
    hkv = k_cache.shape[1]
    max_len = k_cache.shape[2]
    qg = q.reshape(b, hkv, h // hkv, sq, hd)
    scores = jnp.einsum("bngqd,bnkd->bngqk", qg, k_cache) * hd ** -0.5
    kpos = jnp.arange(max_len)
    qpos = (lengths - 1)[:, None]                          # [b, 1]
    visible = kpos[None, :] <= qpos                        # [b, max_len]
    if cfg.attention_window is not None:
        visible &= kpos[None, :] > qpos - cfg.attention_window
    scores = jnp.where(visible[:, None, None, None],
                       scores.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
    out = jnp.einsum("bngqk,bnkd->bngqd", probs, v_cache)
    return out.reshape(b, h, sq, hd)


def _write_rows(cache, new, positions):
    """Write new [b, hkv, s, hd] into cache [b, hkv, max_len, hd] at
    per-row offsets (vmapped dynamic_update_slice)."""
    return jax.vmap(
        lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (0, p, 0))
    )(cache, new, positions)


def _ring_abs_pos(lengths, ring: int):
    """Absolute sequence position held by each ring slot, per row.

    Slot j of a row at logical length L holds the LARGEST position
    p ≡ j (mod ring) with p <= L-1: p = (L-1) - ((L-1-j) mod ring).
    Slots never written (L < ring) come out negative — mask on >= 0.
    Returns [rows, ring] int32."""
    j = jnp.arange(ring)[None, :]
    last = (lengths - 1)[:, None]
    return last - jnp.mod(last - j, ring)


def _slot_ring_attention(q, k_cache, v_cache, lengths, cfg: ModelConfig,
                         window: int):
    """_slot_cached_attention over a RING buffer: the cache holds only
    the last ``ring`` positions (ring = window + chunk slack, chosen so
    in-flight writes never displace keys still inside a live query's
    window); each slot's absolute position is recovered from the row's
    logical length, and visibility is the same causal+window rule on
    absolute positions."""
    b, h, sq, hd = q.shape
    hkv = k_cache.shape[1]
    ring = k_cache.shape[2]
    qg = q.reshape(b, hkv, h // hkv, sq, hd)
    scores = jnp.einsum("bngqd,bnkd->bngqk", qg, k_cache) * hd ** -0.5
    abs_pos = _ring_abs_pos(lengths, ring)                 # [b, ring]
    qpos = (lengths - 1)[:, None]
    visible = (abs_pos >= 0) & (abs_pos <= qpos) \
        & (abs_pos > qpos - window)
    scores = jnp.where(visible[:, None, None, None],
                       scores.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
    out = jnp.einsum("bngqk,bnkd->bngqd", probs, v_cache)
    return out.reshape(b, h, sq, hd)


def _slot_attend(q, k_c, v_c, new_len, cfg: ModelConfig, mesh,
                 ring: bool = False):
    """The cache read for one slot-decode layer: the flash_decode
    kernel with per-row lengths on TPU (wrapped in shard_map under a
    multi-device mesh — GSPMD cannot auto-partition a pallas_call;
    decode.py::_attend's recipe), the per-row einsum mask elsewhere or
    when the slot count does not divide the data axes.  ``ring``
    selects the ring-layout mask on both paths."""
    def einsum_path():
        if ring:
            return _slot_ring_attention(q, k_c, v_c, new_len, cfg,
                                        cfg.attention_window)
        return _slot_cached_attention(q, k_c, v_c, new_len, cfg)

    if cfg.resolved_attention() != "pallas":
        return einsum_path()
    from tpu_autoscaler.workloads.attention import flash_decode

    interpret = jax.default_backend() != "tpu"
    if mesh is None or mesh.size == 1:
        return flash_decode(q, k_c, v_c, new_len,
                            window=cfg.attention_window, ring=ring,
                            interpret=interpret)
    import numpy as _np
    from jax.sharding import PartitionSpec as P

    from tpu_autoscaler.workloads.model import data_axes

    daxes = data_axes(mesh)
    dp = int(_np.prod([mesh.shape[a] for a in daxes])) if daxes else 1  # analysis: allow=TAJ401 mesh axis sizes are static ints
    if q.shape[0] % dp:
        # Static shapes at trace time: an indivisible slot count serves
        # through the einsum path (model._block's fallback philosophy).
        return einsum_path()
    head_ax = "model" if "model" in mesh.axis_names else None
    dspec = P(daxes, head_ax, None, None)

    def kern(q, kc, vc, ln):
        return flash_decode(q, kc, vc, ln, window=cfg.attention_window,
                            ring=ring, interpret=interpret)

    return jax.shard_map(
        kern, mesh=mesh, in_specs=(dspec, dspec, dspec, P(daxes)),
        out_specs=dspec, check_vma=False)(q, k_c, v_c, new_len)


def make_slot_decode_step(cfg: ModelConfig, mesh=None,
                          ring: bool = False):
    """Build ``step(params, cache, tokens, active) -> (logits, cache)``:
    one token for EVERY slot in one batched program — slot s's token
    sits at its own position ``cache.lengths[s]``.  ``active`` [slots]
    bool marks the slots that really decode this tick: inactive slots
    compute garbage the engine ignores (the static-shape price — a
    masked lane is cheaper than a recompile) and their lengths do NOT
    advance, so the garbage K/V they wrote is overwritten by their next
    real write.

    tokens: [slots] int32.  Returns logits [slots, vocab] fp32 and the
    cache with active lengths advanced by 1.

    On TPU the cache read runs the flash_decode kernel with the
    PER-ROW lengths in SMEM (shard_mapped under a multi-device mesh);
    elsewhere the einsum path masks per row.  ``mesh``: shard slots
    over the data axes and KV heads over 'model' (decode.py::
    cache_specs layout).

    ``ring=True`` (requires cfg.attention_window): the cache is a RING
    over its buffer width — writes land at position % width, each
    slot's absolute position is recovered from the row's logical
    length, and per-slot HBM is O(window) instead of O(max sequence):
    sequence length becomes unbounded.  On TPU the read runs the
    fused flash_decode kernel in its ring mode (absolute positions
    recovered in-kernel), shard_mapped under multi-device meshes like
    the linear path.
    """
    if ring and cfg.attention_window is None:
        raise ValueError("ring=True needs cfg.attention_window (the "
                         "ring holds exactly the window of live keys)")
    if mesh is not None:
        cfg = cfg.resolved_for_mesh(mesh)

    def step(params, cache: SlotKVCache, tokens, active):
        from tpu_autoscaler.workloads.model import _ffn_residual

        x = params["embed"].astype(cfg.dtype)[tokens][:, None, :]
        positions = cache.lengths                      # [slots]

        def body(carry, inputs):
            x = carry
            layer, k_c, v_c = inputs
            b, s, d = x.shape
            y = _rmsnorm(x, layer["ln1"])
            q, k, v = _split_qkv(y, layer["qkv"], cfg)
            if cfg.rope:
                q = _rope_rows(q, cfg.rope_theta, positions)
                k = _rope_rows(k, cfg.rope_theta, positions)
            if ring:
                width = k_c.shape[2]
                k_c = _write_rows(k_c, k, positions % width)
                v_c = _write_rows(v_c, v, positions % width)
            else:
                k_c = _write_rows(k_c, k, positions)
                v_c = _write_rows(v_c, v, positions)
            attn = _slot_attend(q, k_c, v_c, positions + 1, cfg, mesh,
                                ring=ring)
            attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
            x = x + jnp.einsum("bsd,de->bse", attn,
                               layer["attn_out"].astype(cfg.dtype))
            y = _rmsnorm(x, layer["ln2"])
            return _ffn_residual(x, y, layer, cfg), (k_c, v_c)

        x, (k_new, v_new) = jax.lax.scan(
            body, x, (params["blocks"], cache.k, cache.v))
        x = _rmsnorm(x, params["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", x,
                            params["unembed"].astype(cfg.dtype))
        new_cache = SlotKVCache(
            k=k_new, v=v_new,
            lengths=cache.lengths + active.astype(jnp.int32))
        return logits[:, 0].astype(jnp.float32), new_cache

    if mesh is None:
        return jax.jit(step)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_autoscaler.workloads.model import data_axes, param_specs

    daxes = data_axes(mesh)
    tp_ok = "model" in mesh.axis_names
    kv = P(None, daxes, "model" if tp_ok else None, None, None)
    cache_shard = SlotKVCache(
        k=NamedSharding(mesh, kv), v=NamedSharding(mesh, kv),
        lengths=NamedSharding(mesh, P(daxes)))
    p_shard = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P))
    tok_shard = NamedSharding(mesh, P(daxes))
    logit_shard = NamedSharding(mesh, P(daxes, None))
    return jax.jit(step,
                   in_shardings=(p_shard, cache_shard, tok_shard,
                                 tok_shard),
                   out_shardings=(logit_shard, cache_shard))


def make_prefill_chunk(cfg: ModelConfig, chunk: int, mesh=None,
                       ring: bool = False):
    """Build ``fill(params, cache, slot, tokens, n_valid) -> (logits,
    cache)``: append ``n_valid`` (<= chunk, traced) prompt tokens to ONE
    slot's cache at its current length.  tokens: [chunk] int32 (padded
    past n_valid; the pad lanes compute but their K/V is overwritten by
    the next write at the corrected length, so they are never visible).
    Returns the last VALID position's logits [vocab] — the seed of
    generation when this was the prompt's final chunk.

    One compiled program per chunk size serves every prompt length:
    the engine splits prompts into ceil(len/chunk) calls interleaved
    with decode ticks.

    ``ring=True``: the cache is a ring over its buffer width (which
    must be >= cfg.attention_window + chunk, so a chunk's writes never
    displace keys still inside its own queries' windows); valid
    entries scatter at position % width and visibility runs on
    absolute positions.
    """
    if ring and cfg.attention_window is None:
        raise ValueError("ring=True needs cfg.attention_window")
    if mesh is not None:
        cfg = cfg.resolved_for_mesh(mesh)

    def fill(params, cache: SlotKVCache, slot, tokens, n_valid):
        x = params["embed"].astype(cfg.dtype)[tokens][None]  # [1, chunk, d]
        offset = cache.lengths[slot]

        def body(carry, inputs):
            x = carry
            layer, k_all, v_all = inputs           # [slots, hkv, max, hd]
            b, s, d = x.shape
            y = _rmsnorm(x, layer["ln1"])
            q, k, v = _split_qkv(y, layer["qkv"], cfg)
            if cfg.rope:
                from tpu_autoscaler.workloads.model import _rope

                q = _rope(q, cfg.rope_theta, offset)
                k = _rope(k, cfg.rope_theta, offset)
            hkv = k_all.shape[1]
            hd = cfg.head_dim
            if ring:
                # Scatter the VALID chunk entries at position % width
                # (mode='drop' discards the pad lanes: in a ring, a pad
                # write would displace a live key — unlike the linear
                # cache, where the next write overwrites it first).
                width = k_all.shape[2]
                i = jnp.arange(s)
                idx = jnp.where(i < n_valid, (offset + i) % width, width)
                k_slot = k_all.at[slot, :, idx, :].set(
                    k.transpose(2, 0, 1, 3)[:, 0], mode="drop")
                v_slot = v_all.at[slot, :, idx, :].set(
                    v.transpose(2, 0, 1, 3)[:, 0], mode="drop")
            else:
                k_slot = jax.lax.dynamic_update_slice(
                    k_all, k, (slot, 0, offset, 0))
                v_slot = jax.lax.dynamic_update_slice(
                    v_all, v, (slot, 0, offset, 0))
            # Attend over this slot's cache: causal within the chunk,
            # plus everything before the offset.
            kc = jax.lax.dynamic_index_in_dim(k_slot, slot, 0,
                                              keepdims=True)
            vc = jax.lax.dynamic_index_in_dim(v_slot, slot, 0,
                                              keepdims=True)
            max_len = kc.shape[2]
            qg = q.reshape(1, hkv, cfg.n_heads // hkv, s, hd)
            scores = jnp.einsum("bngqd,bnkd->bngqk", qg, kc) * hd ** -0.5
            qpos = offset + jnp.arange(s)
            if ring:
                # Per-query visibility on ABSOLUTE positions recovered
                # from the ring layout at this chunk's end state.
                abs_pos = _ring_abs_pos(
                    (offset + n_valid)[None], max_len)[0]  # [width]
                visible = (abs_pos[None, :] >= 0) \
                    & (abs_pos[None, :] <= qpos[:, None]) \
                    & (abs_pos[None, :] > qpos[:, None]
                       - cfg.attention_window)
            else:
                kpos = jnp.arange(max_len)
                visible = kpos[None, :] <= qpos[:, None]
                if cfg.attention_window is not None:
                    visible &= kpos[None, :] > qpos[:, None] \
                        - cfg.attention_window
            scores = jnp.where(visible[None, None, None],
                               scores.astype(jnp.float32), -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
            attn = jnp.einsum("bngqk,bnkd->bngqd", probs, vc).reshape(
                1, cfg.n_heads, s, hd)
            attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
            x = x + jnp.einsum("bsd,de->bse", attn,
                               layer["attn_out"].astype(cfg.dtype))
            y = _rmsnorm(x, layer["ln2"])
            from tpu_autoscaler.workloads.model import _ffn_residual

            return _ffn_residual(x, y, layer, cfg), (k_slot, v_slot)

        x, (k_new, v_new) = jax.lax.scan(
            body, x, (params["blocks"], cache.k, cache.v))
        x = _rmsnorm(x, params["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", x,
                            params["unembed"].astype(cfg.dtype))
        last = jax.lax.dynamic_index_in_dim(
            logits[0], n_valid - 1, axis=0, keepdims=False)
        lengths = cache.lengths.at[slot].add(n_valid)
        return last.astype(jnp.float32), SlotKVCache(
            k=k_new, v=v_new, lengths=lengths)

    if mesh is None:
        return jax.jit(fill)
    # Pin the SAME cache/param layouts as the decode step: without
    # out_shardings, XLA's propagation would hand the decode step a
    # cache committed to whatever layout the prefill computation chose,
    # and its in_shardings would reject it.
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_autoscaler.workloads.model import data_axes, param_specs

    daxes = data_axes(mesh)
    tp_ok = "model" in mesh.axis_names
    kv = P(None, daxes, "model" if tp_ok else None, None, None)
    cache_shard = SlotKVCache(
        k=NamedSharding(mesh, kv), v=NamedSharding(mesh, kv),
        lengths=NamedSharding(mesh, P(daxes)))
    p_shard = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P))
    replicated = NamedSharding(mesh, P())
    return jax.jit(fill,
                   in_shardings=(p_shard, cache_shard, replicated,
                                 replicated, replicated),
                   out_shardings=(replicated, cache_shard))


@dataclasses.dataclass
class Request:
    """One generation request for the engine.  Sampling knobs are
    PER-REQUEST (each slot samples its own row of the batched logits
    host-side, so mixed greedy/sampled traffic batches together)."""

    prompt: np.ndarray                   # [len] int32
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    eos_id: int | None = None
    # Filled by the engine:
    generated: list = dataclasses.field(default_factory=list)
    done: bool = False
    # Engine ticks at submission/completion (stats: latency in ticks —
    # submitted_tick is preserved across preemption re-queues, so a
    # preempted request's latency counts from its ORIGINAL submit).
    submitted_tick: int | None = None
    finished_tick: int | None = None
    # Queue-wait/execute split (ISSUE 14): first tick the request held
    # a slot, and the tick of its last preemption — the engine's
    # admission path feeds both into the stats recorder's wait
    # counters and the request-trace sampler.
    request_id: str | None = None
    first_scheduled_tick: int | None = None
    preempted_tick: int | None = None


@dataclasses.dataclass
class _SlotState:
    request: Request | None = None
    remaining_prompt: np.ndarray | None = None
    seeded: bool = False                 # last-chunk logits sampled?


class ContinuousBatcher:
    """Host-side scheduler over the compiled slot programs.

    Admission: a FREE slot takes the next queued request and prefills
    its prompt one chunk per tick.  Every tick also runs ONE batched
    decode step for all slots holding live generations.  Eviction: a
    sequence that hits max_new_tokens (or eos) frees its slot on the
    spot — the next request is admitted the same tick.  Shapes never
    change; slot occupancy is pure data.

    This is deliberately simple single-thread scheduling (tick =
    [maybe one prefill chunk] + [one decode step]); the point is the
    compiled-program inventory and the slot-cache semantics that make
    real schedulers possible.
    """

    def __init__(self, params, cfg: ModelConfig, *, slots: int = 4,
                 max_len: int = 256, chunk: int = 32, mesh=None,
                 key=None, ring: bool = False,
                 slo_ticks: int | None = None, reqtrace=None):
        """``ring=True`` (needs cfg.attention_window): per-slot cache
        HBM becomes O(window + chunk) instead of O(max_len), and
        sequences may run PAST max_len — max_len then only bounds the
        per-request budget check, not the buffer.

        ``slo_ticks``: completions within this many engine ticks of
        submission count as SLO-attained in ``stats()`` (None = no
        target).

        ``reqtrace``: an optional
        :class:`~tpu_autoscaler.serving.reqtrace.RequestTraceSampler`
        — sampled per-request span trees built from the host-side
        bookkeeping this scheduler already does (submit/admit/seeded/
        preempt/finish); None costs one ``if`` per event."""
        if mesh is not None:
            # Re-place the params onto THIS mesh's TP layout: restored
            # checkpoints arrive committed to the shardings they were
            # saved under, and jit rejects committed args whose
            # sharding differs from the step's in_shardings.
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from tpu_autoscaler.workloads.model import param_specs

            p_shard = jax.tree.map(
                lambda spec: NamedSharding(mesh, spec),
                param_specs(cfg.resolved_for_mesh(mesh)),
                is_leaf=lambda x: isinstance(x, P))
            params = jax.device_put(params, p_shard)
        self.params = params
        self.cfg = cfg
        self.chunk = chunk
        self.max_len = max_len
        self.ring = ring
        self._build_device_state(cfg, slots, max_len, chunk, mesh, ring)
        self._slots = [_SlotState() for _ in range(slots)]
        self._queue: list[Request] = []
        self._pending_token = np.zeros((slots,), np.int32)
        self._has_pending = np.zeros((slots,), bool)
        self._key = key if key is not None else jax.random.PRNGKey(0)
        self.ticks = 0
        self.decode_tokens = 0
        # Signal export (ISSUE 9, serving/stats.py): fixed numpy rings
        # written from the host-side bookkeeping this scheduler already
        # does — the decode path never pays a device sync for it.
        # _stat_lengths mirrors cache.lengths host-side (admission
        # resets it, prefill/decode advance it) so KV occupancy never
        # reads a jax.Array.
        self._stats = ServingStatsRecorder(slots, slo_ticks=slo_ticks)
        self._stat_lengths = np.zeros(slots, np.int64)
        # Request-trace sampler (ISSUE 14): wired to this recorder so
        # promotion counters and exemplars ride the snapshot export.
        self._reqtrace = reqtrace
        if reqtrace is not None and reqtrace.stats is None:
            reqtrace.stats = self._stats
        self._rid_seq = 0

        # Device-side batched sampling (the hot path): greedy rows take
        # argmax, temperature rows sample categorically at their own
        # temperature — only the [slots] token ids cross to host, not
        # the [slots, vocab] logits.  Rows with top_k/top_p fall back
        # to the host sampler (per-row truncation needs data-dependent
        # shapes the batched path cannot express).
        def _batch_sample(logits, key, temps, greedy):
            scaled = logits / jnp.where(greedy, 1.0, temps)[:, None]
            keys = jax.random.split(key, logits.shape[0])
            sampled = jax.vmap(
                lambda k, row: jax.random.categorical(k, row))(
                    keys, scaled)
            return jnp.where(greedy, jnp.argmax(logits, axis=-1),
                             sampled).astype(jnp.int32)

        self._batch_sample = jax.jit(_batch_sample)

    def _build_device_state(self, cfg, slots, max_len, chunk, mesh,
                            ring) -> None:
        """Allocate the cache and build the compiled step inventory.
        Subclasses with a different memory system (paged.PagedBatcher)
        override this — the host-side scheduling above is shared."""
        if ring:
            if cfg.attention_window is None:
                raise ValueError("ring=True needs cfg.attention_window")
            buf_len = cfg.attention_window + chunk
        else:
            buf_len = max_len
        self.cache = SlotKVCache.zeros(
            cfg.resolved_for_mesh(mesh) if mesh is not None else cfg,
            slots, buf_len)
        self._decode = make_slot_decode_step(cfg, mesh, ring=ring)
        self._prefill = make_prefill_chunk(cfg, chunk, mesh, ring=ring)

    def submit(self, request: Request) -> None:
        """Queue a request, validating its cache footprint UP FRONT —
        the compiled steps run at traced lengths and cannot check
        bounds; an oversized request would silently clamp
        dynamic_update_slice writes and corrupt live cache."""
        plen = len(request.prompt)
        if plen < 1:
            raise ValueError("empty prompt (the engine seeds generation "
                             "from the prompt's last logits)")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got "
                f"{request.max_new_tokens}")
        if request.temperature == 0.0 and (
                request.top_k is not None or request.top_p is not None):
            raise ValueError(
                "top_k/top_p require temperature > 0 (temperature 0 is "
                "greedy argmax; truncation would be silently ignored)")
        if request.top_p is not None and not 0.0 < request.top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {request.top_p}")
        if request.top_k is not None and request.top_k < 1:
            raise ValueError(
                f"top_k must be >= 1, got {request.top_k}")
        # Prefill writes chunk-wide blocks: the last chunk's write must
        # fit below max_len even though only n_valid entries are real.
        padded = int(np.ceil(plen / self.chunk) * self.chunk)
        need = max(padded, plen + request.max_new_tokens)
        if need > self.max_len:
            raise ValueError(
                f"request needs {need} cache slots (prompt {plen} "
                f"padded to chunk {self.chunk} multiples, + "
                f"{request.max_new_tokens} new tokens) but max_len is "
                f"{self.max_len}")
        if request.submitted_tick is None:
            request.submitted_tick = self.ticks
        if request.request_id is None:
            self._rid_seq += 1
            request.request_id = f"r{self._rid_seq}"
        if self._reqtrace is not None:
            self._reqtrace.note_submit(request.request_id, self.ticks)
        self._queue.append(request)

    @property
    def idle(self) -> bool:
        return not self._queue and all(
            s.request is None for s in self._slots)

    def _note_admitted(self, req: Request) -> None:
        """Wait-split + trace bookkeeping for one admission (shared by
        every engine variant's ``_admit``): the FIRST admission closes
        the submit→schedule wait, a re-admission closes a preemption
        requeue wait — the split satellite's attribution point."""
        if req.first_scheduled_tick is None:
            req.first_scheduled_tick = self.ticks
            self._stats.note_first_scheduled(
                self.ticks - (req.submitted_tick or 0))
        elif req.preempted_tick is not None:
            self._stats.note_requeue_wait(
                self.ticks - req.preempted_tick)
        if self._reqtrace is not None and req.request_id is not None:
            self._reqtrace.note_admit(req.request_id, self.ticks)

    def _note_seeded(self, req: Request) -> None:
        if self._reqtrace is not None and req.request_id is not None:
            self._reqtrace.note_seeded(req.request_id, self.ticks)

    def _trace_finish_attrs(self, req: Request) -> dict:
        """Extra root-span attrs for a finished request's trace (the
        speculative engine annotates accept economics here)."""
        del req
        return {}

    def _admit(self) -> None:
        if getattr(self, "draining", False):
            return
        for i, slot in enumerate(self._slots):
            if slot.request is None and self._queue:
                req = self._queue.pop(0)
                slot.request = req
                slot.remaining_prompt = np.asarray(req.prompt, np.int32)
                slot.seeded = False
                self._has_pending[i] = False
                self._stats.note_admit()
                self._note_admitted(req)
                self._stat_lengths[i] = 0
                # Reset the slot: stale cache beyond every future write
                # point is invisible by construction.
                self.cache = SlotKVCache(
                    k=self.cache.k, v=self.cache.v,
                    lengths=self.cache.lengths.at[i].set(0))

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _sample_host(self, logits, req: Request):
        tok = _sample(logits, self._next_key(), req.temperature,
                      req.top_k, req.top_p)
        return int(np.asarray(tok))

    def _finish_if_done(self, i: int) -> None:
        slot = self._slots[i]
        req = slot.request
        if req is None:
            return
        if len(req.generated) >= req.max_new_tokens or (
                req.eos_id is not None and req.generated
                and req.generated[-1] == req.eos_id):
            req.done = True
            req.finished_tick = self.ticks
            slot.request = None
            slot.remaining_prompt = None
            self._has_pending[i] = False
            # The DEVICE keeps the stale cache until readmission (by
            # design), but the exported KV signal tracks LIVE
            # sequences — a freed slot stops counting now, or an idle
            # engine would report its historical peak forever.
            self._stat_lengths[i] = 0
            self._stats.note_finish(
                self.ticks - (req.submitted_tick or 0))
            if self._reqtrace is not None \
                    and req.request_id is not None:
                self._reqtrace.note_finish(
                    req.request_id, self.ticks,
                    tokens=len(req.generated),
                    attrs=self._trace_finish_attrs(req) or None)

    def _kv_usage(self) -> tuple[int, int]:
        """(live KV token-slots, capacity), host-side only.  Ring
        caches hold at most the buffer width per slot regardless of
        logical length; PagedBatcher overrides with pool-block
        accounting."""
        width = self.cache.max_len
        used = int(np.minimum(self._stat_lengths, width).sum())
        return used, self._stat_lengths.size * width

    def stats(self) -> ServingSnapshot:
        """O(1) export of this engine's serving signals (ISSUE 9):
        queue depth, admissions/preemptions, token throughput, KV
        occupancy, per-request SLO attainment — the autoscaler's
        metrics-adapter feed (serving/adapter.py)."""
        return self._stats.snapshot()

    def tick(self) -> None:
        """One engine step (then close the stats tick — every engine
        variant's ``_tick`` runs under this wrapper, so export never
        depends on which scheduler loop ran)."""
        self._tick()
        used, cap = self._kv_usage()
        self._stats.end_tick(
            queue_depth=len(self._queue),
            active=sum(1 for s in self._slots
                       if s.request is not None),
            kv_used=used, kv_capacity=cap,
            decode_tokens_total=self.decode_tokens)

    def _tick(self) -> None:
        """One engine step: admit, at most one prefill chunk, then one
        batched decode step for every slot with a pending token."""
        self._admit()
        self.ticks += 1

        # Chunked prefill: the first slot still holding prompt gets one
        # chunk this tick (bounded head-of-line cost for decoders).
        for i, slot in enumerate(self._slots):
            if slot.request is None or slot.remaining_prompt is None \
                    or len(slot.remaining_prompt) == 0:
                continue
            take = min(self.chunk, len(slot.remaining_prompt))
            buf = np.zeros((self.chunk,), np.int32)
            buf[:take] = slot.remaining_prompt[:take]
            slot.remaining_prompt = slot.remaining_prompt[take:]
            logits, self.cache = self._prefill(
                self.params, self.cache, jnp.int32(i), jnp.asarray(buf),
                jnp.int32(take))
            self._stat_lengths[i] += take
            if len(slot.remaining_prompt) == 0:
                # Prompt complete: sample the first generated token.
                tok = self._sample_host(np.asarray(logits), slot.request)
                slot.request.generated.append(tok)
                slot.seeded = True
                self._note_seeded(slot.request)
                self._pending_token[i] = tok
                self._has_pending[i] = True
                self._finish_if_done(i)
            break

        if not self._has_pending.any():
            return

        # Batched decode over every live slot.  Slots without a pending
        # token run masked garbage; the active mask keeps their lengths
        # from advancing ON DEVICE (no host round-trip on the hot
        # path), so their garbage K/V is overwritten by the next real
        # write.
        logits, self.cache = self._decode(
            self.params, self.cache, jnp.asarray(self._pending_token),
            jnp.asarray(self._has_pending))
        self._stat_lengths[self._has_pending] += 1
        # Sample ON DEVICE for rows without truncation knobs; only the
        # [slots] token ids come back to host (EOS checks/output need
        # them anyway).  Truncated rows re-sample their own logits row
        # host-side.
        temps = np.array(
            [s.request.temperature if s.request else 0.0
             for s in self._slots], np.float32)
        greedy = temps == 0.0
        toks = np.asarray(self._batch_sample(
            logits, self._next_key(), jnp.asarray(temps),
            jnp.asarray(greedy)))
        for i, slot in enumerate(self._slots):
            if not self._has_pending[i] or slot.request is None:
                continue
            self.decode_tokens += 1
            req = slot.request
            if req.top_k is not None or req.top_p is not None:
                tok = self._sample_host(np.asarray(logits[i]), req)
            else:
                tok = int(toks[i])
            req.generated.append(tok)
            self._pending_token[i] = tok
            self._finish_if_done(i)

    def run(self, max_ticks: int = 10_000, watcher=None) -> None:
        """Drive until every submitted request completes.

        ``watcher`` (a checkpoint.DrainWatcher): when the autoscaler
        requests the slice back mid-run, stop ADMITTING queued requests
        but finish every in-flight sequence — serving's half of the
        drain contract (there is no state to checkpoint; bounded
        completion inside the drain window is the whole obligation).
        Unserved requests stay queued with done=False for the caller
        to re-dispatch."""
        self.draining = False
        for _ in range(max_ticks):
            if watcher is not None and not self.draining \
                    and watcher.drain_requested():
                self.draining = True
            if self.draining and all(
                    s.request is None for s in self._slots):
                self._note_drain_handoff()
                return
            if self.idle:
                return
            self.tick()
        raise RuntimeError(f"engine did not drain in {max_ticks} ticks")

    def _note_drain_handoff(self) -> None:
        """Drain exit with requests still queued: each one's trace (if
        sampled) closes with a ``drain_handoff`` span — a lost request
        is always tail-captured, whatever the head sampling said."""
        if self._reqtrace is None:
            return
        for req in self._queue:
            if req.request_id is not None:
                self._reqtrace.note_drain_lost(req.request_id,
                                               self.ticks)
