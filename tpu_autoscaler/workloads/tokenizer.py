"""Byte-level BPE tokenizer: the real-text data path for the trainer.

dataio.py serves uint32 token shards; until round 5 the only in-repo
shard was a vocab-256 synthetic bigram stream, so convergence evidence
proved plumbing, not learning at realistic token statistics (VERDICT r4
item 8).  This module closes that: a byte-level BPE (GPT-2 family
lineage: every byte is a base token, so ANY input encodes — no OOV, no
normalization table) trained in pure Python/numpy, a committed corpus
(data/corpus.txt — this repo's own docs + source, ~1.2 MB of mixed
prose/code), and a CLI that writes tokenizer.json plus a
loader-compatible uint32 shard.

Training is the textbook greedy loop — repeatedly merge the most
frequent adjacent pair — vectorized so each merge is a handful of numpy
passes over the (shrinking) corpus instead of a Python scan: pair
counting packs (left, right) into one uint64 key for np.unique;
merging writes the new id at each match site and deletes the right
element, with a small Python pass only to drop overlapping matches of
self-pairs (aaa → (aa)a, not a(aa)).

Encoding arbitrary NEW text replays the merges in rank order on the
text's byte array (same numpy kernel); decode expands ids through the
vocab table back to bytes.  Round-trip is exact by construction and
pinned in tests/test_tokenizer.py.
"""

from __future__ import annotations

import json

import numpy as np

#: Base alphabet: every byte value is a token, so encoding never fails.
N_BYTES = 256


def _pair_counts(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(pairs [n, 2], counts [n]) of adjacent pairs, via one uint64 key."""
    if len(arr) < 2:
        return np.empty((0, 2), np.uint32), np.empty((0,), np.int64)
    keys = (arr[:-1].astype(np.uint64) << np.uint64(32)) \
        | arr[1:].astype(np.uint64)
    uniq, counts = np.unique(keys, return_counts=True)
    pairs = np.stack([(uniq >> np.uint64(32)).astype(np.uint32),
                      (uniq & np.uint64(0xFFFFFFFF)).astype(np.uint32)],
                     axis=1)
    return pairs, counts


def _merge_pair(arr: np.ndarray, a: int, b: int,
                new_id: int) -> np.ndarray:
    """Replace every non-overlapping (a, b) occurrence with new_id."""
    m = (arr[:-1] == a) & (arr[1:] == b)
    idx = np.nonzero(m)[0]
    if len(idx) == 0:
        return arr
    if a == b:
        # Greedy left-to-right: a run "aaa" merges its FIRST pair only.
        keep, last = [], -2
        for i in idx:
            if i == last + 1:
                continue
            keep.append(i)
            last = i
        idx = np.asarray(keep, idx.dtype)
    arr = arr.copy()
    arr[idx] = new_id
    return np.delete(arr, idx + 1)


class ByteBPE:
    """merges: list of (left_id, right_id); merge i creates id 256+i."""

    def __init__(self, merges: list[tuple[int, int]]):
        self.merges = [tuple(m) for m in merges]
        # The vocab_size train() was ASKED for — may exceed the actual
        # vocab when training stopped early (min_count).  Persisted in
        # tokenizer.json so build_shard's cache check can recognize an
        # early-stopped tokenizer instead of silently re-training on
        # every invocation (ADVICE r5 #2).
        self.requested_vocab_size: int | None = None
        # id -> bytes expansion table.
        table: list[bytes] = [bytes([i]) for i in range(N_BYTES)]
        for a, b in self.merges:
            table.append(table[a] + table[b])
        self._table = table

    @property
    def vocab_size(self) -> int:
        return N_BYTES + len(self.merges)

    # ---- training ------------------------------------------------------

    @classmethod
    def train(cls, data: bytes, vocab_size: int,
              min_count: int = 2) -> "ByteBPE":
        """Greedy BPE to ``vocab_size`` (stops early when no pair
        repeats ``min_count`` times — merging singletons memorizes the
        corpus instead of compressing it)."""
        if vocab_size < N_BYTES:
            raise ValueError(
                f"vocab_size must be >= {N_BYTES}, got {vocab_size}")
        arr = np.frombuffer(data, np.uint8).astype(np.uint32)
        merges: list[tuple[int, int]] = []
        while N_BYTES + len(merges) < vocab_size:
            pairs, counts = _pair_counts(arr)
            if len(counts) == 0 or counts.max() < min_count:
                break
            a, b = pairs[int(np.argmax(counts))]
            new_id = N_BYTES + len(merges)
            merges.append((int(a), int(b)))
            arr = _merge_pair(arr, int(a), int(b), new_id)
        bpe = cls(merges)
        bpe.requested_vocab_size = vocab_size
        return bpe

    # ---- encode / decode ----------------------------------------------

    def encode(self, data: bytes | str) -> np.ndarray:
        """Encode bytes/str -> uint32 ids (merges replayed in rank
        order — the canonical BPE encode)."""
        if isinstance(data, str):
            data = data.encode("utf-8")
        arr = np.frombuffer(data, np.uint8).astype(np.uint32)
        for rank, (a, b) in enumerate(self.merges):
            if len(arr) < 2:
                break
            arr = _merge_pair(arr, a, b, N_BYTES + rank)
        return arr

    def decode(self, ids) -> bytes:
        return b"".join(self._table[int(i)] for i in np.asarray(ids))

    def decode_str(self, ids) -> str:
        return self.decode(ids).decode("utf-8", errors="replace")

    # ---- persistence ---------------------------------------------------

    def save(self, path: str) -> None:
        obj = {"format": "byte-bpe-v1",
               "vocab_size": self.vocab_size,
               "merges": [list(m) for m in self.merges]}
        if self.requested_vocab_size is not None:
            obj["requested_vocab_size"] = self.requested_vocab_size
        with open(path, "w") as f:
            json.dump(obj, f)

    @classmethod
    def load(cls, path: str) -> "ByteBPE":
        with open(path) as f:
            obj = json.load(f)
        if obj.get("format") != "byte-bpe-v1":
            raise ValueError(f"{path}: not a byte-bpe-v1 tokenizer file")
        bpe = cls([tuple(m) for m in obj["merges"]])
        bpe.requested_vocab_size = obj.get("requested_vocab_size")
        return bpe


def build_shard(corpus_path: str, tokenizer_path: str, shard_path: str,
                vocab_size: int = 8192) -> tuple[ByteBPE, np.ndarray]:
    """Train (or reuse) a tokenizer on the corpus and write the encoded
    corpus as a dataio-compatible uint32 shard.  Reuses an existing
    tokenizer.json if its vocab matches (training is the slow step)."""
    import os

    from tpu_autoscaler.dataio import write_token_file

    with open(corpus_path, "rb") as f:
        data = f.read()
    bpe = None
    if os.path.exists(tokenizer_path):
        try:
            cached = ByteBPE.load(tokenizer_path)
            # Match on the REQUESTED vocab when recorded: an
            # early-stopped (min_count) tokenizer's actual vocab never
            # equals the request, and without this it re-trained —
            # silently, slowly — on every invocation (ADVICE r5 #2).
            # Files predating the field keep the actual-vocab check.
            if vocab_size in (cached.requested_vocab_size,
                              cached.vocab_size):
                bpe = cached
        except (ValueError, KeyError, json.JSONDecodeError):
            bpe = None
    if bpe is None:
        bpe = ByteBPE.train(data, vocab_size)
        bpe.save(tokenizer_path)
    ids = bpe.encode(data)
    write_token_file(shard_path, ids.astype(np.uint32))
    return bpe, ids


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="Train a byte-level BPE and shard a corpus for the "
                    "trainer (--data-file).")
    p.add_argument("--corpus", default="data/corpus.txt")
    p.add_argument("--vocab", type=int, default=8192)
    p.add_argument("--tokenizer-out", default="data/tokenizer.json")
    p.add_argument("--shard-out", default="data/corpus.bin")
    args = p.parse_args(argv)
    import os

    bpe, ids = build_shard(args.corpus, args.tokenizer_out,
                           args.shard_out, args.vocab)
    ratio = os.path.getsize(args.corpus) / max(1, len(ids))
    print(f"tokenizer: vocab {bpe.vocab_size} -> {args.tokenizer_out}\n"
          f"shard: {len(ids)} tokens ({ratio:.2f} bytes/token) -> "
          f"{args.shard_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
