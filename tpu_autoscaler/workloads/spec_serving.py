"""Speculative decoding INSIDE the continuous-batching paged engine.

decode.py's speculative generators serve one request (a batch shares
one cache length, so mixed accept lengths truncate to the batch
minimum).  The paged slot engine removes that limit: its cache keeps a
length PER SLOT, so each sequence can accept a different number of
draft tokens every round — the draft-assisted serving design (vLLM /
SpecInfer lineage) with zero shape dynamism:

- the DRAFT model holds a mirrored paged cache (own pool/tables/
  allocator, same slot structure); every engine tick it proposes up to
  ``k`` tokens per active slot in k batched decode steps;
- the TARGET scores every slot's ``[pending, d1..dk]`` block in ONE
  multi-token program (make_paged_prefill with return_all_logits —
  the verification primitive), writing the block into the cache as it
  scores;
- acceptance runs per slot on host (greedy: argmax match, the output
  is exactly the target's greedy stream; sampled: the standard
  min(1, p/q) accept + residual resample, both distributions warped
  by the request's temperature/top-k/top-p);
- the cache "rewind" is free: per-slot lengths simply advance by the
  emitted count — rejected draft writes beyond the new length are
  overwritten by later writes before they can ever become visible
  (the same invariant every engine in this tree relies on), and the
  draft cache replays its one missing token on full acceptance.

Per round a slot emits between 1 and k+1 tokens for ONE target pass —
decode is bound by the target's weight/cache reads, so serving
throughput at scale improves by the mean accepted length.  The engine
reports ``target_pass_ratio`` (verify passes / decoded tokens; plain
decode is 1.0).

Greedy parity with the plain paged engine is pinned token-for-token in
tests/test_spec_serving.py; the accept math mirrors
decode.py::speculative_sample_generate, whose marginal-distribution
exactness tests pin the construction itself.
"""

from __future__ import annotations

import numpy as np

try:  # pragma: no cover - import guard mirrors workloads siblings
    import jax
    import jax.numpy as jnp
except ImportError:  # pragma: no cover
    jax = None  # type: ignore[assignment]

from tpu_autoscaler.workloads.model import ModelConfig
from tpu_autoscaler.workloads.paged import (
    BlockAllocator,
    PagedBatcher,
    PagedKVCache,
    Request,
    make_paged_decode_step,
    make_paged_prefill,
)

__all__ = ["SpeculativePagedBatcher", "Request"]


def _np_warp(logits: np.ndarray, temperature: float, top_k, top_p):
    """numpy twin of decode._warp_logits (host-side accept math must
    use the SAME warping the device samplers use)."""
    scaled = logits.astype(np.float64) / temperature
    if top_k is not None:
        kth = np.partition(scaled, -top_k)[-top_k]
        scaled = np.where(scaled < kth, -np.inf, scaled)
    if top_p is not None:
        order = np.argsort(scaled)[::-1]
        sorted_l = scaled[order]
        exp = np.exp(sorted_l - sorted_l[0])
        probs = exp / exp.sum()
        cum = np.cumsum(probs)
        keep = (cum - probs) < top_p
        cutoff = sorted_l[np.sum(keep) - 1]
        scaled = np.where(scaled < cutoff, -np.inf, scaled)
    return scaled


def _np_probs(logits: np.ndarray, temperature: float, top_k, top_p):
    warped = _np_warp(logits, temperature, top_k, top_p)
    warped = warped - warped.max()
    e = np.exp(warped)
    return e / e.sum()


class SpeculativePagedBatcher(PagedBatcher):
    """PagedBatcher whose decode phase is draft-propose / target-verify.

    ``draft_params``/``draft_cfg``: the cheap proposer (same vocab;
    typically fewer layers).  ``k``: draft tokens per round (capped
    per slot by its remaining budget, so the last round degenerates to
    a plain decode step and cache bounds are never exceeded; must be
    < chunk so the block-accounting slack still covers the verify
    look-ahead).
    """

    def __init__(self, params, cfg: ModelConfig, draft_params,
                 draft_cfg: ModelConfig | None = None, *, k: int = 4,
                 slots: int = 4, max_len: int = 256,
                 block_size: int = 16, num_blocks: int | None = None,
                 chunk: int = 32, prefill_lanes: int = 2, mesh=None,
                 key=None, seed: int = 0, slo_ticks: int | None = None,
                 reqtrace=None):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if k >= chunk:
            raise ValueError(
                f"k ({k}) must be < chunk ({chunk}): the accounting "
                "slack and the draft replay program are chunk-sized")
        self.k = k
        self.draft_cfg = draft_cfg if draft_cfg is not None else cfg
        if self.draft_cfg.vocab != cfg.vocab:
            raise ValueError(
                f"draft vocab {self.draft_cfg.vocab} != target vocab "
                f"{cfg.vocab}")
        self._draft_params_in = draft_params
        self._spec_rng = np.random.default_rng(seed)
        self.verify_passes = 0
        self.drafted_tokens = 0
        self.accepted_tokens = 0
        super().__init__(params, cfg, slots=slots, max_len=max_len,
                         block_size=block_size, num_blocks=num_blocks,
                         chunk=chunk, prefill_lanes=prefill_lanes,
                         mesh=mesh, key=key, slo_ticks=slo_ticks,
                         reqtrace=reqtrace)

    def _trace_finish_attrs(self, req) -> dict:
        """Speculative economics on the request's root span: the
        engine-wide accept rate / pass ratio as of this completion —
        the decode span already carries its batched tick count, so a
        slow-decode tail can be told apart from a cold draft."""
        return {"accept_rate": round(self.accept_rate, 4),
                "target_pass_ratio": round(self.target_pass_ratio, 4)}

    # ---- device state ---------------------------------------------------

    def _build_device_state(self, cfg, slots, max_len, chunk, mesh,
                            ring) -> None:
        super()._build_device_state(cfg, slots, max_len, chunk, mesh,
                                    ring)
        dcfg = self.draft_cfg
        self.draft_params = self._draft_params_in
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from tpu_autoscaler.workloads.model import param_specs

            p_shard = jax.tree.map(
                lambda spec: NamedSharding(mesh, spec),
                param_specs(dcfg.resolved_for_mesh(mesh)),
                is_leaf=lambda x: isinstance(x, P))
            self.draft_params = jax.device_put(self._draft_params_in,
                                               p_shard)
        self.d_allocator = BlockAllocator(self._num_blocks)
        self.d_tables = np.full((slots, self.blocks_per_row), -1,
                                np.int32)
        run_dcfg = dcfg.resolved_for_mesh(mesh) if mesh is not None \
            else dcfg
        pool = PagedKVCache.zeros(run_dcfg, self._num_blocks,
                                  self.block_size)
        self.d_cache = PagedKVCache(
            k=pool.k, v=pool.v, lengths=jnp.zeros((slots,), jnp.int32))
        self._d_decode = make_paged_decode_step(dcfg, max_len, mesh)
        self._d_prefill = make_paged_prefill(dcfg, chunk,
                                             self.prefill_lanes,
                                             max_len, mesh)
        # Draft replay: per-slot short appends after full acceptance.
        self._d_replay = make_paged_prefill(dcfg, chunk, slots, max_len,
                                            mesh)
        self._verify = make_paged_prefill(cfg, self.k + 1, slots,
                                          max_len, mesh,
                                          return_all_logits=True)

    # ---- draft block management ----------------------------------------

    def _d_ensure_blocks(self, i: int, upto_tokens: int) -> bool:
        need = int(np.ceil(upto_tokens / self.block_size))
        row = self.d_tables[i]
        have = int((row >= 0).sum())
        while have < need:
            b = self.d_allocator.alloc()
            if b is None:
                return False
            row[have] = b
            have += 1
        return True

    def _release_slot(self, i: int) -> None:
        super()._release_slot(i)
        self.d_allocator.free(self.d_tables[i][self.d_tables[i] >= 0])
        self.d_tables[i] = -1
        self.d_cache = PagedKVCache(
            k=self.d_cache.k, v=self.d_cache.v,
            lengths=self.d_cache.lengths.at[i].set(0))

    def _kv_usage(self) -> tuple[int, int]:
        """Target pool plus the mirrored draft pool: both are real HBM
        pressure the autoscaler's KV-occupancy signal should see."""
        t_used, t_cap = super()._kv_usage()
        return (t_used + self.d_allocator.used_blocks * self.block_size,
                t_cap + self.d_allocator.num_blocks * self.block_size)

    def check_accounting(self) -> None:
        super().check_accounting()
        live = self.live_tokens()
        used = self.d_allocator.used_blocks * self.block_size
        live_seqs = sum(1 for s in self._slots if s.request is not None)
        slack = live_seqs * (self.block_size + self.chunk)
        assert used <= live + slack, (
            f"draft paged accounting violated: {used} for {live} live "
            f"(+{slack})")

    # ---- prefill mirror -------------------------------------------------

    def _after_prefill(self, served: list) -> None:
        """Replay the target's prefill chunks into the draft cache (the
        draft must hold the same prefix to propose from), BEFORE
        completion checks can release the slots."""
        live = [(i, buf, take, off) for i, buf, take, off in served
                if self._slots[i].request is not None]
        for i, _, _, off in live:
            d_len = int(np.asarray(self.d_cache.lengths[i]))
            assert d_len == off, (
                f"draft cache desynced on slot {i}: {d_len} != {off}")
        ok_lanes = []
        for i, buf, take, off in live:
            while not self._d_ensure_blocks(i, off + take):
                if not self._preempt_youngest():
                    break
                if self._slots[i].request is None:
                    break
            if self._slots[i].request is None:
                continue
            if self._d_ensure_blocks(i, off + take):
                ok_lanes.append((i, buf, take, off))
            else:
                # The target got its chunk but the draft can't: the
                # caches would desync — evict the slot back to the
                # queue (a fresh prefill re-enters both together).
                self._preempt_slot(i)
        # A LATER lane's pressure may have preempted an EARLIER
        # collected lane (the base _prefill_phase re-filters for the
        # same hazard): advancing a freed slot's draft length would
        # desync its next occupant.
        ok_lanes = [(i, buf, take, off) for i, buf, take, off in ok_lanes
                    if self._slots[i].request is not None]
        if ok_lanes:
            tok = np.zeros((self.prefill_lanes, self.chunk), np.int32)
            offs = np.zeros((self.prefill_lanes,), np.int32)
            nval = np.zeros((self.prefill_lanes,), np.int32)
            tabs = np.zeros((self.prefill_lanes, self.blocks_per_row),
                            np.int32) - 1
            for lane, (i, buf, take, off) in enumerate(ok_lanes):
                tok[lane] = buf
                offs[lane] = off
                nval[lane] = take
                tabs[lane] = self.d_tables[i]
            _, self.d_cache = self._d_prefill(
                self.draft_params, self.d_cache, jnp.asarray(tabs),
                jnp.asarray(tok), jnp.asarray(offs), jnp.asarray(nval))
            new_lengths = self.d_cache.lengths
            for i, _, take, _ in ok_lanes:
                new_lengths = new_lengths.at[i].add(take)
            self.d_cache = PagedKVCache(
                k=self.d_cache.k, v=self.d_cache.v, lengths=new_lengths)
        self._prefill_finish(served)

    # ---- the speculative decode phase ----------------------------------

    def _decode_phase(self) -> None:
        n_slots = len(self._slots)
        k = self.k
        # Per-slot draft budget: never overrun the request's remaining
        # token budget (k_eff=0 degenerates to a plain decode step).
        k_eff = np.zeros((n_slots,), np.int32)
        for i, slot in enumerate(self._slots):
            if not self._has_pending[i] or slot.request is None:
                continue
            remaining = slot.request.max_new_tokens - len(
                slot.request.generated)
            k_eff[i] = max(0, min(k, remaining - 1))

        # Block reservations: target writes k_eff+1, draft k_eff.
        lengths = np.asarray(self.cache.lengths)
        d_lengths = np.asarray(self.d_cache.lengths)
        for i, slot in enumerate(self._slots):
            if not self._has_pending[i] or slot.request is None:
                continue
            # Draft coverage includes the +1 replay position: on full
            # acceptance _d_replay writes at d_len+k_eff, which may
            # start a new block — without the reservation that write
            # would silently drop (mode='drop') and the draft would
            # attend over garbage there forever after.
            while not (self._ensure_blocks(
                    i, int(lengths[i]) + int(k_eff[i]) + 1)
                    and self._d_ensure_blocks(
                        i, int(d_lengths[i]) + int(k_eff[i]) + 1)):
                if not self._preempt_youngest():
                    raise RuntimeError(
                        "paged pool exhausted with nothing to preempt")
                if self._slots[i].request is None:
                    break
        active = np.array([
            bool(self._has_pending[i])
            and self._slots[i].request is not None
            for i in range(n_slots)])
        if not active.any():
            return
        lengths = np.asarray(self.cache.lengths)
        d_lengths = np.asarray(self.d_cache.lengths)
        assert (d_lengths[active] == lengths[active]).all(), (
            "draft/target cache desync before verify")

        reqs = [s.request for s in self._slots]

        # ---- draft proposes up to k tokens per slot ----
        drafts = np.zeros((k, n_slots), np.int32)
        # Draft distributions are only needed for sampled rows'
        # accept ratios: allocate the [k, slots, vocab] buffer lazily
        # so pure-greedy traffic never pays it.
        any_sampled = any(
            active[i] and reqs[i].temperature != 0.0
            for i in range(n_slots))
        qs = (np.zeros((k, n_slots, self.cfg.vocab), np.float64)
              if any_sampled else
              np.zeros((k, n_slots, 0), np.float64))
        tok = self._pending_token.copy()
        for r in range(k):
            round_active = active & (r < k_eff)
            if not round_active.any():
                break
            dlogits, self.d_cache = self._d_decode(
                self.draft_params, self.d_cache,
                jnp.asarray(self.d_tables), jnp.asarray(tok),
                jnp.asarray(round_active))
            dl = np.asarray(dlogits)
            for i in range(n_slots):
                if not round_active[i]:
                    continue
                req = reqs[i]
                if req.temperature == 0.0:
                    tok[i] = int(np.argmax(dl[i]))
                else:
                    q = _np_probs(dl[i], req.temperature, req.top_k,
                                  req.top_p)
                    qs[r, i] = q
                    tok[i] = int(self._spec_rng.choice(len(q), p=q))
                drafts[r, i] = tok[i]
                self.drafted_tokens += 1

        # ---- one target pass scores [pending, d1..dk] per slot ----
        ver_tok = np.zeros((n_slots, k + 1), np.int32)
        ver_tok[:, 0] = self._pending_token
        ver_tok[:, 1:] = drafts.T
        nval = np.where(active, k_eff + 1, 0).astype(np.int32)
        vlogits, self.cache = self._verify(
            self.params, self.cache, jnp.asarray(self.tables),
            jnp.asarray(ver_tok), jnp.asarray(lengths),
            jnp.asarray(nval))
        T = np.asarray(vlogits)                    # [slots, k+1, vocab]
        self.verify_passes += 1

        # ---- per-slot accept / emit / advance ----
        new_lengths = self.cache.lengths
        new_d_lengths = self.d_cache.lengths
        replay: list[tuple[int, int, int]] = []    # (slot, token, offset)
        for i in range(n_slots):
            if not active[i]:
                continue
            req = reqs[i]
            ke = int(k_eff[i])
            emitted, n_acc = self._accept_row(T[i], drafts[:, i],
                                              qs[:, i], req, ke)
            # eos truncation: stop at the first eos emitted.
            if req.eos_id is not None:
                for j, t in enumerate(emitted):
                    if t == req.eos_id:
                        emitted = emitted[:j + 1]
                        break
            # Accepted-token accounting AFTER truncation: drafts past
            # the eos were never used, and counting them overstated
            # accept_rate for eos-terminating sequences (ADVICE r5 #4).
            self.accepted_tokens += min(n_acc, len(emitted))
            req.generated.extend(emitted)
            self.decode_tokens += len(emitted)
            m = len(emitted)
            # Cache advance: the pending token committed (+1) plus the
            # m-1 emitted tokens before the new pending — uniformly
            # len + m (see module docstring).
            new_lengths = new_lengths.at[i].set(int(lengths[i]) + m)
            self._pending_token[i] = emitted[-1]
            # Draft cache holds [pending, d1..d_{ke-1}] past its old
            # length: valid up to old+min(ke, m); the next pending
            # writes at old+m, so replay the gap (at most one token,
            # on full acceptance).
            d_new = int(d_lengths[i]) + min(ke, m)
            target_new = int(lengths[i]) + m
            if d_new > target_new:
                d_new = target_new
            new_d_lengths = new_d_lengths.at[i].set(d_new)
            if d_new < target_new:
                # Missing exactly one token: position len+m-1, whose
                # content is ver_tok[m-1] (the pending token when
                # k_eff=0, else the last accepted draft).
                assert target_new - d_new == 1
                replay.append((i, int(ver_tok[i, m - 1]), d_new))
        self.cache = PagedKVCache(
            k=self.cache.k, v=self.cache.v, lengths=new_lengths)
        self.d_cache = PagedKVCache(
            k=self.d_cache.k, v=self.d_cache.v, lengths=new_d_lengths)

        if replay:
            tokb = np.zeros((n_slots, self.chunk), np.int32)
            offs = np.zeros((n_slots,), np.int32)
            nvalr = np.zeros((n_slots,), np.int32)
            tabs = np.array(self.d_tables)
            for i, t, off in replay:
                tokb[i, 0] = t
                offs[i] = off
                nvalr[i] = 1
            _, self.d_cache = self._d_replay(
                self.draft_params, self.d_cache, jnp.asarray(tabs),
                jnp.asarray(tokb), jnp.asarray(offs),
                jnp.asarray(nvalr))
            new_d = self.d_cache.lengths
            for i, _, _ in replay:
                new_d = new_d.at[i].add(1)
            self.d_cache = PagedKVCache(
                k=self.d_cache.k, v=self.d_cache.v, lengths=new_d)

        for i in range(n_slots):
            if active[i]:
                self._finish_if_done(i)

    def _accept_row(self, T, drafts_i, qs_i, req, k_eff):
        """One slot's accept/emit decision.  T: [k+1, vocab] target
        logits (T[j] = next-token dist after pending, d1..dj);
        drafts_i: [k]; qs_i: [k, vocab] warped draft probs (sampled
        rows only).  Returns (emitted tokens, n_accepted)."""
        if req.temperature == 0.0:
            emitted = []
            for j in range(k_eff):
                t = int(np.argmax(T[j]))
                emitted.append(t)
                if t != int(drafts_i[j]):
                    return emitted, j
            emitted.append(int(np.argmax(T[k_eff])))
            return emitted, k_eff
        emitted = []
        for j in range(k_eff):
            p = _np_probs(T[j], req.temperature, req.top_k, req.top_p)
            d = int(drafts_i[j])
            q = qs_i[j]
            if self._spec_rng.uniform() * q[d] < p[d]:
                emitted.append(d)
                continue
            residual = np.maximum(p - q, 0.0)
            rs = residual.sum()
            # rs == 0 can only arise when acceptance was certain (p<=q
            # everywhere => p==q); the p fallback keeps choice() total.
            residual = residual / rs if rs > 0 else p
            emitted.append(int(self._spec_rng.choice(
                len(residual), p=residual)))
            return emitted, j
        p = _np_probs(T[k_eff], req.temperature, req.top_k, req.top_p)
        emitted.append(int(self._spec_rng.choice(len(p), p=p)))
        return emitted, k_eff

    @property
    def target_pass_ratio(self) -> float:
        """Target forward passes per decoded token (plain decode: 1.0;
        the speculative win at decode-bound scale)."""
        return self.verify_passes / max(1, self.decode_tokens)

    @property
    def accept_rate(self) -> float:
        return self.accepted_tokens / max(1, self.drafted_tokens)
