"""Autoregressive inference for the flagship model: KV cache + generate.

The training side (model.py) proves a provisioned slice trains; this is
the serving side of the same checkpoint — prefill + single-token decode
steps over a preallocated KV cache, the standard TPU inference shape:

- **Static shapes throughout**: the cache is preallocated at
  ``max_len`` and written with ``lax.dynamic_update_slice`` at a traced
  position, so one compiled decode step serves every position — no
  per-step recompilation, XLA-friendly by construction.
- **GQA pays off here**: the cache stores ``kv_heads`` heads, so an
  8:1 grouped layout cuts cache HBM (the decode-bandwidth bottleneck)
  by 8x relative to MHA.
- **RoPE at cache positions**: the new token's q/k rotate at absolute
  position ``cache.length`` (model._rope's offset arg), so decode
  logits bit-match teacher-forced forward() logits.
- **Sliding window as a mask**: the visibility mask bounds attention to
  the ``attention_window`` most recent cache entries; the cache itself
  stays linear (a ring buffer would shrink HBM to O(window) — noted as
  a further optimization, not needed at these sizes).
- ``generate`` runs decode under ``lax.scan`` (one compiled program for
  the whole rollout) with greedy or temperature/top-k sampling.

The reference has no model/inference code at all (SURVEY §3: it is an
infrastructure controller); this module is beyond-parity evidence that
slices the autoscaler provisions serve traffic, not just train.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from tpu_autoscaler.workloads.model import (
    ModelConfig,
    _rmsnorm,
    _rope,
    _split_qkv,
    moe_ffn,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Preallocated per-layer K/V cache.

    k, v: [layers, batch, kv_heads, max_len, head_dim] in compute dtype;
    length: scalar int32, number of filled positions (same for every
    sequence in the batch — left-aligned prompts; padding support would
    add a per-row length vector and mask term).
    """

    k: jax.Array
    v: jax.Array
    length: jax.Array

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    @classmethod
    def zeros(cls, cfg: ModelConfig, batch: int, max_len: int) -> "KVCache":
        shape = (cfg.n_layers, batch, cfg.kv_heads, max_len, cfg.head_dim)
        return cls(k=jnp.zeros(shape, cfg.dtype),
                   v=jnp.zeros(shape, cfg.dtype),
                   length=jnp.zeros((), jnp.int32))


def _cached_attention(q, k_cache, v_cache, length, cfg: ModelConfig):
    """Attend q [b, h, sq, hd] (positions length-sq .. length-1, already
    rotated) over the cache's first ``length`` entries with causal +
    window visibility.  Grouped-einsum GQA, f32 softmax."""
    b, h, sq, hd = q.shape
    hkv = k_cache.shape[1]
    max_len = k_cache.shape[2]
    qg = q.reshape(b, hkv, h // hkv, sq, hd)
    scores = jnp.einsum("bngqd,bnkd->bngqk", qg, k_cache) * hd ** -0.5
    # Visibility of cache slot j for the query at absolute position p
    # (p = length - sq + qi): j <= p, and with a window, j > p - window.
    kpos = jnp.arange(max_len)
    qpos = length - sq + jnp.arange(sq)
    visible = kpos[None, :] <= qpos[:, None]
    if cfg.attention_window is not None:
        visible &= kpos[None, :] > qpos[:, None] - cfg.attention_window
    scores = jnp.where(visible[None, None, None], scores.astype(jnp.float32),
                       -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
    out = jnp.einsum("bngqk,bnkd->bngqd", probs, v_cache)
    return out.reshape(b, h, sq, hd)


def _block_with_cache(x, layer, k_cache, v_cache, cfg: ModelConfig,
                      offset):
    """One transformer block over [b, s, d], reading/writing the cache.

    Mirrors model._block's math exactly (rmsnorm -> qkv -> rope ->
    attention -> residual -> mlp) but writes this chunk's k/v into the
    cache at ``offset`` and attends over cache contents — one code path
    for prefill (s = prompt len, offset 0) and decode (s = 1, offset =
    cache.length)."""
    b, s, d = x.shape
    y = _rmsnorm(x, layer["ln1"])
    q, k, v = _split_qkv(y, layer["qkv"], cfg)
    if cfg.rope:
        q = _rope(q, cfg.rope_theta, offset)
        k = _rope(k, cfg.rope_theta, offset)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, 0, offset, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, 0, offset, 0))
    attn = _cached_attention(q, k_cache, v_cache, offset + s, cfg)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
    x = x + jnp.einsum("bsd,de->bse", attn,
                       layer["attn_out"].astype(cfg.dtype))
    y = _rmsnorm(x, layer["ln2"])
    if cfg.moe_experts is None:
        hdn = jnp.einsum("bsd,df->bsf", y, layer["w1"].astype(cfg.dtype))
        hdn = jax.nn.gelu(hdn)
        x = x + jnp.einsum("bsf,fd->bsd", hdn,
                           layer["w2"].astype(cfg.dtype))
    else:
        # MoE checkpoints serve with the training-side routing rule
        # (model.moe_ffn); at decode s=1 each token simply visits its
        # top-k experts.
        ffn_out, _aux = moe_ffn(y, layer, cfg)
        x = x + ffn_out
    return x, k_cache, v_cache


def _run_blocks(params, x, cache: KVCache, cfg: ModelConfig, offset):
    """lax.scan over stacked layer params, threading the cache."""

    def body(carry, inputs):
        x = carry
        layer, k_c, v_c = inputs
        x, k_c, v_c = _block_with_cache(x, layer, k_c, v_c, cfg, offset)
        return x, (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v))
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("bsd,dv->bsv", x,
                        params["unembed"].astype(cfg.dtype))
    new_len = offset + x.shape[1]
    return logits.astype(jnp.float32), KVCache(k=k_new, v=v_new,
                                               length=new_len)


def prefill(params: dict, tokens: jax.Array, cfg: ModelConfig,
            max_len: int) -> tuple[jax.Array, KVCache]:
    """Run the prompt [b, s] through the model, filling a fresh cache.

    Returns (logits [b, s, vocab] fp32, cache with length == s).  The
    last position's logits seed generation."""
    b, s = tokens.shape
    if s > max_len:
        raise ValueError(f"prompt length {s} exceeds max_len {max_len}")
    cache = KVCache.zeros(cfg, b, max_len)
    x = params["embed"].astype(cfg.dtype)[tokens]
    return _run_blocks(params, x, cache, cfg, 0)


def decode_step(params: dict, cache: KVCache, tokens: jax.Array,
                cfg: ModelConfig) -> tuple[jax.Array, KVCache]:
    """One token per sequence: tokens [b] int32 at position cache.length.

    Returns (logits [b, vocab] fp32, cache advanced by one).  Fully
    jittable at a traced cache length — one compiled program serves all
    positions."""
    if not isinstance(cache.length, jax.core.Tracer) \
            and int(cache.length) >= cache.max_len:
        # Past max_len, dynamic_update_slice would silently CLAMP the
        # write offset and corrupt the last cache slot.  A traced length
        # (inside jit/scan) cannot be checked here — generate() guards
        # its own loop; direct jitted callers own the bound.
        raise ValueError(
            f"KV cache full: length {int(cache.length)} >= max_len "
            f"{cache.max_len}")
    x = params["embed"].astype(cfg.dtype)[tokens][:, None, :]
    logits, cache = _run_blocks(params, x, cache, cfg, cache.length)
    return logits[:, 0], cache


def _sample(logits: jax.Array, key, temperature: float,
            top_k: int | None, top_p: float | None = None) -> jax.Array:
    """Greedy at temperature 0.0 (static branch), else softmax sampling
    with optional top-k and/or top-p (nucleus) truncation."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / temperature
    if top_k is not None:
        # lax.top_k is O(V) vs a full O(V log V) vocab sort — this runs
        # inside the hot decode scan.
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if top_p is not None:
        # Nucleus sampling: keep the smallest set of tokens whose
        # probability mass reaches top_p.  Sort descending, find the
        # cutoff on the cumulative mass, map it back through a
        # rank-threshold (all static shapes; the sort is the cost, so
        # apply top_k first to cheapen it when both are set).
        sorted_logits = jnp.sort(scaled, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Token i survives when the mass BEFORE it is < top_p (the
        # first token always survives).
        keep_sorted = (cum - probs) < top_p
        n_keep = jnp.sum(keep_sorted, axis=-1, keepdims=True)
        # The n_keep-th largest logit is the cutoff.
        cutoff = jnp.take_along_axis(sorted_logits, n_keep - 1, axis=-1)
        scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


def generate(params: dict, prompt: jax.Array, cfg: ModelConfig,
             steps: int, *, key: jax.Array | None = None,
             temperature: float = 0.0, top_k: int | None = None,
             top_p: float | None = None,
             max_len: int | None = None) -> jax.Array:
    """Prefill the prompt [b, s], then decode ``steps`` tokens under one
    lax.scan.  Returns [b, s + steps] (prompt + generated).  Greedy by
    default; pass key + temperature (and optionally top_k / top_p) to
    sample."""
    b, s = prompt.shape
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    max_len = max_len if max_len is not None else s + steps
    if s + steps > max_len:
        raise ValueError(
            f"prompt {s} + steps {steps} exceeds max_len {max_len}")
    if temperature != 0.0 and key is None:
        raise ValueError("sampling (temperature != 0) needs a PRNG key")
    if temperature == 0.0 and (top_k is not None or top_p is not None):
        # Greedy decoding never consults the truncation knobs; erroring
        # beats silently returning argmax the caller thinks was sampled.
        raise ValueError(
            "top_k/top_p require temperature > 0 (temperature 0 is "
            "greedy argmax; truncation would be silently ignored)")
    vocab = params["unembed"].shape[-1]
    if top_k is not None and not 1 <= top_k <= vocab:
        # Validate here, not inside lax.top_k's trace, so direct API
        # callers get the same clear error the CLI gives.
        raise ValueError(f"top_k must be in [1, {vocab}], got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    logits, cache = prefill(params, prompt, cfg, max_len)
    key = key if key is not None else jax.random.PRNGKey(0)
    all_keys = jax.random.split(key, steps)
    first = _sample(logits[:, -1], all_keys[0], temperature, top_k, top_p)

    def body(carry, step_key):
        cache, token = carry
        logits, cache = decode_step(params, cache, token, cfg)
        nxt = _sample(logits, step_key, temperature, top_k, top_p)
        return (cache, nxt), nxt

    # steps-1 decode_steps: the prefill already produced token 1 of
    # ``steps``; the final sampled token is emitted without a trailing
    # (wasted) decode of it.
    (_, _), rest = jax.lax.scan(body, (cache, first), all_keys[1:])
    out = jnp.concatenate([first[:, None], rest.T], axis=1)
    return jnp.concatenate([prompt, out.astype(prompt.dtype)], axis=1)
