"""Autoregressive inference for the flagship model: KV cache + generate.

The training side (model.py) proves a provisioned slice trains; this is
the serving side of the same checkpoint — prefill + single-token decode
steps over a preallocated KV cache, the standard TPU inference shape:

- **Static shapes throughout**: the cache is preallocated at
  ``max_len`` and written with ``lax.dynamic_update_slice`` at a traced
  position, so one compiled decode step serves every position — no
  per-step recompilation, XLA-friendly by construction.
- **GQA pays off here**: the cache stores ``kv_heads`` heads, so an
  8:1 grouped layout cuts cache HBM (the decode-bandwidth bottleneck)
  by 8x relative to MHA.
- **RoPE at cache positions**: the new token's q/k rotate at absolute
  position ``cache.length`` (model._rope's offset arg), so decode
  logits bit-match teacher-forced forward() logits.
- **Sliding window as a mask**: the visibility mask bounds attention to
  the ``attention_window`` most recent cache entries; the cache itself
  stays linear (a ring buffer would shrink HBM to O(window) — noted as
  a further optimization, not needed at these sizes).
- ``generate`` runs decode under ``lax.scan`` (one compiled program for
  the whole rollout) with greedy or temperature/top-k sampling.

The reference has no model/inference code at all (SURVEY §3: it is an
infrastructure controller); this module is beyond-parity evidence that
slices the autoscaler provisions serve traffic, not just train.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpu_autoscaler.workloads.model import (
    ModelConfig,
    _ffn_residual,
    _rmsnorm,
    _rope,
    _split_qkv,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Preallocated per-layer K/V cache.

    k, v: [layers, batch, kv_heads, max_len, head_dim] in compute dtype;
    length: scalar int32, number of filled positions (same for every
    sequence in the batch — left-aligned prompts; padding support would
    add a per-row length vector and mask term).
    """

    k: jax.Array
    v: jax.Array
    length: jax.Array

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    @classmethod
    def zeros(cls, cfg: ModelConfig, batch: int, max_len: int) -> "KVCache":
        shape = (cfg.n_layers, batch, cfg.kv_heads, max_len, cfg.head_dim)
        return cls(k=jnp.zeros(shape, cfg.dtype),
                   v=jnp.zeros(shape, cfg.dtype),
                   length=jnp.zeros((), jnp.int32))


def _cached_attention(q, k_cache, v_cache, length, cfg: ModelConfig):
    """Attend q [b, h, sq, hd] (positions length-sq .. length-1, already
    rotated) over the cache's first ``length`` entries with causal +
    window visibility.  Grouped-einsum GQA, f32 softmax."""
    b, h, sq, hd = q.shape
    hkv = k_cache.shape[1]
    max_len = k_cache.shape[2]
    qg = q.reshape(b, hkv, h // hkv, sq, hd)
    scores = jnp.einsum("bngqd,bnkd->bngqk", qg, k_cache) * hd ** -0.5
    # Visibility of cache slot j for the query at absolute position p
    # (p = length - sq + qi): j <= p, and with a window, j > p - window.
    kpos = jnp.arange(max_len)
    qpos = length - sq + jnp.arange(sq)
    visible = kpos[None, :] <= qpos[:, None]
    if cfg.attention_window is not None:
        visible &= kpos[None, :] > qpos[:, None] - cfg.attention_window
    scores = jnp.where(visible[None, None, None], scores.astype(jnp.float32),
                       -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
    out = jnp.einsum("bngqk,bnkd->bngqd", probs, v_cache)
    return out.reshape(b, h, sq, hd)


def _attend(q, k, v, k_cache, v_cache, cfg: ModelConfig, offset, s,
            mesh):
    """Pick the attention path for one cached block.

    ``attention="pallas"`` (or "auto" on TPU) fuses both phases:
    decode (s == 1) runs the blocked flash_decode kernel over the cache
    (single-pass HBM read, probabilities never materialized); prefill
    (s > 1 at offset 0) runs the training flash kernel directly on the
    fresh k/v — identical math, since the cache beyond the prompt is
    invisible.  Multi-device meshes wrap the kernels in shard_map
    (batch over the data axes, heads over 'model'), exactly like the
    trainer's model._block.  The einsum path needs no wrapping — GSPMD
    partitions it from the operand shardings."""
    impl = cfg.resolved_attention()
    if impl == "pallas" and (s == 1 or (isinstance(offset, int)
                                        and offset == 0)):
        from tpu_autoscaler.workloads.attention import (
            flash_attention,
            flash_decode,
            make_sharded_flash_attention,
        )
        from tpu_autoscaler.workloads.model import data_axes

        interpret = jax.default_backend() != "tpu"
        multi = mesh is not None and mesh.size > 1
        if multi:
            # Mirror model._block's fallback: the kernel shard_map needs
            # the batch to divide over the data axes (mesh_shardable
            # covers only heads); otherwise serve via the einsum path,
            # which GSPMD partitions for any batch.
            import numpy as _np

            daxes = data_axes(mesh)
            dp = (int(_np.prod([mesh.shape[a] for a in daxes]))  # analysis: allow=TAJ401 mesh axis sizes are static ints
                  if daxes else 1)
            if q.shape[0] % dp:
                import warnings

                warnings.warn(
                    f"attention='pallas': batch {q.shape[0]} does not "
                    f"divide over the {dp} data-parallel devices of mesh "
                    f"{dict(mesh.shape)}; serving this step with einsum "
                    f"attention", stacklevel=2)
                return _cached_attention(q, k_cache, v_cache, offset + s,
                                         cfg)
        if s == 1:
            if multi:
                from jax.sharding import PartitionSpec as P

                dspec = P(data_axes(mesh),
                          "model" if "model" in mesh.axis_names else None,
                          None, None)

                def body(q, kc, vc, ln):
                    return flash_decode(q, kc, vc, ln,
                                        window=cfg.attention_window,
                                        interpret=interpret)

                return jax.shard_map(
                    body, mesh=mesh,
                    in_specs=(dspec, dspec, dspec, P()),
                    out_specs=dspec, check_vma=False,
                )(q, k_cache, v_cache, offset + s)
            return flash_decode(q, k_cache, v_cache, offset + s,
                                window=cfg.attention_window,
                                interpret=interpret)
        if multi:
            attn = make_sharded_flash_attention(
                mesh, causal=True, window=cfg.attention_window,
                batch_axis=data_axes(mesh),
                head_axis="model" if "model" in mesh.axis_names else None)
            return attn(q, k, v)
        return flash_attention(q, k, v, causal=True,
                               window=cfg.attention_window,
                               interpret=interpret)
    return _cached_attention(q, k_cache, v_cache, offset + s, cfg)


def _block_with_cache(x, layer, k_cache, v_cache, cfg: ModelConfig,
                      offset, mesh=None):
    """One transformer block over [b, s, d], reading/writing the cache.

    Mirrors model._block's math exactly (rmsnorm -> qkv -> rope ->
    attention -> residual -> mlp) but writes this chunk's k/v into the
    cache at ``offset`` and attends over cache contents — one code path
    for prefill (s = prompt len, offset 0) and decode (s = 1, offset =
    cache.length)."""
    b, s, d = x.shape
    y = _rmsnorm(x, layer["ln1"])
    q, k, v = _split_qkv(y, layer["qkv"], cfg)
    if cfg.rope:
        q = _rope(q, cfg.rope_theta, offset)
        k = _rope(k, cfg.rope_theta, offset)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, 0, offset, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, 0, offset, 0))
    attn = _attend(q, k, v, k_cache, v_cache, cfg, offset, s, mesh)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
    x = x + jnp.einsum("bsd,de->bse", attn,
                       layer["attn_out"].astype(cfg.dtype))
    y = _rmsnorm(x, layer["ln2"])
    # MoE checkpoints serve with the training-side routing rule
    # (model.moe_ffn via _ffn_residual); at decode s=1 each token
    # simply visits its top-k experts.
    x = _ffn_residual(x, y, layer, cfg)
    return x, k_cache, v_cache


def _run_blocks(params, x, cache: KVCache, cfg: ModelConfig, offset,
                mesh=None):
    """lax.scan over stacked layer params, threading the cache."""

    def body(carry, inputs):
        x = carry
        layer, k_c, v_c = inputs
        x, k_c, v_c = _block_with_cache(x, layer, k_c, v_c, cfg, offset,
                                        mesh)
        return x, (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v))
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("bsd,dv->bsv", x,
                        params["unembed"].astype(cfg.dtype))
    new_len = offset + x.shape[1]
    return logits.astype(jnp.float32), KVCache(k=k_new, v=v_new,
                                               length=new_len)


def cache_specs(mesh) -> KVCache:
    """PartitionSpecs for a KVCache under a (data, model) mesh: batch
    over the data axes, KV heads over 'model' — the serving layout the
    trainer's param_specs implies (qkv heads already split over
    'model'), so an 8-way TP slice holds 1/8 of the decode-bandwidth-
    critical cache.  Requires kv_heads % tp == 0 (cfg.mesh_shardable)."""
    from jax.sharding import PartitionSpec as P

    from tpu_autoscaler.workloads.model import data_axes

    kv = P(None, data_axes(mesh), "model", None, None)
    return KVCache(k=kv, v=kv, length=P())


def _constrain_cache(cache: KVCache, mesh) -> KVCache:
    """Pin the cache's layout under GSPMD so the einsum path keeps it
    TP-sharded instead of letting the partitioner replicate it.

    Degrades per-dimension: a batch that doesn't divide the data axes
    (or KV heads that don't divide tp) stays unsharded on that dim —
    a sharding constraint demands exact divisibility, and serving an
    uneven batch must degrade, not crash (model._block's fallback
    philosophy)."""
    if mesh is None or mesh.size == 1:
        return cache
    import numpy as _np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_autoscaler.workloads.model import data_axes

    daxes = data_axes(mesh)
    dp = int(_np.prod([mesh.shape[a] for a in daxes])) if daxes else 1  # analysis: allow=TAJ401 mesh axis sizes are static ints
    tp = mesh.shape.get("model", 1)
    b, hkv = cache.k.shape[1], cache.k.shape[2]
    spec = P(None,
             daxes if dp > 1 and b % dp == 0 else None,
             "model" if tp > 1 and hkv % tp == 0 else None,
             None, None)
    shard = NamedSharding(mesh, spec)
    return KVCache(
        k=jax.lax.with_sharding_constraint(cache.k, shard),
        v=jax.lax.with_sharding_constraint(cache.v, shard),
        length=cache.length)


def prefill(params: dict, tokens: jax.Array, cfg: ModelConfig,
            max_len: int, mesh=None) -> tuple[jax.Array, KVCache]:
    """Run the prompt [b, s] through the model, filling a fresh cache.

    Returns (logits [b, s, vocab] fp32, cache with length == s).  The
    last position's logits seed generation.  ``mesh``: serve under the
    trainer's (data, model) mesh — the cache shards per cache_specs and
    the pallas kernels run via shard_map."""
    b, s = tokens.shape
    if s > max_len:
        raise ValueError(f"prompt length {s} exceeds max_len {max_len}")
    if mesh is not None:
        cfg = cfg.resolved_for_mesh(mesh)
    cache = _constrain_cache(KVCache.zeros(cfg, b, max_len), mesh)
    x = params["embed"].astype(cfg.dtype)[tokens]
    logits, cache = _run_blocks(params, x, cache, cfg, 0, mesh)
    return logits, _constrain_cache(cache, mesh)


def decode_step(params: dict, cache: KVCache, tokens: jax.Array,
                cfg: ModelConfig, mesh=None) -> tuple[jax.Array, KVCache]:
    """One token per sequence: tokens [b] int32 at position cache.length.

    Returns (logits [b, vocab] fp32, cache advanced by one).  Fully
    jittable at a traced cache length — one compiled program serves all
    positions."""
    if not isinstance(cache.length, jax.core.Tracer) \
            and int(cache.length) >= cache.max_len:  # analysis: allow=TAJ401 Tracer-guarded
        # Past max_len, dynamic_update_slice would silently CLAMP the
        # write offset and corrupt the last cache slot.  A traced length
        # (inside jit/scan) cannot be checked here — generate() guards
        # its own loop; direct jitted callers own the bound.
        raise ValueError(
            f"KV cache full: length {int(cache.length)} >= max_len "  # analysis: allow=TAJ401 concrete by the guard above
            f"{cache.max_len}")
    if mesh is not None:
        cfg = cfg.resolved_for_mesh(mesh)
    x = params["embed"].astype(cfg.dtype)[tokens][:, None, :]
    logits, cache = _run_blocks(params, x, cache, cfg, cache.length, mesh)
    return logits[:, 0], _constrain_cache(cache, mesh)


def _warp_logits(logits: jax.Array, temperature: float,
                 top_k: int | None, top_p: float | None) -> jax.Array:
    """Temperature/top-k/top-p warping (temperature must be > 0).
    softmax of the result IS the sampling distribution — shared by
    _sample and the speculative accept/reject, which must agree on the
    warped distributions for exactness."""
    scaled = logits / temperature
    if top_k is not None:
        # lax.top_k is O(V) vs a full O(V log V) vocab sort — this runs
        # inside the hot decode scan.
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if top_p is not None:
        # Nucleus sampling: keep the smallest set of tokens whose
        # probability mass reaches top_p.  Sort descending, find the
        # cutoff on the cumulative mass, map it back through a
        # rank-threshold (all static shapes; the sort is the cost, so
        # apply top_k first to cheapen it when both are set).
        sorted_logits = jnp.sort(scaled, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Token i survives when the mass BEFORE it is < top_p (the
        # first token always survives).
        keep_sorted = (cum - probs) < top_p
        n_keep = jnp.sum(keep_sorted, axis=-1, keepdims=True)
        # The n_keep-th largest logit is the cutoff.
        cutoff = jnp.take_along_axis(sorted_logits, n_keep - 1, axis=-1)
        scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)
    return scaled


def _sample(logits: jax.Array, key, temperature: float,
            top_k: int | None, top_p: float | None = None) -> jax.Array:
    """Greedy at temperature 0.0 (static branch), else softmax sampling
    with optional top-k and/or top-p (nucleus) truncation."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    warped = _warp_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, warped, axis=-1).astype(jnp.int32)


def generate(params: dict, prompt: jax.Array, cfg: ModelConfig,
             steps: int, *, key: jax.Array | None = None,
             temperature: float = 0.0, top_k: int | None = None,
             top_p: float | None = None,
             max_len: int | None = None, mesh=None) -> jax.Array:
    """Prefill the prompt [b, s], then decode ``steps`` tokens under one
    lax.scan.  Returns [b, s + steps] (prompt + generated).  Greedy by
    default; pass key + temperature (and optionally top_k / top_p) to
    sample.  ``mesh``: serve under the trainer's mesh (see
    make_sharded_generate for the jitted end-to-end wrapper)."""
    b, s = prompt.shape
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    max_len = max_len if max_len is not None else s + steps
    if s + steps > max_len:
        raise ValueError(
            f"prompt {s} + steps {steps} exceeds max_len {max_len}")
    if temperature != 0.0 and key is None:
        raise ValueError("sampling (temperature != 0) needs a PRNG key")
    if temperature == 0.0 and (top_k is not None or top_p is not None):
        # Greedy decoding never consults the truncation knobs; erroring
        # beats silently returning argmax the caller thinks was sampled.
        raise ValueError(
            "top_k/top_p require temperature > 0 (temperature 0 is "
            "greedy argmax; truncation would be silently ignored)")
    vocab = params["unembed"].shape[-1]
    if top_k is not None and not 1 <= top_k <= vocab:
        # Validate here, not inside lax.top_k's trace, so direct API
        # callers get the same clear error the CLI gives.
        raise ValueError(f"top_k must be in [1, {vocab}], got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if mesh is not None:
        cfg = cfg.resolved_for_mesh(mesh)
    logits, cache = prefill(params, prompt, cfg, max_len, mesh)
    key = key if key is not None else jax.random.PRNGKey(0)
    all_keys = jax.random.split(key, steps)
    first = _sample(logits[:, -1], all_keys[0], temperature, top_k, top_p)

    def body(carry, step_key):
        cache, token = carry
        logits, cache = decode_step(params, cache, token, cfg, mesh)
        nxt = _sample(logits, step_key, temperature, top_k, top_p)
        return (cache, nxt), nxt

    # steps-1 decode_steps: the prefill already produced token 1 of
    # ``steps``; the final sampled token is emitted without a trailing
    # (wasted) decode of it.
    (_, _), rest = jax.lax.scan(body, (cache, first), all_keys[1:])
    out = jnp.concatenate([first[:, None], rest.T], axis=1)
    return jnp.concatenate([prompt, out.astype(prompt.dtype)], axis=1)


def extend_step(params: dict, cache: KVCache, tokens: jax.Array,
                cfg: ModelConfig, mesh=None) -> tuple[jax.Array, KVCache]:
    """Append ``tokens`` [b, s] to the cache in ONE forward: returns
    (logits [b, s, vocab] fp32 for every appended position, cache
    advanced by s).  The multi-token sibling of decode_step — the
    verification primitive for speculative decoding (one cached pass
    scores k draft tokens) and a building block for chunked appends."""
    if not isinstance(cache.length, jax.core.Tracer) \
            and int(cache.length) + tokens.shape[1] > cache.max_len:
        raise ValueError(
            f"KV cache overflow: length {int(cache.length)} + "
            f"{tokens.shape[1]} > max_len {cache.max_len}")
    if mesh is not None:
        cfg = cfg.resolved_for_mesh(mesh)
    x = params["embed"].astype(cfg.dtype)[tokens]
    logits, cache = _run_blocks(params, x, cache, cfg, cache.length, mesh)
    return logits, _constrain_cache(cache, mesh)


def _rewind(cache: KVCache, length) -> KVCache:
    """Roll the logical length back (rejected speculative entries stay
    as garbage beyond ``length``; the next write at ``length``
    overwrites them before they can ever become visible)."""
    return KVCache(k=cache.k, v=cache.v,
                   length=jnp.asarray(length, jnp.int32))


def speculative_generate(params: dict, draft_params: dict,
                         prompt: jax.Array, cfg: ModelConfig,
                         steps: int, *, draft_cfg: ModelConfig | None = None,
                         k: int = 4, max_len: int | None = None,
                         mesh=None):
    """Greedy speculative decoding: a cheap DRAFT model proposes ``k``
    tokens autoregressively, the target model scores all k in ONE
    cached forward (extend_step), and the longest prefix agreeing with
    the target's own greedy choices is accepted — plus one corrected
    token from the target logits, so every round emits between 1 and
    k+1 tokens for a single target pass.

    Output matches the target's greedy rollout token for token (tests
    pin it): acceptance only changes the step count, never the tokens
    — the standard speculative guarantee specialized to greedy.  The
    one caveat is numerics, not algorithm: every emitted token is the
    argmax of the TARGET's verification logits (einsum cached
    attention), while plain generate() on TPU may score decode steps
    with the fused flash kernel — a vocab-logit near-tie at the
    kernels' float tolerance could argmax differently there.  Decode
    is bandwidth-bound on the target's weights/cache, so wall-clock
    improves by roughly the mean accepted length when the draft is
    much cheaper (e.g. fewer layers) and agrees often.

    Returns (tokens [b, prompt+steps], stats dict with ``rounds`` and
    ``accept_rate``).  Batched rows share each round's accepted length
    (the minimum across rows) to keep one cache length — b=1 is the
    sweet spot; larger b still matches greedy exactly, just with lower
    effective acceptance.  Peak cache use is exactly ``prompt +
    steps`` (the last round's draft is capped at the tokens
    remaining), the same capacity generate() needs.
    """
    if draft_cfg is None:
        draft_cfg = cfg
    b, s = prompt.shape
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    max_len = max_len if max_len is not None else s + steps
    if s + steps > max_len:
        raise ValueError(
            f"prompt {s} + steps {steps} exceeds max_len {max_len}")
    logits_t, cache_t = prefill(params, prompt, cfg, max_len, mesh)
    _, cache_d = prefill(draft_params, prompt, draft_cfg, max_len, mesh)
    cur = jnp.argmax(logits_t[:, -1], axis=-1).astype(jnp.int32)  # [b]

    out = [cur]
    rounds = 0
    accepted_total = 0
    drafted_total = 0
    while len(out) < steps:
        rounds += 1
        # Draft greedily from the draft's own cache — capped at the
        # tokens still needed, so the last round never does k drafts
        # to emit one token (and peak cache use stays s + steps).
        k_eff = min(k, steps - len(out))
        drafted_total += k_eff
        draft_toks = []
        tok_d = cur
        for _ in range(k_eff):
            dlogits, cache_d = decode_step(draft_params, cache_d, tok_d,
                                           draft_cfg, mesh)
            tok_d = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
            draft_toks.append(tok_d)
        drafts = jnp.stack(draft_toks, axis=1)           # [b, k_eff]
        # One target pass scores cur + the k drafts: logits[:, i] is
        # the target's prediction AFTER seeing cur, d1..di.
        block = jnp.concatenate([cur[:, None], drafts], axis=1)
        tlogits, cache_t = extend_step(params, cache_t, block, cfg,
                                       mesh)
        targets = jnp.argmax(tlogits, axis=-1).astype(jnp.int32)
        match = np.asarray(drafts == targets[:, :k_eff])  # [b, k_eff]
        # Accepted length shared across rows: min over the batch.
        n_acc = int(min(
            (np.argmin(row) if not row.all() else k_eff)
            for row in match))
        emit = np.asarray(targets[:, :n_acc + 1])        # [b, n_acc+1]
        accepted_total += n_acc
        for j in range(emit.shape[1]):
            if len(out) < steps:
                out.append(jnp.asarray(emit[:, j]))
        cur = jnp.asarray(emit[:, -1])
        # Rewind both caches to the confirmed stream: target holds
        # prompt + generated-so-far (excluding cur, which the next
        # round's block re-appends).
        confirmed = s + len(out) - 1
        cache_t = _rewind(cache_t, confirmed)
        # The draft cache wrote [cur, d1..d_{k-1}] — valid exactly on
        # the confirmed prefix, but when every draft was accepted the
        # stream ran one token PAST what the draft ever wrote (d_k was
        # computed, never cached).  Rewind to the valid prefix, then
        # replay the missing confirmed tokens through the draft.
        cache_d = _rewind(cache_d, min(int(cache_d.length), confirmed))
        behind = confirmed - int(cache_d.length)
        if behind > 0:
            replay = jnp.stack(out[-(behind + 1):-1], axis=1)
            _, cache_d = extend_step(draft_params, cache_d, replay,
                                     draft_cfg, mesh)
    tokens = jnp.stack(out[:steps], axis=1)
    stats = {"rounds": rounds,
             "accept_rate": accepted_total / max(drafted_total, 1)}
    return jnp.concatenate([prompt, tokens.astype(prompt.dtype)],
                           axis=1), stats


def speculative_sample_generate(
        params: dict, draft_params: dict, prompt: jax.Array,
        cfg: ModelConfig, steps: int, *, key: jax.Array,
        temperature: float = 1.0, top_k: int | None = None,
        top_p: float | None = None, draft_cfg: ModelConfig | None = None,
        k: int = 4, max_len: int | None = None, mesh=None):
    """Distribution-preserving speculative SAMPLING (the stochastic
    sibling of speculative_generate's greedy path).

    The standard accept/reject construction (speculative decoding /
    rejection-sampling transport): the draft proposes x_i ~ q_i, the
    target scores all k proposals in ONE cached pass (extend_step),
    and each x_i is accepted with probability min(1, p_i(x_i) /
    q_i(x_i)); the first rejection resamples from the residual
    norm(max(p_i - q_i, 0)) and ends the round.  The emitted stream is
    then distributed EXACTLY as sampling from the target alone —
    regardless of the draft — which the marginal-distribution tests
    pin (TestSpeculativeSampling).  Temperature / top-k / top-p warp
    BOTH p and q through the same _warp_logits the plain sampler uses;
    temperature 0 delegates to the greedy speculative path.

    Batched rows each accept/reject independently; the shared cache
    truncates every round at the batch's minimum accept length
    (rows that accepted further emit their accepted token at the
    truncation point — still a valid p-sample, so exactness holds
    per row; b=1 pays no truncation at all).

    Returns (tokens [b, prompt+steps], stats with ``rounds``,
    ``accept_rate``).
    """
    if temperature == 0.0:
        if top_k is not None or top_p is not None:
            raise ValueError(
                "top_k/top_p require temperature > 0 (temperature 0 is "
                "greedy argmax; truncation would be silently ignored)")
        return speculative_generate(
            params, draft_params, prompt, cfg, steps,
            draft_cfg=draft_cfg, k=k, max_len=max_len, mesh=mesh)
    if draft_cfg is None:
        draft_cfg = cfg
    b, s = prompt.shape
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    max_len = max_len if max_len is not None else s + steps
    if s + steps > max_len:
        raise ValueError(
            f"prompt {s} + steps {steps} exceeds max_len {max_len}")

    def warped_probs(logits):
        return jax.nn.softmax(
            _warp_logits(logits.astype(jnp.float32), temperature,
                         top_k, top_p), axis=-1)

    def next_key():
        nonlocal key
        key, sub = jax.random.split(key)
        return sub

    logits_t, cache_t = prefill(params, prompt, cfg, max_len, mesh)
    _, cache_d = prefill(draft_params, prompt, draft_cfg, max_len, mesh)
    cur = _sample(logits_t[:, -1], next_key(), temperature, top_k, top_p)

    out = [cur]
    rounds = 0
    accepted_total = 0
    drafted_total = 0
    while len(out) < steps:
        rounds += 1
        k_eff = min(k, steps - len(out))
        drafted_total += b * k_eff
        draft_toks, draft_q = [], []
        tok_d = cur
        for _ in range(k_eff):
            dlogits, cache_d = decode_step(draft_params, cache_d, tok_d,
                                           draft_cfg, mesh)
            q = warped_probs(dlogits)                      # [b, V]
            tok_d = jax.random.categorical(
                next_key(), jnp.log(q + 1e-30), axis=-1).astype(jnp.int32)
            draft_toks.append(tok_d)
            draft_q.append(q)
        drafts = jnp.stack(draft_toks, axis=1)             # [b, k_eff]
        qs = jnp.stack(draft_q, axis=1)                    # [b, k_eff, V]
        block = jnp.concatenate([cur[:, None], drafts], axis=1)
        tlogits, cache_t = extend_step(params, cache_t, block, cfg, mesh)
        ps = warped_probs(tlogits)                         # [b, k_eff+1, V]
        # Accept x_i with prob min(1, p_i(x)/q_i(x)); first rejection
        # per row ends its accepted prefix.
        p_x = jnp.take_along_axis(ps[:, :k_eff], drafts[..., None],
                                  axis=-1)[..., 0]         # [b, k_eff]
        q_x = jnp.take_along_axis(qs, drafts[..., None], axis=-1)[..., 0]
        u = jax.random.uniform(next_key(), p_x.shape)
        accept = np.asarray(u * q_x < p_x)                 # [b, k_eff]
        acc_len = np.asarray([
            int(np.argmin(row)) if not row.all() else k_eff
            for row in accept])                            # [b]
        n_acc = int(acc_len.min())
        # accept_rate is PER-ROW acceptance (the economics signal); the
        # shared cache only truncates emission at the batch minimum.
        accepted_total += int(acc_len.sum())
        # Token n_acc per row: rejected rows draw from the residual
        # norm(max(p - q, 0)); rows that accepted past the truncation
        # point emit their accepted draft token (a valid p-sample).
        p_n = ps[:, n_acc]                                 # [b, V]
        if n_acc < k_eff:
            residual = jnp.maximum(p_n - qs[:, n_acc], 0.0)
            # A zero residual (p==q) can only arise when acceptance was
            # certain, so the row cannot be in the rejected set; the
            # fallback to p_n keeps categorical() well-defined anyway.
            rsum = residual.sum(axis=-1, keepdims=True)
            residual = jnp.where(rsum > 0, residual / rsum, p_n)
            res_tok = jax.random.categorical(
                next_key(), jnp.log(residual + 1e-30),
                axis=-1).astype(jnp.int32)
            rejected_here = jnp.asarray(acc_len == n_acc)
            bonus = jnp.where(rejected_here, res_tok, drafts[:, n_acc])
        else:
            # Every row accepted the whole block: the (k+1)-th logits
            # row is a fresh target sample past the last draft.
            bonus = jax.random.categorical(
                next_key(), jnp.log(p_n + 1e-30),
                axis=-1).astype(jnp.int32)
        emit = (np.asarray(drafts[:, :n_acc]), np.asarray(bonus))
        for j in range(n_acc):
            if len(out) < steps:
                out.append(jnp.asarray(emit[0][:, j]))
        if len(out) < steps:
            out.append(jnp.asarray(emit[1]))
        cur = out[-1]
        confirmed = s + len(out) - 1
        cache_t = _rewind(cache_t, confirmed)
        cache_d = _rewind(cache_d, min(int(cache_d.length), confirmed))
        behind = confirmed - int(cache_d.length)
        if behind > 0:
            replay = jnp.stack(out[-(behind + 1):-1], axis=1)
            _, cache_d = extend_step(draft_params, cache_d, replay,
                                     draft_cfg, mesh)
    tokens = jnp.stack(out[:steps], axis=1)
    stats = {"rounds": rounds,
             "accept_rate": accepted_total / max(drafted_total, 1)}
    return jnp.concatenate([prompt, tokens.astype(prompt.dtype)],
                           axis=1), stats


def make_sharded_generate(mesh, cfg: ModelConfig, steps: int, *,
                          temperature: float = 0.0,
                          top_k: int | None = None,
                          top_p: float | None = None,
                          max_len: int | None = None):
    """Build ``run(params, prompt, key) -> tokens`` jitted under the
    trainer's (data, model) mesh: the checkpoint serves with the SAME
    TP layout it trained with (model.param_specs — no resharding on the
    train->serve handoff), prompts/outputs shard over the data axes,
    and the KV cache shards over KV heads on 'model' (cache_specs) so
    each TP shard streams only its slice of the decode-bandwidth-
    critical cache.  The pallas decode/prefill kernels run per-shard
    via shard_map; the einsum path is GSPMD-partitioned from the same
    shardings."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_autoscaler.workloads.model import (
        batch_spec,
        param_specs,
    )

    cfg = cfg.resolved_for_mesh(mesh)
    p_shard = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P))
    tok_shard = NamedSharding(mesh, batch_spec(mesh))

    def run(params, prompt, key):
        return generate(params, prompt, cfg, steps, key=key,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, max_len=max_len, mesh=mesh)

    return jax.jit(run, in_shardings=(p_shard, tok_shard, None),
                   out_shardings=tok_shard)
