"""Shared CLI plumbing for the workload commands.

train.py and generate.py must agree on the model-architecture flags —
a checkpoint is only consumable when both sides build the same
ModelConfig — so the flag block exists exactly once, here.
"""

from __future__ import annotations

import click

_MODEL_ARCH_OPTIONS = [
    click.option("--vocab", default=256, show_default=True,
                 help="Vocabulary size (must match the tokenizer of any "
                      "--data-file shard)."),
    click.option("--seq-len", default=64, show_default=True),
    click.option("--d-model", default=128, show_default=True),
    click.option("--n-layers", default=2, show_default=True),
    click.option("--n-kv-heads", default=None, type=int,
                 help="GQA: shared KV heads (default: n_heads, i.e. "
                      "MHA)."),
    click.option("--attention-window", default=None, type=int,
                 help="Sliding-window attention width (default: full "
                      "causal)."),
    click.option("--no-rope", is_flag=True,
                 help="Disable rotary position embeddings."),
    click.option("--moe-experts", default=None, type=int,
                 help="Mixture-of-experts FFN: replace every block's "
                      "dense MLP with this many expert MLPs (top-k "
                      "routed).  Changes the checkpoint pytree, so the "
                      "generate CLI needs the same value."),
    click.option("--moe-top-k", default=2, show_default=True,
                 help="Experts each token visits (with --moe-experts)."),
]


def model_arch_options(f):
    """The architecture flags every checkpoint-sharing command takes."""
    for opt in reversed(_MODEL_ARCH_OPTIONS):
        f = opt(f)
    return f


def model_config(vocab, seq_len, d_model, n_layers, n_kv_heads,
                 attention_window, no_rope, moe_experts=None,
                 moe_top_k=2, **extra):
    """Build the ModelConfig these flags describe (extra kwargs pass
    through to training-only fields like remat/ce_chunk)."""
    from tpu_autoscaler.workloads.model import ModelConfig

    return ModelConfig(vocab=vocab, seq_len=seq_len, d_model=d_model,
                       n_layers=n_layers, n_kv_heads=n_kv_heads,
                       attention_window=attention_window,
                       rope=not no_rope, moe_experts=moe_experts,
                       moe_top_k=moe_top_k, **extra)
