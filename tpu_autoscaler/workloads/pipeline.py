"""Pipeline parallelism (pp): layers split across a mesh axis.

Completes the workload's parallelism portfolio (dp/tp in model.py, sp in
ring_attention.py, ep in moe.py): the transformer's stacked layer params
shard over the ``pp`` axis on their leading (layer) dimension — stage i
holds layers [i·L/P, (i+1)·L/P) — and microbatches stream through the
stage ring via ``lax.ppermute``, GPipe-style.  The schedule is an ordinary
``lax.fori_loop`` (static trip count, so it lowers to scan) inside
``shard_map``, and reverse-mode AD derives the backward pipeline
automatically (ppermute transposes to the reversed ring); no hand-written
1F1B pass is needed at these scales.

Schedule economics (GPipe): with P stages and m microbatches the loop
runs m+P-1 ticks, of which P-1 are bubble — bubble fraction
(P-1)/(m+P-1), so m >= 4P keeps it under ~20%.  1F1B would cut the
activation stash from O(m) to O(P) microbatches but requires scheduling
the backward by hand (JAX's AD owns it here); the same memory lever is
exposed instead as ``remat=True`` on the train step, which checkpoints
each tick's stage forward so AD stores only the O(m) inter-stage carries
and recomputes block activations in the backward — the standard
GPipe-with-remat recipe.

Autoscaler relevance: a pp×dp job spans whole slices with the pp ring on
ICI — another communication pattern that must never be bisected, which is
why drains operate on whole slices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_autoscaler.workloads._shard_utils import pvary
from tpu_autoscaler.workloads.model import (
    ModelConfig,
    TrainConfig,
    _block,
    _rmsnorm,
    make_optimizer,
)


def _stage_forward(blocks: dict, x: jax.Array, cfg: ModelConfig):
    """Run THIS stage's layer stack (leading dim = local layers).

    Returns (x, aux) with aux meaned over the local layers (MoE router
    losses; zeros for dense blocks)."""

    def body(x, layer):
        x, aux = _block(x, layer, cfg)
        return x, aux

    x, aux_stacked = jax.lax.scan(body, x, blocks)
    return x, jax.tree.map(jnp.mean, aux_stacked)


def pipeline_param_specs(cfg: ModelConfig, pp_axis: str = "pp") -> dict:
    """PartitionSpecs for the standard model pytree under pp: blocks
    shard over ``pp_axis`` on the layer dim, embed/unembed/ln_f
    replicate (stage 0 uses the embedding, the last stage the
    unembedding; replication keeps the pytree uniform)."""
    if cfg.moe_experts is None:
        ffn = {"w1": P(pp_axis, None, None), "w2": P(pp_axis, None, None)}
    else:
        ffn = {"router": P(pp_axis, None, None),
               "w1": P(pp_axis, None, None, None),
               "w2": P(pp_axis, None, None, None)}
    block_specs = {
        "qkv": P(pp_axis, None, None), "attn_out": P(pp_axis, None, None),
        **ffn,
        "ln1": P(pp_axis, None), "ln2": P(pp_axis, None),
    }
    return {"embed": P(None, None), "blocks": block_specs,
            "ln_f": P(None), "unembed": P(None, None)}


def make_pipeline_loss(mesh: Mesh, cfg: ModelConfig,
                       num_microbatches: int, pp_axis: str = "pp",
                       remat: bool = False):
    """Build ``loss(params, tokens)`` pipelined over ``mesh``'s pp axis.

    params: the standard model pytree (model.init_params) — blocks shard
    over pp on the layer dim, embed/unembed/ln_f replicate.  tokens:
    [batch, seq+1] int32, batch divisible by num_microbatches.

    ``remat``: checkpoint each tick's stage forward — AD then stores
    only the inter-stage ppermute carries and recomputes the block
    activations in the backward (the GPipe memory lever; see module
    docstring).

    MoE configs fold the router balance/z losses in exactly like
    model.loss_and_metrics (weighted by cfg.moe_*_weight), so the
    pipelined loss stays comparable to the unpipelined one.
    """
    n_stages = mesh.shape[pp_axis]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"{cfg.n_layers} layers not divisible by {n_stages} stages")

    param_specs = pipeline_param_specs(cfg, pp_axis)
    stage_fwd = functools.partial(_stage_forward, cfg=cfg)
    if remat:
        stage_fwd = jax.checkpoint(stage_fwd)

    def local_loss(params, tokens):
        idx = jax.lax.axis_index(pp_axis)
        m = num_microbatches
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        b, s = inputs.shape
        mb = b // m
        x_mb = inputs.reshape(m, mb, s)

        embedded = params["embed"].astype(cfg.dtype)[x_mb]  # [m, mb, s, d]
        d = embedded.shape[-1]
        zeros = jnp.zeros((mb, s, d), cfg.dtype)

        def tick(t, carry):
            buf, outs, aux_sum = carry
            # Stage 0 ingests microbatch t (clamped; only used while
            # t < m); later stages consume the ring buffer.
            ingest = jax.lax.dynamic_index_in_dim(
                embedded, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
            x_in = jnp.where(idx == 0, ingest, buf)
            y, aux = stage_fwd(params["blocks"], x_in)
            # This stage is processing microbatch t - idx; its aux only
            # counts while that is a real microbatch (not bubble).
            stage_valid = jnp.logical_and(t - idx >= 0, t - idx < m)
            aux_sum = jax.tree.map(
                lambda acc, a: acc + jnp.where(stage_valid, a, 0.0),
                aux_sum, aux)
            # Last stage banks microbatch t-(P-1) when in range.
            out_t = t - (n_stages - 1)
            valid = jnp.logical_and(out_t >= 0, out_t < m)
            banked = jax.lax.dynamic_update_index_in_dim(
                outs, y.astype(outs.dtype), jnp.clip(out_t, 0, m - 1),
                axis=0)
            outs = jnp.where(valid, banked, outs)
            # Rotate activations one hop down the stage ring.
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            buf = jax.lax.ppermute(y, pp_axis, perm)
            return buf, outs, aux_sum

        buf0 = pvary(zeros, pp_axis)
        outs0 = pvary(jnp.zeros((m, mb, s, d), cfg.dtype), pp_axis)
        aux0 = jax.tree.map(
            lambda a: pvary(a, pp_axis),
            {"balance_loss": jnp.zeros((), jnp.float32),
             "z_loss": jnp.zeros((), jnp.float32)})
        _, outs, aux_sum = jax.lax.fori_loop(
            0, m + n_stages - 1, tick, (buf0, outs0, aux0))

        # Loss on the last stage only; psum shares it with the ring (and
        # gives every stage the same scalar, keeping grads correct).
        h = _rmsnorm(outs.reshape(m * mb, s, d), params["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", h,
                            params["unembed"].astype(cfg.dtype))
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, targets.reshape(m * mb, s)[..., None], axis=-1)
        local = jnp.where(idx == n_stages - 1, jnp.mean(nll), 0.0)
        loss = jax.lax.psum(local, pp_axis)
        if cfg.moe_experts is not None:
            # Each stage's aux_sum is Σ over its m microbatches of its
            # local-layer mean; psum over stages then / (m·P) recovers
            # the all-layer, all-microbatch mean — the same quantity
            # model.loss_and_metrics reports.
            aux = jax.tree.map(
                lambda a: jax.lax.psum(a, pp_axis)
                / (m * n_stages), aux_sum)
            loss = (loss + cfg.moe_balance_weight * aux["balance_loss"]
                    + cfg.moe_z_weight * aux["z_loss"])
        return loss

    sharded = jax.shard_map(
        local_loss, mesh=mesh,
        in_specs=(param_specs, P()), out_specs=P())

    @functools.wraps(sharded)
    def loss(params, tokens):
        return sharded(params, tokens)

    return loss


def make_pipeline_train_step(mesh: Mesh, cfg: ModelConfig,
                             num_microbatches: int, pp_axis: str = "pp",
                             learning_rate: float = 1e-3,
                             train: TrainConfig | None = None,
                             remat: bool = True):
    """Build (init_fn, step_fn) for GPipe training over ``mesh``'s pp
    axis: grads and the optimizer both live under the pp shardings, so
    each stage updates only the layer shard it owns (plus the small
    replicated embed/unembed/ln leaves).

    step_fn: (params, opt_state, tokens) -> (params, opt_state, loss),
    jitted with the pipeline in/out shardings; loss matches the
    unpipelined train step's (tests pin the parity).  ``remat`` defaults
    True — microbatch rematerialization is the point of pipelining at
    memory-bound scales.

    The optimizer recipe is the trainer's (model.make_optimizer):
    schedules, clipping and accumulation all apply unchanged because
    they act on the (stage-sharded) grads elementwise or via a global
    norm XLA computes with a cross-stage psum.
    """
    from tpu_autoscaler.workloads.model import (
        init_params,
        opt_state_shardings,
    )

    if train is None:
        train = TrainConfig(learning_rate=learning_rate)
    optimizer = make_optimizer(train)
    loss_fn = make_pipeline_loss(mesh, cfg, num_microbatches, pp_axis,
                                 remat=remat)
    p_specs = pipeline_param_specs(cfg, pp_axis)
    p_shard = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), p_specs,
        is_leaf=lambda x: isinstance(x, P))
    replicated = NamedSharding(mesh, P())
    o_shard = opt_state_shardings(cfg, optimizer, p_specs, mesh, False)

    def init(key):
        params = init_params(key, cfg)
        return params, optimizer.init(params)

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    init_jit = jax.jit(init, out_shardings=(p_shard, o_shard))
    step_jit = jax.jit(
        step,
        in_shardings=(p_shard, o_shard, replicated),
        out_shardings=(p_shard, o_shard, replicated),
        donate_argnums=(0, 1),
    )
    return init_jit, step_jit
