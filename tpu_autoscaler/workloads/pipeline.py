"""Pipeline parallelism (pp): layers split across a mesh axis.

Completes the workload's parallelism portfolio (dp/tp in model.py, sp in
ring_attention.py, ep in moe.py): the transformer's stacked layer params
shard over the ``pp`` axis on their leading (layer) dimension — stage i
holds layers [i·L/P, (i+1)·L/P) — and microbatches stream through the
stage ring via ``lax.ppermute``, GPipe-style.  The schedule is an ordinary
``lax.fori_loop`` (static trip count, so it lowers to scan) inside
``shard_map``, and reverse-mode AD derives the backward pipeline
automatically (ppermute transposes to the reversed ring); no hand-written
1F1B pass is needed at these scales.

Schedule economics (GPipe): with P stages and m microbatches the loop
runs m+P-1 ticks, of which P-1 are bubble — bubble fraction
(P-1)/(m+P-1), so m >= 4P keeps it under ~20%.  1F1B would cut the
activation stash from O(m) to O(P) microbatches but requires scheduling
the backward by hand (JAX's AD owns it here); the same memory lever is
exposed instead as ``remat=True`` on the train step, which checkpoints
each tick's stage forward so AD stores only the O(m) inter-stage carries
and recomputes block activations in the backward — the standard
GPipe-with-remat recipe.

Autoscaler relevance: a pp×dp job spans whole slices with the pp ring on
ICI — another communication pattern that must never be bisected, which is
why drains operate on whole slices.

3-axis composition (dp×pp×tp): pass a mesh carrying ``data`` and
``model`` axes alongside ``pp`` and the same GPipe schedule runs with
the batch sharded over ``data`` and every stage's layer weights
Megatron-sharded over ``model`` (column-parallel qkv/w1, row-parallel
attn_out/w2, one psum per half-block riding ICI).  Because the standard
pytree packs q|k|v on one output dim — whose contiguous ``model``
chunks would NOT align with whole attention heads — the 3-axis step
trains on a split-weight pytree (``wq``/``wk``/``wv``; see
split_qkv_weights) so the TP shards hold whole GQA groups with zero
extra collectives.  Converters to/from the standard pytree keep
checkpoints interchangeable.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_autoscaler.workloads._shard_utils import pvary
from tpu_autoscaler.workloads.model import (
    ModelConfig,
    TrainConfig,
    _block,
    _rmsnorm,
    _rope,
    make_optimizer,
)


def _stage_forward(blocks: dict, x: jax.Array, cfg: ModelConfig):
    """Run THIS stage's layer stack (leading dim = local layers).

    Returns (x, aux) with aux meaned over the local layers (MoE router
    losses; zeros for dense blocks)."""

    def body(x, layer):
        x, aux = _block(x, layer, cfg)
        return x, aux

    x, aux_stacked = jax.lax.scan(body, x, blocks)
    return x, jax.tree.map(jnp.mean, aux_stacked)


def split_qkv_weights(params: dict, cfg: ModelConfig) -> dict:
    """Standard pytree -> the 3-axis pipeline's split-weight pytree.

    blocks.qkv [L, d, d + 2·hkv·hd] splits at the q|k|v packing
    boundaries (model._split_qkv's single source of truth) into
    wq [L, d, h·hd], wk/wv [L, d, hkv·hd] so each weight's output dim
    is pure heads and a contiguous ``model`` shard holds whole GQA
    groups.  Pure reshape/split — invertible bit-for-bit
    (merge_qkv_weights), so checkpoints convert either way."""
    d, hkv, hd = cfg.d_model, cfg.kv_heads, cfg.head_dim
    blocks = dict(params["blocks"])
    wq, wk, wv = jnp.split(blocks.pop("qkv"), [d, d + hkv * hd], axis=-1)
    blocks.update(wq=wq, wk=wk, wv=wv)
    return {**params, "blocks": blocks}


def merge_qkv_weights(params3d: dict, cfg: ModelConfig) -> dict:
    """Inverse of split_qkv_weights: repack wq|wk|wv into blocks.qkv."""
    blocks = dict(params3d["blocks"])
    qkv = jnp.concatenate(
        [blocks.pop("wq"), blocks.pop("wk"), blocks.pop("wv")], axis=-1)
    blocks["qkv"] = qkv
    return {**params3d, "blocks": blocks}


def pipeline3d_param_specs(cfg: ModelConfig, pp_axis: str = "pp",
                           model_axis: str = "model") -> dict:
    """PartitionSpecs for the SPLIT-WEIGHT pytree under pp×tp: blocks
    shard over ``pp_axis`` on the layer dim and over ``model_axis``
    Megatron-style (wq/wk/wv/w1 column-parallel, attn_out/w2
    row-parallel); embed/unembed/ln replicate (model.param_specs:638's
    TP pattern, with the layer dim in front)."""
    return {
        "embed": P(None, None),
        "blocks": {
            "wq": P(pp_axis, None, model_axis),
            "wk": P(pp_axis, None, model_axis),
            "wv": P(pp_axis, None, model_axis),
            "attn_out": P(pp_axis, model_axis, None),
            "w1": P(pp_axis, None, model_axis),
            "w2": P(pp_axis, model_axis, None),
            "ln1": P(pp_axis, None),
            "ln2": P(pp_axis, None),
        },
        "ln_f": P(None),
        "unembed": P(None, None),
    }


def _tp_block(x: jax.Array, layer: dict, cfg: ModelConfig, *,
              model_axis: str, tp: int):
    """One transformer block on this TP rank's head/d_ff shard —
    model._block's math (the parity oracle) with the Megatron split
    made explicit for shard_map: q/k/v projections are column-parallel
    (this rank holds n_heads/tp query heads = whole GQA groups),
    attention runs entirely locally, and the two row-parallel output
    projections each finish with one psum over ``model_axis``."""
    b, s, d = x.shape
    h_loc = cfg.n_heads // tp
    hkv_loc = cfg.kv_heads // tp
    hd = cfg.head_dim

    y = _rmsnorm(x, layer["ln1"])
    q = jnp.einsum("bsd,de->bse", y, layer["wq"].astype(cfg.dtype))
    k = jnp.einsum("bsd,de->bse", y, layer["wk"].astype(cfg.dtype))
    v = jnp.einsum("bsd,de->bse", y, layer["wv"].astype(cfg.dtype))
    q = q.reshape(b, s, h_loc, hd).transpose(0, 2, 1, 3)
    k = k.reshape(b, s, hkv_loc, hd).transpose(0, 2, 1, 3)
    v = v.reshape(b, s, hkv_loc, hd).transpose(0, 2, 1, 3)
    if cfg.rope:
        q = _rope(q, cfg.rope_theta)
        k = _rope(k, cfg.rope_theta)

    if cfg.resolved_attention() == "pallas":
        from tpu_autoscaler.workloads.attention import flash_attention

        attn = flash_attention(
            q, k, v, causal=True, window=cfg.attention_window,
            block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
            interpret=jax.default_backend() != "tpu")
    else:
        from tpu_autoscaler.workloads.attention import causal_band_mask

        qg = q.reshape(b, hkv_loc, h_loc // hkv_loc, s, hd)
        scores = jnp.einsum("bngqd,bnkd->bngqk", qg, k) / np.sqrt(hd)
        causal = causal_band_mask(s, cfg.attention_window)
        scores = jnp.where(causal, scores.astype(jnp.float32), -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bngqk,bnkd->bngqd", probs, v).reshape(
            b, h_loc, s, hd)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, h_loc * hd)
    # Row-parallel output projection: this rank's rows are exactly its
    # heads' slice of attn_out; psum completes the full-d sum.
    out = jnp.einsum("bse,ed->bsd", attn,
                     layer["attn_out"].astype(cfg.dtype))
    x = x + jax.lax.psum(out, model_axis)

    y = _rmsnorm(x, layer["ln2"])
    hdn = jnp.einsum("bsd,df->bsf", y, layer["w1"].astype(cfg.dtype))
    hdn = jax.nn.gelu(hdn)
    out = jnp.einsum("bsf,fd->bsd", hdn, layer["w2"].astype(cfg.dtype))
    return x + jax.lax.psum(out, model_axis)


def _tp_stage_forward(blocks: dict, x: jax.Array, cfg: ModelConfig,
                      model_axis: str, tp: int):
    """Run THIS stage's layer stack under TP (dense blocks only)."""

    def body(x, layer):
        return _tp_block(x, layer, cfg, model_axis=model_axis, tp=tp), None

    x, _ = jax.lax.scan(body, x, blocks)
    return x


def pipeline_param_specs(cfg: ModelConfig, pp_axis: str = "pp") -> dict:
    """PartitionSpecs for the standard model pytree under pp: blocks
    shard over ``pp_axis`` on the layer dim, embed/unembed/ln_f
    replicate (stage 0 uses the embedding, the last stage the
    unembedding; replication keeps the pytree uniform)."""
    if cfg.moe_experts is None:
        ffn = {"w1": P(pp_axis, None, None), "w2": P(pp_axis, None, None)}
    else:
        ffn = {"router": P(pp_axis, None, None),
               "w1": P(pp_axis, None, None, None),
               "w2": P(pp_axis, None, None, None)}
    block_specs = {
        "qkv": P(pp_axis, None, None), "attn_out": P(pp_axis, None, None),
        **ffn,
        "ln1": P(pp_axis, None), "ln2": P(pp_axis, None),
    }
    return {"embed": P(None, None), "blocks": block_specs,
            "ln_f": P(None), "unembed": P(None, None)}


def make_pipeline_mesh(devices=None, pp: int = 2, tp: int = 1) -> Mesh:
    """(data, pp, model) mesh: batch over ``data``, stages over ``pp``,
    Megatron TP over ``model``; dp takes the rest of the devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % (pp * tp):
        raise ValueError(f"{n} devices not divisible by pp*tp = {pp * tp}")
    arr = np.asarray(devices).reshape(n // (pp * tp), pp, tp)
    return Mesh(arr, axis_names=("data", "pp", "model"))


def make_pipeline3d_loss(mesh: Mesh, cfg: ModelConfig,
                         num_microbatches: int, pp_axis: str = "pp",
                         data_axis: str = "data",
                         model_axis: str = "model",
                         remat: bool = False):
    """Build ``loss(params3d, tokens)`` pipelined over ``pp_axis`` with
    the batch sharded over ``data_axis`` and the stage weights
    Megatron-sharded over ``model_axis`` — the dp×pp×tp composition.

    params3d: the SPLIT-WEIGHT pytree (split_qkv_weights).  tokens:
    [batch, seq+1] int32, batch divisible by dp·num_microbatches.
    Dense blocks only (MoE routing composes with ep, not tp-inside-pp).
    """
    n_stages = mesh.shape[pp_axis]
    tp = mesh.shape[model_axis]
    dp = mesh.shape[data_axis]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"{cfg.n_layers} layers not divisible by {n_stages} stages")
    if cfg.n_heads % tp or cfg.kv_heads % tp:
        raise ValueError(
            f"heads ({cfg.n_heads} q / {cfg.kv_heads} kv) must divide by "
            f"the {model_axis} axis ({tp})")
    if cfg.d_ff % tp:
        raise ValueError(
            f"d_ff ({cfg.d_ff}) must divide by the {model_axis} axis "
            f"({tp})")
    if cfg.moe_experts is not None:
        raise ValueError(
            "MoE blocks are not supported in the tp-composed pipeline; "
            "use the pp-only pipeline or the dp/ep step")

    param_specs = pipeline3d_param_specs(cfg, pp_axis, model_axis)
    stage_fwd = functools.partial(_tp_stage_forward, cfg=cfg,
                                  model_axis=model_axis, tp=tp)
    if remat:
        stage_fwd = jax.checkpoint(stage_fwd)

    def local_loss(params, tokens):
        idx = jax.lax.axis_index(pp_axis)
        m = num_microbatches
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        b_loc, s = inputs.shape
        if b_loc % m:
            raise ValueError(
                f"per-data-shard batch {b_loc} not divisible by "
                f"{m} microbatches")
        mb = b_loc // m
        x_mb = inputs.reshape(m, mb, s)

        embedded = params["embed"].astype(cfg.dtype)[x_mb]  # [m, mb, s, d]
        d = embedded.shape[-1]
        zeros = jnp.zeros((mb, s, d), cfg.dtype)

        def tick(t, carry):
            buf, outs = carry
            ingest = jax.lax.dynamic_index_in_dim(
                embedded, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
            x_in = jnp.where(idx == 0, ingest, buf)
            y = stage_fwd(params["blocks"], x_in)
            out_t = t - (n_stages - 1)
            valid = jnp.logical_and(out_t >= 0, out_t < m)
            banked = jax.lax.dynamic_update_index_in_dim(
                outs, y.astype(outs.dtype), jnp.clip(out_t, 0, m - 1),
                axis=0)
            outs = jnp.where(valid, banked, outs)
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            buf = jax.lax.ppermute(y, pp_axis, perm)
            return buf, outs

        buf0 = pvary(zeros, pp_axis)
        outs0 = pvary(jnp.zeros((m, mb, s, d), cfg.dtype), pp_axis)
        _, outs = jax.lax.fori_loop(
            0, m + n_stages - 1, tick, (buf0, outs0))

        # Loss on the last stage; psum over (pp, data) shares the same
        # scalar with the whole mesh.  Model ranks hold replicated
        # activations after the forward psums, so no reduction over
        # model (it would multiply by tp).
        h = _rmsnorm(outs.reshape(m * mb, s, d), params["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", h,
                            params["unembed"].astype(cfg.dtype))
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, targets.reshape(m * mb, s)[..., None], axis=-1)
        local = jnp.where(idx == n_stages - 1, jnp.mean(nll), 0.0)
        return jax.lax.psum(local, (pp_axis, data_axis)) / dp

    sharded = jax.shard_map(
        local_loss, mesh=mesh,
        in_specs=(param_specs, P(data_axis, None)), out_specs=P(),
        check_vma=False)

    def loss(params3d, tokens):
        return sharded(params3d, tokens)

    return loss


def make_pipeline3d_train_step(mesh: Mesh, cfg: ModelConfig,
                               num_microbatches: int, pp_axis: str = "pp",
                               data_axis: str = "data",
                               model_axis: str = "model",
                               learning_rate: float = 1e-3,
                               train: TrainConfig | None = None,
                               remat: bool = True):
    """Build (init_fn, step_fn) for dp×pp×tp training: GPipe over
    ``pp_axis``, batch over ``data_axis``, Megatron TP over
    ``model_axis``, all in ONE jitted step over ``mesh``.

    step_fn: (params3d, opt_state, tokens) -> (params3d, opt_state,
    loss) on the split-weight pytree; convert standard checkpoints with
    split_qkv_weights / merge_qkv_weights.  Loss matches the
    unpipelined dp/tp step leaf-for-leaf (tests pin it).  The
    trainer's optimizer recipe applies unchanged — grads arrive under
    the pp×tp shardings with the data-axis psum already inserted by AD.
    """
    if train is None:
        train = TrainConfig(learning_rate=learning_rate)
    optimizer = make_optimizer(train)
    loss_fn = make_pipeline3d_loss(
        mesh, cfg, num_microbatches, pp_axis, data_axis, model_axis,
        remat=remat)
    from tpu_autoscaler.workloads.model import (
        _opt_state_shardings,
        init_params,
    )

    p_specs = pipeline3d_param_specs(cfg, pp_axis, model_axis)
    p_shard = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), p_specs,
        is_leaf=lambda x: isinstance(x, P))
    replicated = NamedSharding(mesh, P())
    batch_shard = NamedSharding(mesh, P(data_axis, None))

    def init(key):
        params = split_qkv_weights(init_params(key, cfg), cfg)
        return params, optimizer.init(params)

    abstract3d = jax.eval_shape(
        lambda k: split_qkv_weights(init_params(k, cfg), cfg),
        jax.random.PRNGKey(0))
    o_shard = _opt_state_shardings(optimizer, abstract3d, p_specs, mesh,
                                   False)

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    init_jit = jax.jit(init, out_shardings=(p_shard, o_shard))
    step_jit = jax.jit(
        step,
        in_shardings=(p_shard, o_shard, batch_shard),
        out_shardings=(p_shard, o_shard, replicated),
        donate_argnums=(0, 1),
    )
    return init_jit, step_jit


def make_pipeline_loss(mesh: Mesh, cfg: ModelConfig,
                       num_microbatches: int, pp_axis: str = "pp",
                       remat: bool = False):
    """Build ``loss(params, tokens)`` pipelined over ``mesh``'s pp axis.

    params: the standard model pytree (model.init_params) — blocks shard
    over pp on the layer dim, embed/unembed/ln_f replicate.  tokens:
    [batch, seq+1] int32, batch divisible by num_microbatches.

    ``remat``: checkpoint each tick's stage forward — AD then stores
    only the inter-stage ppermute carries and recomputes the block
    activations in the backward (the GPipe memory lever; see module
    docstring).

    MoE configs fold the router balance/z losses in exactly like
    model.loss_and_metrics (weighted by cfg.moe_*_weight), so the
    pipelined loss stays comparable to the unpipelined one.
    """
    n_stages = mesh.shape[pp_axis]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"{cfg.n_layers} layers not divisible by {n_stages} stages")

    param_specs = pipeline_param_specs(cfg, pp_axis)
    stage_fwd = functools.partial(_stage_forward, cfg=cfg)
    if remat:
        stage_fwd = jax.checkpoint(stage_fwd)

    def local_loss(params, tokens):
        idx = jax.lax.axis_index(pp_axis)
        m = num_microbatches
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        b, s = inputs.shape
        mb = b // m
        x_mb = inputs.reshape(m, mb, s)

        embedded = params["embed"].astype(cfg.dtype)[x_mb]  # [m, mb, s, d]
        d = embedded.shape[-1]
        zeros = jnp.zeros((mb, s, d), cfg.dtype)

        def tick(t, carry):
            buf, outs, aux_sum = carry
            # Stage 0 ingests microbatch t (clamped; only used while
            # t < m); later stages consume the ring buffer.
            ingest = jax.lax.dynamic_index_in_dim(
                embedded, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
            x_in = jnp.where(idx == 0, ingest, buf)
            y, aux = stage_fwd(params["blocks"], x_in)
            # This stage is processing microbatch t - idx; its aux only
            # counts while that is a real microbatch (not bubble).
            stage_valid = jnp.logical_and(t - idx >= 0, t - idx < m)
            aux_sum = jax.tree.map(
                lambda acc, a: acc + jnp.where(stage_valid, a, 0.0),
                aux_sum, aux)
            # Last stage banks microbatch t-(P-1) when in range.
            out_t = t - (n_stages - 1)
            valid = jnp.logical_and(out_t >= 0, out_t < m)
            banked = jax.lax.dynamic_update_index_in_dim(
                outs, y.astype(outs.dtype), jnp.clip(out_t, 0, m - 1),
                axis=0)
            outs = jnp.where(valid, banked, outs)
            # Rotate activations one hop down the stage ring.
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            buf = jax.lax.ppermute(y, pp_axis, perm)
            return buf, outs, aux_sum

        buf0 = pvary(zeros, pp_axis)
        outs0 = pvary(jnp.zeros((m, mb, s, d), cfg.dtype), pp_axis)
        aux0 = jax.tree.map(
            lambda a: pvary(a, pp_axis),
            {"balance_loss": jnp.zeros((), jnp.float32),
             "z_loss": jnp.zeros((), jnp.float32)})
        _, outs, aux_sum = jax.lax.fori_loop(
            0, m + n_stages - 1, tick, (buf0, outs0, aux0))

        # Loss on the last stage only; psum shares it with the ring (and
        # gives every stage the same scalar, keeping grads correct).
        h = _rmsnorm(outs.reshape(m * mb, s, d), params["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", h,
                            params["unembed"].astype(cfg.dtype))
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, targets.reshape(m * mb, s)[..., None], axis=-1)
        local = jnp.where(idx == n_stages - 1, jnp.mean(nll), 0.0)
        loss = jax.lax.psum(local, pp_axis)
        if cfg.moe_experts is not None:
            # Each stage's aux_sum is Σ over its m microbatches of its
            # local-layer mean; psum over stages then / (m·P) recovers
            # the all-layer, all-microbatch mean — the same quantity
            # model.loss_and_metrics reports.
            aux = jax.tree.map(
                lambda a: jax.lax.psum(a, pp_axis)
                / (m * n_stages), aux_sum)
            loss = (loss + cfg.moe_balance_weight * aux["balance_loss"]
                    + cfg.moe_z_weight * aux["z_loss"])
        return loss

    sharded = jax.shard_map(
        local_loss, mesh=mesh,
        in_specs=(param_specs, P()), out_specs=P())

    @functools.wraps(sharded)
    def loss(params, tokens):
        return sharded(params, tokens)

    return loss


def make_pipeline_train_step(mesh: Mesh, cfg: ModelConfig,
                             num_microbatches: int, pp_axis: str = "pp",
                             learning_rate: float = 1e-3,
                             train: TrainConfig | None = None,
                             remat: bool = True):
    """Build (init_fn, step_fn) for GPipe training over ``mesh``'s pp
    axis: grads and the optimizer both live under the pp shardings, so
    each stage updates only the layer shard it owns (plus the small
    replicated embed/unembed/ln leaves).

    step_fn: (params, opt_state, tokens) -> (params, opt_state, loss),
    jitted with the pipeline in/out shardings; loss matches the
    unpipelined train step's (tests pin the parity).  ``remat`` defaults
    True — microbatch rematerialization is the point of pipelining at
    memory-bound scales.

    The optimizer recipe is the trainer's (model.make_optimizer):
    schedules, clipping and accumulation all apply unchanged because
    they act on the (stage-sharded) grads elementwise or via a global
    norm XLA computes with a cross-stage psum.

    A mesh carrying ``data``/``model`` axes alongside ``pp`` routes to
    the dp×pp×tp step (make_pipeline3d_train_step) — note its
    init_fn/step_fn work on the split-weight pytree.
    """
    if len(mesh.axis_names) > 1:
        others = [a for a in mesh.axis_names if a != pp_axis]
        if pp_axis not in mesh.axis_names or len(others) != 2:
            raise ValueError(
                f"pipeline meshes are either ({pp_axis!r},) or 3-axis "
                f"(data, {pp_axis!r}, model); got {mesh.axis_names} "
                "(make_pipeline_mesh builds the 3-axis form)")
        model_axis = "model" if "model" in others else others[-1]
        others.remove(model_axis)
        return make_pipeline3d_train_step(
            mesh, cfg, num_microbatches, pp_axis,
            data_axis=others[0], model_axis=model_axis,
            learning_rate=learning_rate, train=train, remat=remat)
    from tpu_autoscaler.workloads.model import (
        init_params,
        opt_state_shardings,
    )

    if train is None:
        train = TrainConfig(learning_rate=learning_rate)
    optimizer = make_optimizer(train)
    loss_fn = make_pipeline_loss(mesh, cfg, num_microbatches, pp_axis,
                                 remat=remat)
    p_specs = pipeline_param_specs(cfg, pp_axis)
    p_shard = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), p_specs,
        is_leaf=lambda x: isinstance(x, P))
    replicated = NamedSharding(mesh, P())
    o_shard = opt_state_shardings(cfg, optimizer, p_specs, mesh, False)

    def init(key):
        params = init_params(key, cfg)
        return params, optimizer.init(params)

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    init_jit = jax.jit(init, out_shardings=(p_shard, o_shard))
    step_jit = jax.jit(
        step,
        in_shardings=(p_shard, o_shard, replicated),
        out_shardings=(p_shard, o_shard, replicated),
        donate_argnums=(0, 1),
    )
    return init_jit, step_jit
