"""Pipeline parallelism (pp): layers split across a mesh axis.

Completes the workload's parallelism portfolio (dp/tp in model.py, sp in
ring_attention.py, ep in moe.py): the transformer's stacked layer params
shard over the ``pp`` axis on their leading (layer) dimension — stage i
holds layers [i·L/P, (i+1)·L/P) — and microbatches stream through the
stage ring via ``lax.ppermute``, GPipe-style.  The schedule is an ordinary
``lax.fori_loop`` inside ``shard_map``, so reverse-mode AD derives the
backward pipeline automatically (ppermute transposes to the reversed
ring); no hand-written 1F1B pass is needed at these scales.

Autoscaler relevance: a pp×dp job spans whole slices with the pp ring on
ICI — another communication pattern that must never be bisected, which is
why drains operate on whole slices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_autoscaler.workloads._shard_utils import pvary
from tpu_autoscaler.workloads.model import ModelConfig, _block, _rmsnorm


def _stage_forward(blocks: dict, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Run THIS stage's layer stack (leading dim = local layers)."""

    def body(x, layer):
        x, _aux = _block(x, layer, cfg)
        return x, None

    x, _ = jax.lax.scan(body, x, blocks)
    return x


def make_pipeline_loss(mesh: Mesh, cfg: ModelConfig,
                       num_microbatches: int, pp_axis: str = "pp"):
    """Build ``loss(params, tokens)`` pipelined over ``mesh``'s pp axis.

    params: the standard model pytree (model.init_params) — blocks shard
    over pp on the layer dim, embed/unembed/ln_f replicate.  tokens:
    [batch, seq+1] int32, batch divisible by num_microbatches.
    """
    n_stages = mesh.shape[pp_axis]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"{cfg.n_layers} layers not divisible by {n_stages} stages")

    block_specs = {
        "qkv": P(pp_axis, None, None), "attn_out": P(pp_axis, None, None),
        "w1": P(pp_axis, None, None), "w2": P(pp_axis, None, None),
        "ln1": P(pp_axis, None), "ln2": P(pp_axis, None),
    }
    param_specs = {"embed": P(None, None), "blocks": block_specs,
                   "ln_f": P(None), "unembed": P(None, None)}

    def local_loss(params, tokens):
        idx = jax.lax.axis_index(pp_axis)
        m = num_microbatches
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        b, s = inputs.shape
        mb = b // m
        x_mb = inputs.reshape(m, mb, s)

        embedded = params["embed"].astype(cfg.dtype)[x_mb]  # [m, mb, s, d]
        d = embedded.shape[-1]
        zeros = jnp.zeros((mb, s, d), cfg.dtype)

        def tick(t, carry):
            buf, outs = carry
            # Stage 0 ingests microbatch t (clamped; only used while
            # t < m); later stages consume the ring buffer.
            ingest = jax.lax.dynamic_index_in_dim(
                embedded, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
            x_in = jnp.where(idx == 0, ingest, buf)
            y = _stage_forward(params["blocks"], x_in, cfg)
            # Last stage banks microbatch t-(P-1) when in range.
            out_t = t - (n_stages - 1)
            valid = jnp.logical_and(out_t >= 0, out_t < m)
            banked = jax.lax.dynamic_update_index_in_dim(
                outs, y.astype(outs.dtype), jnp.clip(out_t, 0, m - 1),
                axis=0)
            outs = jnp.where(valid, banked, outs)
            # Rotate activations one hop down the stage ring.
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            buf = jax.lax.ppermute(y, pp_axis, perm)
            return buf, outs

        buf0 = pvary(zeros, pp_axis)
        outs0 = pvary(jnp.zeros((m, mb, s, d), cfg.dtype), pp_axis)
        _, outs = jax.lax.fori_loop(0, m + n_stages - 1, tick, (buf0, outs0))

        # Loss on the last stage only; psum shares it with the ring (and
        # gives every stage the same scalar, keeping grads correct).
        h = _rmsnorm(outs.reshape(m * mb, s, d), params["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", h,
                            params["unembed"].astype(cfg.dtype))
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, targets.reshape(m * mb, s)[..., None], axis=-1)
        local = jnp.where(idx == n_stages - 1, jnp.mean(nll), 0.0)
        return jax.lax.psum(local, pp_axis)

    sharded = jax.shard_map(
        local_loss, mesh=mesh,
        in_specs=(param_specs, P()), out_specs=P())

    @functools.wraps(sharded)
    def loss(params, tokens):
        return sharded(params, tokens)

    return loss
