"""Paged KV cache + batched prefill: the serving engine's memory system.

serving.py's SlotKVCache reserves ``slots x max_len`` HBM up front —
every admitted sequence pays for the longest possible sequence whether it
uses it or not, which caps concurrency at mixed lengths.  This module
replaces that reservation with the vLLM/PagedAttention design, re-shaped
for XLA's static-shape constraint:

- **PagedKVCache**: one global pool of fixed-size blocks
  (``k, v: [layers, num_blocks, kv_heads, block_size, head_dim]``).  A
  sequence owns a *block table* — the list of pool blocks holding its
  keys in order.  HBM cost per sequence is ceil(len / block_size) blocks,
  not max_len.
- **Host-side allocator, device-side data**: block allocation/free is
  host scheduling (BlockAllocator's free list); the compiled programs
  receive block tables as traced int32 inputs.  No device-side shape
  ever depends on occupancy — admission, growth, eviction, and
  preemption all happen without recompilation.
- **On-demand growth + preemption**: blocks are allocated as sequences
  cross block boundaries.  A full pool preempts the youngest sequence
  (its blocks free instantly; the request re-queues for a fresh
  prefill) — so the pool can be sized for the *expected* load, not the
  worst case, exactly the PagedAttention economics.
- **Batched prefill**: up to ``prefill_lanes`` prompts enter the cache
  per tick in ONE compiled program (serving.py admits one chunk per
  tick — a deep queue of short prompts serializes behind it).  Each
  lane scatters its chunk into its own pages and attends with its own
  causal+window mask.

The decode/prefill reads gather each row's pages into a contiguous
[row, kv_heads, len, head_dim] view and then reuse the SAME per-row-
length attention as the linear engine (flash_decode's SMEM lengths on
TPU, the einsum mask elsewhere) — greedy decoding is bit-exact vs the
linear cache, which the parity tests assert.

Reference: the reference repo has no serving stack at all (SURVEY §3);
this extends the beyond-parity serving story of serving.py (VERDICT r4
item 3).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpu_autoscaler.workloads.model import (
    ModelConfig,
    _rmsnorm,
    _split_qkv,
)
from tpu_autoscaler.workloads.serving import (
    ContinuousBatcher,
    Request,
    _rope_rows,
    _slot_attend,
)

__all__ = ["PagedKVCache", "BlockAllocator", "PagedBatcher", "Request",
           "make_paged_decode_step", "make_paged_prefill"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """Global block pool + per-slot block tables.

    k, v: [layers, num_blocks, kv_heads, block_size, head_dim].
    lengths: [slots] int32 — logical sequence length per slot.
    Block tables live HOST-side in the engine (numpy) and enter each
    compiled call as arguments; the pool itself is the only large
    device buffer.
    """

    k: jax.Array
    v: jax.Array
    lengths: jax.Array

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @classmethod
    def zeros(cls, cfg: ModelConfig, num_blocks: int,
              block_size: int) -> "PagedKVCache":
        shape = (cfg.n_layers, num_blocks, cfg.kv_heads, block_size,
                 cfg.head_dim)
        return cls(k=jnp.zeros(shape, cfg.dtype),
                   v=jnp.zeros(shape, cfg.dtype),
                   lengths=jnp.zeros((0,), jnp.int32))  # set by engine


class BlockAllocator:
    """Host-side free list over the pool.  ``-1`` in a block table means
    "no block" — compiled programs turn it into an out-of-range index
    whose reads are masked by the per-row length and whose writes drop
    (jnp ``mode='drop'`` semantics)."""

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, -1, -1))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    def alloc(self) -> int | None:
        return self._free.pop() if self._free else None

    def free(self, blocks) -> None:
        for b in blocks:
            if b >= 0:
                self._free.append(int(b))


def _gather_rows(pool, tables):
    """[L?, nb, hkv, bs, hd] pool + [rows, tpr] tables ->
    [rows, hkv, tpr*bs, hd] contiguous per-row caches (one layer's
    pool: [nb, hkv, bs, hd]).  Table entries < 0 read block 0 — their
    positions sit at/after the row's length, so attention never looks
    at them and writes never target them."""
    nb, hkv, bs, hd = pool.shape
    safe = jnp.clip(tables, 0, nb - 1)
    rows_blocks = pool[safe]                     # [rows, tpr, hkv, bs, hd]
    rows, tpr = tables.shape
    return rows_blocks.transpose(0, 2, 1, 3, 4).reshape(
        rows, hkv, tpr * bs, hd)


def _scatter_token(pool, new, tables, positions, active):
    """Write one token per row into the pool.  pool [nb, hkv, bs, hd];
    new [rows, hkv, 1, hd]; positions [rows] absolute; active [rows]
    bool.  Inactive rows (or rows whose block table has no block at the
    position — cannot happen when the engine allocates ahead) drop."""
    nb, hkv, bs, hd = pool.shape
    block_idx = jnp.clip(positions // bs, 0, tables.shape[1] - 1)
    block = jnp.take_along_axis(tables, block_idx[:, None], axis=1)[:, 0]
    block = jnp.where(active & (block >= 0), block, nb)  # nb => drop
    off = positions % bs
    return pool.at[block, :, off, :].set(new[:, :, 0, :], mode="drop")


def _scatter_chunk(pool, new, table_row, offset, n_valid):
    """Write one lane's prefill chunk into its pages.  pool
    [nb, hkv, bs, hd]; new [hkv, chunk, hd]; table_row [tpr];
    offset scalar (lane's length before the chunk); lanes drop entries
    past n_valid."""
    nb, hkv, bs, hd = pool.shape
    chunk = new.shape[1]
    i = jnp.arange(chunk)
    pos = offset + i
    block = table_row[jnp.clip(pos // bs, 0, table_row.shape[0] - 1)]
    block = jnp.where((i < n_valid) & (block >= 0), block, nb)
    off = pos % bs
    return pool.at[block, :, off, :].set(
        new.transpose(1, 0, 2), mode="drop")


def _paged_attend(q, k_pool, v_pool, tables, new_len, cfg: ModelConfig,
                  mesh):
    """The paged cache read for one decode layer.

    On TPU (and under interpret for tests) the fused paged kernel
    (attention.paged_flash_decode) reads each row's pool blocks IN
    PLACE through the scalar-prefetched block table — no contiguous
    gather copy, so the decode step's HBM traffic is exactly the live
    cache bytes.  Under a TP mesh the kernel shard_maps with KV heads
    on 'model' (pool block dim + tables replicate).  Everywhere else:
    gather the rows and reuse the linear engine's per-row attention
    (_slot_attend — einsum mask or linear flash kernel)."""
    if cfg.resolved_attention() == "pallas":
        from tpu_autoscaler.workloads.attention import paged_flash_decode

        interpret = jax.default_backend() != "tpu"
        if mesh is None or mesh.size == 1:
            return paged_flash_decode(
                q, k_pool, v_pool, tables, new_len,
                window=cfg.attention_window, interpret=interpret)
        # Head divisibility is already enforced upstream: the step
        # builders run cfg.resolved_for_mesh(mesh), which rejects an
        # unshardable explicit 'pallas' and downgrades 'auto'.
        tp_only = all(mesh.shape[a] == 1 for a in mesh.axis_names
                      if a != "model")
        if tp_only and "model" in mesh.axis_names:
            from jax.sharding import PartitionSpec as P

            hspec = P(None, "model", None, None)

            def kern(q, kp, vp, tb, ln):
                return paged_flash_decode(
                    q, kp, vp, tb, ln, window=cfg.attention_window,
                    interpret=interpret)

            return jax.shard_map(
                kern, mesh=mesh,
                in_specs=(hspec, hspec, hspec, P(), P()),
                out_specs=hspec, check_vma=False)(
                    q, k_pool, v_pool, tables, new_len)
    k_rows = _gather_rows(k_pool, tables)
    v_rows = _gather_rows(v_pool, tables)
    return _slot_attend(q, k_rows, v_rows, new_len, cfg, mesh)


def make_paged_decode_step(cfg: ModelConfig, tokens_per_row: int,
                           mesh=None):
    """Build ``step(params, cache, tables, tokens, active) -> (logits,
    cache)``: one token for every slot, reading/writing through the
    block tables.  tables: [slots, tokens_per_row // block_size] int32.

    The cache read gathers each row's pages into a contiguous view and
    runs the same per-row-length kernel as the linear engine
    (flash_decode on TPU via _slot_attend) — bit-exact parity with
    serving.py's decode step.

    ``mesh``: tensor-parallel serving shards KV heads over 'model' and
    replicates the pool's block dim + the slot rows (the pool is shared
    state across all slots, so slots cannot shard over data axes the
    way the linear cache's rows do; data-parallel serving runs one
    engine per replica instead — see PagedBatcher docstring).
    """
    if mesh is not None:
        cfg = cfg.resolved_for_mesh(mesh)

    def step(params, cache: PagedKVCache, tables, tokens, active):
        from tpu_autoscaler.workloads.model import _ffn_residual

        x = params["embed"].astype(cfg.dtype)[tokens][:, None, :]
        positions = cache.lengths                          # [slots]

        def body(carry, inputs):
            x = carry
            layer, k_pool, v_pool = inputs
            b, s, d = x.shape
            y = _rmsnorm(x, layer["ln1"])
            q, k, v = _split_qkv(y, layer["qkv"], cfg)
            if cfg.rope:
                q = _rope_rows(q, cfg.rope_theta, positions)
                k = _rope_rows(k, cfg.rope_theta, positions)
            k_pool = _scatter_token(k_pool, k, tables, positions, active)
            v_pool = _scatter_token(v_pool, v, tables, positions, active)
            attn = _paged_attend(q, k_pool, v_pool, tables,
                                 positions + 1, cfg, mesh)
            attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
            x = x + jnp.einsum("bsd,de->bse", attn,
                               layer["attn_out"].astype(cfg.dtype))
            y = _rmsnorm(x, layer["ln2"])
            return _ffn_residual(x, y, layer, cfg), (k_pool, v_pool)

        x, (k_new, v_new) = jax.lax.scan(
            body, x, (params["blocks"], cache.k, cache.v))
        x = _rmsnorm(x, params["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", x,
                            params["unembed"].astype(cfg.dtype))
        new_cache = PagedKVCache(
            k=k_new, v=v_new,
            lengths=cache.lengths + active.astype(jnp.int32))
        return logits[:, 0].astype(jnp.float32), new_cache

    if mesh is None:
        return jax.jit(step)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_autoscaler.workloads.model import param_specs

    tp_ok = "model" in mesh.axis_names
    kv = P(None, None, "model" if tp_ok else None, None, None)
    cache_shard = PagedKVCache(
        k=NamedSharding(mesh, kv), v=NamedSharding(mesh, kv),
        lengths=NamedSharding(mesh, P()))
    p_shard = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P))
    repl = NamedSharding(mesh, P())
    return jax.jit(step,
                   in_shardings=(p_shard, cache_shard, repl, repl, repl),
                   out_shardings=(repl, cache_shard))


def make_paged_prefill(cfg: ModelConfig, chunk: int, lanes: int,
                       tokens_per_row: int, mesh=None,
                       return_all_logits: bool = False):
    """Build ``fill(params, cache, tables, tokens, offsets, n_valid) ->
    (logits, cache)``: append one chunk to EACH of ``lanes`` prompts in
    one compiled program.

    tables:  [lanes, tokens_per_row // block_size] — each lane's pages.
    tokens:  [lanes, chunk] int32 (padded past n_valid).
    offsets: [lanes] int32 — lane's length before this chunk.
    n_valid: [lanes] int32 — real tokens this chunk (0 = inactive lane).

    Returns logits [lanes, vocab] at each lane's last valid position
    (the generation seed when the lane just finished its prompt) and
    the updated pool.  serving.py admits ONE chunk per tick — this is
    the batched-admission fix (VERDICT r4 item 3): a burst of short
    prompts admits together instead of serializing, and a long prompt
    no longer blocks the queue behind its full length.

    ``return_all_logits=True``: return [lanes, chunk, vocab] instead —
    every appended position's logits, the VERIFICATION primitive for
    in-engine speculative decoding (spec_serving.py): one call scores
    each slot's [pending, d1..dk] block against the target.
    """
    if mesh is not None:
        cfg = cfg.resolved_for_mesh(mesh)

    def fill(params, cache: PagedKVCache, tables, tokens, offsets,
             n_valid):
        from tpu_autoscaler.workloads.model import _ffn_residual

        x = params["embed"].astype(cfg.dtype)[tokens]     # [lanes, chunk, d]

        def body(carry, inputs):
            x = carry
            layer, k_pool, v_pool = inputs
            b, s, d = x.shape
            y = _rmsnorm(x, layer["ln1"])
            q, k, v = _split_qkv(y, layer["qkv"], cfg)     # [b, h, s, hd]
            if cfg.rope:
                q = _rope_rows(q, cfg.rope_theta, offsets)
                k = _rope_rows(k, cfg.rope_theta, offsets)
            k_pool = jax.lax.fori_loop(
                0, b, lambda i, p: _scatter_chunk(
                    p, k[i], tables[i], offsets[i], n_valid[i]), k_pool)
            v_pool = jax.lax.fori_loop(
                0, b, lambda i, p: _scatter_chunk(
                    p, v[i], tables[i], offsets[i], n_valid[i]), v_pool)
            # Attend: each lane over its own gathered pages; causal
            # within the chunk plus everything before the offset.
            k_rows = _gather_rows(k_pool, tables)  # [lanes, hkv, T, hd]
            v_rows = _gather_rows(v_pool, tables)
            hkv = k_rows.shape[1]
            hd = cfg.head_dim
            max_len = k_rows.shape[2]
            qg = q.reshape(b, hkv, cfg.n_heads // hkv, s, hd)
            scores = jnp.einsum("bngqd,bnkd->bngqk", qg,
                                k_rows) * hd ** -0.5
            qpos = offsets[:, None] + jnp.arange(s)[None, :]   # [b, s]
            kpos = jnp.arange(max_len)
            visible = kpos[None, None, :] <= qpos[..., None]   # [b,s,T]
            if cfg.attention_window is not None:
                visible &= kpos[None, None, :] > (
                    qpos[..., None] - cfg.attention_window)
            scores = jnp.where(visible[:, None, None],
                               scores.astype(jnp.float32), -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
            attn = jnp.einsum("bngqk,bnkd->bngqd", probs,
                              v_rows).reshape(b, cfg.n_heads, s, hd)
            attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
            x = x + jnp.einsum("bsd,de->bse", attn,
                               layer["attn_out"].astype(cfg.dtype))
            y = _rmsnorm(x, layer["ln2"])
            return _ffn_residual(x, y, layer, cfg), (k_pool, v_pool)

        x, (k_new, v_new) = jax.lax.scan(
            body, x, (params["blocks"], cache.k, cache.v))
        x = _rmsnorm(x, params["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", x,
                            params["unembed"].astype(cfg.dtype))
        new_cache = PagedKVCache(k=k_new, v=v_new, lengths=cache.lengths)
        if return_all_logits:
            return logits.astype(jnp.float32), new_cache
        last = jnp.take_along_axis(
            logits, jnp.maximum(n_valid - 1, 0)[:, None, None], axis=1
        )[:, 0]                                            # [lanes, vocab]
        return last.astype(jnp.float32), new_cache

    if mesh is None:
        return jax.jit(fill)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_autoscaler.workloads.model import param_specs

    tp_ok = "model" in mesh.axis_names
    kv = P(None, None, "model" if tp_ok else None, None, None)
    cache_shard = PagedKVCache(
        k=NamedSharding(mesh, kv), v=NamedSharding(mesh, kv),
        lengths=NamedSharding(mesh, P()))
    p_shard = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P))
    repl = NamedSharding(mesh, P())
    return jax.jit(fill,
                   in_shardings=(p_shard, cache_shard, repl, repl, repl,
                                 repl),
                   out_shardings=(repl, cache_shard))


class PagedBatcher(ContinuousBatcher):
    """Continuous batching over the paged cache.

    Differences from the linear ContinuousBatcher it subclasses:

    - HBM is the POOL (``num_blocks * block_size`` token-slots shared by
      all sequences), not slots x max_len.  ``slots`` bounds concurrent
      sequences; memory bounds them only through actual usage.
    - Admission allocates blocks for the prompt only; decode grows a
      sequence block-by-block as it crosses block boundaries.
    - Pool exhaustion preempts the YOUNGEST sequence (fewest generated
      tokens — the cheapest prefill to redo): its blocks free
      immediately and its request re-queues, un-done.  Head-of-line
      sequences therefore always complete (no deadlock).
    - Up to ``prefill_lanes`` prompts prefill per tick in one program.

    Tensor-parallel serving passes ``mesh`` (KV heads shard over
    'model'); for data-parallel serving run one engine per replica —
    the pool is shared mutable state across slots, which is exactly
    what data sharding cannot cut.
    """

    def __init__(self, params, cfg: ModelConfig, *, slots: int = 4,
                 max_len: int = 256, block_size: int = 16,
                 num_blocks: int | None = None, chunk: int = 32,
                 prefill_lanes: int = 2, mesh=None, key=None,
                 slo_ticks: int | None = None, reqtrace=None):
        if max_len % block_size:
            raise ValueError(f"max_len {max_len} must be a multiple of "
                             f"block_size {block_size}")
        # Paged geometry must exist before the parent's init calls our
        # _build_device_state override.
        self.block_size = block_size
        self.tokens_per_row = max_len
        self.blocks_per_row = max_len // block_size
        self._num_blocks = (num_blocks if num_blocks is not None
                            else slots * self.blocks_per_row)
        self.prefill_lanes = prefill_lanes
        self.preemptions = 0
        super().__init__(params, cfg, slots=slots, max_len=max_len,
                         chunk=chunk, mesh=mesh, key=key, ring=False,
                         slo_ticks=slo_ticks, reqtrace=reqtrace)

    def _build_device_state(self, cfg, slots, max_len, chunk, mesh,
                            ring) -> None:
        self.allocator = BlockAllocator(self._num_blocks)
        self.tables = np.full((slots, self.blocks_per_row), -1, np.int32)
        run_cfg = cfg.resolved_for_mesh(mesh) if mesh is not None else cfg
        pool = PagedKVCache.zeros(run_cfg, self._num_blocks,
                                  self.block_size)
        self.cache = PagedKVCache(
            k=pool.k, v=pool.v, lengths=jnp.zeros((slots,), jnp.int32))
        self._decode = make_paged_decode_step(cfg, max_len, mesh)
        self._prefill = make_paged_prefill(cfg, chunk,
                                           self.prefill_lanes, max_len,
                                           mesh)

    def submit(self, request: Request) -> None:
        """Linear-engine validation plus the pool-feasibility check: a
        request whose worst-case footprint exceeds the WHOLE pool could
        never run even alone — without this it would self-preempt in a
        loop (admit → grow → preempt itself → re-queue) forever."""
        need_blocks = -(-(len(request.prompt) + request.max_new_tokens)
                        // self.block_size)
        if need_blocks > self.allocator.num_blocks:
            raise ValueError(
                f"request needs {need_blocks} blocks "
                f"({len(request.prompt)} prompt + "
                f"{request.max_new_tokens} new at block_size "
                f"{self.block_size}) but the pool holds only "
                f"{self.allocator.num_blocks}; it can never be "
                "scheduled")
        super().submit(request)

    # ---- accounting ----------------------------------------------------

    def live_tokens(self) -> int:
        lengths = np.asarray(self.cache.lengths)
        return int(sum(
            int(lengths[i]) for i, s in enumerate(self._slots)
            if s.request is not None))

    def check_accounting(self) -> None:
        """The paged invariant: allocated blocks cover live tokens with
        less than one block of slack per live sequence (+ the blocks
        pre-allocated for in-flight prefill chunks)."""
        live = self.live_tokens()
        used = self.allocator.used_blocks * self.block_size
        live_seqs = sum(1 for s in self._slots if s.request is not None)
        slack = live_seqs * (self.block_size + self.chunk)
        assert used <= live + slack, (
            f"paged accounting violated: {used} token-slots allocated "
            f"for {live} live tokens (+{slack} slack)")
        # And the free list + tables agree with the pool size.
        table_blocks = int((self.tables >= 0).sum())
        assert table_blocks == self.allocator.used_blocks, (
            f"table/allocator divergence: {table_blocks} vs "
            f"{self.allocator.used_blocks}")

    # ---- block management ----------------------------------------------

    def _ensure_blocks(self, i: int, upto_tokens: int) -> bool:
        """Grow slot i's table to cover ``upto_tokens`` positions;
        False when the pool is exhausted (caller preempts)."""
        need = int(np.ceil(upto_tokens / self.block_size))
        row = self.tables[i]
        have = int((row >= 0).sum())
        while have < need:
            b = self.allocator.alloc()
            if b is None:
                return False
            row[have] = b
            have += 1
        return True

    def _release_slot(self, i: int) -> None:
        self.allocator.free(self.tables[i][self.tables[i] >= 0])
        self.tables[i] = -1
        self.cache = PagedKVCache(
            k=self.cache.k, v=self.cache.v,
            lengths=self.cache.lengths.at[i].set(0))

    def _finish_if_done(self, i: int) -> None:
        before = self._slots[i].request
        super()._finish_if_done(i)
        if before is not None and self._slots[i].request is None:
            self._release_slot(i)

    def _preempt_youngest(self) -> bool:
        """Evict the live sequence with the fewest generated tokens back
        to the queue (cheapest re-prefill); False if none is live."""
        candidates = [
            (len(s.request.generated), i)
            for i, s in enumerate(self._slots) if s.request is not None]
        if not candidates:
            return False
        _, i = min(candidates)
        self._preempt_slot(i)
        return True

    def _preempt_slot(self, i: int) -> None:
        """Evict slot i's sequence back to the queue head: its request
        restarts from a fresh prefill; every block frees immediately."""
        slot = self._slots[i]
        req = slot.request
        # Reset request progress: it will re-prefill from scratch.
        req.generated.clear()
        req.done = False
        req.preempted_tick = self.ticks
        self._queue.insert(0, req)
        slot.request = None
        slot.remaining_prompt = None
        slot.seeded = False
        self._has_pending[i] = False
        self._release_slot(i)
        self.preemptions += 1
        self._stats.note_preempt()
        if self._reqtrace is not None and req.request_id is not None:
            self._reqtrace.note_preempt(req.request_id, self.ticks)

    # ---- engine loop ---------------------------------------------------

    def _admit(self) -> None:
        if getattr(self, "draining", False):
            return
        for i, slot in enumerate(self._slots):
            if slot.request is None and self._queue:
                req = self._queue[0]
                # Admission only needs the FIRST chunk's blocks; growth
                # is on-demand.  If even that fails, return the partial
                # allocation and stop admitting — decode progress will
                # free blocks.
                first = min(self.chunk, len(req.prompt))
                if not self._ensure_blocks(i, first):
                    self._release_slot(i)
                    return
                self._queue.pop(0)
                slot.request = req
                slot.remaining_prompt = np.asarray(req.prompt, np.int32)
                slot.seeded = False
                self._has_pending[i] = False
                self._stats.note_admit()
                self._note_admitted(req)
                self.cache = PagedKVCache(
                    k=self.cache.k, v=self.cache.v,
                    lengths=self.cache.lengths.at[i].set(0))

    def _kv_usage(self) -> tuple[int, int]:
        """Pool-block accounting: the paged engine's real KV pressure
        is allocator occupancy, not per-slot logical length."""
        return (self.allocator.used_blocks * self.block_size,
                self.allocator.num_blocks * self.block_size)

    def _tick(self) -> None:
        """One engine step: admit, one BATCHED prefill over up to
        ``prefill_lanes`` slots still holding prompt, then one batched
        decode step for every slot with a pending token.  The two
        device phases are overridable hooks (spec_serving.py replaces
        the decode phase with draft-propose/target-verify rounds and
        mirrors the prefill into the draft cache)."""
        self._admit()
        self.ticks += 1
        served = self._prefill_phase()
        self._after_prefill(served)
        if not self._has_pending.any():
            return
        self._decode_phase()

    def _after_prefill(self, served: list) -> None:
        """Hook: called with the prefill phase's served chunks
        ``[(slot, tokens, take, offset_before)]`` (possibly empty).
        Subclasses that mirror the prefill elsewhere (the draft cache)
        must do so BEFORE calling _prefill_finish, which may release
        completed slots."""
        self._prefill_finish(served)

    def _prefill_phase(self) -> list:
        # ---- batched prefill over up to `lanes` slots ----
        lanes: list[int] = []
        for i, slot in enumerate(self._slots):
            if len(lanes) == self.prefill_lanes:
                break
            if slot.request is None or slot.remaining_prompt is None \
                    or len(slot.remaining_prompt) == 0:
                continue
            take = min(self.chunk, len(slot.remaining_prompt))
            upto = int(np.asarray(self.cache.lengths[i])) + take
            while not self._ensure_blocks(i, upto):
                if not self._preempt_youngest():
                    break
                if self._slots[i].request is None:
                    break  # preempted ourselves: lane skipped
            if self._slots[i].request is None or not self._ensure_blocks(
                    i, upto):
                continue
            lanes.append(i)
        # A LATER lane's block pressure may have preempted an EARLIER
        # collected lane (youngest-first victim choice): drop lanes
        # whose slot no longer holds a request.
        lanes = [i for i in lanes
                 if self._slots[i].request is not None
                 and self._slots[i].remaining_prompt is not None]
        served: list = []
        if lanes:
            tok = np.zeros((self.prefill_lanes, self.chunk), np.int32)
            offs = np.zeros((self.prefill_lanes,), np.int32)
            nval = np.zeros((self.prefill_lanes,), np.int32)
            tabs = np.zeros((self.prefill_lanes, self.blocks_per_row),
                            np.int32) - 1
            takes = {}
            lengths_now = np.asarray(self.cache.lengths)
            for lane, i in enumerate(lanes):
                slot = self._slots[i]
                take = min(self.chunk, len(slot.remaining_prompt))
                tok[lane, :take] = slot.remaining_prompt[:take]
                offs[lane] = lengths_now[i]
                nval[lane] = take
                tabs[lane] = self.tables[i]
                takes[i] = take
                served.append((i, tok[lane].copy(), take,
                               int(lengths_now[i])))
            logits, self.cache = self._prefill(
                self.params, self.cache, jnp.asarray(tabs),
                jnp.asarray(tok), jnp.asarray(offs), jnp.asarray(nval))
            # Host-side length advance (the prefill program can't: its
            # lanes are a view, not the slot axis).
            new_lengths = self.cache.lengths
            for lane, i in enumerate(lanes):
                slot = self._slots[i]
                take = takes[i]
                slot.remaining_prompt = slot.remaining_prompt[take:]
                new_lengths = new_lengths.at[i].add(take)
                if len(slot.remaining_prompt) == 0:
                    tokn = self._sample_host(np.asarray(logits[lane]),
                                             slot.request)
                    slot.request.generated.append(tokn)
                    slot.seeded = True
                    self._note_seeded(slot.request)
                    self._pending_token[i] = tokn
                    self._has_pending[i] = True
            self.cache = PagedKVCache(
                k=self.cache.k, v=self.cache.v, lengths=new_lengths)
        return served

    def _prefill_finish(self, served: list) -> None:
        """Completion checks for just-seeded prompt lanes (separated so
        subclasses mirror the prefill BEFORE slots can be released)."""
        for i, _, _, _ in served:
            self._finish_if_done(i)

    def _decode_phase(self) -> None:
        # ---- grow-then-decode ----
        lengths_now = np.asarray(self.cache.lengths)
        for i, slot in enumerate(self._slots):
            if not self._has_pending[i] or slot.request is None:
                continue
            while not self._ensure_blocks(i, int(lengths_now[i]) + 1):
                if not self._preempt_youngest():
                    raise RuntimeError(
                        "paged pool exhausted with nothing to preempt")
                if self._slots[i].request is None:
                    break  # we preempted ourselves; skip this row
        logits, self.cache = self._decode(
            self.params, self.cache, jnp.asarray(self.tables),
            jnp.asarray(self._pending_token),
            jnp.asarray(self._has_pending))
        temps = np.array(
            [s.request.temperature if s.request else 0.0
             for s in self._slots], np.float32)
        greedy = temps == 0.0
        toks = np.asarray(self._batch_sample(
            logits, self._next_key(), jnp.asarray(temps),
            jnp.asarray(greedy)))
        for i, slot in enumerate(self._slots):
            if not self._has_pending[i] or slot.request is None:
                continue
            self.decode_tokens += 1
            req = slot.request
            if req.top_k is not None or req.top_p is not None:
                tok = self._sample_host(np.asarray(logits[i]), req)
            else:
                tok = int(toks[i])
            req.generated.append(tok)
            self._pending_token[i] = tok
            self._finish_if_done(i)
