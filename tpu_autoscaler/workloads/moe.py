"""Expert parallelism (ep): a switch-style MoE FFN over a mesh axis.

The last of the workload's parallelism modes (dp/tp: model.py, sp:
ring_attention.py, pp: pipeline.py).  Experts shard over the ``ep`` axis —
each device owns E/ep experts — and tokens move to their expert and back
via two ``lax.all_to_all`` exchanges (the canonical MoE dispatch/combine,
riding ICI within a slice):

  route (top-1) → bucket by expert with capacity → all_to_all(dispatch)
  → local expert MLPs → all_to_all(combine) → gate-weighted unbucket.

Tokens over an expert's capacity are dropped (contribute zero — the
surrounding residual connection carries them), standard switch-transformer
semantics.  Differentiable end-to-end: all_to_all transposes to itself on
the reverse path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    d_model: int = 32
    d_ff: int = 64
    num_experts: int = 8
    capacity_factor: float = 1.25


def init_moe_params(key: jax.Array, cfg: MoeConfig) -> dict:
    k_r, k1, k2 = jax.random.split(key, 3)
    e, d, f = cfg.num_experts, cfg.d_model, cfg.d_ff
    return {
        "router": jax.random.normal(k_r, (d, e), jnp.float32) * 0.02,
        "w1": jax.random.normal(k1, (e, d, f), jnp.float32) * d ** -0.5,
        "w2": jax.random.normal(k2, (e, f, d), jnp.float32) * f ** -0.5,
    }


def moe_reference(params: dict, x: jax.Array,
                  capacity: int | None = None) -> jax.Array:
    """Unsharded oracle: top-1 routing, optional per-expert capacity."""
    n, d = x.shape
    e = params["router"].shape[1]
    logits = x @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(logits, axis=-1)                      # [n]
    gate = jnp.take_along_axis(probs, top[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(top, e, dtype=jnp.int32)
    rank = jnp.einsum("ne,ne->n", jnp.cumsum(onehot, axis=0) - 1,
                      onehot.astype(jnp.int32))
    keep = jnp.ones((n,), bool) if capacity is None else (rank < capacity)
    h = jax.nn.gelu(jnp.einsum("nd,ndf->nf", x, params["w1"][top]))
    out = jnp.einsum("nf,nfd->nd", h, params["w2"][top])
    return jnp.where(keep[:, None], gate[:, None] * out, 0.0)


def make_moe_layer(mesh: Mesh, cfg: MoeConfig, ep_axis: str = "ep"):
    """Build ``apply(params, x)`` with experts sharded over ``ep``.

    x: [tokens, d_model] sharded over ``ep`` on the token dim; params
    shard on the expert dim (router replicates).  Token count per device
    and expert count must divide the axis size.
    """
    ep = mesh.shape[ep_axis]
    if cfg.num_experts % ep:
        raise ValueError(
            f"{cfg.num_experts} experts not divisible by ep={ep}")
    e_loc = cfg.num_experts // ep

    def local_apply(params, x):
        n_loc, d = x.shape
        e = cfg.num_experts
        cap = max(1, int(cfg.capacity_factor * n_loc / e))

        logits = x @ params["router"]                       # [n_loc, e]
        probs = jax.nn.softmax(logits, axis=-1)
        top = jnp.argmax(logits, axis=-1)
        gate = jnp.take_along_axis(probs, top[:, None], axis=1)[:, 0]
        onehot = jax.nn.one_hot(top, e, dtype=jnp.int32)
        rank = jnp.einsum("ne,ne->n", jnp.cumsum(onehot, axis=0) - 1,
                          onehot)
        keep = rank < cap

        # Dispatch buffer [e, cap, d]: token n -> slot (top[n], rank[n]).
        safe_rank = jnp.where(keep, rank, 0)
        dispatch = jnp.zeros((e, cap, d), x.dtype)
        dispatch = dispatch.at[top, safe_rank].add(
            jnp.where(keep[:, None], x, 0.0))

        # To experts: [ep, e_loc, cap, d] -> exchange dim0 over the axis.
        buckets = dispatch.reshape(ep, e_loc, cap, d)
        received = jax.lax.all_to_all(buckets, ep_axis, split_axis=0,
                                      concat_axis=0, tiled=False)
        # received[src, e_loc, cap, d]: tokens from every source device for
        # MY experts.  params arrive pre-sharded under shard_map: w1/w2 are
        # the local [e_loc, ...] shards.
        h = jax.nn.gelu(
            jnp.einsum("seCd,edf->seCf", received, params["w1"]))
        expert_out = jnp.einsum("seCf,efd->seCd", h, params["w2"])

        # Back to sources: inverse exchange, restoring [e, cap, d] local.
        returned = jax.lax.all_to_all(expert_out, ep_axis, split_axis=0,
                                      concat_axis=0, tiled=False)
        combined = returned.reshape(e, cap, d)
        out = combined[top, safe_rank]                      # [n_loc, d]
        return jnp.where(keep[:, None], gate[:, None] * out, 0.0)

    # Router replicates; experts shard on their leading dim; tokens shard.
    p_specs = {"router": P(None, None), "w1": P(ep_axis, None, None),
               "w2": P(ep_axis, None, None)}
    return jax.shard_map(local_apply, mesh=mesh,
                         in_specs=(p_specs, P(ep_axis, None)),
                         out_specs=P(ep_axis, None))
