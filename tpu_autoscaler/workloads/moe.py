"""Expert parallelism (ep): a trainable top-k MoE FFN over a mesh axis.

One of the workload's parallelism modes (dp/tp: model.py, sp:
ring_attention.py, pp: pipeline.py).  Experts shard over the ``ep`` axis —
each device owns E/ep experts — and tokens move to their experts and back
via two ``lax.all_to_all`` exchanges (the canonical MoE dispatch/combine,
riding ICI within a slice):

  route (top-k) → bucket by expert with capacity → all_to_all(dispatch)
  → local expert MLPs → all_to_all(combine) → gate-weighted unbucket.

Tokens over an expert's capacity are dropped (contribute zero — the
surrounding residual connection carries them), standard switch-transformer
semantics.  Differentiable end-to-end: all_to_all transposes to itself on
the reverse path.

TRAINABLE, not just runnable: routing collapses onto one expert unless the
router is regularized, so the layer computes the two standard auxiliary
losses —

- **load-balance loss** (Switch/GShard): ``E * Σ_e f_e · p_e`` where
  ``f_e`` is the fraction of routed assignments hitting expert e and
  ``p_e`` the mean router probability of e.  Minimized exactly when both
  are uniform; keeps the dispatch balanced so capacity drops stay rare.
- **router z-loss** (ST-MoE): ``mean(logsumexp(logits)²)`` — bounds the
  router logit scale, which otherwise drifts up and saturates the
  softmax.

The flagship model's MoE blocks (model.py with ``moe_experts`` set) reuse
``route_topk`` so the two dispatch implementations cannot disagree on
routing semantics.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    d_model: int = 32
    d_ff: int = 64
    num_experts: int = 8
    capacity_factor: float = 1.25
    top_k: int = 1

    def __post_init__(self) -> None:
        if not 1 <= self.top_k <= self.num_experts:
            raise ValueError(
                f"top_k must be in [1, {self.num_experts}], got "
                f"{self.top_k}")


def init_moe_params(key: jax.Array, cfg: MoeConfig) -> dict:
    k_r, k1, k2 = jax.random.split(key, 3)
    e, d, f = cfg.num_experts, cfg.d_model, cfg.d_ff
    return {
        "router": jax.random.normal(k_r, (d, e), jnp.float32) * 0.02,
        "w1": jax.random.normal(k1, (e, d, f), jnp.float32) * d ** -0.5,
        "w2": jax.random.normal(k2, (e, f, d), jnp.float32) * f ** -0.5,
    }


def route_topk(logits: jax.Array, k: int, capacity: int):
    """THE routing rule, shared by every MoE impl in the tree.

    logits: [n, e] fp32 router scores for n tokens.  Returns
    ``(expert, rank, gate, keep, aux)`` each [n, k]:

    - ``expert[i, c]``: the c-th choice expert of token i;
    - ``rank[i, c]``: its slot within that expert's capacity buffer —
      choices are prioritized choice-major (all first choices before any
      second choice, GShard-style), then token-major;
    - ``gate[i, c]``: combine weight (softmax prob renormalized over the
      k choices);
    - ``keep[i, c]``: False when the expert was already at ``capacity``;
    - ``aux``: dict with the scalar ``balance_loss`` (Switch aux,
      E·Σ f_e·p_e over kept+dropped assignments) and ``z_loss``
      (mean logsumexp² of the raw logits), plus ``expert_fraction``
      [e] — the assignment histogram tests/benchmarks report.
    """
    n, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)                     # [n, k]
    if k == 1:
        # Switch-style: the raw router prob IS the gate — renormalizing
        # a single choice would pin it to 1.0 and cut the router out of
        # the gradient entirely.
        gate = topv
    else:
        # Mixtral/GShard-style: renormalize over the k choices.
        gate = topv / jnp.maximum(
            jnp.sum(topv, axis=-1, keepdims=True), 1e-9)

    onehot = jax.nn.one_hot(topi, e, dtype=jnp.int32)        # [n, k, e]
    # Slot of assignment (token i, choice c) within its expert: count
    # earlier choices of ALL tokens, then same-choice earlier tokens.
    per_choice = onehot.transpose(1, 0, 2)                   # [k, n, e]
    within = jnp.cumsum(per_choice, axis=1) - per_choice     # before me, same c
    prior_choices = jnp.cumsum(
        jnp.sum(per_choice, axis=1), axis=0) - jnp.sum(per_choice, axis=1)
    rank_full = within + prior_choices[:, None, :]           # [k, n, e]
    rank = jnp.sum(rank_full.transpose(1, 0, 2) * onehot, axis=-1)  # [n, k]
    keep = rank < capacity

    # Aux losses over the full (pre-capacity) assignment distribution.
    frac = jnp.mean(
        jnp.sum(onehot, axis=1).astype(jnp.float32), axis=0) / k  # [e]
    mean_prob = jnp.mean(probs, axis=0)                      # [e]
    balance = e * jnp.sum(frac * mean_prob)
    z = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    aux = {"balance_loss": balance, "z_loss": z, "expert_fraction": frac}
    return topi, rank.astype(jnp.int32), gate, keep, aux


def moe_reference(params: dict, x: jax.Array,
                  capacity: int | None = None,
                  top_k: int = 1) -> jax.Array:
    """Unsharded oracle: top-k routing, optional per-expert capacity."""
    n, d = x.shape
    e = params["router"].shape[1]
    logits = (x @ params["router"]).astype(jnp.float32)
    cap = capacity if capacity is not None else n * top_k
    expert, rank, gate, keep, _ = route_topk(logits, top_k, cap)
    out = jnp.zeros_like(x)
    for c in range(top_k):
        h = jax.nn.gelu(
            jnp.einsum("nd,ndf->nf", x, params["w1"][expert[:, c]]))
        o = jnp.einsum("nf,nfd->nd", h, params["w2"][expert[:, c]])
        out = out + jnp.where(keep[:, c, None],
                              gate[:, c, None].astype(o.dtype) * o, 0.0)
    return out.astype(x.dtype)


def make_moe_layer(mesh: Mesh, cfg: MoeConfig, ep_axis: str = "ep",
                   with_aux: bool = False):
    """Build ``apply(params, x)`` with experts sharded over ``ep``.

    x: [tokens, d_model] sharded over ``ep`` on the token dim; params
    shard on the expert dim (router replicates).  Token count per device
    and expert count must divide the axis size.

    ``with_aux=True``: apply returns ``(out, aux)`` where aux holds the
    mesh-averaged ``balance_loss`` / ``z_loss`` scalars and the global
    ``expert_fraction`` histogram — add the scalars (weighted) to the
    training loss to keep routing balanced.
    """
    ep = mesh.shape[ep_axis]
    if cfg.num_experts % ep:
        raise ValueError(
            f"{cfg.num_experts} experts not divisible by ep={ep}")
    e_loc = cfg.num_experts // ep

    def local_apply(params, x):
        n_loc, d = x.shape
        e, k = cfg.num_experts, cfg.top_k
        cap = max(1, int(cfg.capacity_factor * n_loc * k / e))

        logits = (x @ params["router"]).astype(jnp.float32)  # [n_loc, e]
        expert, rank, gate, keep, aux = route_topk(logits, k, cap)

        # Dispatch buffer [e, cap, d]: assignment (i, c) -> slot
        # (expert[i,c], rank[i,c]).  A token can occupy up to k slots
        # across different experts.
        safe_rank = jnp.where(keep, rank, 0)
        dispatch = jnp.zeros((e, cap, d), x.dtype)
        for c in range(k):
            dispatch = dispatch.at[expert[:, c], safe_rank[:, c]].add(
                jnp.where(keep[:, c, None], x, 0.0))

        # To experts: [ep, e_loc, cap, d] -> exchange dim0 over the axis.
        buckets = dispatch.reshape(ep, e_loc, cap, d)
        received = jax.lax.all_to_all(buckets, ep_axis, split_axis=0,
                                      concat_axis=0, tiled=False)
        # received[src, e_loc, cap, d]: tokens from every source device for
        # MY experts.  params arrive pre-sharded under shard_map: w1/w2 are
        # the local [e_loc, ...] shards.
        h = jax.nn.gelu(
            jnp.einsum("seCd,edf->seCf", received, params["w1"]))
        expert_out = jnp.einsum("seCf,efd->seCd", h, params["w2"])

        # Back to sources: inverse exchange, restoring [e, cap, d] local.
        returned = jax.lax.all_to_all(expert_out, ep_axis, split_axis=0,
                                      concat_axis=0, tiled=False)
        combined = returned.reshape(e, cap, d)
        out = jnp.zeros_like(x)
        for c in range(k):
            o = combined[expert[:, c], safe_rank[:, c]]      # [n_loc, d]
            # Cast the fp32 gate into the compute dtype: the combine
            # must not silently promote a bf16 residual stream to fp32.
            out = out + jnp.where(keep[:, c, None],
                                  gate[:, c, None].astype(o.dtype) * o,
                                  0.0)
        if not with_aux:
            return out
        # Mesh-wide aux: mean of the per-device scalars / histograms.
        mesh_aux = {
            key: jax.lax.pmean(val, ep_axis)
            for key, val in aux.items()
        }
        return out, mesh_aux

    # Router replicates; experts shard on their leading dim; tokens shard.
    p_specs = {"router": P(None, None), "w1": P(ep_axis, None, None),
               "w2": P(ep_axis, None, None)}
    out_specs = ((P(ep_axis, None),
                  {"balance_loss": P(), "z_loss": P(),
                   "expert_fraction": P()})
                 if with_aux else P(ep_axis, None))
    return jax.shard_map(local_apply, mesh=mesh,
                         in_specs=(p_specs, P(ep_axis, None)),
                         out_specs=out_specs)


def make_ep_mesh(devices=None, ep: int | None = None, tp: int = 1):
    """(data, ep) mesh for expert-parallel training: the batch shards
    over BOTH axes (every device is data-parallel for the dense ops);
    ``ep`` is additionally the expert-exchange axis for the MoE blocks.
    ``tp > 1`` appends a ``model`` axis — (data, ep, model) — for the
    dp×ep×tp composition: dense attention heads Megatron-shard over
    ``model`` and each expert's d_ff column/row-shards over it too.
    """
    import numpy as np

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp < 1 or tp > n:
        raise ValueError(f"tp={tp} must be in [1, {n}] for {n} devices")
    if ep is None:
        ep = n // tp
    if ep < 1 or n % (ep * tp):
        raise ValueError(
            f"{n} devices not divisible by ep*tp = {ep * tp}")
    if tp == 1:
        arr = np.asarray(devices).reshape(n // ep, ep)
        return Mesh(arr, axis_names=("data", "ep"))
    arr = np.asarray(devices).reshape(n // (ep * tp), ep, tp)
    return Mesh(arr, axis_names=("data", "ep", "model"))


def _ep_moe_ffn(y, layer, cfg, ep_axis: str, ep: int,
                model_axis: str | None = None):
    """Expert-parallel MoE FFN on this device's token pool: route over
    the LOCAL pool (capacity = capacity_factor·n_loc·k/E, pool-level
    GShard semantics, vs model.moe_ffn's per-row dispatch), all_to_all
    to the expert owners, local expert MLPs, all_to_all back,
    gate-weighted combine.  Returns (out, aux).

    ``model_axis``: each expert's d_ff additionally column/row-shards
    over it (w1 holds f/tp columns, w2 f/tp rows; one psum completes
    each expert's output before the return exchange) — expert compute
    and weights drop by tp on top of the ep sharding.

    Aux-loss estimator note: the balance loss E·Σ frac·p is NONLINEAR
    in (frac, p), so the pool-level estimate (product of pool means)
    differs from model.moe_ffn's per-row estimate (mean of per-row
    products) by the cross-row covariance — O(1e-2) unweighted on
    multi-row pools, zero when each pool is one row.  Both are
    legitimate GShard-style regularizers; parity tests pin exactness
    on 1-row pools and train-quality elsewhere."""
    b, s, d = y.shape
    n_loc = b * s
    e, k = cfg.moe_experts, cfg.moe_top_k
    e_loc = e // ep
    cap = max(1, int(cfg.moe_capacity_factor * n_loc * k / e))
    flat = y.reshape(n_loc, d)
    logits = jnp.einsum(
        "nd,de->ne", flat, layer["router"].astype(cfg.dtype)
    ).astype(jnp.float32)
    expert, rank, gate, keep, aux = route_topk(logits, k, cap)

    safe_rank = jnp.where(keep, rank, 0)
    dispatch = jnp.zeros((e, cap, d), flat.dtype)
    for c in range(k):
        dispatch = dispatch.at[expert[:, c], safe_rank[:, c]].add(
            jnp.where(keep[:, c, None], flat, 0.0))

    buckets = dispatch.reshape(ep, e_loc, cap, d)
    received = jax.lax.all_to_all(buckets, ep_axis, split_axis=0,
                                  concat_axis=0, tiled=False)
    w1 = layer["w1"].astype(cfg.dtype)   # local [e_loc, d, f(/tp)]
    w2 = layer["w2"].astype(cfg.dtype)
    h = jax.nn.gelu(jnp.einsum("seCd,edf->seCf", received, w1))
    expert_out = jnp.einsum("seCf,efd->seCd", h, w2)
    returned = jax.lax.all_to_all(expert_out, ep_axis, split_axis=0,
                                  concat_axis=0, tiled=False)
    combined = returned.reshape(e, cap, d)
    out = jnp.zeros_like(flat)
    for c in range(k):
        o = combined[expert[:, c], safe_rank[:, c]]
        out = out + jnp.where(keep[:, c, None],
                              gate[:, c, None].astype(o.dtype) * o, 0.0)
    if model_axis is not None:
        # Row-parallel completion of the expert outputs.  The return
        # all_to_all, the gather-by-rank combine, and the gate weights
        # are all LINEAR in expert_out, so the psum commutes to here —
        # reducing [n_loc, d] instead of the capacity_factor·k×-larger
        # [e, cap, d] buffer.
        out = jax.lax.psum(out, model_axis)
    return out.reshape(b, s, d), aux


def _ep_tp_block(x, layer, cfg, *, ep_axis: str, ep: int,
                 model_axis: str, tp: int, ep_ffn):
    """One block of the dp×ep×tp step: the SHARED full-seq TP attention
    (sp.py::tp_attention — flash or einsum per cfg, row-parallel psum)
    followed by the expert-parallel FFN with model-sharded expert
    d_ff."""
    from tpu_autoscaler.workloads.model import _rmsnorm
    from tpu_autoscaler.workloads.sp import tp_attention

    y = _rmsnorm(x, layer["ln1"])
    x = tp_attention(x, y, layer, cfg, model_axis=model_axis, tp=tp)
    y = _rmsnorm(x, layer["ln2"])
    out, aux = ep_ffn(y, layer)
    return x + out, aux


def make_ep_train_step(mesh: Mesh, cfg, *, train=None,
                       learning_rate: float = 1e-3,
                       data_axis: str = "data", ep_axis: str = "ep"):
    """Build (init_fn, step_fn) for dp×ep MoE training: the flagship
    model (cfg.moe_experts set) with expert weights sharded over
    ``ep_axis`` and the batch over BOTH mesh axes, in one jitted step.

    step_fn: (params, opt_state, tokens [b, s+1]) ->
    (params, opt_state, loss, metrics) — metrics carries the
    mesh-averaged ``balance_loss`` / ``z_loss`` and the global
    ``expert_fraction`` histogram (layer-meaned), the observability a
    trainable MoE needs.  Dense (non-expert) params replicate; expert
    w1/w2 (and their optimizer moments) shard on the expert dim, so
    per-device expert HBM drops by the ep degree — the lever that
    scales expert count past one chip.

    Routing uses pool-level capacity over each device's local tokens
    (GShard semantics); with ample ``moe_capacity_factor`` no token
    drops and the loss equals model.loss_fn's per-row-dispatch MoE
    exactly (tests pin it).
    """
    from tpu_autoscaler.workloads.model import (
        ModelConfig,
        TrainConfig,
        _block,
        _rmsnorm,
        init_params,
        make_optimizer,
        opt_state_shardings,
    )

    assert isinstance(cfg, ModelConfig)
    if cfg.moe_experts is None:
        raise ValueError("make_ep_train_step needs cfg.moe_experts set")
    ep = mesh.shape[ep_axis]
    if cfg.moe_experts % ep:
        raise ValueError(
            f"{cfg.moe_experts} experts not divisible by the {ep_axis} "
            f"axis ({ep})")
    model_axis = "model" if "model" in mesh.axis_names else None
    tp = mesh.shape[model_axis] if model_axis else 1
    if tp > 1:
        if cfg.n_heads % tp or cfg.kv_heads % tp:
            raise ValueError(
                f"ep×tp needs heads divisible by the {model_axis} axis "
                f"({tp}): got {cfg.n_heads} q / {cfg.kv_heads} kv heads")
        if cfg.d_ff % tp:
            raise ValueError(
                f"ep×tp needs d_ff ({cfg.d_ff}) divisible by the "
                f"{model_axis} axis ({tp})")
    if train is None:
        train = TrainConfig(learning_rate=learning_rate)
    optimizer = make_optimizer(train)

    def ep_ffn(y, layer):
        out, aux = _ep_moe_ffn(y, layer, cfg, ep_axis, ep,
                               model_axis if tp > 1 else None)
        return out, {"balance_loss": aux["balance_loss"],
                     "z_loss": aux["z_loss"],
                     "expert_fraction": aux["expert_fraction"]}

    if tp > 1:
        import functools

        block = functools.partial(
            _ep_tp_block, cfg=cfg, ep_axis=ep_axis, ep=ep,
            model_axis=model_axis, tp=tp, ep_ffn=ep_ffn)
    else:
        def block(x, layer):
            """model._block's attention path untouched (mesh=None: we
            are inside shard_map, attention is device-local) with the
            FFN half replaced by the expert-parallel dispatch via the
            ffn hook."""
            return _block(x, layer, cfg, mesh=None, ffn=ep_ffn)

    blk = jax.checkpoint(block) if cfg.remat else block

    def local_loss(params, inputs, targets):
        x = params["embed"].astype(cfg.dtype)[inputs]

        def body(x, layer):
            x, aux = blk(x, layer)
            return x, aux

        x, aux_stacked = jax.lax.scan(body, x, params["blocks"])
        aux = jax.tree.map(lambda a: jnp.mean(a, axis=0), aux_stacked)
        x = _rmsnorm(x, params["ln_f"])
        b_loc, s_loc = inputs.shape
        logits = jnp.einsum("bsd,dv->bsv", x,
                            params["unembed"].astype(cfg.dtype)
                            ).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        local_sum = jnp.sum(
            -jnp.take_along_axis(logp, targets[..., None], axis=-1))
        total = jax.lax.psum(local_sum, (data_axis, ep_axis))
        n_tok = (b_loc * s_loc * jax.lax.psum(1, data_axis)
                 * jax.lax.psum(1, ep_axis))
        ce = total / n_tok
        # Mesh-wide aux: mean over every device's local routing stats.
        aux = jax.tree.map(
            lambda a: jax.lax.pmean(a, (data_axis, ep_axis)), aux)
        loss = (ce + cfg.moe_balance_weight * aux["balance_loss"]
                + cfg.moe_z_weight * aux["z_loss"])
        return loss, {"ce": ce, **aux}

    # Expert weights shard over ep on the expert dim; under ep×tp each
    # expert's d_ff additionally column/row-shards over model.  Dense
    # weights replicate (under tp each rank slices its own head/d_ff
    # columns — the sp×tp approach, no split pytree needed).
    if tp > 1:
        w1_spec = P(None, ep_axis, None, model_axis)
        w2_spec = P(None, ep_axis, model_axis, None)
    else:
        w1_spec = P(None, ep_axis, None, None)
        w2_spec = P(None, ep_axis, None, None)
    p_specs = {
        "embed": P(None, None),
        "blocks": {
            "qkv": P(None, None, None),
            "attn_out": P(None, None, None),
            "router": P(None, None, None),
            "w1": w1_spec,
            "w2": w2_spec,
            "ln1": P(None, None), "ln2": P(None, None),
        },
        "ln_f": P(None),
        "unembed": P(None, None),
    }
    tok_spec = P((data_axis, ep_axis), None)
    metric_specs = {"ce": P(), "balance_loss": P(), "z_loss": P(),
                    "expert_fraction": P()}
    sharded_loss = jax.shard_map(
        local_loss, mesh=mesh,
        in_specs=(p_specs, tok_spec, tok_spec),
        out_specs=(P(), metric_specs), check_vma=False)

    def loss(params, tokens):
        return sharded_loss(params, tokens[:, :-1], tokens[:, 1:])

    def init(key):
        params = init_params(key, cfg)
        return params, optimizer.init(params)

    def step(params, opt_state, tokens):
        import optax

        (loss_val, metrics), grads = jax.value_and_grad(
            loss, has_aux=True)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss_val, metrics

    from jax.sharding import NamedSharding

    p_shard = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), p_specs,
        is_leaf=lambda x: isinstance(x, P))
    replicated = NamedSharding(mesh, P())
    batch_shard = NamedSharding(mesh, P((data_axis, ep_axis), None))
    metric_shard = {k: replicated for k in metric_specs}
    o_shard = opt_state_shardings(cfg, optimizer, p_specs, mesh, False)
    init_jit = jax.jit(init, out_shardings=(p_shard, o_shard))
    step_jit = jax.jit(
        step,
        in_shardings=(p_shard, o_shard, batch_shard),
        out_shardings=(p_shard, o_shard, replicated, metric_shard),
        donate_argnums=(0, 1),
    )
    return init_jit, step_jit
