"""Runnable continuous-batching server CLI.

``python -m tpu_autoscaler.workloads.serve --checkpoint-dir ...
--requests reqs.jsonl`` restores the latest trainer checkpoint and
drives the ContinuousBatcher (workloads/serving.py) over a batch of
mixed-length requests — the traffic-shaped counterpart of generate.py's
single fixed batch.  Requests are JSON lines:

    {"prompt": [3, 17, 4], "max_new_tokens": 16}
    {"prompt": [9], "max_new_tokens": 8, "temperature": 0.8,
     "top_k": 40, "eos_id": 0}

(or ``--random N`` synthesizes N random requests).  Output is one JSON
line per request, in submission order:

    {"id": 0, "prompt_len": 3, "tokens": [..generated..], "done": true}

followed by ONE machine-readable final-stats line — the drain
contract's receipt (ISSUE 9; typed as
``serving.drain.DrainReceipt`` since ISSUE 18, so the autoscaler's
``confirm_scale_in`` and the router's ``absorb_drain`` parse it with
per-field validation instead of duck-typing a log line; reclaim tests
assert ``unserved == 0`` from it):

    {"event": "final_stats", "served": N, "unserved": M,
     "drained": bool, "request_latency_ticks": [...], "stats": {...}}

``--final-stats PATH`` additionally writes the same object to a file
(the autoscaler side of a reclaim can collect it after exit).

Model flags must match the training run (shared block in _cli.py);
``--ring`` turns on the O(window) ring cache for windowed models.
"""

from __future__ import annotations

import json
import logging
import sys

import click

log = logging.getLogger(__name__)


from tpu_autoscaler.workloads._cli import model_arch_options, model_config


def final_stats_receipt(reqs, engine, elapsed_s: float,
                        replica_id: str = ""):
    """The drain contract's machine-readable receipt, built as the
    typed :class:`~tpu_autoscaler.serving.drain.DrainReceipt` (ISSUE
    18) so the emitter, the router migration path and the scaler's
    scale-in advice share one field-name definition: what was served,
    what was not, per-request latencies — split into queue-wait vs
    execute (ISSUE 14: ``submitted_tick`` survives preemption
    re-queues, so end-to-end latency alone hides requeue wait) — and
    the engine's final stats snapshot."""
    from tpu_autoscaler.serving.drain import DrainReceipt

    latencies = [
        (r.finished_tick - r.submitted_tick
         if r.done and r.finished_tick is not None
         and r.submitted_tick is not None else None)
        for r in reqs]
    # Queue-wait = submit -> FIRST admission; execute = everything
    # after (which still includes any requeue wait for preempted
    # requests — the aggregate requeue_wait_ticks_total in ``stats``
    # carries that remainder's split).
    waits = [
        (r.first_scheduled_tick - r.submitted_tick
         if r.first_scheduled_tick is not None
         and r.submitted_tick is not None else None)
        for r in reqs]
    execs = [
        (lat - w if lat is not None and w is not None else None)
        for lat, w in zip(latencies, waits)]
    return DrainReceipt(
        served=sum(1 for r in reqs if r.done),
        unserved=sum(1 for r in reqs if not r.done),
        drained=bool(getattr(engine, "draining", False)),
        elapsed_s=round(elapsed_s, 3),
        ticks=int(engine.ticks),
        decode_tokens=int(engine.decode_tokens),
        request_latency_ticks=tuple(latencies),
        request_wait_ticks=tuple(waits),
        request_exec_ticks=tuple(execs),
        stats=engine.stats().as_dict(),
        replica=replica_id)


def final_stats_payload(reqs, engine, elapsed_s: float,
                        replica_id: str = "") -> dict:
    """Wire-dict form of :func:`final_stats_receipt` (the historical
    key set; older consumers parse it unchanged)."""
    return final_stats_receipt(reqs, engine, elapsed_s,
                               replica_id).to_payload()


@click.command()
@click.option("--checkpoint-dir", default="/tmp/tpu-train-ckpt",
              show_default=True)
@click.option("--requests", "requests_file", default=None,
              help="JSONL file of requests (see module docstring); "
                   "'-' reads stdin.")
@click.option("--random", "random_n", default=None, type=int,
              help="Synthesize N random requests instead of --requests.")
@click.option("--max-new-tokens", default=16, show_default=True,
              help="Default/maximum for --random requests.")
@click.option("--slots", default=4, show_default=True,
              help="Concurrent sequences resident in the cache.")
@click.option("--max-len", default=256, show_default=True,
              help="Per-slot cache capacity (prompt + generation).")
@click.option("--chunk", default=32, show_default=True,
              help="Prefill chunk size (one chunk per engine tick).")
@click.option("--ring", is_flag=True,
              help="Ring cache: O(--attention-window) per-slot HBM, "
                   "unbounded sequence length (needs a window).")
@click.option("--paged", is_flag=True,
              help="Paged KV cache (workloads/paged.py): block pool + "
                   "per-slot block tables, on-demand growth, batched "
                   "prefill — HBM scales with LIVE tokens, not "
                   "slots x max-len.  Mutually exclusive with --ring.")
@click.option("--block-size", default=16, show_default=True,
              help="Paged cache block size (tokens per pool block).")
@click.option("--num-blocks", default=None, type=int,
              help="Paged pool size in blocks (default: worst case "
                   "slots * max-len / block-size; smaller pools "
                   "oversubscribe HBM and preempt under pressure).")
@click.option("--spec-k", default=0, show_default=True,
              help="Speculative decoding inside the paged engine "
                   "(needs --paged): a draft proposes K tokens per "
                   "round, the target verifies them in one pass per "
                   "round.  0 = off.")
@click.option("--draft-layers", default=1, show_default=True,
              help="Draft model = the target's first N layers "
                   "(with --spec-k).")
@click.option("--tp", "tp_degree", default=None, type=int,
              help="Serve under a (data, model) mesh: slots shard over "
                   "data, KV heads + cache over 'model' (the trainer's "
                   "TP layout).  Default: single-device.")
@click.option("--seed", default=0, show_default=True)
@click.option("--final-stats", "final_stats_file", default=None,
              help="Also write the final-stats JSON (the drain "
                   "contract's receipt: served/unserved counts, "
                   "per-request latencies, engine stats) to this "
                   "path; it is always printed as the last stdout "
                   "line.")
@click.option("--replica-id", default="",
              help="This replica's fleet id, stamped into the drain "
                   "receipt so the request router's migration path "
                   "(serving/router.py absorb_drain) knows whose "
                   "unserved remainder it is re-dispatching.")
@click.option("--annotations-file", default=None,
              help="Downward-API annotations path for the drain "
                   "contract (default: the standard "
                   "/etc/podinfo/annotations).  When the autoscaler "
                   "requests the slice back, the server stops "
                   "admitting, finishes in-flight sequences, and "
                   "exits 0 inside the drain window.")
@click.option("--trace-sample", default=0.0, show_default=True,
              type=click.FloatRange(0.0, 1.0),
              help="Request-trace head-sampling rate (ISSUE 14): "
                   "sampled requests (plus the ALWAYS-captured tail — "
                   "SLO misses, preemptions, drain losses) emit span "
                   "trees; counts ride the final-stats receipt.  "
                   "0 disables the sampler entirely.")
@click.option("--slo-ticks", default=None, type=int,
              help="Engine-tick latency target: completions within "
                   "this many ticks count as SLO-attained in the "
                   "stats, and slower ones are tail-captured when "
                   "--trace-sample is on.")
@model_arch_options
@click.option("--platform", default=None,
              help="Force a jax platform (e.g. cpu).")
def main(checkpoint_dir, requests_file, random_n, max_new_tokens, slots,
         max_len, chunk, ring, paged, block_size, num_blocks, spec_k,
         draft_layers, tp_degree, seed, final_stats_file, replica_id,
         annotations_file, trace_sample, slo_ticks, vocab, seq_len,
         d_model, n_layers, n_kv_heads, attention_window, no_rope,
         moe_experts, moe_top_k, platform):
    """Serve mixed-length requests from the latest checkpoint."""
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(asctime)s %(levelname)s: %(message)s")
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    import numpy as np

    from tpu_autoscaler.workloads.checkpoint import (
        DEFAULT_ANNOTATIONS_PATH,
        DrainWatcher,
        latest_step,
        restore_checkpoint,
    )
    from tpu_autoscaler.workloads.serving import (
        ContinuousBatcher,
        Request,
    )

    cfg = model_config(vocab, seq_len, d_model, n_layers, n_kv_heads,
                       attention_window, no_rope, moe_experts, moe_top_k)
    if (requests_file is None) == (random_n is None):
        raise click.UsageError("pass exactly one of --requests/--random")
    # Pure flag validation BEFORE the checkpoint restore (the expensive
    # step): a bad combination must error instantly.
    if paged and ring:
        raise click.UsageError(
            "--paged and --ring are different cache layouts; pick one")
    if spec_k:
        if not paged:
            raise click.UsageError(
                "--spec-k runs inside the paged engine: add --paged")
        if not 1 <= draft_layers < n_layers:
            raise click.UsageError(
                f"--draft-layers must be in [1, {n_layers - 1}] "
                f"(a {n_layers}-layer target), got {draft_layers}")
        if spec_k >= chunk:
            raise click.UsageError(
                f"--spec-k {spec_k} must be < --chunk {chunk}")
        if moe_experts is not None:
            raise click.UsageError(
                "--spec-k with MoE targets is not wired (the layer-"
                "prefix draft would need its own router scaling)")
    if paged:
        if block_size < 1:
            raise click.UsageError(
                f"--block-size must be >= 1, got {block_size}")
        if max_len % block_size:
            raise click.UsageError(
                f"--max-len {max_len} must be a multiple of "
                f"--block-size {block_size}")
        min_blocks = -(-chunk // block_size)  # one prefill chunk
        if num_blocks is not None and num_blocks < min_blocks:
            raise click.UsageError(
                f"--num-blocks {num_blocks} cannot hold even one "
                f"prefill chunk (--chunk {chunk} needs >= {min_blocks} "
                f"blocks of {block_size}); admission would livelock")

    step = latest_step(checkpoint_dir)
    if step is None:
        raise click.UsageError(
            f"no checkpoint found in {checkpoint_dir!r} (train first: "
            f"python -m tpu_autoscaler.workloads.train)")
    try:
        state = restore_checkpoint(checkpoint_dir, step, None)
    except ValueError as e:
        if "available devices are different" in str(e):
            # Restoring WITHOUT an abstract tree inherits the saved
            # shardings, which pins the device topology.  The trainer
            # restores elastically (it rebuilds the abstract from its
            # own live shardings — train.py); the server does not know
            # the checkpoint's optimizer recipe, so it cannot.
            raise click.UsageError(
                "checkpoint was saved under a different device "
                "topology; serve with the same device count, or resume "
                "the trainer once on this topology to rewrite it: "
                + str(e)) from e
        raise
    if not isinstance(state, dict) or "params" not in state:
        raise click.UsageError(
            f"checkpoint at step {step} is not a trainer checkpoint "
            f"(expected a {{'params', 'opt'}} tree)")
    params = state["params"]
    log.info("restored step %d from %s", step, checkpoint_dir)

    reqs: list[Request] = []
    if random_n is not None:
        rng = np.random.default_rng(seed)
        for _ in range(random_n):
            plen = int(rng.integers(1, max(2, cfg.seq_len // 2)))
            reqs.append(Request(
                prompt=rng.integers(0, cfg.vocab, (plen,)).astype(
                    np.int32),
                max_new_tokens=int(rng.integers(1, max_new_tokens + 1))))
    else:
        src = sys.stdin if requests_file == "-" else open(requests_file)
        try:
            for n, line in enumerate(src):
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                    reqs.append(Request(
                        prompt=np.asarray(obj["prompt"], np.int32),
                        max_new_tokens=int(
                            obj.get("max_new_tokens", max_new_tokens)),
                        temperature=float(obj.get("temperature", 0.0)),
                        top_k=obj.get("top_k"),
                        top_p=obj.get("top_p"),
                        eos_id=obj.get("eos_id")))
                except (KeyError, ValueError, TypeError) as e:
                    raise click.UsageError(
                        f"bad request on line {n + 1}: {e}") from e
        finally:
            if src is not sys.stdin:
                src.close()
    if not reqs:
        raise click.UsageError("no requests to serve")

    mesh = None
    if tp_degree is not None and tp_degree > 1:
        from tpu_autoscaler.workloads.model import make_mesh

        n_dev = len(jax.devices())
        if n_dev % tp_degree:
            raise click.UsageError(
                f"--tp {tp_degree} must divide the {n_dev} available "
                f"devices")
        dp = n_dev // tp_degree
        if slots % dp:
            raise click.UsageError(
                f"--slots {slots} must divide over the {dp} "
                f"data-parallel devices (devices / tp) — the slot "
                f"batch shards over them")
        mesh = make_mesh(tp=tp_degree)
        log.info("serving under mesh %s", dict(mesh.shape))
    sampler = None
    if trace_sample > 0.0:
        from tpu_autoscaler.serving.reqtrace import RequestTraceSampler

        sampler = RequestTraceSampler("serve",
                                      sample_rate=trace_sample,
                                      slo_ticks=slo_ticks)
    if paged:
        from tpu_autoscaler.workloads.paged import PagedBatcher

        if mesh is not None and len(jax.devices()) // tp_degree > 1:
            raise click.UsageError(
                "--paged serves TP-only meshes (all slots share ONE "
                "block pool, which data sharding cannot cut); for data "
                "parallelism run one server per replica, or use "
                "devices == --tp")
        if spec_k:
            import dataclasses as _dc

            from tpu_autoscaler.workloads.spec_serving import (
                SpeculativePagedBatcher,
            )

            dparams = {**params, "blocks": jax.tree.map(
                lambda x: x[:draft_layers], params["blocks"])}
            dcfg = _dc.replace(cfg, n_layers=draft_layers)
            engine = SpeculativePagedBatcher(
                params, cfg, dparams, dcfg, k=spec_k, slots=slots,
                max_len=max_len, block_size=block_size,
                num_blocks=num_blocks, chunk=chunk, mesh=mesh,
                key=jax.random.PRNGKey(seed), seed=seed,
                slo_ticks=slo_ticks, reqtrace=sampler)
        else:
            engine = PagedBatcher(
                params, cfg, slots=slots, max_len=max_len,
                block_size=block_size, num_blocks=num_blocks,
                chunk=chunk, mesh=mesh, key=jax.random.PRNGKey(seed),
                slo_ticks=slo_ticks, reqtrace=sampler)
    else:
        engine = ContinuousBatcher(
            params, cfg, slots=slots, max_len=max_len, chunk=chunk,
            ring=ring, mesh=mesh, key=jax.random.PRNGKey(seed),
            slo_ticks=slo_ticks, reqtrace=sampler)
    import time

    watcher = DrainWatcher(annotations_file or DEFAULT_ANNOTATIONS_PATH)
    t0 = time.perf_counter()
    try:
        for r in reqs:
            engine.submit(r)
    except ValueError as e:
        raise click.UsageError(str(e)) from e
    engine.run(watcher=watcher)
    dt = time.perf_counter() - t0
    for i, r in enumerate(reqs):
        print(json.dumps({"id": i, "prompt_len": len(r.prompt),
                          "tokens": [int(t) for t in r.generated],
                          "done": r.done}))
    decoded = sum(len(r.generated) for r in reqs)
    log.info("%d requests, %d tokens in %.2fs (%.0f tok/s, %d ticks)",
             len(reqs), decoded, dt, decoded / max(dt, 1e-9),
             engine.ticks)
    if spec_k:
        log.info("speculative: accept_rate %.3f, target_pass_ratio "
                 "%.3f (plain decode = 1.0)", engine.accept_rate,
                 engine.target_pass_ratio)
    # The drain contract's machine-readable receipt (ISSUE 9): always
    # the LAST stdout line, so the reclaim side can assert zero lost
    # requests without parsing logs.
    final = final_stats_payload(reqs, engine, dt,
                                replica_id=replica_id)
    if sampler is not None:
        final["trace"] = sampler.debug_state()
    print(json.dumps(final))
    if final_stats_file:
        with open(final_stats_file, "w", encoding="utf-8") as f:
            json.dump(final, f, indent=2)
            f.write("\n")
    if engine.draining:
        log.info("drain requested: in-flight sequences completed, %d "
                 "queued requests unserved; exiting cleanly",
                 final["unserved"])


if __name__ == "__main__":
    main()
