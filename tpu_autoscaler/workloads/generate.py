"""Runnable generation CLI: serve a checkpoint trained by train.py.

``python -m tpu_autoscaler.workloads.generate --checkpoint-dir ...``
restores the latest checkpoint's params (the trainer's state layout) and
runs the KV-cache decode path (workloads/decode.py) — the serving-side
proof that a slice the autoscaler provisioned answers, not just trains.

The model flags must match the training run (same rule as resume); the
prompt is token ids (comma-separated) or random with ``--prompt-len``.
"""

from __future__ import annotations

import logging
import sys

import click

log = logging.getLogger(__name__)


from tpu_autoscaler.workloads._cli import model_arch_options, model_config


@click.command()
@click.option("--checkpoint-dir", default="/tmp/tpu-train-ckpt",
              show_default=True)
@click.option("--steps", default=32, show_default=True,
              help="Tokens to generate.")
@click.option("--prompt", default=None,
              help="Comma-separated token ids (default: random).")
@click.option("--prompt-len", default=8, show_default=True,
              help="Random prompt length when --prompt is not given.")
@click.option("--batch", default=1, show_default=True)
@click.option("--temperature", default=0.0, show_default=True,
              help="0 = greedy; > 0 samples.")
@click.option("--top-k", default=None, type=click.IntRange(min=1))
@click.option("--seed", default=0, show_default=True)
@model_arch_options
@click.option("--platform", default=None,
              help="Force a jax platform (e.g. cpu).")
def main(checkpoint_dir, steps, prompt, prompt_len, batch, temperature,
         top_k, seed, seq_len, d_model, n_layers, n_kv_heads,
         attention_window, no_rope, platform):
    """Generate tokens from the latest checkpoint in --checkpoint-dir."""
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(asctime)s %(levelname)s: %(message)s")
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp

    from tpu_autoscaler.workloads.checkpoint import (
        latest_step,
        restore_checkpoint,
    )
    from tpu_autoscaler.workloads.decode import generate
    from tpu_autoscaler.workloads.model import init_params

    cfg = model_config(seq_len, d_model, n_layers, n_kv_heads,
                       attention_window, no_rope)
    if top_k is not None and top_k > cfg.vocab:
        raise click.UsageError(
            f"--top-k {top_k} exceeds the vocab size {cfg.vocab}")

    step = latest_step(checkpoint_dir)
    if step is None:
        raise click.UsageError(
            f"no checkpoint found in {checkpoint_dir!r} (train first: "
            f"python -m tpu_autoscaler.workloads.train)")
    # The trainer checkpoints {"params": ..., "opt": ...}; orbax restores
    # whole trees, so mirror the trainer's state shapes (the AdamW
    # hyperparams don't affect state SHAPES) and discard the opt half.
    import optax

    def abstract_state(key):
        params = init_params(key, cfg)
        return {"params": params, "opt": optax.adamw(1e-3).init(params)}

    abstract = jax.eval_shape(abstract_state, jax.random.PRNGKey(0))
    try:
        state = restore_checkpoint(checkpoint_dir, step, abstract)
    except Exception as e:  # noqa: BLE001 — tree-structure mismatch
        raise click.UsageError(
            f"checkpoint at step {step} does not match the model flags "
            f"(train and generate must agree on "
            f"--d-model/--n-layers/...): {e}") from e
    # Orbax restores the SAVED shapes regardless of the abstract tree's,
    # so a flag mismatch surfaces here, not in restore.
    mismatches = [
        f"{'/'.join(str(k.key) for k in path)}: checkpoint "
        f"{tuple(got.shape)} vs flags {tuple(want.shape)}"
        for (path, got), (_, want) in zip(
            jax.tree_util.tree_flatten_with_path(state["params"])[0],
            jax.tree_util.tree_flatten_with_path(abstract["params"])[0])
        if tuple(got.shape) != tuple(want.shape)]
    if mismatches:
        raise click.UsageError(
            "checkpoint does not match the model flags: "
            + "; ".join(mismatches[:4]))
    params = state["params"]
    log.info("restored step %d from %s", step, checkpoint_dir)

    if prompt is not None:
        try:
            ids = [int(t) for t in prompt.split(",") if t.strip()]
        except ValueError as e:
            raise click.UsageError(
                f"--prompt must be comma-separated ints: {e}") from e
        if not ids:
            raise click.UsageError("--prompt is empty")
        if any(t < 0 or t >= cfg.vocab for t in ids):
            raise click.UsageError(
                f"--prompt ids must be in [0, {cfg.vocab})")
        tokens = jnp.asarray([ids] * batch, jnp.int32)
    else:
        tokens = jax.random.randint(jax.random.PRNGKey(seed),
                                    (batch, prompt_len), 0, cfg.vocab,
                                    dtype=jnp.int32)

    key = jax.random.PRNGKey(seed) if temperature > 0 else None
    out = generate(params, tokens, cfg, steps, key=key,
                   temperature=temperature, top_k=top_k)
    prompt_n = tokens.shape[1]
    for row in out:
        ids = [int(t) for t in row]
        print(f"{','.join(map(str, ids[:prompt_n]))} | "
              f"{','.join(map(str, ids[prompt_n:]))}")


if __name__ == "__main__":
    main()
